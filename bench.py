"""Benchmark: both BASELINE.json north-star metrics on one TPU chip.

1. ``mcd_t50_inference_throughput`` — T=50 stochastic passes of the full
   ~851K-param Alarcón 1D-CNN over SHHS2-shaped (60, 4) windows
   (windows/sec/chip).  The reference publishes no numbers (BASELINE.md),
   so ``vs_baseline`` is measured against a same-chip reimplementation of
   the reference's execution pattern — T sequential full-set float32
   passes, one Keras-style ``model(x, training=True)`` call per pass
   (uq_techniques.py:22) — versus this framework's fused bf16
   vmap-over-keys path.  The ``baseline`` field records this provenance.
2. ``de10_train_wallclock`` (in ``secondary``) — N=10 Deep-Ensemble
   training wall-clock, concurrent vmap-over-members vs the reference's
   sequential member loop (train_deep_ensemble_cnns.py:125-177) on the
   same chip.

The ``context`` block reports absolute per-chip numbers (model FLOPs per
window, achieved TFLOP/s, implied MFU where the chip's peak is known) so
round-over-round regressions are visible without re-deriving the setup.

Timing methodology: each timed function reduces its result to a scalar on
device and the harness fetches that scalar to host.  This forces the full
computation on every backend — ``jax.block_until_ready`` alone returns
early on tunneled/remote TPU platforms (observed: a 1.1-TFLOP matmul
"completing" in 80 µs) — while keeping the device->host transfer to 4
bytes so the tunnel's bandwidth doesn't pollute a compute measurement.

Prints ONE json line with the primary metric in the driver's schema
({"metric", "value", "unit", "vs_baseline"}) plus the extra fields above
AND the result-v2 envelope (docs/OBSERVABILITY.md "Bench result payload
v2"): "schema": 2, a "backend" facts section, a "proxy" flag, and a
per-block "blocks" status map ({status: ok|error|skipped|unavailable,
seconds, error_tail}) — every measurement runs as an ISOLATED block, so
one raising block degrades to a per-block error status instead of
sinking the whole capture ("bench_error" is now only the total-failure
shape: watchdog fire or an init abort, and even those fold whatever
per-block checkpoints survived into the payload).
Every metric block is ALSO checkpointed to an on-disk progress file
(BENCH_PROGRESS_FILE, default ./bench_progress.json, "" disables) the
moment it is measured, and the final line is assembled from that file —
a tunnel death or kill -9 mid-run no longer loses already-captured
numbers (the failure mode of three consecutive bench rounds).
Env knobs: BENCH_WINDOWS/PASSES/CHUNK (MCD), BENCH_MEMBERS/TRAIN_WINDOWS/
EPOCHS/BATCH/DE_REPS (DE), BENCH_METRIC=de_train for the DE metric alone,
BENCH_SKIP_DE=1 to skip the DE secondary, BENCH_SKIP_STREAMED=1 to skip
the streamed-overhead context, BENCH_SKIP_FUSED=1 to skip the
fused-reduction context (fused (4, M) sufficient-stats output vs the
full (T, M) probability round-trip, end-to-end incl. host fetch),
BENCH_SKIP_MCD_KERNEL=1 to skip the mcd_kernel context (XLA-vs-Pallas
MCD engines and f32-vs-bf16 compute at the fixed smoke operating
point; its speedup ratios gate as backend-independent relatives
across the CPU-proxy boundary),
BENCH_SKIP_DE_KERNEL=1 to skip the de_kernel context (XLA-vs-Pallas
Deep-Ensemble engines at the same fixed smoke operating point, member
sweep instead of MC passes; `de_kernel.xla_vs_pallas` gates as a
backend-independent relative like the mcd_kernel ratios),
BENCH_SKIP_AUTOTUNE=1 to skip the autotune context (a tiny
window_tile x member_group/pass_group sweep through the real
`apnea-uq autotune` harness — winners returned, never persisted;
`autotune.best_vs_default` gates as a backend-independent relative),
BENCH_SKIP_COMPILE=1 to skip the compile context (cold-vs-warm process
start of the MCD hot path through the persistent compile cache + AOT
program store, measured as two probe subprocesses),
BENCH_SKIP_AUDIT=1 to skip the program-audit context (the IR-level
`apnea-uq audit` over the inference zoo as a CPU subprocess — lowering
only, no device time; records per-program FLOPs/arithmetic intensity
and whether the lowered-IR promises still hold),
BENCH_SKIP_DATA=1 to skip the data-plane context (cold stage-start
load of the same window set as monolithic .npz vs sharded memmap
store + one streamed pass — host-only, no device time),
BENCH_SKIP_QUALITY=1 to skip the quality context (fixed-seed synthetic
calibration ECE/MCE/Brier + fingerprint drift self/shift scores — the
model-quality tooling proof, host-only NumPy, so its scalars gate as
backend-independent metrics across the CPU-proxy boundary;
BENCH_QUALITY_WINDOWS scales it, default 4096),
BENCH_SKIP_SERVE=1 to skip the serve context (the online serving tier's
load-generated SLO proof: AOT-warm the bucket-ladder fused-stats
programs, drive `serving/loadgen.py` through the request coalescer, and
record p50/p95/p99 request latency, windows/sec, mean queue wait, and
pad waste — backend-aware: it runs on whatever backend the capture
targets, CPU-proxy rounds included, and `telemetry compare` gates only
the relative pad-waste ratio across the proxy boundary;
BENCH_SERVE_REQUESTS scales the request count, default 64;
BENCH_SERVE_DRIFT_AFTER moves the built-in online-drift cohort shift —
the loadgen traffic shifts scale/offset from that request on and the
serve_drift verdict must flip, default halfway, -1 disables;
BENCH_SERVE_TRACE_EVERY sets the 1-in-N baseline exemplar stream,
default 8, 0 disables; BENCH_SERVE_TRACE_SLOW_MS arms the tail-based
exemplar sampler's slow budget, default 250 — the block asserts every
over-budget request kept its waterfall, the tail-sampling contract),
BENCH_SKIP_CAPACITY=1 to skip the capacity context (the
fleet-saturation sweep: K serve replica SUBPROCESSES per offered-rate
cell, Poisson arrivals, one shared warm program store, each cell
fleet-merged via telemetry/fleet.py into offered-vs-achieved
throughput and fleet p99 — the knee is the first cell whose
achieved/offered ratio drops below 0.95 or whose fleet p99 blows the
budget; absolutes are backend-bound, the lowest cell's
achieved/offered ratio gates across the proxy boundary;
BENCH_CAPACITY_RATES sets the offered fleet req/s cells, default
"4,8,16"; BENCH_CAPACITY_REPLICAS the replica count, default 2;
BENCH_CAPACITY_REQUESTS the per-replica request count per cell,
default 24; BENCH_CAPACITY_P99_BUDGET_MS the knee's latency budget,
default 0 = ratio-only; BENCH_CAPACITY_TRACE_EVERY the per-replica
1-in-N exemplar stream, default 4, 0 disables;
BENCH_CAPACITY_TRACE_SLOW_MS the per-replica tail-exemplar budget,
default 250 — each cell's dirs are trace-merged and the cell carries
queue/service share at p99 plus exemplar coverage, asserted 1.0),
BENCH_DE_CHUNK for its DE chunk size,
BENCH_WASTE_EPOCHS for the early-stop-waste context's epoch cap (0
skips it), BENCH_BOOT_WINDOWS for the bootstrap context scale,
BENCH_WATCHDOG_SECS to change or disable (0) the hang watchdog
(default 45 min), BENCH_INIT_WAIT_SECS to change or disable (0) the
backend-init retry budget (default 25 min; BENCH_BACKEND_BUDGET_S is
the same budget under its watch-era name and wins when both are set;
BENCH_INIT_PROBE_SECS caps each individual probe, default 2 min;
BENCH_BACKEND_PROBES caps the probe COUNT, 0 = budget-only — each
probe attempt is also replayed into the run log as a `probe` telemetry
event, so the r03-r05 tunnel-outage pattern is diagnosable from
events.jsonl instead of one error string),
BENCH_CPU_PROXY for the CPU-proxy capture mode: =1 forces it, unset
auto-selects it when the init probe budget is exhausted (the r03-r05
condition), =0 forbids the automatic fallback and restores the exit-2
abort.  Proxy mode retargets jax to CPU, shrinks the shape knobs to the
smoke operating point, runs ONLY the backend-independent blocks
(compile cold/warm, data plane, program audit, D2H accounting — device
blocks report status "unavailable"), and marks the payload
"proxy": true so `telemetry compare` refuses cross-backend
absolute-throughput comparisons while still gating the relative
metrics.  BENCH_PLATFORM wins over BENCH_CPU_PROXY when both are set.
BENCH_RUN_DIR for the telemetry
run directory (default ./bench_run; "" falls back to a temp dir — the
run log is never disabled, because the DE context block is *sourced*
from its ensemble_fit events; read it back with
``apnea-uq telemetry summarize <dir>``), BENCH_PROFILE=1 to capture one
steady-state framework MCD pass as a bounded jax.profiler trace under
<run dir>/profile/ (announced via a profile_captured event; the capture
runs AFTER the timed reps so it cannot pollute the throughput number),
and two smoke-run knobs: BENCH_PLATFORM=cpu runs the whole bench off-TPU
(the CPU smoke test's path; sitecustomize pins JAX_PLATFORMS at
interpreter start, so this is a config update, not an env passthrough)
and BENCH_DTYPE=float32 swaps the bf16 compute dtype (CPU emulates bf16
convs too slowly to smoke).
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

# Must precede any device use: the environment's sitecustomize forces
# JAX_PLATFORMS=axon at interpreter start, so an env var alone cannot
# retarget the bench — only this config update can (the same dance
# tests/conftest.py does for the CPU test rig).  An explicit
# BENCH_CPU_PROXY=1 is the same dance toward CPU; the automatic
# exhaustion-triggered variant applies it in _resolve_backend instead
# (still before any device use in this process — probes run in
# subprocesses).
if os.environ.get("BENCH_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
elif os.environ.get("BENCH_CPU_PROXY", "") not in ("", "0"):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

# Per-chip peak dense bf16 TFLOP/s — drives the implied-MFU context
# (reported only for known chips).  The HBM side of the old spec table
# lives in telemetry/memory.py (CHIP_HBM_BYTES / device_hbm_limit), the
# one copy the memory_profile events and this script's sizing hint share.
_CHIP_PEAK_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5": 459.0,   # v5p
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


# The result-v2 payload contract (docs/OBSERVABILITY.md "Bench result
# payload v2"): schema-versioned, always parseable, per-block statuses.
RESULT_SCHEMA_VERSION = 2

# CPU-proxy mode (ISSUE 11 tentpole, piece 2): set once by main() after
# backend resolution; every knob-default helper below consults it so a
# proxy capture shrinks to the smoke operating point automatically.
_PROXY = [False]


def _proxy_active() -> bool:
    return bool(_PROXY[0])


def _set_proxy(on: bool) -> None:
    _PROXY[0] = bool(on)


def _bench_dtype() -> str:
    """Compute dtype for both timed model paths (default the TPU operating
    point, bf16 on the MXU).  BENCH_DTYPE=float32 exists for the CPU smoke
    run — CPU backends emulate bf16 convolutions orders of magnitude too
    slowly to execute the bench logic at any size — and is the CPU-proxy
    default for the same reason."""
    explicit = os.environ.get("BENCH_DTYPE")
    if explicit:
        return explicit
    return "float32" if _proxy_active() else "bfloat16"


def _shape_knobs() -> tuple:
    """(windows, passes, chunk) for the MCD-shaped blocks.  Defaults are
    the TPU operating point; CPU-proxy mode shrinks them to the smoke
    shapes (the compile probe subprocesses execute the real programs at
    these sizes off-TPU).  Env knobs win in both modes."""
    dw, dp, dc = (256, 4, 64) if _proxy_active() else (32768, 50, 512)
    return (int(os.environ.get("BENCH_WINDOWS", dw)),
            int(os.environ.get("BENCH_PASSES", dp)),
            int(os.environ.get("BENCH_CHUNK", dc)))


def _progress_path() -> str:
    """On-disk progress file; every measured metric block lands here the
    moment it exists so a mid-run death loses nothing (r5 verdict item 2).
    Empty string disables."""
    return os.environ.get("BENCH_PROGRESS_FILE", "bench_progress.json")


def _progress_reset() -> None:
    """Start a fresh capture: the file describes THIS run only."""
    path = _progress_path()
    if path:
        _atomic_write_json(path, {})


def _atomic_write_json(path: str, data: dict) -> None:
    """tmp + fsync + rename so a kill -9 mid-write can never leave a
    truncated file: the previous complete snapshot survives instead.
    Routed through the shared commit-protocol writer (utils/io.py) the
    flow gate enforces for every artifact-rooted write."""
    from apnea_uq_tpu.utils.io import atomic_write_json

    atomic_write_json(path, data)


def _progress_read() -> dict:
    """Torn-tail-tolerant progress load (the shared reader the conc
    gate's torn-read rule enforces): a half-written snapshot degrades to
    a fresh capture, never a crash-loop."""
    from apnea_uq_tpu.utils.io import read_json_tolerant

    path = _progress_path()
    if not path:
        return {}
    doc = read_json_tolerant(path, default={})
    return doc if isinstance(doc, dict) else {}


def _progress_record(key: str, value: dict) -> dict:
    """Checkpoint one metric block under ``key`` (read-modify-write, so
    blocks recorded earlier in the run are preserved).  Returns ``value``
    so call sites can record-and-use in one expression."""
    path = _progress_path()
    if path:
        data = _progress_read()
        data[key] = value
        _atomic_write_json(path, data)
    return value


def _bench_run_log():
    """The bench's run-scoped telemetry log (events.jsonl under
    BENCH_RUN_DIR).  Opened once per process and reused: bench_de_train
    and bench_de_earlystop_waste SOURCE their zero-waste accounting from
    the ``ensemble_fit`` events ``fit_ensemble`` appends here, instead of
    recomputing it inline — the same record every CLI stage reports
    through, so BENCH context numbers and run logs cannot drift."""
    from apnea_uq_tpu import telemetry

    run = telemetry.current_run()
    if run is None:
        run_dir = os.environ.get("BENCH_RUN_DIR", "bench_run")
        if not run_dir:
            import tempfile

            run_dir = tempfile.mkdtemp(prefix="bench_run_")
        run = telemetry.start_run(run_dir, stage="bench", argv=sys.argv[1:])
    return run


def _last_ensemble_fit_event(run_log) -> dict:
    """The most recent ``ensemble_fit`` accounting event in the bench's
    run log — the telemetry-sourced ground truth for effective-member /
    promoted-slot / wasted-epoch context fields."""
    from apnea_uq_tpu.telemetry import read_events

    fits = [e for e in read_events(run_log.run_dir)
            if e.get("kind") == "ensemble_fit"]
    if not fits:
        raise RuntimeError(
            "fit_ensemble recorded no ensemble_fit telemetry event under "
            f"{run_log.run_dir!r}; cannot source the DE context block"
        )
    return fits[-1]


def _emit_bench_error(msg: str, *, this_run: bool = True) -> None:
    """The driver-schema error line; shared by every give-up path (init
    retry exhaustion with the proxy fallback forbidden, hang watchdog)
    so the parsers downstream see one shape.  Whatever per-block
    checkpoints survived in BENCH_PROGRESS_FILE are folded into the
    payload — a hang after N good blocks still reports N blocks, and
    `telemetry compare` can gate the survived metrics.

    ``this_run=False`` marks the progress file as a PREVIOUS run's
    (the init-abort path fires before ``_progress_reset``): the content
    is still preserved under ``prior_progress`` — never discarded — but
    not as this run's blocks/primary, so a stale capture can never gate
    as fresh evidence or count as surviving blocks downstream."""
    doc = {
        "metric": "bench_error",
        "value": 0,
        "unit": "error",
        "vs_baseline": 0,
        "error": msg,
        "schema": RESULT_SCHEMA_VERSION,
    }
    saved = _progress_read()
    if this_run:
        for key in ("proxy", "backend", "blocks", "primary",
                    "secondary"):
            if saved.get(key) is not None:
                doc[key] = saved[key]
        # Context values checkpointed before a headline existed (proxy
        # mode / dead mcd block) ride at top level; compare extracts
        # them like any capture's context.
        if saved.get("context") and not saved.get("primary"):
            doc["context"] = saved["context"]
    elif saved:
        doc["prior_progress"] = saved
    # The driver-schema stdout contract: this line must be raw stdout,
    # not telemetry.log (which an active run log would also mirror and
    # narration_to_stderr would redirect away from the parser).
    # apnea-lint: disable=bare-print -- bench stdout IS the machine interface; see one-JSON-line contract in tests/test_bench_smoke.py
    print(json.dumps(doc), flush=True)


def _resolve_backend() -> tuple:
    """Decide what backend this capture runs against; returns
    ``(proxy, probe_records)`` where each probe record is the
    ``{attempt, green, detail}`` shape the `probe` telemetry event
    carries (main replays them into the run log once one exists).

    Retry backend init until it works or a budget expires (r4 verdict:
    the round-4 capture died in seconds on a fast ``UNAVAILABLE`` from a
    flapping tunnel, and the watchdog only covers the *hang* failure
    mode).  The probe loop itself — ``jax.devices()`` in a budgeted
    subprocess (the call can hang indefinitely during a tunnel outage,
    so it must not run in this process), backoff between failures, the
    final sleep clamped to the remaining budget — lives in
    telemetry/watch.py (``wait_for_green``), where ``apnea-uq telemetry
    watch`` reuses it as the tunnel-watcher.  Budget:
    BENCH_BACKEND_BUDGET_S, falling back to BENCH_INIT_WAIT_SECS
    (default 25 min, 0 disables); per-probe cap BENCH_INIT_PROBE_SECS;
    probe-count cap BENCH_BACKEND_PROBES (0 = budget-only).

    On exhaustion the capture degrades to CPU-proxy mode (the r03-r05
    rounds each lost a whole PR's evidence to this abort) unless
    BENCH_CPU_PROXY=0 pins the old behavior — then the standard error
    JSON line (with surviving progress folded in) is emitted and the
    process exits 2.  Skipped entirely under BENCH_PLATFORM (an
    explicitly retargeted backend has no tunnel to wait for) and under
    an explicit BENCH_CPU_PROXY=1 (proxy was requested, not probed
    into)."""
    from apnea_uq_tpu.telemetry.watch import wait_for_green

    if os.environ.get("BENCH_PLATFORM"):
        return False, []
    cpu_proxy = os.environ.get("BENCH_CPU_PROXY", "")
    if cpu_proxy not in ("", "0"):
        return True, []
    budget = float(os.environ.get("BENCH_BACKEND_BUDGET_S")
                   or os.environ.get("BENCH_INIT_WAIT_SECS", 1500))
    if budget <= 0:
        return False, []
    probe_timeout = float(os.environ.get("BENCH_INIT_PROBE_SECS", 120))
    max_probes = int(os.environ.get("BENCH_BACKEND_PROBES", 0))
    records = []

    def on_attempt(n: int, green: bool, detail: str) -> None:
        records.append({"attempt": n, "green": green, "detail": detail})

    green, attempts, last = wait_for_green(
        budget, probe_timeout_s=probe_timeout,
        max_attempts=max_probes or None, on_attempt=on_attempt,
    )
    if green:
        return False, records
    msg = (f"TPU backend unavailable after {attempts} init probes "
           f"over {budget:.0f}s; last: {last}")
    if cpu_proxy == "0":
        _abort_unavailable(msg, records)
    # Auto-proxy (the tentpole's point): the same config update the
    # explicit modes perform, still before any device use in this
    # process (every probe ran in a subprocess).
    jax.config.update("jax_platforms", "cpu")
    return True, records


def _abort_unavailable(msg: str, records: list) -> None:
    """The forbidden-proxy give-up path: leave the probe trail in the
    run log (no run_started topology probe — jax.devices() against the
    dead backend is exactly what hangs), emit the folded error payload,
    exit 2."""
    from apnea_uq_tpu.telemetry.runlog import SCHEMA_VERSION, RunLog

    run_dir = os.environ.get("BENCH_RUN_DIR", "bench_run")
    if not run_dir:
        # Same contract as _bench_run_log: "" means a temp dir, never a
        # disabled log — the probe trail IS the outage diagnosis.
        import tempfile

        run_dir = tempfile.mkdtemp(prefix="bench_run_")
    run_log = RunLog(run_dir)
    run_log.event("run_started", schema_version=SCHEMA_VERSION,
                  stage="bench",
                  topology={"platform": "unavailable"})
    for record in records:
        run_log.event("probe", **record)
    run_log.event("error", where="backend", error=msg)
    run_log.close(status="error")
    # No block of THIS run has executed yet, so anything in the
    # progress file is a previous run's capture: preserve it as
    # prior_progress, never as this run's blocks.
    _emit_bench_error(msg, this_run=False)
    sys.exit(2)


def _time(fn, *args, warmup: int = 1, reps: int = 3) -> float:
    """Best-of-reps wall time of ``fn`` (which must return a scalar array)."""
    for _ in range(warmup):
        float(np.asarray(fn(*args)))
    best = np.inf
    for _ in range(reps):
        t0 = time.perf_counter()
        float(np.asarray(fn(*args)))
        best = min(best, time.perf_counter() - t0)
    return best


def _is_oom(e: Exception) -> bool:
    """Only out-of-memory failures trigger the size step-down; anything
    else (shape bug, bad env knob) re-raises with its real configuration."""
    msg = str(e)
    return ("RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg
            or "out of memory" in msg)


def model_flops_per_window(cfg) -> int:
    """Forward-pass FLOPs per window: conv + dense MACs x 2 (BN/ReLU/GAP
    are O(channels) and negligible against the convs)."""
    c_in = cfg.num_channels
    flops = 0
    for feat, k in zip(cfg.features, cfg.kernel_sizes):
        flops += 2 * cfg.time_steps * k * c_in * feat
        c_in = feat
    flops += 2 * c_in  # Dense(1) head
    return flops


def bench_de_train(progress_key: str = "secondary") -> dict:
    """Secondary north-star metric: N=10 Deep-Ensemble training wall-clock,
    concurrent vmap-over-members vs the reference's sequential member loop
    on the same chip.  Early stopping is disabled so both paths run a fixed
    number of epochs; ``fit``/``fit_ensemble`` fetch per-epoch losses to
    host, which forces execution on every backend (see timing note above).

    The ``context`` block reports the zero-waste accounting (r5 verdict
    items 3/5): ``effective_members`` — the lockstep slot count the run
    actually trains, all returned as real members via
    ``keep_padded_members`` — with ``cost_per_member`` (median concurrent
    wall-clock / effective members), plus the OTHER known lockstep waste,
    quantified not fixed: ``early_stop_waste`` runs the ensemble at the
    reference operating point (patience=5) and counts the member-epochs
    computed for members that had already stopped while the last active
    member kept the lockstep program running.
    """
    from apnea_uq_tpu.config import EnsembleConfig, ModelConfig, TrainConfig
    from apnea_uq_tpu.models import AlarconCNN1D
    from apnea_uq_tpu.parallel import fit_ensemble
    from apnea_uq_tpu.training import create_train_state, fit

    # 32768 windows keeps the whole bench comfortably inside a ~10 min
    # budget over the tunneled chip (compiles dominate; the fit itself
    # halves) while the concurrent-vs-sequential ratio is unchanged —
    # the `effective` block records the operating point either way.
    n_members = int(os.environ.get("BENCH_MEMBERS", 10))
    n_windows = int(os.environ.get("BENCH_TRAIN_WINDOWS", 32768))
    n_epochs = int(os.environ.get("BENCH_EPOCHS", 3))
    batch = int(os.environ.get("BENCH_BATCH", 1024))

    rng = np.random.default_rng(2025)
    x = rng.normal(size=(n_windows, 60, 4)).astype(np.float32)
    y = rng.integers(0, 2, n_windows).astype(np.float32)

    model = AlarconCNN1D(ModelConfig(compute_dtype=_bench_dtype()))
    no_stop = n_epochs + 1  # patience > epochs -> fixed-length run

    # Setup (config construction, param init) stays OUTSIDE the timed
    # functions — _time measures the whole call, and any per-call setup in
    # sequential_one would be amplified 10x into t_sequential.
    # keep_padded_members: any lockstep slots the mesh pads in are counted
    # (and returned) as real members — the zero-waste operating point.  On
    # a single-chip mesh the ensemble axis is 1, so nothing pads and the
    # effective count equals the requested one.
    ens_cfg = EnsembleConfig(
        num_members=n_members, num_epochs=n_epochs, batch_size=batch,
        validation_split=0.1, early_stopping_patience=no_stop,
        keep_padded_members=True,
    )
    one_cfg = TrainConfig(
        num_epochs=n_epochs, batch_size=batch, validation_split=0.1,
        early_stopping_patience=no_stop,
    )
    state0 = create_train_state(model, jax.random.key(0))
    run_log = _bench_run_log()

    def concurrent():
        # Fetches losses -> forces exec.  The result itself is DROPPED
        # (no member-stacked params/opt_state pinned in HBM between reps):
        # the run's accounting lands in the run log's ensemble_fit event,
        # which the context block below is sourced from.
        fit_ensemble(model, x, y, ens_cfg, run_log=run_log)
        return 0.0

    def sequential_one():
        fit(model, state0, x, y, one_cfg)   # fetches losses -> forces exec
        return 0.0

    # Median-of-reps of PAIRED ratios: the tunneled chip drifts +/-30%
    # run-to-run, but slow windows hit adjacent measurements alike, so
    # timing the two paths back-to-back per rep and taking the median
    # per-rep ratio is stable where independent best-of-N ratios jumped
    # between rounds (r02 recorded 2.63x against a 3.1-5.2x band).
    reps = int(os.environ.get("BENCH_DE_REPS", 3))
    with run_log.stage("de_train", snapshot_memory=True,
                       members=n_members, windows=n_windows,
                       epochs=n_epochs, reps=reps):
        concurrent(); sequential_one()  # compile warmup, both paths
        t_conc, ratios = [], []
        for _ in range(reps):
            t0 = time.perf_counter(); concurrent()
            tc = time.perf_counter() - t0
            t0 = time.perf_counter(); sequential_one()
            to = time.perf_counter() - t0
            t_conc.append(tc)
            ratios.append(n_members * to / tc)

    t_median = float(np.median(t_conc))
    # Telemetry-sourced zero-waste accounting: the numbers below come from
    # the ensemble_fit event the last concurrent rep appended, not from an
    # inline recomputation (one record, one schema, everywhere).
    fit_event = _last_ensemble_fit_event(run_log)
    effective_members = int(fit_event["num_members"])
    result = {
        "metric": f"de{n_members}_train_wallclock",
        "value": round(t_median, 2),
        "unit": "seconds",
        "vs_baseline": round(float(np.median(ratios)), 3),
        "baseline": "same-chip sequential member loop "
                    "(train_deep_ensemble_cnns.py pattern)",
        "effective": {"members": n_members, "windows": n_windows,
                      "epochs": n_epochs, "batch": batch,
                      "per_rep_ratios": [round(r, 2) for r in ratios]},
        "context": {
            # Lockstep slots actually trained AND returned (padded slots
            # promoted); the honest per-member price of the concurrent run.
            "effective_members": effective_members,
            "promoted_members": int(fit_event["promoted_members"]),
            "cost_per_member": round(t_median / effective_members, 3),
        },
    }
    _progress_record(progress_key, result)
    # The early-stop-waste measurement is its own isolated block now
    # (main's orchestrator runs it with this state and attaches the
    # value under context.early_stop_waste).
    return result, {"model": model, "x": x, "y": y, "batch": batch}


def bench_de_earlystop_waste(model, x, y, batch: int) -> dict:
    """Quantify (NOT fix) the remaining lockstep waste: under vmapped
    lockstep execution members cannot exit at different epochs, so an
    early-stopped member's slot keeps computing (masked, discarded) until
    the LAST active member stops (`_epoch_bookkeeping`).  Reported at the
    reference operating point patience=5 so BASELINE.md can say whether
    unbalanced scheduling work would ever pay for itself."""
    from apnea_uq_tpu.config import EnsembleConfig
    from apnea_uq_tpu.parallel import fit_ensemble

    n_members = int(os.environ.get("BENCH_MEMBERS", 10))
    epochs_cap = int(os.environ.get("BENCH_WASTE_EPOCHS", 12))
    patience = 5
    cfg = EnsembleConfig(
        num_members=n_members, num_epochs=epochs_cap, batch_size=batch,
        validation_split=0.1, early_stopping_patience=patience,
        keep_padded_members=True,
    )
    run_log = _bench_run_log()
    with run_log.stage("de_earlystop_waste", snapshot_memory=True,
                       patience=patience, epochs_cap=epochs_cap):
        fit_ensemble(model, x, y, cfg, run_log=run_log)
    # Sourced from the run's ensemble_fit telemetry event (same record
    # the CLI's train-ensemble stage logs), not recomputed inline.
    ev = _last_ensemble_fit_event(run_log)
    computed = int(ev["num_members"]) * int(ev["lockstep_epochs"])
    wasted = int(ev["wasted_member_epochs"])
    return {
        "patience": patience,
        "epochs_cap": epochs_cap,
        "members": int(ev["num_members"]),
        "lockstep_epochs": int(ev["lockstep_epochs"]),
        "member_epochs_computed": computed,
        "member_epochs_active": computed - wasted,
        "wasted_member_epochs": wasted,
        "wasted_fraction": round(wasted / computed, 4) if computed else 0.0,
    }


def bench_bootstrap(n_windows: int, n_boot: int = 100, n_chain: int = 10) -> dict:
    """Bootstrap engine comparison at B=100 over ``n_windows`` windows:
    exact multinomial gather vs the fused Pallas Poisson kernel
    (ops/pallas_bootstrap.py).  Chained iterations inside one jit so the
    tunnel dispatch latency doesn't pollute the per-call number."""
    import jax.numpy as jnp

    from apnea_uq_tpu.uq.bootstrap import _bootstrap_core, _pack_rows
    from apnea_uq_tpu.ops.pallas_bootstrap import poisson_bootstrap_sums

    rng = np.random.default_rng(3)
    pv = jnp.asarray(rng.uniform(0.0, 0.25, n_windows), jnp.float32)
    te = jnp.asarray(rng.uniform(0.0, 0.7, n_windows), jnp.float32)
    al = jnp.asarray(rng.uniform(0.0, 0.7, n_windows), jnp.float32)
    mi = jnp.asarray(rng.uniform(0.0, 0.1, n_windows), jnp.float32)
    y = jnp.asarray(rng.integers(0, 2, n_windows), jnp.float32)
    key = jax.random.key(0)

    @jax.jit
    def chain_exact(pv, te, al, mi, y, key):
        def body(i, carry):
            out = _bootstrap_core.__wrapped__(
                pv + carry * 0, te, al, mi, y,
                jax.random.fold_in(key, i), n_boot)
            return jnp.sum(out["overall_mean_variance"]).astype(jnp.float32)
        return jax.lax.fori_loop(0, n_chain, body, jnp.zeros(()))

    v = _pack_rows(pv, te, al, mi, y)

    @jax.jit
    def chain_poisson(v, key):
        def body(i, carry):
            s = poisson_bootstrap_sums(v + carry * 0, jax.random.fold_in(key, i), n_boot)
            return jnp.sum(s[:, 0]).astype(jnp.float32)
        return jax.lax.fori_loop(0, n_chain, body, jnp.zeros(()))

    t_exact = _time(chain_exact, pv, te, al, mi, y, key, reps=2) / n_chain
    t_poisson = _time(chain_poisson, v, key, reps=2) / n_chain
    return {
        "exact_ms": round(t_exact * 1e3, 2),
        "poisson_ms": round(t_poisson * 1e3, 2),
        "speedup": round(t_exact / t_poisson, 1),
    }


def _run_block(run_log, blocks: dict, name: str, fn, *,
               skip: bool = False, unavailable: bool = False,
               reason: str = None):
    """Run ONE bench block in isolation (the tentpole's promotion of the
    old ``_guarded`` helper): the block's outcome is recorded as a
    status record {status: ok|error|skipped|unavailable, seconds,
    error_tail, reason} in ``blocks``, mirrored as a ``bench_block``
    telemetry event, and checkpointed to the progress file — so one
    raising block degrades to a per-block error instead of sinking the
    capture (the main() watchdog still covers hangs).  Returns the
    block's value, or None for any non-ok outcome."""
    value = None
    if unavailable:
        # The backend this block needs is absent (CPU-proxy mode).
        rec = {"status": "unavailable"}
        if reason:
            rec["reason"] = reason
    elif skip:
        rec = {"status": "skipped"}
        if reason:
            rec["reason"] = reason
    else:
        t0 = time.perf_counter()
        try:
            value = fn()
            rec = {"status": "ok",
                   "seconds": round(time.perf_counter() - t0, 3)}
        except Exception as e:  # noqa: BLE001 — a block must not kill the bench
            import traceback

            rec = {"status": "error",
                   "seconds": round(time.perf_counter() - t0, 3),
                   "error_tail":
                       "".join(traceback.format_exception(e))[-800:]}
            run_log.error(f"block:{name}", e)
    blocks[name] = rec
    run_log.event("bench_block", name=name, **rec)
    _progress_record("blocks", blocks)
    return value


def _ctx_entry(blocks: dict, name: str, value):
    """A block's slot in the payload ``context`` section: the measured
    value when ok, a degraded ``{"error": ...}`` field when it raised
    (the shape the pre-v2 ``_guarded`` consumers expect), None when the
    block was skipped or the backend unavailable."""
    rec = blocks.get(name) or {}
    if rec.get("status") == "ok":
        return value
    if rec.get("status") == "error":
        return {"error": rec.get("error_tail", "").strip()
                .splitlines()[-1] if rec.get("error_tail") else "error"}
    return None


def _backend_facts(proxy: bool) -> dict:
    """The payload's ``backend`` section: what backend this capture
    actually ran against (vs what was requested), so a proxy round can
    never masquerade as a device round."""
    try:
        # apnea-lint: disable=single-host-device-enumeration -- bench is a single-process driver; the payload stamps the global backend it measured
        dev = jax.devices()[0]
        facts = {"platform": dev.platform, "device_kind": dev.device_kind}
    except Exception as e:  # noqa: BLE001 — facts are best-effort
        facts = {"platform": "unavailable",
                 "error": f"{type(e).__name__}: {e}"}
    facts["requested"] = (os.environ.get("BENCH_PLATFORM")
                          or ("cpu-proxy" if proxy else "default"))
    return facts


def bench_streamed(model, variables, x_host, n_passes, chunk) -> dict:
    """Streamed-vs-in-HBM overhead at identical shapes (r3 verdict item 5):
    streaming is the framework's scaling story for HBM-exceeding test sets
    (replacing the whole-set-as-one-batch pattern of uq_techniques.py:22),
    and "identical results" was proven in tests while its single-chip cost
    was unmeasured.  Both paths are timed end-to-end INCLUDING host
    assembly of the full (T, M)/(N, M) result — that is what a user of
    either path gets — so the ratio is the true cost of keeping the
    window set in host memory.  MCD streams T stochastic passes; DE
    streams a 10-member deterministic ensemble."""
    from apnea_uq_tpu.models import init_variables
    from apnea_uq_tpu.uq import (
        ensemble_predict,
        ensemble_predict_streaming,
        mc_dropout_predict,
        mc_dropout_predict_streaming,
    )
    from apnea_uq_tpu.uq.predict import stack_member_variables
    from apnea_uq_tpu.utils import prng

    def t_end_to_end(fn, reps=2):
        fn()  # warmup/compile
        best = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    key = prng.stochastic_key(1)
    t_mcd_hbm = t_end_to_end(lambda: np.asarray(mc_dropout_predict(
        model, variables, x_host, n_passes=n_passes, mode="clean",
        batch_size=chunk, key=key,
    )))
    t_mcd_str = t_end_to_end(lambda: mc_dropout_predict_streaming(
        model, variables, x_host, n_passes=n_passes, mode="clean",
        batch_size=chunk, key=key,
    ))

    n_members = 10
    members = stack_member_variables([
        init_variables(model, jax.random.key(s)) for s in range(n_members)
    ])
    de_chunk = int(os.environ.get("BENCH_DE_CHUNK", 2048))
    t_de_hbm = t_end_to_end(lambda: np.asarray(ensemble_predict(
        model, members, x_host, batch_size=de_chunk,
    )))
    t_de_str = t_end_to_end(lambda: ensemble_predict_streaming(
        model, members, x_host, batch_size=de_chunk,
    ))
    return {
        "mcd_inhbm_s": round(t_mcd_hbm, 3),
        "mcd_streamed_s": round(t_mcd_str, 3),
        "mcd_streamed_vs_inhbm": round(t_mcd_str / t_mcd_hbm, 3),
        "de10_inhbm_s": round(t_de_hbm, 3),
        "de10_streamed_s": round(t_de_str, 3),
        "de10_streamed_vs_inhbm": round(t_de_str / t_de_hbm, 3),
        "de_chunk": de_chunk,
    }


def bench_fused(model, variables, x_host, n_passes, chunk) -> dict:
    """Fused-reduction payoff at the bench shapes: the same T-pass MCD
    program timed end-to-end (host fetch included) returning the full
    (T, M) probability matrix vs the fused (4, M) sufficient-statistics
    stack (``stats=('nats', 1e-10)``) — the measured cost of shipping
    the K axis off device, next to the exact D2H byte counts the
    ``eval_predict`` telemetry estimates."""
    from apnea_uq_tpu.uq import mc_dropout_predict
    from apnea_uq_tpu.uq.metrics import N_STAT_ROWS
    from apnea_uq_tpu.utils import prng

    def t_end_to_end(fn, reps=2):
        fn()  # warmup/compile
        best = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    key = prng.stochastic_key(1)
    t_full = t_end_to_end(lambda: np.asarray(mc_dropout_predict(
        model, variables, x_host, n_passes=n_passes, mode="clean",
        batch_size=chunk, key=key,
    )))
    t_fused = t_end_to_end(lambda: np.asarray(mc_dropout_predict(
        model, variables, x_host, n_passes=n_passes, mode="clean",
        batch_size=chunk, key=key, stats=("nats", 1e-10),
    )))
    m = int(np.shape(x_host)[0])
    return {
        "full_probs_s": round(t_full, 3),
        "fused_s": round(t_fused, 3),
        "fused_vs_full": round(t_fused / t_full, 3),
        "d2h_bytes_full": n_passes * m * 4,
        "d2h_bytes_fused": N_STAT_ROWS * m * 4,
    }


def bench_mcd_kernel() -> dict:
    """Isolated ``mcd_kernel`` block (ISSUE 12): XLA-vs-Pallas MCD
    engines and f32-vs-bf16 compute at the FIXED smoke operating point
    (256 windows x T=4 x chunk 64 — deliberately not the headline
    shapes, so every round measures the same cheap point on every chip).
    The speedup ratios are backend-independent-relative metrics
    (``mcd_kernel.xla_vs_pallas`` / ``mcd_kernel.f32_vs_bf16``, like
    ``bootstrap.speedup``), so `telemetry compare`/`trend` gate them
    across the CPU-proxy boundary instead of refusing them as
    backend-bound absolutes.  Off-TPU the pallas engine resolves to its
    XLA fallback (uq/predict.py ``resolve_mcd_engine``); the recorded
    ``pallas_engine`` field names the body that actually ran, so a
    fallback round's ~1.0 ratio reads as what it is.  The bf16 half runs
    only when the bench dtype is bf16 (BENCH_DTYPE=float32 smoke runs
    skip it — CPU emulates bf16 convs orders of magnitude too slowly)."""
    from apnea_uq_tpu.config import ModelConfig
    from apnea_uq_tpu.models import AlarconCNN1D, init_variables
    from apnea_uq_tpu.uq import mc_dropout_predict
    from apnea_uq_tpu.uq.predict import resolve_mcd_engine
    from apnea_uq_tpu.utils import prng

    n_windows, n_passes, chunk = 256, 4, 64
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(n_windows, 60, 4)), jnp.float32)
    key = prng.stochastic_key(5)

    def timed(dtype: str, engine: str) -> float:
        model = AlarconCNN1D(ModelConfig(compute_dtype=dtype))
        variables = init_variables(model, jax.random.key(0))

        def fn(x):
            return jnp.sum(mc_dropout_predict(
                model, variables, x, n_passes=n_passes, mode="clean",
                batch_size=chunk, key=key, engine=engine,
            ))

        return _time(fn, x, reps=3)

    t_xla = timed("float32", "xla")
    t_pallas = timed("float32", "pallas")
    out = {
        "windows": n_windows,
        "passes": n_passes,
        "chunk": chunk,
        "xla_f32_s": round(t_xla, 4),
        "pallas_f32_s": round(t_pallas, 4),
        "xla_vs_pallas": round(t_xla / t_pallas, 3),
        "pallas_engine": resolve_mcd_engine("pallas", "clean", None),
    }
    if _bench_dtype() == "bfloat16":
        t_bf16 = timed("bfloat16", "xla")
        out["xla_bf16_s"] = round(t_bf16, 4)
        out["f32_vs_bf16"] = round(t_xla / t_bf16, 3)
    return out


def bench_de_kernel() -> dict:
    """Isolated ``de_kernel`` block (ISSUE 16): XLA-vs-Pallas DE engines
    at the mcd_kernel block's FIXED smoke operating point (256 windows x
    4 members x chunk 64 — same cheap point on every chip).  The
    ``de_kernel.xla_vs_pallas`` speedup is a backend-independent
    relative metric exactly like ``mcd_kernel.xla_vs_pallas``, so
    `telemetry compare`/`trend` gate it across the CPU-proxy boundary;
    off-TPU the pallas engine resolves to its XLA fallback
    (uq/predict.py ``resolve_de_engine``) and the recorded
    ``pallas_engine`` field names the body that actually ran.  The bf16
    half runs only when the bench dtype is bf16, mirroring mcd_kernel."""
    from apnea_uq_tpu.config import ModelConfig
    from apnea_uq_tpu.models import AlarconCNN1D, init_variables
    from apnea_uq_tpu.uq.predict import (ensemble_predict, resolve_de_engine,
                                         stack_member_variables)

    n_windows, n_members, chunk = 256, 4, 64
    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.normal(size=(n_windows, 60, 4)), jnp.float32)

    def timed(dtype: str, engine: str) -> float:
        model = AlarconCNN1D(ModelConfig(compute_dtype=dtype))
        members = stack_member_variables([
            init_variables(model, jax.random.key(i))
            for i in range(n_members)
        ])

        def fn(x):
            return jnp.sum(ensemble_predict(
                model, members, x, batch_size=chunk, engine=engine,
            ))

        return _time(fn, x, reps=3)

    t_xla = timed("float32", "xla")
    t_pallas = timed("float32", "pallas")
    out = {
        "windows": n_windows,
        "members": n_members,
        "chunk": chunk,
        "xla_f32_s": round(t_xla, 4),
        "pallas_f32_s": round(t_pallas, 4),
        "xla_vs_pallas": round(t_xla / t_pallas, 3),
        "pallas_engine": resolve_de_engine("pallas", None),
    }
    if _bench_dtype() == "bfloat16":
        t_bf16 = timed("bfloat16", "xla")
        out["xla_bf16_s"] = round(t_bf16, 4)
        out["f32_vs_bf16"] = round(t_xla / t_bf16, 3)
    return out


def bench_autotune(run_log) -> dict:
    """Isolated ``autotune`` block (ISSUE 16): a small
    ``window_tile x member_group/pass_group`` sweep through
    ops/autotune.py ``run_autotune`` — the REAL harness `apnea-uq
    autotune` runs, at a deliberately tiny operating point (one serving
    bucket, a 2x2 grid) so the block prices the sweep machinery, not a
    production tuning session.  Emits the harness's own
    ``autotune_cell``/``autotune_result`` telemetry into the bench run
    log, and reports ``autotune.best_vs_default`` — the largest
    measured default-vs-winner speedup across the swept labels, a
    backend-independent relative metric (~1.0 on the CPU fallback
    bodies, where every cell dispatches the same XLA program) that
    gates across the CPU-proxy boundary like the kernel-block ratios.
    The winners are returned, NOT persisted: the bench must never
    install tuned geometry under the production registry's feet."""
    from apnea_uq_tpu.config import ModelConfig
    from apnea_uq_tpu.ops.autotune import run_autotune

    config = ModelConfig(features=(8, 16), kernel_sizes=(5, 3),
                         dropout_rates=(0.1, 0.2))
    document = run_autotune(
        model_config=config, members=3, n_passes=4, windows=64, chunk=32,
        buckets=(16,), window_tiles=(8, 16), groups=(4, 8), reps=2,
        run_log=run_log,
    )
    winners = document["winners"]
    best_label, best_ratio = None, 1.0
    for label, record in sorted(winners.items()):
        if record["best_vs_default"] >= best_ratio:
            best_label, best_ratio = label, record["best_vs_default"]
    return {
        "labels": len(winners),
        "best_label": best_label,
        "best_vs_default": round(best_ratio, 3),
        "winners": {label: {"window_tile": r["window_tile"],
                            "group": r.get("member_group",
                                           r.get("pass_group")),
                            "best_vs_default": r["best_vs_default"]}
                    for label, r in sorted(winners.items())},
    }


def bench_compile_startup(n_windows: int, n_passes: int, chunk: int) -> dict:
    """Cold-vs-warm process start of the MCD hot path, end to end
    (ISSUE 7): run the compile-cost probe subprocess twice against the
    same fresh persistent-cache + program-store directories.  Run 1 is
    the true cold start — trace + lower + XLA backend compile — and run
    2 the warmed start the subsystem buys: a program-store hit (no
    trace/lower) whose backend compile is a persistent-cache disk hit
    (zero fresh XLA compiles, pinned by the probe's counters).  Each run
    reports its in-process acquire/predict split plus the full process
    wall clock (interpreter + jax import included), so the number is
    what an operator actually waits."""
    import shutil
    import subprocess
    import tempfile

    td = tempfile.mkdtemp(prefix="bench_compile_")
    cmd = [
        sys.executable, "-m", "apnea_uq_tpu.compilecache.probe",
        "--cache-dir", os.path.join(td, "xla-cache"),
        "--store-dir", os.path.join(td, "program-store"),
        "--windows", str(n_windows), "--passes", str(n_passes),
        "--chunk", str(chunk), "--dtype", _bench_dtype(),
    ]
    if os.environ.get("BENCH_PLATFORM"):
        cmd += ["--platform", os.environ["BENCH_PLATFORM"]]
    elif _proxy_active():
        # CPU-proxy: the probe subprocesses inherit the tunnel-pinned
        # env, so they need the same explicit retarget this process got.
        cmd += ["--platform", "cpu"]

    def run_probe() -> dict:
        t0 = time.perf_counter()
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=1800)
        wall = time.perf_counter() - t0
        if proc.returncode != 0:
            raise RuntimeError(
                f"compile probe failed rc={proc.returncode}: "
                f"{proc.stderr[-500:]}"
            )
        doc = json.loads(proc.stdout.strip().splitlines()[-1])
        doc["process_wall_s"] = round(wall, 3)
        return doc

    try:
        cold = run_probe()
        warm = run_probe()
    finally:
        # The cache/store pair only exists to span the two probes; on TPU
        # the serialized executables are large, and leaking one pair per
        # bench round would grow /tmp without bound.
        shutil.rmtree(td, ignore_errors=True)
    out = {"cold": cold, "warm": warm}
    if warm["total_s"] > 0:
        out["cold_vs_warm_total"] = round(cold["total_s"] / warm["total_s"],
                                          3)
    if warm["process_wall_s"] > 0:
        out["cold_vs_warm_wall"] = round(
            cold["process_wall_s"] / warm["process_wall_s"], 3)
    return out


def bench_data_plane(n_windows: int, chunk: int) -> dict:
    """Out-of-core data plane vs the monolithic artifact path (ISSUE 9):
    the same synthetic window set saved both ways into a temp registry,
    then the cold stage-start cost measured for each — the full ``.npz``
    decompress-and-materialize versus the sharded store's zero-copy
    memmap open, plus one full streamed pass over the store in
    ``chunk``-row gathers (what a streamed epoch actually reads).  The
    registry emits a ``data_load`` telemetry event per load, so the
    same numbers land in the run log and `telemetry compare` can gate
    them."""
    import shutil
    import tempfile

    from apnea_uq_tpu.data import registry as reg
    from apnea_uq_tpu.data.registry import ArtifactRegistry

    rng = np.random.default_rng(7)
    x = rng.normal(size=(n_windows, 60, 4)).astype(np.float32)
    y = rng.integers(0, 2, n_windows).astype(np.int8)
    arrays = {"x": x, "y": y}
    run_log = _bench_run_log()

    td = tempfile.mkdtemp(prefix="bench_data_")
    try:
        registry = ArtifactRegistry(td)
        registry.save_arrays(reg.WINDOWS, arrays)
        store_key = f"{reg.WINDOWS}:store"
        registry.save_array_store(
            store_key, arrays,
            rows_per_shard=max(1, min(n_windows, 65536)),
        )
        with run_log.stage("data_plane", windows=n_windows, chunk=chunk):
            t0 = time.perf_counter()
            npz = registry.load_arrays(reg.WINDOWS)
            npz_rows = int(np.asarray(npz["x"]).shape[0])
            t_npz = time.perf_counter() - t0

            t0 = time.perf_counter()
            mapped = registry.load_arrays(store_key, mmap=True)
            t_open = time.perf_counter() - t0

            xs = mapped["x"]
            t0 = time.perf_counter()
            rows_read = 0
            for lo in range(0, xs.shape[0], chunk):
                rows_read += len(np.asarray(
                    xs[np.arange(lo, min(lo + chunk, xs.shape[0]))]
                ))
            t_stream = time.perf_counter() - t0
    finally:
        shutil.rmtree(td, ignore_errors=True)
    return {
        "rows": n_windows,
        "npz_load_s": round(t_npz, 4),
        "npz_rows_per_s": round(npz_rows / max(t_npz, 1e-9), 1),
        "store_open_s": round(t_open, 4),
        "store_stream_s": round(t_stream, 4),
        "store_rows_per_s": round(rows_read / max(t_stream, 1e-9), 1),
        # Cold time-to-first-batch: full npz materialization vs the
        # store's mmap open + ONE chunk gather.
        "store_vs_npz_first_batch": round(
            (t_open + t_stream * chunk / max(n_windows, 1))
            / max(t_npz, 1e-9), 4),
    }


def bench_program_audit() -> dict:
    """IR-level audit of the inference zoo (`apnea-uq audit`, ISSUE 8)
    as a CPU subprocess: the bench capture's context records whether the
    lowered programs still honor the structural promises (no f64, no
    cross-member collectives, donation intact, no baked weights, no host
    callbacks) and each program's FLOPs/arithmetic intensity — so a
    round's headline throughput is read next to the IR it was achieved
    with.  Always CPU (lowering only, nothing dispatches), so the block
    costs no device time."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-m", "apnea_uq_tpu.cli.main", "audit", "--json",
         "--programs", "eval-mcd,eval-de"],
        capture_output=True, text=True, timeout=1200, env=env,
        cwd=os.path.dirname(os.path.abspath(__file__)),
    )
    if proc.returncode not in (0, 1) or "{" not in proc.stdout:
        raise RuntimeError(
            f"audit subprocess failed rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-500:]}"
        )
    doc = json.loads(proc.stdout[proc.stdout.index("{"):])
    return {
        "clean": proc.returncode == 0,
        "unsuppressed": doc["summary"]["unsuppressed"],
        "programs": {
            label: {
                "flops": facts["flops"],
                "arithmetic_intensity": facts["arithmetic_intensity"],
            }
            for label, facts in sorted(doc["programs"].items())
        },
    }


def bench_mcd() -> dict:
    from apnea_uq_tpu.config import ModelConfig
    from apnea_uq_tpu.models import AlarconCNN1D, apply_model, init_variables, predict_proba
    from apnea_uq_tpu.uq import mc_dropout_predict
    from apnea_uq_tpu.utils import prng

    # Env knobs allow a small-shape smoke run on CPU (BENCH_WINDOWS=256
    # BENCH_PASSES=4 BENCH_CHUNK=64); defaults are the TPU operating point
    # (chunk 512 measured fastest on v5e; 2048 exceeds HBM at T=50),
    # shrunk to the smoke shapes in CPU-proxy mode.
    n_windows, n_passes, chunk = _shape_knobs()

    rng = np.random.default_rng(2025)
    x = jnp.asarray(rng.normal(size=(n_windows, 60, 4)), jnp.float32)

    # Framework path: bf16 MXU compute, vmap over dropout keys, chunked.
    model_cfg = ModelConfig(compute_dtype=_bench_dtype())
    model = AlarconCNN1D(model_cfg)
    variables = init_variables(model, jax.random.key(0))

    def framework(x, chunk):
        # stochastic_key -> hardware rbg on TPU (threefry mask generation
        # alone costs ~40% of MCD wall-clock there; utils/prng.py).
        return jnp.sum(mc_dropout_predict(
            model, variables, x, n_passes=n_passes, mode="clean",
            batch_size=chunk, key=prng.stochastic_key(1),
        ))

    # The T axis multiplies the chunk's activation footprint; step down on
    # out-of-memory so one bench binary serves every chip size.
    run_log = _bench_run_log()
    with run_log.stage("mcd_framework", snapshot_memory=True,
                       windows=n_windows, passes=n_passes):
        while True:
            try:
                t_framework = _time(framework, x, chunk)
                break
            except Exception as e:
                if chunk <= 128 or not _is_oom(e):
                    raise
                chunk //= 2
        if os.environ.get("BENCH_PROFILE"):
            # One EXTRA steady-state pass under a bounded trace capture,
            # after the timed reps — the profile can never pollute the
            # throughput number, and the artifact lands under the run
            # dir (profile_captured event) like every CLI --profile.
            from apnea_uq_tpu.telemetry.profiler import TraceSession

            with TraceSession(run_log, label="mcd_framework",
                              warmup_steps=0, max_steps=1):
                float(np.asarray(framework(x, chunk)))
    throughput = n_windows / t_framework
    run_log.event("bench_throughput", metric="mcd_t50_inference_throughput",
                  windows_per_s=round(throughput, 1), chunk=chunk)

    # Reference-pattern path on the same chip: float32, one jitted full-set
    # stochastic pass per Python-loop iteration (the sequential np.stack
    # pattern of uq_techniques.py:22), timed over a subset of passes.
    ref_model = AlarconCNN1D(ModelConfig(compute_dtype="float32"))
    ref_vars = init_variables(ref_model, jax.random.key(0))

    @jax.jit
    def one_pass(x, key):
        logits, _ = apply_model(ref_model, ref_vars, x, mode="mcd_clean",
                                dropout_rng=key)
        return jnp.sum(predict_proba(logits))

    naive_passes = max(n_passes // 10, 1)
    def naive(x):
        return sum(one_pass(x, jax.random.key(t)) for t in range(naive_passes))

    # The reference pattern does not fit a 16-GB chip at full size: XLA
    # needs ~72 GB for one 32768-window f32 pass with per-layer threefry
    # dropout masks (whole-set-as-one-batch, uq_techniques.py:22).  Halve
    # the naive path's set until it compiles and normalize per window —
    # throughput is size-independent once the MXU is saturated, and this
    # only *flatters* the baseline (smaller batches lose less to memory
    # pressure).  Each failed attempt costs a full compile over the
    # tunnel (~1 min), so seed the start from the chip's memory limit
    # when the runtime exposes it (measured ~2.2 MB/window of peak
    # temporaries at 32768 windows); the halving loop stays as the
    # correctness net.
    n_naive = n_windows
    dev = jax.devices()[0]  # apnea-lint: disable=single-host-device-enumeration -- bench is a single-process driver sizing against the one chip it dispatches to
    from apnea_uq_tpu.telemetry.memory import device_hbm_limit

    limit = device_hbm_limit(dev)
    if limit:
        est = int(0.6 * limit / 2.2e6)
        while n_naive > 1024 and n_naive > est:
            n_naive //= 2
    with run_log.stage("mcd_reference_pattern", snapshot_memory=True,
                       n_naive=n_naive):
        while True:
            try:
                t_naive_sub = _time(naive, x[:n_naive], warmup=1, reps=2)
                break
            except Exception as e:
                if n_naive <= 1024 or not _is_oom(e):
                    raise
                n_naive //= 2
    t_naive_per_window_pass = t_naive_sub / naive_passes / n_naive
    naive_throughput = 1.0 / (t_naive_per_window_pass * n_passes)

    flops = model_flops_per_window(model_cfg)
    achieved_tflops = throughput * n_passes * flops / 1e12
    kind = dev.device_kind
    peak = _CHIP_PEAK_TFLOPS.get(kind)
    result = {
        "metric": "mcd_t50_inference_throughput",
        "value": round(throughput, 1),
        "unit": "windows/sec/chip",
        "vs_baseline": round(throughput / naive_throughput, 3),
        "baseline": "same-chip reference-pattern reimplementation "
                    "(sequential f32 full-set training=True passes, "
                    "uq_techniques.py:22)",
        "effective": {"windows": n_windows, "passes": n_passes,
                      "chunk": chunk, "n_naive": n_naive},
        "context": {
            "device_kind": kind,
            "model_flops_per_window": flops,
            "achieved_tflops": round(achieved_tflops, 2),
            "peak_bf16_tflops": peak,
            "implied_mfu": round(achieved_tflops / peak, 4) if peak else None,
        },
    }
    # The headline number is banked on disk BEFORE the context blocks run:
    # a backend death inside a context measurement (the one mid-run window
    # the init retry + watchdog don't cover) can no longer lose it.  The
    # context blocks themselves run as ISOLATED blocks in main's
    # orchestrator, which needs this state to time the streamed/fused
    # variants at the exact shapes the headline ran.
    _progress_record("primary", result)
    state = {"model": model, "variables": variables,
             "x": np.asarray(x), "n_passes": n_passes, "chunk": chunk}
    return result, state


def bench_d2h_accounting(n_windows: int, n_passes: int) -> dict:
    """Backend-independent D2H volume accounting of the fused reduction:
    the exact device->host byte contract of one eval at the configured
    shapes — full (T, M) probability matrix vs the fused (4, M)
    sufficient-statistics stack — derived from shapes alone, so the
    CPU-proxy mode can gate the transfer contract with no device."""
    from apnea_uq_tpu.uq.metrics import N_STAT_ROWS

    full = n_passes * n_windows * 4
    fused = N_STAT_ROWS * n_windows * 4
    return {
        "windows": n_windows,
        "passes": n_passes,
        "d2h_bytes_full": full,
        "d2h_bytes_fused": fused,
        "reduction_factor": round(full / fused, 3),
    }


def bench_quality() -> dict:
    """Backend-independent model-quality tooling proof: a fixed-seed
    synthetic calibrated predictor scored with the real calibration
    engine (`analysis/calibration.py` — ECE is sampling noise, Brier ~
    E[p(1-p)]), plus the drift fingerprint scored against itself (PSI ~
    0) and against a deliberately shifted cohort (PSI >> threshold) —
    so a regression in the quality tooling itself gates round-over-round
    like any perf number.  Host-only NumPy at a pinned operating point:
    the scalars are backend-independent and `telemetry compare` gates
    them across the CPU-proxy boundary."""
    import numpy as np

    from apnea_uq_tpu.analysis import fingerprint as fp_mod
    from apnea_uq_tpu.analysis.calibration import \
        calibration_summary_from_arrays

    n = int(os.environ.get("BENCH_QUALITY_WINDOWS", 4096))
    rng = np.random.default_rng(0)
    probs = rng.uniform(0.02, 0.98, n)
    y = (rng.uniform(size=n) < probs).astype(np.float64)
    cal = calibration_summary_from_arrays(probs, y, num_bins=15)
    x = rng.normal(size=(n, 16, 2)).astype(np.float32)
    baseline = fp_mod.compute_fingerprint(x)
    self_report = fp_mod.score_against_baseline(x, baseline)
    shifted_report = fp_mod.score_against_baseline(
        x * 1.5 + 0.75, baseline)
    return {
        "windows": n,
        "ece": round(cal.ece, 6),
        "mce": round(cal.mce, 6),
        "brier": round(cal.brier, 6),
        "self_max_psi": self_report["max_psi"],
        "self_max_ks": self_report["max_ks"],
        "shifted_max_psi": shifted_report["max_psi"],
        "shifted_max_ks": shifted_report["max_ks"],
    }


def bench_serve(run_log, n_passes: int) -> dict:
    """Online serving tier proof (ISSUE 15): build a ServingEngine over
    a fresh-initialized model (weight values never matter to a perf
    block), AOT-warm every bucket-ladder program, then drive the serve
    loop with the seeded load generator and return the final SLO
    summary — p50/p95/p99 request latency, windows/sec, mean queue
    wait, and pad waste.  Backend-aware, not backend-gated: the block
    runs on whatever backend the capture targets (CPU-proxy rounds
    included), the serving telemetry triple lands in the bench run dir,
    and `telemetry compare` marks the absolute latencies backend-bound
    so only the coalescer's pad-waste ratio gates across the proxy
    boundary.

    The block also exercises the online-drift path (ISSUE 17): a
    DriftMonitor scores the loadgen traffic against a seeded
    standard-normal baseline (the loadgen's own distribution, so the
    unshifted half scores PSI ~ 0) while ``--drift-after``-style cohort
    shift kicks in halfway (BENCH_SERVE_DRIFT_AFTER overrides; -1
    disables) — the final summary carries the flipped verdict, proving
    drift detection works end to end at bench cadence.

    Tracing rides along (ISSUE 20): the loadgen runs with exemplar
    tracing armed (BENCH_SERVE_TRACE_EVERY / BENCH_SERVE_TRACE_SLOW_MS
    override the 1-in-8 stream and the 250 ms slow budget), and the
    block asserts the tail-sampling contract — every over-budget
    request produced an exemplar span (over_budget == over_budget_traced
    by construction; a mismatch is a sampler bug, not a perf fact)."""
    import numpy as np

    from apnea_uq_tpu.analysis.fingerprint import compute_fingerprint
    from apnea_uq_tpu.config import ModelConfig, UQConfig
    from apnea_uq_tpu.models import AlarconCNN1D, init_variables
    from apnea_uq_tpu.serving.drift import DriftMonitor
    from apnea_uq_tpu.serving.engine import ServingEngine
    from apnea_uq_tpu.serving.loadgen import run_loadgen

    n_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", 64))
    drift_after = int(os.environ.get("BENCH_SERVE_DRIFT_AFTER",
                                     n_requests // 2))
    trace_every = int(os.environ.get("BENCH_SERVE_TRACE_EVERY", 8))
    trace_slow_ms = float(
        os.environ.get("BENCH_SERVE_TRACE_SLOW_MS", 250.0))
    cfg = ModelConfig(compute_dtype=_bench_dtype())
    model = AlarconCNN1D(cfg)
    variables = init_variables(model, jax.random.key(0))
    engine = ServingEngine(
        model, variables, method="mcd",
        uq=UQConfig(mc_passes=n_passes), run_log=run_log, seed=0,
    )
    engine.warm()
    drift = None
    if drift_after >= 0:
        baseline = compute_fingerprint(
            np.random.default_rng(7).normal(
                size=(2048, cfg.time_steps, cfg.num_channels)
            ).astype(np.float32))
        drift = DriftMonitor(baseline, score_every=64, run_log=run_log)
    summary = run_loadgen(engine, n_requests, max_windows=4, seed=0,
                          drift_after=drift_after if drift_after >= 0
                          else None,
                          drift=drift,
                          trace_every=trace_every,
                          trace_slow_ms=trace_slow_ms)
    if drift is not None:
        summary["drift_verdicts"] = drift.verdicts()
    trace = summary.get("trace") or {}
    if trace and trace.get("over_budget", 0) != trace.get(
            "over_budget_traced", 0):
        raise RuntimeError(
            f"tail-sampling contract broken: {trace['over_budget']} "
            f"requests over the {trace_slow_ms}ms budget but only "
            f"{trace['over_budget_traced']} exemplar spans emitted")
    return summary


#: The keeping-up floor of the capacity sweep: a cell whose fleet
#: completes fewer than this fraction of its offered requests per
#: second has saturated — the knee.
CAPACITY_KEEPUP_RATIO = 0.95


def capacity_knee(cells, p99_budget_ms: float = 0.0):
    """First saturated cell of a capacity curve: achieved/offered below
    :data:`CAPACITY_KEEPUP_RATIO`, or fleet p99 over the budget when one
    is set.  Returns ``(knee_offered_rps, reason)`` — ``(None, None)``
    when the fleet kept up across the whole swept range (a finding too:
    the knee is beyond max(rates))."""
    for cell in cells:
        ratio = cell.get("achieved_ratio")
        p99 = cell.get("p99_ms")
        if ratio is not None and ratio < CAPACITY_KEEPUP_RATIO:
            return (cell["offered_rps"],
                    f"achieved/offered {ratio} < {CAPACITY_KEEPUP_RATIO}")
        if p99_budget_ms > 0 and p99 is not None and p99 > p99_budget_ms:
            return (cell["offered_rps"],
                    f"fleet p99 {p99}ms > {p99_budget_ms}ms budget")
    return None, None


def bench_capacity(run_log, proxy: bool) -> dict:
    """Capacity/saturation sweep (ISSUE 18): how much offered load the
    serving tier absorbs before it stops keeping up.  Each offered-rate
    cell launches BENCH_CAPACITY_REPLICAS serve replica SUBPROCESSES
    (``python -m apnea_uq_tpu.serving.replica``) splitting the fleet
    rate evenly, Poisson arrivals, all sharing ONE warm program store
    (a warm-up replica pre-pays the compiles, so cells measure serving,
    not compilation).  Each cell's replica run dirs are merged with
    telemetry/fleet.py into fleet throughput + p99, yielding the
    saturation curve: offered vs achieved req/s and p99 vs load.  The
    knee is the first cell whose achieved/offered ratio drops below
    0.95, or whose fleet p99 exceeds BENCH_CAPACITY_P99_BUDGET_MS when
    a budget is set.  Backend-aware, not backend-gated: absolutes
    (knee rate, peak throughput) are backend-bound; the lowest cell's
    achieved/offered ratio is a pure keeping-up relative and gates
    across the CPU-proxy boundary.

    Tracing rides along (ISSUE 20): every replica runs with exemplar
    tracing armed (BENCH_CAPACITY_TRACE_EVERY /
    BENCH_CAPACITY_TRACE_SLOW_MS), each cell's replica dirs are merged
    with telemetry/spans.py BEFORE the tree is cleaned up, and the
    block hard-fails when any over-budget request escaped without an
    exemplar span (coverage < 1.0) or two replicas minted the same
    span id."""
    import shutil
    import subprocess
    import tempfile

    from apnea_uq_tpu.telemetry import fleet as fleet_mod
    from apnea_uq_tpu.telemetry import spans as spans_mod

    rates = [float(r) for r in os.environ.get(
        "BENCH_CAPACITY_RATES", "4,8,16").split(",") if r.strip()]
    if len(rates) < 3:
        raise ValueError(
            f"BENCH_CAPACITY_RATES needs >= 3 offered-rate cells for a "
            f"curve with a knee, got {rates}")
    n_replicas = int(os.environ.get("BENCH_CAPACITY_REPLICAS", 2))
    n_requests = int(os.environ.get("BENCH_CAPACITY_REQUESTS", 24))
    p99_budget = float(os.environ.get("BENCH_CAPACITY_P99_BUDGET_MS", 0))
    trace_every = int(os.environ.get("BENCH_CAPACITY_TRACE_EVERY", 4))
    trace_slow_ms = float(
        os.environ.get("BENCH_CAPACITY_TRACE_SLOW_MS", 250.0))

    root = tempfile.mkdtemp(prefix="bench_capacity_")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.abspath(__file__))]
        + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else []))
    # One shared store/cache pair for the whole sweep: the warm-up
    # replica pays the compiles, every later acquisition is a disk hit
    # (the multi-replica warm-serve contract under test).
    env["APNEA_UQ_PROGRAM_STORE_DIR"] = os.path.join(root, "program-store")
    env["APNEA_UQ_XLA_CACHE_DIR"] = os.path.join(root, "xla-cache")
    # Replica subprocesses don't read BENCH_PLATFORM (that's this
    # script's in-process override); hand them the same retarget via
    # JAX_PLATFORMS, which beats sitecustomize's env default.
    if os.environ.get("BENCH_PLATFORM"):
        env["JAX_PLATFORMS"] = os.environ["BENCH_PLATFORM"]
    elif proxy:
        env["JAX_PLATFORMS"] = "cpu"

    def replica_cmd(run_dir, *, requests, rate, seed):
        return [
            sys.executable, "-m", "apnea_uq_tpu.serving.replica",
            "--run-dir", run_dir, "--requests", str(requests),
            "--rate", str(rate), "--arrival", "poisson",
            "--passes", "2", "--seed", str(seed),
            "--trace-every", str(trace_every),
            "--trace-slow-ms", str(trace_slow_ms),
        ]

    def check(proc, tail_len=20):
        out, _ = proc.communicate(timeout=900)
        if proc.returncode != 0:
            tail = "\n".join(out.splitlines()[-tail_len:])
            raise RuntimeError(
                f"capacity replica exited {proc.returncode}:\n{tail}")

    try:
        warm_dir = os.path.join(root, "warmup")
        warm_env = dict(env, APNEA_UQ_REPLICA_ID="cap-warmup")
        check(subprocess.Popen(
            replica_cmd(warm_dir, requests=2, rate=0.0, seed=0),
            env=warm_env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))

        cells = []
        for cell_i, offered in enumerate(rates):
            cell_dirs = []
            procs = []
            for r in range(n_replicas):
                run_dir = os.path.join(root, f"cell{cell_i}", f"rep{r}")
                cell_dirs.append(run_dir)
                rep_env = dict(env,
                               APNEA_UQ_REPLICA_ID=f"cap-c{cell_i}-r{r}")
                procs.append(subprocess.Popen(
                    replica_cmd(run_dir, requests=n_requests,
                                rate=offered / n_replicas,
                                seed=100 * cell_i + r),
                    env=rep_env, stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT, text=True))
            for proc in procs:
                check(proc)
            rollup = fleet_mod.build_rollup(cell_dirs)
            achieved = rollup.requests_per_s or 0.0
            ratio = round(achieved / offered, 4) if offered else None
            # Trace merge happens here, inside the try: the finally
            # below rmtree's the replica dirs, so the exemplar contract
            # must be checked while the serve_trace ledgers still exist.
            p99_phases = {}
            coverage = None
            if trace_every > 0 or trace_slow_ms > 0:
                report = spans_mod.build_trace(cell_dirs)
                if report.collisions:
                    raise RuntimeError(
                        f"capacity cell {cell_i}: span-id collision "
                        f"across replicas: "
                        f"{sorted(report.collisions)[:3]}")
                coverage = report.exemplar_coverage
                if coverage is not None and coverage < 1.0:
                    raise RuntimeError(
                        f"capacity cell {cell_i}: {report.over_budget} "
                        f"requests over the {trace_slow_ms}ms budget "
                        f"but only {report.slow_spans} exemplar spans "
                        f"(coverage {coverage})")
                p99_phases = report.phases.get("p99") or {}
            cell = {
                "offered_rps": offered,
                "achieved_rps": achieved,
                "achieved_ratio": ratio,
                "windows_per_s": rollup.windows_per_s,
                "p99_ms": rollup.p99_ms,
                "queue_wait_mean_s": rollup.queue_wait_mean_s,
                "imbalance_ratio": rollup.imbalance_ratio,
                "queue_share_p99": p99_phases.get("queue_share"),
                "service_share_p99": p99_phases.get("service_share"),
                "exemplar_coverage": coverage,
            }
            cells.append(cell)
            run_log.event(
                "capacity_cell", offered_rps=offered,
                achieved_rps=achieved, achieved_ratio=ratio,
                windows_per_s=rollup.windows_per_s,
                p99_ms=rollup.p99_ms,
                imbalance_ratio=rollup.imbalance_ratio,
                replicas=n_replicas,
                queue_share_p99=p99_phases.get("queue_share"),
                service_share_p99=p99_phases.get("service_share"),
                exemplar_coverage=coverage,
            )
    finally:
        shutil.rmtree(root, ignore_errors=True)

    knee_offered, knee_reason = capacity_knee(cells, p99_budget)
    return {
        "replicas": n_replicas,
        "requests_per_replica": n_requests,
        "arrival": "poisson",
        "rates": rates,
        "p99_budget_ms": p99_budget or None,
        "cells": cells,
        # No knee inside the swept range is a finding too: the fleet
        # kept up everywhere, so the knee is beyond max(rates).
        "knee_offered_rps": knee_offered,
        "knee_reason": knee_reason,
        "peak_windows_per_s": max(
            (c["windows_per_s"] for c in cells
             if c["windows_per_s"] is not None), default=None),
    }


def _start_watchdog():
    """Fail loudly instead of hanging the driver's whole budget: the
    tunneled TPU backend can stall indefinitely at device init (observed:
    ``jax.devices()`` blocking >5 min during a tunnel outage), and a bench
    that never prints looks identical to one still working.  After
    BENCH_WATCHDOG_SECS (default 45 min, 0 disables) emit a
    machine-readable error line and exit non-zero.  Returns the timer;
    ``main`` cancels it once results are in hand so a run finishing near
    the deadline cannot emit both a result line and the error line."""
    import threading

    secs = float(os.environ.get("BENCH_WATCHDOG_SECS", 2700))
    if secs <= 0:
        return None

    def fire():
        _emit_bench_error(
            f"bench did not complete within {secs:.0f}s "
            f"(device/tunnel hang?)"
        )
        os._exit(3)

    timer = threading.Timer(secs, fire)
    timer.daemon = True
    timer.start()
    return timer


def _record_metric_event(run_log, result: dict, role: str) -> None:
    """Mirror one driver-schema metric block into the run log, so the
    telemetry capture carries the same headline numbers the JSON line
    prints (``telemetry summarize`` shows both sides of a run).  The v2
    block-count headlines (unit "blocks": a proxy or mcd-less capture's
    parseable stand-in) are payload envelopes, not measurements — they
    must not land as gateable bench_metric events."""
    if not isinstance(result, dict) or result.get("unit") == "blocks":
        return
    run_log.event(
        "bench_metric", role=role, metric=result.get("metric"),
        value=result.get("value"), unit=result.get("unit"),
        vs_baseline=result.get("vs_baseline"),
    )


def _run_bench(run_log, proxy: bool) -> dict:
    """Orchestrate the bench as isolated blocks and assemble the
    result-v2 payload.  Device blocks are marked ``unavailable`` in
    CPU-proxy mode; the backend-independent blocks (compile, data
    plane, program audit, D2H accounting) run either way, so the exact
    r03-r05 condition still yields a gateable capture."""
    blocks: dict = {}
    state: dict = {}
    ctx_values: dict = {}
    n_windows, n_passes, chunk = _shape_knobs()
    backend = _backend_facts(proxy)
    _progress_record("schema", RESULT_SCHEMA_VERSION)
    _progress_record("proxy", proxy)
    _progress_record("backend", backend)
    # The run dir's own record of the capture mode, so run-directory
    # sources carry the same proxy provenance the JSON payload does
    # (compare/trend refuse cross-backend absolutes for dirs too).
    run_log.event("bench_mode", proxy=proxy,
                  platform=backend.get("platform"),
                  requested=backend.get("requested"))

    de_only = os.environ.get("BENCH_METRIC") == "de_train"
    waste_skip = int(os.environ.get("BENCH_WASTE_EPOCHS", 12)) <= 0

    def run(name, fn, *, device=False, skip=False, reason=None):
        return _run_block(run_log, blocks, name, fn, skip=skip,
                          unavailable=device and proxy, reason=reason)

    primary = secondary = None

    def attach(ctx_key, block_name, value):
        """Land one context block's value in the payload AND the
        progress file the moment it exists (the pre-v2 per-block
        re-record contract: a watchdog fire after N good context blocks
        must not lose their measured values — the folded error payload
        still gates them)."""
        ctx_values[ctx_key] = _ctx_entry(blocks, block_name, value)
        if primary is not None:
            primary.setdefault("context", {})[ctx_key] = \
                ctx_values[ctx_key]
            _progress_record("primary", primary)
        else:
            # No device headline yet (proxy mode / dead mcd block):
            # checkpoint the growing context on its own key; the error
            # and final payload paths both fold it back in.
            _progress_record("context", ctx_values)
    if de_only:
        def de_primary():
            result, waste_state = bench_de_train("primary")
            state["waste"] = waste_state
            return result

        primary = run("de_train", de_primary, device=True)
        for name in ("mcd", "bootstrap", "streamed", "fused", "mcd_kernel",
                     "de_kernel", "autotune", "compile", "program_audit",
                     "data_plane", "d2h_accounting", "quality", "serve",
                     "capacity"):
            run(name, None, skip=True, reason="BENCH_METRIC=de_train")
    else:
        def mcd():
            result, mcd_state = bench_mcd()
            state["mcd"] = mcd_state
            return result

        primary = run("mcd", mcd, device=True)
        boot = run(
            "bootstrap",
            lambda: bench_bootstrap(
                int(os.environ.get("BENCH_BOOT_WINDOWS", 293_000))),
            device=True,
        )
        attach("bootstrap_b100_m293k", "bootstrap", boot)
        ms = state.get("mcd")
        dep_gone = ms is None and not proxy
        streamed = run(
            "streamed",
            (lambda: bench_streamed(ms["model"], ms["variables"],
                                    ms["x"], ms["n_passes"], ms["chunk"]))
            if ms else None,
            device=True,
            skip=bool(os.environ.get("BENCH_SKIP_STREAMED")) or dep_gone,
            reason="mcd block did not complete" if dep_gone else None,
        )
        attach("streamed_overhead", "streamed", streamed)
        fused = run(
            "fused",
            (lambda: bench_fused(ms["model"], ms["variables"], ms["x"],
                                 ms["n_passes"], ms["chunk"]))
            if ms else None,
            device=True,
            skip=bool(os.environ.get("BENCH_SKIP_FUSED")) or dep_gone,
            reason="mcd block did not complete" if dep_gone else None,
        )
        attach("fused_reduction", "fused", fused)
        kernel = run(
            "mcd_kernel", bench_mcd_kernel, device=True,
            skip=bool(os.environ.get("BENCH_SKIP_MCD_KERNEL")),
            reason=("BENCH_SKIP_MCD_KERNEL"
                    if os.environ.get("BENCH_SKIP_MCD_KERNEL") else None),
        )
        attach("mcd_kernel", "mcd_kernel", kernel)
        de_kernel = run(
            "de_kernel", bench_de_kernel, device=True,
            skip=bool(os.environ.get("BENCH_SKIP_DE_KERNEL")),
            reason=("BENCH_SKIP_DE_KERNEL"
                    if os.environ.get("BENCH_SKIP_DE_KERNEL") else None),
        )
        attach("de_kernel", "de_kernel", de_kernel)

        def de():
            result, waste_state = bench_de_train("secondary")
            state["waste"] = waste_state
            return result

        secondary = run("de_train", de, device=True,
                        skip=bool(os.environ.get("BENCH_SKIP_DE")),
                        reason="BENCH_SKIP_DE"
                        if os.environ.get("BENCH_SKIP_DE") else None)

    ws = state.get("waste")
    if waste_skip:
        waste_reason = None
    elif os.environ.get("BENCH_SKIP_DE") and not de_only:
        waste_reason = "BENCH_SKIP_DE"  # deliberate, not a failure
    elif ws is None and not proxy:
        waste_reason = "de_train block did not complete"
    else:
        waste_reason = None
    waste = run(
        "earlystop_waste",
        (lambda: bench_de_earlystop_waste(ws["model"], ws["x"], ws["y"],
                                          ws["batch"])) if ws else None,
        device=True,
        skip=waste_skip or (ws is None and not proxy),
        reason=waste_reason,
    )

    if not de_only:
        # Backend-independent blocks: exactly what a CPU-proxy round
        # can still measure (compile cold/warm through the persistent
        # cache + program store, the host-side data plane, the IR-level
        # audit, and the arithmetic D2H contract).
        compile_v = run(
            "compile",
            lambda: bench_compile_startup(n_windows, n_passes, chunk),
            skip=bool(os.environ.get("BENCH_SKIP_COMPILE")))
        attach("compile", "compile", compile_v)
        audit_v = run("program_audit", bench_program_audit,
                      skip=bool(os.environ.get("BENCH_SKIP_AUDIT")))
        attach("program_audit", "program_audit", audit_v)
        data_v = run("data_plane",
                     lambda: bench_data_plane(n_windows, chunk),
                     skip=bool(os.environ.get("BENCH_SKIP_DATA")))
        attach("data_plane", "data_plane", data_v)
        d2h_v = run("d2h_accounting",
                    lambda: bench_d2h_accounting(n_windows, n_passes))
        attach("d2h_accounting", "d2h_accounting", d2h_v)
        quality_v = run(
            "quality", bench_quality,
            skip=bool(os.environ.get("BENCH_SKIP_QUALITY")),
            reason=("BENCH_SKIP_QUALITY"
                    if os.environ.get("BENCH_SKIP_QUALITY") else None))
        attach("quality", "quality", quality_v)
        serve_v = run(
            "serve", lambda: bench_serve(run_log, n_passes),
            skip=bool(os.environ.get("BENCH_SKIP_SERVE")),
            reason=("BENCH_SKIP_SERVE"
                    if os.environ.get("BENCH_SKIP_SERVE") else None))
        attach("serve", "serve", serve_v)
        capacity_v = run(
            "capacity", lambda: bench_capacity(run_log, proxy),
            skip=bool(os.environ.get("BENCH_SKIP_CAPACITY")),
            reason=("BENCH_SKIP_CAPACITY"
                    if os.environ.get("BENCH_SKIP_CAPACITY") else None))
        attach("capacity", "capacity", capacity_v)
        autotune_v = run(
            "autotune", lambda: bench_autotune(run_log),
            skip=bool(os.environ.get("BENCH_SKIP_AUTOTUNE")),
            reason=("BENCH_SKIP_AUTOTUNE"
                    if os.environ.get("BENCH_SKIP_AUTOTUNE") else None))
        attach("autotune", "autotune", autotune_v)

    n_ok = sum(1 for r in blocks.values() if r.get("status") == "ok")
    headline = primary
    if headline is None:
        # No device headline (proxy mode, or the mcd/de block died):
        # the stdout line still needs the driver schema, so a
        # block-count stand-in keeps it parseable.  compare treats the
        # "blocks" unit as an envelope, never a metric.  The context
        # values attach() checkpointed along the way fold in here.
        headline = {
            "metric": "bench_cpu_proxy" if proxy else "bench_partial",
            "value": n_ok,
            "unit": "blocks",
            "vs_baseline": 0,
        }
        if not de_only:
            headline["context"] = dict(ctx_values)
    waste_home = primary if de_only else secondary
    if waste_home is not None:
        waste_home.setdefault("context", {})["early_stop_waste"] = (
            _ctx_entry(blocks, "earlystop_waste", waste))
    _progress_record("primary", headline)
    if secondary is not None:
        _progress_record("secondary", secondary)

    payload = dict(headline)
    if secondary is not None:
        payload["secondary"] = secondary
    payload["schema"] = RESULT_SCHEMA_VERSION
    payload["proxy"] = proxy
    payload["backend"] = backend
    payload["blocks"] = blocks
    return payload


def _payload_from_progress(fallback: dict) -> dict:
    """The final line is assembled FROM the progress file (when
    enabled), so the printed result and the crash-surviving on-disk
    capture are one and the same artifact and cannot drift."""
    saved = _progress_read()
    if not saved.get("primary"):
        return fallback
    payload = dict(saved["primary"])
    if isinstance(saved.get("secondary"), dict):
        payload["secondary"] = saved["secondary"]
    for key in ("schema", "proxy", "backend", "blocks"):
        if saved.get(key) is not None:
            payload[key] = saved[key]
    return payload


def main() -> None:
    from apnea_uq_tpu.telemetry.logging_shim import narration_to_stderr

    proxy, probe_records = _resolve_backend()
    _set_proxy(proxy)
    watchdog = _start_watchdog()
    _progress_reset()
    # stdout is this script's machine interface — exactly one JSON line.
    # Library narration (e.g. the BENCH_PROFILE capture announcing its
    # trace dir) goes to stderr for the duration; the watchdog's and
    # _emit_bench_error's driver-schema lines print directly to stdout
    # and are unaffected.
    with narration_to_stderr():
        run_log = _bench_run_log()
        # Replay the init-probe trail into the run log (it could not be
        # open during the wait: opening it probes device topology, and
        # jax.devices() against a flapping tunnel is the hang the probes
        # exist to avoid) — the watch autopilot's diagnosable pattern.
        for record in probe_records:
            run_log.event("probe", **record)
        if probe_records and probe_records[-1].get("green"):
            run_log.event("probe_green", attempts=len(probe_records))
        try:
            result = _payload_from_progress(_run_bench(run_log, proxy))
            _record_metric_event(run_log, result, "primary")
            if isinstance(result.get("secondary"), dict):
                _record_metric_event(run_log, result["secondary"],
                                     "secondary")
        except BaseException as e:
            run_log.error("bench", e)
            run_log.close(status="error")
            raise
        n_ok = sum(1 for r in (result.get("blocks") or {}).values()
                   if isinstance(r, dict) and r.get("status") == "ok")
        run_log.close(status="ok" if n_ok else "error")
    if watchdog is not None:
        watchdog.cancel()
    # apnea-lint: disable=bare-print -- the ONE result line of the stdout machine contract (driver schema); must not route through telemetry.log
    print(json.dumps(result))
    if n_ok == 0:
        # Nothing measured: the one remaining whole-capture failure
        # shape (every block errored/skipped) — same exit code as the
        # historical init-retry exhaustion.
        sys.exit(2)


if __name__ == "__main__":
    main()
