"""Benchmark: MC-Dropout T=50 inference throughput (windows/sec/chip).

North-star metric per BASELINE.json: T=50 stochastic passes of the full
~851K-param Alarcón 1D-CNN over SHHS2-shaped (60, 4) windows on one TPU
chip.  The reference has no published numbers (BASELINE.md), so
``vs_baseline`` is measured against a same-hardware implementation of the
reference's execution pattern — T sequential full-set float32 passes, one
Keras-style ``model(x, training=True)`` call per pass
(uq_techniques.py:22) — versus this framework's fused bf16 vmap-over-keys
path.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np


def _time(fn, *args, warmup: int = 1, reps: int = 3) -> float:
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    best = np.inf
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> None:
    from apnea_uq_tpu.config import ModelConfig
    from apnea_uq_tpu.models import AlarconCNN1D, apply_model, init_variables, predict_proba
    from apnea_uq_tpu.uq import mc_dropout_predict

    # Env knobs allow a small-shape smoke run on CPU (BENCH_WINDOWS=256
    # BENCH_PASSES=4 BENCH_CHUNK=64); defaults are the TPU operating point.
    n_windows = int(os.environ.get("BENCH_WINDOWS", 32768))
    n_passes = int(os.environ.get("BENCH_PASSES", 50))
    chunk = int(os.environ.get("BENCH_CHUNK", 2048))

    rng = np.random.default_rng(2025)
    x = jnp.asarray(rng.normal(size=(n_windows, 60, 4)), jnp.float32)

    # Framework path: bf16 MXU compute, vmap over dropout keys, chunked.
    model = AlarconCNN1D(ModelConfig(compute_dtype="bfloat16"))
    variables = init_variables(model, jax.random.key(0))

    def framework(x):
        return mc_dropout_predict(
            model, variables, x, n_passes=n_passes, mode="clean",
            batch_size=chunk, key=jax.random.key(1),
        )

    t_framework = _time(framework, x)
    throughput = n_windows / t_framework

    # Reference-pattern path on the same chip: float32, one jitted full-set
    # stochastic pass per Python-loop iteration (the sequential np.stack
    # pattern of uq_techniques.py:22), timed over a subset of passes.
    ref_model = AlarconCNN1D(ModelConfig(compute_dtype="float32"))
    ref_vars = init_variables(ref_model, jax.random.key(0))

    @jax.jit
    def one_pass(x, key):
        logits, _ = apply_model(ref_model, ref_vars, x, mode="mcd_clean",
                                dropout_rng=key)
        return predict_proba(logits)

    naive_passes = 5
    def naive(x):
        return [one_pass(x, jax.random.key(t)) for t in range(naive_passes)]

    t_naive_sub = _time(naive, x, warmup=1, reps=2)
    t_naive = t_naive_sub * (n_passes / naive_passes)
    naive_throughput = n_windows / t_naive

    print(json.dumps({
        "metric": "mcd_t50_inference_throughput",
        "value": round(throughput, 1),
        "unit": "windows/sec/chip",
        "vs_baseline": round(throughput / naive_throughput, 3),
    }))


if __name__ == "__main__":
    main()
