// Native EDF record decoding for apnea_uq_tpu.data.edf.
//
// EDF data records interleave signals: each record holds
// samples_per_record[i] little-endian int16 samples for every signal i in
// order.  Decoding one signal is therefore a strided gather + affine scale
// over the whole file.  The NumPy fallback does this with a reshape/slice
// copy plus a separate scale pass; here both fuse into one streaming loop
// (single read of the int16 block, single write of the float32 output),
// which is the reference's pyedflib/EDFlib (C) capability re-provided
// in-tree (preprocess_shhs_raw.py:3,129-137).
//
// Build: make -C native   (or apnea_uq_tpu/data/_native.py compiles it on
// first use with g++ -O3 -fPIC -shared -std=c++17).

#include <cstdint>

extern "C" {

// De-interleave signal samples from EDF records and scale to physical
// units.  data: the full int16 record block (n_records * record_words).
// out: n_records * spr float32 physical samples.
void edf_decode_signal(const int16_t* data,
                       int64_t n_records,
                       int64_t record_words,
                       int64_t signal_offset,
                       int64_t spr,
                       float gain,
                       float offset,
                       float* out) {
  for (int64_t r = 0; r < n_records; ++r) {
    const int16_t* src = data + r * record_words + signal_offset;
    float* dst = out + r * spr;
    for (int64_t s = 0; s < spr; ++s) {
      dst[s] = static_cast<float>(src[s]) * gain + offset;
    }
  }
}

// ABI/version probe for the ctypes loader.
int edf_native_abi_version() { return 1; }

}  // extern "C"
