"""The ``apnea-uq topo`` subcommand.

``apnea-uq topo [paths ...] [--json | --format gha] [--rule NAME ...]
[--update-manifest] [--update-docs [--docs PATH]] [--run-dir DIR]`` —
the multi-host topology-readiness gate: AST source rules over the
package (plus ``bench.py``) AND the simulated-topology program sweep
(mesh program families lowered on CPU under every topology of the
canonical rig, nothing dispatched).  Exits 0 when every finding is
suppressed-with-justification, 1 on unsuppressed findings, 2 on usage
errors — the lint/audit/flow contract, same reporters, same suppression
machinery (source findings suppress at the call site, program findings
at the zoo-registration site in ``compilecache/zoo.py``).

Selecting only source rules (``--rule single-host-device-enumeration``)
skips the jax-loading sweep entirely, so the source side stays runnable
anywhere lint runs.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Dict

from apnea_uq_tpu.telemetry import log
from apnea_uq_tpu.utils.env import pin_host_analysis_rig


def topo_program_data(facts) -> Dict[str, Any]:
    """The per-cell payload of ``topo --json`` AND the ``topo_program``
    telemetry event — one projection, so the two machine-readable views
    cannot drift (the audit CLI's pattern)."""
    return {
        "label": facts.label,
        "topology": facts.topology,
        "mesh_ensemble": facts.mesh_ensemble,
        "mesh_data": facts.mesh_data,
        "collectives": sum(facts.collectives.values()),
        "cross_host_collectives": len(facts.cross_host),
        "cross_host_bytes": facts.cross_host_bytes,
        "replication_blowup": facts.replication_blowup,
        "per_device_bytes": facts.per_device_bytes,
        "hbm_budget_bytes": facts.hbm_budget_bytes,
    }


def _emit_events(run_log, facts) -> None:
    for key in sorted(facts):
        d = topo_program_data(facts[key])
        run_log.event(
            "topo_program",
            label=d["label"], topology=d["topology"],
            mesh_ensemble=d["mesh_ensemble"], mesh_data=d["mesh_data"],
            collectives=d["collectives"],
            cross_host_collectives=d["cross_host_collectives"],
            cross_host_bytes=d["cross_host_bytes"],
            replication_blowup=d["replication_blowup"],
            per_device_bytes=d["per_device_bytes"],
            hbm_budget_bytes=d["hbm_budget_bytes"],
        )


def cmd_topo(args, config) -> int:
    from apnea_uq_tpu.audit.manifest import zoo_label_lines
    from apnea_uq_tpu.lint.cli import default_paths
    from apnea_uq_tpu.lint.engine import (
        LintContext, LintResult, apply_suppressions, default_repo_root,
        load_files,
    )
    from apnea_uq_tpu.lint.report import emit_result, resolve_format
    from apnea_uq_tpu.telemetry.logging_shim import narration_to_stderr
    from apnea_uq_tpu.topo.manifest import (
        load_manifest, merge_rows, render_topology_doc, write_manifest,
    )
    from apnea_uq_tpu.topo.rules import (
        RULE_SUBJECTS, TOPO_RULES, TopoContext, run_topo_rules,
    )

    fmt = resolve_format(args)

    def narrate(message: str) -> None:
        # In --json mode stdout is one machine-readable document;
        # progress/skip/manifest lines go to stderr (the audit CLI's
        # contract) so `topo --json | jq .` parses without stripping.
        if fmt == "json":
            with narration_to_stderr():
                log(message)
        else:
            log(message)

    selected = tuple(dict.fromkeys(args.rule)) if args.rule else None
    unknown = [r for r in (selected or ()) if r not in TOPO_RULES]
    if unknown:
        log(f"apnea-uq topo: unknown topo rule(s) {unknown}; "
            f"available: {sorted(TOPO_RULES)}")
        raise SystemExit(2)
    need_programs = (selected is None
                     or any(RULE_SUBJECTS[r] == "program"
                            for r in selected))

    paths = args.paths or default_paths()
    try:
        repo_root = default_repo_root(paths)
        files = load_files(paths, repo_root)
    except (FileNotFoundError, ValueError, SyntaxError) as e:
        log(f"apnea-uq topo: {e}")
        raise SystemExit(2)
    lint_ctx = LintContext(files=files, repo_root=repo_root)
    by_path = {f.path: f for f in files}

    facts: Dict = {}
    manifest = None
    zoo_sf = None
    if need_programs:
        # The sweep is lowering-only and needs the canonical rig: pin
        # CPU + 8 virtual devices before the first jax import (an
        # already-imported jax, e.g. under the test rig, is left alone —
        # the helper no-ops and returns False).
        pin_host_analysis_rig()

        from apnea_uq_tpu.topo.capture import sweep_topologies

        facts, skipped, failures = sweep_topologies(config)
        for name, reason in skipped:
            narrate(f"topo: topology {name} SKIPPED — {reason}")
        if failures:
            for key, error in sorted(failures.items()):
                log(f"topo: capturing {key} FAILED — {error}")
            raise SystemExit(2)
        if not facts:
            log("topo: no topology of the simulated sweep fits this "
                "rig's device count — run on the canonical 8-device "
                "CPU rig (JAX_PLATFORMS=cpu with "
                "--xla_force_host_platform_device_count=8)")
            raise SystemExit(2)

        manifest = load_manifest(args.manifest)
        if args.update_manifest:
            manifest = merge_rows(facts, prior=manifest)
        elif manifest is None:
            log(f"topo: no manifest at {args.manifest!r} — run "
                f"`apnea-uq topo --update-manifest` once to record the "
                f"golden per-topology rows")
            raise SystemExit(2)

        zoo_abs, label_lines = zoo_label_lines()
        zoo_root = default_repo_root([zoo_abs])
        zoo_sf = load_files([zoo_abs], zoo_root)[0]
    else:
        zoo_abs, label_lines = "", {}

    context = TopoContext(
        lint=lint_ctx, programs=facts, manifest=manifest,
        zoo_path=(zoo_sf.path if zoo_sf is not None else ""),
        label_lines=label_lines,
    )
    findings = run_topo_rules(context, rules=selected)
    resolved = []
    for f in findings:
        sf = by_path.get(f.path)
        if sf is None and zoo_sf is not None and f.path == zoo_sf.path:
            sf = zoo_sf
        resolved.append(apply_suppressions(f, sf) if sf is not None
                        else f)
    result = LintResult(
        findings=resolved,
        files_scanned=len(files),
        rules_run=selected or tuple(sorted(TOPO_RULES)),
        scanned_paths=tuple(f.path for f in files),
    )

    import contextlib

    with contextlib.ExitStack() as stack:
        if getattr(args, "run_dir", None) and facts:
            from apnea_uq_tpu.telemetry import start_run

            run_log = stack.enter_context(
                start_run(args.run_dir, stage="topo", config=config,
                          argv=sys.argv[1:]))
            narrate(f"telemetry -> {args.run_dir}")
            _emit_events(run_log, facts)

        if need_programs and args.update_manifest:
            if result.unsuppressed:
                narrate("topo: manifest NOT updated — unsuppressed "
                        "finding(s) remain; fix (or suppress) them, "
                        "then re-run --update-manifest")
            else:
                # `manifest` already holds the merged rows the rules
                # just validated — persist exactly those (the audit
                # CLI's write-after-pass discipline).
                write_manifest(args.manifest, manifest)
                narrate(f"manifest -> {args.manifest} "
                        f"({len(facts)} cell(s) updated)")

        if args.update_docs:
            rows = load_manifest(args.manifest)
            if rows is None:
                narrate("topo: docs NOT updated — no manifest to render "
                        "(run --update-manifest first)")
            else:
                from apnea_uq_tpu.utils.io import atomic_write_text

                docs_path = args.docs or os.path.join(
                    default_repo_root(paths), "docs", "TOPOLOGY.md")
                os.makedirs(os.path.dirname(os.path.abspath(docs_path)),
                            exist_ok=True)
                atomic_write_text(docs_path, render_topology_doc(rows))
                narrate(f"topology doc -> {docs_path}")

        emit_result(result, fmt, json_extra={
            "programs": {
                f"{label}@{topology}": topo_program_data(
                    facts[(topology, label)])
                for topology, label in sorted(facts)
            },
        })
    return 1 if result.unsuppressed else 0


def register(sub, add_config_arg, load_config_fn) -> None:
    """Attach the ``topo`` subcommand to the CLI's subparser registry
    (same lazy-config wiring as audit)."""
    from apnea_uq_tpu.lint.report import add_format_args
    from apnea_uq_tpu.topo.manifest import DEFAULT_MANIFEST_PATH

    p = sub.add_parser(
        "topo",
        help="Multi-host topology-readiness gate: AST rules for "
             "process-local enumeration / primary-only I/O / lockstep "
             "collective discipline, plus the mesh program families "
             "lowered under a sweep of simulated topologies on CPU "
             "(collective sets, cross-host payload, per-device HBM vs "
             "budget) against the checked-in topo/manifest.json.")
    add_config_arg(p)
    p.add_argument("paths", nargs="*", default=None,
                   help="Files/directories for the source rules; "
                        "default: the apnea_uq_tpu package plus "
                        "bench.py beside it.")
    add_format_args(p)
    p.add_argument("--rule", action="append", default=[], metavar="NAME",
                   help="Run only this topo rule (repeatable); default: "
                        "all — see docs/LINT.md \"Topology rules\".  "
                        "Selecting only source rules skips the "
                        "jax-loading topology sweep.")
    p.add_argument("--manifest", default=DEFAULT_MANIFEST_PATH,
                   help="Manifest path (default: the in-package golden "
                        "apnea_uq_tpu/topo/manifest.json).")
    p.add_argument("--update-manifest", action="store_true",
                   help="Regenerate the per-(program, topology) rows "
                        "from the live sweep (stale rows pruned); "
                        "written only when every rule passes.  "
                        "Gather-style cross-host collectives still "
                        "fail: no manifest can bless them.")
    p.add_argument("--update-docs", action="store_true",
                   help="Regenerate the generated docs/TOPOLOGY.md "
                        "from the manifest rows.")
    p.add_argument("--docs", default=None,
                   help="With --update-docs: destination path (default "
                        "<repo>/docs/TOPOLOGY.md).")
    p.add_argument("--run-dir", default=None,
                   help="Telemetry run directory: persists one "
                        "topo_program event per (program, topology) "
                        "cell (cross-host bytes, per-device memory), "
                        "gateable by `telemetry compare` as "
                        "topo.<label>.<topology>.cross_host_bytes.")
    p.set_defaults(fn=lambda args: cmd_topo(args, load_config_fn(args)))
