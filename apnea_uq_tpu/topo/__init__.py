"""Multi-host topology-readiness static analysis (``apnea-uq topo``).

Fourth rule family on the PR-4 lint engine: the hazards that only
surface at pod scale — host-local device enumeration where process-local
is required, primary-only I/O left unguarded under multiprocess,
lockstep collectives inside per-process-divergent branches, cross-host
collective payloads, per-device HBM overflow under a topology — checked
statically on the CPU rig, before any multi-host window.

- :mod:`apnea_uq_tpu.topo.capture` — lower the mesh program families
  under a sweep of simulated topologies (the PR-7 audit seam);
- :mod:`apnea_uq_tpu.topo.rules` — the source + program rule registry;
- :mod:`apnea_uq_tpu.topo.manifest` — the per-(label, topology) golden
  rows and the generated ``docs/TOPOLOGY.md`` render;
- :mod:`apnea_uq_tpu.topo.cli` — the subcommand (shared reporters,
  exit 0/1/2, suppression machinery).
"""

from apnea_uq_tpu.topo.rules import (  # noqa: F401
    TOPO_RULES,
    TopoContext,
    run_topo_rules,
)
