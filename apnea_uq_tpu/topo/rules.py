"""The topology rule family: multi-host readiness, checked statically.

Fourth rule family on the lint engine — same :class:`Finding` type,
severities, suppression mechanism and reporters — covering the hazard
class that only surfaces under ``jax.distributed`` multi-host meshes,
which is exactly the hardware this repo rarely holds.  Two subjects:

**Source rules** (AST, anchored at the offending call site):

- ``single-host-device-enumeration`` — ``jax.devices()`` (and its
  ``[0]`` head-grab) in library code: under multiprocess the global
  list contains non-addressable remote devices, so "the device" must be
  ``jax.local_devices()[0]`` and per-process work must enumerate
  locally.  The deliberate global-enumeration sites (mesh construction,
  the run-log topology stamp) carry justified suppressions.
- ``unguarded-primary-io`` — a file/registry write inside a
  mesh-parallel function with no ``process_index() == 0`` /
  ``is_primary()`` guard: under multiprocess every process races the
  same path (the run-log already guards; this generalizes that
  discipline to checkpoints, registry artifacts, and plots).
- ``lockstep-collective-discipline`` — ``host_values`` /
  ``process_allgather`` inside a branch whose condition can diverge per
  process (process index, filesystem/env state, exception handlers):
  the processes that skip the branch never join the collective and the
  ones that enter it hang forever.

**Program rules** (per lowered (program, topology) cell from the
simulated-topology sweep, anchored at the zoo-registration site like the
audit rules):

- ``topo-collective-manifest`` — the (collective set, mesh layout) of
  each mesh-family program under each swept topology must match the
  checked-in ``topo/manifest.json`` row.
- ``topo-cross-host-payload`` — gather-style collectives over a
  host-spanning axis are unconditional violations (their wire cost
  scales with the process count); reduce-style cross-host traffic is
  charged against the spec's DCN budget.
- ``topo-hbm-budget`` — the compiled per-device memory estimate must
  fit the topology spec's per-device HBM budget.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from apnea_uq_tpu.lint import astwalk
from apnea_uq_tpu.lint.engine import (
    SEVERITIES,
    Finding,
    LintContext,
    Rule,
)
from apnea_uq_tpu.topo.capture import GATHER_STYLE_PRIMS, _prim_of

TOPO_RULES: Dict[str, Rule] = {}
# Which subject each rule checks: "source" rules see the parsed files,
# "program" rules the per-(label, topology) sweep facts.  The CLI uses
# this to skip the (jax-loading) sweep when only source rules run.
RULE_SUBJECTS: Dict[str, str] = {}


def register_topo_rule(name: str, severity: str, summary: str, *,
                       subject: str):
    """Decorator twin of :func:`apnea_uq_tpu.lint.engine.register_rule`
    for the topology family; ``subject`` is ``source`` or ``program``."""
    if severity not in SEVERITIES:
        raise ValueError(
            f"severity must be one of {SEVERITIES}, got {severity!r}")
    if subject not in ("source", "program"):
        raise ValueError(f"subject must be source|program, got {subject!r}")

    def wrap(fn: Callable[["TopoContext"], Iterable[Finding]]):
        TOPO_RULES[name] = Rule(name=name, severity=severity,
                                summary=summary, check=fn)
        RULE_SUBJECTS[name] = subject
        return fn

    return wrap


@dataclasses.dataclass
class TopoContext:
    """Everything a topo rule sees: the parsed in-scope files (source
    rules) and the simulated-topology sweep facts plus the
    zoo-registration anchor (program rules).  ``programs`` maps
    ``(topology name, label)`` to
    :class:`~apnea_uq_tpu.topo.capture.TopoProgramFacts`; ``manifest``
    maps label -> topology -> golden row (None = no manifest yet)."""

    lint: Optional[LintContext] = None
    programs: Dict[Tuple[str, str], Any] = dataclasses.field(
        default_factory=dict)
    manifest: Optional[Dict[str, Dict[str, Any]]] = None
    zoo_path: str = ""
    label_lines: Dict[str, int] = dataclasses.field(default_factory=dict)

    def finding(self, rule: str, label: str, message: str) -> Finding:
        return Finding(
            rule=rule, severity=TOPO_RULES[rule].severity,
            path=self.zoo_path, line=self.label_lines.get(label, 1),
            message=f"{label}: {message}",
        )


# ------------------------------------------------------- source rules --

# The one blessed replacement: process-local enumeration.
_LOCAL_SPELLING = "jax.local_devices()"


@register_topo_rule(
    "single-host-device-enumeration", "error",
    "jax.devices() enumerates the GLOBAL device list: under a "
    "multi-process mesh it contains non-addressable remote devices, so "
    "per-process work (memory stats, platform probes, local placement) "
    "must use jax.local_devices() instead",
    subject="source",
)
def check_device_enumeration(context: "TopoContext"
                             ) -> Iterable[Finding]:
    for sf in context.lint.files:
        aliases = astwalk.import_aliases(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = astwalk.canonical_call(node, aliases)
            if name != "jax.devices" or node.args or node.keywords:
                continue
            yield Finding(
                rule="single-host-device-enumeration",
                severity=TOPO_RULES[
                    "single-host-device-enumeration"].severity,
                path=sf.path, line=node.lineno,
                message=(
                    "jax.devices() is host-global: under multiprocess "
                    "its entries include other hosts' devices (a [0] "
                    "head-grab can land on a non-addressable remote "
                    f"device) — use {_LOCAL_SPELLING} for process-local "
                    "work, or suppress with the reason this site "
                    "genuinely wants the global list"),
            )


# Calls whose terminal name is a write effect when reached under a
# multi-process mesh: the shared atomic writers, raw writes, and the
# save_* persistence surface (checkpoints, registry artifacts, plots).
_WRITE_CALL_NAMES = frozenset({
    "atomic_write_json", "atomic_write_text", "atomic_write_bytes",
})
_WRITE_CALL_PREFIXES = ("save", "adopt_array_store")
_NP_SAVE = frozenset({"save", "savez", "savez_compressed", "savetxt"})
_WRITE_MODES = ("w", "a", "x")

# Markers that a function participates in mesh-parallel execution: a
# mesh is built/bound/passed, shard_map is used, or the distributed
# runtime / lockstep helpers appear.
_MESH_MARKERS = frozenset({
    "make_mesh", "make_mesh_from_config", "shard_map", "host_values",
    "process_allgather", "build_mesh",
})

# Guard spellings: a conditional mentioning one of these is the
# primary-process discipline the rule wants to see.
_GUARD_MARKERS = ("process_index", "is_primary", "primary")


def _terminal_name(call: ast.Call) -> Optional[str]:
    name = astwalk.call_name(call)
    return name.split(".")[-1] if name else None


def _is_write_call(call: ast.Call) -> bool:
    name = _terminal_name(call)
    if name is None:
        return False
    if name in _WRITE_CALL_NAMES:
        return True
    if name == "to_csv":
        return True
    if any(name == p or name.startswith(p + "_")
           for p in _WRITE_CALL_PREFIXES):
        # save_config on a fresh path is still multiprocess-racy; the
        # whole save_* persistence surface counts.
        return True
    full = astwalk.call_name(call) or ""
    if full.split(".")[0] in ("np", "numpy") and name in _NP_SAVE:
        return True
    if name == "replace" and (full.startswith("os.")
                              or full == "replace"):
        return full.startswith("os.")
    if isinstance(call.func, ast.Name) and call.func.id == "open":
        mode = None
        if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
            mode = call.args[1].value
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        return isinstance(mode, str) and any(
            m in mode for m in _WRITE_MODES)
    return False


def _mesh_parallel(fn: ast.AST) -> bool:
    """Does this function visibly participate in mesh execution?  A
    ``mesh`` parameter/local/keyword, a mesh constructor, shard_map, or
    the distributed helpers."""
    args = getattr(fn, "args", None)
    if args is not None:
        names = [a.arg for a in (args.args + args.kwonlyargs
                                 + args.posonlyargs)]
        if "mesh" in names:
            return True
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and node.id == "mesh":
            return True
        if isinstance(node, ast.keyword) and node.arg == "mesh":
            return True
        if isinstance(node, ast.Call):
            name = _terminal_name(node)
            if name in _MESH_MARKERS:
                return True
        if isinstance(node, ast.Attribute) and node.attr == "distributed":
            return True
    return False


def _guarded(fn: ast.AST, call: ast.Call) -> bool:
    """Is ``call`` under a primary-process guard?  Either an enclosing
    ``if`` whose test mentions a guard marker, or an early-return guard
    (an ``if`` mentioning a marker whose body returns/raises) anywhere
    above the call in the function body."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        test_src = ast.dump(node.test)
        if not any(m in test_src for m in _GUARD_MARKERS):
            continue
        if any(sub is call for sub in ast.walk(node)):
            return True
        returns = any(isinstance(s, (ast.Return, ast.Raise))
                      for s in node.body)
        if returns and node.lineno < call.lineno:
            return True
    return False


@register_topo_rule(
    "unguarded-primary-io", "error",
    "a file/registry write inside a mesh-parallel function with no "
    "process_index()==0 / is_primary() guard: under a multi-process "
    "mesh every process races the same path (the run-log and compile "
    "cache already guard; checkpoints, artifacts and plots must too)",
    subject="source",
)
def check_unguarded_primary_io(context: "TopoContext"
                               ) -> Iterable[Finding]:
    for sf in context.lint.files:
        # A write inside a nested function is visited from both the
        # enclosing and the nested def; one finding per site.
        reported: set = set()
        for fn in ast.walk(sf.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if not _mesh_parallel(fn):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if not _is_write_call(node):
                    continue
                mark = (sf.path, node.lineno)
                if mark in reported:
                    continue
                if _guarded(fn, node):
                    continue
                reported.add(mark)
                name = _terminal_name(node)
                yield Finding(
                    rule="unguarded-primary-io",
                    severity=TOPO_RULES["unguarded-primary-io"].severity,
                    path=sf.path, line=node.lineno,
                    message=(
                        f"{name}(...) in mesh-parallel `{fn.name}` has "
                        f"no primary-process guard — under "
                        f"jax.distributed every process executes this "
                        f"write against the same path; wrap it in `if "
                        f"is_primary():` (utils/multihost.py) or "
                        f"justify why every process must write"),
                )


# Branch-test spellings that can differ per process: the process's own
# identity, per-host filesystem/env state, anything wall-clock or
# random, and exception handlers (an error on one host is not an error
# on all).
_DIVERGENT_TEST_MARKERS = (
    "process_index", "process_count", "is_primary", "local_devices",
    "exists", "isfile", "isdir", "environ", "getenv", "getpid",
    "random", "perf_counter", "time.time", "monotonic",
)
_LOCKSTEP_CALLS = frozenset({
    "host_values", "_host_values", "_host_predictions",
    "process_allgather",
})


def _divergent_reason(test: ast.AST) -> Optional[str]:
    src = ast.dump(test)
    for marker in _DIVERGENT_TEST_MARKERS:
        head = marker.split(".")[-1]
        if f"'{head}'" in src or f"id='{head}'" in src:
            return head
    return None


@register_topo_rule(
    "lockstep-collective-discipline", "error",
    "host_values()/process_allgather() are lockstep collectives under "
    "a multi-process mesh: calling them inside a branch whose condition "
    "can diverge per process (process index, filesystem/env state, an "
    "exception handler) deadlocks the processes that skipped the branch",
    subject="source",
)
def check_lockstep_discipline(context: "TopoContext"
                              ) -> Iterable[Finding]:
    severity = TOPO_RULES["lockstep-collective-discipline"].severity
    for sf in context.lint.files:
        if sf.path.replace("\\", "/").endswith("utils/multihost.py"):
            # The helper's own fully-addressable fast path branches on
            # a property of the GLOBAL array (identical on every
            # process) — the one sanctioned branch.
            continue
        for fn_node, body in astwalk.scopes(sf.tree):
            if fn_node is None:
                continue
            yield from _scan_lockstep(sf, fn_node, severity)


def _scan_lockstep(sf, fn: ast.AST, severity: str) -> Iterable[Finding]:
    def emit(call: ast.Call, why: str) -> Finding:
        name = _terminal_name(call)
        return Finding(
            rule="lockstep-collective-discipline", severity=severity,
            path=sf.path, line=call.lineno,
            message=(
                f"{name}(...) is a lockstep collective, but this call "
                f"sits in a branch that can diverge per process "
                f"({why}) — a process that skips it never joins the "
                f"allgather and the others hang; hoist the collective "
                f"out of the branch or make the condition provably "
                f"process-invariant"),
        )

    def walk(node: ast.AST, divergent: Optional[str]) -> Iterable[Finding]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            return
        if isinstance(node, ast.If):
            why = _divergent_reason(node.test) or divergent
            for child in node.body + node.orelse:
                yield from walk(child, why)
            return
        if isinstance(node, ast.Try):
            for child in node.body + node.orelse + node.finalbody:
                yield from walk(child, divergent)
            for handler in node.handlers:
                for child in handler.body:
                    yield from walk(child, divergent
                                    or "exception handler")
            return
        if isinstance(node, ast.Call) and divergent:
            name = _terminal_name(node)
            if name in _LOCKSTEP_CALLS:
                yield emit(node, f"condition reads `{divergent}`"
                           if divergent != "exception handler"
                           else "an exception handler runs only where "
                                "the error happened")
        for child in ast.iter_child_nodes(node):
            yield from walk(child, divergent)

    for stmt in fn.body:
        yield from walk(stmt, None)


# ------------------------------------------------------ program rules --

@register_topo_rule(
    "topo-collective-manifest", "error",
    "each mesh-family program's (collective set, mesh layout) under "
    "each swept topology must match the checked-in topo/manifest.json "
    "row — a refactor that grows the collective set or reshapes the "
    "layout fails CI against a reviewable file",
    subject="program",
)
def check_topo_manifest(context: "TopoContext") -> Iterable[Finding]:
    if context.manifest is None:
        return
    for (topology, label), f in sorted(context.programs.items()):
        row = (context.manifest.get(label) or {}).get(topology)
        if row is None:
            yield context.finding(
                "topo-collective-manifest", label,
                f"no manifest row for topology {topology} — run "
                f"`apnea-uq topo --update-manifest` to record its "
                f"per-topology budget",
            )
            continue
        captured = {
            "mesh": {"ensemble": f.mesh_ensemble, "data": f.mesh_data},
            "collectives": dict(f.collectives),
            "cross_host": list(f.cross_host),
        }
        if captured != {k: row.get(k) for k in captured}:
            yield context.finding(
                "topo-collective-manifest", label,
                f"topology {topology} drift: program lowers with "
                f"{captured} but the manifest records "
                f"{ {k: row.get(k) for k in captured} } — an intended "
                f"change needs `--update-manifest`",
            )


@register_topo_rule(
    "topo-cross-host-payload", "error",
    "gather-style collectives over a host-spanning axis scale their "
    "wire cost with the process count (unconditional violation); "
    "reduce-style cross-host traffic must fit the topology spec's DCN "
    "budget",
    subject="program",
)
def check_cross_host_payload(context: "TopoContext") -> Iterable[Finding]:
    for (topology, label), f in sorted(context.programs.items()):
        scaling = [k for k in f.cross_host
                   if _prim_of(k) in GATHER_STYLE_PRIMS]
        if scaling:
            yield context.finding(
                "topo-cross-host-payload", label,
                f"topology {topology}: gather-style cross-host "
                f"collective(s) {scaling} replicate "
                f"{f.replication_blowup}x across hosts — their payload "
                f"scales with the process count, so no budget can bless "
                f"them; reduce on-device or keep the gather within a "
                f"host",
            )
        if f.cross_host_bytes > f.cross_host_budget_bytes:
            yield context.finding(
                "topo-cross-host-payload", label,
                f"topology {topology}: {f.cross_host_bytes} cross-host "
                f"collective bytes exceed the spec's DCN budget "
                f"{f.cross_host_budget_bytes} (keys {f.cross_host}) — "
                f"the data axis must stay within hosts so its psum "
                f"rides ICI",
            )


@register_topo_rule(
    "topo-hbm-budget", "error",
    "the compiled per-device memory estimate of each mesh-family "
    "program must fit the topology spec's per-device HBM budget — a "
    "replicated buffer that should shard shows up here before any "
    "multi-host window",
    subject="program",
)
def check_hbm_budget(context: "TopoContext") -> Iterable[Finding]:
    for (topology, label), f in sorted(context.programs.items()):
        if f.per_device_bytes is None:
            continue
        if f.per_device_bytes > f.hbm_budget_bytes:
            yield context.finding(
                "topo-hbm-budget", label,
                f"topology {topology}: per-device memory estimate "
                f"{f.per_device_bytes} bytes exceeds the spec's HBM "
                f"budget {f.hbm_budget_bytes} (mesh "
                f"{f.mesh_ensemble}x{f.mesh_data}) — shard or stream "
                f"the overflowing buffers before a device OOM proves "
                f"it on hardware",
            )


def run_topo_rules(
    context: TopoContext,
    *,
    rules: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run the (selected) topo rules over ``context``; findings come
    back sorted — suppressions are the caller's job (source findings
    resolve against their own file, program findings against zoo.py)."""
    if rules is None:
        selected = tuple(sorted(TOPO_RULES))
    else:
        selected = tuple(dict.fromkeys(rules))
    unknown = [r for r in selected if r not in TOPO_RULES]
    if unknown:
        raise ValueError(
            f"unknown topo rule(s) {unknown}; "
            f"available: {sorted(TOPO_RULES)}")
    findings: List[Finding] = []
    for name in selected:
        if RULE_SUBJECTS[name] == "source" and context.lint is None:
            continue
        findings.extend(TOPO_RULES[name].check(context))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings
