"""Lower the mesh program families under a sweep of simulated topologies.

Rides the PR-7 audit seam: a :class:`~apnea_uq_tpu.audit.capture
.CaptureStore` is pushed around the real no-dispatch entry points
(``record_memory_only=True`` predictors, ``compile_only=True`` trainers),
once per :class:`~apnea_uq_tpu.parallel.topology.TopologySpec` of the
sweep, each over a mesh built BY that spec — so the captured jaxprs,
collectives, payload bytes, and compiled per-device memory facts are the
programs the topology-driven mesh construction would actually dispatch.
Host boundaries are simulated by the spec over the real (virtual-CPU)
devices: the cross-host classification is pure layout math
(:func:`~apnea_uq_tpu.parallel.topology.axis_spans_hosts`), which is all
the static analysis needs.

The distilled :class:`TopoProgramFacts` are plain data, so the rules
(:mod:`apnea_uq_tpu.topo.rules`) stay jax-free and tests inject
violations as synthetic facts — including topologies (2x8, 4x8) larger
than any CPU rig can lower today.
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING, Dict, List, Optional, Tuple

if TYPE_CHECKING:  # import-time jax freedom: the parallel package pulls
    # jax at import, and the topo source rules (and the CLI parser
    # registration) must stay runnable where jax is unusable — the
    # topology helpers are imported inside the functions that need them.
    from apnea_uq_tpu.parallel.topology import TopologySpec

# Canonical sweep shapes: the audit's own smoke shapes — the checked
# invariants (collectives, payload scaling, per-device footprint vs a
# fixed budget at these shapes) are structural, so tiny shapes keep the
# three-topology sweep a CPU-seconds affair.
TOPO_WINDOWS = 64
TOPO_WINDOW_SHAPE = (60, 4)
TOPO_BATCH = 32
TOPO_PASSES = 4
TOPO_MEMBERS = 4
TOPO_TRAIN_BATCH = 16

# The mesh program families the sweep lowers per topology: one fused
# predict family per UQ method plus both trainer epochs — the programs
# that actually ride the (ensemble, data) mesh.  tests/test_topo.py
# pins that every label here exists in the compile-cache zoo, and the
# manifest-coverage test pins a committed row per (label, topology).
MESH_FAMILY_LABELS: Tuple[str, ...] = (
    "mcd_predict_fused",
    "de_predict_fused",
    "train_epoch",
    "val_loss",
    "ensemble_epoch",
)

# Collectives whose moved bytes GROW with the axis size (each
# participant receives every other shard): over a host-spanning axis
# their wire cost scales with the process count — the "payload scales
# with process count" hazard class.  Reduce-style collectives move
# O(payload) regardless of axis size (ring all-reduce).
GATHER_STYLE_PRIMS = frozenset({
    "all_gather", "all_to_all", "ppermute", "collective_permute",
})


@dataclasses.dataclass
class TopoProgramFacts:
    """One (program, topology) cell of the sweep — jax-free to read."""

    label: str
    topology: str                    # spec name, e.g. "2x4"
    mesh_ensemble: int
    mesh_data: int
    collectives: Dict[str, int]      # "psum[data]" -> count
    collective_payloads: Dict[str, int]   # same keys -> operand bytes
    cross_host: List[str]            # keys whose axes span hosts
    cross_host_bytes: int            # modeled DCN traffic, see below
    replication_blowup: int          # max axis-size factor charged
    per_device_bytes: Optional[int]  # compiled memory-analysis peak
    hbm_budget_bytes: int
    cross_host_budget_bytes: int


def _collective_axes(key: str) -> Tuple[str, ...]:
    if "[" not in key:
        return ()
    inner = key[key.index("[") + 1:].rstrip("]")
    return tuple(a for a in inner.split(",") if a)


def _prim_of(key: str) -> str:
    return key.split("[", 1)[0]


def distill_facts(program, spec: "TopologySpec", e: int, d: int,
                  ) -> TopoProgramFacts:
    """Project one captured :class:`ProgramAudit` onto one topology.

    The cross-host traffic model is first-order and documented:
    reduce-style collectives over a host-spanning axis charge their
    payload once (ring all-reduce moves O(payload) per participant);
    gather-style collectives charge payload x axis size (every
    participant receives every shard — the replication blowup).
    """
    from apnea_uq_tpu.parallel.topology import axis_sizes, axis_spans_hosts

    sizes = axis_sizes(e, d)
    spans = {axis: axis_spans_hosts(spec, e, d, axis) for axis in sizes}
    payloads = dict(getattr(program, "collective_payloads", {}) or {})
    cross: List[str] = []
    cross_bytes = 0
    blowup = 1
    for key in sorted(program.collectives):
        axes = _collective_axes(key)
        if not any(spans.get(a, True) for a in axes):
            continue
        cross.append(key)
        payload = int(payloads.get(key, 0))
        if _prim_of(key) in GATHER_STYLE_PRIMS:
            factor = 1
            for a in axes:
                factor *= sizes.get(a, 1)
            blowup = max(blowup, factor)
            cross_bytes += payload * factor
        else:
            cross_bytes += payload
    memory = program.memory_fields or {}
    peak = memory.get("peak_bytes")
    return TopoProgramFacts(
        label=program.label, topology=spec.name,
        mesh_ensemble=e, mesh_data=d,
        collectives=dict(program.collectives),
        collective_payloads=payloads,
        cross_host=cross, cross_host_bytes=cross_bytes,
        replication_blowup=blowup,
        per_device_bytes=int(peak) if peak is not None else None,
        hbm_budget_bytes=spec.hbm_bytes_per_device,
        cross_host_budget_bytes=spec.cross_host_budget_bytes,
    )


def capture_topology(config, spec: "TopologySpec",
                     ) -> Tuple[Dict[str, TopoProgramFacts],
                                Dict[str, str]]:
    """Lower the mesh program families over ``spec``'s mesh on the
    current backend.  Returns ``(facts_by_label, failures)``."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from apnea_uq_tpu.audit.capture import CaptureStore
    from apnea_uq_tpu.compilecache.store import use_store
    from apnea_uq_tpu.models import AlarconCNN1D, init_variables
    from apnea_uq_tpu.parallel import fit_ensemble
    from apnea_uq_tpu.parallel.mesh import make_mesh
    from apnea_uq_tpu.training import create_train_state, fit
    from apnea_uq_tpu.uq.predict import (
        ensemble_predict,
        mc_dropout_predict,
        stack_member_variables,
    )
    from apnea_uq_tpu.utils import prng

    store = CaptureStore()
    model = AlarconCNN1D(config.model)
    variables = init_variables(model, jax.random.key(0))
    uq = config.uq
    x_aval = jax.ShapeDtypeStruct((TOPO_WINDOWS,) + TOPO_WINDOW_SHAPE,
                                  jnp.float32)
    rng = np.random.default_rng(0)
    x_train = rng.normal(
        size=(TOPO_WINDOWS,) + TOPO_WINDOW_SHAPE).astype(np.float32)
    y_train = (np.arange(TOPO_WINDOWS) % 2).astype(np.int8)

    layouts: Dict[str, Tuple[int, int]] = {}

    def topo_mesh(num_members: int):
        mesh = make_mesh(num_members=num_members, topology=spec)
        return mesh, tuple(mesh.devices.shape)

    with use_store(store):
        store.group = "eval-mcd"
        mesh, (e, d) = topo_mesh(TOPO_PASSES)
        layouts["mcd_predict_fused"] = (e, d)
        mc_dropout_predict(
            model, variables, x_aval, n_passes=TOPO_PASSES,
            mode=uq.mcd_mode, batch_size=TOPO_BATCH,
            key=prng.stochastic_key(config.train.seed), mesh=mesh,
            record_memory_only=True,
            stats=("nats", float(uq.entropy_eps)), engine="xla",
        )

        store.group = "eval-de"
        members = stack_member_variables([variables] * TOPO_MEMBERS)
        mesh, (e, d) = topo_mesh(TOPO_MEMBERS)
        layouts["de_predict_fused"] = (e, d)
        ensemble_predict(
            model, members, x_aval, batch_size=TOPO_BATCH, mesh=mesh,
            record_memory_only=True, stats=("nats", float(uq.entropy_eps)),
        )

        store.group = "train"
        mesh, (e, d) = topo_mesh(1)
        layouts["train_epoch"] = layouts["val_loss"] = (e, d)
        tcfg = dataclasses.replace(config.train,
                                   batch_size=TOPO_TRAIN_BATCH,
                                   streaming=False)
        state = create_train_state(
            model, jax.random.key(tcfg.seed),
            learning_rate=tcfg.learning_rate)
        fit(model, state, x_train, y_train, tcfg, mesh=mesh,
            compile_only=True)

        store.group = "train-ensemble"
        ecfg = dataclasses.replace(
            config.ensemble, num_members=TOPO_MEMBERS,
            batch_size=TOPO_TRAIN_BATCH, streaming=False)
        mesh, (e, d) = topo_mesh(TOPO_MEMBERS)
        layouts["ensemble_epoch"] = (e, d)
        fit_ensemble(model, x_train, y_train, ecfg, mesh=mesh,
                     compile_only=True)

    failures = dict(store.failures)
    facts: Dict[str, TopoProgramFacts] = {}
    for label in MESH_FAMILY_LABELS:
        program = store.captures.get(label)
        if program is None:
            if label not in failures:
                failures[label] = (
                    "entry point never acquired this label through the "
                    "program store — mesh-family/driver drift")
            continue
        layout = layouts.get(label)
        if layout is None:
            # A silent fallback here would attribute the wrong mesh
            # layout to the program and miscount cross-host traffic —
            # surface the wiring gap as a capture failure instead.
            failures[label] = (
                "label captured but no mesh layout recorded — wire a "
                "layouts[...] assignment for it in capture_topology")
            continue
        facts[label] = distill_facts(program, spec, *layout)
    return facts, failures


def sweep_topologies(config, specs: Optional[Tuple["TopologySpec", ...]]
                     = None):
    """Run :func:`capture_topology` per simulated topology of the
    current rig.  Returns ``(facts, skipped, failures)`` with ``facts``
    keyed ``(topology name, label)`` and ``skipped`` a list of
    ``(topology name, reason)`` for specs the rig cannot host."""
    import jax

    from apnea_uq_tpu.parallel.topology import simulated_topologies

    n = len(jax.devices())  # apnea-lint: disable=single-host-device-enumeration -- the sweep is a single-process analysis tool sizing itself from the whole rig on purpose
    if specs is None:
        specs = simulated_topologies(n)
    facts: Dict[Tuple[str, str], TopoProgramFacts] = {}
    skipped: List[Tuple[str, str]] = []
    failures: Dict[str, str] = {}
    for spec in specs:
        if spec.total_devices != n:
            skipped.append(
                (spec.name, f"needs {spec.total_devices} devices, rig "
                            f"has {n}"))
            continue
        per_label, fail = capture_topology(config, spec)
        for label, f in per_label.items():
            facts[(spec.name, label)] = f
        for label, err in fail.items():
            failures[f"{spec.name}/{label}"] = err
    return facts, skipped, failures
