"""Per-channel data fingerprints and input-drift scoring (PSI/KS).

The telemetry layer (PRs 2/3/10) observes only *systems* facts; this
module adds the data half of model-quality observability: a compact
statistical fingerprint of a window set — per channel: mean/std,
min/max, histogram, approximate quantiles, NaN rate, flatline rate
(dead lead) and saturation rate (railed sensor) — computed **streaming**
over any row-indexable source (a plain ndarray or the sharded store's
:class:`~apnea_uq_tpu.data.store.ShardedArray`, O(block) host memory).

The fingerprint of the prepared test set is frozen into the registry at
prepare time as the ``quality_baseline`` artifact; at eval time the
live windows are re-binned against the **baseline's own histogram
edges** and scored per channel with PSI (population stability index)
and the two-sample KS statistic, so a drifted cohort becomes a
gateable ``drift_fingerprint`` telemetry number instead of a silent
miscalibration.

Deliberately jax-free (pure NumPy): the fingerprint must be computable
in ingest/prepare/CLI contexts where no accelerator backend exists.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

FINGERPRINT_VERSION = 1
DEFAULT_NUM_BINS = 32
DEFAULT_BLOCK_ROWS = 16384

#: Percentiles reported per channel (approximate, histogram-derived).
QUANTILES = (1, 5, 25, 50, 75, 95, 99)

# Proportion floor for PSI: empty bins would make the log-ratio
# undefined, and the standard remedy is clipping, not smoothing the
# whole distribution.
_PSI_EPS = 1e-6

# A window's channel counts as *saturated* when more than this fraction
# of its samples sit exactly on the window's own extreme values while
# the window is not flat — the railed-sensor shape (clipped ADC).
_SATURATION_FRACTION = 0.5


def _iter_blocks(x, block_rows: int):
    """(start_row, materialized block) over any row-indexable source —
    the ShardedArray scan primitive when available, plain slicing
    otherwise.  Each block is O(block_rows)."""
    iter_blocks = getattr(x, "iter_blocks", None)
    if iter_blocks is not None:
        yield from iter_blocks(block_rows)
        return
    for lo in range(0, len(x), block_rows):
        yield lo, np.asarray(x[lo:lo + block_rows])


def _derive_edges(x, num_bins: int, block_rows: int) -> List[np.ndarray]:
    """Per-channel histogram edges from one cheap streaming min/max
    pass: the observed range widened by half its span (floor 1e-3) so
    moderate tail growth in a later cohort still lands in interior
    bins; anything outside clamps into the boundary bins (which is
    itself drift signal).  A separate pass — not the first block — so
    the fingerprint is invariant to ``block_rows`` and the in-core and
    out-of-core prepare paths freeze identical baselines."""
    n_channels = int(np.shape(x)[-1])
    lo = np.full(n_channels, np.inf)
    hi = np.full(n_channels, -np.inf)
    for _start, block in _iter_blocks(x, block_rows):
        block = np.asarray(block, np.float64)
        finite = np.isfinite(block)
        lo = np.minimum(lo, np.where(finite, block,
                                     np.inf).min(axis=(0, 1)))
        hi = np.maximum(hi, np.where(finite, block,
                                     -np.inf).max(axis=(0, 1)))
    lo = np.where(np.isfinite(lo), lo, 0.0)
    hi = np.where(np.isfinite(hi), hi, 0.0)
    margin = np.maximum((hi - lo) * 0.5, 1e-3)
    return [
        np.linspace(lo[c] - margin[c], hi[c] + margin[c], num_bins + 1)
        for c in range(n_channels)
    ]


def _hist_quantiles(edges: np.ndarray, counts: np.ndarray) -> Dict[str, Optional[float]]:
    """Approximate percentiles from a histogram: linear interpolation
    inside the bin where the CDF crosses each target.  Resolution is the
    bin width — good enough for drift triage, and it keeps the
    fingerprint one streaming pass."""
    counts = np.asarray(counts, np.float64)
    total = counts.sum()
    out: Dict[str, Optional[float]] = {}
    if total <= 0:
        return {f"p{q:02d}": None for q in QUANTILES}
    cdf = np.cumsum(counts) / total
    for q in QUANTILES:
        target = q / 100.0
        i = min(int(np.searchsorted(cdf, target, side="left")),
                len(counts) - 1)
        prev = cdf[i - 1] if i else 0.0
        width = counts[i] / total
        frac = 0.0 if width <= 0 else min((target - prev) / width, 1.0)
        out[f"p{q:02d}"] = float(edges[i] + frac * (edges[i + 1] - edges[i]))
    return out


def compute_fingerprint(
    x,
    *,
    channel_names: Optional[Sequence[str]] = None,
    num_bins: int = DEFAULT_NUM_BINS,
    block_rows: int = DEFAULT_BLOCK_ROWS,
    edges: Optional[Sequence[np.ndarray]] = None,
) -> Dict[str, Any]:
    """Streaming pass(es) over ``x`` (shape (N, T, C)) -> the JSON-able
    fingerprint document.  ``edges`` pins the per-channel histogram
    edges (pass a baseline's to make two fingerprints bin-comparable;
    one pass total); by default a separate cheap min/max pass derives
    them from the GLOBAL range — never from the first block, which
    would make the fingerprint depend on ``block_rows`` and break the
    pinned in-core/out-of-core baseline byte-parity."""
    shape = tuple(np.shape(x))
    if len(shape) != 3:
        raise ValueError(f"expected (rows, steps, channels) windows, got "
                         f"shape {shape}")
    n, steps, n_channels = shape
    if n == 0 or n_channels == 0:
        raise ValueError(f"cannot fingerprint an empty window set "
                         f"(shape {shape})")
    if num_bins < 2:
        raise ValueError(f"num_bins must be >= 2, got {num_bins}")
    if channel_names is None:
        channel_names = [f"ch{i}" for i in range(n_channels)]
    if len(channel_names) != n_channels:
        raise ValueError(f"{len(channel_names)} channel names for "
                         f"{n_channels} channels")
    if edges is not None:
        edges = [np.asarray(e, np.float64) for e in edges]
        if len(edges) != n_channels:
            raise ValueError(f"{len(edges)} edge arrays for "
                             f"{n_channels} channels")
    else:
        edges = _derive_edges(x, num_bins, block_rows)

    total = np.zeros(n_channels, np.float64)
    total_sq = np.zeros(n_channels, np.float64)
    finite_count = np.zeros(n_channels, np.int64)
    nan_count = np.zeros(n_channels, np.int64)
    run_min = np.full(n_channels, np.inf)
    run_max = np.full(n_channels, -np.inf)
    flat_windows = np.zeros(n_channels, np.int64)
    saturated_windows = np.zeros(n_channels, np.int64)
    counts = np.zeros((n_channels, len(edges[0]) - 1), np.int64)

    for _lo, block in _iter_blocks(x, block_rows):
        block = np.asarray(block, np.float64)
        finite = np.isfinite(block)
        nan_count += (~finite).sum(axis=(0, 1))
        finite_count += finite.sum(axis=(0, 1))
        safe = np.where(finite, block, 0.0)
        total += safe.sum(axis=(0, 1))
        total_sq += (safe * safe).sum(axis=(0, 1))
        # Per-(window, channel) shape facts over the finite samples.
        w_min = np.where(finite, block, np.inf).min(axis=1)
        w_max = np.where(finite, block, -np.inf).max(axis=1)
        has_finite = finite.any(axis=1)
        run_min = np.minimum(run_min,
                             np.where(np.isfinite(w_min), w_min,
                                      np.inf).min(axis=0))
        run_max = np.maximum(run_max,
                             np.where(np.isfinite(w_max), w_max,
                                      -np.inf).max(axis=0))
        flat = has_finite & (w_max == w_min)
        flat_windows += flat.sum(axis=0)
        railed = (np.isclose(block, w_min[:, None, :])
                  | np.isclose(block, w_max[:, None, :])) & finite
        railed_frac = railed.sum(axis=1) / np.maximum(finite.sum(axis=1), 1)
        saturated_windows += (has_finite & ~flat
                              & (railed_frac > _SATURATION_FRACTION)
                              ).sum(axis=0)
        for c in range(n_channels):
            vals = block[:, :, c][finite[:, :, c]]
            if vals.size:
                clipped = np.clip(vals, edges[c][0], edges[c][-1])
                counts[c] += np.histogram(clipped, bins=edges[c])[0]

    samples = n * steps
    channels = []
    for c in range(n_channels):
        nf = int(finite_count[c])
        mean = total[c] / nf if nf else 0.0
        var = max(total_sq[c] / nf - mean * mean, 0.0) if nf else 0.0
        channels.append({
            "name": str(channel_names[c]),
            "mean": round(float(mean), 9),
            "std": round(float(np.sqrt(var)), 9),
            "min": float(run_min[c]) if np.isfinite(run_min[c]) else None,
            "max": float(run_max[c]) if np.isfinite(run_max[c]) else None,
            "nan_rate": round(float(nan_count[c] / samples), 9),
            "flatline_rate": round(float(flat_windows[c] / n), 9),
            "saturation_rate": round(float(saturated_windows[c] / n), 9),
            "quantiles": _hist_quantiles(edges[c], counts[c]),
            "edges": [float(e) for e in edges[c]],
            "counts": [int(v) for v in counts[c]],
        })
    return {
        "version": FINGERPRINT_VERSION,
        "rows": int(n),
        "window_steps": int(steps),
        "num_bins": int(len(channels[0]["counts"])),
        "channels": channels,
    }


def _proportions(counts) -> np.ndarray:
    counts = np.asarray(counts, np.float64)
    total = counts.sum()
    if total <= 0:
        return np.full(counts.shape, 1.0 / len(counts))
    return counts / total


def population_stability_index(baseline_counts, current_counts) -> float:
    """PSI over two histograms sharing one bin axis: proportions clipped
    at 1e-6 (the standard remedy for empty bins), sum of
    ``(p_c - p_b) * ln(p_c / p_b)``.  Rule of thumb: < 0.1 stable,
    0.1-0.2 moderate shift, > 0.2 significant drift."""
    b = np.clip(_proportions(baseline_counts), _PSI_EPS, None)
    c = np.clip(_proportions(current_counts), _PSI_EPS, None)
    return float(np.sum((c - b) * np.log(c / b)))


def ks_statistic(baseline_counts, current_counts) -> float:
    """Two-sample Kolmogorov–Smirnov statistic from binned counts: max
    |CDF difference| over the shared bin axis (bin-resolution exact)."""
    b = np.cumsum(_proportions(baseline_counts))
    c = np.cumsum(_proportions(current_counts))
    return float(np.max(np.abs(b - c)))


def drift_report(baseline: Dict[str, Any],
                 current: Dict[str, Any]) -> Dict[str, Any]:
    """Per-channel PSI/KS/mean-shift of ``current`` against ``baseline``
    (both :func:`compute_fingerprint` documents over the SAME histogram
    edges — compute ``current`` with ``edges`` taken from the
    baseline, or via :func:`score_against_baseline`)."""
    b_channels = baseline.get("channels") or []
    c_channels = current.get("channels") or []
    if len(b_channels) != len(c_channels):
        raise ValueError(
            f"channel count changed: baseline has {len(b_channels)}, "
            f"current has {len(c_channels)} — the fingerprints are not "
            f"comparable"
        )
    channels = []
    for b, c in zip(b_channels, c_channels):
        if not np.allclose(b["edges"], c["edges"]):
            raise ValueError(
                f"histogram edges differ for channel {b['name']!r}; "
                f"recompute the current fingerprint with the baseline's "
                f"edges (score_against_baseline does this)"
            )
        denom = float(b["std"]) + 1e-12
        channels.append({
            "name": b["name"],
            "psi": round(population_stability_index(b["counts"],
                                                    c["counts"]), 6),
            "ks": round(ks_statistic(b["counts"], c["counts"]), 6),
            "mean_shift": round(abs(float(c["mean"]) - float(b["mean"]))
                                / denom, 6),
            "nan_rate_delta": round(float(c["nan_rate"])
                                    - float(b["nan_rate"]), 9),
            "flatline_rate_delta": round(float(c["flatline_rate"])
                                         - float(b["flatline_rate"]), 9),
            "saturation_rate_delta": round(float(c["saturation_rate"])
                                           - float(b["saturation_rate"]),
                                           9),
        })
    worst = max(channels, key=lambda ch: ch["psi"])
    return {
        "rows": int(current["rows"]),
        "baseline_rows": int(baseline["rows"]),
        "max_psi": max(ch["psi"] for ch in channels),
        "max_ks": max(ch["ks"] for ch in channels),
        "max_mean_shift": max(ch["mean_shift"] for ch in channels),
        "worst_channel": worst["name"],
        "channels": channels,
    }


def baseline_edges(baseline: Dict[str, Any]) -> List[np.ndarray]:
    """The per-channel histogram edges frozen in a fingerprint document."""
    return [np.asarray(ch["edges"], np.float64)
            for ch in baseline["channels"]]


ROLLING_STATE_VERSION = 1


class RollingFingerprint:
    """Online fingerprint accumulator on a baseline's **frozen** edges.

    The batch fingerprint above is one pass over a materialized window
    set; the serving tier needs the same statistics *online*, one scored
    window at a time, with bounded memory and a recency bias.  This
    variant keeps O(channels x bins) state — decayed histogram counts
    plus decayed moment/shape sums — binned on the baseline's own
    histogram edges, so :func:`drift_report` can score it against the
    frozen ``quality_baseline`` directly (PSI/KS numbers comparable to
    the eval-time ``drift_fingerprint`` events).

    ``half_life`` (in windows) sets the exponential decay: after that
    many further windows an observation's weight has halved, so the
    fingerprint tracks *recent* traffic and a resolved upstream incident
    ages out instead of polluting the score forever.  ``None`` disables
    decay (cumulative counts — the all-traffic view).

    The full state round-trips through :meth:`to_json` /
    :meth:`from_json` (plain JSON scalars/lists), which is how it rides
    the stream scorer's atomic ``stream_state.json`` snapshot: a kill -9
    resume restores the rolling window instead of resetting the verdict.
    Jax-free like the rest of the module — update cost is a handful of
    numpy reductions per window batch, never a compile.
    """

    def __init__(self, baseline: Dict[str, Any], *,
                 half_life: Optional[float] = None):
        names = [ch["name"] for ch in baseline.get("channels") or []]
        if not names:
            raise ValueError("baseline fingerprint has no channels")
        self.channel_names = names
        self.edges = baseline_edges(baseline)
        if half_life is not None and half_life <= 0:
            raise ValueError(f"half_life must be positive, got {half_life}")
        self.half_life = None if half_life is None else float(half_life)
        self._decay = (1.0 if half_life is None
                       else float(0.5 ** (1.0 / half_life)))
        c, b = len(names), len(self.edges[0]) - 1
        self.counts = np.zeros((c, b), np.float64)
        self.sum = np.zeros(c, np.float64)
        self.sumsq = np.zeros(c, np.float64)
        self.finite_w = np.zeros(c, np.float64)
        self.nan_w = np.zeros(c, np.float64)
        self.flat_w = np.zeros(c, np.float64)
        self.sat_w = np.zeros(c, np.float64)
        self.run_min = np.full(c, np.inf)
        self.run_max = np.full(c, -np.inf)
        self.window_w = 0.0   # decayed effective window count
        self.seen = 0         # total windows ever ingested (no decay)
        self.steps: Optional[int] = None

    def update(self, windows) -> None:
        """Fold a window — shape (T, C) — or a batch (N, T, C) into the
        rolling state.  An n-window batch fades the prior state by
        ``decay**n`` and enters at full weight: relative recency INSIDE
        one fold is not modeled (folds are a handful of windows against
        a half-life of thousands), but n windows always advance the
        clock by n regardless of how they were batched."""
        block = np.asarray(windows, np.float64)
        if block.ndim == 2:
            block = block[None]
        if block.ndim != 3 or block.shape[-1] != len(self.channel_names):
            raise ValueError(
                f"expected (T, {len(self.channel_names)}) or "
                f"(N, T, {len(self.channel_names)}) windows, got shape "
                f"{block.shape}")
        n, steps, _c = block.shape
        if n == 0:
            return
        if self.steps is None:
            self.steps = int(steps)
        if self._decay != 1.0:
            fade = self._decay ** n
            self.counts *= fade
            self.sum *= fade
            self.sumsq *= fade
            self.finite_w *= fade
            self.nan_w *= fade
            self.flat_w *= fade
            self.sat_w *= fade
            self.window_w *= fade
        finite = np.isfinite(block)
        self.nan_w += (~finite).sum(axis=(0, 1))
        self.finite_w += finite.sum(axis=(0, 1))
        safe = np.where(finite, block, 0.0)
        self.sum += safe.sum(axis=(0, 1))
        self.sumsq += (safe * safe).sum(axis=(0, 1))
        w_min = np.where(finite, block, np.inf).min(axis=1)
        w_max = np.where(finite, block, -np.inf).max(axis=1)
        has_finite = finite.any(axis=1)
        self.run_min = np.minimum(
            self.run_min,
            np.where(np.isfinite(w_min), w_min, np.inf).min(axis=0))
        self.run_max = np.maximum(
            self.run_max,
            np.where(np.isfinite(w_max), w_max, -np.inf).max(axis=0))
        flat = has_finite & (w_max == w_min)
        self.flat_w += flat.sum(axis=0)
        railed = (np.isclose(block, w_min[:, None, :])
                  | np.isclose(block, w_max[:, None, :])) & finite
        railed_frac = railed.sum(axis=1) / np.maximum(
            finite.sum(axis=1), 1)
        self.sat_w += (has_finite & ~flat
                       & (railed_frac > _SATURATION_FRACTION)).sum(axis=0)
        for c in range(len(self.channel_names)):
            vals = block[:, :, c][finite[:, :, c]]
            if vals.size:
                clipped = np.clip(vals, self.edges[c][0],
                                  self.edges[c][-1])
                self.counts[c] += np.histogram(clipped,
                                               bins=self.edges[c])[0]
        self.window_w += float(n)
        self.seen += int(n)

    def fingerprint(self) -> Dict[str, Any]:
        """The rolling state as a fingerprint document — same shape as
        :func:`compute_fingerprint`'s, so :func:`drift_report` accepts
        it as the ``current`` side against the frozen baseline."""
        if self.seen == 0:
            raise ValueError("rolling fingerprint has seen no windows")
        channels = []
        for c, name in enumerate(self.channel_names):
            wf = self.finite_w[c]
            mean = self.sum[c] / wf if wf > 0 else 0.0
            var = (max(self.sumsq[c] / wf - mean * mean, 0.0)
                   if wf > 0 else 0.0)
            samples_w = wf + self.nan_w[c]
            channels.append({
                "name": name,
                "mean": round(float(mean), 9),
                "std": round(float(np.sqrt(var)), 9),
                "min": (float(self.run_min[c])
                        if np.isfinite(self.run_min[c]) else None),
                "max": (float(self.run_max[c])
                        if np.isfinite(self.run_max[c]) else None),
                "nan_rate": round(float(self.nan_w[c] / samples_w), 9)
                if samples_w > 0 else 0.0,
                "flatline_rate": round(
                    float(self.flat_w[c] / self.window_w), 9)
                if self.window_w > 0 else 0.0,
                "saturation_rate": round(
                    float(self.sat_w[c] / self.window_w), 9)
                if self.window_w > 0 else 0.0,
                "quantiles": _hist_quantiles(self.edges[c],
                                             self.counts[c]),
                "edges": [float(e) for e in self.edges[c]],
                "counts": [float(v) for v in self.counts[c]],
            })
        return {
            "version": FINGERPRINT_VERSION,
            "rows": max(int(round(self.window_w)), 1),
            "window_steps": int(self.steps or 0),
            "num_bins": int(self.counts.shape[1]),
            "channels": channels,
        }

    def score(self, baseline: Dict[str, Any]) -> Dict[str, Any]:
        """:func:`drift_report` of the rolling state vs ``baseline`` —
        valid because the state accumulated on the baseline's edges."""
        return drift_report(baseline, self.fingerprint())

    def to_json(self) -> Dict[str, Any]:
        """The complete rolling state as plain JSON scalars/lists."""
        return {
            "version": ROLLING_STATE_VERSION,
            "half_life": self.half_life,
            "channel_names": list(self.channel_names),
            "edges": [[float(e) for e in ed] for ed in self.edges],
            "counts": [[float(v) for v in row] for row in self.counts],
            "sum": [float(v) for v in self.sum],
            "sumsq": [float(v) for v in self.sumsq],
            "finite_w": [float(v) for v in self.finite_w],
            "nan_w": [float(v) for v in self.nan_w],
            "flat_w": [float(v) for v in self.flat_w],
            "sat_w": [float(v) for v in self.sat_w],
            "min": [float(v) if np.isfinite(v) else None
                    for v in self.run_min],
            "max": [float(v) if np.isfinite(v) else None
                    for v in self.run_max],
            "window_w": float(self.window_w),
            "seen": int(self.seen),
            "steps": self.steps,
        }

    @classmethod
    def from_json(cls, doc: Dict[str, Any]) -> "RollingFingerprint":
        version = doc.get("version")
        if version != ROLLING_STATE_VERSION:
            raise ValueError(
                f"rolling fingerprint state version {version!r} != "
                f"{ROLLING_STATE_VERSION}")
        self = cls.__new__(cls)
        self.channel_names = list(doc["channel_names"])
        self.edges = [np.asarray(e, np.float64) for e in doc["edges"]]
        self.half_life = (None if doc.get("half_life") is None
                          else float(doc["half_life"]))
        self._decay = (1.0 if self.half_life is None
                       else float(0.5 ** (1.0 / self.half_life)))
        self.counts = np.asarray(doc["counts"], np.float64)
        self.sum = np.asarray(doc["sum"], np.float64)
        self.sumsq = np.asarray(doc["sumsq"], np.float64)
        self.finite_w = np.asarray(doc["finite_w"], np.float64)
        self.nan_w = np.asarray(doc["nan_w"], np.float64)
        self.flat_w = np.asarray(doc["flat_w"], np.float64)
        self.sat_w = np.asarray(doc["sat_w"], np.float64)
        self.run_min = np.asarray(
            [np.inf if v is None else v for v in doc["min"]], np.float64)
        self.run_max = np.asarray(
            [-np.inf if v is None else v for v in doc["max"]], np.float64)
        self.window_w = float(doc["window_w"])
        self.seen = int(doc["seen"])
        self.steps = None if doc.get("steps") is None else int(doc["steps"])
        return self


def score_against_baseline(
    x,
    baseline: Dict[str, Any],
    *,
    block_rows: int = DEFAULT_BLOCK_ROWS,
) -> Dict[str, Any]:
    """Fingerprint ``x`` on the baseline's own bin axis and score the
    drift — the one call the eval/feed path makes per test set."""
    current = compute_fingerprint(
        x,
        channel_names=[ch["name"] for ch in baseline["channels"]],
        block_rows=block_rows,
        edges=baseline_edges(baseline),
    )
    return drift_report(baseline, current)
