"""Canonical column names of the detailed per-window results table.

These match the reference's L5->L6 CSV schema byte-for-byte
(analyze_mcd_patient_level.py:134-152, analyze_de_patient_level.py:146-164)
so a user migrating from the reference finds identical artifacts; every
in-tree producer and consumer imports them from here instead of
re-spelling strings (the reference re-spells them in five scripts).
"""

COL_PATIENT = "Patient_ID"
COL_WINDOW = "Window_Index"
COL_TRUE_LABEL = "True_Label"
COL_PRED_LABEL = "Predicted_Label"
COL_PROB = "Predicted_Probability"
COL_VARIANCE = "Predictive_Variance"
COL_ENTROPY = "Predictive_Entropy"
# Derived, added by analysis stages (aggregate_patient_uq_metrics.py:34).
COL_CORRECT = "Correct"

DETAILED_COLUMNS = (
    COL_PATIENT,
    COL_WINDOW,
    COL_TRUE_LABEL,
    COL_PRED_LABEL,
    COL_PROB,
    COL_VARIANCE,
    COL_ENTROPY,
)
