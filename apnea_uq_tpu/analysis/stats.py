"""Statistical tests for the UQ analyses (reference C21/C22).

In-tree implementations of the two tests the reference takes from
``scipy.stats`` (patient_accuracy_entropy_correlation.py:36-41,
window_uncertainty_vs_correctness_mannwhitney.py:18) — the core math is
NumPy here (rank transform, tie correction, t / normal conversion), and
the CDF special functions are in-tree scalar float64 implementations
(utils/special.py).  Both tests are verified against scipy.stats in the
test suite.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from apnea_uq_tpu.utils.special import ndtr, stdtr

from apnea_uq_tpu.analysis.columns import (
    COL_CORRECT,
    COL_ENTROPY,
    COL_PRED_LABEL,
    COL_TRUE_LABEL,
)

_ALTERNATIVES = ("two-sided", "greater", "less")


def pearson_corr(x, y) -> Tuple[float, float]:
    """Pearson correlation coefficient with two-sided p-value.

    p comes from t = r * sqrt((n-2) / (1-r^2)) under the t(n-2) null,
    matching ``scipy.stats.pearsonr`` for n > 3.
    """
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    if x.shape != y.shape or x.ndim != 1:
        raise ValueError(f"expected equal-length 1-D inputs, got {x.shape}, {y.shape}")
    n = x.size
    if n < 2:
        raise ValueError("pearson_corr requires at least 2 observations")
    xd = x - x.mean()
    yd = y - y.mean()
    denom = np.sqrt((xd * xd).sum() * (yd * yd).sum())
    if denom == 0.0:
        # A constant input has undefined correlation.
        return float("nan"), float("nan")
    r = float(np.clip((xd * yd).sum() / denom, -1.0, 1.0))
    if n == 2:
        return r, 1.0
    if abs(r) == 1.0:
        return r, 0.0
    df = n - 2
    t = r * np.sqrt(df / (1.0 - r * r))
    p = 2.0 * stdtr(df, -abs(t))
    return r, float(p)


from apnea_uq_tpu.utils.ranking import rank_with_ties as _rank_with_ties


def mann_whitney_u(
    x, y, *, alternative: str = "two-sided", use_continuity: bool = True
) -> Tuple[float, float]:
    """Mann-Whitney U rank-sum test, asymptotic normal p with tie correction.

    ``alternative='greater'`` tests that ``x`` is stochastically greater
    than ``y`` — the direction the reference uses for
    entropy(incorrect) > entropy(correct)
    (window_uncertainty_vs_correctness_mannwhitney.py:18).  Matches
    ``scipy.stats.mannwhitneyu(method='asymptotic')``.
    """
    if alternative not in _ALTERNATIVES:
        raise ValueError(f"alternative must be one of {_ALTERNATIVES}")
    x = np.asarray(x, np.float64)
    y = np.asarray(y, np.float64)
    n1, n2 = x.size, y.size
    if n1 == 0 or n2 == 0:
        raise ValueError("both samples must be non-empty")

    ranks, tie_counts = _rank_with_ties(np.concatenate([x, y]))
    r1 = ranks[:n1].sum()
    u1 = r1 - n1 * (n1 + 1) / 2.0  # U statistic of x

    n = n1 + n2
    mean_u = n1 * n2 / 2.0
    tie_term = ((tie_counts**3 - tie_counts).sum()) / (n * (n - 1.0)) if n > 1 else 0.0
    var_u = n1 * n2 / 12.0 * ((n + 1.0) - tie_term)
    if var_u == 0.0:
        # All observations identical: no evidence either way.
        return float(u1), 1.0

    cc = 0.5 if use_continuity else 0.0
    if alternative == "greater":
        z = (u1 - mean_u - cc) / np.sqrt(var_u)
        p = float(ndtr(-z))
    elif alternative == "less":
        z = (u1 - mean_u + cc) / np.sqrt(var_u)
        p = float(ndtr(z))
    else:
        z = (u1 - mean_u - np.sign(u1 - mean_u) * cc) / np.sqrt(var_u)
        p = float(min(2.0 * ndtr(-abs(z)), 1.0))
    return float(u1), p


def patient_accuracy_entropy_correlation(summary) -> Dict[str, float]:
    """Pearson r between per-patient mean entropy and accuracy (C21).

    ``summary`` is the frame from :func:`~apnea_uq_tpu.analysis.patient.
    aggregate_patients`; mirrors patient_accuracy_entropy_correlation.py:36-41.
    """
    for col in ("mean_entropy", "patient_accuracy"):
        if col not in summary.columns:
            raise ValueError(f"patient summary frame is missing column {col!r}")
    r, p = pearson_corr(
        summary["mean_entropy"].to_numpy(), summary["patient_accuracy"].to_numpy()
    )
    return {"pearson_r": r, "p_value": p, "n_patients": int(len(summary))}


def uncertainty_correctness_test(
    detailed, *, metric: str = COL_ENTROPY, alpha: float = 0.05
) -> Dict[str, float]:
    """One-sided Mann-Whitney U: uncertainty(incorrect) > uncertainty(correct).

    Mirrors window_uncertainty_vs_correctness_mannwhitney.py:10-28 including
    its p < alpha significance verdict.
    """
    frame = detailed
    if COL_CORRECT in frame.columns:
        correct_mask = frame[COL_CORRECT].to_numpy(dtype=bool)
    else:
        correct_mask = (
            frame[COL_TRUE_LABEL].to_numpy() == frame[COL_PRED_LABEL].to_numpy()
        )
    values = frame[metric].to_numpy(dtype=np.float64)
    incorrect = values[~correct_mask]
    correct = values[correct_mask]
    if incorrect.size == 0 or correct.size == 0:
        # All-correct (or all-wrong) predictions: the test is undefined.
        # The reference would crash here (scipy raises on empty samples);
        # report "no evidence" instead so pipelines keep running.
        u, p = float("nan"), float("nan")
    else:
        u, p = mann_whitney_u(incorrect, correct, alternative="greater")
    return {
        "u_statistic": u,
        "p_value": p,
        "significant": bool(p < alpha),
        "n_incorrect": int(incorrect.size),
        "n_correct": int(correct.size),
        "median_incorrect": float(np.median(incorrect)) if incorrect.size else float("nan"),
        "median_correct": float(np.median(correct)) if correct.size else float("nan"),
    }
