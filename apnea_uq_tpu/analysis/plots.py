"""Plotting suite for UQ results (reference C11, C19, C20).

Covers the reference's three plotting surfaces with one module:

- per-metric window plots, class-mean bar chart, per-class histograms
  (uq_techniques.py:210-275);
- the thesis overview figures — patient-entropy histograms, patient
  accuracy-vs-entropy scatter with Pearson r, correct-vs-incorrect
  entropy boxplots, binned-accuracy lines
  (uq_analysis/final_plot_uq_overview_figures.py:57-206);
- the T/N convergence plot
  (uq_analysis/hyperparameter_plot_mcd_or_de_pass_convergence.py:30-141),
  fed by the in-tree sweep runner the reference lacks (SURVEY §5.6).

All functions draw on a non-interactive Agg backend, write a PNG, and
return the path.  Where the reference hard-codes its MCD-vs-DE method
pair, these take any {label: frame} mapping.
"""

from __future__ import annotations

import os
from typing import Dict, Mapping, Optional, Sequence

import matplotlib
matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402

from apnea_uq_tpu.analysis.columns import (  # noqa: E402
    COL_CORRECT,
    COL_ENTROPY,
    COL_PRED_LABEL,
    COL_TRUE_LABEL,
)
from apnea_uq_tpu.analysis.stats import pearson_corr  # noqa: E402


def _save(fig, out_path: str) -> str:
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    fig.savefig(out_path, dpi=150, bbox_inches="tight")
    plt.close(fig)
    return out_path


def _with_correct(frame):
    if COL_CORRECT not in frame.columns:
        frame = frame.copy()
        frame[COL_CORRECT] = frame[COL_TRUE_LABEL] == frame[COL_PRED_LABEL]
    return frame


# ---------------------------------------------------------------- C11 ----

def plot_uncertainty_metric(
    values,
    metric_name: str,
    out_path: str,
    *,
    max_windows: int = 5000,
    seed: int = 0,
) -> str:
    """Per-window metric line plot, subsampled beyond ``max_windows``
    (uq_techniques.py:210-239)."""
    values = np.asarray(values)
    if values.shape[0] > max_windows:
        idx = np.sort(
            np.random.default_rng(seed).choice(
                values.shape[0], max_windows, replace=False
            )
        )
        values = values[idx]
    fig, ax = plt.subplots(figsize=(10, 4))
    ax.plot(values, lw=0.5)
    ax.set_xlabel("window")
    ax.set_ylabel(metric_name)
    ax.set_title(f"{metric_name} across windows")
    return _save(fig, out_path)


def plot_class_uncertainties(
    class_mean_variances: Mapping[str, float], out_path: str
) -> str:
    """Bar chart of per-class mean predictive variance
    (uq_techniques.py:242-255)."""
    fig, ax = plt.subplots(figsize=(5, 4))
    names = list(class_mean_variances)
    ax.bar(names, [class_mean_variances[n] for n in names])
    ax.set_ylabel("mean predictive variance")
    ax.set_title("Mean predictive variance by true class")
    return _save(fig, out_path)


def plot_metric_distribution(
    values,
    y_true,
    metric_name: str,
    out_path: str,
    *,
    bins: int = 50,
) -> str:
    """Overlaid per-true-class histograms of one uncertainty metric
    (uq_techniques.py:258-275)."""
    values = np.asarray(values)
    y = np.asarray(y_true).astype(int).reshape(-1)
    fig, ax = plt.subplots(figsize=(7, 4))
    for cls in (0, 1):
        sel = values[y == cls]
        if sel.size:
            ax.hist(sel, bins=bins, alpha=0.6, label=f"class {cls}", density=True)
    ax.set_xlabel(metric_name)
    ax.set_ylabel("density")
    ax.set_title(f"{metric_name} distribution by true class")
    ax.legend()
    return _save(fig, out_path)


# ---------------------------------------------------------------- C19 ----

def plot_patient_entropy_histograms(
    summaries: Mapping[str, "object"], out_path: str, *, bins: int = 30
) -> str:
    """Side-by-side histograms of per-patient mean entropy per method
    (final_plot_uq_overview_figures.py:58-76)."""
    n = len(summaries)
    fig, axes = plt.subplots(1, n, figsize=(5 * n, 4), squeeze=False)
    for ax, (label, summary) in zip(axes[0], summaries.items()):
        ax.hist(summary["mean_entropy"].dropna(), bins=bins)
        ax.set_title(label)
        ax.set_xlabel("mean predictive entropy")
        ax.set_ylabel("patients")
    fig.suptitle("Distribution of mean predictive entropy across patients")
    return _save(fig, out_path)


def plot_accuracy_vs_entropy(
    summaries: Mapping[str, "object"], out_path: str
) -> str:
    """Per-method scatter of patient accuracy vs mean entropy, annotated
    with Pearson r (final_plot_uq_overview_figures.py:79-109)."""
    n = len(summaries)
    fig, axes = plt.subplots(1, n, figsize=(5 * n, 4), squeeze=False)
    for ax, (label, summary) in zip(axes[0], summaries.items()):
        sub = summary[["mean_entropy", "patient_accuracy"]].dropna()
        r, _ = pearson_corr(
            sub["mean_entropy"].to_numpy(), sub["patient_accuracy"].to_numpy()
        )
        ax.scatter(sub["mean_entropy"], sub["patient_accuracy"], s=12, alpha=0.7)
        ax.set_title(f"{label} (r = {r:.2f})")
        ax.set_xlabel("mean predictive entropy")
        ax.set_ylabel("patient accuracy")
    fig.suptitle("Patient accuracy vs mean predictive entropy")
    return _save(fig, out_path)


def plot_correct_incorrect_box(
    detailed_frames: Mapping[str, "object"],
    out_path: str,
    *,
    metric: str = COL_ENTROPY,
) -> str:
    """Boxplots of window uncertainty for correct vs incorrect predictions
    per method (final_plot_uq_overview_figures.py:113-140)."""
    n = len(detailed_frames)
    fig, axes = plt.subplots(1, n, figsize=(5 * n, 4), squeeze=False)
    for ax, (label, frame) in zip(axes[0], detailed_frames.items()):
        frame = _with_correct(frame)
        groups = [
            frame.loc[frame[COL_CORRECT], metric].to_numpy(),
            frame.loc[~frame[COL_CORRECT], metric].to_numpy(),
        ]
        ax.boxplot(groups, tick_labels=["correct", "incorrect"], showfliers=False)
        ax.set_title(label)
        ax.set_ylabel(metric)
    fig.suptitle(f"{metric} for correct vs incorrect windows")
    return _save(fig, out_path)


def plot_binned_accuracy(
    binned_frames: Mapping[str, "object"], out_path: str
) -> str:
    """Accuracy across uncertainty bins per method, annotated with the
    first (most-confident) bin's accuracy
    (final_plot_uq_overview_figures.py:144-206)."""
    n = len(binned_frames)
    fig, axes = plt.subplots(1, n, figsize=(6 * n, 4), squeeze=False)
    for ax, (label, binned) in zip(axes[0], binned_frames.items()):
        acc = binned["accuracy"].to_numpy()
        ax.plot(range(len(acc)), acc, marker="o")
        ax.set_xticks(range(len(acc)))
        ax.set_xticklabels(binned.iloc[:, 0].astype(str), rotation=45, ha="right",
                           fontsize=7)
        finite = np.isfinite(acc)
        if finite.any():
            first = int(np.flatnonzero(finite)[0])
            ax.annotate(f"{acc[first]:.3f}", (first, acc[first]),
                        textcoords="offset points", xytext=(6, 6))
        ax.set_title(label)
        ax.set_xlabel("uncertainty bin")
        ax.set_ylabel("accuracy")
        ax.set_ylim(0.0, 1.05)
    fig.suptitle("Accuracy across predictive-entropy bins")
    return _save(fig, out_path)


# ---------------------------------------------------------------- C20 ----

def plot_convergence(
    sweep_frame,
    out_path: str,
    *,
    x_label: str = "K (MC passes / ensemble members)",
) -> str:
    """Overall mean variance vs K for balanced/unbalanced sets
    (hyperparameter_plot_mcd_or_de_pass_convergence.py:30-141).

    Expects the sweep-runner schema: column ``N`` plus one
    ``Variance_<set>`` column per test set.
    """
    fig, ax = plt.subplots(figsize=(7, 4))
    var_cols = [c for c in sweep_frame.columns if c.startswith("Variance_")]
    if "N" not in sweep_frame.columns or not var_cols:
        raise ValueError(
            "sweep frame must have column 'N' and >=1 'Variance_*' column; "
            f"got {list(sweep_frame.columns)}"
        )
    for col in var_cols:
        ax.plot(sweep_frame["N"], sweep_frame[col], marker="o",
                label=col.removeprefix("Variance_"))
    ax.set_xlabel(x_label)
    ax.set_ylabel("overall mean predictive variance")
    ax.set_title("Uncertainty convergence")
    ax.legend()
    return _save(fig, out_path)


# ---------------------------------------------------- retention curve ----

def plot_retention_curve(curves: Mapping[str, "pd.DataFrame"], out_path: str) -> str:
    """Accuracy vs retained fraction, one line per label.

    ``curves`` maps a run label to a retention frame
    (analysis/windows.retention_curve schema: fraction/accuracy columns).
    Visualizes the reference's headline ">99% on the most-confident
    subset" claim (reference README.md:14) as a curve instead of a single
    annotated bin.
    """
    fig, ax = plt.subplots(figsize=(7, 4))
    for label, frame in curves.items():
        if not {"fraction", "accuracy"}.issubset(frame.columns):
            raise ValueError(
                f"retention frame for {label!r} needs fraction/accuracy "
                f"columns; got {list(frame.columns)}"
            )
        ax.plot(frame["fraction"], frame["accuracy"], marker="o", label=label)
    ax.set_xlabel("fraction of windows retained (lowest uncertainty first)")
    ax.set_ylabel("accuracy on retained windows")
    ax.set_title("Selective prediction: accuracy vs retention")
    ax.set_ylim(None, 1.005)
    ax.legend()
    return _save(fig, out_path)


# ------------------------------------------------ reliability diagram ----

def plot_reliability_diagram(
    summaries: Mapping[str, "pd.DataFrame"], out_path: str
) -> str:
    """Reliability diagram: empirical positive rate vs mean predicted
    probability per confidence bin, one line per label, with the y = x
    perfect-calibration diagonal.

    ``summaries`` maps a run label to a reliability table
    (analysis/calibration.reliability_bins schema).
    """
    fig, ax = plt.subplots(figsize=(5.5, 5))
    ax.plot([0, 1], [0, 1], linestyle="--", color="grey",
            label="perfect calibration")
    for label, frame in summaries.items():
        if not {"mean_confidence", "positive_rate", "count"}.issubset(
            frame.columns
        ):
            raise ValueError(
                f"reliability frame for {label!r} needs mean_confidence/"
                f"positive_rate/count columns; got {list(frame.columns)}"
            )
        occupied = frame["count"] > 0
        ax.plot(frame.loc[occupied, "mean_confidence"],
                frame.loc[occupied, "positive_rate"],
                marker="o", label=label)
    ax.set_xlabel("mean predicted probability (confidence)")
    ax.set_ylabel("empirical positive rate")
    ax.set_title("Reliability diagram")
    ax.set_xlim(0, 1)
    ax.set_ylim(0, 1)
    ax.legend()
    return _save(fig, out_path)
