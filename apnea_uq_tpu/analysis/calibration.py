"""Probability-calibration analysis of the per-window predictions.

The reference quantifies *uncertainty* (variance/entropy/MI) but never
asks whether the predicted probabilities are *calibrated* — whether
windows predicted at p ≈ 0.8 are in fact apnea 80 % of the time.  For a
UQ framework that question is table stakes, so this module adds it on
the same detailed-frame contract the other analyses consume
(``Predicted_Probability`` + ``True_Label``, uq/drivers.detailed_frame):

- ``reliability_bins``: confidence-binned mean predicted probability vs
  empirical positive rate (the reliability-diagram table),
- ``expected_calibration_error`` / ``max_calibration_error``: the usual
  count-weighted / worst-bin |confidence − accuracy| summaries,
- ``brier_score``: mean squared error of the probabilities.

Everything is host-side NumPy/pandas like the rest of the analysis layer
— at SHHS2 scale (~293K windows) these are sub-millisecond reductions.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pandas as pd

from apnea_uq_tpu.analysis.columns import COL_PROB, COL_TRUE_LABEL


def _validated(detailed: pd.DataFrame):
    for col in (COL_PROB, COL_TRUE_LABEL):
        if col not in detailed.columns:
            raise ValueError(f"detailed results frame is missing column {col!r}")
    if len(detailed) == 0:
        raise ValueError("detailed results frame has no windows")
    probs = detailed[COL_PROB].to_numpy(dtype=np.float64)
    y = detailed[COL_TRUE_LABEL].to_numpy(dtype=np.float64)
    if ((probs < 0) | (probs > 1)).any():
        raise ValueError("probabilities must lie in [0, 1]")
    return probs, y


def reliability_bins(
    detailed: pd.DataFrame, *, num_bins: int = 15
) -> pd.DataFrame:
    """Confidence-binned reliability table.

    Equal-width probability bins over [0, 1] (left-closed; p = 1.0 joins
    the last bin).  Columns: ``bin`` ("lo-hi"), ``count``,
    ``mean_confidence`` (mean predicted probability), ``positive_rate``
    (empirical P(y=1)), ``gap`` (positive_rate − mean_confidence).
    Empty bins are kept with count 0 so the bin axis is always complete.
    """
    probs, y = _validated(detailed)
    return _bins_from_arrays(probs, y, num_bins)


def _bins_from_arrays(probs, y, num_bins: int) -> pd.DataFrame:
    if num_bins < 1:
        raise ValueError(f"num_bins must be >= 1, got {num_bins}")
    idx = np.minimum((probs * num_bins).astype(np.int64), num_bins - 1)
    count = np.bincount(idx, minlength=num_bins).astype(np.int64)
    sum_p = np.bincount(idx, weights=probs, minlength=num_bins)
    sum_y = np.bincount(idx, weights=y, minlength=num_bins)
    safe = np.maximum(count, 1)
    edges = np.linspace(0.0, 1.0, num_bins + 1)
    return pd.DataFrame({
        "bin": [f"{edges[i]:.3f}-{edges[i + 1]:.3f}" for i in range(num_bins)],
        "count": count,
        "mean_confidence": np.where(count > 0, sum_p / safe, np.nan),
        "positive_rate": np.where(count > 0, sum_y / safe, np.nan),
        "gap": np.where(count > 0, (sum_y - sum_p) / safe, np.nan),
    })


@dataclasses.dataclass
class CalibrationSummary:
    ece: float                 # count-weighted mean |gap|
    mce: float                 # worst-bin |gap|
    brier: float               # mean (p - y)^2
    num_bins: int
    num_windows: int
    bins: pd.DataFrame         # the reliability_bins table

    def report(self) -> str:
        return "\n".join([
            f"Windows: {self.num_windows}  (bins: {self.num_bins})",
            f"Expected calibration error (ECE): {self.ece:.4f}",
            f"Maximum calibration error (MCE):  {self.mce:.4f}",
            f"Brier score:                      {self.brier:.4f}",
            "",
            self.bins.to_string(index=False, float_format="%.4f"),
        ])


def calibration_summary(
    detailed: pd.DataFrame, *, num_bins: int = 15
) -> CalibrationSummary:
    """ECE/MCE/Brier plus the reliability table, in one pass."""
    probs, y = _validated(detailed)
    return calibration_summary_from_arrays(probs, y, num_bins=num_bins)


def calibration_summary_from_arrays(
    probs, y, *, num_bins: int = 15
) -> CalibrationSummary:
    """The same summary straight from probability/label vectors — the
    frame-free entry point the quality-telemetry layer uses (the eval
    drivers already hold the per-window mean probabilities as arrays;
    round-tripping them through a DataFrame would buy nothing)."""
    probs = np.asarray(probs, np.float64).reshape(-1)
    y = np.asarray(y, np.float64).reshape(-1)
    if probs.size == 0:
        raise ValueError("no probabilities to calibrate")
    if probs.shape != y.shape:
        raise ValueError(f"probs ({probs.shape[0]}) != labels ({y.shape[0]})")
    if ((probs < 0) | (probs > 1)).any():
        raise ValueError("probabilities must lie in [0, 1]")
    bins = _bins_from_arrays(probs, y, num_bins)
    occupied = bins["count"] > 0
    gaps = np.abs(bins.loc[occupied, "gap"].to_numpy())
    weights = bins.loc[occupied, "count"].to_numpy() / len(probs)
    return CalibrationSummary(
        ece=float(np.sum(weights * gaps)),
        mce=float(np.max(gaps)) if occupied.any() else float("nan"),
        brier=float(np.mean((probs - y) ** 2)),
        num_bins=num_bins,
        num_windows=len(probs),
        bins=bins,
    )
