"""SHHS2 cohort demographics and signal-quality statistics (C23/C24).

Structured replacements for the two print-only side scripts
``datasets/SHHS_cohort_analysis.py`` and ``datasets/SHHS_signal_quality.py``:
the same NSRR metadata CSV goes in, but the results come back as dicts /
frames (reported via ``format_*``) instead of interleaved prints, so the
CLI stage, tests, and downstream notebooks all consume one structure.

Cohort definition matches the reference: rows with a non-missing, numeric
apnea-hypopnea index ``ahi_a0h3a`` (SHHS_cohort_analysis.py:38-51,
SHHS_signal_quality.py:60-74).
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np
import pandas as pd

AHI_COL = "ahi_a0h3a"
AGE_COL = "age_s2"
GENDER_COL = "gender"
RACE_COL = "race"

GENDER_LABELS = {1: "Male", 2: "Female"}
RACE_LABELS = {1: "White", 2: "Black or African American", 3: "Other"}

# Clinical AHI severity thresholds (Berry et al. 2012;
# SHHS_cohort_analysis.py:139-152).
AHI_SEVERITY_BINS = (
    ("Normal (AHI < 5.0)", -np.inf, 5.0),
    ("Mild OSA (AHI 5.0-14.9)", 5.0, 15.0),
    ("Moderate OSA (AHI 15.0-29.9)", 15.0, 30.0),
    ("Severe OSA (AHI >= 30.0)", 30.0, np.inf),
)

# NSRR 1-5 artifact-free-percentage codes (SHHS_signal_quality.py:29-51).
QUALITY_CODE_LABELS = {
    1: "<25% artifact-free",
    2: "25-49% artifact-free",
    3: "50-74% artifact-free",
    4: "75-94% artifact-free",
    5: ">=95% artifact-free",
}
QUALITY_VARS = {
    "quoxim": "SaO2 Signal Quality (Oximeter)",
    "quhr": "Heart Rate Signal Quality (Pulse)",
    "quchest": "Thoracic Effort Signal Quality (Chest Inductance)",
    "quabdo": "Abdominal Effort Signal Quality (Abdominal Inductance)",
}


def define_cohort(metadata: pd.DataFrame, *, ahi_col: str = AHI_COL) -> pd.DataFrame:
    """Rows with a numeric, non-missing AHI — the analysis cohort."""
    if ahi_col not in metadata.columns:
        raise ValueError(f"metadata is missing AHI column {ahi_col!r}")
    ahi = pd.to_numeric(metadata[ahi_col], errors="coerce")
    cohort = metadata.loc[ahi.notna()].copy()
    cohort[ahi_col] = ahi.loc[ahi.notna()]
    return cohort


def _numeric_summary(series: pd.Series) -> Dict[str, float]:
    values = pd.to_numeric(series, errors="coerce").dropna()
    if values.empty:
        return {"n": 0}
    return {
        "n": int(len(values)),
        "mean": float(values.mean()),
        "std": float(values.std()),
        "median": float(values.median()),
        "min": float(values.min()),
        "max": float(values.max()),
    }


def _categorical_summary(series: pd.Series, labels: Dict[int, str]) -> Dict[str, Any]:
    values = series.dropna()
    counts = values.value_counts().sort_index()
    total = int(counts.sum())
    out: Dict[str, Any] = {"n": total, "categories": {}}
    for code, count in counts.items():
        try:
            label = labels.get(int(code), f"Unknown code ({code})")
        except (TypeError, ValueError):
            label = f"Unknown code ({code})"
        out["categories"][label] = {
            "count": int(count),
            "percent": 100.0 * count / total if total else 0.0,
        }
    return out


def ahi_severity_distribution(cohort: pd.DataFrame, *, ahi_col: str = AHI_COL) -> pd.DataFrame:
    """Counts/percentages per clinical severity category, in clinical order."""
    ahi = pd.to_numeric(cohort[ahi_col], errors="coerce")
    total = int(ahi.notna().sum())
    rows = []
    for name, lo, hi in AHI_SEVERITY_BINS:
        count = int(((ahi >= lo) & (ahi < hi)).sum()) if np.isfinite(lo) else int((ahi < hi).sum())
        rows.append({
            "category": name,
            "count": count,
            "percent": 100.0 * count / total if total else 0.0,
        })
    return pd.DataFrame(rows)


def analyze_cohort(metadata: pd.DataFrame) -> Dict[str, Any]:
    """Demographics + AHI stats for the AHI-defined cohort (C23)."""
    cohort = define_cohort(metadata)
    out: Dict[str, Any] = {
        "n_total_records": int(len(metadata)),
        "n_cohort": int(len(cohort)),
        "ahi": _numeric_summary(cohort[AHI_COL]),
        "ahi_severity": ahi_severity_distribution(cohort),
    }
    if AGE_COL in cohort.columns:
        out["age"] = _numeric_summary(cohort[AGE_COL])
    if GENDER_COL in cohort.columns:
        out["gender"] = _categorical_summary(cohort[GENDER_COL], GENDER_LABELS)
    if RACE_COL in cohort.columns:
        out["race"] = _categorical_summary(cohort[RACE_COL], RACE_LABELS)
    return out


def analyze_signal_quality(metadata: pd.DataFrame) -> Dict[str, Any]:
    """Per-channel 1-5 quality-code distributions over the cohort (C24)."""
    cohort = define_cohort(metadata)
    out: Dict[str, Any] = {"n_cohort": int(len(cohort)), "channels": {}}
    for var, display in QUALITY_VARS.items():
        if var not in cohort.columns:
            continue
        out["channels"][var] = {
            "name": display,
            **_categorical_summary(cohort[var], QUALITY_CODE_LABELS),
        }
    return out


def format_cohort_report(stats: Dict[str, Any]) -> str:
    lines = [
        f"Total records: {stats['n_total_records']}",
        f"Cohort (non-missing {AHI_COL}): {stats['n_cohort']}",
    ]
    if "age" in stats and stats["age"].get("n"):
        a = stats["age"]
        lines.append(
            f"Age: {a['mean']:.1f} ± {a['std']:.1f} y "
            f"(median {a['median']:.1f}, range {a['min']:.1f}-{a['max']:.1f})"
        )
    for key in ("gender", "race"):
        if key in stats:
            lines.append(f"{key.capitalize()}:")
            for label, c in stats[key]["categories"].items():
                lines.append(f"  {label}: {c['count']} ({c['percent']:.1f}%)")
    ahi = stats["ahi"]
    if ahi.get("n"):
        lines.append(
            f"AHI: {ahi['mean']:.1f} ± {ahi['std']:.1f} events/h "
            f"(median {ahi['median']:.1f}, range {ahi['min']:.1f}-{ahi['max']:.1f})"
        )
    lines.append("AHI severity distribution:")
    for _, row in stats["ahi_severity"].iterrows():
        lines.append(f"  {row['category']}: {row['count']} ({row['percent']:.1f}%)")
    return "\n".join(lines)


def format_signal_quality_report(stats: Dict[str, Any]) -> str:
    lines = [f"Cohort: {stats['n_cohort']}"]
    for var, info in stats["channels"].items():
        lines.append(f"{info['name']} [{var}] (n={info['n']}):")
        for label, c in info["categories"].items():
            lines.append(f"  {label}: {c['count']} ({c['percent']:.1f}%)")
    return "\n".join(lines)
