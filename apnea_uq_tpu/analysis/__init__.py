from apnea_uq_tpu.analysis.columns import (
    COL_CORRECT,
    COL_ENTROPY,
    COL_PATIENT,
    COL_PRED_LABEL,
    COL_PROB,
    COL_TRUE_LABEL,
    COL_VARIANCE,
    COL_WINDOW,
    DETAILED_COLUMNS,
)
from apnea_uq_tpu.analysis.patient import (
    aggregate_patients,
    patient_summary_report,
)
from apnea_uq_tpu.analysis.stats import (
    mann_whitney_u,
    patient_accuracy_entropy_correlation,
    pearson_corr,
    uncertainty_correctness_test,
)
# NOTE: apnea_uq_tpu.analysis.sweep is intentionally NOT imported here —
# it pulls in jax/flax via uq.predict, and the pure-pandas analysis stages
# (aggregate-patients, analyze-windows, correlate, figures) must stay
# importable and fast without a device runtime.  Import it directly:
# ``from apnea_uq_tpu.analysis.sweep import mcd_pass_sweep``.
from apnea_uq_tpu.analysis.calibration import (
    CalibrationSummary,
    calibration_summary,
    calibration_summary_from_arrays,
    reliability_bins,
)
from apnea_uq_tpu.analysis.windows import (
    WindowAnalysis,
    retention_curve,
    window_level_analysis,
)

__all__ = [
    "COL_PATIENT",
    "COL_WINDOW",
    "COL_TRUE_LABEL",
    "COL_PRED_LABEL",
    "COL_PROB",
    "COL_VARIANCE",
    "COL_ENTROPY",
    "COL_CORRECT",
    "DETAILED_COLUMNS",
    "aggregate_patients",
    "patient_summary_report",
    "window_level_analysis",
    "retention_curve",
    "calibration_summary",
    "calibration_summary_from_arrays",
    "reliability_bins",
    "CalibrationSummary",
    "WindowAnalysis",
    "pearson_corr",
    "mann_whitney_u",
    "patient_accuracy_entropy_correlation",
    "uncertainty_correctness_test",
]
