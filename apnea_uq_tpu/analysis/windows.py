"""Window-level uncertainty-vs-correctness analysis (reference C18).

Replaces ``analyze_window_level_uncertainty.py``: correct-vs-incorrect
descriptive statistics of entropy/variance (``:37-44``) and a 10-equal-
width-bin table of per-bin window count, accuracy, and error rate over the
chosen uncertainty metric (``:47-67``).

Adds the selective-prediction retention curve the reference's headline
claim implies but never computes: "DE ... identif[ies] a large subset of
predictions with very high accuracy (over 99%)" (reference README.md:14)
is a statement about accuracy on the lowest-uncertainty fraction of
windows, which the reference only approximates through its equal-width
bins.  ``retention_curve`` sorts windows by uncertainty and reports
cumulative accuracy at each retained fraction, so that claim becomes a
reproducible number.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pandas as pd

from apnea_uq_tpu.analysis.columns import (
    COL_CORRECT,
    COL_ENTROPY,
    COL_PRED_LABEL,
    COL_TRUE_LABEL,
    COL_VARIANCE,
)


@dataclasses.dataclass
class WindowAnalysis:
    overall_accuracy: float
    num_windows: int
    correct_stats: pd.DataFrame      # describe() of entropy/variance, correct
    incorrect_stats: pd.DataFrame    # describe() of entropy/variance, incorrect
    binned: pd.DataFrame             # per-bin window_count/accuracy/error_rate
    metric: str

    def report(self) -> str:
        return "\n".join([
            f"Windows: {self.num_windows}, overall accuracy "
            f"{self.overall_accuracy:.4f}",
            "",
            "Correctly classified windows:",
            self.correct_stats.to_string(),
            "",
            "Incorrectly classified windows:",
            self.incorrect_stats.to_string(),
            "",
            f"Binned accuracy / error rate vs {self.metric}:",
            self.binned.to_string(float_format="%.4f"),
        ])


def window_level_analysis(
    detailed: pd.DataFrame,
    *,
    metric: str = COL_ENTROPY,
    num_bins: int = 10,
) -> WindowAnalysis:
    """Correct/incorrect stats + equal-width binned accuracy table.

    Bin edges span [min, max + 1e-9) in ``num_bins`` equal widths with
    left-closed intervals, matching analyze_window_level_uncertainty.py:52-60;
    empty bins are kept (``observed=False`` groupby semantics) so the bin
    axis is always complete.
    """
    for col in (COL_TRUE_LABEL, COL_PRED_LABEL, COL_VARIANCE, metric):
        if col not in detailed.columns:
            raise ValueError(f"detailed results frame is missing column {col!r}")

    frame = detailed.copy()
    if COL_CORRECT not in frame.columns:
        frame[COL_CORRECT] = frame[COL_TRUE_LABEL] == frame[COL_PRED_LABEL]

    stat_cols = [COL_ENTROPY, COL_VARIANCE] if metric == COL_ENTROPY else [metric, COL_VARIANCE]
    correct_stats = frame.loc[frame[COL_CORRECT], stat_cols].describe()
    incorrect_stats = frame.loc[~frame[COL_CORRECT], stat_cols].describe()

    values = frame[metric].to_numpy(dtype=np.float64)
    edges = np.linspace(values.min(), values.max() + 1e-9, num_bins + 1)
    labels = [f"{edges[i]:.3f}-{edges[i + 1]:.3f}" for i in range(num_bins)]
    # A tight metric range can make 3-decimal labels collide (which the
    # reference would crash on); keep the categorical unordered then.
    ordered = len(set(labels)) == len(labels)
    frame["_bin"] = pd.cut(
        frame[metric], bins=edges, labels=labels, right=False, ordered=ordered
    )
    binned = frame.groupby("_bin", observed=False).agg(
        window_count=(COL_CORRECT, "size"),
        accuracy=(COL_CORRECT, "mean"),
    )
    binned["error_rate"] = 1.0 - binned["accuracy"]
    binned.index.name = f"{metric}_Bin"

    return WindowAnalysis(
        overall_accuracy=float(frame[COL_CORRECT].mean()),
        num_windows=int(len(frame)),
        correct_stats=correct_stats,
        incorrect_stats=incorrect_stats,
        binned=binned.reset_index(),
        metric=metric,
    )


def retention_curve(
    detailed: pd.DataFrame,
    *,
    metric: str = COL_ENTROPY,
    fractions=None,
) -> pd.DataFrame:
    """Accuracy on the lowest-uncertainty fraction of windows.

    Windows are sorted ascending by ``metric`` (most confident first;
    ties broken stably so results are deterministic) and cumulative
    accuracy is evaluated at each retained fraction.  Columns:
    ``fraction``, ``n_windows``, ``accuracy``, ``threshold`` (the largest
    metric value retained).  ``fraction=1.0`` equals overall accuracy.
    """
    for col in (COL_TRUE_LABEL, COL_PRED_LABEL, metric):
        if col not in detailed.columns:
            raise ValueError(f"detailed results frame is missing column {col!r}")
    if fractions is None:
        fractions = np.round(np.arange(0.05, 1.0001, 0.05), 2)
    fractions = np.asarray(list(fractions), dtype=np.float64)
    if len(fractions) == 0 or (fractions <= 0).any() or (fractions > 1).any():
        raise ValueError(f"fractions must lie in (0, 1], got {fractions}")

    if len(detailed) == 0:
        raise ValueError("detailed results frame has no windows")
    values = detailed[metric].to_numpy(dtype=np.float64)
    correct = (
        detailed[COL_TRUE_LABEL].to_numpy() == detailed[COL_PRED_LABEL].to_numpy()
    ).astype(np.float64)
    order = np.argsort(values, kind="mergesort")
    sorted_vals = values[order]
    cum_correct = np.cumsum(correct[order])

    n = len(values)
    rows = []
    for f in fractions:
        k = max(1, int(round(f * n)))
        rows.append({
            "fraction": float(f),
            "n_windows": k,
            "accuracy": float(cum_correct[k - 1] / k),
            "threshold": float(sorted_vals[k - 1]),
        })
    return pd.DataFrame(rows)
