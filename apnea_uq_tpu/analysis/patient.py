"""Patient-level aggregation of per-window UQ results (reference C17).

Replaces ``aggregate_patient_uq_metrics.py``: groupby patient -> mean /
median / std of predictive variance and entropy, per-patient accuracy and
window count (``:35-44``), with std zeroed for single-window patients
(``:45-46``).  Unlike the reference — which is switched MCD<->DE by
hand-editing its input path (``:7``) — this is a pure function over the
detailed frame, and the CLI stage parameterizes the method tag.
"""

from __future__ import annotations

import pandas as pd

from apnea_uq_tpu.analysis.columns import (
    COL_CORRECT,
    COL_ENTROPY,
    COL_PATIENT,
    COL_PRED_LABEL,
    COL_TRUE_LABEL,
    COL_VARIANCE,
)

_REQUIRED = (COL_PATIENT, COL_TRUE_LABEL, COL_PRED_LABEL, COL_VARIANCE, COL_ENTROPY)

SUMMARY_METRIC_COLUMNS = (
    "mean_variance",
    "median_variance",
    "std_variance",
    "mean_entropy",
    "median_entropy",
    "std_entropy",
    "patient_accuracy",
    "num_windows",
)


def _check_columns(frame: pd.DataFrame) -> None:
    missing = [c for c in _REQUIRED if c not in frame.columns]
    if missing:
        raise ValueError(
            f"detailed results frame is missing column(s) {missing}; "
            f"have {list(frame.columns)}"
        )


def aggregate_patients(detailed: pd.DataFrame) -> pd.DataFrame:
    """Per-patient summary frame from the detailed per-window frame.

    Columns: ``Patient_ID`` + :data:`SUMMARY_METRIC_COLUMNS`, matching the
    reference's ``patient_summary_metrics_{MCD,DE}.csv`` schema
    (aggregate_patient_uq_metrics.py:35-54).
    """
    _check_columns(detailed)
    frame = detailed.copy()
    frame[COL_CORRECT] = frame[COL_TRUE_LABEL] == frame[COL_PRED_LABEL]
    summary = (
        frame.groupby(COL_PATIENT)
        .agg(
            mean_variance=(COL_VARIANCE, "mean"),
            median_variance=(COL_VARIANCE, "median"),
            std_variance=(COL_VARIANCE, "std"),
            mean_entropy=(COL_ENTROPY, "mean"),
            median_entropy=(COL_ENTROPY, "median"),
            std_entropy=(COL_ENTROPY, "std"),
            patient_accuracy=(COL_CORRECT, "mean"),
            num_windows=(COL_PATIENT, "size"),
        )
        .reset_index()
    )
    # pandas .std() is NaN for n=1; the reference zeroes it (:45-46).
    single = summary["num_windows"] <= 1
    summary.loc[single, ["std_variance", "std_entropy"]] = 0.0
    return summary


def patient_summary_report(summary: pd.DataFrame, *, n_examples: int = 5) -> str:
    """Textual report: overall describe + highest/lowest-entropy patients
    (aggregate_patient_uq_metrics.py:60-83)."""
    stat_cols = [
        "mean_entropy", "mean_variance", "std_entropy", "std_variance",
        "patient_accuracy",
    ]
    example_cols = [
        COL_PATIENT, "mean_entropy", "mean_variance", "patient_accuracy",
        "num_windows",
    ]
    ordered = summary.sort_values("mean_entropy", ascending=False)
    high, low = ordered.head(n_examples), ordered.tail(n_examples)
    parts = [
        f"Patients: {len(summary)}",
        "",
        "Overall patient statistics:",
        summary[stat_cols].describe().to_string(),
        "",
        f"Top {n_examples} patients by mean entropy:",
        high[example_cols].to_string(index=False),
        "",
        f"Bottom {n_examples} patients by mean entropy:",
        low[example_cols].to_string(index=False),
    ]
    return "\n".join(parts)
