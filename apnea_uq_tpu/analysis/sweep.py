"""T/N convergence sweep runner — the piece the reference lacks.

The reference's convergence figure (C20) plots a CSV of overall mean
variance vs K that was collected by *hand-re-running* the MCD/DE drivers
with different pass/member counts (SURVEY §5.6: "there is no sweep runner
in the repo"; hyperparameter_plot_mcd_or_de_pass_convergence.py:13-17
documents only the CSV schema).  Here the sweep is one prediction run:
predict once at K_max, then every smaller K is the prefix subset of
passes/members — distributionally identical to independent runs (passes
are i.i.d. given the model; members are a fixed ordered pool) and K_max/K
times cheaper.

Output schema matches the reference plot's input contract: column ``N``
plus one ``Variance_<set>`` column per test set.
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

import jax
import numpy as np
import pandas as pd

from apnea_uq_tpu.config import UQConfig
from apnea_uq_tpu.uq.predict import ensemble_predict, mc_dropout_predict
from apnea_uq_tpu.utils import prng

# Reference operating points (BASELINE.json sweep axes).
DEFAULT_PASS_COUNTS = (10, 25, 50, 100)
DEFAULT_MEMBER_COUNTS = (5, 10, 20)


def _variance_table(
    predictions_per_set: Mapping[str, np.ndarray],
    counts: Sequence[int],
) -> pd.DataFrame:
    rows = []
    for k in counts:
        row = {"N": int(k)}
        for set_name, preds in predictions_per_set.items():
            if k > preds.shape[0]:
                raise ValueError(
                    f"count {k} exceeds available passes/members {preds.shape[0]}"
                )
            row[f"Variance_{set_name}"] = float(preds[:k].var(axis=0).mean())
        rows.append(row)
    return pd.DataFrame(rows)


def mcd_pass_sweep(
    model,
    variables: dict,
    test_sets: Mapping[str, np.ndarray],
    *,
    pass_counts: Sequence[int] = DEFAULT_PASS_COUNTS,
    config: UQConfig = UQConfig(),
    key: Optional[jax.Array] = None,
    mesh=None,
) -> pd.DataFrame:
    """Overall mean predictive variance vs number of MC-Dropout passes.

    ``test_sets`` maps a set label (e.g. 'Unbalanced', 'Balanced') to its
    window array; one T=max(pass_counts) prediction per set feeds every row.
    """
    if key is None:
        key = prng.stochastic_key(0)
    t_max = max(pass_counts)
    preds = {}
    for i, (name, x) in enumerate(test_sets.items()):
        preds[name] = np.asarray(mc_dropout_predict(
            model, variables, x,
            n_passes=t_max,
            mode=config.mcd_mode,
            batch_size=config.mcd_batch_size,
            key=jax.random.fold_in(key, i),
            mesh=mesh,
        ))
    return _variance_table(preds, sorted(pass_counts))


def de_member_sweep(
    model,
    member_variables,
    test_sets: Mapping[str, np.ndarray],
    *,
    member_counts: Sequence[int] = DEFAULT_MEMBER_COUNTS,
    config: UQConfig = UQConfig(),
    mesh=None,
) -> pd.DataFrame:
    """Overall mean predictive variance vs ensemble size.

    Ensemble-size K uses the first K members of the pool, mirroring how
    the reference's N=5 patient-level ensemble is a prefix of its N=20
    global pool (analyze_de_patient_level.py:18-20, evaluate_de_global.py:11).
    """
    preds = {
        name: np.asarray(ensemble_predict(
            model, member_variables, x,
            batch_size=config.inference_batch_size, mesh=mesh,
        ))
        for name, x in test_sets.items()
    }
    n_members = next(iter(preds.values())).shape[0]
    counts = sorted(member_counts)
    if counts[-1] > n_members:
        raise ValueError(
            f"member_counts max {counts[-1]} exceeds pool size {n_members}"
        )
    return _variance_table(preds, counts)
