"""HBM accounting for the jitted hot paths (ISSUE 3 tentpole, piece 1).

Two complementary sources, both recorded as run-log events so
``apnea-uq telemetry summarize`` can render a per-stage HBM/headroom
table and ``telemetry compare`` can gate on footprint regressions:

- :func:`record_jit_memory` — XLA's *static* accounting: lower+compile
  the exact jitted program a hot path is about to dispatch and record
  ``Compiled.memory_analysis()`` (argument/output/temp bytes and the
  derived peak) as a ``memory_profile`` event.  The numbers are what the
  compiler reserves, so they are exact on TPU — including over the
  tunneled backend, whose runtime ``memory_stats()`` returns None and
  hides live usage from us.
- :func:`snapshot_device_memory` — the *dynamic* view at a stage
  bracket: ``device.memory_stats()`` (bytes in use / peak / limit, when
  the runtime exposes them) plus a ``jax.profiler.device_memory_profile``
  pprof dump saved under ``<run_dir>/memory/``, recorded as a
  ``memory_snapshot`` event.

Cost note: with a driver-supplied ``program`` (the compile-cost
subsystem's :class:`~apnea_uq_tpu.compilecache.Program`, carrying the
executable and the stats priced when it was first compiled — persisted
alongside the serialized program, so a ProgramStore hit skips the
``memory_analysis()`` recompute entirely) the accounting costs nothing:
one lowering serves pricing and execution both.  WITHOUT one — library
callers outside any active store — ``record_jit_memory`` falls back to
compiling the program a second time (AOT ``lower().compile()`` does not
share the jit call cache), so call sites invoke it once per program
signature — a per-run-log memo enforces that even when a caller (e.g.
bench's repeated ``fit_ensemble`` reps against one run log) cannot.
Per-RUN, not per-process: a second
run in the same process (a notebook driver, back-to-back CLI stages)
must get its own ``memory_profile`` events, or its HBM table comes up
empty and its footprint metrics silently drop out of the compare gate.
Everything is best-effort: accounting must never break or slow a run
beyond that one-time compile.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax

from apnea_uq_tpu.utils.io import atomic_write_bytes

# Public HBM capacity per chip kind — the fallback sizing hint when the
# runtime exposes no memory_stats (the tunneled TPU backend returns
# None).  bench.py seeds its reference-pattern set size from this table
# too, so the one copy lives here.
CHIP_HBM_BYTES: Dict[str, float] = {
    "TPU v4": 32e9,
    "TPU v5 lite": 16e9,
    "TPU v5e": 16e9,
    "TPU v5": 95e9,   # v5p
    "TPU v5p": 95e9,
    "TPU v6 lite": 32e9,
    "TPU v6e": 32e9,
}

def _memo(run_log) -> set:
    """The run log's (label, abstract-signature) dedupe set — keeps
    repeated dispatches against one run (bench reps, per-test-set eval
    loops at equal shapes) from paying the AOT compile more than once,
    while a fresh run log always records afresh."""
    memo = getattr(run_log, "_memory_profile_memo", None)
    if memo is None:
        memo = set()
        run_log._memory_profile_memo = memo
    return memo


def device_hbm_limit(device=None) -> Optional[int]:
    """Per-device HBM capacity in bytes: ``memory_stats()['bytes_limit']``
    when the runtime exposes it, else the public spec for the chip kind,
    else None (e.g. CPU).  Never raises.  The default device is
    process-LOCAL: under a multi-process mesh ``jax.devices()[0]`` can
    be another host's device, whose ``memory_stats()`` raises."""
    try:
        if device is None:
            device = jax.local_devices()[0]
        try:
            stats = device.memory_stats() or {}
        except Exception:  # noqa: BLE001 - tunneled backends may raise
            stats = {}
        limit = stats.get("bytes_limit")
        if limit:
            return int(limit)
        limit = CHIP_HBM_BYTES.get(device.device_kind)
        return int(limit) if limit else None
    except Exception:  # noqa: BLE001 - no backend at all
        return None


def memory_analysis_fields(stats) -> Dict[str, int]:
    """Flatten a ``CompiledMemoryStats`` into event fields.  ``peak_bytes``
    is the standard XLA accounting: arguments + outputs + temporaries,
    minus buffers aliased between them (donations)."""
    arg = int(getattr(stats, "argument_size_in_bytes", 0))
    out = int(getattr(stats, "output_size_in_bytes", 0))
    temp = int(getattr(stats, "temp_size_in_bytes", 0))
    alias = int(getattr(stats, "alias_size_in_bytes", 0))
    return {
        "argument_bytes": arg,
        "output_bytes": out,
        "temp_bytes": temp,
        "alias_bytes": alias,
        "generated_code_bytes": int(
            getattr(stats, "generated_code_size_in_bytes", 0)
        ),
        "peak_bytes": arg + out + temp - alias,
    }


def _abstract_signature(args: tuple, kwargs: dict) -> str:
    """A cheap process-stable signature of a jitted call's arguments:
    array leaves become (shape, dtype), everything else (static args,
    meshes, scalars) its repr — the same distinctions the jit cache key
    makes, coarse enough to build without tracing."""

    def leaf(a: Any) -> str:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            return f"arr{tuple(shape)}:{dtype}"
        return repr(a)

    tree = (args, tuple(sorted(kwargs.items())))
    return str(jax.tree.map(leaf, tree))


def record_jit_memory(run_log, label: str, fn, *args,
                      program=None, **kwargs) -> Optional[Dict[str, Any]]:
    """Lower+compile ``fn(*args, **kwargs)`` (a ``jax.jit``-wrapped
    callable, invoked exactly as the hot path is about to) and append a
    ``memory_profile`` event with its compiled memory analysis plus the
    device's HBM limit and headroom.  Deduped per run log per (label,
    argument signature); best-effort — returns the event record or None,
    never raises.

    ``program`` (a :class:`~apnea_uq_tpu.compilecache.Program` the
    caller acquired for this exact call) supplies the memory fields
    priced when the executable was first compiled — persisted alongside
    the serialized program, so even a ProgramStore hit skips the
    ``memory_analysis()`` recompute — and NO second AOT compile happens
    here.  Without one, the historical double-compile fallback runs;
    ``APNEA_UQ_MEMORY_PROFILE=0`` disables the accounting
    entirely — the opt-out for runs where even one extra AOT compile of
    the heaviest program (absorbed as a disk hit under a warm persistent
    compilation cache, but a real compile without one) is unwelcome."""
    if run_log is None or getattr(run_log, "disabled", False):
        return None
    if os.environ.get("APNEA_UQ_MEMORY_PROFILE", "1").lower() in (
            "0", "false", "off"):
        return None
    try:
        memo = _memo(run_log)
        key = (label, _abstract_signature(args, kwargs))
        if key in memo:
            return None
        # Memoize the ATTEMPT, not the success: on a backend where
        # memory_analysis() is unimplemented (returns None/raises),
        # retrying every call would re-pay the full AOT compile — inside
        # the timed windows the drivers' pre-pass exists to protect.
        memo.add(key)
        if program is not None:
            # One-lowering sharing (compilecache.get_program): the fields
            # were priced when the executable was built — or read back
            # from the store's metadata on a hit — so the historical
            # second AOT compile below never runs.
            if program.memory_fields is None:
                return None
            fields = dict(program.memory_fields)
        else:
            stats = fn.lower(*args, **kwargs).compile().memory_analysis()
            if stats is None:
                return None
            fields = memory_analysis_fields(stats)
        # Process-local on purpose: the profile describes THIS process's
        # compiled module, and a remote host's device has no stats here.
        device = jax.local_devices()[0]
        limit = device_hbm_limit(device)
        return run_log.event(
            "memory_profile",
            label=label,
            platform=device.platform,
            device_kind=device.device_kind,
            hbm_limit_bytes=limit,
            headroom_bytes=(limit - fields["peak_bytes"]
                            if limit is not None else None),
            **fields,
        )
    except Exception:  # noqa: BLE001 - accounting must never break a run
        return None


def snapshot_device_memory(run_log, label: str) -> Optional[Dict[str, Any]]:
    """Append a ``memory_snapshot`` event: the runtime's live-usage
    counters (when exposed) and a ``jax.profiler.device_memory_profile``
    pprof dump saved to ``<run_dir>/memory/<label>.pprof.gz``.
    Best-effort; never raises."""
    if run_log is None or getattr(run_log, "disabled", False):
        return None
    try:
        fields: Dict[str, Any] = {"label": label}
        try:
            # Process-local: memory_stats of a remote device would raise.
            device = jax.local_devices()[0]
            stats = device.memory_stats() or {}
        except Exception:  # noqa: BLE001 - backend may be unusable
            stats = {}
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            fields[key] = (int(stats[key]) if stats.get(key) is not None
                           else None)
        try:
            profile = jax.profiler.device_memory_profile()
            rel = os.path.join("memory",
                               f"{label.replace(os.sep, '_')}.pprof.gz")
            path = os.path.join(run_log.run_dir, rel)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            # Atomic: snapshots land in a run dir summarize reads live.
            atomic_write_bytes(path, profile)
            fields["profile_path"] = rel
            fields["profile_bytes"] = len(profile)
        except Exception:  # noqa: BLE001 - profiler-less builds
            pass
        return run_log.event("memory_snapshot", **fields)
    except Exception:  # noqa: BLE001 - accounting must never break a run
        return None
