"""Bounded programmatic profiler capture (ISSUE 3 tentpole, piece 2).

``utils.timing.profile_trace`` wraps an arbitrary block in a
``jax.profiler`` trace; that is the right shape for a one-shot eval but
wrong for training, where tracing every epoch captures the compile storm
of epoch 1 and produces a dump too large to ship over a tunnel.
:class:`TraceSession` adds the two bounds a long loop needs:

- **warmup skip** — the trace starts only after ``warmup_steps`` calls
  to :meth:`TraceSession.step`, so compilation and cache warming stay
  out of the capture;
- **step budget** — the trace stops after ``max_steps`` profiled steps,
  so the artifact stays bounded no matter how long the run is.

The trace directory defaults to ``<run_dir>/profile/<label>`` — the
capture lives next to the run's ``events.jsonl`` — and the stop is
announced with a ``profile_captured`` event so tooling (and the
summarizer) can find it without globbing.

Used as ``--profile`` on the train/train-ensemble/eval-mcd/eval-de CLI
stages and as ``BENCH_PROFILE`` in bench.py.  With ``warmup_steps=0``
the session starts capturing at ``__enter__`` and stops at ``__exit__``
(bracket mode — what the single-dispatch eval stages use).
"""

from __future__ import annotations

import contextlib
import os
from typing import Any, Dict, Optional

from apnea_uq_tpu.telemetry.logging_shim import log


class TraceSession:
    """Bounded ``jax.profiler`` capture around a stepped loop.

    Call :meth:`step` at every step boundary (the trainers call it once
    per epoch).  Degrades to inert if the profiler is unavailable or a
    trace is already active; a session that ends before its warmup is
    satisfied captures nothing and says so through ``telemetry.log``.
    """

    def __init__(self, run_log=None, *, label: str = "trace",
                 trace_dir: Optional[str] = None, warmup_steps: int = 1,
                 max_steps: int = 4):
        if trace_dir is None:
            if run_log is None or getattr(run_log, "run_dir", None) is None:
                raise ValueError(
                    "TraceSession needs a run_log (trace goes under its "
                    "run dir) or an explicit trace_dir"
                )
            trace_dir = os.path.join(run_log.run_dir, "profile", label)
        self.run_log = run_log
        self.label = label
        self.trace_dir = trace_dir
        self.warmup_steps = int(warmup_steps)
        self.max_steps = int(max_steps)
        self.steps_seen = 0
        self.steps_profiled = 0
        self.started = False
        self.stopped = False
        self._broken = False

    # -- capture lifecycle -----------------------------------------------

    def _start(self) -> None:
        if self.started or self._broken:
            return
        try:
            import jax

            jax.profiler.start_trace(self.trace_dir)
            self.started = True
        except Exception as e:  # noqa: BLE001 - a busy/absent profiler
            self._broken = True  # must never break the run it observes
            log(f"profiler capture {self.label!r} unavailable: "
                f"{type(e).__name__}: {e}")

    def _finish(self) -> None:
        if not self.started or self.stopped:
            return
        self.stopped = True
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001
            log(f"profiler capture {self.label!r} failed to stop: "
                f"{type(e).__name__}: {e}")
            return
        self._announce()

    def _announce(self) -> None:
        # No step() ever marked a boundary: a bracket capture (the eval
        # stages, bench's BENCH_PROFILE pass) covering the whole block.
        # steps_profiled=None there, so tooling can tell a full bracket
        # capture from a stepped session that stopped before profiling
        # anything (e.g. a run exactly as long as its warmup).
        bracket = self.steps_seen == 0
        fields: Dict[str, Any] = {
            "label": self.label,
            "trace_dir": self._relative_trace_dir(),
            "mode": "bracket" if bracket else "steps",
            "steps_profiled": None if bracket else self.steps_profiled,
            "warmup_steps": self.warmup_steps,
        }
        if self.run_log is not None:
            self.run_log.event("profile_captured", **fields)
        span = ("whole block" if bracket
                else f"{self.steps_profiled} step(s)")
        log(f"profiler trace ({self.label}, {span}) -> {self.trace_dir}")

    def _relative_trace_dir(self) -> str:
        run_dir = getattr(self.run_log, "run_dir", None)
        if run_dir:
            rel = os.path.relpath(self.trace_dir, run_dir)
            if not rel.startswith(os.pardir):
                return rel
        return self.trace_dir

    # -- caller surface ---------------------------------------------------

    def step(self) -> None:
        """Mark one step boundary: starts the trace once the warmup is
        skipped, stops it once the step budget is spent."""
        self.steps_seen += 1
        if not self.started:
            if self.steps_seen >= self.warmup_steps:
                self._start()
            return
        if not self.stopped:
            self.steps_profiled += 1
            if self.steps_profiled >= self.max_steps:
                self._finish()

    def __enter__(self) -> "TraceSession":
        if self.warmup_steps <= 0:
            self._start()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self.started:
            self._finish()
        elif not self._broken:
            log(f"profiler capture {self.label!r} requested but the run "
                f"ended after {self.steps_seen} step(s), inside the "
                f"{self.warmup_steps}-step warmup; nothing captured")


@contextlib.contextmanager
def maybe_profile(run_log, enabled: bool, **session_kwargs):
    """``with maybe_profile(run_log, args.profile, label=...) as prof:`` —
    yields a live :class:`TraceSession` when enabled, else None, so call
    sites pass ``prof`` straight through as a trainer's ``profiler``."""
    if not enabled:
        yield None
        return
    with TraceSession(run_log, **session_kwargs) as session:
        yield session
