"""Model-quality telemetry: calibration/uncertainty events + the gate.

The systems telemetry (device time, HBM, compile cost, D2H bytes) would
pass a model that silently miscalibrates; this module makes *quality* a
first-class, gateable stream:

- **Write side** — :func:`emit_quality_metrics`: every
  ``run_{mcd,de}_analysis`` eval emits one ``quality_metrics`` event
  per run label, carrying ECE/MCE/Brier (``analysis/calibration.py``
  over the per-window mean probabilities — which the fused path derives
  from the (4, M) sufficient statistics, so no raw (K, M) stack is ever
  revived for this), uncertainty-distribution summaries
  (quantiles + histograms of variance / total entropy / aleatoric
  entropy / mutual information), and the per-patient rollup aggregates.
  The input-drift twin (``drift_fingerprint``) is emitted by the eval
  stages against the frozen ``quality_baseline`` artifact
  (``analysis/fingerprint.py``).

- **Read side** — :func:`check_run` behind ``apnea-uq quality check
  <run-dir> [--baseline PRIOR]``: drift scores over threshold and
  calibration regressions vs a prior run become findings rendered
  through the shared lint reporters (text/``--json``/``--format gha``),
  exit 1 on failure, exit 2 when a source carries no quality telemetry.
  Serve run directories gate too (ISSUE 17): the online ``serve_drift``
  verdicts emitted by ``serving/drift.py`` are checked per tenant
  against the thresholds each event was scored with, so a drifted
  serve session exits 1 with no jax anywhere on the path
  (the ``telemetry compare`` usage-error contract — a gate must never
  report a clean pass over zero metrics).  The verdict is appended to
  the checked run's own event log as a ``quality_gate`` event, so the
  audit trail lives next to the numbers it judged.

Jax-free end to end (NumPy + the jax-free lint reporters); pandas is
imported only inside the write-side helpers that consume the detailed
frame.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from apnea_uq_tpu.telemetry.runlog import (EVENTS_FILENAME, append_events,
                                           latest_run, read_events)

DEFAULT_THRESHOLD_PCT = 5.0
DEFAULT_PSI_THRESHOLD = 0.2    # the standard "significant shift" PSI bar
DEFAULT_KS_THRESHOLD = 0.2

#: Calibration scalars gated against a baseline run (all lower-is-better).
CALIBRATION_METRICS = ("ece", "mce", "brier")

#: Per-window uncertainty vectors summarized into the quality event.
UNCERTAINTY_KEYS = ("pred_variance", "total_pred_entropy",
                    "expected_aleatoric_entropy", "mutual_info")

_SUMMARY_HIST_BINS = 16


class NoQualityTelemetry(ValueError):
    """A source parsed cleanly but carries no ``quality_metrics`` /
    ``drift_fingerprint`` / ``serve_drift`` events (or a baseline shares
    no run label with the candidate): nothing is gateable, which is a
    usage error (exit 2), never a clean pass."""


# ---------------------------------------------------------- write side --

def uncertainty_summary(per_window: Dict[str, Any]) -> Dict[str, Any]:
    """Distribution summaries of the per-window uncertainty vectors:
    mean + p05/p25/p50/p75/p95 + a 16-bin histogram per metric — enough
    to see a collapsed or inflated uncertainty distribution from the
    event stream without shipping M floats per metric."""
    out: Dict[str, Any] = {}
    for key in UNCERTAINTY_KEYS:
        if key not in per_window:
            continue
        v = np.asarray(per_window[key], np.float64).reshape(-1)
        v = v[np.isfinite(v)]
        if v.size == 0:
            out[key] = None
            continue
        p05, p25, p50, p75, p95 = np.percentile(v, (5, 25, 50, 75, 95))
        counts, edges = np.histogram(v, bins=_SUMMARY_HIST_BINS)
        out[key] = {
            "mean": round(float(v.mean()), 9),
            "p05": round(float(p05), 9), "p25": round(float(p25), 9),
            "p50": round(float(p50), 9), "p75": round(float(p75), 9),
            "p95": round(float(p95), 9),
            "histogram": {
                "edges": [round(float(e), 9) for e in edges],
                "counts": [int(c) for c in counts],
            },
        }
    return out


def patient_rollup(detailed) -> Optional[Dict[str, Any]]:
    """Per-patient rollup aggregates of a detailed frame (None when the
    run kept no frame or carries no real patient ids): patient count,
    mean/min patient accuracy, and the patient-mean-entropy spread —
    the worst-patient view a cohort-level ECE can hide."""
    from apnea_uq_tpu.analysis.columns import (COL_ENTROPY, COL_PATIENT,
                                               COL_PRED_LABEL,
                                               COL_TRUE_LABEL)

    if detailed is None or COL_PATIENT not in getattr(detailed, "columns",
                                                      ()):
        return None
    ids = detailed[COL_PATIENT].astype(str)
    if set(ids.unique()) == {"UNKNOWN"}:
        # The drivers' placeholder for id-less runs (detailed_frame
        # fills "UNKNOWN"), not patient structure.  A genuine
        # single-patient cohort with a real id still gets its rollup.
        return None
    correct = (detailed[COL_PRED_LABEL]
               == detailed[COL_TRUE_LABEL]).astype(float)
    acc = correct.groupby(ids).mean()
    ent = detailed[COL_ENTROPY].groupby(ids).mean()
    return {
        "n_patients": int(acc.size),
        "accuracy_mean": round(float(acc.mean()), 6),
        "accuracy_min": round(float(acc.min()), 6),
        "entropy_mean": round(float(ent.mean()), 6),
        "entropy_max": round(float(ent.max()), 6),
    }


def emit_quality_metrics(run_log, result, *, num_bins: int = 15):
    """One ``quality_metrics`` event for a finished UQ run: calibration
    scalars + uncertainty-distribution summaries + patient rollup.
    Everything derives from the evaluation's per-window vectors (which a
    fused run computed from the (4, M) sufficient statistics on device)
    and the detailed frame — never from a revived probability stack."""
    from apnea_uq_tpu.analysis.calibration import \
        calibration_summary_from_arrays

    ev = result.evaluation
    probs = np.clip(
        np.asarray(ev.per_window["mean_pred"], np.float64).reshape(-1),
        0.0, 1.0,
    )
    cal = calibration_summary_from_arrays(probs, result.y_true,
                                          num_bins=num_bins)
    return run_log.event(
        "quality_metrics",
        label=result.label,
        n_windows=int(ev.n_windows),
        n_passes=int(ev.n_passes),
        fused=bool(result.fused),
        num_bins=int(num_bins),
        ece=round(cal.ece, 6),
        mce=round(cal.mce, 6),
        brier=round(cal.brier, 6),
        uncertainty=uncertainty_summary(ev.per_window),
        patients=patient_rollup(result.detailed),
    )


# ----------------------------------------------------------- read side --

def quality_events(
    run_dir: str,
) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]],
           List[Dict[str, Any]]]:
    """(quality_metrics, drift_fingerprint, serve_drift events) of the
    latest run in ``run_dir`` — the same run-boundary rule
    summarize/compare use.  The third element is how a serve run
    directory becomes gateable: its online per-tenant drift verdicts
    stand in where a batch eval would have emitted fingerprints."""
    events = read_events(run_dir)
    if not events:
        raise FileNotFoundError(
            f"no {EVENTS_FILENAME} events under {run_dir!r} — not a "
            f"telemetry run directory"
        )
    events, _earlier = latest_run(events)
    return (
        [e for e in events if e.get("kind") == "quality_metrics"],
        [e for e in events if e.get("kind") == "drift_fingerprint"],
        [e for e in events if e.get("kind") == "serve_drift"],
    )


@dataclasses.dataclass
class QualityCheck:
    """One gate decision: a drift score against its threshold, or a
    calibration scalar against its baseline-run value."""

    kind: str                       # "drift" | "serve_drift" | "calibration"
    label: str                      # run label / test-set label / tenant
    metric: str                     # max_psi, max_ks, ece, mce, brier
    value: float
    passed: bool
    limit: Optional[float] = None          # drift: the threshold
    baseline: Optional[float] = None       # calibration: prior value
    delta_pct: Optional[float] = None      # calibration: signed worsening
    detail: str = ""

    def message(self) -> str:
        if self.kind in ("drift", "serve_drift"):
            verdict = "within" if self.passed else "over"
            prefix = ("serve drift" if self.kind == "serve_drift"
                      else "drift")
            text = (f"{prefix} {self.metric}={self.value:g} {verdict} "
                    f"threshold {self.limit:g} for {self.label}")
        else:
            delta = ("n/a" if self.delta_pct is None
                     else f"{self.delta_pct:+.1f}%")
            text = (f"calibration {self.metric} {self.baseline:g} -> "
                    f"{self.value:g} ({delta}) for {self.label}")
        if self.detail:
            text += f" ({self.detail})"
        return text


@dataclasses.dataclass
class QualityGate:
    """The full verdict of one ``quality check`` invocation."""

    run_dir: str
    baseline_path: Optional[str]
    threshold_pct: float
    psi_threshold: float
    ks_threshold: float
    checks: List[QualityCheck]

    @property
    def failures(self) -> List[QualityCheck]:
        return [c for c in self.checks if not c.passed]

    @property
    def passed(self) -> bool:
        return not self.failures


def check_run(
    run_dir: str,
    *,
    baseline: Optional[str] = None,
    threshold_pct: float = DEFAULT_THRESHOLD_PCT,
    psi_threshold: float = DEFAULT_PSI_THRESHOLD,
    ks_threshold: float = DEFAULT_KS_THRESHOLD,
) -> QualityGate:
    """Gate one run's quality telemetry.

    Drift: every ``drift_fingerprint`` event's ``max_psi``/``max_ks``
    against the thresholds (the baseline comparison already happened at
    emission time, against the frozen ``quality_baseline`` artifact).
    Calibration: with ``baseline`` (a prior run directory), every
    shared-label ``quality_metrics`` event's ECE/MCE/Brier against the
    prior value — a lower-is-better worsening past ``threshold_pct`` is
    a regression.  Self-comparison is a clean pass by construction.

    Serve runs: each tenant's LAST ``serve_drift`` event (append order —
    usually the ``final=True`` shutdown flush) gates ``max_psi`` /
    ``max_ks`` against the drift thresholds the event itself was scored
    with (falling back to the CLI thresholds for pre-threshold-field
    logs), so a per-tenant override gates with the override and a
    drifted serve session exits 1."""
    qm, drifts, serve_drifts = quality_events(run_dir)
    if not qm and not drifts and not serve_drifts:
        raise NoQualityTelemetry(
            f"no quality_metrics, drift_fingerprint, or serve_drift "
            f"events in {run_dir!r} — was the eval run with a "
            f"quality-aware build (or the serve run with --drift-check), "
            f"and does the registry carry a quality_baseline?"
        )
    checks: List[QualityCheck] = []
    for e in drifts:
        for metric, limit in (("max_psi", psi_threshold),
                              ("max_ks", ks_threshold)):
            value = e.get(metric)
            if value is None:
                continue
            checks.append(QualityCheck(
                kind="drift", label=str(e.get("label", "?")),
                metric=metric, value=float(value), limit=float(limit),
                passed=float(value) <= float(limit),
                detail=(f"worst channel {e.get('worst_channel')}"
                        if e.get("worst_channel") else ""),
            ))
    # Serve-path drift: the monitor emits >= as the drift verdict, so
    # the gate fails at value >= limit (not >) — the gate and the
    # emitted verdict can never disagree about the same event.
    last_by_tenant: Dict[str, Dict[str, Any]] = {}
    for e in serve_drifts:
        last_by_tenant[str(e.get("tenant", "?"))] = e
    for tenant in sorted(last_by_tenant):
        e = last_by_tenant[tenant]
        for metric, key, fallback in (("max_psi", "drift_psi",
                                       psi_threshold),
                                      ("max_ks", "drift_ks",
                                       ks_threshold)):
            value = e.get(metric)
            if value is None:
                continue
            limit = e.get(key)
            limit = fallback if limit is None else limit
            checks.append(QualityCheck(
                kind="serve_drift", label=f"tenant {tenant}",
                metric=metric, value=float(value), limit=float(limit),
                passed=float(value) < float(limit),
                detail=(f"worst channel {e.get('worst_channel')}"
                        if e.get("worst_channel") else ""),
            ))
    if baseline is not None:
        base_qm, _base_drifts, _base_serve = quality_events(baseline)
        base_by_label = {e.get("label"): e for e in base_qm}
        shared = [e for e in qm if e.get("label") in base_by_label]
        if not shared and not checks:
            # No shared calibration label AND no drift checks built:
            # nothing at all is gateable.  With drift checks in hand the
            # gate proceeds on those instead (compare's rule: missing-
            # on-one-side metrics are listed, never fatal) — discarding
            # valid drift gating over a label mismatch would turn a
            # drifted cohort into exit 2.
            raise NoQualityTelemetry(
                f"baseline {baseline!r} shares no quality_metrics run "
                f"label with {run_dir!r} (baseline labels: "
                f"{sorted(base_by_label)}, candidate labels: "
                f"{sorted(e.get('label') for e in qm)}), and the "
                f"candidate carries no drift_fingerprint or "
                f"serve_drift events"
            )
        for e in shared:
            b = base_by_label[e.get("label")]
            for metric in CALIBRATION_METRICS:
                bv, cv = b.get(metric), e.get(metric)
                if bv is None or cv is None:
                    continue
                bv, cv = float(bv), float(cv)
                if bv == 0.0:
                    # Undefined percent: any worsening from a perfect
                    # score regresses (compare's zero-baseline rule).
                    delta_pct = None
                    passed = cv <= 0.0
                else:
                    delta_pct = round(100.0 * (cv - bv) / abs(bv), 4)
                    passed = delta_pct <= threshold_pct
                checks.append(QualityCheck(
                    kind="calibration", label=str(e.get("label", "?")),
                    metric=metric, value=cv, baseline=bv,
                    delta_pct=delta_pct, passed=passed,
                ))
    if not checks:
        # quality_metrics exist but nothing is gateable (no drift
        # events, no --baseline): same contract as compare's
        # no-comparable-metrics — a gate must fail the invocation, not
        # report a clean pass over zero checks.
        raise NoQualityTelemetry(
            f"nothing gateable in {run_dir!r}: the run carries "
            f"quality_metrics but no drift_fingerprint or serve_drift "
            f"events, and no --baseline run was given to gate "
            f"calibration against"
        )
    return QualityGate(
        run_dir=run_dir, baseline_path=baseline,
        threshold_pct=threshold_pct, psi_threshold=psi_threshold,
        ks_threshold=ks_threshold, checks=checks,
    )


def gate_data(gate: QualityGate) -> Dict[str, Any]:
    """The gate verdict as one JSON-able document (the ``--json``
    extra payload beside the findings)."""
    return {
        "run_dir": gate.run_dir,
        "baseline": gate.baseline_path,
        "threshold_pct": gate.threshold_pct,
        "psi_threshold": gate.psi_threshold,
        "ks_threshold": gate.ks_threshold,
        "passed": gate.passed,
        "checks": [dataclasses.asdict(c) for c in gate.checks],
        "failures": [c.message() for c in gate.failures],
    }


def gate_findings(gate: QualityGate):
    """Failed checks as lint-engine findings, so the shared reporters
    (text / ``--json`` / ``--format gha``) render the quality gate with
    the exact machinery ``lint``/``audit``/``flow`` use."""
    from apnea_uq_tpu.lint.engine import Finding

    rule_by_kind = {"drift": "quality-drift",
                    "serve_drift": "quality-serve-drift",
                    "calibration": "quality-calibration-regression"}
    return [
        Finding(rule=rule_by_kind[c.kind], severity="error",
                path=gate.run_dir, line=0, message=c.message())
        for c in gate.failures
    ]


def gate_result(gate: QualityGate):
    """The findings wrapped as a :class:`LintResult` for
    ``emit_result`` — ``files_scanned`` counts gate checks."""
    from apnea_uq_tpu.lint.engine import LintResult

    return LintResult(
        findings=gate_findings(gate),
        files_scanned=len(gate.checks),
        rules_run=("quality-calibration-regression", "quality-drift",
                   "quality-serve-drift"),
        scanned_paths=(gate.run_dir,),
    )


def record_gate_event(gate: QualityGate) -> None:
    """Append the verdict to the checked run's own event log as a
    ``quality_gate`` event — the gate's audit trail lives next to the
    numbers it judged, and ``telemetry summarize`` renders it."""
    with append_events(gate.run_dir) as run_log:
        run_log.event(
            "quality_gate",
            passed=gate.passed,
            checks=len(gate.checks),
            failures=[c.message() for c in gate.failures],
            baseline=gate.baseline_path,
            threshold_pct=gate.threshold_pct,
            psi_threshold=gate.psi_threshold,
            ks_threshold=gate.ks_threshold,
        )
