"""Step-level device metrics: dispatch vs device time, throughput, and
XLA recompilation counters.

``StepMetrics.measure`` times one dispatched program twice — once to the
return of the Python call (dispatch time: trace + compile + enqueue) and
once to ``jax.block_until_ready`` on the result (device time: the whole
step, compute included).  The gap is what async dispatch hides; a step
whose dispatch time suddenly matches its device time is retracing.

Recompilations are counted through ``jax.monitoring``'s event-duration
hooks: JAX records ``.../jaxpr_trace_duration`` on every retrace and
``.../backend_compile_duration`` on every XLA compile, so a silent
retrace storm (e.g. a shape-varying member axis in the vmap-over-members
ensemble path) shows up as a per-step counter instead of a mystery
slowdown.  The listener is process-global and installed once, lazily.

Attribution caveat: the counters are process-global and unsynchronized,
so per-step deltas are only attributable while one ``measure`` runs at a
time (true of every pipeline today, which dispatches steps sequentially
from the main thread).  Concurrent measurers would cross-attribute each
other's compiles; totals stay correct either way.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Dict, List, Optional

import jax

_COUNTS: Dict[str, int] = {
    "retraces": 0, "backend_compiles": 0,
    "persistent_cache_hits": 0, "persistent_cache_misses": 0,
}
_INSTALLED = False


def _on_event_duration(name: str, secs: float, **kwargs: Any) -> None:
    if name.endswith("jaxpr_trace_duration"):
        _COUNTS["retraces"] += 1
    elif name.endswith("backend_compile_duration"):
        _COUNTS["backend_compiles"] += 1


def _on_event(name: str, **kwargs: Any) -> None:
    # Persistent-compilation-cache outcomes: `backend_compiles` counts a
    # disk HIT too (jax records the duration event around the whole
    # compile-or-load), so "fresh XLA compile" questions — the
    # compile-cost subsystem's zero-recompile claim — key on cache_misses
    # when a cache dir is configured.
    if name.endswith("compilation_cache/cache_hits"):
        _COUNTS["persistent_cache_hits"] += 1
    elif name.endswith("compilation_cache/cache_misses"):
        _COUNTS["persistent_cache_misses"] += 1


def install_compile_listener() -> bool:
    """Idempotently hook the process-global compile counters into
    ``jax.monitoring``; False when this JAX build has no listener API."""
    global _INSTALLED
    if _INSTALLED:
        return True
    try:
        jax.monitoring.register_event_duration_secs_listener(
            _on_event_duration
        )
    except Exception:  # noqa: BLE001 - older/newer jax without the hook
        return False
    try:
        jax.monitoring.register_event_listener(_on_event)
    except Exception:  # noqa: BLE001 - cache counters stay at zero
        pass
    _INSTALLED = True
    return True


def compile_counts() -> Dict[str, int]:
    """Snapshot of cumulative {retraces, backend_compiles,
    persistent_cache_hits, persistent_cache_misses} since install."""
    install_compile_listener()
    return dict(_COUNTS)


@dataclasses.dataclass
class StepRecord:
    """One measured step."""

    label: str
    dispatch_s: float     # call return: trace/compile/enqueue, no compute
    device_s: float       # block_until_ready-bounded: the whole step
    n_items: Optional[int]
    retraces: int
    backend_compiles: int

    @property
    def items_per_s(self) -> Optional[float]:
        if self.n_items is None or self.device_s <= 0:
            return None
        return self.n_items / self.device_s


class StepMetrics:
    """Measure dispatched steps; optionally emit each as a ``step`` event.

    ``run_log`` may be None — the records still accumulate on the host for
    callers that only want the timings (e.g. the UQ drivers' predict
    seconds)."""

    def __init__(self, run_log=None):
        self.run_log = run_log
        self.records: List[StepRecord] = []
        install_compile_listener()

    def measure(self, label: str, thunk: Callable[[], Any], *,
                n_items: Optional[int] = None,
                extra: Optional[Dict[str, Any]] = None) -> Any:
        """Run ``thunk``, record dispatch/device time + compile deltas,
        and return its result (blocked until ready)."""
        before = compile_counts()
        t0 = time.perf_counter()
        out = thunk()
        dispatch_s = time.perf_counter() - t0
        jax.block_until_ready(out)
        device_s = time.perf_counter() - t0
        after = compile_counts()
        record = StepRecord(
            label=label,
            dispatch_s=dispatch_s,
            device_s=device_s,
            n_items=n_items,
            retraces=after["retraces"] - before["retraces"],
            backend_compiles=(after["backend_compiles"]
                              - before["backend_compiles"]),
        )
        self.records.append(record)
        if self.run_log is not None:
            fields: Dict[str, Any] = {
                "label": label,
                "dispatch_s": round(dispatch_s, 6),
                "device_s": round(device_s, 6),
                "retraces": record.retraces,
                "backend_compiles": record.backend_compiles,
            }
            if n_items is not None:
                fields["n_items"] = int(n_items)
                ips = record.items_per_s
                if ips is not None:
                    fields["items_per_s"] = round(ips, 3)
            fields.update(extra or {})
            self.run_log.event("step", **fields)
        return out

    @property
    def last(self) -> Optional[StepRecord]:
        return self.records[-1] if self.records else None

    def totals(self) -> Dict[str, float]:
        return {
            "steps": len(self.records),
            "device_s": sum(r.device_s for r in self.records),
            "dispatch_s": sum(r.dispatch_s for r in self.records),
            "retraces": sum(r.retraces for r in self.records),
            "backend_compiles": sum(r.backend_compiles for r in self.records),
        }
