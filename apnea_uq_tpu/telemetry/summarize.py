"""Render a run's JSONL event log as a human-readable summary.

``apnea-uq telemetry summarize <run-dir>`` — the read side of the
telemetry layer: per-stage wall/device time, step counts, throughput and
recompile counters, epoch trajectories, eval predict lines, and errors,
all derived purely from ``events.jsonl`` (no JAX import, instant)."""

from __future__ import annotations

import datetime
import os
from typing import Any, Dict, List, Optional

from apnea_uq_tpu.telemetry.runlog import (EVENTS_FILENAME, latest_run,
                                           read_events)

_NO_STAGE = "(no stage)"


def _iso(ts: Optional[float]) -> str:
    if ts is None:
        return "unknown"
    dt = datetime.datetime.fromtimestamp(float(ts), tz=datetime.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def _fmt(value: Optional[float], decimals: int) -> str:
    return "-" if value is None else f"{value:.{decimals}f}"


def _stage_rows(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One row per stage, in first-appearance order, merging stage_end
    wall-clock with the ``step`` events emitted inside the stage."""
    order: List[str] = []
    rows: Dict[str, Dict[str, Any]] = {}

    def row(name: str) -> Dict[str, Any]:
        if name not in rows:
            order.append(name)
            rows[name] = {
                "stage": name, "wall_s": None, "steps": 0, "device_s": 0.0,
                "dispatch_s": 0.0, "retraces": 0, "backend_compiles": 0,
                "n_items": 0,
            }
        return rows[name]

    for e in events:
        kind = e.get("kind")
        if kind == "stage_start":
            row(e.get("stage", _NO_STAGE))
        elif kind == "stage_end":
            r = row(e.get("stage", _NO_STAGE))
            r["wall_s"] = (r["wall_s"] or 0.0) + float(e.get("wall_s", 0.0))
        elif kind == "step":
            r = row(e.get("stage", _NO_STAGE))
            r["steps"] += 1
            r["device_s"] += float(e.get("device_s", 0.0))
            r["dispatch_s"] += float(e.get("dispatch_s", 0.0))
            r["retraces"] += int(e.get("retraces", 0))
            r["backend_compiles"] += int(e.get("backend_compiles", 0))
            r["n_items"] += int(e.get("n_items", 0) or 0)
    return [rows[name] for name in order]


def _render_stage_table(rows: List[Dict[str, Any]]) -> List[str]:
    header = ("stage", "wall_s", "steps", "device_s", "dispatch_s",
              "retraces", "compiles", "items/s")
    name_w = max([len(header[0])] + [len(r["stage"]) for r in rows])
    fmt = (f"{{:<{name_w}}}  {{:>9}}  {{:>5}}  {{:>9}}  {{:>10}}  "
           f"{{:>8}}  {{:>8}}  {{:>10}}")
    lines = [fmt.format(*header)]
    for r in rows:
        items_per_s = None
        if r["n_items"] and r["device_s"] > 0:
            items_per_s = r["n_items"] / r["device_s"]
        lines.append(fmt.format(
            r["stage"],
            _fmt(r["wall_s"], 3),
            r["steps"] if r["steps"] else "-",
            _fmt(r["device_s"] if r["steps"] else None, 3),
            _fmt(r["dispatch_s"] if r["steps"] else None, 3),
            r["retraces"] if r["steps"] else "-",
            r["backend_compiles"] if r["steps"] else "-",
            _fmt(items_per_s, 1),
        ))
    return lines


def _first_last(values: List[float]) -> str:
    return f"{values[0]:.4f} -> {values[-1]:.4f}"


def _mb(value: Optional[float]) -> str:
    """Bytes as MiB with one decimal; '-' for unknown."""
    return "-" if value is None else f"{value / 2**20:.1f}"


def _render_memory_table(mems: List[Dict[str, Any]]) -> List[str]:
    """The per-program HBM/headroom table from ``memory_profile`` events
    (compiled memory analysis; telemetry/memory.py)."""
    header = ("program", "args_mb", "out_mb", "temp_mb", "peak_mb",
              "limit_mb", "headroom")
    name_w = max([len(header[0])]
                 + [len(str(e.get("label", "?"))) for e in mems])
    fmt = (f"{{:<{name_w}}}  {{:>8}}  {{:>8}}  {{:>8}}  {{:>8}}  "
           f"{{:>9}}  {{:>8}}")
    lines = ["hbm (compiled memory analysis):", fmt.format(*header)]
    for e in mems:
        limit = e.get("hbm_limit_bytes")
        peak = e.get("peak_bytes")
        headroom = "-"
        if limit and peak is not None:
            headroom = f"{100.0 * (limit - peak) / limit:.1f}%"
        lines.append(fmt.format(
            e.get("label", "?"),
            _mb(e.get("argument_bytes")),
            _mb(e.get("output_bytes")),
            _mb(e.get("temp_bytes")),
            _mb(peak),
            _mb(limit),
            headroom,
        ))
    return lines


def _render_memory_snapshots(snaps: List[Dict[str, Any]]) -> List[str]:
    lines = ["hbm snapshots:"]
    for e in snaps:
        parts = [f"  {e.get('label', '?')}:"]
        parts.append(f"in_use={_mb(e.get('bytes_in_use'))}")
        parts.append(f"peak={_mb(e.get('peak_bytes_in_use'))}")
        parts.append(f"limit={_mb(e.get('bytes_limit'))}")
        if e.get("profile_path"):
            parts.append(f"profile={e['profile_path']}"
                         f" ({e.get('profile_bytes', '?')} B)")
        lines.append(" ".join(parts))
    return lines


def _render_profiles(profs: List[Dict[str, Any]]) -> List[str]:
    lines = ["profiler traces:"]
    for e in profs:
        if e.get("steps_profiled") is None:  # bracket capture
            span = "whole block"
        else:
            span = (f"{e['steps_profiled']} step(s) "
                    f"(warmup {e.get('warmup_steps', '?')})")
        lines.append(
            f"  {e.get('label', '?')}: {span} -> {e.get('trace_dir', '?')}"
        )
    return lines


def _render_program_audits(audits: List[Dict[str, Any]]) -> List[str]:
    """The per-program cost table from ``program_audit`` events
    (``apnea-uq audit --run-dir``: lowered-IR FLOPs, bytes accessed,
    arithmetic intensity, and the structural facts)."""
    header = ("program", "gflops", "mb_accessed", "flops/byte",
              "colls", "donated")
    name_w = max([len(header[0])]
                 + [len(str(e.get("label", "?"))) for e in audits])
    fmt = (f"{{:<{name_w}}}  {{:>10}}  {{:>11}}  {{:>10}}  {{:>5}}  "
           f"{{:>7}}")
    lines = ["program audit (lowered-IR cost):", fmt.format(*header)]
    for e in audits:
        flops = e.get("flops")
        colls = e.get("collectives")
        donated = e.get("donated_args")
        lines.append(fmt.format(
            e.get("label", "?"),
            _fmt(flops / 1e9 if flops is not None else None, 3),
            _mb(e.get("bytes_accessed")),
            _fmt(e.get("arithmetic_intensity"), 2),
            "-" if colls is None else colls,
            "-" if donated is None else donated,
        ))
    return lines


def _render_data_loads(loads: List[Dict[str, Any]]) -> List[str]:
    """The data-plane stage-start table from ``data_load`` events
    (registry artifact loads: cold npz decompress vs zero-copy store
    mmap)."""
    lines = ["data plane (artifact loads):"]
    for e in loads:
        parts = [
            f"  {e.get('key', '?')}:",
            f"{e.get('artifact_kind', '?')}"
            + (" (mmap)" if e.get("mmap") else ""),
            f"{e.get('rows', '?')} rows",
            f"{_mb(e.get('bytes'))} MiB",
            f"in {_fmt(e.get('load_s'), 3)}s",
        ]
        if e.get("rss_bytes") is not None:
            parts.append(f"rss {_mb(e['rss_bytes'])} MiB")
        lines.append(" ".join(parts))
    return lines


def _render_ingest(progress: List[Dict[str, Any]]) -> List[str]:
    """One line from the LAST ``ingest_progress`` event — the stream is
    per-recording; the tail carries the run's totals."""
    e = progress[-1]
    line = (
        f"ingest: {e.get('done', '?')}/{e.get('total', '?')} recordings"
        f" ({e.get('skipped', 0)} resumed), {e.get('rows', '?')} rows"
        f" at {_fmt(e.get('rows_per_s'), 1)} rows/s,"
        f" {_mb(e.get('bytes_written'))} MiB written"
    )
    if e.get("rss_bytes") is not None:
        line += f", peak rss {_mb(e['rss_bytes'])} MiB"
    return [line]


def _render_quality(quals: List[Dict[str, Any]]) -> List[str]:
    """The model-quality table from ``quality_metrics`` events
    (telemetry/quality.py): calibration scalars + the patient-rollup
    floor per eval label."""
    lines = ["quality (calibration + uncertainty):"]
    for e in quals:
        line = (
            f"  {e.get('label', '?')}: ece {_fmt(e.get('ece'), 4)}"
            f"  mce {_fmt(e.get('mce'), 4)}"
            f"  brier {_fmt(e.get('brier'), 4)}"
            f"  ({e.get('n_windows', '?')} windows"
            + (", fused" if e.get("fused") else "") + ")"
        )
        unc = e.get("uncertainty") or {}
        ent = unc.get("total_pred_entropy") or {}
        if ent.get("p50") is not None:
            line += (f"  entropy p50 {_fmt(ent.get('p50'), 4)}"
                     f" p95 {_fmt(ent.get('p95'), 4)}")
        pats = e.get("patients")
        if pats:
            line += (f"  [{pats.get('n_patients', '?')} patients, "
                     f"min acc {_fmt(pats.get('accuracy_min'), 3)}]")
        lines.append(line)
    return lines


def _render_drift(drifts: List[Dict[str, Any]]) -> List[str]:
    """The input-drift table from ``drift_fingerprint`` events: per-set
    PSI/KS against the frozen ``quality_baseline`` fingerprint."""
    lines = ["drift (vs frozen quality_baseline):"]
    for e in drifts:
        lines.append(
            f"  {e.get('label', '?')}: max_psi {_fmt(e.get('max_psi'), 4)}"
            f"  max_ks {_fmt(e.get('max_ks'), 4)}"
            f"  mean-shift {_fmt(e.get('max_mean_shift'), 4)}"
            f"  (worst {e.get('worst_channel', '?')}, "
            f"{e.get('rows', '?')} rows vs "
            f"{e.get('baseline_rows', '?')} baseline)"
        )
    return lines


def _render_quality_gates(gates: List[Dict[str, Any]]) -> List[str]:
    """The ``quality_gate`` audit trail `apnea-uq quality check`
    appends to the run it judged."""
    lines = []
    for e in gates:
        verdict = "PASSED" if e.get("passed") else "FAILED"
        line = (f"quality gate: {verdict} ({e.get('checks', '?')} "
                f"check(s))")
        if e.get("baseline"):
            line += f" vs baseline {e['baseline']}"
        lines.append(line)
        for failure in e.get("failures") or []:
            lines.append(f"  FAILED: {failure}")
    return lines


def _render_serve_slo(slos: List[Dict[str, Any]]) -> List[str]:
    """The serving SLO trail from ``serve_slo`` events (serving/slo.py):
    snapshots are cumulative, so the LAST line — the session summary
    `telemetry compare` gates — is the one that matters; earlier lines
    show how the SLO evolved as load arrived."""
    lines = ["serve slo (cumulative snapshots; last = session summary):"]
    for e in slos:
        line = (
            f"  {e.get('requests', '?')} req / {e.get('windows', '?')} win"
            f" in {e.get('batches', '?')} batch(es):"
            f" p50 {_fmt(e.get('p50_ms'), 1)}ms"
            f" p99 {_fmt(e.get('p99_ms'), 1)}ms"
            f"  {_fmt(e.get('windows_per_s'), 1)} win/s"
            f"  wait {_fmt(e.get('queue_wait_mean_s'), 4)}s"
            f"  pad {_fmt(e.get('pad_waste'), 3)}"
        )
        if e.get("patients") is not None:
            line += f"  [{e['patients']} patients]"
        if e.get("final"):
            line += "  (final)"
        lines.append(line)
    # Per-bucket breakdown of the final snapshot (ISSUE 17 satellite):
    # one row per ladder bucket, so a saturated 256-bucket is visible
    # next to a healthy global p95.
    buckets = (slos[-1].get("buckets") or {}) if slos else {}
    if buckets:
        lines.append("  per-bucket (final snapshot):")
        for size in sorted(buckets, key=lambda s: int(s)):
            b = buckets[size]
            lines.append(
                f"    b{size}: {b.get('batches', '?')} batch(es) / "
                f"{b.get('windows', '?')} win"
                f"  p50 {_fmt(b.get('p50_ms'), 1)}ms"
                f"  p95 {_fmt(b.get('p95_ms'), 1)}ms"
                f"  p99 {_fmt(b.get('p99_ms'), 1)}ms"
                f"  pad {_fmt(b.get('pad_waste'), 3)}"
            )
    return lines


def _render_serve_drift(drifts: List[Dict[str, Any]]) -> List[str]:
    """The online drift trail from ``serve_drift`` events
    (serving/drift.py): one line per re-score, per tenant, against the
    frozen quality_baseline — the LAST line per tenant is the verdict
    `apnea-uq quality check` gates on a serve run dir."""
    lines = ["serve drift (online, vs frozen quality_baseline):"]
    for e in drifts:
        line = (
            f"  {e.get('tenant', '?')}: {str(e.get('verdict', '?')).upper()}"
            f"  max_psi {_fmt(e.get('max_psi'), 4)}"
            f"  max_ks {_fmt(e.get('max_ks'), 4)}"
            f"  mean-shift {_fmt(e.get('max_mean_shift'), 4)}"
            f"  (worst {e.get('worst_channel', '?')}, "
            f"{e.get('windows', '?')} windows)"
        )
        if e.get("final"):
            line += "  (final)"
        lines.append(line)
    return lines


def _render_serve_trace(traces: List[Dict[str, Any]]) -> List[str]:
    """The sampled span waterfalls from ``serve_trace`` events: one
    enqueue -> coalesce -> dispatch -> D2H -> respond decomposition per
    traced request (queue_s + service_s = the SLO latency, exactly)."""
    lines = ["serve traces (sampled request waterfalls):"]
    for e in traces:
        line = (
            f"  {e.get('span_id', '?')} [{e.get('request_id', '?')}]"
            f" {e.get('windows', '?')} win / {e.get('batches', '?')}"
            f" batch(es) b{e.get('bucket', '?')}"
            f" pad {e.get('pad_rows', '?')}:"
            f" queue {_fmt(e.get('queue_s'), 4)}s"
            f" -> dispatch {_fmt(e.get('dispatch_s'), 4)}s"
            f" -> d2h {_fmt(e.get('d2h_s'), 4)}s"
            f" -> respond {_fmt(e.get('respond_s'), 4)}s"
            f"  (latency {_fmt(e.get('latency_s'), 4)}s,"
            f" {e.get('label', '?')})"
        )
        reasons = e.get("sampled_for")
        if reasons:
            line += f"  [{','.join(str(r) for r in reasons)}]"
        if e.get("exemplar"):
            line += "  EXEMPLAR"
        lines.append(line)
    return lines


def _render_bench_blocks(blocks: List[Dict[str, Any]]) -> List[str]:
    """The per-block status trail from ``bench_block`` events (bench.py's
    isolated block runner): one line per block with its outcome, so a
    partially-failed capture's shape is readable without re-parsing the
    payload JSON."""
    lines = ["bench blocks:"]
    for e in blocks:
        line = f"  {e.get('name', '?')}: {e.get('status', '?')}"
        if e.get("seconds") is not None:
            line += f" in {e['seconds']:g}s"
        if e.get("reason"):
            line += f" ({e['reason']})"
        if e.get("error_tail"):
            tail = e["error_tail"].strip().splitlines()
            if tail:
                line += f" — {tail[-1][:120]}"
        lines.append(line)
    return lines


def _compile_aggregate(comps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Roll-up of a run's compile_event stream: acquisition count, hit
    ratio (store/cache vs fresh jit compiles), and the total
    lower+compile seconds the run spent — the two numbers `telemetry
    compare` gates cold-start regressions on."""
    hits = sum(1 for e in comps if e.get("hit"))
    total = sum((e.get("lower_s") or 0.0) + (e.get("compile_s") or 0.0)
                for e in comps)
    return {
        "count": len(comps),
        "hits": hits,
        "hit_ratio": round(hits / len(comps), 4) if comps else None,
        "total_s": round(total, 6),
    }


def _render_compile(comps: List[Dict[str, Any]]) -> List[str]:
    agg = _compile_aggregate(comps)
    lines = [
        f"compile: {agg['count']} acquisition(s), hit ratio "
        f"{_fmt(agg['hit_ratio'], 2)}, total {agg['total_s']:.3f}s"
    ]
    for e in comps:
        lines.append(
            f"  {e.get('label', '?')}: {e.get('source', '?')}"
            f" lower {_fmt(e.get('lower_s'), 3)}s"
            f" compile {_fmt(e.get('compile_s'), 3)}s"
        )
    return lines


# The field projections the renderer's capture sections AND the --json
# document share — one list per event kind, so a field added to one
# output cannot silently miss the other.
_MEMORY_PROFILE_FIELDS = (
    "label", "argument_bytes", "output_bytes", "temp_bytes",
    "alias_bytes", "peak_bytes", "hbm_limit_bytes", "headroom_bytes",
    "device_kind")
_MEMORY_SNAPSHOT_FIELDS = (
    "label", "bytes_in_use", "peak_bytes_in_use", "bytes_limit",
    "profile_path", "profile_bytes")
_PROFILE_FIELDS = (
    "label", "trace_dir", "mode", "steps_profiled", "warmup_steps")
_COMPILE_EVENT_FIELDS = (
    "label", "source", "hit", "lower_s", "compile_s",
    "backend_compiles", "persistent_cache_hits",
    "persistent_cache_misses")
_PROGRAM_AUDIT_FIELDS = (
    "label", "group", "flops", "bytes_accessed",
    "arithmetic_intensity", "collectives", "donated_args",
    "aliased_outputs", "const_bytes", "peak_bytes")
_DATA_LOAD_FIELDS = (
    "key", "artifact_kind", "mmap", "rows", "bytes", "load_s",
    "rss_bytes")
_BENCH_BLOCK_FIELDS = (
    "name", "status", "seconds", "error_tail", "reason")
_INGEST_PROGRESS_FIELDS = (
    "done", "total", "skipped", "rows", "rows_per_s", "bytes_written",
    "rss_bytes")
_QUALITY_METRICS_FIELDS = (
    "label", "n_windows", "n_passes", "fused", "num_bins", "ece", "mce",
    "brier", "uncertainty", "patients")
_DRIFT_FINGERPRINT_FIELDS = (
    "label", "rows", "baseline_rows", "max_psi", "max_ks",
    "max_mean_shift", "worst_channel", "channels")
_QUALITY_GATE_FIELDS = (
    "passed", "checks", "failures", "baseline", "threshold_pct",
    "psi_threshold", "ks_threshold")
_SERVE_SLO_FIELDS = (
    "replica_id", "requests", "windows", "batches", "p50_ms", "p95_ms",
    "p99_ms", "windows_per_s", "queue_wait_mean_s", "pad_waste",
    "device_s", "interval_s", "final", "patients", "buckets", "trace")
_SERVE_DRIFT_FIELDS = (
    "replica_id", "tenant", "verdict", "windows", "max_psi", "max_ks",
    "max_mean_shift", "worst_channel", "warn_psi", "drift_psi",
    "warn_ks", "drift_ks", "final")
_SERVE_TRACE_FIELDS = (
    "replica_id", "span_id", "trace_id", "request_id", "windows",
    "batches", "bucket", "pad_rows", "label", "queue_s", "service_s",
    "dispatch_s", "device_s", "d2h_s", "respond_s", "latency_s",
    "sampled_for", "exemplar", "children")


def _section(events: List[Dict[str, Any]], kind: str,
             fields: tuple) -> List[Dict[str, Any]]:
    return [{k: e.get(k) for k in fields}
            for e in events if e.get("kind") == kind]


# Merging appended runs would double-count stage tables and epoch
# trajectories — both read paths keep only the latest run (runlog's
# shared boundary rule).
_latest_run = latest_run


def summarize_events(run_dir: str,
                     events: List[Dict[str, Any]]) -> str:
    events, earlier_runs = _latest_run(events)
    started = next((e for e in events if e.get("kind") == "run_started"), None)
    finished = [e for e in events if e.get("kind") == "run_finished"]
    lines = [f"run: {os.path.basename(os.path.normpath(run_dir))}"]

    topo = (started or {}).get("topology", {})
    lines.append(
        f"started: {_iso((started or {}).get('ts'))}"
        f"  stage: {(started or {}).get('stage', 'unknown')}"
        f"  platform: {topo.get('platform', 'unknown')}"
        f"  devices: {topo.get('device_count', '-')}"
    )
    cfg = (started or {}).get("config_hash")
    lines.append(
        f"config: {cfg[:12] if cfg else '-'}"
        f"  schema: v{(started or {}).get('schema_version', '?')}"
        f"  events: {len(events)}"
        f"  status: {finished[-1].get('status') if finished else 'unknown'}"
    )
    if earlier_runs:
        lines.append(
            f"(latest of {earlier_runs + 1} runs appended to this log; "
            f"earlier runs not shown)"
        )

    rows = _stage_rows(events)
    if rows:
        lines.append("")
        lines.extend(_render_stage_table(rows))

    epochs = [e for e in events if e.get("kind") == "epoch"]
    if epochs:
        loss = [float(e["loss"]) for e in epochs if "loss" in e]
        parts = [f"epochs: {len(epochs)}"]
        if loss:
            parts.append(f"loss {_first_last(loss)}")
        val = [float(e["val_loss"]) for e in epochs if "val_loss" in e]
        if val:
            parts.append(f"val_loss {_first_last(val)}")
        lines.append("")
        lines.append("  ".join(parts))

    ens_epochs = [e for e in events if e.get("kind") == "ensemble_epoch"]
    fits = [e for e in events if e.get("kind") == "ensemble_fit"]
    if ens_epochs or fits:
        lines.append("")
        if ens_epochs:
            lines.append(f"ensemble epochs: {len(ens_epochs)}")
        for fit in fits:
            lines.append(
                f"ensemble fit: {fit.get('num_members')} members"
                f" (requested {fit.get('num_requested')},"
                f" promoted {fit.get('promoted_members')})"
                f"  lockstep epochs {fit.get('lockstep_epochs')}"
                f"  wasted member-epochs {fit.get('wasted_member_epochs')}"
            )

    evals = [e for e in events if e.get("kind") == "eval_predict"]
    if evals:
        lines.append("")
        lines.append("evals:")
        for e in evals:
            wps = e.get("windows_per_s")
            line = (
                f"  {e.get('label')}: {e.get('n_passes')}x"
                f"{e.get('n_windows')} windows in "
                f"{_fmt(e.get('predict_s'), 3)}s"
                f" ({_fmt(wps, 1)} windows/s)"
            )
            # Runs predating the fused reduction carry neither field;
            # render their lines unchanged.
            if e.get("fused") is not None:
                d2h = e.get("d2h_bytes")
                line += (f" [{'fused' if e['fused'] else 'full-probs'}"
                         f", d2h {_mb(d2h)} MiB]")
            lines.append(line)

    quals = _section(events, "quality_metrics", _QUALITY_METRICS_FIELDS)
    if quals:
        lines.append("")
        lines.extend(_render_quality(quals))

    drifts = _section(events, "drift_fingerprint",
                      _DRIFT_FINGERPRINT_FIELDS)
    if drifts:
        lines.append("")
        lines.extend(_render_drift(drifts))

    gates = _section(events, "quality_gate", _QUALITY_GATE_FIELDS)
    if gates:
        lines.append("")
        lines.extend(_render_quality_gates(gates))

    mems = _section(events, "memory_profile", _MEMORY_PROFILE_FIELDS)
    if mems:
        lines.append("")
        lines.extend(_render_memory_table(mems))

    snaps = _section(events, "memory_snapshot", _MEMORY_SNAPSHOT_FIELDS)
    if snaps:
        lines.append("")
        lines.extend(_render_memory_snapshots(snaps))

    profs = _section(events, "profile_captured", _PROFILE_FIELDS)
    if profs:
        lines.append("")
        lines.extend(_render_profiles(profs))

    comps = _section(events, "compile_event", _COMPILE_EVENT_FIELDS)
    if comps:
        lines.append("")
        lines.extend(_render_compile(comps))

    audits = _section(events, "program_audit", _PROGRAM_AUDIT_FIELDS)
    if audits:
        lines.append("")
        lines.extend(_render_program_audits(audits))

    ingest = _section(events, "ingest_progress", _INGEST_PROGRESS_FIELDS)
    if ingest:
        lines.append("")
        lines.extend(_render_ingest(ingest))

    loads = _section(events, "data_load", _DATA_LOAD_FIELDS)
    if loads:
        lines.append("")
        lines.extend(_render_data_loads(loads))

    slos = _section(events, "serve_slo", _SERVE_SLO_FIELDS)
    if slos:
        lines.append("")
        lines.extend(_render_serve_slo(slos))

    serve_drifts = _section(events, "serve_drift", _SERVE_DRIFT_FIELDS)
    if serve_drifts:
        lines.append("")
        lines.extend(_render_serve_drift(serve_drifts))

    traces = _section(events, "serve_trace", _SERVE_TRACE_FIELDS)
    if traces:
        lines.append("")
        lines.extend(_render_serve_trace(traces))

    bench_blocks = _section(events, "bench_block", _BENCH_BLOCK_FIELDS)
    if bench_blocks:
        lines.append("")
        lines.extend(_render_bench_blocks(bench_blocks))

    errors = [e for e in events if e.get("kind") == "error"]
    lines.append("")
    if errors:
        lines.append(f"errors: {len(errors)}")
        for e in errors:
            lines.append(f"  [{e.get('where', '?')}] {e.get('error', '')}")
    else:
        lines.append("errors: none")
    return "\n".join(lines)


def summarize_run(run_dir: str) -> str:
    """Human-readable summary of ``<run_dir>/events.jsonl``."""
    events = read_events(run_dir)
    if not events:
        raise FileNotFoundError(
            f"no {EVENTS_FILENAME} events under {run_dir!r} — "
            f"is this a telemetry run directory?"
        )
    return summarize_events(run_dir, events)


def summarize_data(run_dir: str) -> Dict[str, Any]:
    """Machine-readable summary (``telemetry summarize --json``): the
    same fields the rendered table derives, as one JSON-able document —
    latest run of an appended log, like the text renderer."""
    all_events = read_events(run_dir)
    if not all_events:
        raise FileNotFoundError(
            f"no {EVENTS_FILENAME} events under {run_dir!r} — "
            f"is this a telemetry run directory?"
        )
    events, earlier_runs = _latest_run(all_events)
    return _run_data(run_dir, events, earlier_runs, earlier_runs + 1)


def _run_data(run_dir: str, events: List[Dict[str, Any]],
              earlier_runs: int, run_count: int) -> Dict[str, Any]:
    """One run's summary document (the body of :func:`summarize_data`,
    reusable per run for ``--all-runs``)."""
    started = next((e for e in events if e.get("kind") == "run_started"), None)
    finished = [e for e in events if e.get("kind") == "run_finished"]
    topo = (started or {}).get("topology", {})

    rows = _stage_rows(events)
    for r in rows:
        # The derived column the table renders; None when undefined.
        r["items_per_s"] = (
            r["n_items"] / r["device_s"]
            if r["n_items"] and r["device_s"] > 0 else None
        )

    epochs = [e for e in events if e.get("kind") == "epoch"]
    loss = [float(e["loss"]) for e in epochs if "loss" in e]
    val = [float(e["val_loss"]) for e in epochs if "val_loss" in e]

    def section(kind: str, fields: tuple) -> List[Dict[str, Any]]:
        return _section(events, kind, fields)

    compile_events = section("compile_event", _COMPILE_EVENT_FIELDS)
    return {
        "run": os.path.basename(os.path.normpath(run_dir)),
        "started_ts": (started or {}).get("ts"),
        "stage": (started or {}).get("stage"),
        "platform": topo.get("platform"),
        "devices": topo.get("device_count"),
        "config_hash": (started or {}).get("config_hash"),
        "schema_version": (started or {}).get("schema_version"),
        "events": len(events),
        "status": finished[-1].get("status") if finished else None,
        "earlier_runs": earlier_runs,
        "run_count": run_count,
        "stages": rows,
        "epochs": {
            "count": len(epochs),
            "loss_first": loss[0] if loss else None,
            "loss_last": loss[-1] if loss else None,
            "val_loss_first": val[0] if val else None,
            "val_loss_last": val[-1] if val else None,
        },
        "ensemble_fits": section("ensemble_fit", (
            "num_members", "num_requested", "promoted_members",
            "lockstep_epochs", "wasted_member_epochs")),
        "evals": section("eval_predict", (
            "label", "method", "n_passes", "n_windows", "predict_s",
            "windows_per_s", "fused", "d2h_bytes")),
        "quality_metrics": section("quality_metrics",
                                   _QUALITY_METRICS_FIELDS),
        "drift_fingerprints": section("drift_fingerprint",
                                      _DRIFT_FINGERPRINT_FIELDS),
        "quality_gates": section("quality_gate", _QUALITY_GATE_FIELDS),
        "memory_profiles": section("memory_profile",
                                   _MEMORY_PROFILE_FIELDS),
        "memory_snapshots": section("memory_snapshot",
                                    _MEMORY_SNAPSHOT_FIELDS),
        "profiles": section("profile_captured", _PROFILE_FIELDS),
        "program_audits": section("program_audit", _PROGRAM_AUDIT_FIELDS),
        "compile_events": compile_events,
        "compile": _compile_aggregate(compile_events),
        "data_loads": section("data_load", _DATA_LOAD_FIELDS),
        "serve_slos": section("serve_slo", _SERVE_SLO_FIELDS),
        "serve_drifts": section("serve_drift", _SERVE_DRIFT_FIELDS),
        "serve_traces": section("serve_trace", _SERVE_TRACE_FIELDS),
        "bench_blocks": section("bench_block", _BENCH_BLOCK_FIELDS),
        "ingest_progress": section("ingest_progress",
                                   _INGEST_PROGRESS_FIELDS),
        "errors": section("error", ("where", "error")),
    }


def split_runs(events: List[Dict[str, Any]]) -> List[List[Dict[str, Any]]]:
    """Split an appended multi-run log at its ``run_started`` boundaries
    into per-run event lists, oldest first — :func:`latest_run`'s
    every-run sibling (``--all-runs``).  Events before the first
    ``run_started`` (an append-only gate verdict on a torn log, say)
    stay attached to the first run."""
    starts = [i for i, e in enumerate(events)
              if e.get("kind") == "run_started"]
    if len(starts) <= 1:
        return [events]
    bounds = [0] + starts[1:] + [len(events)]
    return [events[bounds[i]:bounds[i + 1]]
            for i in range(len(bounds) - 1)]


def summarize_all_runs_text(run_dir: str) -> str:
    """Every run of an appended log rendered back to back, oldest first
    — so a replica restart (a second ``run_started`` in the same dir)
    is visible instead of silently hiding all but the latest run."""
    all_events = read_events(run_dir)
    if not all_events:
        raise FileNotFoundError(
            f"no {EVENTS_FILENAME} events under {run_dir!r} — "
            f"is this a telemetry run directory?"
        )
    runs = split_runs(all_events)
    blocks = []
    for i, events in enumerate(runs):
        blocks.append(f"=== run {i + 1} of {len(runs)} ===")
        blocks.append(summarize_events(run_dir, events))
    return "\n".join(blocks)


def summarize_all_runs_data(run_dir: str) -> Dict[str, Any]:
    """Machine-readable ``--all-runs --json``: the run count plus one
    per-run summary document (oldest first; each shaped exactly like
    :func:`summarize_data`'s single-run payload)."""
    all_events = read_events(run_dir)
    if not all_events:
        raise FileNotFoundError(
            f"no {EVENTS_FILENAME} events under {run_dir!r} — "
            f"is this a telemetry run directory?"
        )
    runs = split_runs(all_events)
    return {
        "run": os.path.basename(os.path.normpath(run_dir)),
        "run_count": len(runs),
        "runs": [_run_data(run_dir, events, i, len(runs))
                 for i, events in enumerate(runs)],
    }
