"""Render a run's JSONL event log as a human-readable summary.

``apnea-uq telemetry summarize <run-dir>`` — the read side of the
telemetry layer: per-stage wall/device time, step counts, throughput and
recompile counters, epoch trajectories, eval predict lines, and errors,
all derived purely from ``events.jsonl`` (no JAX import, instant)."""

from __future__ import annotations

import datetime
import os
from typing import Any, Dict, List, Optional

from apnea_uq_tpu.telemetry.runlog import EVENTS_FILENAME, read_events

_NO_STAGE = "(no stage)"


def _iso(ts: Optional[float]) -> str:
    if ts is None:
        return "unknown"
    dt = datetime.datetime.fromtimestamp(float(ts), tz=datetime.timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%SZ")


def _fmt(value: Optional[float], decimals: int) -> str:
    return "-" if value is None else f"{value:.{decimals}f}"


def _stage_rows(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """One row per stage, in first-appearance order, merging stage_end
    wall-clock with the ``step`` events emitted inside the stage."""
    order: List[str] = []
    rows: Dict[str, Dict[str, Any]] = {}

    def row(name: str) -> Dict[str, Any]:
        if name not in rows:
            order.append(name)
            rows[name] = {
                "stage": name, "wall_s": None, "steps": 0, "device_s": 0.0,
                "dispatch_s": 0.0, "retraces": 0, "backend_compiles": 0,
                "n_items": 0,
            }
        return rows[name]

    for e in events:
        kind = e.get("kind")
        if kind == "stage_start":
            row(e.get("stage", _NO_STAGE))
        elif kind == "stage_end":
            r = row(e.get("stage", _NO_STAGE))
            r["wall_s"] = (r["wall_s"] or 0.0) + float(e.get("wall_s", 0.0))
        elif kind == "step":
            r = row(e.get("stage", _NO_STAGE))
            r["steps"] += 1
            r["device_s"] += float(e.get("device_s", 0.0))
            r["dispatch_s"] += float(e.get("dispatch_s", 0.0))
            r["retraces"] += int(e.get("retraces", 0))
            r["backend_compiles"] += int(e.get("backend_compiles", 0))
            r["n_items"] += int(e.get("n_items", 0) or 0)
    return [rows[name] for name in order]


def _render_stage_table(rows: List[Dict[str, Any]]) -> List[str]:
    header = ("stage", "wall_s", "steps", "device_s", "dispatch_s",
              "retraces", "compiles", "items/s")
    name_w = max([len(header[0])] + [len(r["stage"]) for r in rows])
    fmt = (f"{{:<{name_w}}}  {{:>9}}  {{:>5}}  {{:>9}}  {{:>10}}  "
           f"{{:>8}}  {{:>8}}  {{:>10}}")
    lines = [fmt.format(*header)]
    for r in rows:
        items_per_s = None
        if r["n_items"] and r["device_s"] > 0:
            items_per_s = r["n_items"] / r["device_s"]
        lines.append(fmt.format(
            r["stage"],
            _fmt(r["wall_s"], 3),
            r["steps"] if r["steps"] else "-",
            _fmt(r["device_s"] if r["steps"] else None, 3),
            _fmt(r["dispatch_s"] if r["steps"] else None, 3),
            r["retraces"] if r["steps"] else "-",
            r["backend_compiles"] if r["steps"] else "-",
            _fmt(items_per_s, 1),
        ))
    return lines


def _first_last(values: List[float]) -> str:
    return f"{values[0]:.4f} -> {values[-1]:.4f}"


def _latest_run(events: List[Dict[str, Any]]):
    """Split an appended multi-run log (bench.py reuses BENCH_RUN_DIR, so
    events.jsonl can hold several runs back-to-back) at its run_started
    boundaries; returns (latest run's events, count of earlier runs).
    Merging runs would double-count stage tables and epoch trajectories."""
    starts = [i for i, e in enumerate(events)
              if e.get("kind") == "run_started"]
    if len(starts) <= 1:
        return events, 0
    return events[starts[-1]:], len(starts) - 1


def summarize_events(run_dir: str,
                     events: List[Dict[str, Any]]) -> str:
    events, earlier_runs = _latest_run(events)
    started = next((e for e in events if e.get("kind") == "run_started"), None)
    finished = [e for e in events if e.get("kind") == "run_finished"]
    lines = [f"run: {os.path.basename(os.path.normpath(run_dir))}"]

    topo = (started or {}).get("topology", {})
    lines.append(
        f"started: {_iso((started or {}).get('ts'))}"
        f"  stage: {(started or {}).get('stage', 'unknown')}"
        f"  platform: {topo.get('platform', 'unknown')}"
        f"  devices: {topo.get('device_count', '-')}"
    )
    cfg = (started or {}).get("config_hash")
    lines.append(
        f"config: {cfg[:12] if cfg else '-'}"
        f"  schema: v{(started or {}).get('schema_version', '?')}"
        f"  events: {len(events)}"
        f"  status: {finished[-1].get('status') if finished else 'unknown'}"
    )
    if earlier_runs:
        lines.append(
            f"(latest of {earlier_runs + 1} runs appended to this log; "
            f"earlier runs not shown)"
        )

    rows = _stage_rows(events)
    if rows:
        lines.append("")
        lines.extend(_render_stage_table(rows))

    epochs = [e for e in events if e.get("kind") == "epoch"]
    if epochs:
        loss = [float(e["loss"]) for e in epochs if "loss" in e]
        parts = [f"epochs: {len(epochs)}"]
        if loss:
            parts.append(f"loss {_first_last(loss)}")
        val = [float(e["val_loss"]) for e in epochs if "val_loss" in e]
        if val:
            parts.append(f"val_loss {_first_last(val)}")
        lines.append("")
        lines.append("  ".join(parts))

    ens_epochs = [e for e in events if e.get("kind") == "ensemble_epoch"]
    fits = [e for e in events if e.get("kind") == "ensemble_fit"]
    if ens_epochs or fits:
        lines.append("")
        if ens_epochs:
            lines.append(f"ensemble epochs: {len(ens_epochs)}")
        for fit in fits:
            lines.append(
                f"ensemble fit: {fit.get('num_members')} members"
                f" (requested {fit.get('num_requested')},"
                f" promoted {fit.get('promoted_members')})"
                f"  lockstep epochs {fit.get('lockstep_epochs')}"
                f"  wasted member-epochs {fit.get('wasted_member_epochs')}"
            )

    evals = [e for e in events if e.get("kind") == "eval_predict"]
    if evals:
        lines.append("")
        lines.append("evals:")
        for e in evals:
            wps = e.get("windows_per_s")
            lines.append(
                f"  {e.get('label')}: {e.get('n_passes')}x"
                f"{e.get('n_windows')} windows in "
                f"{_fmt(e.get('predict_s'), 3)}s"
                f" ({_fmt(wps, 1)} windows/s)"
            )

    errors = [e for e in events if e.get("kind") == "error"]
    lines.append("")
    if errors:
        lines.append(f"errors: {len(errors)}")
        for e in errors:
            lines.append(f"  [{e.get('where', '?')}] {e.get('error', '')}")
    else:
        lines.append("errors: none")
    return "\n".join(lines)


def summarize_run(run_dir: str) -> str:
    """Human-readable summary of ``<run_dir>/events.jsonl``."""
    events = read_events(run_dir)
    if not events:
        raise FileNotFoundError(
            f"no {EVENTS_FILENAME} events under {run_dir!r} — "
            f"is this a telemetry run directory?"
        )
    return summarize_events(run_dir, events)
