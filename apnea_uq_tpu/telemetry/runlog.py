"""Run-scoped structured event log: append-only JSONL per run directory.

Run-directory layout (see docs/OBSERVABILITY.md for the full schema):

    <run_dir>/events.jsonl   one JSON object per line, append-only
    <run_dir>/config.json    the ExperimentConfig the run started with
                             (written by start_run when a config is given)

Every event carries the envelope ``{"seq", "ts", "kind"}`` plus a
``"stage"`` field when emitted inside a :meth:`RunLog.stage` block.  The
file is flushed per event, so a killed run keeps everything recorded up
to the kill — the same crash-survivability contract bench.py's progress
file established for metric blocks.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import time
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1
EVENTS_FILENAME = "events.jsonl"

# Stack of active run logs (innermost last); log() mirrors lines into the
# top entry and nested helpers (trainer, drivers) can attach their events
# to the run the CLI stage opened without threading the object everywhere.
_ACTIVE: List["RunLog"] = []


def current_run() -> Optional["RunLog"]:
    """The innermost active run log, or None outside any run."""
    return _ACTIVE[-1] if _ACTIVE else None


def replica_id() -> str:
    """This process's serving-replica identity, stamped on every serve
    event so fleet rollups can attribute latency to the process that
    produced it.  ``APNEA_UQ_REPLICA_ID`` overrides (the capacity
    harness names its subprocess replicas); default ``<hostname>-<pid>``
    — unique per process on a host and stable for the process lifetime.
    Read per call so tests (and forked replicas) see env changes."""
    explicit = os.environ.get("APNEA_UQ_REPLICA_ID")
    if explicit:
        return explicit
    import socket

    return f"{socket.gethostname()}-{os.getpid()}"


def config_hash(config: Any) -> str:
    """sha256 of the canonical JSON serialization of a config dataclass —
    two runs share a hash iff they ran the exact same configuration."""
    from apnea_uq_tpu.config import _to_jsonable

    payload = json.dumps(_to_jsonable(config), sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()


def device_topology() -> Dict[str, Any]:
    """Best-effort device/mesh topology for the run_started event; never
    raises (telemetry must work before — or without — a usable backend)."""
    try:
        import jax

        # apnea-lint: disable=single-host-device-enumeration -- run_started records the GLOBAL topology (device/process counts) by design; best-effort and guarded
        devices = jax.devices()
        return {
            "platform": devices[0].platform if devices else "unknown",
            "device_kind": devices[0].device_kind if devices else "unknown",
            "device_count": jax.device_count(),
            "local_device_count": jax.local_device_count(),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
        }
    except Exception as e:  # noqa: BLE001 - backend init can fail freely
        return {"platform": "unavailable", "error": f"{type(e).__name__}: {e}"}


class RunLog:
    """Append-only JSONL event writer for one run directory.

    ``disabled=True`` yields a no-op instance (used on non-primary hosts of
    a multi-process run, where every process would otherwise race on the
    same file); the API surface is identical so callers never branch.
    """

    def __init__(self, run_dir: str, *, disabled: bool = False,
                 _clock=time.time):
        self.run_dir = run_dir
        self.disabled = disabled
        self._clock = _clock
        self._seq = 0
        self._stages: List[str] = []
        self._last_exc: Optional[BaseException] = None
        self._last_error_record: Optional[Dict[str, Any]] = None
        self._fh = None
        if not disabled:
            os.makedirs(run_dir, exist_ok=True)
            self._fh = open(os.path.join(run_dir, EVENTS_FILENAME), "a")

    # -- core ------------------------------------------------------------

    def event(self, kind: str, **fields: Any) -> Dict[str, Any]:
        """Append one event; returns the full record (envelope included)."""
        record: Dict[str, Any] = {
            "seq": self._seq, "ts": round(float(self._clock()), 6),
            "kind": kind,
        }
        if self._stages and "stage" not in fields:
            record["stage"] = self._stages[-1]
        record.update(fields)
        self._seq += 1
        if self._fh is not None:
            self._fh.write(json.dumps(record, sort_keys=False) + "\n")
            self._fh.flush()
        return record

    def run_started(self, *, stage: Optional[str] = None, config: Any = None,
                    argv: Optional[List[str]] = None) -> Dict[str, Any]:
        fields: Dict[str, Any] = {
            "schema_version": SCHEMA_VERSION,
            "topology": device_topology(),
        }
        if stage is not None:
            fields["stage"] = stage
        if config is not None:
            fields["config_hash"] = config_hash(config)
        if argv is not None:
            fields["argv"] = list(argv)
        return self.event("run_started", **fields)

    @contextlib.contextmanager
    def stage(self, name: str, *, snapshot_memory: bool = False,
              **fields: Any):
        """Bracket a pipeline stage with stage_start/stage_end events;
        events emitted inside inherit ``stage=name``.  An escaping
        exception is recorded (status='error' + an ``error`` event) and
        re-raised.  ``snapshot_memory=True`` additionally records a
        device-memory snapshot (``memory_snapshot`` event + pprof dump,
        telemetry/memory.py) at entry and exit — including the error
        exit, where an OOM unwind is exactly when you want the numbers."""
        self.event("stage_start", stage=name, **fields)
        self._stages.append(name)
        if snapshot_memory:
            self._snapshot_memory(f"{name}.start")
        t0 = time.perf_counter()
        try:
            yield self
        except BaseException as e:
            wall = time.perf_counter() - t0
            if snapshot_memory:
                self._snapshot_memory(f"{name}.error")
            self._stages.pop()
            self.error(name, e)
            self.event("stage_end", stage=name, wall_s=round(wall, 6),
                       status="error")
            raise
        else:
            wall = time.perf_counter() - t0
            if snapshot_memory:
                self._snapshot_memory(f"{name}.end")
            self._stages.pop()
            self.event("stage_end", stage=name, wall_s=round(wall, 6),
                       status="ok")

    def _snapshot_memory(self, label: str) -> None:
        """Lazy, best-effort device-memory snapshot — the import keeps
        this module (and the jax-free read side) free of jax until a
        caller opts in."""
        if self.disabled:
            return
        try:
            from apnea_uq_tpu.telemetry import memory as memory_mod

            memory_mod.snapshot_device_memory(self, label)
        except Exception:  # noqa: BLE001 - telemetry must never break a run
            pass

    def error(self, where: str, exc: BaseException) -> Dict[str, Any]:
        # One exception, one error event: a failure inside a stage block
        # unwinds through stage() AND the run's __exit__ (and bench.py's
        # own handler), each of which reports it here — dedupe by object
        # identity so `summarize` counts failures, not unwind frames.
        if exc is self._last_exc and self._last_error_record is not None:
            return self._last_error_record
        self._last_exc = exc
        self._last_error_record = self.event(
            "error", where=where, error=f"{type(exc).__name__}: {exc}")
        return self._last_error_record

    # -- lifecycle --------------------------------------------------------

    def close(self, status: str = "ok") -> None:
        if self._fh is not None:
            self.event("run_finished", status=status)
            self._fh.close()
            self._fh = None
        while self in _ACTIVE:
            _ACTIVE.remove(self)

    def __enter__(self) -> "RunLog":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None and self._fh is not None:
            self.error("run", exc)
        self.close(status="ok" if exc_type is None else "error")


def default_run_dir(root: str, stage: str) -> str:
    """``<root>/runs/<stage>-<utc stamp>-<pid>`` — unique per invocation,
    grouped under the artifact root so runs live next to their outputs."""
    stamp = time.strftime("%Y%m%d-%H%M%S", time.gmtime())
    return os.path.join(root, "runs", f"{stage}-{stamp}-{os.getpid()}")


def start_run(run_dir: str, *, stage: Optional[str] = None,
              config: Any = None, argv: Optional[List[str]] = None) -> RunLog:
    """Open a run log, write the run_started event, and make it the
    active run (so ``telemetry.log`` lines mirror into it).  On a
    multi-process mesh only process 0 writes; other processes get a
    disabled no-op log with the same API."""
    primary = True
    try:
        import jax

        primary = jax.process_index() == 0
    except Exception:  # noqa: BLE001 - no backend => single process
        pass
    run_log = RunLog(run_dir, disabled=not primary)
    if primary:
        run_log.run_started(stage=stage, config=config, argv=argv)
        if config is not None:
            from apnea_uq_tpu.config import _to_jsonable
            from apnea_uq_tpu.utils.io import atomic_write_json

            # Atomic commit: summarize/compare read run dirs while runs
            # are live, and a torn config.json would poison both.
            atomic_write_json(os.path.join(run_dir, "config.json"),
                              _to_jsonable(config))
    _ACTIVE.append(run_log)
    return run_log


@contextlib.contextmanager
def append_events(run_dir: str):
    """Append events to an existing run's log WITHOUT opening a new run:
    no ``run_started``, and closing writes no ``run_finished`` — so
    ``latest_run`` keeps the appended events attached to the run they
    annotate.  The ``quality_gate`` audit-trail seam: a post-hoc verdict
    about a run belongs in that run's own event stream."""
    run_log = RunLog(run_dir)
    try:
        yield run_log
    finally:
        # Only the file handle to release: append_events never joins
        # the _ACTIVE stack (that is start_run's job).
        if run_log._fh is not None:
            run_log._fh.close()
            run_log._fh = None


def read_events(run_dir: str) -> List[Dict[str, Any]]:
    """All events of a run, in append order; [] when no log exists yet.
    Tolerates a truncated final line (a run killed mid-write)."""
    path = os.path.join(run_dir, EVENTS_FILENAME)
    if not os.path.exists(path):
        return []
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except ValueError:
                continue  # torn tail write; everything before it is good
    return events


def latest_run(events: List[Dict[str, Any]]):
    """Split an appended multi-run log (bench.py reuses BENCH_RUN_DIR, so
    events.jsonl can hold several runs back-to-back) at its run_started
    boundaries; returns (latest run's events, count of earlier runs).
    The ONE run-boundary rule — summarize and compare both consume it,
    so they can never disagree about which run a dir's metrics are."""
    starts = [i for i, e in enumerate(events)
              if e.get("kind") == "run_started"]
    if len(starts) <= 1:
        return events, 0
    return events[starts[-1]:], len(starts) - 1
