"""Mergeable log-spaced latency histogram digest (the fleet seam).

``SLOTracker``'s percentiles run over a bounded raw-sample history, which
is the right write-side answer for one process but cannot be combined
across replicas: percentiles do not add.  This digest is the mergeable
twin — a fixed ladder of log-spaced bins with EXACT integer counts, so

- merging N replicas' digests is bin-wise integer addition (associative,
  commutative, lossless: the merged digest equals the digest of the
  pooled samples), and
- any percentile of the merged digest is within a documented
  multiplicative bound of the same percentile over the pooled raw
  samples.

**The error bound.**  Bin ``i`` covers ``[LO * R**i, LO * R**(i+1))``
with ``R = 10 ** (1 / BINS_PER_DECADE)``; a bin's representative value
is its geometric midpoint ``LO * R**(i + 0.5)``, so every sample in
range is reproduced within a multiplicative factor of ``sqrt(R)``.
:meth:`LatencyDigest.percentile` applies NumPy's default
linear-interpolation rank convention to the reconstructed order
statistics, and linear interpolation between two values each within a
factor ``f`` of their true counterparts stays within the same factor
``f`` of the interpolated truth.  Hence for samples inside
``[LO, HI)``::

    digest.percentile(q) / np.percentile(pool, q)  in  [1/sqrt(R), sqrt(R)]

i.e. relative error at most ``REL_ERROR_BOUND = sqrt(R) - 1`` (~1.8% at
64 bins/decade).  Samples outside ``[LO, HI)`` clamp into the underflow/
overflow bins (counted exactly; values saturate at the range edge), so
the bound is conditional on range — 10 decades from 1 microsecond up
covers any latency this repo can observe.

Serialization is sparse (only occupied bins), versioned, and
self-describing (``unit`` rides along), sized for embedding on every
``serve_slo`` event — a serve run touches a handful of bins, not the
640-bin ladder.  jax-free by construction, like the rest of the
telemetry read side.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, Optional, Sequence

DIGEST_VERSION = 1

#: Bins per decade of the log-spaced ladder.  64 gives a per-bin growth
#: ratio of 10**(1/64) ~ 1.0366 and a percentile error bound of
#: sqrt(10**(1/64)) - 1 ~ 1.8% — far below any SLO threshold the
#: compare gate would act on.
BINS_PER_DECADE = 64

#: Smallest representable value (1 microsecond when values are seconds).
LO = 1e-6

#: Number of decades covered above :data:`LO` (so the range is
#: ``[1e-6, 1e4)`` — 1 us to ~2.7 hours for second-valued latencies).
DECADES = 10

#: Per-bin growth ratio.
RATIO = 10.0 ** (1.0 / BINS_PER_DECADE)

#: Exclusive upper edge of the in-range ladder.
HI = LO * 10.0 ** DECADES

#: Total in-range bins (underflow/overflow counted separately).
NUM_BINS = BINS_PER_DECADE * DECADES

#: The documented multiplicative percentile error bound: a digest
#: percentile is within a factor of ``1 + REL_ERROR_BOUND`` (above or
#: below) of the same percentile over the pooled raw samples, for
#: samples inside ``[LO, HI)``.
REL_ERROR_BOUND = math.sqrt(RATIO) - 1.0


def bin_index(value: float) -> int:
    """The in-range bin holding ``value``; -1 = underflow, NUM_BINS =
    overflow.  NaN and non-positive values underflow (a latency of
    exactly 0.0 has no log-spaced home; it clamps to the range floor
    like any sub-LO sample); +inf overflows like any super-HI sample."""
    if value == math.inf:
        return NUM_BINS
    if not (value > 0.0) or not math.isfinite(value):
        return -1
    if value < LO:
        return -1
    if value >= HI:
        return NUM_BINS
    i = int(math.floor(math.log10(value / LO) * BINS_PER_DECADE))
    # log10 rounding can land exactly on an edge from either side; clamp
    # into range rather than trusting the last float ulp.
    return min(max(i, 0), NUM_BINS - 1)


def bin_value(index: int) -> float:
    """The representative (geometric-midpoint) value of a bin; the
    underflow/overflow bins saturate at the range edges."""
    if index < 0:
        return LO
    if index >= NUM_BINS:
        return HI
    return LO * RATIO ** (index + 0.5)


class LatencyDigest:
    """Sparse fixed-ladder histogram with exact counts.

    ``unit`` is carried for self-description only (the serve path stores
    request latencies in seconds and per-bucket device times in
    milliseconds); merging digests with different units is refused —
    silently pooling seconds into milliseconds would be a 1000x lie.
    """

    __slots__ = ("unit", "counts", "underflow", "overflow")

    def __init__(self, unit: str = "s"):
        self.unit = str(unit)
        self.counts: Dict[int, int] = {}
        self.underflow = 0
        self.overflow = 0

    # -- write side -------------------------------------------------------

    def add(self, value: float) -> None:
        i = bin_index(float(value))
        if i < 0:
            self.underflow += 1
        elif i >= NUM_BINS:
            self.overflow += 1
        else:
            self.counts[i] = self.counts.get(i, 0) + 1

    def extend(self, values: Iterable[float]) -> None:
        for v in values:
            self.add(v)

    def merge(self, other: "LatencyDigest") -> "LatencyDigest":
        """Fold ``other`` into this digest (bin-wise addition) and
        return self.  Exact: count conservation holds under any merge
        order (integer addition is associative and commutative)."""
        if other.unit != self.unit:
            raise ValueError(
                f"cannot merge digests with different units: "
                f"{self.unit!r} vs {other.unit!r}"
            )
        for i, c in other.counts.items():
            self.counts[i] = self.counts.get(i, 0) + int(c)
        self.underflow += other.underflow
        self.overflow += other.overflow
        return self

    # -- read side --------------------------------------------------------

    @property
    def count(self) -> int:
        return sum(self.counts.values()) + self.underflow + self.overflow

    def percentile(self, q: float) -> Optional[float]:
        """The ``q``-th percentile (0..100) under NumPy's default
        linear-interpolation rank convention, reconstructed from bin
        representatives; None when the digest is empty.  Within
        :data:`REL_ERROR_BOUND` (multiplicative) of ``np.percentile``
        over the pooled raw samples, for in-range samples (module
        docstring has the derivation)."""
        n = self.count
        if n == 0:
            return None
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile must be in [0, 100], got {q}")
        h = (n - 1) * (q / 100.0)
        lo_rank = int(math.floor(h))
        hi_rank = min(lo_rank + 1, n - 1)
        frac = h - lo_rank
        lo_v = self._order_stat(lo_rank)
        if frac == 0.0 or hi_rank == lo_rank:
            return lo_v
        return lo_v + frac * (self._order_stat(hi_rank) - lo_v)

    def percentiles(self, qs: Sequence[float]):
        return [self.percentile(q) for q in qs]

    def _order_stat(self, rank: int) -> float:
        """Representative value of the 0-based ``rank``-th smallest
        sample (underflow sorts first, overflow last)."""
        if rank < self.underflow:
            return bin_value(-1)
        seen = self.underflow
        for i in sorted(self.counts):
            seen += self.counts[i]
            if rank < seen:
                return bin_value(i)
        return bin_value(NUM_BINS)

    # -- serialization ----------------------------------------------------

    def to_payload(self) -> Dict[str, Any]:
        """Sparse JSON-safe form (JSON object keys are strings)."""
        payload: Dict[str, Any] = {
            "v": DIGEST_VERSION,
            "unit": self.unit,
            "n": self.count,
            "bins": {str(i): int(c) for i, c in sorted(self.counts.items())},
        }
        if self.underflow:
            payload["underflow"] = int(self.underflow)
        if self.overflow:
            payload["overflow"] = int(self.overflow)
        return payload

    @classmethod
    def from_payload(cls, payload: Dict[str, Any]) -> "LatencyDigest":
        version = payload.get("v")
        if version != DIGEST_VERSION:
            raise ValueError(
                f"unsupported digest version {version!r} "
                f"(this reader speaks v{DIGEST_VERSION})"
            )
        digest = cls(unit=str(payload.get("unit", "s")))
        for key, c in (payload.get("bins") or {}).items():
            i = int(key)
            if not 0 <= i < NUM_BINS:
                raise ValueError(f"digest bin index {i} out of range")
            if int(c) < 0:
                raise ValueError(f"digest bin {i} has negative count {c}")
            if int(c):
                digest.counts[i] = int(c)
        digest.underflow = int(payload.get("underflow", 0))
        digest.overflow = int(payload.get("overflow", 0))
        return digest


def merge_payloads(payloads: Iterable[Dict[str, Any]],
                   unit: Optional[str] = None) -> LatencyDigest:
    """Merge serialized digests (e.g. collected off N replicas'
    ``serve_slo`` events) into one digest.  ``unit`` pins the expected
    unit; when omitted the first payload's unit wins and the rest must
    agree (mixed units refuse, same as :meth:`LatencyDigest.merge`)."""
    merged: Optional[LatencyDigest] = None
    for payload in payloads:
        digest = LatencyDigest.from_payload(payload)
        if merged is None:
            merged = LatencyDigest(unit=unit if unit is not None
                                   else digest.unit)
        merged.merge(digest)
    return merged if merged is not None else LatencyDigest(
        unit=unit if unit is not None else "s")
