"""Central logging shim — the one place library output reaches a stream.

Library code must not call ``print`` directly (``tests/test_no_bare_print.py``
enforces an allowlist of exactly this file): every user-facing line routes
through :func:`log`, which writes the plain message to the *current*
``sys.stdout`` via stdlib logging.  That keeps CLI output byte-identical to
the historical behavior (tests capture stdout), lets applications redirect
or silence the library with standard ``logging`` configuration, and — when
a run log is active (:mod:`apnea_uq_tpu.telemetry.runlog`) — mirrors every
line into the run's JSONL event stream, so terminal scrollback is never the
only copy of a run's console transcript.
"""

from __future__ import annotations

import contextlib
import logging
import sys

LOGGER_NAME = "apnea_uq_tpu"

# Which sys stream narration reaches, resolved per record ("stdout" |
# "stderr").  Flipped only by :func:`narration_to_stderr`.
_STREAM_NAME = "stdout"


class _StdoutHandler(logging.Handler):
    """Writes plain messages to the CURRENT ``sys.stdout`` (or, inside a
    :func:`narration_to_stderr` scope, ``sys.stderr``), resolved per
    record — pytest's capsys and ``contextlib.redirect_stdout`` see the
    lines exactly where they saw the bare-``print`` output this shim
    replaced (a ``StreamHandler`` would pin the stream object it was
    constructed with instead)."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            # apnea-lint: disable=bare-print -- the central sink every log() line funnels into; by design the one print in the library
            print(self.format(record), file=getattr(sys, _STREAM_NAME))
        except Exception:  # pragma: no cover - stdlib handler contract
            self.handleError(record)


@contextlib.contextmanager
def narration_to_stderr():
    """Route library ``log()`` lines to the current ``sys.stderr`` for
    the duration of the block — for applications whose stdout is a
    machine interface (bench.py's one-JSON-line driver contract must not
    gain a second line just because a profiler capture announced
    itself).  The active-run JSONL mirror is unaffected."""
    global _STREAM_NAME
    prev = _STREAM_NAME
    _STREAM_NAME = "stderr"
    try:
        yield
    finally:
        _STREAM_NAME = prev


def get_logger() -> logging.Logger:
    """The shared library logger, lazily wired to stdout exactly once."""
    logger = logging.getLogger(LOGGER_NAME)
    if not any(isinstance(h, _StdoutHandler) for h in logger.handlers):
        handler = _StdoutHandler()
        handler.setFormatter(logging.Formatter("%(message)s"))
        logger.addHandler(handler)
        logger.setLevel(logging.INFO)
        logger.propagate = False
    return logger


def log(message: str = "", *, level: int = logging.INFO) -> None:
    """Library-wide stdout line: one plain message through the shared
    logger, mirrored as a ``log`` event into the active run log (if any)."""
    get_logger().log(level, message)
    # Local import: runlog never imports this module at import time, but
    # keeping the edge lazy makes the no-cycle property structural.
    from apnea_uq_tpu.telemetry import runlog

    active = runlog.current_run()
    if active is not None:
        active.event("log", message=str(message))
