"""Profiler annotation helpers for the hot paths.

Two complementary mechanisms (both near-zero cost when no trace is being
captured):

- :func:`annotate` — a HOST-side ``jax.profiler.TraceAnnotation`` span:
  labels a region of the Python timeline (one training epoch, one
  predict call) in an XProf/TensorBoard capture.
- ``named_scope`` — re-exported ``jax.named_scope``: labels TRACED
  computation, so the XLA ops inside a jitted program carry readable
  name-stack prefixes (``mcd_pass/...``, ``ensemble_member_epoch/...``)
  in the device timeline instead of fused op soup.

``utils.timing.profile_trace`` starts/stops the capture itself; these
helpers make what it captures legible.
"""

from __future__ import annotations

import contextlib

import jax

named_scope = jax.named_scope


@contextlib.contextmanager
def annotate(name: str, **kwargs):
    """Host-side trace annotation; degrades to a no-op if the profiler
    surface is unavailable (annotation must never break a run)."""
    try:
        ctx = jax.profiler.TraceAnnotation(name, **kwargs)
    except Exception:  # noqa: BLE001 - profiler-less builds
        ctx = None
    if ctx is None:
        yield
        return
    with ctx:
        yield
