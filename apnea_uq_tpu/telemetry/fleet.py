"""Fleet SLO rollups: replica-aware aggregation over N serve run dirs.

The read side of ISSUE 18's fleet arc, behind ``apnea-uq telemetry
fleet <run-dir>...``.  Each serving replica writes its own run
directory; the final ``serve_slo`` event of each carries the mergeable
latency digest (telemetry/digest.py) overall and per bucket, so this
module can reconstruct CROSS-REPLICA percentiles from event streams
alone — exact counts, error bounded by the digest bin width — where
averaging per-replica percentiles would be statistically meaningless.

Beyond the merged summary the rollup answers the two fleet questions
the per-process events cannot: *which replica is the outlier* (the
per-replica attribution table, flagged when a replica's p99 exceeds
``spread_threshold`` times the replica median) and *is any tenant
drifting anywhere* (``serve_drift`` verdicts rolled up per tenant
across replicas, worst verdict wins).

The rollup is emitted as a ``fleet_rollup`` event (plus the
``fleet_rollup`` registry artifact) into a fresh rollup directory, so
``telemetry compare`` gates ``fleet.p99_ms`` / ``fleet.windows_per_s``
/ ``fleet.imbalance_ratio`` between two rollups and ``telemetry
trend`` carries them as series — through the exact run-dir seam every
other gateable kind uses.  jax-free like the rest of the read side;
torn tails and appended multi-run logs are tolerated via the
``read_events``/``latest_run`` seam.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from apnea_uq_tpu.telemetry.digest import REL_ERROR_BOUND, LatencyDigest
from apnea_uq_tpu.telemetry.runlog import (
    append_events,
    latest_run,
    read_events,
)

#: A replica whose p99 latency is at least this many times the
#: replica-median p99 is flagged as the fleet outlier.
DEFAULT_SPREAD_THRESHOLD = 2.0

#: Worst-verdict-wins ordering for the per-tenant drift rollup.
_VERDICT_RANK = {"ok": 0, "warn": 1, "drift": 2}


class NoFleetTelemetry(ValueError):
    """A source carries nothing the fleet rollup can aggregate — a
    usage error (CLI exit 2), never a clean rollup over zero replicas."""


@dataclasses.dataclass
class ReplicaStats:
    """One replica's contribution, read from its run dir's final
    ``serve_slo`` (latest run of an appended log)."""

    run_dir: str
    replica_id: str
    earlier_runs: int
    requests: int
    windows: int
    batches: int
    p50_ms: Optional[float]
    p95_ms: Optional[float]
    p99_ms: Optional[float]
    windows_per_s: float
    requests_per_s: Optional[float]
    queue_wait_mean_s: float
    pad_waste: float
    interval_s: Optional[float]
    digest: LatencyDigest
    digest_source: str          # 'serve_slo' | 'serve_request' | 'none'
    buckets: Dict[str, Dict[str, Any]]
    drift: Dict[str, Dict[str, Any]]
    outlier: bool = False
    # The exemplar tracer's counter ledger off the final serve_slo
    # (ISSUE 20) — empty for untraced replicas.  Carried so the fleet
    # table can flag a replica whose over-budget requests lost their
    # waterfalls without re-reading every event stream.
    trace: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class FleetRollup:
    """The merged fleet view plus per-replica attribution."""

    replicas: List[ReplicaStats]
    spread_threshold: float
    digest: LatencyDigest
    requests: int
    windows: int
    batches: int
    p50_ms: Optional[float]
    p95_ms: Optional[float]
    p99_ms: Optional[float]
    windows_per_s: float
    requests_per_s: Optional[float]
    queue_wait_mean_s: float
    pad_waste: float
    imbalance_ratio: Optional[float]
    outliers: List[str]
    buckets: Dict[str, Dict[str, Any]]
    drift: Dict[str, Dict[str, Any]]


def _digest_ms(digest: LatencyDigest, q: float) -> Optional[float]:
    """A digest percentile in milliseconds regardless of the digest's
    native unit (request latencies store seconds, bucket device times
    store ms)."""
    value = digest.percentile(q)
    if value is None:
        return None
    return round(value * 1e3 if digest.unit == "s" else value, 3)


def replica_stats(run_dir: str) -> ReplicaStats:
    """Read one replica's final SLO snapshot.  Raises
    :class:`NoFleetTelemetry` when the dir has no events or no
    ``serve_slo`` — a rollup silently skipping a replica would
    under-report fleet load exactly when a replica is sick."""
    events = read_events(run_dir)
    if not events:
        raise NoFleetTelemetry(
            f"no events.jsonl events under {run_dir!r} — not a telemetry "
            f"run directory"
        )
    events, earlier = latest_run(events)
    slo: Optional[Dict[str, Any]] = None
    for e in events:
        if e.get("kind") == "serve_slo":
            slo = e  # append-order overwrite: the LAST snapshot wins
    if slo is None:
        raise NoFleetTelemetry(
            f"{run_dir!r} carries no serve_slo events — not a serve "
            f"replica run (its latest run has nothing to aggregate)"
        )
    digest_source = "serve_slo"
    payload = slo.get("digest")
    if isinstance(payload, dict):
        digest = LatencyDigest.from_payload(payload)
    else:
        # Pre-digest serve runs: reconstruct from per-request events so
        # old replica logs still merge (same values, same bound).
        digest = LatencyDigest(unit="s")
        digest_source = "serve_request"
        for e in events:
            if (e.get("kind") == "serve_request"
                    and e.get("latency_s") is not None):
                digest.add(float(e["latency_s"]))
        if digest.count == 0:
            digest_source = "none"
    drift: Dict[str, Dict[str, Any]] = {}
    for e in events:
        if e.get("kind") != "serve_drift":
            continue
        tenant = str(e.get("tenant", "default"))
        drift[tenant] = {  # last verdict per tenant wins, like quality
            "verdict": str(e.get("verdict", "ok")),
            "windows": e.get("windows"),
            "max_psi": e.get("max_psi"),
            "max_ks": e.get("max_ks"),
        }
    interval = slo.get("interval_s")
    requests = int(slo.get("requests", 0))
    return ReplicaStats(
        run_dir=run_dir,
        replica_id=str(slo.get("replica_id")
                       or os.path.basename(os.path.normpath(run_dir))),
        earlier_runs=earlier,
        requests=requests,
        windows=int(slo.get("windows", 0)),
        batches=int(slo.get("batches", 0)),
        p50_ms=slo.get("p50_ms"),
        p95_ms=slo.get("p95_ms"),
        p99_ms=slo.get("p99_ms"),
        windows_per_s=float(slo.get("windows_per_s", 0.0)),
        requests_per_s=(round(requests / float(interval), 3)
                        if interval else None),
        queue_wait_mean_s=float(slo.get("queue_wait_mean_s", 0.0)),
        pad_waste=float(slo.get("pad_waste", 0.0)),
        interval_s=interval,
        digest=digest,
        digest_source=digest_source,
        buckets=dict(slo.get("buckets") or {}),
        drift=drift,
        trace=dict(slo.get("trace") or {}),
    )


def _merge_buckets(replicas: Sequence[ReplicaStats]) -> Dict[str, Dict[str, Any]]:
    merged: Dict[str, Dict[str, Any]] = {}
    digests: Dict[str, LatencyDigest] = {}
    for rep in replicas:
        for key, per in rep.buckets.items():
            row = merged.setdefault(
                key, {"batches": 0, "windows": 0, "pad_rows": 0})
            row["batches"] += int(per.get("batches", 0))
            row["windows"] += int(per.get("windows", 0))
            row["pad_rows"] += int(per.get("pad_rows", 0))
            payload = per.get("digest")
            if isinstance(payload, dict):
                digest = LatencyDigest.from_payload(payload)
                if key in digests:
                    digests[key].merge(digest)
                else:
                    digests[key] = digest
    for key, row in merged.items():
        dispatched = row["batches"] * int(key)
        row["pad_waste"] = (round(row["pad_rows"] / dispatched, 4)
                            if dispatched else 0.0)
        digest = digests.get(key)
        if digest is not None:
            row["p50_ms"] = _digest_ms(digest, 50.0)
            row["p95_ms"] = _digest_ms(digest, 95.0)
            row["p99_ms"] = _digest_ms(digest, 99.0)
            row["digest"] = digest.to_payload()
        else:
            row["p50_ms"] = row["p95_ms"] = row["p99_ms"] = None
    return {key: merged[key] for key in sorted(merged, key=int)}


def _rollup_drift(replicas: Sequence[ReplicaStats]) -> Dict[str, Dict[str, Any]]:
    """Per-tenant worst-verdict-wins across replicas, with per-replica
    attribution so 'drift' points at the replica that saw it."""
    tenants: Dict[str, Dict[str, Any]] = {}
    for rep in replicas:
        for tenant, doc in rep.drift.items():
            row = tenants.setdefault(tenant, {
                "verdict": "ok", "replicas": {},
                "max_psi": None, "max_ks": None,
            })
            verdict = doc.get("verdict", "ok")
            row["replicas"][rep.replica_id] = verdict
            if (_VERDICT_RANK.get(verdict, 0)
                    > _VERDICT_RANK.get(row["verdict"], 0)):
                row["verdict"] = verdict
            for field in ("max_psi", "max_ks"):
                value = doc.get(field)
                if value is not None and (row[field] is None
                                          or value > row[field]):
                    row[field] = round(float(value), 6)
    return {tenant: tenants[tenant] for tenant in sorted(tenants)}


def build_rollup(
    run_dirs: Sequence[str],
    *,
    spread_threshold: float = DEFAULT_SPREAD_THRESHOLD,
) -> FleetRollup:
    """Merge N replica run dirs into one fleet rollup.  Percentiles come
    from the bin-wise-added digests (within the digest error bound of
    the pooled raw samples — telemetry/digest.py documents it); counters
    are exact sums; throughput adds across replicas."""
    if not run_dirs:
        raise NoFleetTelemetry("no run directories given")
    if spread_threshold <= 1.0:
        raise ValueError(
            f"spread threshold must be > 1.0 (a multiple of the median "
            f"replica p99), got {spread_threshold}"
        )
    replicas = [replica_stats(d) for d in run_dirs]
    fleet_digest = LatencyDigest(unit="s")
    for rep in replicas:
        fleet_digest.merge(rep.digest)
    # Batch-weighted queue wait: each replica's mean covers its own
    # dispatched batches, so batches are the right weights.
    total_batches = sum(r.batches for r in replicas)
    queue_wait = (
        sum(r.queue_wait_mean_s * r.batches for r in replicas)
        / total_batches if total_batches else 0.0)
    buckets = _merge_buckets(replicas)
    # Pad waste, exactly, from the merged bucket tables (pad_rows and
    # batches*bucket are both exact counters); replicas without bucket
    # tables fall back to a window-weighted mean of their ratios.
    dispatched = sum(row["batches"] * int(key)
                     for key, row in buckets.items())
    if dispatched:
        pad_waste = round(
            sum(row["pad_rows"] for row in buckets.values()) / dispatched, 4)
    else:
        total_windows = sum(r.windows for r in replicas)
        pad_waste = (round(
            sum(r.pad_waste * r.windows for r in replicas) / total_windows, 4)
            if total_windows else 0.0)
    p99s = [r.p99_ms for r in replicas if r.p99_ms is not None]
    imbalance: Optional[float] = None
    outliers: List[str] = []
    if p99s:
        median = float(np.median(np.asarray(p99s, np.float64)))
        if median > 0.0:
            imbalance = round(max(p99s) / median, 3)
            if len(replicas) > 1:
                for rep in replicas:
                    if (rep.p99_ms is not None
                            and rep.p99_ms >= spread_threshold * median):
                        rep.outlier = True
                        outliers.append(rep.replica_id)
    rps = [r.requests_per_s for r in replicas if r.requests_per_s is not None]
    return FleetRollup(
        replicas=replicas,
        spread_threshold=float(spread_threshold),
        digest=fleet_digest,
        requests=sum(r.requests for r in replicas),
        windows=sum(r.windows for r in replicas),
        batches=total_batches,
        p50_ms=_digest_ms(fleet_digest, 50.0),
        p95_ms=_digest_ms(fleet_digest, 95.0),
        p99_ms=_digest_ms(fleet_digest, 99.0),
        windows_per_s=round(sum(r.windows_per_s for r in replicas), 3),
        requests_per_s=round(sum(rps), 3) if rps else None,
        queue_wait_mean_s=round(queue_wait, 6),
        pad_waste=pad_waste,
        imbalance_ratio=imbalance,
        outliers=outliers,
        buckets=buckets,
        drift=_rollup_drift(replicas),
    )


# ------------------------------------------------------------- read out --

def replica_data(rep: ReplicaStats) -> Dict[str, Any]:
    return {
        "run_dir": rep.run_dir,
        "replica_id": rep.replica_id,
        "earlier_runs": rep.earlier_runs,
        "requests": rep.requests,
        "windows": rep.windows,
        "batches": rep.batches,
        "p50_ms": rep.p50_ms,
        "p95_ms": rep.p95_ms,
        "p99_ms": rep.p99_ms,
        "windows_per_s": rep.windows_per_s,
        "requests_per_s": rep.requests_per_s,
        "queue_wait_mean_s": rep.queue_wait_mean_s,
        "pad_waste": rep.pad_waste,
        "interval_s": rep.interval_s,
        "digest_source": rep.digest_source,
        "digest_count": rep.digest.count,
        "outlier": rep.outlier,
        "drift": rep.drift,
        "trace": rep.trace,
    }


def rollup_data(rollup: FleetRollup) -> Dict[str, Any]:
    """The rollup as one JSON-able document — the ``fleet_rollup``
    registry artifact body and the ``--json`` extra payload."""
    return {
        "replicas": [replica_data(r) for r in rollup.replicas],
        "spread_threshold": rollup.spread_threshold,
        "requests": rollup.requests,
        "windows": rollup.windows,
        "batches": rollup.batches,
        "p50_ms": rollup.p50_ms,
        "p95_ms": rollup.p95_ms,
        "p99_ms": rollup.p99_ms,
        "windows_per_s": rollup.windows_per_s,
        "requests_per_s": rollup.requests_per_s,
        "queue_wait_mean_s": rollup.queue_wait_mean_s,
        "pad_waste": rollup.pad_waste,
        "imbalance_ratio": rollup.imbalance_ratio,
        "outliers": list(rollup.outliers),
        "digest": rollup.digest.to_payload(),
        "buckets": rollup.buckets,
        "drift": rollup.drift,
    }


def _ms(value: Optional[float]) -> str:
    return "-" if value is None else f"{value}ms"


def render_fleet(rollup: FleetRollup) -> str:
    """The human view: fleet summary, per-replica attribution table,
    merged bucket table, per-tenant drift rollup."""
    lines: List[str] = []
    lines.append(
        f"fleet: {len(rollup.replicas)} replica(s), {rollup.requests} "
        f"request(s) / {rollup.windows} window(s) in {rollup.batches} "
        f"batch(es)")
    lines.append(
        f"  p50 {_ms(rollup.p50_ms)}  p95 {_ms(rollup.p95_ms)}  "
        f"p99 {_ms(rollup.p99_ms)}  (digest-merged, error <= "
        f"{100 * REL_ERROR_BOUND:.1f}%)")
    lines.append(
        f"  {rollup.windows_per_s} windows/s"
        + (f", {rollup.requests_per_s} req/s" if rollup.requests_per_s
           is not None else "")
        + f", queue wait {rollup.queue_wait_mean_s}s, pad waste "
        + f"{rollup.pad_waste}")
    if rollup.imbalance_ratio is not None:
        flagged = (", ".join(rollup.outliers) if rollup.outliers
                   else "no outliers")
        lines.append(
            f"  imbalance ratio {rollup.imbalance_ratio} "
            f"(max/median replica p99; outlier at >= "
            f"{rollup.spread_threshold}x): {flagged}")
    lines.append("")
    header = (f"  {'replica':<24} {'requests':>8} {'win/s':>9} "
              f"{'p50_ms':>8} {'p99_ms':>8} {'wait_s':>8} "
              f"{'pad':>6}  flags")
    lines.append("replicas:")
    lines.append(header)
    for rep in rollup.replicas:
        flags = []
        if rep.outlier:
            flags.append("OUTLIER")
        if rep.digest_source != "serve_slo":
            flags.append(f"digest:{rep.digest_source}")
        if (rep.trace.get("over_budget") is not None
                and rep.trace.get("over_budget_traced") is not None
                and rep.trace["over_budget_traced"]
                < rep.trace["over_budget"]):
            flags.append("MISSING-EXEMPLARS")
        if rep.earlier_runs:
            flags.append(f"+{rep.earlier_runs} earlier run(s)")
        lines.append(
            f"  {rep.replica_id:<24} {rep.requests:>8} "
            f"{rep.windows_per_s:>9} "
            f"{rep.p50_ms if rep.p50_ms is not None else '-':>8} "
            f"{rep.p99_ms if rep.p99_ms is not None else '-':>8} "
            f"{rep.queue_wait_mean_s:>8} {rep.pad_waste:>6}  "
            f"{' '.join(flags) if flags else '-'}")
    if rollup.buckets:
        lines.append("")
        lines.append("buckets (device-time percentiles, digest-merged):")
        lines.append(f"  {'bucket':>6} {'batches':>8} {'windows':>8} "
                     f"{'pad':>6} {'p50_ms':>8} {'p99_ms':>8}")
        for key, row in rollup.buckets.items():
            lines.append(
                f"  {key:>6} {row['batches']:>8} {row['windows']:>8} "
                f"{row['pad_waste']:>6} "
                f"{row['p50_ms'] if row['p50_ms'] is not None else '-':>8} "
                f"{row['p99_ms'] if row['p99_ms'] is not None else '-':>8}")
    if rollup.drift:
        lines.append("")
        lines.append("drift rollup (worst verdict wins):")
        for tenant, row in rollup.drift.items():
            per = ", ".join(f"{rid}={v}" for rid, v
                            in sorted(row["replicas"].items()))
            lines.append(
                f"  [{tenant}] {row['verdict']} "
                f"(max_psi {row['max_psi']}, max_ks {row['max_ks']}; "
                f"{per})")
    return "\n".join(lines)


def fleet_findings(rollup: FleetRollup):
    """Outlier replicas and drifted tenants as lint-engine findings, so
    the shared reporters (text / ``--json`` / ``--format gha``) render
    the fleet gate with the machinery lint/flow/quality use."""
    from apnea_uq_tpu.lint.engine import Finding

    findings = []
    for rep in rollup.replicas:
        if rep.outlier:
            findings.append(Finding(
                rule="fleet-outlier-replica", severity="error",
                path=rep.run_dir, line=0,
                message=(
                    f"replica {rep.replica_id!r} p99 {rep.p99_ms}ms is "
                    f">= {rollup.spread_threshold}x the replica-median "
                    f"p99 (fleet imbalance ratio "
                    f"{rollup.imbalance_ratio})"),
            ))
    for tenant, row in rollup.drift.items():
        if row["verdict"] == "drift":
            drifted = sorted(rid for rid, v in row["replicas"].items()
                             if v == "drift")
            findings.append(Finding(
                rule="fleet-drift", severity="error",
                path=rollup.replicas[0].run_dir if rollup.replicas else "",
                line=0,
                message=(
                    f"tenant {tenant!r} rolled up to verdict 'drift' "
                    f"(max_psi {row['max_psi']}, max_ks {row['max_ks']}) "
                    f"on replica(s): {', '.join(drifted)}"),
            ))
    return findings


def fleet_result(rollup: FleetRollup):
    """The findings wrapped as a :class:`LintResult` for
    ``emit_result`` — ``files_scanned`` counts replicas."""
    from apnea_uq_tpu.lint.engine import LintResult

    return LintResult(
        findings=fleet_findings(rollup),
        files_scanned=len(rollup.replicas),
        rules_run=("fleet-outlier-replica", "fleet-drift"),
        scanned_paths=tuple(r.run_dir for r in rollup.replicas),
    )


def record_rollup(rollup: FleetRollup, out_dir: str) -> None:
    """Persist the rollup into ``out_dir``: the ``fleet_rollup``
    registry artifact (atomic JSON + manifest row) plus one
    ``fleet_rollup`` event in ``<out_dir>/events.jsonl`` — making the
    rollup dir a first-class source for ``telemetry compare`` and
    ``telemetry trend`` through the same run-dir seam every other
    gateable kind rides."""
    from apnea_uq_tpu.data import registry as registry_mod

    data = rollup_data(rollup)
    registry = registry_mod.ArtifactRegistry(out_dir)
    # apnea-lint: disable=artifact-never-consumed -- end product: the rollup document is read by compare/trend through the rollup dir's event stream (load_source) and by operators, not by a registry-loading pipeline stage
    registry.save_json(registry_mod.FLEET_ROLLUP, data)
    with append_events(out_dir) as run_log:
        run_log.event(
            "fleet_rollup",
            replicas=len(rollup.replicas),
            sources=[r.run_dir for r in rollup.replicas],
            requests=rollup.requests,
            windows=rollup.windows,
            batches=rollup.batches,
            p50_ms=rollup.p50_ms,
            p95_ms=rollup.p95_ms,
            p99_ms=rollup.p99_ms,
            windows_per_s=rollup.windows_per_s,
            requests_per_s=rollup.requests_per_s,
            queue_wait_mean_s=rollup.queue_wait_mean_s,
            pad_waste=rollup.pad_waste,
            imbalance_ratio=rollup.imbalance_ratio,
            spread_threshold=rollup.spread_threshold,
            outliers=list(rollup.outliers),
            digest=rollup.digest.to_payload(),
            buckets=rollup.buckets,
            drift=rollup.drift,
        )
