"""Hardware-watch evidence autopilot (ISSUE 3 tentpole, piece 4).

The TPU tunnel has been down for three consecutive rounds, and each
round the evidence ritual (a bench capture + the TPU-gated tests) had to
be remembered and run by hand in whatever window the tunnel offered.
``apnea-uq telemetry watch`` closes that loop: it probes the backend
with the same budgeted-subprocess probe and backoff schedule bench.py's
init retry uses (:func:`probe_backend` / :func:`wait_for_green` — bench
imports them from here), and on the FIRST green probe runs the
configured evidence ritual into a fresh telemetry run directory:

1. ``python bench.py`` with ``BENCH_RUN_DIR``/``BENCH_PROGRESS_FILE``
   pointed inside the watch run dir (a BENCH_r06-grade capture);
2. ``APNEA_UQ_TEST_TPU=1 python -m pytest tests/test_bootstrap.py -k
   on_tpu`` (the TPU-gated kernel tests).

Every probe attempt, the green transition, and each ritual step's exit
code land in the run's ``events.jsonl`` (``probe``, ``probe_green``,
``ritual_step``), with each step's stdout/stderr saved next to it — so
the evidence of WHEN hardware appeared and what ran is itself a
telemetry artifact.
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

from apnea_uq_tpu.telemetry.logging_shim import log
from apnea_uq_tpu.telemetry.runlog import default_run_dir, start_run
from apnea_uq_tpu.utils.io import atomic_write_text

# Backoff schedule shared with bench.py's init retry (its unit tests pin
# the first two sleeps at 20.0 and 32.0 seconds).
BACKOFF_INITIAL_S = 20.0
BACKOFF_FACTOR = 1.6
BACKOFF_MAX_S = 300.0

_PROBE_SNIPPET = "import jax; assert jax.devices()"

# The repo root (bench.py, tests/) sits two levels above this package.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def probe_backend(probe_timeout_s: float = 120.0) -> Tuple[bool, str]:
    """One budgeted backend probe: ``jax.devices()`` in a subprocess —
    the call can hang indefinitely during a tunnel outage, so it must
    never run in this process.  Returns (green, detail)."""
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SNIPPET],
            capture_output=True, text=True, timeout=probe_timeout_s,
        )
        if r.returncode == 0:
            return True, "ok"
        tail = (r.stderr or r.stdout).strip().splitlines()
        return False, tail[-1] if tail else f"probe exited rc={r.returncode}"
    except subprocess.TimeoutExpired:
        return False, (f"probe hung >{probe_timeout_s:.0f}s in "
                       f"jax.devices() (tunnel-outage pattern)")


def wait_for_green(
    budget_s: float,
    *,
    probe_timeout_s: float = 120.0,
    probe: Optional[Callable[[float], Tuple[bool, str]]] = None,
    on_attempt: Optional[Callable[[int, bool, str], None]] = None,
    max_attempts: Optional[int] = None,
) -> Tuple[bool, int, str]:
    """Probe with backoff until green or the budget expires.  Returns
    (green, attempts, last_detail).  The final sleep is clamped to the
    remaining budget rather than giving up early, and a hang-mode probe
    never overshoots the deadline — the semantics bench.py's init retry
    established (its tests pin them).  ``max_attempts`` additionally caps
    the probe count (bench's BENCH_BACKEND_PROBES knob; None = budget
    only)."""
    probe = probe or probe_backend
    deadline = time.monotonic() + budget_s
    delay = BACKOFF_INITIAL_S
    attempts, last = 0, "no probe ran"
    while True:
        attempts += 1
        probe_budget = min(probe_timeout_s,
                           max(deadline - time.monotonic(), 1.0))
        green, last = probe(probe_budget)
        if on_attempt is not None:
            on_attempt(attempts, green, last)
        if green:
            return True, attempts, last
        if max_attempts is not None and attempts >= max_attempts:
            return False, attempts, last
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False, attempts, last
        time.sleep(min(delay, remaining))
        delay = min(delay * BACKOFF_FACTOR, BACKOFF_MAX_S)


@dataclasses.dataclass
class RitualStep:
    """One command of the evidence ritual."""

    name: str
    argv: List[str]
    env: Dict[str, str]
    # A hung subprocess must not hang the (unattended, up-to-24h) watch:
    # the TPU-gated pytest step has no internal watchdog, and a tunnel
    # that flaps AFTER the green probe hangs jax.devices() inside it.
    timeout_s: float = 7200.0
    # The step's stdout ends in a bench result payload: gate the step on
    # its per-block statuses, not the exit code alone — a bench that
    # banked N good blocks before a mid-run death is evidence, not a
    # failure (ISSUE 11 tentpole, piece 4).
    payload_json: bool = False


def bench_payload_summary(stdout_text: str) -> Optional[Dict]:
    """Per-block verdict of a bench step's stdout: parse the LAST JSON
    line (the result payload — schema v2 carries a ``blocks`` status
    map; v1 lines count as zero blocks) into
    ``{payload_metric, proxy, blocks_ok, blocks_error}``.  None when no
    line parses — then the exit code stays the only verdict."""
    doc = None
    for line in reversed(stdout_text.strip().splitlines()):
        line = line.strip()
        if not line:
            continue
        try:
            candidate = json.loads(line)
        except ValueError:
            continue
        if isinstance(candidate, dict):
            doc = candidate
            break
    if doc is None:
        return None
    blocks = doc.get("blocks") if isinstance(doc.get("blocks"), dict) else {}
    statuses = [b.get("status") for b in blocks.values()
                if isinstance(b, dict)]
    return {
        "payload_metric": doc.get("metric"),
        "proxy": bool(doc.get("proxy")),
        "blocks_ok": sum(1 for s in statuses if s == "ok"),
        "blocks_error": sum(1 for s in statuses if s == "error"),
    }


def evidence_ritual_steps(
    run_dir: str,
    *,
    skip_tests: bool = False,
    repo_root: str = _REPO_ROOT,
    python: str = sys.executable,
) -> List[RitualStep]:
    """The round-5 verdict's hardware ritual, parameterized to land its
    artifacts inside the watch run directory — bench capture, TPU-gated
    tests, and a closing ``telemetry trend`` snapshot so every ritual
    ends with the cross-round trajectory including the round it just
    landed (the bench run dir's ``bench_metric`` events are the extra
    trend source)."""
    steps = [RitualStep(
        name="bench",
        argv=[python, os.path.join(repo_root, "bench.py")],
        env={
            "BENCH_RUN_DIR": os.path.join(run_dir, "bench"),
            "BENCH_PROGRESS_FILE": os.path.join(run_dir,
                                                "bench_progress.json"),
        },
        payload_json=True,
    )]
    if not skip_tests:
        steps.append(RitualStep(
            name="tpu_tests",
            argv=[python, "-m", "pytest", "tests/test_bootstrap.py",
                  "-k", "on_tpu", "-q"],
            env={"APNEA_UQ_TEST_TPU": "1"},
            timeout_s=3600.0,
        ))
    steps.append(RitualStep(
        name="trend",
        argv=[python, "-m", "apnea_uq_tpu.cli.main", "telemetry", "trend",
              os.path.join(run_dir, "bench")],
        env={},
        timeout_s=600.0,
    ))
    return steps


def ritual_preflight(
    *,
    skip_tests: bool = False,
    repo_root: str = _REPO_ROOT,
) -> List[str]:
    """Paths the ritual will exec, that do not exist.  Checked BEFORE the
    (up to 24h) green wait: a site-packages install or a moved checkout
    must fail in seconds, not crash with a FileNotFoundError the moment
    the long-awaited hardware window finally opens."""
    required = [os.path.join(repo_root, "bench.py")]
    if not skip_tests:
        required.append(os.path.join(repo_root, "tests",
                                     "test_bootstrap.py"))
    return [p for p in required if not os.path.exists(p)]


def run_evidence_ritual(
    run_log,
    steps: List[RitualStep],
    *,
    repo_root: str = _REPO_ROOT,
    runner: Optional[Callable[..., "subprocess.CompletedProcess"]] = None,
) -> List[Tuple[int, bool]]:
    """Execute the ritual steps sequentially, each under its own stage
    bracket, stdout/stderr saved under the run dir, exit codes recorded
    as ``ritual_step`` events.  A failing step does not stop the ritual
    (a red TPU test after a good bench capture must not discard it).
    Returns ``[(returncode, passed)]`` per step: ``passed`` is the
    per-block verdict for ``payload_json`` steps — a bench payload with
    at least one ``ok`` block passes even when the process exited
    nonzero (partial results are evidence, not failure) — and the plain
    rc==0 check otherwise."""
    runner = runner or subprocess.run
    results: List[Tuple[int, bool]] = []
    for step in steps:
        env = dict(os.environ)
        env.update(step.env)
        log(f"[watch] running {step.name}: {' '.join(step.argv)}")
        with run_log.stage(f"ritual:{step.name}"):
            t0 = time.perf_counter()
            timed_out = False
            try:
                result = runner(step.argv, cwd=repo_root, env=env,
                                capture_output=True, text=True,
                                timeout=step.timeout_s)
                returncode = int(result.returncode)
            except subprocess.TimeoutExpired as e:
                # A hung step (tunnel flap mid-ritual) is a failed step,
                # not a hung watch; partial output is still evidence.
                timed_out = True
                returncode = -1
                result = e
            wall = time.perf_counter() - t0
            outputs = {}
            stdout_text = ""
            for stream in ("stdout", "stderr"):
                text = getattr(result, stream, None) or ""
                if isinstance(text, bytes):  # TimeoutExpired keeps bytes
                    text = text.decode(errors="replace")
                if stream == "stdout":
                    stdout_text = text
                rel = f"{step.name}.{stream}.txt"
                # Atomic: the ritual evidence lands in a run dir other
                # tools read back; a torn capture is false evidence.
                atomic_write_text(os.path.join(run_log.run_dir, rel), text)
                outputs[f"{stream}_path"] = rel
            passed = returncode == 0
            extra = {}
            if step.payload_json:
                summary = bench_payload_summary(stdout_text)
                if summary is not None:
                    extra.update(summary)
                    # Per-block gating: a payload with surviving ok
                    # blocks is a usable (partial) capture regardless of
                    # how the process ended.
                    passed = passed or summary["blocks_ok"] > 0
            run_log.event(
                "ritual_step", name=step.name, argv=step.argv,
                returncode=returncode, passed=passed, timed_out=timed_out,
                timeout_s=step.timeout_s,
                wall_s=round(wall, 3), env_overrides=step.env,
                **outputs, **extra,
            )
        log(f"[watch] {step.name} "
            + (f"timed out after {step.timeout_s:.0f}s"
               if timed_out
               else f"finished rc={returncode} in {wall:.0f}s"
                    + ("" if passed == (returncode == 0)
                       else f" (passed={passed} on per-block statuses)")))
        results.append((returncode, passed))
    return results


def watch(
    out_root: str,
    *,
    budget_s: float = 86400.0,
    probe_timeout_s: float = 120.0,
    skip_tests: bool = False,
    repo_root: str = _REPO_ROOT,
    probe: Optional[Callable[[float], Tuple[bool, str]]] = None,
    runner=None,
) -> int:
    """Watch for the backend to come up, then land the evidence.

    Returns 0 when every ritual step passed, 1 when any step failed
    (a timed-out step counts as failed), 2 when the ritual never ran —
    probe budget expired without a green backend (the same exit code
    bench.py uses for init-retry exhaustion) or the ritual's files are
    missing from ``repo_root`` (checked up front, so a misconfigured
    install fails in seconds instead of after the wait)."""
    missing = ritual_preflight(skip_tests=skip_tests, repo_root=repo_root)
    if missing:
        log(f"[watch] evidence ritual misconfigured: {missing} not "
            f"found — run from a repo checkout (or pass repo_root); "
            f"refusing to start the probe wait")
        return 2
    log(f"[watch] probing backend (budget {budget_s:.0f}s, "
        f"probe timeout {probe_timeout_s:.0f}s)")
    attempts_log: List[Dict] = []

    def on_attempt(n: int, green: bool, detail: str) -> None:
        attempts_log.append({"attempt": n, "green": green,
                             "detail": detail})
        log(f"[watch] probe {n}: {'GREEN' if green else detail}")

    green, attempts, last = wait_for_green(
        budget_s, probe_timeout_s=probe_timeout_s, probe=probe,
        on_attempt=on_attempt,
    )
    if not green:
        log(f"[watch] backend never came up in {budget_s:.0f}s "
            f"({attempts} probes; last: {last})")
        return 2
    run_dir = default_run_dir(out_root, "watch")
    run_log = start_run(run_dir, stage="watch")
    try:
        for record in attempts_log:
            run_log.event("probe", **record)
        run_log.event("probe_green", attempts=attempts)
        log(f"[watch] backend GREEN after {attempts} probe(s); "
            f"evidence -> {run_dir}")
        steps = evidence_ritual_steps(
            run_dir, skip_tests=skip_tests, repo_root=repo_root,
        )
        results = run_evidence_ritual(run_log, steps, repo_root=repo_root,
                                      runner=runner)
    except BaseException as e:
        run_log.error("watch", e)
        run_log.close(status="error")
        raise
    status = "ok" if all(passed for _rc, passed in results) else "error"
    run_log.close(status=status)
    return 0 if status == "ok" else 1
