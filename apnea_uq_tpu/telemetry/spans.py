"""Fleet-wide distributed tracing: span identity, tail-based exemplar
sampling, and the cross-replica trace assembler.

The write side fixes the two things PR 17's spans could not do at
fleet scale.  **Identity:** span ids are globally unique —
``<replica_id>/<trace_id>`` with the trace id minted at the request
source (or carried inbound on an NDJSON request line), so two replicas
can never emit colliding ``span-0`` counters and a rollup can join
spans safely.  **Sampling:** :class:`ExemplarTracer` decides *at
request completion* whether a span is emitted — the first completed
request always (a light-load serve must leave evidence), the existing
1-in-N head stream for baseline coverage, and in tail mode
(``--trace-slow-ms`` > 0) every request over the latency budget plus
rolling per-bucket p99 outliers through a bounded per-bucket exemplar
reservoir with EXACT drop counters.  Over-budget requests are never
dropped — that is the ``trace.exemplar_coverage == 1.0`` contract the
bench asserts and ``telemetry trace`` verifies from the event streams.

The read side mirrors ``telemetry/fleet.py``: jax-free, torn tails
tolerated via ``read_events``, appended logs split via ``latest_run``.
``build_trace`` merges ``serve_trace`` events across N replica run
dirs, detects span-id collisions, reconstructs per-request waterfalls,
computes the phase-attribution breakdown (queue vs service vs pad
overhead) at p50/p95/p99 per bucket and per replica, and names the
replica/phase that dominates the fleet tail.  The report persists as
the ``trace_report`` registry artifact plus a ``trace_report`` event,
so ``telemetry compare`` gates ``trace.queue_share_p99`` /
``trace.service_share_p99`` / ``trace.exemplar_coverage``
(backend-unbound ratios) and ``telemetry trend`` carries them as
series through the same run-dir seam every gateable kind rides.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from apnea_uq_tpu.telemetry.runlog import (
    append_events,
    latest_run,
    read_events,
    replica_id,
)

#: Rolling per-bucket latency window the p99-outlier test runs over —
#: enough samples for a stable tail estimate, bounded memory.
P99_WINDOW = 512

#: The per-bucket p99 test stays off until the bucket has seen this
#: many completions: a p99 over 3 samples flags every third request.
DEFAULT_P99_MIN_SAMPLES = 20

#: Bounded per-bucket budget for p99-tail exemplars (NOT over-budget
#: ones — those always emit).  Exceeding it increments the bucket's
#: exact drop counter instead of emitting.
DEFAULT_RESERVOIR_PER_BUCKET = 32

#: How many exemplar span ids a serve_slo snapshot carries — the SLO
#: line links to evidence without growing unboundedly.
DEFAULT_EXEMPLAR_IDS = 64

#: Waterfall phase names (queue vs service vs pad) the attribution
#: breakdown reports shares for.
PHASES = ("queue", "service", "pad")

_TRACE_COUNTER = itertools.count()


def mint_trace_id() -> str:
    """A fresh per-process trace id.  Global uniqueness comes from the
    replica prefix :func:`span_id_for` adds — the counter only has to
    be unique within one process."""
    return f"t{next(_TRACE_COUNTER)}"


def span_id_for(trace_id: str) -> str:
    """The globally-unique span id: ``<replica_id>/<trace_id>``.
    ``replica_id()`` is read per call (``$APNEA_UQ_REPLICA_ID`` else
    ``<hostname>-<pid>``), so two concurrent replica subprocesses can
    never collide even when their per-process counters align."""
    return f"{replica_id()}/{trace_id}"


class ExemplarTracer:
    """The at-completion sampling decision for one serve session.

    ``decide`` is called once per completed request (span) and returns
    the tuple of sampling reasons — empty means "do not emit":

    * ``"first"`` — the first completed request, unconditionally, so a
      light-load serve with ``trace_every=50`` and 3 requests still
      leaves one waterfall (the PR 17 head sampler's blind spot).
    * ``"every_n"`` — the 1-in-N baseline head stream.
    * ``"slow"`` — latency exceeded the explicit ``slow_ms`` budget.
      NEVER dropped; ``over_budget`` / ``over_budget_traced`` count it
      exactly, and their equality is the exemplar-coverage contract.
    * ``"p99"`` — tail mode only: latency at or above the bucket's
      rolling p99 (over the last :data:`P99_WINDOW` completions, once
      ``p99_min_samples`` have landed), through the bounded per-bucket
      reservoir.  Reservoir exhaustion increments the bucket's exact
      ``p99_dropped`` counter instead of emitting.

    Tail mode is armed by ``slow_ms > 0``; the head stream by
    ``trace_every > 0``; either enables the tracer.
    """

    def __init__(self, *, trace_every: int = 0, slow_ms: float = 0.0,
                 reservoir_per_bucket: int = DEFAULT_RESERVOIR_PER_BUCKET,
                 p99_min_samples: int = DEFAULT_P99_MIN_SAMPLES):
        self.trace_every = int(trace_every)
        self.slow_ms = float(slow_ms)
        self.reservoir_per_bucket = int(reservoir_per_bucket)
        self.p99_min_samples = int(p99_min_samples)
        self.completed = 0
        self.traced = 0
        self.over_budget = 0
        self.over_budget_traced = 0
        self._history: Dict[int, collections.deque] = {}
        self._p99_taken: Dict[int, int] = {}
        self._p99_dropped: Dict[int, int] = {}
        self._exemplars: collections.deque = collections.deque(
            maxlen=DEFAULT_EXEMPLAR_IDS)

    @property
    def enabled(self) -> bool:
        return self.trace_every > 0 or self.slow_ms > 0

    def decide(self, *, bucket: int, latency_s: float,
               span_id: str) -> Tuple[str, ...]:
        """The at-completion verdict for one span; advances the rolling
        state either way.  The span's latency joins the bucket history
        AFTER the p99 test — a request must not dilute the very tail it
        is being judged against."""
        if not self.enabled:
            return ()
        reasons: List[str] = []
        if self.completed == 0:
            reasons.append("first")
        elif (self.trace_every > 0
                and self.completed % self.trace_every == 0):
            reasons.append("every_n")
        if self.slow_ms > 0:
            bucket = int(bucket)
            lat_ms = float(latency_s) * 1e3
            hist = self._history.get(bucket)
            if hist is None:
                hist = self._history[bucket] = collections.deque(
                    maxlen=P99_WINDOW)
            if lat_ms > self.slow_ms:
                reasons.append("slow")
                self.over_budget += 1
                self.over_budget_traced += 1
            elif (len(hist) >= self.p99_min_samples
                    and lat_ms >= float(np.percentile(
                        np.asarray(hist, np.float64), 99.0))):
                if reasons:
                    # Already emitting for another reason: tag the
                    # tail membership without spending reservoir.
                    reasons.append("p99")
                elif (self._p99_taken.get(bucket, 0)
                        < self.reservoir_per_bucket):
                    self._p99_taken[bucket] = (
                        self._p99_taken.get(bucket, 0) + 1)
                    reasons.append("p99")
                else:
                    self._p99_dropped[bucket] = (
                        self._p99_dropped.get(bucket, 0) + 1)
            hist.append(lat_ms)
        self.completed += 1
        if reasons:
            self.traced += 1
            self._exemplars.append(str(span_id))
        return tuple(reasons)

    def stats(self) -> Dict[str, Any]:
        """The sampling ledger a ``serve_slo`` snapshot carries as its
        ``trace`` field: exact counters (what completed, what emitted,
        what the reservoir dropped) plus the recent exemplar span ids
        linking the SLO line to evidence."""
        return {
            "completed": self.completed,
            "traced": self.traced,
            "trace_every": self.trace_every,
            "slow_ms": self.slow_ms,
            "over_budget": self.over_budget,
            "over_budget_traced": self.over_budget_traced,
            "p99_taken": {str(b): n for b, n
                          in sorted(self._p99_taken.items())},
            "p99_dropped": {str(b): n for b, n
                            in sorted(self._p99_dropped.items())},
            "exemplar_span_ids": list(self._exemplars),
        }


def waterfall_children(*, enqueue_t: float, dequeue_t: Optional[float],
                       first_dispatch_t: float, done_t: float,
                       end_t: float, dispatch_s: float, d2h_s: float,
                       drift_s: float = 0.0) -> List[Dict[str, Any]]:
    """The child-span list for one request waterfall: each child is
    ``{"phase", "start_s", "dur_s"}`` with starts relative to the
    request's enqueue.  ``dequeue_t`` (the pump handoff clock) may be
    missing — a request dispatched straight off the coalescer skips the
    pump/coalesce split and reports one combined coalesce child."""
    children: List[Dict[str, Any]] = []

    def child(phase: str, start: float, dur: float) -> None:
        children.append({
            "phase": phase,
            "start_s": round(max(float(start), 0.0), 6),
            "dur_s": round(max(float(dur), 0.0), 6),
        })

    queue_s = first_dispatch_t - enqueue_t
    if dequeue_t is not None:
        child("pump", 0.0, dequeue_t - enqueue_t)
        child("coalesce", dequeue_t - enqueue_t,
              first_dispatch_t - dequeue_t)
    else:
        child("coalesce", 0.0, queue_s)
    if drift_s > 0.0:
        child("drift_fold", queue_s, drift_s)
    child("dispatch", queue_s, dispatch_s)
    child("d2h", (done_t - enqueue_t) - d2h_s, d2h_s)
    child("respond", done_t - enqueue_t, end_t - done_t)
    return children


# ---------------------------------------------------------- read side --

class NoTraceTelemetry(ValueError):
    """A source carries nothing the trace assembler can join — a usage
    error (CLI exit 2), never a clean report over zero spans."""


@dataclasses.dataclass
class ReplicaTraces:
    """One replica's contribution: its sampled spans (latest run of an
    appended log) plus the final ``serve_slo``'s ``trace`` counter
    ledger when present.  ``spans`` may be empty — a torn tail or a
    replica run without tracing degrades to a partial fleet view, it
    never fails the assembly."""

    run_dir: str
    replica_id: str
    earlier_runs: int
    spans: List[Dict[str, Any]]
    trace_stats: Optional[Dict[str, Any]]


@dataclasses.dataclass
class TraceReport:
    """The merged fleet trace view: annotated spans, collision ledger,
    phase attribution at p50/p95/p99, per-replica and per-bucket
    breakdowns, and the tail verdict."""

    replicas: List[ReplicaTraces]
    spans: List[Dict[str, Any]]
    collisions: List[str]
    phases: Dict[str, Dict[str, Any]]
    per_replica: List[Dict[str, Any]]
    buckets: Dict[str, Dict[str, Any]]
    p99_latency_ms: Optional[float]
    tail_replica: Optional[str]
    tail_phase: Optional[str]
    tail_share: Optional[float]
    tail_spans: int
    tail_spans_of_leader: int
    over_budget: int
    slow_spans: int
    exemplar_coverage: Optional[float]


def replica_traces(run_dir: str) -> ReplicaTraces:
    """Read one replica's sampled spans.  Raises
    :class:`NoTraceTelemetry` only when the dir is not a telemetry run
    directory at all; a run whose trace events were torn off the tail
    still contributes whatever survived."""
    events = read_events(run_dir)
    if not events:
        raise NoTraceTelemetry(
            f"no events.jsonl events under {run_dir!r} — not a telemetry "
            f"run directory"
        )
    events, earlier = latest_run(events)
    spans: List[Dict[str, Any]] = []
    slo: Optional[Dict[str, Any]] = None
    for e in events:
        kind = e.get("kind")
        if kind == "serve_trace":
            spans.append(e)
        elif kind == "serve_slo":
            slo = e  # append-order overwrite: the LAST snapshot wins
    rid: Optional[str] = None
    for span in spans:
        if span.get("replica_id"):
            rid = str(span["replica_id"])
            break
    if rid is None and slo is not None and slo.get("replica_id"):
        rid = str(slo["replica_id"])
    if rid is None:
        rid = os.path.basename(os.path.normpath(run_dir))
    stats = slo.get("trace") if isinstance(slo, dict) else None
    return ReplicaTraces(
        run_dir=run_dir,
        replica_id=rid,
        earlier_runs=earlier,
        spans=spans,
        trace_stats=stats if isinstance(stats, dict) else None,
    )


def _span_shares(span: Dict[str, Any]) -> Optional[Dict[str, float]]:
    """Queue/service/pad fractions of one span's latency.  Pad overhead
    is the device time attributed to the pad rows the request rode with
    (``device_s * pad_rows / (windows + pad_rows)``) — the cost the
    fixed-bucket ladder pays for zero request-path compiles."""
    latency = float(span.get("latency_s") or 0.0)
    if latency <= 0.0:
        return None
    queue = max(float(span.get("queue_s") or 0.0), 0.0)
    service = max(float(span.get("service_s") or 0.0), 0.0)
    device = max(float(span.get("device_s") or 0.0), 0.0)
    pad_rows = max(float(span.get("pad_rows") or 0.0), 0.0)
    windows = max(float(span.get("windows") or 0.0), 0.0)
    rows = pad_rows + windows
    pad_s = device * (pad_rows / rows) if rows > 0 else 0.0
    return {
        "queue": min(queue / latency, 1.0),
        "service": min(service / latency, 1.0),
        "pad": min(pad_s / latency, 1.0),
    }


def _mean_shares(shares: Sequence[Dict[str, float]]) -> Dict[str, float]:
    out = {}
    for phase in PHASES:
        vals = [s[phase] for s in shares]
        out[f"{phase}_share"] = (round(float(np.mean(vals)), 4)
                                 if vals else 0.0)
    return out


def build_trace(run_dirs: Sequence[str]) -> TraceReport:
    """Merge N replica run dirs into one fleet trace report.  Spans
    join on their globally-unique ids (a duplicate id is a COLLISION
    finding, never silently merged); the attribution breakdown is over
    every span with a positive latency."""
    if not run_dirs:
        raise NoTraceTelemetry("no run directories given")
    replicas = [replica_traces(d) for d in run_dirs]
    merged: List[Dict[str, Any]] = []
    for rep in replicas:
        for span in rep.spans:
            doc = dict(span)
            doc["_replica"] = rep.replica_id
            doc["_run_dir"] = rep.run_dir
            merged.append(doc)
    if not merged:
        raise NoTraceTelemetry(
            "no serve_trace spans in any source — enable tracing on the "
            "replicas (`--trace-every N` and/or `--trace-slow-ms MS`)"
        )
    counts = collections.Counter(
        str(s.get("span_id")) for s in merged if s.get("span_id"))
    collisions = sorted(sid for sid, n in counts.items() if n > 1)
    annotated: List[Dict[str, Any]] = []
    for span in merged:
        shares = _span_shares(span)
        if shares is not None:
            span["_shares"] = shares
        annotated.append(span)
    scored = [s for s in annotated if "_shares" in s]
    latencies = np.asarray(
        [float(s["latency_s"]) for s in scored], np.float64)
    phases: Dict[str, Dict[str, Any]] = {}
    p99_thr: Optional[float] = None
    tail: List[Dict[str, Any]] = []
    if latencies.size:
        for q in (50.0, 95.0, 99.0):
            thr = float(np.percentile(latencies, q))
            subset = [s for s in scored
                      if float(s["latency_s"]) >= thr]
            row = {"latency_ms": round(thr * 1e3, 3),
                   "spans": len(subset)}
            row.update(_mean_shares([s["_shares"] for s in subset]))
            phases[f"p{int(q)}"] = row
        p99_thr = float(np.percentile(latencies, 99.0))
        tail = [s for s in scored if float(s["latency_s"]) >= p99_thr]
    # Per-replica attribution: every replica appears (even span-less
    # torn ones), tail membership against the FLEET p99.
    per_replica: List[Dict[str, Any]] = []
    for rep in replicas:
        mine = [s for s in scored if s["_replica"] == rep.replica_id]
        mine_tail = [s for s in tail if s["_replica"] == rep.replica_id]
        row: Dict[str, Any] = {
            "replica_id": rep.replica_id,
            "run_dir": rep.run_dir,
            "earlier_runs": rep.earlier_runs,
            "spans": len(rep.spans),
            "tail_spans": len(mine_tail),
            "max_latency_ms": (round(max(
                float(s["latency_s"]) for s in mine) * 1e3, 3)
                if mine else None),
        }
        row.update(_mean_shares([s["_shares"] for s in mine]))
        stats = rep.trace_stats or {}
        row["over_budget"] = (int(stats["over_budget"])
                              if "over_budget" in stats else None)
        row["over_budget_traced"] = (int(stats["over_budget_traced"])
                                     if "over_budget_traced" in stats
                                     else None)
        per_replica.append(row)
    buckets: Dict[str, Dict[str, Any]] = {}
    for key in sorted({int(s.get("bucket") or 0) for s in scored}):
        mine = [s for s in scored if int(s.get("bucket") or 0) == key]
        mine_tail = [s for s in tail if int(s.get("bucket") or 0) == key]
        row = {"spans": len(mine), "tail_spans": len(mine_tail)}
        row.update(_mean_shares([s["_shares"] for s in mine]))
        buckets[str(key)] = row
    # The tail verdict: the replica holding the most p99-tail spans
    # (max tail latency breaks ties), then its dominant phase.
    tail_replica = tail_phase = None
    tail_share: Optional[float] = None
    leader_tail = 0
    if tail:
        by_replica: Dict[str, List[Dict[str, Any]]] = {}
        for s in tail:
            by_replica.setdefault(s["_replica"], []).append(s)
        tail_replica = max(
            by_replica,
            key=lambda rid: (len(by_replica[rid]),
                             max(float(s["latency_s"])
                                 for s in by_replica[rid])))
        leader = by_replica[tail_replica]
        leader_tail = len(leader)
        leader_shares = _mean_shares([s["_shares"] for s in leader])
        tail_phase = max(
            PHASES, key=lambda p: leader_shares[f"{p}_share"])
        tail_share = leader_shares[f"{tail_phase}_share"]
    # Exemplar coverage: slow-tagged spans FOUND IN THE EVENT STREAMS
    # against the exact over-budget counters — a torn-off exemplar
    # shows up as coverage < 1.0, which is the point.
    slow_spans = sum(
        1 for s in annotated
        if "slow" in (s.get("sampled_for") or ()))
    ledgers = [r.trace_stats for r in replicas if r.trace_stats]
    over_budget = sum(int(st.get("over_budget", 0)) for st in ledgers)
    tail_mode = any(float(st.get("slow_ms", 0.0) or 0.0) > 0.0
                    for st in ledgers)
    if over_budget > 0:
        coverage: Optional[float] = round(
            min(slow_spans / over_budget, 1.0), 4)
    elif tail_mode:
        coverage = 1.0
    else:
        coverage = None
    return TraceReport(
        replicas=replicas,
        spans=annotated,
        collisions=collisions,
        phases=phases,
        per_replica=per_replica,
        buckets=buckets,
        p99_latency_ms=(round(p99_thr * 1e3, 3)
                        if p99_thr is not None else None),
        tail_replica=tail_replica,
        tail_phase=tail_phase,
        tail_share=tail_share,
        tail_spans=len(tail),
        tail_spans_of_leader=leader_tail,
        over_budget=over_budget,
        slow_spans=slow_spans,
        exemplar_coverage=coverage,
    )


# ------------------------------------------------------------- read out --

def _span_data(span: Dict[str, Any]) -> Dict[str, Any]:
    doc = {k: v for k, v in span.items()
           if not k.startswith("_") and k not in ("seq", "ts", "stage",
                                                  "kind")}
    shares = span.get("_shares")
    if shares is not None:
        for phase in PHASES:
            doc[f"{phase}_share"] = round(shares[phase], 4)
    doc["replica"] = span.get("_replica")
    return doc


def trace_data(report: TraceReport) -> Dict[str, Any]:
    """The report as one JSON-able document — the ``trace_report``
    registry artifact body and the ``--json`` extra payload."""
    p99 = report.phases.get("p99", {})
    return {
        "sources": [r.run_dir for r in report.replicas],
        "replicas": report.per_replica,
        "spans": [_span_data(s) for s in report.spans],
        "span_count": len(report.spans),
        "collisions": list(report.collisions),
        "phases": report.phases,
        "buckets": report.buckets,
        "p99_latency_ms": report.p99_latency_ms,
        "queue_share_p99": p99.get("queue_share"),
        "service_share_p99": p99.get("service_share"),
        "pad_share_p99": p99.get("pad_share"),
        "tail_replica": report.tail_replica,
        "tail_phase": report.tail_phase,
        "tail_share": report.tail_share,
        "tail_spans": report.tail_spans,
        "over_budget": report.over_budget,
        "slow_spans": report.slow_spans,
        "exemplar_coverage": report.exemplar_coverage,
    }


def _pct(value: Optional[float]) -> str:
    return "-" if value is None else f"{100 * value:.1f}%"


def _waterfall_line(span: Dict[str, Any]) -> List[str]:
    reasons = ",".join(span.get("sampled_for") or ()) or "head"
    lat_ms = round(float(span.get("latency_s") or 0.0) * 1e3, 3)
    lines = [
        f"  {span.get('span_id')} [{span.get('request_id')}] "
        f"{span.get('windows')} win / {span.get('batches')} batch(es) "
        f"b{span.get('bucket')} pad {span.get('pad_rows')}: "
        f"{lat_ms}ms ({reasons}, {span.get('label')})"
    ]
    for child in span.get("children") or ():
        lines.append(
            f"    {child.get('phase'):<12} +{child.get('start_s')}s "
            f"for {child.get('dur_s')}s")
    return lines


def render_trace(report: TraceReport) -> str:
    """The human view: fleet span summary, phase attribution at
    p50/p95/p99, per-replica table, the tail verdict, and the slowest
    exemplar waterfalls."""
    lines: List[str] = []
    lines.append(
        f"trace: {len(report.replicas)} replica(s), "
        f"{len(report.spans)} span(s), "
        f"{len(report.collisions)} collision(s)")
    if report.phases:
        lines.append("phase attribution (share of latency, mean over "
                     "spans at/above the percentile):")
        for name in ("p50", "p95", "p99"):
            row = report.phases.get(name)
            if row is None:
                continue
            lines.append(
                f"  {name}: >= {row['latency_ms']}ms "
                f"({row['spans']} span(s))  "
                f"queue {_pct(row['queue_share'])}  "
                f"service {_pct(row['service_share'])}  "
                f"pad {_pct(row['pad_share'])}")
    if report.tail_replica is not None:
        lines.append(
            f"tail: {report.tail_replica} {report.tail_phase} phase "
            f"dominates the fleet p99 ({_pct(report.tail_share)} of "
            f"latency, {report.tail_spans_of_leader}/{report.tail_spans} "
            f"tail span(s))")
    if report.exemplar_coverage is not None:
        lines.append(
            f"exemplar coverage {report.exemplar_coverage} "
            f"({report.over_budget} over-budget request(s), "
            f"{report.slow_spans} slow exemplar(s))")
    lines.append("")
    lines.append("replicas:")
    lines.append(
        f"  {'replica':<24} {'spans':>6} {'tail':>5} {'queue':>7} "
        f"{'service':>8} {'pad':>7} {'over_budget':>12}  flags")
    for row in report.per_replica:
        flags = []
        if (row["over_budget"] is not None
                and row["over_budget_traced"] is not None
                and row["over_budget_traced"] < row["over_budget"]):
            flags.append("MISSING-EXEMPLARS")
        if not row["spans"]:
            flags.append("no-spans")
        if row["earlier_runs"]:
            flags.append(f"+{row['earlier_runs']} earlier run(s)")
        over = (row["over_budget"] if row["over_budget"] is not None
                else "-")
        lines.append(
            f"  {row['replica_id']:<24} {row['spans']:>6} "
            f"{row['tail_spans']:>5} {_pct(row['queue_share']):>7} "
            f"{_pct(row['service_share']):>8} {_pct(row['pad_share']):>7} "
            f"{over:>12}  {' '.join(flags) if flags else '-'}")
    if report.buckets:
        lines.append("")
        lines.append("buckets:")
        lines.append(f"  {'bucket':>6} {'spans':>6} {'tail':>5} "
                     f"{'queue':>7} {'service':>8} {'pad':>7}")
        for key, row in report.buckets.items():
            lines.append(
                f"  {key:>6} {row['spans']:>6} {row['tail_spans']:>5} "
                f"{_pct(row['queue_share']):>7} "
                f"{_pct(row['service_share']):>8} "
                f"{_pct(row['pad_share']):>7}")
    slowest = sorted(
        (s for s in report.spans if s.get("latency_s") is not None),
        key=lambda s: float(s["latency_s"]), reverse=True)[:3]
    if slowest:
        lines.append("")
        lines.append("slowest waterfalls:")
        for span in slowest:
            lines.extend(_waterfall_line(span))
    return "\n".join(lines)


def trace_findings(report: TraceReport):
    """Collisions, missing exemplars, and a tail-dominating replica as
    lint-engine findings for the shared reporters (text / ``--json`` /
    ``--format gha``)."""
    from apnea_uq_tpu.lint.engine import Finding

    findings = []
    for sid in report.collisions:
        mine = [s for s in report.spans if str(s.get("span_id")) == sid]
        owners = sorted({str(s.get("_run_dir", "")) for s in mine})
        findings.append(Finding(
            rule="trace-span-collision", severity="error",
            path=owners[0] if owners else "", line=0,
            message=(
                f"span id {sid!r} appears {len(mine)} times across "
                f"{', '.join(owners)} — span ids must be globally "
                f"unique (<replica_id>/<trace_id>)"),
        ))
    if (report.exemplar_coverage is not None
            and report.exemplar_coverage < 1.0):
        findings.append(Finding(
            rule="trace-missing-exemplar", severity="error",
            path=report.replicas[0].run_dir if report.replicas else "",
            line=0,
            message=(
                f"exemplar coverage {report.exemplar_coverage}: only "
                f"{report.slow_spans} of {report.over_budget} "
                f"over-budget request(s) carry a waterfall — the event "
                f"stream lost exemplars (torn tail / killed replica?)"),
        ))
    if (len(report.replicas) > 1 and report.tail_replica is not None
            and report.tail_share is not None
            and report.tail_share >= 0.5
            and report.tail_spans > 0
            and report.tail_spans_of_leader * 2 >= report.tail_spans):
        run_dir = next(
            (r.run_dir for r in report.replicas
             if r.replica_id == report.tail_replica), "")
        findings.append(Finding(
            rule="trace-tail-dominated", severity="error",
            path=run_dir, line=0,
            message=(
                f"replica {report.tail_replica!r} {report.tail_phase} "
                f"phase dominates the fleet p99 tail "
                f"({report.tail_spans_of_leader}/{report.tail_spans} "
                f"tail span(s), {report.tail_share} of their latency) "
                f"— fix that replica/phase first"),
        ))
    return findings


def trace_result(report: TraceReport):
    """The findings wrapped as a :class:`LintResult` for
    ``emit_result`` — ``files_scanned`` counts replicas."""
    from apnea_uq_tpu.lint.engine import LintResult

    return LintResult(
        findings=trace_findings(report),
        files_scanned=len(report.replicas),
        rules_run=("trace-span-collision", "trace-missing-exemplar",
                   "trace-tail-dominated"),
        scanned_paths=tuple(r.run_dir for r in report.replicas),
    )


def record_trace(report: TraceReport, out_dir: str) -> None:
    """Persist the report into ``out_dir``: the ``trace_report``
    registry artifact (atomic JSON + manifest row) plus one
    ``trace_report`` event in ``<out_dir>/events.jsonl`` — making the
    report dir a first-class source for ``telemetry compare`` and
    ``telemetry trend`` through the same run-dir seam every other
    gateable kind rides."""
    from apnea_uq_tpu.data import registry as registry_mod

    data = trace_data(report)
    registry = registry_mod.ArtifactRegistry(out_dir)
    # apnea-lint: disable=artifact-never-consumed -- end product: the trace report is read by compare/trend through the report dir's event stream (load_source) and by operators, not by a registry-loading pipeline stage
    registry.save_json(registry_mod.TRACE_REPORT, data)
    p99 = report.phases.get("p99", {})
    with append_events(out_dir) as run_log:
        run_log.event(
            "trace_report",
            replicas=len(report.replicas),
            sources=[r.run_dir for r in report.replicas],
            spans=len(report.spans),
            collisions=len(report.collisions),
            p99_latency_ms=report.p99_latency_ms,
            queue_share_p99=p99.get("queue_share"),
            service_share_p99=p99.get("service_share"),
            pad_share_p99=p99.get("pad_share"),
            tail_replica=report.tail_replica,
            tail_phase=report.tail_phase,
            tail_share=report.tail_share,
            tail_spans=report.tail_spans,
            over_budget=report.over_budget,
            slow_spans=report.slow_spans,
            exemplar_coverage=report.exemplar_coverage,
            phases=report.phases,
            buckets=report.buckets,
        )
