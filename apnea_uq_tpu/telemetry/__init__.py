"""Run-scoped observability: structured events, step metrics, traces.

The layer every stage reports through (ISSUE 2 tentpole):

- :mod:`~apnea_uq_tpu.telemetry.logging_shim` — ``log()``, the central
  replacement for bare ``print`` in library code;
- :mod:`~apnea_uq_tpu.telemetry.runlog` — ``RunLog``/``start_run``: the
  per-run JSONL event stream (run metadata, stages, epochs, errors);
- :mod:`~apnea_uq_tpu.telemetry.steps` — ``StepMetrics``: dispatch- vs
  device-time per step, throughput, XLA recompile counters;
- :mod:`~apnea_uq_tpu.telemetry.trace` — ``annotate``/``named_scope``
  profiler labels for the train/UQ hot paths;
- :mod:`~apnea_uq_tpu.telemetry.summarize` — the
  ``apnea-uq telemetry summarize`` renderer.

Only the logging shim is imported eagerly (the CLI needs ``log`` before
anything heavy loads); everything touching jax resolves lazily via PEP
562 so ``--help`` stays instant.
"""

from __future__ import annotations

from apnea_uq_tpu.telemetry.logging_shim import get_logger, log

_LAZY = {
    "RunLog": "runlog",
    "start_run": "runlog",
    "current_run": "runlog",
    "read_events": "runlog",
    "default_run_dir": "runlog",
    "config_hash": "runlog",
    "device_topology": "runlog",
    "SCHEMA_VERSION": "runlog",
    "EVENTS_FILENAME": "runlog",
    "StepMetrics": "steps",
    "StepRecord": "steps",
    "compile_counts": "steps",
    "install_compile_listener": "steps",
    "annotate": "trace",
    "named_scope": "trace",
    "summarize_run": "summarize",
    "summarize_events": "summarize",
}

__all__ = ["log", "get_logger"] + sorted(_LAZY)


def __getattr__(name: str):
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(
        importlib.import_module(f"apnea_uq_tpu.telemetry.{module}"), name
    )
