"""Run-scoped observability: structured events, step metrics, traces.

The layer every stage reports through (ISSUE 2 tentpole):

- :mod:`~apnea_uq_tpu.telemetry.logging_shim` — ``log()``, the central
  replacement for bare ``print`` in library code;
- :mod:`~apnea_uq_tpu.telemetry.runlog` — ``RunLog``/``start_run``: the
  per-run JSONL event stream (run metadata, stages, epochs, errors);
- :mod:`~apnea_uq_tpu.telemetry.steps` — ``StepMetrics``: dispatch- vs
  device-time per step, throughput, XLA recompile counters;
- :mod:`~apnea_uq_tpu.telemetry.trace` — ``annotate``/``named_scope``
  profiler labels for the train/UQ hot paths;
- :mod:`~apnea_uq_tpu.telemetry.summarize` — the
  ``apnea-uq telemetry summarize`` renderer;
- :mod:`~apnea_uq_tpu.telemetry.memory` — compiled HBM accounting
  (``memory_profile`` events) + device memory snapshots (ISSUE 3);
- :mod:`~apnea_uq_tpu.telemetry.profiler` — bounded programmatic trace
  capture with warmup skip and a step budget (``profile_captured``);
- :mod:`~apnea_uq_tpu.telemetry.compare` — the metric regression
  comparator behind ``apnea-uq telemetry compare``;
- :mod:`~apnea_uq_tpu.telemetry.watch` — the hardware-watch evidence
  autopilot behind ``apnea-uq telemetry watch``;
- :mod:`~apnea_uq_tpu.telemetry.trend` — the cross-run perf-trajectory
  ledger behind ``apnea-uq telemetry trend``;
- :mod:`~apnea_uq_tpu.telemetry.quality` — the model-quality stream:
  ``quality_metrics`` emission for the eval drivers and the gate
  behind ``apnea-uq quality check``;
- :mod:`~apnea_uq_tpu.telemetry.digest` — the mergeable log-spaced
  latency histogram every ``serve_slo`` event carries (fleet
  percentiles from event streams alone);
- :mod:`~apnea_uq_tpu.telemetry.fleet` — the cross-replica SLO
  aggregator behind ``apnea-uq telemetry fleet``.

Only the logging shim is imported eagerly (the CLI needs ``log`` before
anything heavy loads); everything touching jax resolves lazily via PEP
562 so ``--help`` stays instant.
"""

from __future__ import annotations

from apnea_uq_tpu.telemetry.logging_shim import get_logger, log

_LAZY = {
    "RunLog": "runlog",
    "start_run": "runlog",
    "current_run": "runlog",
    "read_events": "runlog",
    "default_run_dir": "runlog",
    "config_hash": "runlog",
    "device_topology": "runlog",
    "SCHEMA_VERSION": "runlog",
    "EVENTS_FILENAME": "runlog",
    "StepMetrics": "steps",
    "StepRecord": "steps",
    "compile_counts": "steps",
    "install_compile_listener": "steps",
    "annotate": "trace",
    "named_scope": "trace",
    "summarize_run": "summarize",
    "summarize_events": "summarize",
    "summarize_data": "summarize",
    "record_jit_memory": "memory",
    "snapshot_device_memory": "memory",
    "device_hbm_limit": "memory",
    "TraceSession": "profiler",
    "maybe_profile": "profiler",
    "compare_paths": "compare",
    "render_comparison": "compare",
    # NOT "watch": that name IS the submodule — lazily exporting the
    # watch() function under it would make telemetry.watch flip between
    # a function (first access) and the module (after any submodule
    # import binds the parent attribute).  Call telemetry.watch.watch().
    "wait_for_green": "watch",
    "probe_backend": "watch",
    "build_trajectory": "trend",
    "render_trajectory": "trend",
    "trajectory_data": "trend",
    "emit_quality_metrics": "quality",
    "check_run": "quality",
    "LatencyDigest": "digest",
    "merge_payloads": "digest",
    "replica_id": "runlog",
    "build_rollup": "fleet",
    "render_fleet": "fleet",
}

__all__ = ["log", "get_logger"] + sorted(_LAZY)


# Submodules reachable as lazy attributes (telemetry.watch.watch(...)
# works without a prior explicit submodule import, and the name always
# resolves to the module — never to a same-named function inside it).
_SUBMODULES = frozenset({
    "runlog", "steps", "trace", "summarize", "memory", "profiler",
    "compare", "watch", "trend", "quality", "logging_shim", "digest",
    "fleet",
})


def __getattr__(name: str):
    import importlib

    module = _LAZY.get(name)
    if module is not None:
        return getattr(
            importlib.import_module(f"apnea_uq_tpu.telemetry.{module}"),
            name,
        )
    if name in _SUBMODULES:
        return importlib.import_module(f"apnea_uq_tpu.telemetry.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
