"""Metric regression comparator (ISSUE 3 tentpole, piece 3).

BENCH_r01..r05 were compared by eye; this module makes the comparison a
tool with an exit code, so bench/CI can *gate* on it:

    apnea-uq telemetry compare BASELINE CANDIDATE [--threshold-pct 5]

``BASELINE``/``CANDIDATE`` are each either a bench capture (a
``BENCH_r*.json`` file — the driver-schema line bench.py prints) or a
telemetry run directory (``events.jsonl``; the latest run of an appended
log).  Metrics are extracted into one namespace, deltas computed per
metric, and a delta that *worsens* past its threshold is a regression:
the comparator (and the CLI) report nonzero.

Direction is inferred from the metric's unit — throughput (``.../sec``)
higher-is-better, seconds/bytes lower-is-better — so a faster candidate
never "regresses" by being different.  Unknown units default to
higher-is-better; override per metric with ``--metric-direction
NAME=lower`` (``per_metric_direction`` programmatically) when that is
wrong — without it, an unknown-unit lower-is-better metric could never
regress.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional

from apnea_uq_tpu.telemetry.runlog import (EVENTS_FILENAME, latest_run,
                                           read_events)

DEFAULT_THRESHOLD_PCT = 5.0


class NoComparableMetrics(ValueError):
    """A source parsed cleanly but carries nothing gateable — e.g. a
    ``bench_error`` capture (a run that never measured anything).  The
    CLI maps this to the usage-error exit code (2), distinct from exit 1
    = a real regression: a gate fed an error capture must fail the
    *invocation*, never report a clean pass over zero metrics."""


@dataclasses.dataclass
class Metric:
    """One comparable scalar: name, value, direction."""

    name: str
    value: float
    unit: Optional[str] = None
    higher_better: bool = True


@dataclasses.dataclass
class MetricDelta:
    """Baseline-vs-candidate outcome for one metric."""

    name: str
    baseline: float
    candidate: float
    unit: Optional[str]
    higher_better: bool
    threshold_pct: float
    delta_pct: float        # signed (candidate - baseline) / |baseline|
    regressed: bool

    @property
    def improved(self) -> bool:
        if self.delta_pct == 0.0:
            return False
        return (self.delta_pct > 0) == self.higher_better


@dataclasses.dataclass
class Comparison:
    baseline_path: str
    candidate_path: str
    deltas: List[MetricDelta]
    only_in_baseline: List[str]
    only_in_candidate: List[str]

    @property
    def regressions(self) -> List[MetricDelta]:
        return [d for d in self.deltas if d.regressed]


def unit_direction(unit: Optional[str]) -> bool:
    """higher-is-better for throughput-like units, lower for cost-like.

    A trailing ``_s`` (``load_s``, ``predict_s``) is a seconds suffix,
    not a per-second rate — rates always carry a slash (``windows/s``) —
    and ``byte`` anywhere (``bytes``, ``rss_bytes``, ``d2h_bytes``)
    means volume; both gate lower-is-better."""
    u = (unit or "").lower()
    if "/sec" in u or "/s" in u or u in ("ratio", "speedup", "x"):
        return True
    if (u in ("seconds", "s", "ms", "milliseconds", "flops", "flop")
            or u.endswith("_s") or "byte" in u):
        return False
    return True


def _metrics_from_bench_doc(doc: Dict[str, Any]) -> Dict[str, Metric]:
    """The driver-schema blocks of one BENCH_r*.json line: primary +
    optional secondary metric values and their vs_baseline speedups.
    Two wrappers are unwrapped first: a BENCH_PROGRESS_FILE capture's
    ``{"primary": {...}, "secondary": {...}}``, and the watch/driver
    capture shape that stores the parsed stdout line under ``"parsed"``
    (the repo's archived BENCH_r*.json files) — in both cases the
    wrapped blocks must gate exactly like the printed line (extracting
    only part of a wrapper would silently pass a regressed metric).

    ``bench_error`` records (the give-up line every failed capture
    prints: value 0, unit "error") are NOT metrics — comparing two of
    them would "pass" on the constant zero — so they are skipped here
    and surface upstream as :class:`NoComparableMetrics`."""
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if isinstance(doc.get("primary"), dict):
        merged = dict(doc["primary"])
        if "secondary" not in merged and isinstance(doc.get("secondary"),
                                                    dict):
            merged["secondary"] = doc["secondary"]
        doc = merged
    out: Dict[str, Metric] = {}

    def block(d: Dict[str, Any]) -> None:
        name = d.get("metric")
        if not name or d.get("value") is None:
            return
        if name == "bench_error" or d.get("unit") == "error":
            return
        unit = d.get("unit")
        out[name] = Metric(name, float(d["value"]), unit,
                           unit_direction(unit))
        if isinstance(d.get("vs_baseline"), (int, float)):
            out[f"{name}.vs_baseline"] = Metric(
                f"{name}.vs_baseline", float(d["vs_baseline"]), "ratio",
                True,
            )

    block(doc)
    if isinstance(doc.get("secondary"), dict):
        block(doc["secondary"])
    return out


def _metrics_from_events(events: List[Any]) -> Dict[str, Metric]:
    """Comparable scalars of one run's event log: bench metric mirrors,
    eval throughput, the compiled-HBM peaks (so a footprint regression
    gates like a speed regression), and the compile-cost roll-up —
    ``compile.total_s`` (seconds spent acquiring programs,
    lower-is-better) and ``compile.hit_ratio`` (store/cache hits over
    acquisitions, higher-is-better) — so a cold-start regression (a
    label falling out of the program store, a cache key churn) gates
    like any other."""
    out: Dict[str, Metric] = {}
    compile_n = compile_hits = 0
    compile_total = 0.0
    for e in events:
        kind = e.get("kind")
        if kind == "bench_metric" and e.get("value") is not None:
            name = e.get("metric") or f"bench.{e.get('role', '?')}"
            unit = e.get("unit")
            out[name] = Metric(name, float(e["value"]), unit,
                               unit_direction(unit))
            if isinstance(e.get("vs_baseline"), (int, float)):
                out[f"{name}.vs_baseline"] = Metric(
                    f"{name}.vs_baseline", float(e["vs_baseline"]),
                    "ratio", True,
                )
        elif kind == "bench_throughput" and e.get("windows_per_s"):
            name = f"{e.get('metric', 'bench')}.windows_per_s"
            out[name] = Metric(name, float(e["windows_per_s"]),
                               "windows/sec", True)
        elif kind == "eval_predict":
            if e.get("windows_per_s"):
                name = f"eval.{e.get('label', '?')}.windows_per_s"
                out[name] = Metric(name, float(e["windows_per_s"]),
                                   "windows/sec", True)
            if e.get("d2h_bytes") is not None:
                # Estimated device->host result volume of the predict —
                # the fused-reduction win (bytes: lower is better), so a
                # future change that silently re-inflates the transfer
                # gates like any other regression.
                name = f"eval.{e.get('label', '?')}.d2h_bytes"
                out[name] = Metric(name, float(e["d2h_bytes"]), "bytes",
                                   False)
        elif kind == "data_load":
            # Stage-start artifact-load cost (registry data_load events):
            # seconds to first batch and peak host RSS, both
            # lower-is-better per artifact key — so a store falling back
            # to whole-set materialization gates like a speed regression.
            if e.get("load_s") is not None:
                name = f"data.{e.get('key', '?')}.load_s"
                out[name] = Metric(name, float(e["load_s"]), "load_s",
                                   False)
            if e.get("rss_bytes") is not None:
                name = f"data.{e.get('key', '?')}.rss_bytes"
                out[name] = Metric(name, float(e["rss_bytes"]),
                                   "rss_bytes", False)
        elif kind == "memory_profile" and e.get("peak_bytes") is not None:
            name = f"memory.{e.get('label', '?')}.peak_bytes"
            out[name] = Metric(name, float(e["peak_bytes"]), "bytes",
                               False)
        elif kind == "program_audit":
            # The IR-level cost of one zoo program (`apnea-uq audit
            # --run-dir`): FLOPs and bytes accessed, both lower-is-better
            # per label — a refactor that inflates a hot-path program's
            # compute or traffic gates like any other regression.
            if e.get("flops") is not None:
                name = f"audit.{e.get('label', '?')}.flops"
                out[name] = Metric(name, float(e["flops"]), "flops",
                                   False)
            if e.get("bytes_accessed") is not None:
                name = f"audit.{e.get('label', '?')}.bytes_accessed"
                out[name] = Metric(name, float(e["bytes_accessed"]),
                                   "bytes", False)
        elif kind == "compile_event":
            compile_n += 1
            compile_hits += 1 if e.get("hit") else 0
            compile_total += ((e.get("lower_s") or 0.0)
                              + (e.get("compile_s") or 0.0))
    if compile_n:
        out["compile.total_s"] = Metric(
            "compile.total_s", round(compile_total, 6), "seconds", False)
        out["compile.hit_ratio"] = Metric(
            "compile.hit_ratio", round(compile_hits / compile_n, 4),
            "ratio", True)
    return out


def load_metrics(path: str) -> Dict[str, Metric]:
    """Extract the comparable metrics of ``path`` — a BENCH_r*.json file
    or a telemetry run directory (latest run of an appended log)."""
    if os.path.isdir(path):
        events = read_events(path)
        if not events:
            raise FileNotFoundError(
                f"no {EVENTS_FILENAME} events under {path!r} — not a "
                f"telemetry run directory"
            )
        events, _earlier = latest_run(events)
        metrics = _metrics_from_events(events)
        if not metrics:
            # Same contract as the bench-JSON branch: a source with
            # nothing gateable is a usage error, never a clean pass
            # (nor a spurious exit-1 "regression" from the no-common-
            # metrics check downstream).
            raise NoComparableMetrics(
                f"no comparable metrics in source {path!r}: the run's "
                f"events carry no bench/eval throughput, d2h, "
                f"memory-peak, compile-cost, data-load, or "
                f"program-audit metrics"
            )
        return metrics
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path!r} is not a bench JSON object")
    metrics = _metrics_from_bench_doc(doc)
    if not metrics:
        inner = doc.get("parsed") if isinstance(doc.get("parsed"),
                                                dict) else doc
        detail = (
            "its payload is a bench_error record — the capture failed "
            "before measuring anything"
            if isinstance(inner, dict)
            and (inner.get("metric") == "bench_error"
                 or inner.get("unit") == "error")
            else "expected driver-schema 'metric' + 'value' blocks"
        )
        raise NoComparableMetrics(
            f"no comparable metrics in source {path!r}: {detail}"
        )
    return metrics


def compare_metrics(
    baseline: Dict[str, Metric],
    candidate: Dict[str, Metric],
    *,
    threshold_pct: float = DEFAULT_THRESHOLD_PCT,
    per_metric_threshold: Optional[Dict[str, float]] = None,
    per_metric_direction: Optional[Dict[str, bool]] = None,
) -> List[MetricDelta]:
    """Deltas for every metric present on both sides.  A regression is a
    direction-adjusted worsening beyond the metric's threshold; an
    exactly-zero baseline compares by sign only (any worsening from zero
    regresses, since percent change is undefined).
    ``per_metric_direction`` maps a metric name to higher-is-better,
    overriding the unit inference where it guessed wrong."""
    per_metric_threshold = per_metric_threshold or {}
    per_metric_direction = per_metric_direction or {}
    deltas = []
    for name in sorted(set(baseline) & set(candidate)):
        b, c = baseline[name], candidate[name]
        thr = float(per_metric_threshold.get(name, threshold_pct))
        higher_better = bool(per_metric_direction.get(name,
                                                      b.higher_better))
        if b.value == 0.0:
            delta_pct = float("inf") if c.value != 0.0 else 0.0
            worsened = (c.value < 0.0) if higher_better else (c.value > 0.0)
            regressed = worsened
        else:
            delta_pct = 100.0 * (c.value - b.value) / abs(b.value)
            worsening = -delta_pct if higher_better else delta_pct
            regressed = worsening > thr
        deltas.append(MetricDelta(
            name=name, baseline=b.value, candidate=c.value, unit=b.unit,
            higher_better=higher_better, threshold_pct=thr,
            delta_pct=delta_pct, regressed=regressed,
        ))
    return deltas


def compare_paths(
    baseline_path: str,
    candidate_path: str,
    *,
    threshold_pct: float = DEFAULT_THRESHOLD_PCT,
    per_metric_threshold: Optional[Dict[str, float]] = None,
    per_metric_direction: Optional[Dict[str, bool]] = None,
) -> Comparison:
    baseline = load_metrics(baseline_path)
    candidate = load_metrics(candidate_path)
    common = set(baseline) & set(candidate)
    if not common:
        raise ValueError(
            f"no common metrics between {baseline_path!r} "
            f"({sorted(baseline)}) and {candidate_path!r} "
            f"({sorted(candidate)})"
        )
    return Comparison(
        baseline_path=baseline_path,
        candidate_path=candidate_path,
        deltas=compare_metrics(
            baseline, candidate, threshold_pct=threshold_pct,
            per_metric_threshold=per_metric_threshold,
            per_metric_direction=per_metric_direction,
        ),
        only_in_baseline=sorted(set(baseline) - common),
        only_in_candidate=sorted(set(candidate) - common),
    )


def comparison_data(comparison: Comparison) -> Dict[str, Any]:
    """The comparison as one JSON-able document (the ``--json`` shape)."""
    deltas = []
    for d in comparison.deltas:
        doc = dataclasses.asdict(d)
        if doc["delta_pct"] == float("inf"):
            # Undefined percent (zero baseline): JSON has no Infinity —
            # json.dumps would emit a bare `Infinity` token no strict
            # parser accepts.  null = "no percentage"; `regressed`
            # still carries the verdict.
            doc["delta_pct"] = None
        deltas.append(doc)
    return {
        "baseline": comparison.baseline_path,
        "candidate": comparison.candidate_path,
        "regressed": bool(comparison.regressions),
        "deltas": deltas,
        "only_in_baseline": comparison.only_in_baseline,
        "only_in_candidate": comparison.only_in_candidate,
    }


def render_comparison(comparison: Comparison) -> str:
    """Human-readable delta table, regressions flagged."""
    lines = [
        f"baseline:  {comparison.baseline_path}",
        f"candidate: {comparison.candidate_path}",
        "",
    ]
    header = ("metric", "baseline", "candidate", "delta", "threshold",
              "verdict")
    # +4: every row's name carries a " (^)" / " (v)" direction suffix.
    name_w = max([len(header[0])]
                 + [len(d.name) + 4 for d in comparison.deltas])
    fmt = (f"{{:<{name_w}}}  {{:>12}}  {{:>12}}  {{:>9}}  {{:>9}}  "
           f"{{:<10}}")
    lines.append(fmt.format(*header))
    for d in comparison.deltas:
        if d.delta_pct == float("inf"):
            delta = "inf"
        else:
            delta = f"{d.delta_pct:+.1f}%"
        verdict = ("REGRESSED" if d.regressed
                   else "improved" if d.improved else "ok")
        arrow = "^" if d.higher_better else "v"
        lines.append(fmt.format(
            f"{d.name} ({arrow})",
            f"{d.baseline:g}", f"{d.candidate:g}", delta,
            f"{d.threshold_pct:g}%", verdict,
        ))
    for label, names in (("only in baseline", comparison.only_in_baseline),
                         ("only in candidate", comparison.only_in_candidate)):
        if names:
            lines.append("")
            lines.append(f"{label}: {', '.join(names)}")
    lines.append("")
    n_reg = len(comparison.regressions)
    lines.append(f"regressions: {n_reg or 'none'}")
    return "\n".join(lines)
