"""Metric regression comparator (ISSUE 3 tentpole, piece 3).

BENCH_r01..r05 were compared by eye; this module makes the comparison a
tool with an exit code, so bench/CI can *gate* on it:

    apnea-uq telemetry compare BASELINE CANDIDATE [--threshold-pct 5]

``BASELINE``/``CANDIDATE`` are each either a bench capture (a
``BENCH_r*.json`` file — the driver-schema line bench.py prints, v1 or
the schema-v2 per-block payload) or a telemetry run directory
(``events.jsonl``; the latest run of an appended log).  Metrics are
extracted into one namespace, deltas computed per metric, and a delta
that *worsens* past its threshold is a regression: the comparator (and
the CLI) report nonzero.

Direction is inferred from the metric's unit — throughput (``.../sec``)
higher-is-better, seconds/bytes lower-is-better — so a faster candidate
never "regresses" by being different.  Unknown units default to
higher-is-better; override per metric with ``--metric-direction
NAME=lower`` (``per_metric_direction`` programmatically) when that is
wrong — without it, an unknown-unit lower-is-better metric could never
regress.

CPU-proxy captures (``proxy: true`` in the v2 payload — the bench ran
its backend-independent blocks off-TPU) gate only *relative* and
host-side metrics across the proxy boundary: when exactly one side of a
comparison is a proxy capture, backend-bound absolute metrics (device
throughput, device wall-clock, compiled HBM peaks, compile seconds) are
dropped from the comparison and listed as skipped, never compared
cross-backend.  Two proxy captures (or two device captures) compare
everything.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from apnea_uq_tpu.telemetry.runlog import (EVENTS_FILENAME, latest_run,
                                           read_events)

DEFAULT_THRESHOLD_PCT = 5.0


class NoComparableMetrics(ValueError):
    """A comparison has nothing gateable — a source parsed cleanly but
    carries no metrics (e.g. a ``bench_error`` capture: a run that never
    measured anything), or no metric exists on both sides (including
    after the proxy-boundary backend-bound drop).  The CLI maps this to
    the usage-error exit code (2), distinct from exit 1 = a real
    regression: a gate that cannot compare a single block must fail the
    *invocation*, never report a clean pass over zero metrics."""


@dataclasses.dataclass
class Metric:
    """One comparable scalar: name, value, direction.

    ``backend_bound`` marks absolute numbers tied to the backend OR
    operating point that produced them — device throughput/wall-clock,
    compiled HBM peaks, compile seconds, and the shape-derived volumes
    and host-load costs (CPU-proxy mode shrinks the shape knobs, so
    those absolutes differ by orders of magnitude from a device round's
    purely from the shrink).  They are dropped when one side of a
    comparison is a CPU-proxy capture and the other is not; relative
    ratios and fixed-shape facts stay comparable."""

    name: str
    value: float
    unit: Optional[str] = None
    higher_better: bool = True
    backend_bound: bool = False


@dataclasses.dataclass
class MetricDelta:
    """Baseline-vs-candidate outcome for one metric."""

    name: str
    baseline: float
    candidate: float
    unit: Optional[str]
    higher_better: bool
    threshold_pct: float
    delta_pct: float        # signed (candidate - baseline) / |baseline|
    regressed: bool

    @property
    def improved(self) -> bool:
        if self.delta_pct == 0.0:
            return False
        return (self.delta_pct > 0) == self.higher_better


@dataclasses.dataclass
class Comparison:
    baseline_path: str
    candidate_path: str
    deltas: List[MetricDelta]
    only_in_baseline: List[str]
    only_in_candidate: List[str]
    baseline_proxy: bool = False
    candidate_proxy: bool = False
    # Backend-bound absolute metrics refused across the proxy boundary
    # (one side ran off-TPU in CPU-proxy mode): listed, never compared.
    skipped_backend_bound: List[str] = dataclasses.field(
        default_factory=list)

    @property
    def regressions(self) -> List[MetricDelta]:
        return [d for d in self.deltas if d.regressed]


def unit_direction(unit: Optional[str]) -> bool:
    """higher-is-better for throughput-like units, lower for cost-like.

    A trailing ``_s`` (``load_s``, ``predict_s``) is a seconds suffix,
    not a per-second rate — rates always carry a slash (``windows/s``) —
    and ``byte`` anywhere (``bytes``, ``rss_bytes``, ``d2h_bytes``)
    means volume; both gate lower-is-better."""
    u = (unit or "").lower()
    if "/sec" in u or "/s" in u or u in ("ratio", "speedup", "x"):
        return True
    if (u in ("seconds", "s", "ms", "milliseconds", "flops", "flop")
            or u.endswith("_s") or "byte" in u):
        return False
    return True


# Name tokens that mark a metric lower-is-better regardless of unit:
# calibration error scores (ECE/MCE/Brier) and drift statistics
# (PSI, KS) are scores where zero is perfect — a candidate could
# otherwise only ever "improve" by miscalibrating harder.  The serving
# SLO family (ISSUE 15) rides the same table: latency percentiles
# (p50/p95/p99), queue waits, and pad waste are all costs — without the
# tokens, `serve.pad_waste` (unit "ratio") would gate higher-is-better
# and a coalescer that pads every bucket to 99% waste could only ever
# "improve".
_LOWER_BETTER_NAME_TOKENS = frozenset(
    {"ece", "mce", "brier", "psi", "ks", "drift",
     "p50", "p95", "p99", "latency", "wait", "waste"})


def name_direction(name: Optional[str]) -> Optional[bool]:
    """Direction inferred from the metric NAME alone: ``ece``/``mce``/
    ``brier``/``psi``/``ks``/``drift`` — plus the serving SLO tokens
    ``p50``/``p95``/``p99``/``latency``/``wait``/``waste`` — appearing
    as a name token (``quality.CNN_MCD.ece``, ``serve.p99_ms``,
    ``serve.queue_wait_mean_s``) is lower-is-better without needing
    ``--metric-direction``; None when the name says nothing and the
    unit inference should decide."""
    tokens = re.findall(r"[a-z0-9]+", (name or "").lower())
    if any(t in _LOWER_BETTER_NAME_TOKENS for t in tokens):
        return False
    return None


def metric_direction(name: Optional[str], unit: Optional[str]) -> bool:
    """higher-is-better for a metric, combining the name inference
    (authoritative when it fires) with the unit inference."""
    named = name_direction(name)
    return unit_direction(unit) if named is None else named


# Headline records that are payload envelopes, not measurements: the
# give-up line (bench_error), and the v2 block-count headlines a proxy
# or mcd-less capture prints in the driver schema so its stdout line
# stays parseable (value = ok-block count, unit "blocks").
_HEADLINE_NON_METRICS = ("bench_error", "bench_cpu_proxy", "bench_partial")


def _normalize_bench_doc(
    doc: Dict[str, Any],
) -> Tuple[Dict[str, Any], bool]:
    """Unwrap the capture shapes onto one headline document and pull the
    v2 ``proxy`` flag.  Wrappers handled: the watch/driver capture that
    stores the parsed stdout line under ``"parsed"`` (the archived
    BENCH_r*.json files) and the BENCH_PROGRESS_FILE capture's
    ``{"primary": ..., "secondary": ...}`` — in both cases the wrapped
    blocks must gate exactly like the printed line (extracting only part
    of a wrapper would silently pass a regressed metric)."""
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    proxy = bool(doc.get("proxy"))
    if isinstance(doc.get("primary"), dict):
        merged = dict(doc["primary"])
        if "secondary" not in merged and isinstance(doc.get("secondary"),
                                                    dict):
            merged["secondary"] = doc["secondary"]
        doc = merged
    return doc, proxy


def _metrics_from_context(ctx: Any) -> Dict[str, Metric]:
    """Gateable scalars of a capture's ``context`` blocks (v1 context or
    the v2 payload's block values): the relative ratios and host-side
    costs every round carries regardless of headline, so proxy rounds
    and device rounds share a comparable namespace.  A block degraded to
    an ``{"error": ...}`` field contributes nothing."""
    out: Dict[str, Metric] = {}
    if not isinstance(ctx, dict):
        return out

    def ok(name: str) -> Optional[Dict[str, Any]]:
        v = ctx.get(name)
        return v if isinstance(v, dict) and "error" not in v else None

    def put(name: str, value: Any, unit: str, higher: bool,
            *, bound: bool = False) -> None:
        if isinstance(value, (int, float)):
            out[name] = Metric(name, float(value), unit, higher,
                               backend_bound=bound)

    put("mcd.achieved_tflops", ctx.get("achieved_tflops"), "tflops/s",
        True, bound=True)
    boot = ok("bootstrap_b100_m293k")
    if boot:
        put("bootstrap.speedup", boot.get("speedup"), "ratio", True)
    streamed = ok("streamed_overhead")
    if streamed:
        # Streamed-vs-in-HBM overhead: the ratio GROWING is the
        # regression, so lower-is-better despite the ratio unit.
        put("streamed.mcd_streamed_vs_inhbm",
            streamed.get("mcd_streamed_vs_inhbm"), "ratio", False)
        put("streamed.de10_streamed_vs_inhbm",
            streamed.get("de10_streamed_vs_inhbm"), "ratio", False)
    kernel = ok("mcd_kernel")
    if kernel:
        # XLA-vs-Pallas and f32-vs-bf16 speedups at the fixed smoke
        # operating point: relative, backend-INDEPENDENT ratios (like
        # bootstrap.speedup) — deliberately NOT bound, so they gate
        # across the CPU-proxy boundary whenever both rounds carry them.
        put("mcd_kernel.xla_vs_pallas", kernel.get("xla_vs_pallas"),
            "ratio", True)
        put("mcd_kernel.f32_vs_bf16", kernel.get("f32_vs_bf16"),
            "ratio", True)
    de_kernel = ok("de_kernel")
    if de_kernel:
        # The DE twin of the mcd_kernel ratios: same fixed operating
        # point, member sweep instead of MC passes — unbound relatives.
        put("de_kernel.xla_vs_pallas", de_kernel.get("xla_vs_pallas"),
            "ratio", True)
        put("de_kernel.f32_vs_bf16", de_kernel.get("f32_vs_bf16"),
            "ratio", True)
    autotune = ok("autotune")
    if autotune:
        # Best measured default-vs-winner speedup across the swept
        # labels (ops/autotune.py): ~1.0 on CPU fallback bodies, >1.0
        # when a non-default tile geometry wins on device — the
        # relative metric engine-default flips are arbitrated on.
        put("autotune.best_vs_default", autotune.get("best_vs_default"),
            "ratio", True)
    fused = ok("fused_reduction")
    if fused:
        put("fused.fused_vs_full", fused.get("fused_vs_full"), "ratio",
            False)
        # Shape-derived volumes: meaningful only among rounds at the
        # same operating point -> bound.
        put("fused.d2h_bytes_fused", fused.get("d2h_bytes_fused"),
            "bytes", False, bound=True)
        put("fused.d2h_bytes_full", fused.get("d2h_bytes_full"),
            "bytes", False, bound=True)
    comp = ok("compile")
    if comp:
        put("compile.cold_vs_warm_total", comp.get("cold_vs_warm_total"),
            "ratio", True)
        put("compile.cold_vs_warm_wall", comp.get("cold_vs_warm_wall"),
            "ratio", True)
    audit = ok("program_audit")
    if audit:
        # Same audit.<label>.flops namespace the run-dir program_audit
        # events gate under — the two sources stay comparable.
        for label, facts in sorted((audit.get("programs") or {}).items()):
            if isinstance(facts, dict):
                put(f"audit.{label}.flops", facts.get("flops"), "flops",
                    False)
    data = ok("data_plane")
    if data:
        # Host-side but row-count-dependent: a proxy round loads 256
        # rows where a device round loads 32768, so the absolute
        # seconds are operating-point-bound; the per-row rates stay
        # roughly comparable but are kept bound too (page-cache and
        # shard-count effects do not scale linearly).
        put("data_plane.npz_load_s", data.get("npz_load_s"), "load_s",
            False, bound=True)
        put("data_plane.store_open_s", data.get("store_open_s"),
            "load_s", False, bound=True)
        put("data_plane.store_stream_s", data.get("store_stream_s"),
            "load_s", False, bound=True)
        put("data_plane.npz_rows_per_s", data.get("npz_rows_per_s"),
            "rows/sec", True, bound=True)
        put("data_plane.store_rows_per_s", data.get("store_rows_per_s"),
            "rows/sec", True, bound=True)
    d2h = ok("d2h_accounting")
    if d2h:
        put("d2h.bytes_full", d2h.get("d2h_bytes_full"), "bytes", False,
            bound=True)
        put("d2h.bytes_fused", d2h.get("d2h_bytes_fused"), "bytes",
            False, bound=True)
    serve = ok("serve")
    if serve:
        # Online serving SLO block (bench.py bench_serve, ISSUE 15):
        # the load-generated serve loop's latency percentiles,
        # throughput, and mean queue wait are absolute numbers of the
        # backend (and arrival pattern) that produced them -> bound.
        # pad_waste — the padded fraction of all dispatched bucket rows
        # — is a pure coalescing-efficiency ratio, backend-independent,
        # so a coalescer regression gates even across the CPU-proxy
        # boundary.
        put("serve.p50_ms", serve.get("p50_ms"), "ms", False, bound=True)
        put("serve.p95_ms", serve.get("p95_ms"), "ms", False, bound=True)
        put("serve.p99_ms", serve.get("p99_ms"), "ms", False, bound=True)
        put("serve.windows_per_s", serve.get("windows_per_s"),
            "windows/sec", True, bound=True)
        put("serve.queue_wait_mean_s", serve.get("queue_wait_mean_s"),
            "seconds", False, bound=True)
        put("serve.pad_waste", serve.get("pad_waste"), "ratio", False)
    capacity = ok("capacity")
    if capacity:
        # Capacity/saturation sweep (bench.py bench_capacity, ISSUE 18):
        # the knee — the first offered rate where the fleet stops
        # keeping up (achieved/offered < threshold or p99 over budget)
        # — and the peak achieved throughput are absolutes of the
        # backend + replica count -> bound.  The achieved/offered ratio
        # at the lowest offered rate is a pure keeping-up relative:
        # every backend must hold ~1.0 at its own easiest cell, so it
        # gates across the proxy boundary.
        put("capacity.knee_offered_rps", capacity.get("knee_offered_rps"),
            "req/sec", True, bound=True)
        put("capacity.peak_windows_per_s",
            capacity.get("peak_windows_per_s"), "windows/sec", True,
            bound=True)
        cells = capacity.get("cells") or []
        if cells and isinstance(cells[0], dict):
            put("capacity.base_achieved_ratio",
                cells[0].get("achieved_ratio"), "ratio", True)
    qual = ok("quality")
    if qual:
        # Model-quality proof block (bench.py bench_quality): fixed-seed
        # synthetic calibration + drift self/shift scores — backend-
        # INDEPENDENT (host NumPy at a pinned operating point), so a
        # quality-tooling regression gates across the CPU-proxy
        # boundary.  The error scores and the self-drift score are
        # lower-is-better; the shifted-cohort PSI is the detector's
        # sensitivity — SHRINKING is the regression, so higher-better.
        put("quality.ece", qual.get("ece"), "ece", False)
        put("quality.mce", qual.get("mce"), "mce", False)
        put("quality.brier", qual.get("brier"), "brier", False)
        put("quality.self_max_psi", qual.get("self_max_psi"), "psi",
            False)
        put("quality.shifted_max_psi", qual.get("shifted_max_psi"),
            "psi", True)
    return out


def _metrics_from_bench_doc(doc: Dict[str, Any]) -> Dict[str, Metric]:
    """The gateable metrics of one BENCH_r*.json capture: the
    driver-schema primary + optional secondary metric values (marked
    backend-bound) and their vs_baseline speedups, plus the relative /
    host-side context metrics (:func:`_metrics_from_context`).

    ``bench_error`` records (the give-up line every failed capture
    prints: value 0, unit "error") and the v2 block-count headlines are
    NOT metrics — comparing two of them would "pass" on a constant — so
    they are skipped here; a capture with nothing else surfaces upstream
    as :class:`NoComparableMetrics`."""
    doc, _proxy = _normalize_bench_doc(doc)
    out: Dict[str, Metric] = {}

    def block(d: Dict[str, Any]) -> None:
        name = d.get("metric")
        if not name or d.get("value") is None:
            return
        if name in _HEADLINE_NON_METRICS or d.get("unit") in ("error",
                                                              "blocks"):
            return
        unit = d.get("unit")
        # The headline value is an absolute device measurement
        # (windows/sec/chip, train wall-clock): backend-bound.
        out[name] = Metric(name, float(d["value"]), unit,
                           metric_direction(name, unit),
                           backend_bound=True)
        if isinstance(d.get("vs_baseline"), (int, float)):
            out[f"{name}.vs_baseline"] = Metric(
                f"{name}.vs_baseline", float(d["vs_baseline"]), "ratio",
                True,
            )

    block(doc)
    if isinstance(doc.get("secondary"), dict):
        block(doc["secondary"])
        sec_ctx = doc["secondary"].get("context")
        if isinstance(sec_ctx, dict):
            out.update(_metrics_from_context(sec_ctx))
    out.update(_metrics_from_context(doc.get("context")))
    return out


def bench_doc_proxy(doc: Dict[str, Any]) -> bool:
    """Whether a bench capture document is a CPU-proxy round (one
    unwrap path — :func:`_normalize_bench_doc` — so the flag can never
    diverge from what the metric extraction saw)."""
    _doc, proxy = _normalize_bench_doc(doc)
    return proxy


def _metrics_from_events(events: List[Any]) -> Dict[str, Metric]:
    """Comparable scalars of one run's event log: bench metric mirrors,
    eval throughput, the compiled-HBM peaks (so a footprint regression
    gates like a speed regression), the serving SLO summary (the last
    ``serve_slo`` snapshot of an `apnea-uq serve`/`score` run), and the
    compile-cost roll-up —
    ``compile.total_s`` (seconds spent acquiring programs,
    lower-is-better) and ``compile.hit_ratio`` (store/cache hits over
    acquisitions, higher-is-better) — so a cold-start regression (a
    label falling out of the program store, a cache key churn) gates
    like any other."""
    out: Dict[str, Metric] = {}
    compile_n = compile_hits = 0
    compile_total = 0.0
    for e in events:
        kind = e.get("kind")
        if kind == "bench_metric" and e.get("value") is not None:
            name = e.get("metric") or f"bench.{e.get('role', '?')}"
            unit = e.get("unit")
            out[name] = Metric(name, float(e["value"]), unit,
                               metric_direction(name, unit),
                               backend_bound=True)
            if isinstance(e.get("vs_baseline"), (int, float)):
                out[f"{name}.vs_baseline"] = Metric(
                    f"{name}.vs_baseline", float(e["vs_baseline"]),
                    "ratio", True,
                )
        elif kind == "bench_throughput" and e.get("windows_per_s"):
            name = f"{e.get('metric', 'bench')}.windows_per_s"
            out[name] = Metric(name, float(e["windows_per_s"]),
                               "windows/sec", True, backend_bound=True)
        elif kind == "eval_predict":
            if e.get("windows_per_s"):
                name = f"eval.{e.get('label', '?')}.windows_per_s"
                out[name] = Metric(name, float(e["windows_per_s"]),
                                   "windows/sec", True,
                                   backend_bound=True)
            if e.get("d2h_bytes") is not None:
                # Estimated device->host result volume of the predict —
                # the fused-reduction win (bytes: lower is better), so a
                # future change that silently re-inflates the transfer
                # gates like any other regression.
                name = f"eval.{e.get('label', '?')}.d2h_bytes"
                out[name] = Metric(name, float(e["d2h_bytes"]), "bytes",
                                   False)
        elif kind == "data_load":
            # Stage-start artifact-load cost (registry data_load events):
            # seconds to first batch and peak host RSS, both
            # lower-is-better per artifact key — so a store falling back
            # to whole-set materialization gates like a speed regression.
            # Row-count-dependent absolutes -> operating-point-bound
            # (a proxy bench run loads smoke-shape sets).
            if e.get("load_s") is not None:
                name = f"data.{e.get('key', '?')}.load_s"
                out[name] = Metric(name, float(e["load_s"]), "load_s",
                                   False, backend_bound=True)
            if e.get("rss_bytes") is not None:
                name = f"data.{e.get('key', '?')}.rss_bytes"
                out[name] = Metric(name, float(e["rss_bytes"]),
                                   "rss_bytes", False,
                                   backend_bound=True)
        elif kind == "memory_profile" and e.get("peak_bytes") is not None:
            # Compiled for a specific backend: cross-backend comparison
            # of the peak is meaningless -> backend_bound.
            name = f"memory.{e.get('label', '?')}.peak_bytes"
            out[name] = Metric(name, float(e["peak_bytes"]), "bytes",
                               False, backend_bound=True)
        elif kind == "program_audit":
            # The IR-level cost of one zoo program (`apnea-uq audit
            # --run-dir`): FLOPs and bytes accessed, both lower-is-better
            # per label — a refactor that inflates a hot-path program's
            # compute or traffic gates like any other regression.
            if e.get("flops") is not None:
                name = f"audit.{e.get('label', '?')}.flops"
                out[name] = Metric(name, float(e["flops"]), "flops",
                                   False)
            if e.get("bytes_accessed") is not None:
                name = f"audit.{e.get('label', '?')}.bytes_accessed"
                out[name] = Metric(name, float(e["bytes_accessed"]),
                                   "bytes", False)
        elif kind == "topo_program":
            # The topology sweep's per-(program, topology) cell
            # (`apnea-uq topo --run-dir`): modeled cross-host DCN bytes
            # and the compiled per-device memory estimate, both
            # lower-is-better.  The cross-host model is structural math
            # over canonical shapes -> comparable anywhere; the
            # per-device estimate comes from a backend compile ->
            # backend-bound like the memory_profile peaks.
            label = e.get("label", "?")
            topology = e.get("topology", "?")
            if e.get("cross_host_bytes") is not None:
                name = f"topo.{label}.{topology}.cross_host_bytes"
                out[name] = Metric(name, float(e["cross_host_bytes"]),
                                   "bytes", False)
            if e.get("per_device_bytes") is not None:
                name = f"topo.{label}.{topology}.per_device_bytes"
                out[name] = Metric(name, float(e["per_device_bytes"]),
                                   "bytes", False, backend_bound=True)
        elif kind == "quality_metrics":
            # Model-quality scalars of one eval run (telemetry/quality.py
            # emits them from run_{mcd,de}_analysis): ECE/MCE/Brier per
            # run label, all lower-is-better by name inference.  Quality
            # is a property of the MODEL + data, not the backend — these
            # deliberately stay unbound so they gate across the
            # CPU-proxy boundary.
            label = e.get("label", "?")
            for field in ("ece", "mce", "brier"):
                if e.get(field) is not None:
                    name = f"quality.{label}.{field}"
                    out[name] = Metric(name, float(e[field]), field,
                                       metric_direction(name, field))
        elif kind == "drift_fingerprint":
            # Input-drift scores vs the frozen quality_baseline: PSI/KS
            # growing is the regression.  Backend-independent like the
            # quality scalars.
            label = e.get("label", "?")
            for field, unit in (("max_psi", "psi"), ("max_ks", "ks")):
                if e.get(field) is not None:
                    name = f"drift.{label}.{field}"
                    out[name] = Metric(name, float(e[field]), unit,
                                       metric_direction(name, unit))
        elif kind == "serve_drift":
            # Online drift verdicts of the serving path (serving/drift.py,
            # ISSUE 17): the rolling-fingerprint PSI/KS per tenant, scored
            # against the same frozen quality_baseline as the batch-eval
            # drift_fingerprint events.  Input drift is a property of the
            # TRAFFIC, not the backend -> unbound, gates across the
            # CPU-proxy boundary; append-order overwrite keeps each
            # tenant's LAST (usually final=True) score.
            tenant = e.get("tenant", "?")
            for field, unit in (("max_psi", "psi"), ("max_ks", "ks")):
                if e.get(field) is not None:
                    name = f"serve_drift.{tenant}.{field}"
                    out[name] = Metric(name, float(e[field]), unit,
                                       metric_direction(name, unit))
        elif kind == "serve_slo":
            # Online serving SLO snapshot (serving/slo.py, ISSUE 15).
            # Snapshots are cumulative and the append-order overwrite
            # means the LAST serve_slo of the run — the session summary
            # — is the one that gates.  Latency percentiles, throughput,
            # and queue wait are absolutes of the serving backend ->
            # backend-bound; pad_waste is the coalescer's efficiency
            # ratio and gates everywhere.
            for field, unit, higher, bound in (
                    ("p50_ms", "ms", False, True),
                    ("p95_ms", "ms", False, True),
                    ("p99_ms", "ms", False, True),
                    ("windows_per_s", "windows/sec", True, True),
                    ("queue_wait_mean_s", "seconds", False, True),
                    ("pad_waste", "ratio", False, False)):
                if e.get(field) is not None:
                    name = f"serve.{field}"
                    out[name] = Metric(name, float(e[field]), unit,
                                       higher, backend_bound=bound)
        elif kind == "fleet_rollup":
            # Cross-replica SLO rollup (telemetry/fleet.py, ISSUE 18):
            # digest-merged fleet percentiles and summed throughput are
            # absolutes of the serving backend -> bound; pad_waste and
            # the imbalance ratio (max/median replica p99 — a pure
            # load-balance property) gate across the proxy boundary.
            # imbalance_ratio needs the explicit direction: its "ratio"
            # unit would otherwise infer higher-is-better, and no
            # lower-better name token matches it.
            for field, unit, higher, bound in (
                    ("p50_ms", "ms", False, True),
                    ("p95_ms", "ms", False, True),
                    ("p99_ms", "ms", False, True),
                    ("windows_per_s", "windows/sec", True, True),
                    ("requests_per_s", "req/sec", True, True),
                    ("queue_wait_mean_s", "seconds", False, True),
                    ("pad_waste", "ratio", False, False),
                    ("imbalance_ratio", "ratio", False, False)):
                if e.get(field) is not None:
                    name = f"fleet.{field}"
                    out[name] = Metric(name, float(e[field]), unit,
                                       higher, backend_bound=bound)
        elif kind == "trace_report":
            # Cross-replica trace analysis (telemetry/spans.py, ISSUE
            # 20): phase shares of tail latency and exemplar coverage
            # are pure properties of the traffic/schedule, not the
            # backend -> unbound ratios that cross the CPU-proxy
            # boundary.  queue_share growing means the tail is waiting,
            # not computing (a coalescer/load regression);
            # service_share is the healthy complement; coverage
            # dropping below 1.0 means over-budget requests lost their
            # waterfalls.  All need explicit directions: "ratio" would
            # infer higher-is-better across the board.
            for field, higher in (
                    ("queue_share_p99", False),
                    ("service_share_p99", True),
                    ("pad_share_p99", False),
                    ("exemplar_coverage", True)):
                if e.get(field) is not None:
                    name = f"trace.{field}"
                    out[name] = Metric(name, float(e[field]), "ratio",
                                       higher, backend_bound=False)
        elif kind == "compile_event":
            compile_n += 1
            compile_hits += 1 if e.get("hit") else 0
            compile_total += ((e.get("lower_s") or 0.0)
                              + (e.get("compile_s") or 0.0))
    if compile_n:
        out["compile.total_s"] = Metric(
            "compile.total_s", round(compile_total, 6), "seconds", False,
            backend_bound=True)
        out["compile.hit_ratio"] = Metric(
            "compile.hit_ratio", round(compile_hits / compile_n, 4),
            "ratio", True)
    return out


def load_source(
    path: str,
) -> Tuple[Dict[str, Metric], Dict[str, Any]]:
    """Extract the comparable metrics of ``path`` — a BENCH_r*.json file
    or a telemetry run directory (latest run of an appended log) — plus
    source facts: ``{"kind": "bench"|"run_dir", "proxy": bool}``."""
    if os.path.isdir(path):
        events = read_events(path)
        if not events:
            raise FileNotFoundError(
                f"no {EVENTS_FILENAME} events under {path!r} — not a "
                f"telemetry run directory"
            )
        events, _earlier = latest_run(events)
        metrics = _metrics_from_events(events)
        # A proxy bench run stamps its mode into its own run dir
        # (bench_mode event), so run-directory sources carry the same
        # proxy provenance as the JSON payload — without it, a proxy
        # run dir would compare its smoke-shape absolutes straight
        # against device numbers.
        dir_proxy = any(e.get("kind") == "bench_mode" and e.get("proxy")
                        for e in events)
        if not metrics:
            # Same contract as the bench-JSON branch: a source with
            # nothing gateable is a usage error, never a clean pass
            # (nor a spurious exit-1 "regression" from the no-common-
            # metrics check downstream).
            raise NoComparableMetrics(
                f"no comparable metrics in source {path!r}: the run's "
                f"events carry no bench/eval throughput, d2h, "
                f"memory-peak, compile-cost, data-load, program-audit, "
                f"topology, quality, drift, serve-drift, serve-SLO, "
                f"fleet-rollup, or trace-report metrics"
            )
        return metrics, {"kind": "run_dir", "proxy": dir_proxy}
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path!r} is not a bench JSON object")
    metrics = _metrics_from_bench_doc(doc)
    if not metrics:
        inner = doc.get("parsed") if isinstance(doc.get("parsed"),
                                                dict) else doc
        detail = (
            "its payload is a bench_error record — the capture failed "
            "before measuring anything"
            if isinstance(inner, dict)
            and (inner.get("metric") == "bench_error"
                 or inner.get("unit") == "error")
            else "expected driver-schema 'metric' + 'value' blocks"
        )
        raise NoComparableMetrics(
            f"no comparable metrics in source {path!r}: {detail}"
        )
    return metrics, {"kind": "bench", "proxy": bench_doc_proxy(doc)}


def load_metrics(path: str) -> Dict[str, Metric]:
    """Extract the comparable metrics of ``path`` — a BENCH_r*.json file
    or a telemetry run directory (latest run of an appended log)."""
    metrics, _info = load_source(path)
    return metrics


def compare_metrics(
    baseline: Dict[str, Metric],
    candidate: Dict[str, Metric],
    *,
    threshold_pct: float = DEFAULT_THRESHOLD_PCT,
    per_metric_threshold: Optional[Dict[str, float]] = None,
    per_metric_direction: Optional[Dict[str, bool]] = None,
) -> List[MetricDelta]:
    """Deltas for every metric present on both sides.  A regression is a
    direction-adjusted worsening beyond the metric's threshold; an
    exactly-zero baseline compares by sign only (any worsening from zero
    regresses, since percent change is undefined).
    ``per_metric_direction`` maps a metric name to higher-is-better,
    overriding the unit inference where it guessed wrong."""
    per_metric_threshold = per_metric_threshold or {}
    per_metric_direction = per_metric_direction or {}
    deltas = []
    for name in sorted(set(baseline) & set(candidate)):
        b, c = baseline[name], candidate[name]
        thr = float(per_metric_threshold.get(name, threshold_pct))
        higher_better = bool(per_metric_direction.get(name,
                                                      b.higher_better))
        if b.value == 0.0:
            delta_pct = float("inf") if c.value != 0.0 else 0.0
            worsened = (c.value < 0.0) if higher_better else (c.value > 0.0)
            regressed = worsened
        else:
            delta_pct = 100.0 * (c.value - b.value) / abs(b.value)
            worsening = -delta_pct if higher_better else delta_pct
            regressed = worsening > thr
        deltas.append(MetricDelta(
            name=name, baseline=b.value, candidate=c.value, unit=b.unit,
            higher_better=higher_better, threshold_pct=thr,
            delta_pct=delta_pct, regressed=regressed,
        ))
    return deltas


def compare_paths(
    baseline_path: str,
    candidate_path: str,
    *,
    threshold_pct: float = DEFAULT_THRESHOLD_PCT,
    per_metric_threshold: Optional[Dict[str, float]] = None,
    per_metric_direction: Optional[Dict[str, bool]] = None,
) -> Comparison:
    baseline, b_info = load_source(baseline_path)
    candidate, c_info = load_source(candidate_path)
    skipped: List[str] = []
    if b_info["proxy"] != c_info["proxy"]:
        # One side is a CPU-proxy capture: absolute backend-bound
        # numbers must not be compared cross-backend — drop them from
        # BOTH sides and report them as skipped.
        merged = dict(candidate)
        merged.update(baseline)
        skipped = sorted(n for n, m in merged.items() if m.backend_bound)
        baseline = {n: m for n, m in baseline.items()
                    if not m.backend_bound}
        candidate = {n: m for n, m in candidate.items()
                     if not m.backend_bound}
    common = set(baseline) & set(candidate)
    if not common:
        proxy_note = (
            " (after dropping backend-bound metrics "
            f"{skipped} across the proxy boundary)" if skipped else ""
        )
        raise NoComparableMetrics(
            f"no common metrics between {baseline_path!r} "
            f"({sorted(baseline)}) and {candidate_path!r} "
            f"({sorted(candidate)}){proxy_note}"
        )
    return Comparison(
        baseline_path=baseline_path,
        candidate_path=candidate_path,
        deltas=compare_metrics(
            baseline, candidate, threshold_pct=threshold_pct,
            per_metric_threshold=per_metric_threshold,
            per_metric_direction=per_metric_direction,
        ),
        only_in_baseline=sorted(set(baseline) - common),
        only_in_candidate=sorted(set(candidate) - common),
        baseline_proxy=b_info["proxy"],
        candidate_proxy=c_info["proxy"],
        skipped_backend_bound=skipped,
    )


def comparison_data(comparison: Comparison) -> Dict[str, Any]:
    """The comparison as one JSON-able document (the ``--json`` shape)."""
    deltas = []
    for d in comparison.deltas:
        doc = dataclasses.asdict(d)
        if doc["delta_pct"] == float("inf"):
            # Undefined percent (zero baseline): JSON has no Infinity —
            # json.dumps would emit a bare `Infinity` token no strict
            # parser accepts.  null = "no percentage"; `regressed`
            # still carries the verdict.
            doc["delta_pct"] = None
        deltas.append(doc)
    return {
        "baseline": comparison.baseline_path,
        "candidate": comparison.candidate_path,
        "baseline_proxy": comparison.baseline_proxy,
        "candidate_proxy": comparison.candidate_proxy,
        "regressed": bool(comparison.regressions),
        "deltas": deltas,
        "only_in_baseline": comparison.only_in_baseline,
        "only_in_candidate": comparison.only_in_candidate,
        "skipped_backend_bound": comparison.skipped_backend_bound,
    }


def render_comparison(comparison: Comparison) -> str:
    """Human-readable delta table, regressions flagged."""
    lines = [
        f"baseline:  {comparison.baseline_path}"
        + (" [cpu-proxy]" if comparison.baseline_proxy else ""),
        f"candidate: {comparison.candidate_path}"
        + (" [cpu-proxy]" if comparison.candidate_proxy else ""),
        "",
    ]
    header = ("metric", "baseline", "candidate", "delta", "threshold",
              "verdict")
    # +4: every row's name carries a " (^)" / " (v)" direction suffix.
    name_w = max([len(header[0])]
                 + [len(d.name) + 4 for d in comparison.deltas])
    fmt = (f"{{:<{name_w}}}  {{:>12}}  {{:>12}}  {{:>9}}  {{:>9}}  "
           f"{{:<10}}")
    lines.append(fmt.format(*header))
    for d in comparison.deltas:
        if d.delta_pct == float("inf"):
            delta = "inf"
        else:
            delta = f"{d.delta_pct:+.1f}%"
        verdict = ("REGRESSED" if d.regressed
                   else "improved" if d.improved else "ok")
        arrow = "^" if d.higher_better else "v"
        lines.append(fmt.format(
            f"{d.name} ({arrow})",
            f"{d.baseline:g}", f"{d.candidate:g}", delta,
            f"{d.threshold_pct:g}%", verdict,
        ))
    for label, names in (("only in baseline", comparison.only_in_baseline),
                         ("only in candidate", comparison.only_in_candidate)):
        if names:
            lines.append("")
            lines.append(f"{label}: {', '.join(names)}")
    if comparison.skipped_backend_bound:
        lines.append("")
        lines.append(
            "skipped (backend-bound, refused across the cpu-proxy "
            "boundary): " + ", ".join(comparison.skipped_backend_bound))
    lines.append("")
    n_reg = len(comparison.regressions)
    lines.append(f"regressions: {n_reg or 'none'}")
    return "\n".join(lines)
