"""The ``apnea-uq flow`` subcommand.

``apnea-uq flow [paths ...] [--json | --format gha] [--rule NAME ...]
[--manifest PATH] [--update-manifest] [--update-docs [--docs PATH]]`` —
exits 0 when every finding is suppressed-with-justification, 1 on
unsuppressed findings, 2 on usage errors (including a missing manifest:
run ``--update-manifest`` once to record the golden graph).  With no
paths it analyzes the installed package plus the repo's ``bench.py`` —
the exact scope the tier-1 gate (``tests/test_flow.py``) runs.

Kept jax-free end to end, like ``apnea-uq lint``: the handler imports
only the flow package, the lint engine, and the shared reporters.
"""

from __future__ import annotations

from apnea_uq_tpu.telemetry import log


def cmd_flow(args) -> int:
    from apnea_uq_tpu.flow import graph_rows, run_flow
    from apnea_uq_tpu.flow.manifest import (
        load_manifest, merge_rows, write_manifest,
    )
    from apnea_uq_tpu.lint.cli import default_paths
    from apnea_uq_tpu.lint.engine import default_repo_root
    from apnea_uq_tpu.lint.report import emit_result, resolve_format
    from apnea_uq_tpu.telemetry.logging_shim import narration_to_stderr

    fmt = resolve_format(args)

    def narrate(message: str) -> None:
        # In --json mode stdout is one machine-readable document;
        # manifest/docs progress lines go to stderr so `flow --json |
        # jq .` parses without stripping (the audit CLI's contract).
        if fmt == "json":
            with narration_to_stderr():
                log(message)
        else:
            log(message)

    paths = args.paths or default_paths()
    try:
        manifest = load_manifest(args.manifest)
    except ValueError as e:
        log(f"apnea-uq flow: {e}")
        raise SystemExit(2)

    # First pass without the manifest diff: extraction + every other
    # rule.  The drift rule needs the effective rows, which depend on
    # --update-manifest (merged rows drive the diff NOW; the file is
    # written only after the rules pass, so a failed update never
    # mutates the golden manifest — the audit CLI's pattern).
    try:
        if args.update_manifest:
            prior = manifest

            def effective_rows(graph):
                # Partial scope extracts a partial graph: keep the prior
                # rows rather than blessing an incomplete extraction.
                return (merge_rows(graph) if graph.full_scope
                        else (prior or {}))

            result, graph = run_flow(paths, rules=args.rule or None,
                                     manifest=effective_rows)
            rows = effective_rows(graph)
        else:
            if manifest is None:
                log(f"apnea-uq flow: no manifest at {args.manifest!r} — "
                    f"run `apnea-uq flow --update-manifest` once to "
                    f"record the golden dataflow rows")
                raise SystemExit(2)
            result, graph = run_flow(paths, rules=args.rule or None,
                                     manifest=manifest)
    except (FileNotFoundError, ValueError, SyntaxError) as e:
        # Usage errors exit 2, distinct from exit 1 = real findings.
        log(f"apnea-uq flow: {e}")
        raise SystemExit(2)

    if args.update_manifest:
        if result.unsuppressed:
            narrate("flow: manifest NOT updated — unsuppressed finding(s) "
                    "remain; fix (or suppress) them, then re-run "
                    "--update-manifest")
        elif not graph.full_scope:
            narrate("flow: manifest NOT updated — the scan scope lacks "
                    "the registry catalog and/or cli/stages.py, so the "
                    "extracted graph is partial")
        else:
            write_manifest(args.manifest, rows)
            narrate(f"manifest -> {args.manifest} ({len(rows)} row(s))")

    if args.update_docs:
        import os

        from apnea_uq_tpu.flow.pipedoc import render_pipeline_doc
        from apnea_uq_tpu.utils.io import atomic_write_text

        docs_path = args.docs or os.path.join(
            default_repo_root(paths), "docs", "PIPELINE.md")
        if not graph.full_scope:
            narrate("flow: docs NOT updated — partial scan scope")
        else:
            os.makedirs(os.path.dirname(os.path.abspath(docs_path)),
                        exist_ok=True)
            atomic_write_text(docs_path, render_pipeline_doc(graph))
            narrate(f"pipeline doc -> {docs_path}")

    emit_result(result, fmt, json_extra={
        "artifacts": graph_rows(graph) if graph.full_scope else {},
    })
    return 1 if result.unsuppressed else 0


def register(sub) -> None:
    """Attach the ``flow`` subcommand to the CLI's subparser registry."""
    from apnea_uq_tpu.flow.manifest import DEFAULT_MANIFEST_PATH
    from apnea_uq_tpu.lint.report import add_format_args

    p = sub.add_parser(
        "flow",
        help="Pipeline dataflow analysis: statically verify the "
             "artifact contract (producer->consumer graph over registry "
             "keys, diffed against flow/manifest.json) and the "
             "filesystem crash-consistency discipline.")
    p.add_argument("paths", nargs="*", default=None,
                   help="Files/directories to analyze; default: the "
                        "apnea_uq_tpu package plus bench.py beside it.")
    add_format_args(p)
    p.add_argument("--rule", action="append", default=[], metavar="NAME",
                   help="Run only this flow rule (repeatable); default: "
                        "all — see docs/LINT.md \"Flow rules\".")
    p.add_argument("--manifest", default=DEFAULT_MANIFEST_PATH,
                   help="Manifest path (default: the in-package golden "
                        "apnea_uq_tpu/flow/manifest.json).")
    p.add_argument("--update-manifest", action="store_true",
                   help="Regenerate the manifest rows from the live "
                        "extraction (stale rows pruned); written only "
                        "when every rule passes.")
    p.add_argument("--update-docs", action="store_true",
                   help="Regenerate the generated dataflow table in "
                        "docs/PIPELINE.md from the live extraction.")
    p.add_argument("--docs", default=None,
                   help="With --update-docs: destination path (default "
                        "<repo>/docs/PIPELINE.md).")
    p.set_defaults(fn=cmd_flow)
