"""``apnea-uq flow`` — pipeline dataflow analysis (ISSUE 10).

Third static-analysis family on the lint engine: extract every
:class:`~apnea_uq_tpu.data.registry.ArtifactRegistry` read/write site
into a producer -> consumer graph over pipeline stages
(:mod:`apnea_uq_tpu.flow.extract`), verify the artifact contract and
the filesystem crash-consistency discipline
(:mod:`apnea_uq_tpu.flow.rules`), diff against the checked-in
``flow/manifest.json`` (:mod:`apnea_uq_tpu.flow.manifest`), and render
the generated ``docs/PIPELINE.md`` (:mod:`apnea_uq_tpu.flow.pipedoc`).
Jax-free end to end.
"""

from apnea_uq_tpu.flow.extract import extract_graph, graph_rows
from apnea_uq_tpu.flow.rules import FLOW_RULES, run_flow_rules

__all__ = ["extract_graph", "graph_rows", "FLOW_RULES", "run_flow_rules",
           "run_flow"]


def run_flow(paths, *, rules=None, repo_root=None, manifest=None):
    """Programmatic twin of the CLI: lint-engine file loading +
    extraction + flow rules + suppression resolution, returning the
    same :class:`~apnea_uq_tpu.lint.engine.LintResult` shape the
    reporters render.  ``manifest`` is the loaded row dict (None skips
    the graph-drift rule) or a callable ``graph -> rows`` resolved after
    extraction — the ``--update-manifest`` path diffs against the
    freshly merged rows without re-running the analysis.  Returns
    ``(result, graph)``."""
    from apnea_uq_tpu.flow.rules import FlowContext
    from apnea_uq_tpu.lint.engine import (
        LintContext, LintResult, apply_suppressions, default_repo_root,
        load_files,
    )

    paths = list(paths)
    if not paths:
        raise ValueError("run_flow needs at least one path")
    if repo_root is None:
        repo_root = default_repo_root(paths)
    files = load_files(paths, repo_root)
    context = LintContext(files=files, repo_root=repo_root)
    graph = extract_graph(context)
    if callable(manifest):
        manifest = manifest(graph)
    fc = FlowContext(context=context, graph=graph, manifest=manifest)
    selected = tuple(dict.fromkeys(rules)) if rules is not None \
        else tuple(sorted(FLOW_RULES))
    findings = run_flow_rules(fc, rules=selected)
    by_path = {f.path: f for f in files}
    findings = [
        apply_suppressions(f, by_path[f.path]) if f.path in by_path else f
        for f in findings
    ]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    result = LintResult(
        findings=findings, files_scanned=len(files), rules_run=selected,
        scanned_paths=tuple(f.path for f in files),
    )
    return result, graph
