"""Registry dataflow extraction: every artifact read/write site in scope,
resolved to canonical keys, as one producer -> consumer graph.

The pipeline's interface is files on disk, mediated by
:class:`~apnea_uq_tpu.data.registry.ArtifactRegistry`: a stage *promises*
to write key K with fields F, and a later stage *assumes* both.  Those
promises live in call sites scattered across the package (plus
``bench.py``), so a refactor can orphan a consumer or strand a producer
without any single file looking wrong.  This module makes the graph a
static object: an AST walk collects every ``save_arrays`` /
``save_array_store`` / ``adopt_array_store`` / ``save_table`` /
``save_json`` / ``directory_for`` / ``load_arrays`` /
``open_array_store`` / ``load_table`` / ``load_json`` call, resolves its
key expression, and records the statically-known field sets.

Key resolution handles the package's real idioms:

- ``reg.WINDOWS`` attribute constants (any alias of the registry
  module), resolved against the catalog parsed from the in-scope
  ``registry.py`` (``CANONICAL_KEYS`` when present, else every
  module-level ``UPPER = "string"`` assignment);
- direct constant imports (``from ..registry import WINDOWS``);
- ``f"{reg.UQ_STATS}:{label}"`` tag-suffix construction — the tagged
  variant resolves to its *base* catalog entry, so ``save_run``'s
  per-label keys never read as drift;
- locals assigned earlier in the same function
  (``key = f"{reg.METRICS}:{args.label}"; registry.load_json(key)``);
- local write aliases (``save = registry.save_array_store if store else
  registry.save_arrays; save(KEY, {...})``).

Anything else is dynamic and is deliberately *not* guessed at: an
unresolvable key contributes no graph edge (and no finding).

Jax-free by construction, like the lint engine it rides.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from apnea_uq_tpu.lint.engine import LintContext, SourceFile

#: Registry write methods and the artifact kind each records.
WRITE_METHODS: Dict[str, str] = {
    "save_arrays": "arrays",
    "save_array_store": "array_store",
    "adopt_array_store": "array_store",
    "save_table": "table",
    "save_json": "json",
}

#: Registry read methods.
READ_METHODS: Tuple[str, ...] = (
    "load_arrays", "open_array_store", "load_table", "load_json",
)

#: Managed-handle methods: ``directory_for`` both creates and locates a
#: directory artifact, so a site counts as producer AND consumer.
MANAGE_METHODS: Tuple[str, ...] = ("directory_for",)

#: Methods that take a fields mapping as their second argument.
_FIELD_WRITE_METHODS = ("save_arrays", "save_array_store")


@dataclasses.dataclass(frozen=True)
class KeyRef:
    """One resolved key expression."""

    base: Optional[str]     # canonical base key text; None = unresolvable
    tagged: bool = False    # carries a ':<tag>' suffix
    literal: bool = False   # base spelled as a raw string literal


@dataclasses.dataclass(frozen=True)
class AccessSite:
    """One registry access call, located and classified."""

    path: str               # repo-root-relative display path
    line: int
    function: str           # enclosing function name ('<module>' at top level)
    method: str             # registry method (aliased writes join with '|')
    role: str               # 'produce' | 'consume' | 'manage'
    key: KeyRef
    kinds: Tuple[str, ...] = ()              # artifact kind(s), writes only
    fields: Optional[Tuple[str, ...]] = None  # written names / names= subset

    @property
    def site(self) -> str:
        """Line-independent identity used in flow/manifest.json rows."""
        return f"{self.path.replace(chr(92), '/')}::{self.function}"


@dataclasses.dataclass
class Catalog:
    """The canonical key catalog parsed from the in-scope registry.py."""

    path: Optional[str] = None           # display path, None = not in scope
    names: Dict[str, str] = dataclasses.field(default_factory=dict)
    lines: Dict[str, int] = dataclasses.field(default_factory=dict)
    order: List[str] = dataclasses.field(default_factory=list)

    @property
    def values(self) -> Set[str]:
        return set(self.order)


@dataclasses.dataclass
class FlowGraph:
    catalog: Catalog
    sites: List[AccessSite]
    #: Graph-completeness rules need the whole pipeline universe in
    #: scope: the registry module (the catalog) AND the stage registry
    #: (cli/stages.py).  Mirrors the telemetry-schema rule's anchor
    #: logic — a partial scan must never claim an artifact is orphaned.
    full_scope: bool = False

    def sites_for(self, base: str) -> List[AccessSite]:
        return [s for s in self.sites if s.key.base == base]


# ------------------------------------------------------------- catalog --

def _registry_file(context: LintContext) -> Optional[SourceFile]:
    return context.file_named("registry.py")


def parse_catalog(sf: SourceFile) -> Catalog:
    """Module-level ``UPPER = "string"`` assignments, ordered by the
    ``CANONICAL_KEYS`` tuple when the module declares one (the real
    registry does), else by declaration order (synthetic fixtures)."""
    names: Dict[str, str] = {}
    lines: Dict[str, int] = {}
    canonical: Optional[List[str]] = None
    for node in sf.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if (target.id == "CANONICAL_KEYS"
                and isinstance(node.value, ast.Tuple)):
            canonical = [e.id for e in node.value.elts
                         if isinstance(e, ast.Name)]
        elif (target.id.isupper()
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            names[target.id] = node.value.value
            lines.setdefault(node.value.value, node.lineno)
    if canonical is not None:
        order = [names[n] for n in canonical if n in names]
    else:
        order = list(dict.fromkeys(names.values()))
    return Catalog(path=sf.path, names=names, lines=lines, order=order)


# ------------------------------------------------------------- aliases --

def _registry_aliases(tree: ast.Module) -> Tuple[Set[str], Dict[str, str]]:
    """(module aliases, directly-imported constant names) for the
    registry module in one file — ``import ... as reg`` and
    ``from ...registry import WINDOWS as W`` both resolve."""
    mod_aliases: Set[str] = set()
    const_names: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[-1] == "registry" and alias.asname:
                    mod_aliases.add(alias.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[-1] == "registry":
                for alias in node.names:
                    const_names[alias.asname or alias.name] = alias.name
            else:
                for alias in node.names:
                    if alias.name == "registry":
                        mod_aliases.add(alias.asname or "registry")
    return mod_aliases, const_names


# ------------------------------------------------------ key resolution --

def walk_scope(stmts: Sequence[ast.stmt]):
    """Like ``ast.walk`` over ``stmts`` but pruned at nested function
    boundaries: a call (or assignment) inside an inner ``def`` belongs
    to the inner scope, which gets its own pass."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stack.append(child)


class _Scope:
    """One function (or the module top level): local assignments for
    name resolution, in source order."""

    def __init__(self, name: str, body: Sequence[ast.stmt]):
        self.name = name
        self.assigns: Dict[str, List[Tuple[int, ast.AST]]] = {}
        for node in walk_scope(body):
            if isinstance(node, ast.Assign):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        self.assigns.setdefault(target.id, []).append(
                            (node.lineno, node.value))

    def value_before(self, name: str, line: int) -> Optional[ast.AST]:
        best: Optional[Tuple[int, ast.AST]] = None
        for ln, value in self.assigns.get(name, ()):
            if ln <= line and (best is None or ln > best[0]):
                best = (ln, value)
        return best[1] if best else None


def _iter_scopes(tree: ast.Module):
    """Yield (_Scope, statements) for the module top level (nested
    function bodies excluded) and for every function, innermost wins for
    nested defs because later scopes re-cover their own bodies."""
    top = [s for s in tree.body
           if not isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))]
    yield _Scope("<module>", top), top
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield _Scope(node.name, node.body), node.body


def resolve_key(
    expr: ast.AST,
    catalog: Catalog,
    mod_aliases: Set[str],
    const_names: Dict[str, str],
    scope: _Scope,
    line: int,
    _depth: int = 0,
) -> KeyRef:
    """Resolve a key expression to its base catalog entry (tag suffixes
    stripped).  Unresolvable expressions return ``KeyRef(None)``."""
    if _depth > 4:
        return KeyRef(None)
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        text = expr.value
        base, sep, _tag = text.partition(":")
        return KeyRef(base=base, tagged=bool(sep), literal=True)
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id in mod_aliases:
        value = catalog.names.get(expr.attr)
        return KeyRef(base=value) if value is not None else KeyRef(None)
    if isinstance(expr, ast.Name):
        if expr.id in const_names:
            value = catalog.names.get(const_names[expr.id])
            return KeyRef(base=value) if value is not None else KeyRef(None)
        bound = scope.value_before(expr.id, line)
        if bound is not None:
            return resolve_key(bound, catalog, mod_aliases, const_names,
                               scope, line, _depth + 1)
        return KeyRef(None)
    if isinstance(expr, ast.JoinedStr) and expr.values:
        head = expr.values[0]
        rest = expr.values[1:]
        if isinstance(head, ast.Constant) and isinstance(head.value, str):
            text = head.value
            base, sep, _ = text.partition(":")
            tagged = bool(sep) or bool(rest)
            return KeyRef(base=base, tagged=tagged, literal=True)
        if isinstance(head, ast.FormattedValue):
            inner = resolve_key(head.value, catalog, mod_aliases,
                                const_names, scope, line, _depth + 1)
            if inner.base is None:
                return KeyRef(None)
            if not rest:
                return inner
            # The remainder must start with the ':' tag separator for
            # this to be a tagged variant of the base key.
            nxt = rest[0]
            if isinstance(nxt, ast.Constant) and isinstance(nxt.value, str) \
                    and nxt.value.startswith(":"):
                return KeyRef(base=inner.base, tagged=True,
                              literal=inner.literal)
            return KeyRef(None)
    return KeyRef(None)


# ----------------------------------------------------- field resolution --

def _dict_display_keys(expr: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(expr, ast.Dict) and all(
            isinstance(k, ast.Constant) and isinstance(k.value, str)
            for k in expr.keys):
        return tuple(k.value for k in expr.keys)  # type: ignore[union-attr]
    return None


def _resolve_fields_arg(expr: Optional[ast.AST], scope: _Scope,
                        line: int) -> Optional[Tuple[str, ...]]:
    """Written field names when statically known: a dict display at the
    call, or a local assigned one earlier in the function."""
    if expr is None:
        return None
    keys = _dict_display_keys(expr)
    if keys is not None:
        return keys
    if isinstance(expr, ast.Name):
        bound = scope.value_before(expr.id, line)
        if bound is not None:
            return _dict_display_keys(bound)
    return None


def _names_kwarg(call: ast.Call) -> Optional[Tuple[str, ...]]:
    for kw in call.keywords:
        if kw.arg == "names" and isinstance(kw.value, (ast.Tuple, ast.List)):
            if all(isinstance(e, ast.Constant) and isinstance(e.value, str)
                   for e in kw.value.elts):
                return tuple(e.value for e in kw.value.elts)
    return None


def _write_aliases(scope: _Scope) -> Dict[str, Tuple[str, ...]]:
    """Local names bound to registry write methods (directly or via a
    conditional/lambda expression): calls through them are writes of
    every method the binding mentions."""
    out: Dict[str, Tuple[str, ...]] = {}
    for name, bindings in scope.assigns.items():
        for _line, value in bindings:
            methods = tuple(sorted({
                node.attr for node in ast.walk(value)
                if isinstance(node, ast.Attribute)
                and node.attr in WRITE_METHODS
            }))
            if methods:
                out[name] = methods
    return out


# ------------------------------------------------------------ extraction --

def _extract_file_sites(sf: SourceFile, catalog: Catalog) -> List[AccessSite]:
    mod_aliases, const_names = _registry_aliases(sf.tree)
    sites: List[AccessSite] = []
    seen: Set[int] = set()  # call node ids, so nested scopes don't double
    for scope, body in _iter_scopes(sf.tree):
        aliases = _write_aliases(scope)
        for node in walk_scope(body):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            method: Optional[str] = None
            methods: Tuple[str, ...] = ()
            if isinstance(node.func, ast.Attribute):
                attr = node.func.attr
                if attr in WRITE_METHODS or attr in READ_METHODS \
                        or attr in MANAGE_METHODS:
                    method = attr
                    methods = (attr,)
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in aliases:
                methods = aliases[node.func.id]
                method = "|".join(methods)
            if method is None or not node.args:
                continue
            seen.add(id(node))
            key = resolve_key(node.args[0], catalog, mod_aliases,
                              const_names, scope, node.lineno)
            if methods[0] in MANAGE_METHODS:
                role = "manage"
                kinds: Tuple[str, ...] = ("directory",)
                fields = None
            elif methods[0] in WRITE_METHODS:
                role = "produce"
                kinds = tuple(sorted({WRITE_METHODS[m] for m in methods}))
                fields = None
                if any(m in _FIELD_WRITE_METHODS for m in methods):
                    arg = node.args[1] if len(node.args) > 1 else None
                    fields = _resolve_fields_arg(arg, scope, node.lineno)
            else:
                role = "consume"
                kinds = ()
                fields = _names_kwarg(node)
            sites.append(AccessSite(
                path=sf.path, line=node.lineno, function=scope.name,
                method=method, role=role, key=key, kinds=kinds,
                fields=fields,
            ))
    sites.sort(key=lambda s: (s.path, s.line, s.method))
    return sites


def extract_graph(context: LintContext) -> FlowGraph:
    reg_sf = _registry_file(context)
    catalog = parse_catalog(reg_sf) if reg_sf is not None else Catalog()
    sites: List[AccessSite] = []
    for sf in context.files:
        sites.extend(_extract_file_sites(sf, catalog))
    sites.sort(key=lambda s: (s.path, s.line, s.method))
    full_scope = (reg_sf is not None
                  and context.file_named("cli/stages.py") is not None)
    return FlowGraph(catalog=catalog, sites=sites, full_scope=full_scope)


# ------------------------------------------------------- manifest rows --

def graph_rows(graph: FlowGraph) -> Dict[str, Dict[str, object]]:
    """One structural row per canonical key — what flow/manifest.json
    records and ``artifact-graph-drift`` diffs.  Line numbers stay out
    (they churn under unrelated edits); ``path::function`` identities
    move only when code actually moves."""
    rows: Dict[str, Dict[str, object]] = {}
    for key in graph.catalog.order:
        produced = sorted({s.site for s in graph.sites_for(key)
                           if s.role in ("produce", "manage")})
        consumed = sorted({s.site for s in graph.sites_for(key)
                           if s.role in ("consume", "manage")})
        kinds = sorted({k for s in graph.sites_for(key) for k in s.kinds})
        fields = sorted({f for s in graph.sites_for(key)
                         if s.role == "produce" and s.fields
                         for f in s.fields})
        rows[key] = {
            "kinds": kinds,
            "producers": produced,
            "consumers": consumed,
            "fields": fields,
        }
    return rows
