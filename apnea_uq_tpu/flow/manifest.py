"""The golden pipeline-dataflow manifest, checked into the repo.

One JSON row per canonical artifact key (``registry.py``'s
``CANONICAL_KEYS``) records the structural dataflow facts of the
pipeline — which stages produce it (``path::function``), which consume
it, the artifact kinds it is stored as, and the statically-known field
names — so CI fails the moment a refactor orphans a consumer, strands a
producer, or silently changes a field set, against a file a reviewer
can read in the diff.  Line numbers stay out: rows move only when code
actually moves.

``apnea-uq flow --update-manifest`` regenerates the rows from the live
extraction (the same audit-manifest pattern as
``apnea_uq_tpu/audit/manifest.json``); rows for keys that left the
catalog are pruned.  This module is jax-free.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

from apnea_uq_tpu.flow.extract import FlowGraph, graph_rows

MANIFEST_VERSION = 1
DEFAULT_MANIFEST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "manifest.json")


def load_manifest(path: str = DEFAULT_MANIFEST_PATH,
                  ) -> Optional[Dict[str, Dict[str, Any]]]:
    """key -> row, or None when no manifest exists yet."""
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "artifacts" not in doc:
        raise ValueError(
            f"{path!r} is not a flow manifest (no 'artifacts' key)")
    return dict(doc["artifacts"])


def merge_rows(graph: FlowGraph) -> Dict[str, Dict[str, Any]]:
    """The would-be manifest after an update: one row per canonical key
    from the live extraction.  Keys no longer in the catalog are pruned
    (``--update-manifest`` is the documented remediation for the
    stale-row finding, so it must actually remove them)."""
    return graph_rows(graph)


def write_manifest(path: str, rows: Dict[str, Dict[str, Any]]) -> None:
    from apnea_uq_tpu.utils.io import atomic_write_json

    doc = {
        "version": MANIFEST_VERSION,
        "artifacts": {key: rows[key] for key in rows},
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    # sort_keys=False keeps the version header first and the rows in
    # catalog (pipeline) order — the reviewable layout.
    atomic_write_json(path, doc, sort_keys=False, trailing_newline=True)
