"""The flow-rule family: static verification of the artifact contract
and the filesystem crash-consistency discipline.

Third rule family on the lint engine — same :class:`Finding` type, same
severities, same ``# apnea-lint: disable=<rule> -- <why>`` suppressions,
same reporters — but the subject is the *pipeline dataflow graph*
(:mod:`apnea_uq_tpu.flow.extract`) plus the filesystem effects of every
scanned function, not a single AST in isolation.

Graph rules (need the full pipeline universe in scope — the registry
module and ``cli/stages.py`` — exactly like the telemetry-schema rule's
phantom direction):

- ``artifact-never-produced`` — a canonical key some stage consumes but
  nothing in scope produces: the refactor orphaned a consumer, and the
  pipeline now fails at stage start instead of review time.
- ``artifact-never-consumed`` — a canonical key produced but never read
  back: a dead artifact (or a lost consumer).  End-product artifacts
  read by analysts/tests rather than stages carry a justified
  suppression at the producer site — the audit trail the gate pins.
- ``artifact-key-drift`` — a key spelled as a string literal instead of
  the ``registry.py`` catalog constant: exactly the contract drift the
  registry exists to end (SURVEY §1), one typo away from a silent fork.
- ``artifact-field-contract`` — a consumer's ``names=`` subset requests
  a field some statically-known producer never writes: that pairing
  KeyErrors at stage start on the producer's path.
- ``artifact-graph-drift`` — the extracted graph no longer matches the
  checked-in ``flow/manifest.json`` row (the audit-manifest pattern):
  re-bless intended changes with ``apnea-uq flow --update-manifest``
  and review the JSON diff.

Write-discipline rules (always run, any scope):

- ``non-atomic-artifact-write`` — an ``open(..., "w")`` / ``np.save*``
  / ``.to_csv`` whose path derives from a registry root, run dir, or
  store dir, in a function with no ``os.replace`` commit: readers can
  observe a torn file.  Route through ``utils/io.py``'s atomic writers.
- ``replace-without-fsync`` — a tmp -> ``os.replace`` commit that never
  fsyncs the data first: after a power loss the rename can land before
  the data blocks, publishing an empty/truncated file.  A memmap
  ``.flush()`` (msync) counts — that is the shard writer's protocol.

Jax-free by construction.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from apnea_uq_tpu.flow.extract import FlowGraph, graph_rows, walk_scope
from apnea_uq_tpu.lint.engine import (
    SEVERITIES,
    Finding,
    LintContext,
    Rule,
    SourceFile,
)

FLOW_RULES: Dict[str, Rule] = {}


def register_flow_rule(name: str, severity: str, summary: str):
    """Decorator twin of :func:`apnea_uq_tpu.lint.engine.register_rule`
    for rules that check the pipeline dataflow graph."""
    if severity not in SEVERITIES:
        raise ValueError(
            f"severity must be one of {SEVERITIES}, got {severity!r}")

    def wrap(fn):
        FLOW_RULES[name] = Rule(name=name, severity=severity,
                                summary=summary, check=fn)
        return fn

    return wrap


@dataclasses.dataclass
class FlowContext:
    """Everything a flow rule sees: the parsed files, the extracted
    graph, and the checked-in manifest rows (None = no manifest yet —
    the drift rule then skips, fixtures and partial scans stay green)."""

    context: LintContext
    graph: FlowGraph
    manifest: Optional[Dict[str, Dict[str, object]]] = None


def _finding(rule: str, path: str, line: int, message: str) -> Finding:
    return Finding(rule=rule, severity=FLOW_RULES[rule].severity,
                   path=path, line=int(line), message=message)


# ------------------------------------------------------------ graph rules --

@register_flow_rule(
    "artifact-never-produced", "error",
    "a canonical artifact key is consumed by some stage but produced by "
    "none — the pipeline fails at stage start, not review time",
)
def check_never_produced(fc: FlowContext) -> Iterable[Finding]:
    if not fc.graph.full_scope:
        return
    for key in fc.graph.catalog.order:
        sites = fc.graph.sites_for(key)
        if any(s.role in ("produce", "manage") for s in sites):
            continue
        for s in sites:
            if s.role == "consume":
                yield _finding(
                    "artifact-never-produced", s.path, s.line,
                    f"artifact '{key}' is consumed here ({s.method}) but "
                    f"no stage in scope produces it — the producer was "
                    f"removed or renamed without this consumer",
                )


@register_flow_rule(
    "artifact-never-consumed", "warning",
    "a canonical artifact key is produced but consumed by no stage — a "
    "dead artifact, or a consumer lost in a refactor",
)
def check_never_consumed(fc: FlowContext) -> Iterable[Finding]:
    if not fc.graph.full_scope:
        return
    for key in fc.graph.catalog.order:
        sites = fc.graph.sites_for(key)
        if any(s.role in ("consume", "manage") for s in sites):
            continue
        for s in sites:
            if s.role == "produce":
                yield _finding(
                    "artifact-never-consumed", s.path, s.line,
                    f"artifact '{key}' is produced here ({s.method}) but "
                    f"no stage in scope consumes it — dead artifact, or "
                    f"its consumer was lost (suppress with a "
                    f"justification if analysts/tests read it directly)",
                )


@register_flow_rule(
    "artifact-key-drift", "error",
    "an artifact key spelled as a string literal bypasses the canonical "
    "registry.py catalog — the contract-drift class the registry ends",
)
def check_key_drift(fc: FlowContext) -> Iterable[Finding]:
    catalog = fc.graph.catalog
    if catalog.path is None:
        return
    for s in fc.graph.sites:
        if s.key.base is None or not s.key.literal:
            continue
        if s.path == catalog.path:
            continue  # the catalog module itself may spell its constants
        if s.key.base in catalog.values:
            hint = (f"use the registry catalog constant for "
                    f"'{s.key.base}' instead of a string literal")
        else:
            hint = (f"'{s.key.base}' is not a canonical key — add it to "
                    f"the registry.py catalog (and CANONICAL_KEYS) or "
                    f"use an existing constant")
        yield _finding(
            "artifact-key-drift", s.path, s.line,
            f"artifact key '{s.key.base}' is spelled as a string literal "
            f"at this {s.method} site; {hint}",
        )


@register_flow_rule(
    "artifact-field-contract", "error",
    "a consumer's names= subset requests a field some statically-known "
    "producer never writes — a stage-start KeyError on that path",
)
def check_field_contract(fc: FlowContext) -> Iterable[Finding]:
    if not fc.graph.full_scope:
        return
    for key in fc.graph.catalog.order:
        sites = fc.graph.sites_for(key)
        producers = [s for s in sites
                     if s.role == "produce" and s.fields is not None]
        if not producers:
            continue
        for s in sites:
            if s.role != "consume" or s.fields is None:
                continue
            for p in producers:
                missing = sorted(set(s.fields) - set(p.fields))
                if missing:
                    yield _finding(
                        "artifact-field-contract", s.path, s.line,
                        f"consumer requests field(s) {missing} of "
                        f"'{key}' that the producer at {p.path}:{p.line} "
                        f"({p.method}) does not write "
                        f"(writes {sorted(p.fields)})",
                    )
                    break  # one finding per consumer site


@register_flow_rule(
    "artifact-graph-drift", "error",
    "the extracted producer->consumer graph no longer matches the "
    "checked-in flow/manifest.json — re-bless intended changes with "
    "`apnea-uq flow --update-manifest`",
)
def check_graph_drift(fc: FlowContext) -> Iterable[Finding]:
    if not fc.graph.full_scope or fc.manifest is None:
        return
    catalog = fc.graph.catalog
    rows = graph_rows(fc.graph)
    anchor_path = catalog.path or "registry.py"
    for key in catalog.order:
        line = catalog.lines.get(key, 1)
        prior = fc.manifest.get(key)
        if prior is None:
            yield _finding(
                "artifact-graph-drift", anchor_path, line,
                f"canonical key '{key}' has no flow/manifest.json row — "
                f"run `apnea-uq flow --update-manifest` to record it",
            )
            continue
        changed = sorted(
            field for field in ("kinds", "producers", "consumers", "fields")
            if prior.get(field) != rows[key][field]
        )
        if changed:
            detail = "; ".join(
                f"{field}: manifest {prior.get(field)} != extracted "
                f"{rows[key][field]}" for field in changed
            )
            yield _finding(
                "artifact-graph-drift", anchor_path, line,
                f"artifact '{key}' drifted from its manifest row "
                f"({detail}) — review and re-bless with "
                f"`apnea-uq flow --update-manifest`",
            )
    for key in sorted(set(fc.manifest) - set(catalog.order)):
        yield _finding(
            "artifact-graph-drift", anchor_path, 1,
            f"flow/manifest.json has a stale row for '{key}', which is "
            f"no longer a canonical key — `apnea-uq flow "
            f"--update-manifest` prunes it",
        )


# ------------------------------------------------- write-discipline rules --

#: Calls that locate artifact storage: anything derived from them is an
#: artifact-rooted path.
MARKER_CALLS = frozenset({
    "path_for", "_manifest_path", "directory_for", "default_run_dir",
    "_progress_path", "_blob_path", "_meta_path",
})

#: Names that *are* artifact roots wherever they appear.
MARKER_NAMES = frozenset({"run_dir", "store_dir", "registry_root"})

#: Attribute names that are artifact roots (``self.root``,
#: ``run_log.run_dir``, ``store.directory``).
MARKER_ATTRS = MARKER_NAMES | frozenset({"root", "directory"})


def _is_rooted(expr: ast.AST, tainted: Set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            f = node.func
            name = (f.attr if isinstance(f, ast.Attribute)
                    else f.id if isinstance(f, ast.Name) else None)
            if name in MARKER_CALLS:
                return True
        elif isinstance(node, ast.Name):
            if node.id in MARKER_NAMES or node.id in tainted:
                return True
        elif isinstance(node, ast.Attribute):
            if node.attr in MARKER_ATTRS:
                return True
    return False


def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


@dataclasses.dataclass
class _FnEffects:
    """Filesystem effects of one function scope."""

    write_calls: List[Tuple[ast.Call, ast.AST]]  # (call, path expr)
    replace_lines: List[int]
    has_fsync: bool
    has_memmap_flush: bool
    tainted: Set[str]


def _scan_effects(body) -> _FnEffects:
    nodes = list(walk_scope(body))
    # Two taint passes: assignments may chain (path = join(run_dir, x);
    # tmp = path + '.tmp').
    tainted: Set[str] = set()
    for _ in range(2):
        for node in nodes:
            if isinstance(node, ast.Assign):
                if _is_rooted(node.value, tainted):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tainted.add(t.id)
    handle_names: Set[str] = set()   # file objects from open(...)
    memmap_names: Set[str] = set()   # arrays from open_memmap(...)
    for node in nodes:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = _call_name(node.value)
            for t in node.targets:
                if isinstance(t, ast.Name):
                    if name == "open":
                        handle_names.add(t.id)
                    elif name == "open_memmap":
                        memmap_names.add(t.id)
        elif isinstance(node, ast.withitem) and isinstance(
                node.context_expr, ast.Call):
            if _call_name(node.context_expr) == "open" and isinstance(
                    node.optional_vars, ast.Name):
                handle_names.add(node.optional_vars.id)

    write_calls: List[Tuple[ast.Call, ast.AST]] = []
    replace_lines: List[int] = []
    has_fsync = False
    has_memmap_flush = False
    for node in nodes:
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name == "replace" and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "os":
            # os.replace only — str.replace must not read as a commit.
            replace_lines.append(node.lineno)
        elif name == "fsync":
            has_fsync = True
        elif (name == "flush" and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in memmap_names):
            has_memmap_flush = True
        elif name == "open" and isinstance(node.func, ast.Name):
            mode = None
            if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
                mode = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            if isinstance(mode, str) and mode.startswith(("w", "x")) \
                    and node.args:
                write_calls.append((node, node.args[0]))
        elif name == "open_memmap" and node.args:
            mode = None
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            if not isinstance(mode, str) or "w" in mode or "+" in mode:
                write_calls.append((node, node.args[0]))
        elif name in ("save", "savez", "savez_compressed") and isinstance(
                node.func, ast.Attribute) and isinstance(
                node.func.value, ast.Name) and node.func.value.id in (
                "np", "numpy") and node.args:
            if not (isinstance(node.args[0], ast.Name)
                    and node.args[0].id in handle_names):
                write_calls.append((node, node.args[0]))
        elif name == "to_csv" and isinstance(node.func, ast.Attribute) \
                and node.args:
            if not (isinstance(node.args[0], ast.Name)
                    and node.args[0].id in handle_names):
                write_calls.append((node, node.args[0]))
    return _FnEffects(write_calls=write_calls, replace_lines=replace_lines,
                      has_fsync=has_fsync,
                      has_memmap_flush=has_memmap_flush, tainted=tainted)


def _iter_fn_effects(sf: SourceFile):
    from apnea_uq_tpu.flow.extract import _iter_scopes

    for scope, body in _iter_scopes(sf.tree):
        yield scope, _scan_effects(body)


@register_flow_rule(
    "non-atomic-artifact-write", "error",
    "a write landing under a registry root / run dir / store dir "
    "without a tmp -> os.replace commit — readers can observe a torn "
    "file; route through utils/io.py's atomic writers",
)
def check_non_atomic_write(fc: FlowContext) -> Iterable[Finding]:
    for sf in fc.context.files:
        for _scope, fx in _iter_fn_effects(sf):
            if fx.replace_lines:
                continue  # this function commits atomically
            for call, path_expr in fx.write_calls:
                if _is_rooted(path_expr, fx.tainted):
                    yield _finding(
                        "non-atomic-artifact-write", sf.path, call.lineno,
                        "artifact-rooted write without a tmp -> "
                        "os.replace commit — a crash (or a concurrent "
                        "reader) can observe a torn file; route through "
                        "apnea_uq_tpu.utils.io.atomic_write_json/"
                        "text/bytes",
                    )


@register_flow_rule(
    "replace-without-fsync", "warning",
    "a tmp -> os.replace commit that never fsyncs the data first — a "
    "power loss can publish an empty/truncated file",
)
def check_replace_without_fsync(fc: FlowContext) -> Iterable[Finding]:
    for sf in fc.context.files:
        for _scope, fx in _iter_fn_effects(sf):
            if not fx.replace_lines or not fx.write_calls:
                continue
            if fx.has_fsync or fx.has_memmap_flush:
                continue
            yield _finding(
                "replace-without-fsync", sf.path, fx.replace_lines[0],
                "tmp -> os.replace commit without an os.fsync (or memmap "
                ".flush) of the written data — after a power loss the "
                "rename can land before the data blocks, publishing a "
                "truncated file; route through "
                "apnea_uq_tpu.utils.io's atomic writers",
            )


# ----------------------------------------------------------------- runner --

def run_flow_rules(fc: FlowContext,
                   rules: Optional[Iterable[str]] = None) -> List[Finding]:
    if rules is None:
        selected: Tuple[str, ...] = tuple(sorted(FLOW_RULES))
    else:
        selected = tuple(dict.fromkeys(rules))
    unknown = [r for r in selected if r not in FLOW_RULES]
    if unknown:
        raise ValueError(
            f"unknown flow rule(s) {unknown}; "
            f"available: {sorted(FLOW_RULES)}")
    findings: List[Finding] = []
    for name in selected:
        findings.extend(FLOW_RULES[name].check(fc))
    return findings
