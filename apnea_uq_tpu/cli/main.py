"""``apnea-uq`` — one CLI covering every pipeline stage.

The reference uses a separate argparse block (or hand-edited constants) per
script (SURVEY §5.6).  Here each stage is a subcommand; all of them accept
``--config`` (a JSON ExperimentConfig) plus targeted overrides.

Subcommands grow as stages land; ``apnea-uq <cmd> --help`` is the contract.
"""

from __future__ import annotations

import argparse
import sys

from apnea_uq_tpu import __version__
from apnea_uq_tpu.config import ExperimentConfig, load_config, save_config
from apnea_uq_tpu.telemetry import log


def _add_config_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", type=str, default=None,
                   help="Path to an ExperimentConfig JSON (see `init-config`).")


def _load(args) -> ExperimentConfig:
    return load_config(args.config) if args.config else ExperimentConfig()


def cmd_init_config(args) -> int:
    save_config(ExperimentConfig(), args.out)
    log(f"wrote default config to {args.out}")
    return 0


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="apnea-uq",
        description="TPU-native sleep-apnea UQ pipeline (JAX/Flax).",
    )
    parser.add_argument("--version", action="version", version=__version__)
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("init-config", help="Write the default config JSON.")
    p.add_argument("--out", type=str, default="apnea_uq_config.json")
    p.set_defaults(fn=cmd_init_config)

    # Stage subcommands are registered lazily by their modules to keep
    # CLI startup free of jax/pandas imports until a stage actually runs.
    from apnea_uq_tpu.cli import stages

    stages.register(sub, _add_config_arg, _load)
    return parser


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
