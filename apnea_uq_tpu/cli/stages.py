"""Stage subcommand registry for the ``apnea-uq`` CLI.

One subcommand per pipeline stage, replacing the reference's 18 standalone
scripts (SURVEY §1): the stage graph is

    ingest -> prepare -> train / train-ensemble
           -> eval-mcd / eval-de -> aggregate-patients / analyze-windows
           -> correlate / sweep / figures        (+ cohort, on raw metadata)

Every stage reads/writes the shared :class:`ArtifactRegistry`, so the
hand-maintained file names the reference drifted on (SURVEY §1) are never
spelled by the user.  Handlers import heavy dependencies (jax, pandas)
lazily so ``--help`` stays instant.
"""

from __future__ import annotations

import os
import sys

from apnea_uq_tpu.telemetry import log


def _registry(args):
    from apnea_uq_tpu.data.registry import ArtifactRegistry

    return ArtifactRegistry(args.registry)


def _run(args, stage: str, config):
    """Open the stage's telemetry run log (events.jsonl + config snapshot
    under ``--run-dir``, defaulting to ``<registry>/runs/<stage>-...``).
    Device-heavy stages emit their per-epoch / per-eval metric blocks
    through this; ``apnea-uq telemetry summarize`` reads it back."""
    from apnea_uq_tpu.telemetry import default_run_dir, start_run

    run_dir = getattr(args, "run_dir", None) or default_run_dir(
        args.registry, stage
    )
    run_log = start_run(run_dir, stage=stage, config=config,
                        argv=sys.argv[1:])
    log(f"telemetry -> {run_dir}")
    return run_log


def _add_run_dir_arg(p) -> None:
    p.add_argument("--run-dir", default=None,
                   help="Telemetry run directory (events.jsonl + config "
                        "snapshot); default <registry>/runs/<stage>-"
                        "<timestamp>-<pid>.  Read it back with "
                        "`apnea-uq telemetry summarize <run-dir>`.")


def _compile_env(args, config):
    """Activate the compile-cost subsystem for a device-heavy stage:
    persistent XLA cache under <registry>/xla-cache + the AOT program
    store under <registry>/program-store (CompileCacheConfig knobs /
    env overrides; APNEA_UQ_COMPILE_CACHE=0 disables).  Identical XLA
    compiles become disk hits across processes, and `apnea-uq
    warm-cache` can precompile the whole zoo ahead of time.

    Also activates any persisted ``autotune_config`` artifact (ops/
    autotune.py): every device-heavy stage — warm-cache, the evals, and
    the serving tier — bakes the SAME measured tile geometry into its
    kernel-program signatures, so a warm process and a serve process
    can never key the same program differently."""
    from apnea_uq_tpu import compilecache
    from apnea_uq_tpu.ops import autotune

    if getattr(args, "registry", None):
        activated = autotune.activate_from_registry(_registry(args))
        if activated:
            log(f"autotune: tuned tile geometry active for {activated} "
                f"program label(s)")
    return compilecache.activate(
        config.compilecache, registry_root=getattr(args, "registry", None)
    )


def _ckpt_root(args) -> str:
    if getattr(args, "ckpt_dir", None):
        return args.ckpt_dir
    from apnea_uq_tpu.data import registry as reg

    return _registry(args).directory_for(reg.CHECKPOINT)


def _model(config):
    from apnea_uq_tpu.models import AlarconCNN1D

    return AlarconCNN1D(config.model)


def _mesh(config, num_members: int = 1):
    """The (ensemble, data) device mesh config.mesh describes — every
    device-heavy stage (train, train-ensemble, eval-mcd/de, sweep) runs
    over it; on one device it degenerates to a 1x1 mesh."""
    from apnea_uq_tpu.parallel.mesh import make_mesh_from_config

    return make_mesh_from_config(config.mesh, num_members=num_members)


def _data_mesh():
    """Pure data-parallel (1, D) mesh for single-model stages: the baseline
    trainer has no member axis, so an ensemble_axis pinned in config.mesh
    (natural for train-ensemble) must not replicate its batches."""
    from apnea_uq_tpu.parallel.mesh import make_mesh

    return make_mesh(num_members=1)


def _baseline_template(config):
    """Model + abstract-structure state for restoring checkpoints."""
    import jax

    from apnea_uq_tpu.training import create_train_state

    model = _model(config)
    template = create_train_state(
        model, jax.random.key(0), learning_rate=config.train.learning_rate
    )
    return model, template


# The eval test-set labels, defined ONCE beside the loader that names
# the sets: _emit_drift_fingerprints maps them back to the registry
# keys prepare froze the per-set quality baselines under, and a rename
# here renames both sides together.
UNBALANCED_LABEL = "Unbalanced"
RUS_LABEL = "Balanced_RUS"


def _test_set_registry_keys():
    """{eval-set label: registry artifact key its windows come from}."""
    from apnea_uq_tpu.data import registry as reg

    return {UNBALANCED_LABEL: reg.TEST_STD_UNBALANCED,
            RUS_LABEL: reg.TEST_STD_RUS}


def _load_test_sets(registry, *, include_train: bool = False):
    """{label: (x, y, patient_ids|None)} for the unbalanced + RUS sets.

    Loaded with ``mmap=True``: ``array_store`` artifacts come back as
    memmap-backed lazy arrays (zero copy, zero load time — streamed
    consumers slice batches off the mapping, in-HBM consumers
    materialize on device transfer), ``.npz`` artifacts load as before.
    Call inside the stage's run-log scope so the ``data_load`` telemetry
    events land in the run's events.jsonl."""
    from apnea_uq_tpu.data.prepare import load_prepared

    prepared = load_prepared(registry, include_train=include_train,
                             mmap=True)
    sets = {
        UNBALANCED_LABEL: (prepared.x_test, prepared.y_test,
                           prepared.patient_ids_test)
    }
    if prepared.x_test_rus is not None:
        sets[RUS_LABEL] = (prepared.x_test_rus, prepared.y_test_rus, None)
    return prepared, sets


def _emit_drift_fingerprints(registry, sets, run_log) -> None:
    """Re-score each eval test set against ITS OWN frozen fingerprint
    in the ``quality_baseline`` artifact (prepare freezes one per
    prepared set, keyed by registry artifact key;
    analysis/fingerprint.py) and emit one ``drift_fingerprint`` event
    per set — per-channel PSI/KS drift vs the cohort the pipeline was
    prepared on, so `apnea-uq quality check` can gate a shifted cohort
    before anyone trusts its calibration.  The RUS set scores against
    the RUS baseline: its deliberate class re-balance must never read
    as drift.  Registries predating the baseline simply skip; ANY
    scoring failure (non-comparable or malformed baseline) is logged,
    never fatal — telemetry must not break an eval."""
    from apnea_uq_tpu.data import registry as reg

    if not registry.exists(reg.QUALITY_BASELINE):
        return
    from apnea_uq_tpu.analysis import fingerprint as fp_mod

    baseline = registry.load_json(reg.QUALITY_BASELINE)
    baselines = baseline.get("sets") if isinstance(baseline, dict) else None
    set_keys = _test_set_registry_keys()
    for label, (x, _y, _ids) in sets.items():
        fingerprint = (baselines or {}).get(set_keys.get(label))
        if fingerprint is None:
            log(f"drift fingerprint skipped for {label}: no frozen "
                f"baseline for this set (re-run prepare to freeze one)")
            continue
        try:
            report = fp_mod.score_against_baseline(x, fingerprint)
        except Exception as e:  # noqa: BLE001 - telemetry never kills an eval
            log(f"drift fingerprint skipped for {label}: "
                f"{type(e).__name__}: {e}")
            continue
        run_log.event(
            "drift_fingerprint",
            label=label,
            rows=report["rows"],
            baseline_rows=report["baseline_rows"],
            max_psi=report["max_psi"],
            max_ks=report["max_ks"],
            max_mean_shift=report["max_mean_shift"],
            worst_channel=report["worst_channel"],
            channels=report["channels"],
        )


# ---------------------------------------------------------------- stages --

def cmd_ingest(args, config) -> int:
    from apnea_uq_tpu.data import ingest_directory
    from apnea_uq_tpu.data import registry as reg
    from apnea_uq_tpu.data.ingest import ingest_directory_to_store

    registry = _registry(args)
    with _run(args, "ingest", config) as run_log:
        if args.store:
            # Out-of-core ingest: one committed shard per recording, peak
            # host memory O(one recording), resumable after kill -9
            # (ingest_progress.json; --fresh discards prior progress).
            store_dir = registry.path_for(reg.WINDOWS, ".store")
            with run_log.stage("ingest"):
                store, reports = ingest_directory_to_store(
                    args.edf_dir, args.xml_dir, store_dir, config.ingest,
                    num_files=args.num_files, workers=args.workers,
                    mode=args.mode, resume=not args.fresh, run_log=run_log,
                )
            windows_len = store.rows if store is not None else 0
        else:
            with run_log.stage("ingest"):
                windows, reports = ingest_directory(
                    args.edf_dir, args.xml_dir, config.ingest,
                    num_files=args.num_files, workers=args.workers,
                    mode=args.mode,
                )
            windows_len = 0 if windows is None else len(windows)
        excluded = [r for r in reports if r.excluded]
        errored = [r for r in reports if r.error]
        log(f"processed {len(reports)} recordings, excluded "
            f"{len(excluded)}, errored {len(errored)}")
        for r in excluded:
            log(f"  excluded {r.patient_id}: {r.excluded}")
        for r in errored:
            log(f"  errored {r.patient_id}: {r.error}")
        if windows_len == 0:
            log("no windows produced")
            return 1
        if args.store:
            registry.adopt_array_store(reg.WINDOWS, config=config.ingest)
        else:
            registry.save_arrays(reg.WINDOWS, windows.to_arrays(),
                                 config=config.ingest)
        log(f"saved {windows_len} windows -> {registry.root}")
    return 0


def cmd_prepare(args, config) -> int:
    from apnea_uq_tpu.data import WindowSet, windows_from_reference_csv
    from apnea_uq_tpu.data import registry as reg
    from apnea_uq_tpu.data.prepare import (
        load_prepared, prepare_datasets, prepare_from_store, save_prepared,
    )

    registry = _registry(args)
    with _run(args, "prepare", config) as run_log:
        entry = registry.describe(reg.WINDOWS)
        if (args.store and not args.from_csv and entry is not None
                and entry.get("kind") == "array_store"):
            # Fully out-of-core: windows stream from the sharded store,
            # prepared artifacts stream into sharded stores — host memory
            # stays O(block), never O(dataset).
            with run_log.stage("prepare"):
                prepare_from_store(
                    registry.open_array_store(reg.WINDOWS), registry,
                    config.prepare,
                )
            prepared = load_prepared(registry, mmap=True)
        else:
            if args.from_csv:
                windows = windows_from_reference_csv(args.from_csv)
            elif entry is not None and entry.get("kind") == "array_store":
                # Store-kind windows without --store: in-core prepare
                # over the materialized store (channels come from the
                # store's manifest, not a row field).
                from apnea_uq_tpu.data.ingest import windows_from_store

                windows = windows_from_store(
                    registry.open_array_store(reg.WINDOWS))
            else:
                windows = WindowSet.from_arrays(
                    registry.load_arrays(reg.WINDOWS)
                )
            with run_log.stage("prepare"):
                prepared = prepare_datasets(windows, config.prepare)
                save_prepared(prepared, registry, config.prepare,
                              store=args.store)
        log(
            f"train {prepared.x_train.shape}, test {prepared.x_test.shape}, "
            f"rus {None if prepared.x_test_rus is None else prepared.x_test_rus.shape}"
        )
    return 0


def cmd_migrate(args, config) -> int:
    """Convert monolithic ``.npz`` array artifacts to the sharded memmap
    ``array_store`` kind in place (same keys, verified content) so every
    later stage start memory-maps instead of decompressing the whole
    dataset.  Old registries stay readable without migrating — this is
    the one-command upgrade."""
    from apnea_uq_tpu.data.registry import migrate_to_store

    registry = _registry(args)
    keys = args.keys or [
        k for k, e in registry.manifest()["artifacts"].items()
        if e.get("kind") == "arrays"
    ]
    if not keys:
        log("nothing to migrate: no .npz array artifacts in the registry")
        return 0
    for key in keys:
        path = migrate_to_store(registry, key,
                                rows_per_shard=args.rows_per_shard)
        log(f"migrated {key} -> {path}")
    return 0


def cmd_train(args, config) -> int:
    import jax

    from apnea_uq_tpu.evaluation.classification import evaluate_classification
    from apnea_uq_tpu.training import (
        create_train_state, fit, predict_proba_batched, save_state,
    )

    registry = _registry(args)
    model = _model(config)
    state = create_train_state(
        model, jax.random.key(config.train.seed),
        learning_rate=config.train.learning_rate,
    )
    mesh = _data_mesh()
    from apnea_uq_tpu.telemetry.profiler import maybe_profile

    with _compile_env(args, config), _run(args, "train", config) as run_log:
        # Loaded inside the run scope so the artifact's data_load event
        # (cold stage-start cost: load_s / rss_bytes) lands in this run.
        prepared, sets = _load_test_sets(registry, include_train=True)
        with run_log.stage("fit", snapshot_memory=True), \
                maybe_profile(run_log, args.profile, label="train") as prof:
            result = fit(
                model, state, prepared.x_train, prepared.y_train,
                config.train, mesh=mesh, log_fn=log, run_log=run_log,
                profiler=prof,
            )
        from apnea_uq_tpu.utils.multihost import is_primary

        if is_primary():
            # Process-0-only write (the run-log discipline, enforced by
            # `apnea-uq topo` unguarded-primary-io): every process holds
            # the same trained state, one of them persists it.
            path = save_state(os.path.join(_ckpt_root(args), "baseline"),
                              result.state)
            log(f"saved baseline checkpoint -> {path} "
                f"(best epoch {result.best_epoch + 1}, "
                f"stopped_early={result.stopped_early})")
        with run_log.stage("evaluate", snapshot_memory=True):
            for label, (x, y, _ids) in sets.items():
                probs = predict_proba_batched(
                    model, result.state.variables(), x,
                    batch_size=config.uq.inference_batch_size, mesh=mesh,
                )
                evaluate_classification(
                    probs, y, threshold=config.uq.decision_threshold,
                    description=f"baseline on {label}", verbose=True,
                )
    return 0


def cmd_train_ensemble(args, config) -> int:
    from apnea_uq_tpu.parallel import fit_ensemble
    from apnea_uq_tpu.training import (
        EnsembleCheckpointStore, save_ensemble_result,
    )

    registry = _registry(args)
    model = _model(config)
    store = EnsembleCheckpointStore(os.path.join(_ckpt_root(args), "ensemble"))

    cfg = config.ensemble
    all_seeds = [cfg.seed_base + i for i in range(cfg.num_members)]
    missing = [s for s in all_seeds if not store.member_exists(s)]
    if not missing:
        log(f"all {cfg.num_members} members already checkpointed; nothing to do")
        return 0
    if len(missing) < len(all_seeds):
        log(f"resuming: {len(all_seeds) - len(missing)} members exist, "
            f"training {len(missing)}")

    # Train only the missing members, as one concurrent mesh-parallel run.
    import dataclasses

    run_cfg = dataclasses.replace(cfg, num_members=len(missing))
    # Per-member RNG is derived from the member's global index so a resumed
    # run reproduces exactly the members a fresh run would have produced.
    from apnea_uq_tpu.telemetry.profiler import maybe_profile

    with _compile_env(args, config), \
            _run(args, "train-ensemble", config) as run_log:
        prepared, _ = _load_test_sets(registry, include_train=True)
        with run_log.stage("fit_ensemble", snapshot_memory=True), \
                maybe_profile(run_log, args.profile,
                              label="train-ensemble") as prof:
            result = fit_ensemble(
                model, prepared.x_train, prepared.y_train, run_cfg,
                mesh=_mesh(config, num_members=len(missing)),
                member_indices=[s - cfg.seed_base for s in missing],
                log_fn=log, run_log=run_log, profiler=prof,
            )
        # The result may carry MORE members than requested: with
        # keep_padded_members the padded lockstep slots come back as real
        # members, each checkpointed under its global-index seed
        # (bit-identical to what a fresh larger run would save, so growing
        # N later re-trains nothing).  skip_existing covers the resume
        # corner where a promoted slot's seed is already on disk from an
        # earlier run.  Process 0 persists (fit_ensemble's host_values
        # gather hands every process the full member stack).
        from apnea_uq_tpu.utils.multihost import is_primary

        if is_primary():
            save_ensemble_result(store, result, seed_base=cfg.seed_base,
                                 skip_existing=True)
            promoted = result.promoted_members
            extra = (f" (incl. {promoted} promoted padded slots)"
                     if promoted else "")
            log(f"saved {result.num_members} members{extra} -> "
                f"{store.root}")
    return 0


def cmd_warm_cache(args, config) -> int:
    """Precompile the hot-path program zoo for this config (ISSUE 7):
    every program a later train / train-ensemble / eval-mcd / eval-de
    run would compile is compiled NOW — exportable ones serialized into
    the program store, every backend compile banked in the persistent
    XLA cache — so production stages start hot instead of paying
    multi-minute cold-start compiles per process."""
    from apnea_uq_tpu.compilecache import zoo

    # Engine/dtype overrides fold in BEFORE warming so the warmed label
    # set is exactly what an identically-flagged eval/serve dispatches.
    config = _apply_eval_overrides(args, config)
    registry = _registry(args)
    groups = tuple(g.strip() for g in args.programs.split(",") if g.strip())
    bad = set(groups) - set(zoo.WARM_GROUPS)
    if bad:
        raise SystemExit(
            f"warm-cache: unknown --programs group(s) {sorted(bad)}; "
            f"valid: {','.join(zoo.WARM_GROUPS)}"
        )
    with _compile_env(args, config) as store, \
            _run(args, "warm-cache", config) as run_log:
        if store is None:
            raise SystemExit(
                "warm-cache: the compile-cost subsystem is disabled "
                "(CompileCacheConfig.enabled=false or "
                "APNEA_UQ_COMPILE_CACHE=0); nothing to warm"
            )
        with run_log.stage("warm_cache", snapshot_memory=True):
            warmed = zoo.warm_cache(
                registry, config, num_members=args.num_members,
                groups=groups, ckpt_root=_ckpt_root(args),
                run_log=run_log,
            )
        fresh = sum(1 for w in warmed if w["source"] == "jit")
        total = sum(w["lower_s"] + w["compile_s"] for w in warmed)
        for w in warmed:
            log(f"  {w['label']}: {w['source']}"
                f" (lower {w['lower_s']:.2f}s"
                f" compile {w['compile_s']:.2f}s)")
        log(f"warmed {len(warmed)} program(s) ({fresh} freshly compiled, "
            f"{len(warmed) - fresh} already hot) in {total:.1f}s"
            + (f" -> {store.root}" if store.root else ""))
    return 0


def cmd_autotune(args, config) -> int:
    """Measure the fused-kernel tile grid (ISSUE 16): time every
    ``window_tile x member_group/pass_group`` cell against the real
    DE-predict and serve-bucket program families, persist the winning
    geometry per program label as the registry's ``autotune_config``
    artifact (atomic JSON beside the program store, stamped with the
    program store's own backend/jax/source fingerprint), and activate
    it in-process.  Every later `_compile_env` stage — warm-cache,
    eval-de, serve — bakes the winners into its program signatures, so
    tuned geometry flows through the zero-request-path-compile contract
    unchanged.  Off-TPU the cells time the XLA fallback bodies: the
    ratios read ~1.0 and the sweep doubles as a plumbing check."""
    from apnea_uq_tpu.compilecache import zoo
    from apnea_uq_tpu.data import registry as reg
    from apnea_uq_tpu.ops import autotune

    registry = _registry(args)
    buckets = tuple(int(b) for b in args.buckets.split(",") if b.strip())
    tiles = tuple(int(v) for v in args.window_tiles.split(",") if v.strip())
    groups = tuple(int(v) for v in args.groups.split(",") if v.strip())
    members = zoo.resolve_de_members(args.num_members, config,
                                     _ckpt_root(args))
    with _compile_env(args, config), \
            _run(args, "autotune", config) as run_log:
        with run_log.stage("autotune", snapshot_memory=True):
            document = autotune.run_autotune(
                model_config=config.model, members=members,
                n_passes=config.uq.mc_passes, windows=args.windows,
                chunk=config.uq.inference_batch_size, buckets=buckets,
                window_tiles=tiles, groups=groups, reps=args.reps,
                seed=config.train.seed, run_log=run_log,
            )
        path = registry.save_json(reg.AUTOTUNE_CONFIG, document)
        activated = autotune.activate(document)
        for label, rec in sorted(document["winners"].items()):
            log(f"  {label}: window_tile={rec['window_tile']} "
                f"group={rec.get('member_group', rec.get('pass_group'))} "
                f"best={rec['best_s']:.5f}s "
                f"({rec['best_vs_default']:.2f}x vs default)")
        log(f"autotune: {activated} winner(s) -> {path}")
    return 0


def _restore_members(args, config, n_members):
    from apnea_uq_tpu.training import EnsembleCheckpointStore

    model, template = _baseline_template(config)
    store = EnsembleCheckpointStore(os.path.join(_ckpt_root(args), "ensemble"))
    seeds = store.existing_seeds()
    if n_members <= 0:
        # "All checkpointed members" — the natural companion of padded-slot
        # promotion, where the store holds more members than the configured
        # N and every one of them is free uncertainty capacity.
        n_members = len(seeds)
    if not seeds or len(seeds) < n_members:
        raise SystemExit(
            f"need {max(n_members, 1)} ensemble members, found {len(seeds)} "
            f"in {store.root} — run train-ensemble first"
        )
    states = store.restore_members(seeds[:n_members], template)
    return model, [s.variables() for s in states]


def _emit_plots(args, result) -> None:
    if getattr(args, "plots_dir", None):
        from apnea_uq_tpu.uq import save_run_plots

        for p in save_run_plots(result, args.plots_dir):
            log(f"wrote {p}")


def _add_plots_arg(p) -> None:
    p.add_argument("--plots-dir", default=None,
                   help="Emit the per-run metric-distribution + class-bar "
                        "PNGs here (reference uq_techniques.py:369-387).")


def _add_no_detailed_arg(p) -> None:
    p.add_argument("--no-detailed", action="store_true",
                   help="Skip the per-window detailed CSV — the reference's "
                        "global evaluation variants (evaluate_mcd_global.py:"
                        "96-124, evaluate_de_global.py:117-141), which "
                        "compute aggregates + CIs only.")


def _add_full_probs_arg(p) -> None:
    p.add_argument("--full-probs", action="store_true",
                   help="Disable the fused on-device uncertainty "
                        "reduction: ship the full (K, M) probability "
                        "matrix device->host and decompose from it "
                        "(UQConfig.fused_reduction=False).  The parity "
                        "escape hatch — fused and full metric documents "
                        "agree to <=1e-6; full-probs runs additionally "
                        "persist the raw_predictions artifact.")


def _eval_uq_config(args, config):
    """The UQConfig an eval stage actually runs: ``--full-probs`` flips
    the fused default off for this invocation only."""
    if getattr(args, "full_probs", False):
        import dataclasses

        return dataclasses.replace(config.uq, fused_reduction=False)
    return config.uq


def _add_compute_dtype_arg(p) -> None:
    from apnea_uq_tpu.config import VALID_COMPUTE_DTYPES

    p.add_argument("--compute-dtype", choices=VALID_COMPUTE_DTYPES,
                   default=None,
                   help="Inference compute dtype for this invocation "
                        "(ModelConfig.compute_dtype): 'bfloat16' runs "
                        "conv/dense math on the MXU in bf16 with f32 "
                        "parameters and f32 stats/entropy accumulation "
                        "— the blessed low-precision tier, <=2e-2 vs "
                        "f32 (PARITY.md \"Tolerance tiers\"); programs "
                        "price/store under `_bf16` labels.")


def _apply_eval_overrides(args, config):
    """Fold the eval-only CLI overrides (--compute-dtype, --mcd-engine,
    --de-engine) into the ExperimentConfig BEFORE the stage's run log
    opens, so the
    run-dir config snapshot records the dtype/engine the eval actually
    ran — a bf16 number must never be attributable to an f32 config."""
    import dataclasses

    dtype = getattr(args, "compute_dtype", None)
    if dtype:
        config = dataclasses.replace(
            config, model=dataclasses.replace(config.model,
                                              compute_dtype=dtype))
    engine = getattr(args, "mcd_engine", None)
    if engine:
        config = dataclasses.replace(
            config, uq=dataclasses.replace(config.uq, mcd_engine=engine))
    de_engine = getattr(args, "de_engine", None)
    if de_engine:
        config = dataclasses.replace(
            config, uq=dataclasses.replace(config.uq, de_engine=de_engine))
    return config


def _add_de_engine_arg(p) -> None:
    p.add_argument("--de-engine", choices=("xla", "pallas"), default=None,
                   help="Deep-Ensemble predictor engine for this "
                        "invocation (UQConfig.de_engine): 'pallas' runs "
                        "the fused member-batched conv->bias->ReLU->BN "
                        "TPU kernel (ops/pallas_de.py; members replace "
                        "MC passes, no PRNG), falling back to the "
                        "default 'xla' member sweep off-TPU / on a "
                        "mesh.  Tile geometry comes from any persisted "
                        "`apnea-uq autotune` winners.")


def _add_profile_arg(p) -> None:
    p.add_argument("--profile-dir", default=None,
                   help="Wrap the evaluation in a jax.profiler trace and "
                        "write it here (viewable in TensorBoard/XProf); "
                        "the SURVEY §5.1 tracing hook.")


def _add_profile_flag(p) -> None:
    p.add_argument("--profile", action="store_true",
                   help="Capture a bounded jax.profiler trace into "
                        "<run-dir>/profile/<stage> (warmup skip + step "
                        "budget; telemetry/profiler.py), announced as a "
                        "profile_captured event in the run's events.jsonl.")


def _no_double_profile(args) -> None:
    """``--profile`` and ``--profile-dir`` both start a jax.profiler
    session; jax supports one at a time, so nesting them would fail
    mid-evaluation with a confusing profiler error."""
    if getattr(args, "profile", False) and getattr(args, "profile_dir", None):
        raise SystemExit(
            "--profile and --profile-dir are mutually exclusive "
            "(one jax.profiler session at a time); pick the bounded "
            "run-dir capture (--profile) or the explicit directory "
            "(--profile-dir)."
        )


def _print_metrics_doc(doc) -> None:
    """One printer for a run's scalar results — used for live eval output
    AND the `metrics` read-back, so the two can't drift apart."""
    log(f"=== {doc['label']} ===")
    log(f"predict: {doc['predict_seconds']:.2f}s for "
        f"{doc['n_passes']}x{doc['n_windows']} windows"
        + (" (fused reduction)" if doc.get("fused") else ""))
    det = doc.get("deterministic_classification")
    if det is not None:
        log(f"deterministic accuracy: {det['accuracy']:.4f}")
    log(f"stochastic-mean accuracy: {doc['classification']['accuracy']:.4f}")
    cis = doc["confidence_intervals"]
    for k, v in doc["aggregates"].items():
        ci_lo = cis.get(f"{k}_ci_lower")
        ci_hi = cis.get(f"{k}_ci_upper")
        if ci_lo is not None:
            log(f"  {k}: {v:.6f}  [{ci_lo:.6f}, {ci_hi:.6f}]")
        else:
            log(f"  {k}: {v:.6f}")


def _print_run(result) -> None:
    from apnea_uq_tpu.uq import run_metrics_document

    _print_metrics_doc(run_metrics_document(result))


def cmd_eval_mcd(args, config) -> int:
    from apnea_uq_tpu.training import restore_state
    from apnea_uq_tpu.uq import run_mcd_analysis, save_run
    from apnea_uq_tpu.utils.timing import profile_trace

    from apnea_uq_tpu.telemetry.profiler import TraceSession

    _no_double_profile(args)
    config = _apply_eval_overrides(args, config)
    registry = _registry(args)
    model, template = _baseline_template(config)
    state = restore_state(os.path.join(_ckpt_root(args), "baseline"), template)
    uq_config = _eval_uq_config(args, config)
    with _compile_env(args, config), \
            _run(args, "eval-mcd", config) as run_log:
        _prepared, sets = _load_test_sets(registry)
        _emit_drift_fingerprints(registry, sets, run_log)
        for i, (label, (x, y, ids)) in enumerate(sets.items()):
            # Trace only the device-heavy evaluation; plots/registry writes
            # would otherwise dominate the XProf host timeline.  The
            # --profile session is handed UNENTERED to the driver, which
            # brackets only the timed predict — the memory pre-pass's
            # AOT compile stays out of the capture.
            with run_log.stage(f"CNN_MCD_{label}", snapshot_memory=True), \
                    profile_trace(getattr(args, "profile_dir", None)):
                result = run_mcd_analysis(
                    model, state.variables(), x, y, patient_ids=ids,
                    config=uq_config, label=f"CNN_MCD_{label}",
                    seed=config.train.seed,
                    mesh=_mesh(config, num_members=config.uq.mc_passes),
                    detailed=ids is not None and not args.no_detailed,
                    # The reference probes deterministic accuracy once,
                    # before the per-set loop (analyze_mcd_patient_level
                    # .py:203-211) — not once per test set.
                    sanity_check=i == 0,
                    run_log=run_log,
                    profiler=(TraceSession(run_log, label=f"mcd-{label}",
                                           warmup_steps=0)
                              if args.profile else None),
                )
            _print_run(result)
            # Artifact writes are primary-only under a multi-process
            # mesh (the predict results are allgathered, so process 0
            # holds everything the registry needs).
            from apnea_uq_tpu.utils.multihost import is_primary

            if is_primary():
                save_run(registry, result, config=uq_config)
                _emit_plots(args, result)
    return 0


def cmd_eval_de(args, config) -> int:
    from apnea_uq_tpu.uq import run_de_analysis, save_run
    from apnea_uq_tpu.utils.timing import profile_trace

    from apnea_uq_tpu.telemetry.profiler import TraceSession

    _no_double_profile(args)
    config = _apply_eval_overrides(args, config)
    registry = _registry(args)
    model, member_variables = _restore_members(args, config, args.num_members)
    n_members = len(member_variables)  # resolved count (0 -> all existing)
    uq_config = _eval_uq_config(args, config)
    with _compile_env(args, config), \
            _run(args, "eval-de", config) as run_log:
        _prepared, sets = _load_test_sets(registry)
        _emit_drift_fingerprints(registry, sets, run_log)
        for label, (x, y, ids) in sets.items():
            with run_log.stage(f"CNN_DE_{label}", snapshot_memory=True), \
                    profile_trace(getattr(args, "profile_dir", None)):
                result = run_de_analysis(
                    model, member_variables, x, y, patient_ids=ids,
                    config=uq_config, label=f"CNN_DE_{label}",
                    seed=config.train.seed,
                    mesh=_mesh(config, num_members=n_members),
                    detailed=ids is not None and not args.no_detailed,
                    run_log=run_log,
                    profiler=(TraceSession(run_log, label=f"de-{label}",
                                           warmup_steps=0)
                              if args.profile else None),
                )
            _print_run(result)
            from apnea_uq_tpu.utils.multihost import is_primary

            if is_primary():
                save_run(registry, result, config=uq_config)
                _emit_plots(args, result)
    return 0


def _serving_engine(args, config, run_log):
    """Build the serving engine a serve/score invocation runs: restore
    the method's weights (baseline checkpoint for MCD, the ensemble
    store for DE), validate the requested bucket subset, and hand back
    an engine bound to the stage's run log."""
    from apnea_uq_tpu.serving.engine import ServingEngine
    from apnea_uq_tpu.training import restore_state

    if args.method == "mcd":
        model, template = _baseline_template(config)
        state = restore_state(os.path.join(_ckpt_root(args), "baseline"),
                              template)
        carrier = state.variables()
    else:
        model, carrier = _restore_members(args, config,
                                          getattr(args, "num_members", 0))
    buckets = tuple(int(b) for b in args.buckets.split(",") if b.strip())
    return ServingEngine(model, carrier, method=args.method,
                         uq=config.uq, buckets=buckets, run_log=run_log,
                         seed=config.train.seed)


def _drift_monitor(args, run_log):
    """The online drift monitor of a ``--drift-check`` serve/score
    invocation (None without the flag): baseline from the registry's
    frozen ``quality_baseline``, re-score cadence from ``--drift-every``.
    Host-side NumPy end to end — building it compiles nothing."""
    if not getattr(args, "drift_check", False):
        return None
    from apnea_uq_tpu.serving.drift import DriftMonitor

    baseline = DriftMonitor.baseline_from_registry(_registry(args))
    kwargs = {}
    if getattr(args, "drift_every", None):
        kwargs["score_every"] = args.drift_every
    return DriftMonitor(baseline, run_log=run_log, **kwargs)


def cmd_serve(args, config) -> int:
    """The long-lived online scoring process (ISSUE 15 tentpole): warm
    the bucket-ladder programs (all `source=store|cache` after
    `apnea-uq warm-cache` — zero request-path compiles, the PR-6
    contract extended to serving), then coalesce incoming requests into
    fixed bucket batches and stream the serving telemetry triple
    (serve_request / serve_batch / serve_slo) into the run log, where
    `telemetry compare`/`trend` gate the SLO summary.  ``--out``
    appends one NDJSON decomposition row per scored window (keyed by
    request id + window index) — the scoring-API output; without it the
    run is telemetry-only (the loadgen/bench shape)."""
    import json as json_mod

    from apnea_uq_tpu.serving import loadgen as loadgen_mod
    from apnea_uq_tpu.serving.engine import (decomposition_rows,
                                             serve_requests)

    config = _apply_eval_overrides(args, config)
    if not args.loadgen and not args.input:
        raise SystemExit(
            "serve needs a request source: --loadgen N (synthetic "
            "load-generated requests) or --input FILE|- (NDJSON request "
            "lines)"
        )
    if args.loadgen and args.input:
        raise SystemExit(
            "serve takes ONE request source: --loadgen and --input "
            "conflict (silently preferring one would score requests "
            "the operator never asked about)"
        )
    if args.drift_after is not None and not args.loadgen:
        raise SystemExit(
            "--drift-after shifts the synthetic loadgen cohort and "
            "needs --loadgen N (real --input traffic drifts on its own)"
        )
    with _compile_env(args, config), _run(args, "serve", config) as run_log:
        engine = _serving_engine(args, config, run_log)
        drift = _drift_monitor(args, run_log)
        with run_log.stage("warm_buckets"):
            engine.warm()
        if args.loadgen:
            requests = loadgen_mod.synthetic_requests(
                args.loadgen, max_windows=args.request_windows,
                time_steps=config.model.time_steps,
                channels=config.model.num_channels,
                seed=config.train.seed, rate=args.rate,
                arrival=args.arrival, drift_after=args.drift_after,
            )
        else:
            requests = loadgen_mod.ndjson_requests(
                args.input, time_steps=config.model.time_steps,
                channels=config.model.num_channels,
            )
        out_fh = None
        if args.out:
            os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                        exist_ok=True)
            out_fh = open(args.out, "a", encoding="utf-8")

        def on_result(req, stats, start):
            if out_fh is None:
                return
            decomp = decomposition_rows(stats)
            for i in range(int(stats.shape[1])):
                record = {"id": req.request_id, "window": start + i}
                if req.patient is not None:
                    record["patient"] = req.patient
                record.update({k: round(float(v[i]), 6)
                               for k, v in decomp.items()})
                out_fh.write(json_mod.dumps(record) + "\n")
            out_fh.flush()

        if args.input and not args.out:
            log("serve: no --out given — request scores are not "
                "persisted (telemetry-only run)")
        try:
            with run_log.stage("serve"):
                summary = serve_requests(
                    engine, requests, max_wait_s=args.max_wait_ms / 1e3,
                    slo_every=args.slo_every, on_result=on_result,
                    drift=drift, trace_every=args.trace_every,
                    trace_slow_ms=args.trace_slow_ms,
                )
        finally:
            if out_fh is not None:
                out_fh.close()

        def ms(value):
            return "-" if value is None else f"{value}ms"

        log(f"served {summary['requests']} request(s) / "
            f"{summary['windows']} window(s) in {summary['batches']} "
            f"batch(es): p50 {ms(summary['p50_ms'])} p99 "
            f"{ms(summary['p99_ms'])}, {summary['windows_per_s']} "
            f"windows/s, pad waste {summary['pad_waste']}")
        if drift is not None:
            for tenant, verdict in drift.verdicts().items():
                log(f"serve drift [{tenant}]: {verdict} over "
                    f"{drift.windows_seen(tenant)} window(s)")
    return 0


def cmd_score(args, config) -> int:
    """Sliding-window continuous scoring over a live PSG signal stream
    (`--stream`): per-patient ring buffers re-window the sample stream
    with a configurable hop, every window scores through the same
    bucket programs `serve` dispatches, per-window decompositions
    append to --out as NDJSON, and the resumable ring state commits
    atomically under --state-dir after every scored batch (kill -9
    safe; re-feeding the stream resumes without rescoring)."""
    from apnea_uq_tpu.serving.stream import StreamScorer, read_sample_lines

    config = _apply_eval_overrides(args, config)
    if not args.stream:
        raise SystemExit(
            "score currently supports --stream only (the continuous "
            "sliding-window scorer); batch evaluation remains "
            "eval-mcd/eval-de"
        )
    with _compile_env(args, config), _run(args, "score", config) as run_log:
        engine = _serving_engine(args, config, run_log)
        drift = _drift_monitor(args, run_log)
        with run_log.stage("warm_buckets"):
            engine.warm()
        scorer = StreamScorer(
            engine, state_dir=args.state_dir, out_path=args.out,
            hop=args.hop, run_log=run_log, drift=drift,
            trace_every=args.trace_every,
            trace_slow_ms=args.trace_slow_ms,
        )
        with run_log.stage("score_stream"):
            summary = scorer.run(
                read_sample_lines(
                    args.input, follow=args.follow,
                    max_idle_s=args.max_idle_secs,
                ),
                max_pending_s=args.max_pending_secs,
            )
        log(f"scored {summary['windows']} window(s) from "
            f"{len(scorer.patients)} patient stream(s) -> {args.out}")
    return 0


def cmd_demo(args, config) -> int:
    """Zero-data smoke demo of the UQ engine (reference C12 __main__:
    ``python uq_techniques.py`` ran a synthetic 5x1000 evaluation,
    uq_techniques.py:395-446)."""
    from apnea_uq_tpu.uq import run_synthetic_demo

    result = run_synthetic_demo(
        n_models=args.num_models,
        n_windows=args.num_windows,
        seed=args.seed,
        config=config.uq,
    )
    _print_run(result)
    _emit_plots(args, result)
    return 0


def cmd_metrics(args, config) -> int:
    """Read back a persisted evaluation's scalar results (the
    ``metrics:<label>`` JSON artifact written by eval-mcd/eval-de) — the
    numbers the reference only ever printed to a scrolled-away terminal."""
    import json

    from apnea_uq_tpu.data import registry as reg

    registry = _registry(args)
    key = f"{reg.METRICS}:{args.label}"
    if not registry.exists(key):
        have = [
            k.split(":", 1)[1]
            for k in registry.available(f"{reg.METRICS}:")
        ]
        raise SystemExit(
            f"no metrics stored for label {args.label!r} "
            f"(have: {have or 'none'}) — run eval-mcd/eval-de first"
        )
    doc = registry.load_json(key)
    if args.json:
        log(json.dumps(doc, indent=2, sort_keys=True))
        return 0
    _print_metrics_doc(doc)
    return 0


def cmd_aggregate_patients(args, config) -> int:
    from apnea_uq_tpu.analysis import aggregate_patients, patient_summary_report
    from apnea_uq_tpu.data import registry as reg

    registry = _registry(args)
    detailed = registry.load_table(f"{reg.DETAILED_WINDOWS}:{args.label}")
    summary = aggregate_patients(detailed)
    registry.save_table(f"{reg.PATIENT_SUMMARY}:{args.label}", summary)
    log(patient_summary_report(summary))
    return 0


def cmd_analyze_windows(args, config) -> int:
    from apnea_uq_tpu.analysis import (
        calibration_summary,
        retention_curve,
        window_level_analysis,
    )
    from apnea_uq_tpu.data import registry as reg

    registry = _registry(args)
    detailed = registry.load_table(f"{reg.DETAILED_WINDOWS}:{args.label}")
    log(window_level_analysis(detailed, num_bins=args.num_bins).report())
    if args.calibration or args.calibration_plot:
        # --calibration-plot implies --calibration.  Confidence bins are
        # a separate axis from the entropy bins, hence their own flag.
        summary = calibration_summary(detailed,
                                      num_bins=args.calibration_bins)
        log("\nCalibration (mean-probability reliability):")
        log(summary.report())
        if args.calibration_plot:
            from apnea_uq_tpu.analysis.plots import plot_reliability_diagram

            path = plot_reliability_diagram({args.label: summary.bins},
                                            args.calibration_plot)
            log(f"reliability diagram -> {path}")
    if args.retention or args.retention_plot:
        # The thesis headline ("over 99% on the most-confident subset",
        # reference README.md:14) as a reproducible table.
        # --retention-plot implies --retention.
        curve = retention_curve(detailed)
        log("\nSelective prediction (windows retained by lowest "
        "uncertainty first):")
        log(curve.to_string(index=False, float_format="%.4f"))
        if args.retention_plot:
            from apnea_uq_tpu.analysis.plots import plot_retention_curve

            path = plot_retention_curve({args.label: curve},
                                        args.retention_plot)
            log(f"retention plot -> {path}")
    return 0


def cmd_correlate(args, config) -> int:
    from apnea_uq_tpu.analysis import (
        aggregate_patients,
        patient_accuracy_entropy_correlation,
        uncertainty_correctness_test,
    )
    from apnea_uq_tpu.data import registry as reg

    registry = _registry(args)
    for label in args.labels:
        detailed = registry.load_table(f"{reg.DETAILED_WINDOWS}:{label}")
        if registry.exists(f"{reg.PATIENT_SUMMARY}:{label}"):
            summary = registry.load_table(f"{reg.PATIENT_SUMMARY}:{label}")
        else:
            # aggregate-patients hasn't run for this label; derive the
            # summary on the fly (and don't persist — that stage owns it).
            summary = aggregate_patients(detailed)
        corr = patient_accuracy_entropy_correlation(summary)
        log(f"[{label}] patient accuracy vs mean entropy: "
            f"r={corr['pearson_r']:.4f} p={corr['p_value']:.2e} "
            f"(n={corr['n_patients']})")
        mw = uncertainty_correctness_test(detailed)
        verdict = "significant" if mw["significant"] else "not significant"
        log(f"[{label}] entropy(incorrect) > entropy(correct): "
            f"U={mw['u_statistic']:.0f} p={mw['p_value']:.2e} ({verdict})")
    return 0


def cmd_sweep(args, config) -> int:
    from apnea_uq_tpu.analysis.plots import plot_convergence

    if args.from_csv:
        # Plot an existing sweep table (the reference's C20 workflow: its
        # convergence CSVs were hand-collected, and
        # hyperparameter_plot_mcd_or_de_pass_convergence.py only plots
        # them).  Schema: column ``N`` + one ``Variance_<set>`` per set.
        # This branch stays above the sweep/training imports so a
        # plot-only run never pays JAX initialization.
        import pandas as pd

        if not args.plot:
            raise SystemExit("--from-csv requires --plot OUT.png")
        frame = pd.read_csv(args.from_csv)
        log(frame.to_string(index=False))
        path = plot_convergence(frame, args.plot)
        log(f"convergence plot -> {path}")
        return 0

    from apnea_uq_tpu.analysis.sweep import de_member_sweep, mcd_pass_sweep
    from apnea_uq_tpu.data import registry as reg
    from apnea_uq_tpu.training import restore_state
    from apnea_uq_tpu.utils import prng

    if not (args.registry and args.method and args.counts):
        raise SystemExit(
            "sweep needs --registry, --method and --counts (or --from-csv "
            "with --plot to plot an existing table)"
        )
    registry = _registry(args)
    _prepared, sets = _load_test_sets(registry)
    test_sets = {label: x for label, (x, _y, _ids) in sets.items()}
    counts = [int(c) for c in args.counts]
    if args.method == "mcd":
        model, template = _baseline_template(config)
        state = restore_state(os.path.join(_ckpt_root(args), "baseline"), template)
        frame = mcd_pass_sweep(
            model, state.variables(), test_sets,
            pass_counts=counts, config=config.uq,
            key=prng.stochastic_key(config.train.seed),
            mesh=_mesh(config, num_members=max(counts)),
        )
    else:
        model, member_variables = _restore_members(args, config, max(counts))
        frame = de_member_sweep(
            model, member_variables, test_sets,
            member_counts=counts, config=config.uq,
            mesh=_mesh(config, num_members=max(counts)),
        )
    # Canonical key, not a literal: `apnea-uq flow` flags string-spelled
    # keys as artifact-key-drift (this very line was the true positive).
    key = f"{reg.SWEEP}:{args.method}"
    from apnea_uq_tpu.utils.multihost import is_primary

    if is_primary():
        # apnea-lint: disable=artifact-never-consumed -- end product: the convergence table is plotted here and read back by analysts, not by a later stage
        registry.save_table(key, frame)
    log(frame.to_string(index=False))
    if args.plot:
        path = plot_convergence(frame, args.plot)
        log(f"convergence plot -> {path}")
    return 0


def cmd_figures(args, config) -> int:
    from apnea_uq_tpu.analysis import (
        aggregate_patients,
        retention_curve,
        window_level_analysis,
    )
    from apnea_uq_tpu.analysis import plots
    from apnea_uq_tpu.data import registry as reg

    registry = _registry(args)
    frames = {
        label: registry.load_table(f"{reg.DETAILED_WINDOWS}:{label}")
        for label in args.labels
    }
    summaries = {k: aggregate_patients(v) for k, v in frames.items()}
    binned = {
        k: window_level_analysis(v, num_bins=args.num_bins).binned
        for k, v in frames.items()
    }
    retention = {k: retention_curve(v) for k, v in frames.items()}
    out = args.out_dir
    paths = [
        plots.plot_patient_entropy_histograms(
            summaries, os.path.join(out, "patient_entropy_hist.png")),
        plots.plot_accuracy_vs_entropy(
            summaries, os.path.join(out, "accuracy_vs_entropy.png")),
        plots.plot_correct_incorrect_box(
            frames, os.path.join(out, "correct_incorrect_box.png")),
        plots.plot_binned_accuracy(
            binned, os.path.join(out, "binned_accuracy.png")),
        # MCD-vs-DE selective prediction in one frame — the comparison
        # behind the reference's ">99% on the most-confident subset"
        # headline (README.md:14).
        plots.plot_retention_curve(
            retention, os.path.join(out, "retention_curves.png")),
    ]
    for p in paths:
        log(f"wrote {p}")
    return 0


def cmd_telemetry_summarize(args) -> int:
    """Render a run directory's ``events.jsonl`` (written by the train/
    eval stages and bench.py) as the per-stage wall/device-time,
    throughput, recompile-count and HBM/headroom tables — the read side
    of the telemetry layer.  Needs no config and never imports jax.
    ``--json`` emits the same fields machine-readable."""
    import json

    from apnea_uq_tpu.telemetry import summarize_data, summarize_run
    from apnea_uq_tpu.telemetry.summarize import (
        summarize_all_runs_data,
        summarize_all_runs_text,
    )

    try:
        if getattr(args, "all_runs", False):
            if args.json:
                log(json.dumps(summarize_all_runs_data(args.run_dir),
                               indent=2))
            else:
                log(summarize_all_runs_text(args.run_dir))
        elif args.json:
            log(json.dumps(summarize_data(args.run_dir), indent=2))
        else:
            log(summarize_run(args.run_dir))
    except FileNotFoundError as e:
        raise SystemExit(str(e))
    return 0


def cmd_telemetry_fleet(args) -> int:
    """Cross-replica SLO rollup (ISSUE 18): merge N serve replica run
    dirs' final serve_slo digests into fleet p50/p95/p99 + throughput
    overall and per bucket, render the per-replica attribution table
    (outlier flagged at ``--spread-threshold`` times the median replica
    p99), and roll up serve_drift verdicts per tenant (worst wins).
    ``--out DIR`` persists the rollup as a ``fleet_rollup`` event +
    registry artifact so `telemetry compare`/`trend` gate it like any
    run dir.  Findings ride the shared lint reporters (text / ``--json``
    / ``--format gha``).  Exit 0 clean, 1 on an outlier or drifted
    tenant, 2 when a source carries no fleet telemetry — never a clean
    pass over zero replicas.  Needs no config and never imports jax."""
    from apnea_uq_tpu.lint.report import emit_result, resolve_format
    from apnea_uq_tpu.telemetry import fleet as fleet_mod

    try:
        rollup = fleet_mod.build_rollup(
            args.run_dirs, spread_threshold=args.spread_threshold)
    except fleet_mod.NoFleetTelemetry as e:
        log(f"apnea-uq telemetry fleet: {e}")
        raise SystemExit(2)
    except (FileNotFoundError, ValueError, OSError) as e:
        raise SystemExit(str(e))
    if args.out:
        try:
            fleet_mod.record_rollup(rollup, args.out)
            log(f"fleet rollup -> {args.out}")
        except OSError as e:
            # Best-effort like the quality gate's audit append: a
            # read-only destination must not cost the user the rollup.
            log(f"fleet rollup not recorded in {args.out}: {e}")
    fmt = resolve_format(args)
    if fmt == "text":
        log(fleet_mod.render_fleet(rollup))
    emit_result(fleet_mod.fleet_result(rollup), fmt,
                subject="replica(s)",
                json_extra={"fleet_rollup": fleet_mod.rollup_data(rollup)})
    return 1 if fleet_mod.fleet_findings(rollup) else 0


def cmd_telemetry_trace(args) -> int:
    """Cross-replica critical-path analyzer (ISSUE 20): merge N serve
    run dirs' serve_trace spans (globally-unique ids, torn tails
    tolerated), reconstruct per-request waterfalls, attribute latency
    to queue vs service vs pad overhead at p50/p95/p99 per bucket and
    per replica, name the replica/phase dominating the fleet tail, and
    audit tail-based exemplar coverage against the serve_slo counter
    ledgers.  ``--out DIR`` persists the report as a ``trace_report``
    event + registry artifact so `telemetry compare` gates
    trace.queue_share_p99 / trace.service_share_p99 /
    trace.exemplar_coverage and `telemetry trend` carries them.
    Findings ride the shared lint reporters (text / ``--json`` /
    ``--format gha``).  Exit 0 clean, 1 on a collision / missing
    exemplar / tail-dominating replica, 2 when no source carries spans
    — never a clean pass over zero spans.  Needs no config and never
    imports jax."""
    from apnea_uq_tpu.lint.report import emit_result, resolve_format
    from apnea_uq_tpu.telemetry import spans as spans_mod

    try:
        report = spans_mod.build_trace(args.run_dirs)
    except spans_mod.NoTraceTelemetry as e:
        log(f"apnea-uq telemetry trace: {e}")
        raise SystemExit(2)
    except (FileNotFoundError, ValueError, OSError) as e:
        raise SystemExit(str(e))
    if args.out:
        try:
            spans_mod.record_trace(report, args.out)
            log(f"trace report -> {args.out}")
        except OSError as e:
            # Best-effort like the fleet rollup: a read-only
            # destination must not cost the user the analysis.
            log(f"trace report not recorded in {args.out}: {e}")
    fmt = resolve_format(args)
    if fmt == "text":
        log(spans_mod.render_trace(report))
    emit_result(spans_mod.trace_result(report), fmt,
                subject="replica(s)",
                json_extra={"trace_report": spans_mod.trace_data(report)})
    return 1 if spans_mod.trace_findings(report) else 0


def cmd_telemetry_compare(args) -> int:
    """Metric regression gate: compare a baseline and a candidate (each
    a BENCH_r*.json capture or a telemetry run dir), exit 1 when any
    metric worsened past its threshold — so bench/CI can gate on the
    exit code.  Needs no config and never imports jax."""
    import json

    from apnea_uq_tpu.telemetry import compare as compare_mod

    per_metric = {}
    for spec in args.metric_threshold or []:
        name, sep, pct = spec.rpartition("=")
        if not sep or not name:
            raise SystemExit(
                f"--metric-threshold takes NAME=PCT, got {spec!r}")
        try:
            per_metric[name] = float(pct)
        except ValueError:
            raise SystemExit(
                f"--metric-threshold {spec!r}: {pct!r} is not a number")
    directions = {}
    for spec in args.metric_direction or []:
        name, sep, word = spec.rpartition("=")
        if not sep or not name or word not in ("higher", "lower"):
            raise SystemExit(
                f"--metric-direction takes NAME=higher|lower, got {spec!r}")
        directions[name] = word == "higher"
    try:
        comparison = compare_mod.compare_paths(
            args.baseline, args.candidate,
            threshold_pct=args.threshold_pct,
            per_metric_threshold=per_metric,
            per_metric_direction=directions,
        )
    except compare_mod.NoComparableMetrics as e:
        # A bench_error capture (or an otherwise metric-free source) is a
        # usage error: exit 2, like lint's bad-input path — never a clean
        # exit-0 "no regressions" over zero metrics, and distinct from
        # exit 1 = a real regression.
        log(f"apnea-uq telemetry compare: {e}")
        raise SystemExit(2)
    except (FileNotFoundError, ValueError, OSError) as e:
        raise SystemExit(str(e))
    if args.json:
        log(json.dumps(compare_mod.comparison_data(comparison), indent=2))
    else:
        log(compare_mod.render_comparison(comparison))
    return 1 if comparison.regressions else 0


def cmd_telemetry_trend(args) -> int:
    """The cross-run perf-trajectory ledger: ingest every archived
    BENCH_r*.json round (error rounds become gaps, never crashes) plus
    any extra capture files / run dirs, and render the per-metric
    best/latest/delta series with regression flags.  ``--update-docs``
    regenerates the byte-for-byte-pinned docs/BENCH_TRAJECTORY.md from
    the archived rounds alone.  Needs no config and never imports jax."""
    import json

    from apnea_uq_tpu.telemetry import trend as trend_mod

    archived = trend_mod.archived_rounds(args.rounds_dir)
    if args.update_docs:
        if args.sources:
            # The doc is byte-pinned against a render from the archived
            # rounds alone; silently dropping extra sources would let
            # the user believe their round made it into the doc.
            raise SystemExit(
                "telemetry trend --update-docs renders the archived "
                "BENCH_r*.json / MULTICHIP_r*.json rounds only and "
                f"cannot include extra sources ({args.sources}); "
                "archive the capture as BENCH_r<N>.json first, or "
                "render it ad hoc without --update-docs"
            )
        if not archived:
            raise SystemExit(
                "telemetry trend --update-docs: no BENCH_r*.json or "
                "MULTICHIP_r*.json rounds found under "
                f"{args.rounds_dir or trend_mod.default_rounds_dir()!r}"
            )
        from apnea_uq_tpu.utils.io import atomic_write_text

        # Archived rounds ONLY: the doc is pinned byte-for-byte against
        # a fresh render, so ad-hoc extra sources must not leak into it.
        traj = trend_mod.build_trajectory(
            [trend_mod.load_round(p) for p in archived],
            threshold_pct=args.threshold_pct,
        )
        docs_path = args.docs or os.path.join(
            trend_mod.default_rounds_dir(), trend_mod.DOC_RELPATH)
        atomic_write_text(docs_path, trend_mod.render_trajectory_doc(traj))
        log(f"wrote {docs_path}")
        return 0
    # Beside the archived captures, sweep <rounds-dir>/runs/ for
    # telemetry run directories (the registry layout) so quality/eval
    # history rides the ledger without hand-listing run dirs.  Dedupe
    # by real path: a --sources run dir that the sweep also finds must
    # contribute ONE round, not double-count its series.
    paths = []
    seen = set()
    for p in (archived + trend_mod.registry_run_dirs(args.rounds_dir)
              + list(args.sources or [])):
        real = os.path.realpath(p)
        if real not in seen:
            seen.add(real)
            paths.append(p)
    if not paths:
        raise SystemExit(
            "telemetry trend: no BENCH_r*.json / MULTICHIP_r*.json "
            "rounds or runs/ directories found under "
            f"{args.rounds_dir or trend_mod.default_rounds_dir()!r} and no extra "
            "sources given"
        )
    traj = trend_mod.build_trajectory(
        [trend_mod.load_round(p) for p in paths],
        threshold_pct=args.threshold_pct,
    )
    if args.json:
        log(json.dumps(trend_mod.trajectory_data(traj), indent=2))
    else:
        log(trend_mod.render_trajectory(traj))
    return 0


def cmd_quality_check(args) -> int:
    """The model-quality gate: drift scores over threshold and (with
    ``--baseline``) calibration regressions vs a prior run become
    nonzero exit codes CI can gate on.  Reads only ``events.jsonl``
    (latest run of an appended log) — no config, never imports jax —
    and renders findings through the shared lint reporters (text /
    ``--json`` / ``--format gha``).  The verdict is appended to the
    checked run's own log as a ``quality_gate`` event.  Exit 0 clean,
    1 on a failed check, 2 when a source carries no quality telemetry
    (`telemetry compare`'s usage-error contract: a gate must never
    report a clean pass over zero metrics)."""
    from apnea_uq_tpu.lint.report import emit_result, resolve_format
    from apnea_uq_tpu.telemetry import quality as quality_mod

    try:
        gate = quality_mod.check_run(
            args.run_dir,
            baseline=args.baseline,
            threshold_pct=args.threshold_pct,
            psi_threshold=args.psi_threshold,
            ks_threshold=args.ks_threshold,
        )
    except quality_mod.NoQualityTelemetry as e:
        log(f"apnea-uq quality check: {e}")
        raise SystemExit(2)
    except (FileNotFoundError, ValueError, OSError) as e:
        raise SystemExit(str(e))
    try:
        quality_mod.record_gate_event(gate)
    except OSError as e:
        # The audit-trail append is best-effort: a read-only run dir
        # (CI artifact mount) must not cost the user the verdict the
        # gate just computed.
        log(f"quality gate verdict not recorded in {args.run_dir}: {e}")
    emit_result(quality_mod.gate_result(gate), resolve_format(args),
                subject="check(s)",
                json_extra={"quality_gate": quality_mod.gate_data(gate)})
    return 0 if gate.passed else 1


def cmd_telemetry_watch(args) -> int:
    """The hardware-watch evidence autopilot: probe the TPU backend with
    bench's backoff probe and, on the first green probe, run the
    round-5 evidence ritual (bench capture + TPU-gated tests) into a
    fresh run dir under ``--out``.  Imports jax only in probe
    subprocesses, never in this process."""
    from apnea_uq_tpu.telemetry import watch as watch_mod

    return watch_mod.watch(
        args.out,
        budget_s=args.budget_secs,
        probe_timeout_s=args.probe_secs,
        skip_tests=args.skip_tests,
    )


def cmd_cohort(args, config) -> int:
    import pandas as pd

    from apnea_uq_tpu.analysis.cohort import (
        analyze_cohort,
        analyze_signal_quality,
        format_cohort_report,
        format_signal_quality_report,
    )

    metadata = pd.read_csv(args.metadata_csv, encoding="latin1", low_memory=False)
    log(format_cohort_report(analyze_cohort(metadata)))
    if args.signal_quality:
        log()
        log(format_signal_quality_report(analyze_signal_quality(metadata)))
    return 0


def cmd_check(args, config) -> int:
    """The ``apnea-uq check`` meta-gate: lint + flow + audit + topo +
    conc in one invocation, merged output, one exit code (0 all clean,
    1 any findings, 2 any usage error) — so CI needs one step, not
    five.  Each gate runs with its tier-1 defaults; a gate's usage
    error is reported and the remaining gates still run, so one broken
    manifest cannot hide another gate's findings."""
    import argparse

    # Pin the canonical analysis rig BEFORE any gate touches jax: audit
    # runs before topo and would otherwise initialize a 1-device CPU
    # backend, after which topo's own pin (guarded by "jax not yet
    # imported") can no longer apply and its sweep would see a 1x1
    # topology with no manifest rows — failing the documented
    # `JAX_PLATFORMS=cpu apnea-uq check` recipe on a clean tree.
    from apnea_uq_tpu.utils.env import pin_host_analysis_rig

    pin_host_analysis_rig()

    from apnea_uq_tpu.audit.cli import cmd_audit
    from apnea_uq_tpu.audit.manifest import (
        DEFAULT_MANIFEST_PATH as AUDIT_MANIFEST,
    )
    from apnea_uq_tpu.compilecache.zoo import WARM_GROUPS
    from apnea_uq_tpu.conc.cli import cmd_conc
    from apnea_uq_tpu.flow.cli import cmd_flow
    from apnea_uq_tpu.flow.manifest import (
        DEFAULT_MANIFEST_PATH as FLOW_MANIFEST,
    )
    from apnea_uq_tpu.lint.cli import cmd_lint
    from apnea_uq_tpu.topo.cli import cmd_topo
    from apnea_uq_tpu.topo.manifest import (
        DEFAULT_MANIFEST_PATH as TOPO_MANIFEST,
    )

    fmt = args.format
    common = dict(paths=None, json=False, format=fmt, rule=[])
    gates = (
        ("lint", lambda: cmd_lint(argparse.Namespace(**common))),
        ("flow", lambda: cmd_flow(argparse.Namespace(
            **common, manifest=FLOW_MANIFEST, update_manifest=False,
            update_docs=False, docs=None))),
        ("audit", lambda: cmd_audit(argparse.Namespace(
            programs=",".join(WARM_GROUPS), json=False, format=fmt,
            rule=[], update_manifest=False, manifest=AUDIT_MANIFEST,
            run_dir=None), config)),
        ("topo", lambda: cmd_topo(argparse.Namespace(
            **common, manifest=TOPO_MANIFEST, update_manifest=False,
            update_docs=False, docs=None, run_dir=None), config)),
        ("conc", lambda: cmd_conc(argparse.Namespace(**common))),
    )
    codes = {}
    for name, run in gates:
        if fmt != "gha":
            log(f"== apnea-uq {name} ==")
        try:
            codes[name] = run()
        except SystemExit as e:
            codes[name] = int(e.code or 0)
    if fmt != "gha":
        verdicts = ", ".join(
            f"{name}: {'clean' if rc == 0 else 'FINDINGS' if rc == 1 else 'USAGE ERROR'}"
            for name, rc in codes.items())
        log(f"== check: {verdicts} ==")
    if any(rc == 2 for rc in codes.values()):
        return 2
    return 1 if any(rc == 1 for rc in codes.values()) else 0


# -------------------------------------------------------------- registry --

def register(sub, add_config_arg, load_config_fn) -> None:
    def add(name, fn, help_text):
        p = sub.add_parser(name, help=help_text)
        add_config_arg(p)
        p.set_defaults(fn=lambda args: fn(args, load_config_fn(args)))
        return p

    p = add("ingest", cmd_ingest, "EDF+XML recordings -> labeled windows.")
    p.add_argument("--edf-dir", required=True)
    p.add_argument("--xml-dir", required=True)
    p.add_argument("--registry", required=True)
    p.add_argument("--num-files", type=int, default=None)
    p.add_argument("--workers", type=int, default=0)
    p.add_argument("--mode", choices=("thread", "process"), default="thread",
                   help="Worker pool flavor for --workers > 0: 'thread' "
                        "(GIL-releasing NumPy decode) or 'process' "
                        "(fully parallel CPU-bound decode+resample). "
                        "Results keep job order either way.")
    p.add_argument("--store", action="store_true",
                   help="Stream recordings straight into a sharded memmap "
                        "store (array_store kind; data/store.py): peak "
                        "host memory O(one recording), resumable after "
                        "kill -9 via the per-recording progress manifest.")
    p.add_argument("--fresh", action="store_true",
                   help="With --store: discard any previous ingest "
                        "progress and shards instead of resuming.")
    _add_run_dir_arg(p)

    p = add("prepare", cmd_prepare,
            "Windows -> split/standardized/balanced train+test arrays.")
    p.add_argument("--registry", required=True)
    p.add_argument("--from-csv", default=None,
                   help="Ingest from a reference-format flattened CSV instead "
                        "of the registry windows artifact.")
    p.add_argument("--store", action="store_true",
                   help="Write the prepared artifacts as sharded memmap "
                        "stores; with a store-kind windows artifact the "
                        "whole prepare runs out-of-core (O(block) host "
                        "memory).")
    _add_run_dir_arg(p)

    p = add("migrate", cmd_migrate,
            "Convert .npz array artifacts to sharded memmap stores "
            "(zero-copy loads) in place.")
    p.add_argument("--registry", required=True)
    p.add_argument("--keys", nargs="*", default=None,
                   help="Artifact keys to convert (default: every .npz "
                        "array artifact in the registry).")
    p.add_argument("--rows-per-shard", type=int, default=65536)

    p = add("train", cmd_train, "Train the baseline 1D-CNN.")
    p.add_argument("--registry", required=True)
    p.add_argument("--ckpt-dir", default=None)
    _add_run_dir_arg(p)
    _add_profile_flag(p)

    p = add("train-ensemble", cmd_train_ensemble,
            "Train the Deep Ensemble (mesh-parallel, resumable).")
    p.add_argument("--registry", required=True)
    p.add_argument("--ckpt-dir", default=None)
    _add_run_dir_arg(p)
    _add_profile_flag(p)

    p = add("warm-cache", cmd_warm_cache,
            "Precompile the hot-path program zoo (AOT program store + "
            "persistent XLA cache) so later stages start hot.")
    p.add_argument("--registry", required=True)
    p.add_argument("--ckpt-dir", default=None)
    _add_run_dir_arg(p)
    # Derived from the zoo (jax-free import) so a new warm group lands
    # in the default scope of BOTH warm-cache and audit automatically.
    from apnea_uq_tpu.compilecache.zoo import WARM_GROUPS

    p.add_argument("--programs", default=",".join(WARM_GROUPS),
                   help=f"Comma-separated stage groups to warm "
                        f"({','.join(WARM_GROUPS)}; default all).")
    p.add_argument("--num-members", type=int, default=0,
                   help="Ensemble members the later eval-de will run "
                        "with (must match its --num-members; default 0 "
                        "= every checkpointed member when an ensemble "
                        "store exists, else the configured "
                        "EnsembleConfig.num_members).")
    _add_compute_dtype_arg(p)
    p.add_argument("--mcd-engine", choices=("xla", "pallas"), default=None,
                   help="Warm the MCD programs under this engine's "
                        "labels (UQConfig.mcd_engine) — must match the "
                        "later eval-mcd/serve --mcd-engine for warm "
                        "starts.")
    _add_de_engine_arg(p)

    p = add("autotune", cmd_autotune,
            "Measure fused-kernel tile geometry (window_tile x "
            "member_group/pass_group) and persist the winners beside "
            "the program store for warm-cache/serve to bake in.")
    p.add_argument("--registry", required=True)
    p.add_argument("--ckpt-dir", default=None)
    _add_run_dir_arg(p)
    p.add_argument("--num-members", type=int, default=0,
                   help="DE members to time with (0 = every checkpointed "
                        "member when an ensemble store exists, else the "
                        "configured EnsembleConfig.num_members) — match "
                        "the warm-cache/eval-de member count.")
    p.add_argument("--windows", type=int, default=64,
                   help="Window count of the batch-predict timing point.")
    from apnea_uq_tpu.serving.coalescer import (
        SERVE_BUCKET_SIZES as _LADDER,
    )

    p.add_argument("--buckets", default=",".join(str(b) for b in _LADDER),
                   help=f"Serving buckets to tune per-bucket kernels for "
                        f"(subset of {_LADDER}).")
    p.add_argument("--window-tiles", default="8,16,32",
                   help="Comma-separated window_tile grid to sweep.")
    p.add_argument("--groups", default="4,8,16",
                   help="Comma-separated member_group/pass_group grid to "
                        "sweep.")
    p.add_argument("--reps", type=int, default=3,
                   help="Timing repetitions per cell (best-of).")

    p = add("eval-mcd", cmd_eval_mcd, "MC-Dropout UQ analysis on the test sets.")
    p.add_argument("--registry", required=True)
    p.add_argument("--ckpt-dir", default=None)
    _add_run_dir_arg(p)
    _add_no_detailed_arg(p)
    _add_full_probs_arg(p)
    _add_compute_dtype_arg(p)
    p.add_argument("--mcd-engine", choices=("xla", "pallas"), default=None,
                   help="MCD predictor engine for this invocation "
                        "(UQConfig.mcd_engine): 'pallas' runs the fused "
                        "conv->BN->ReLU->dropout TPU kernel "
                        "(ops/pallas_mcd.py; masks drawn in-kernel from "
                        "the hardware PRNG), falling back to the "
                        "default 'xla' body off-TPU / in parity mode / "
                        "on a mesh.")
    _add_plots_arg(p)
    _add_profile_arg(p)
    _add_profile_flag(p)

    p = add("eval-de", cmd_eval_de, "Deep-Ensemble UQ analysis on the test sets.")
    p.add_argument("--registry", required=True)
    p.add_argument("--ckpt-dir", default=None)
    _add_run_dir_arg(p)
    p.add_argument("--num-members", type=int, default=5,
                   help="Ensemble members to evaluate (default 5); 0 (or "
                        "negative) evaluates every checkpointed member — "
                        "incl. padded slots promoted by "
                        "EnsembleConfig.keep_padded_members.")
    _add_no_detailed_arg(p)
    _add_full_probs_arg(p)
    _add_compute_dtype_arg(p)
    _add_de_engine_arg(p)
    _add_plots_arg(p)
    _add_profile_arg(p)
    _add_profile_flag(p)

    # The online serving tier (ISSUE 15): serve = request-path scoring
    # behind the coalescer's bucket ladder; score = sliding-window
    # continuous scoring over a live signal stream.  Both dispatch the
    # zoo's `serve` group programs, so `apnea-uq warm-cache` makes them
    # start with zero request-path compiles.
    def _add_serving_args(p) -> None:
        # jax-free on purpose: the parser must build with jax poisoned
        # (the ladder constant lives in the host-side coalescer, the
        # drift cadence in the NumPy-only drift monitor).
        from apnea_uq_tpu.serving.coalescer import SERVE_BUCKET_SIZES
        from apnea_uq_tpu.serving.drift import DEFAULT_SCORE_EVERY

        p.add_argument("--registry", required=True)
        p.add_argument("--ckpt-dir", default=None)
        p.add_argument("--method", choices=("mcd", "de"), default="mcd",
                       help="UQ method to serve: clean-mode MC-Dropout "
                            "from the baseline checkpoint (default) or "
                            "the deterministic Deep Ensemble.")
        p.add_argument("--num-members", type=int, default=0,
                       help="With --method de: ensemble members to "
                            "serve (0 = every checkpointed member, the "
                            "eval-de contract).  Must match the "
                            "warm-cache --num-members for warm starts.")
        p.add_argument("--buckets",
                       default=",".join(str(b) for b in SERVE_BUCKET_SIZES),
                       help=f"Comma-separated bucket ladder (subset of "
                            f"the registered serving buckets "
                            f"{SERVE_BUCKET_SIZES}; each bucket is a "
                            f"warm-cache/audit program label).")
        _add_compute_dtype_arg(p)
        p.add_argument("--mcd-engine", choices=("xla", "pallas"),
                       default=None,
                       help="With --method mcd: serve through this "
                            "engine's bucket labels (UQConfig."
                            "mcd_engine) — match the warm-cache "
                            "--mcd-engine for warm starts.")
        p.add_argument("--drift-check", action="store_true",
                       help="Online input-drift detection (ISSUE 17): "
                            "keep one rolling fingerprint per "
                            "stream/tenant on the frozen "
                            "quality_baseline's histogram edges and "
                            "emit gateable serve_drift verdicts "
                            "(host-side NumPy — zero extra request-path "
                            "compiles; `apnea-uq quality check "
                            "<run-dir>` gates them).")
        p.add_argument("--drift-every", type=int, default=None,
                       metavar="N",
                       help=f"With --drift-check: re-score a tenant's "
                            f"rolling fingerprint against the baseline "
                            f"every N folded windows (default "
                            f"{DEFAULT_SCORE_EVERY}).")
        _add_de_engine_arg(p)
        _add_run_dir_arg(p)

    def _add_trace_args(p):
        # Shared by `serve` and `score --stream`: the ISSUE 17 head
        # sampler plus ISSUE 20's tail-based exemplar capture.
        p.add_argument("--trace-every", type=int, default=0, metavar="N",
                       help="Sample every N-th completed request into a "
                            "serve_trace span event: the enqueue -> "
                            "coalesce -> dispatch -> D2H -> respond "
                            "waterfall with bucket/pad attribution "
                            "(0 = off; the first completed request "
                            "always emits when tracing is on).")
        p.add_argument("--trace-slow-ms", type=float, default=0.0,
                       metavar="MS",
                       help="Tail-based exemplar capture: EVERY request "
                            "over this latency budget emits its "
                            "serve_trace waterfall (never sampled "
                            "away — the trace.exemplar_coverage == 1.0 "
                            "contract), plus rolling per-bucket p99 "
                            "outliers through a bounded reservoir "
                            "(0 = off).  `apnea-uq telemetry trace` "
                            "audits the coverage across replicas.")

    p = add("serve", cmd_serve,
            "Long-lived online UQ scoring: coalesced bucket batches "
            "through AOT-warm fused-stats programs, with SLO telemetry.")
    _add_serving_args(p)
    p.add_argument("--loadgen", type=int, default=0, metavar="N",
                   help="Serve N synthetic load-generated requests "
                        "(serving/loadgen.py) instead of reading "
                        "--input, then exit — the bench/acceptance "
                        "mode.")
    p.add_argument("--rate", type=float, default=0.0,
                   help="With --loadgen: open-loop arrival rate in "
                        "requests/sec (0 = as fast as possible).")
    p.add_argument("--arrival", choices=("uniform", "poisson"),
                   default="uniform",
                   help="With --loadgen and --rate: arrival schedule — "
                        "'uniform' paces at a fixed i/rate cadence, "
                        "'poisson' draws seeded exponential gaps of "
                        "mean 1/rate (the bursty process capacity "
                        "sweeps use; payloads are identical either "
                        "way).")
    p.add_argument("--request-windows", type=int, default=4,
                   help="With --loadgen: max windows per synthetic "
                        "request (sizes draw uniformly from 1..N).")
    p.add_argument("--drift-after", type=int, default=None, metavar="N",
                   help="With --loadgen: apply a per-channel mean/scale "
                        "shift to every request from the N-th on — the "
                        "seeded way to exercise --drift-check (the "
                        "first N requests score PSI ~ 0, the shifted "
                        "cohort flips the serve_drift verdict).")
    _add_trace_args(p)
    p.add_argument("--input", default=None,
                   help="NDJSON request source (- = stdin): one "
                        "{\"id\", \"windows\": [[[ch]x60]xk], "
                        "optional \"trace_id\"} object per line "
                        "(an inbound trace_id rides into the span id "
                        "<replica_id>/<trace_id>).")
    p.add_argument("--max-wait-ms", type=float, default=5.0,
                   help="Coalescing deadline: a partial batch "
                        "dispatches once its oldest request has waited "
                        "this long (the latency/efficiency knob).")
    p.add_argument("--slo-every", type=int, default=100,
                   help="Emit a cumulative serve_slo snapshot every N "
                        "completed requests (the final summary always "
                        "emits).")
    p.add_argument("--out", default=None,
                   help="Append one NDJSON decomposition row per scored "
                        "window (keyed by request id + window index) — "
                        "the scoring-API output.  Omitted = telemetry-"
                        "only run (the loadgen/bench shape).")

    p = add("score", cmd_score,
            "Continuous sliding-window scoring over a live PSG sample "
            "stream, with resumable per-patient ring state.")
    _add_serving_args(p)
    p.add_argument("--stream", action="store_true",
                   help="Consume a live per-sample NDJSON stream "
                        "(required; batch evaluation remains "
                        "eval-mcd/eval-de).")
    p.add_argument("--input", required=False, default="-",
                   help="Sample NDJSON source (- = stdin): one "
                        "{\"patient\", \"t\", \"v\": [4 floats]} "
                        "object per line.")
    p.add_argument("--hop", type=int, default=60,
                   help="Samples between consecutive window starts "
                        "(60 = non-overlapping 60-s windows; smaller = "
                        "overlapping re-windowing).")
    p.add_argument("--state-dir", required=True,
                   help="Where the resumable per-patient ring state "
                        "commits (stream_state.json, atomic per scored "
                        "batch — kill -9 safe).")
    p.add_argument("--out", required=True,
                   help="Per-window decomposition NDJSON results file "
                        "(appended; windows key on patient+start_t).")
    p.add_argument("--follow", action="store_true",
                   help="Keep tailing --input past EOF (file-tail "
                        "mode) until --max-idle-secs passes with no "
                        "new samples.")
    p.add_argument("--max-idle-secs", type=float, default=5.0,
                   help="With --follow: exit after this long with no "
                        "stream growth.")
    p.add_argument("--max-pending-secs", type=float, default=1.0,
                   help="Score a partial batch once its oldest pending "
                        "window has waited this long — the live-stream "
                        "latency/crash-loss bound (a slow feed must not "
                        "hold admitted samples hostage to a full "
                        "max-bucket batch).")
    _add_trace_args(p)

    p = add("metrics", cmd_metrics,
            "Print a stored evaluation's aggregates/CIs/accuracy.")
    p.add_argument("--registry", required=True)
    p.add_argument("--label", required=True,
                   help="Run label, e.g. CNN_MCD_Unbalanced.")
    p.add_argument("--json", action="store_true",
                   help="Dump the raw metrics JSON document.")

    p = add("aggregate-patients", cmd_aggregate_patients,
            "Detailed windows -> per-patient summary.")
    p.add_argument("--registry", required=True)
    p.add_argument("--label", required=True,
                   help="Run label, e.g. CNN_MCD_Unbalanced.")

    p = add("analyze-windows", cmd_analyze_windows,
            "Window-level uncertainty-vs-correctness analysis.")
    p.add_argument("--registry", required=True)
    p.add_argument("--label", required=True)
    p.add_argument("--num-bins", type=int, default=10)
    p.add_argument("--retention", action="store_true",
                   help="Also print the selective-prediction retention "
                        "table (accuracy on the lowest-uncertainty "
                        "fraction; reference README.md:14's >99%% claim).")
    p.add_argument("--retention-plot", default=None,
                   help="With --retention: write the accuracy-vs-retained"
                        "-fraction curve PNG here.")
    p.add_argument("--calibration", action="store_true",
                   help="Also print the reliability table + ECE/MCE/Brier "
                        "of the mean predicted probabilities.")
    p.add_argument("--calibration-plot", default=None,
                   help="With --calibration: write the reliability-diagram "
                        "PNG here.")
    p.add_argument("--calibration-bins", type=int, default=15,
                   help="Confidence bins for the reliability table/ECE "
                        "(independent of --num-bins, which bins entropy).")

    p = add("correlate", cmd_correlate,
            "Patient Pearson correlation + window Mann-Whitney tests.")
    p.add_argument("--registry", required=True)
    p.add_argument("--labels", nargs="+", required=True)

    p = add("sweep", cmd_sweep, "T/N uncertainty-convergence sweep.")
    p.add_argument("--registry", required=False, default=None)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--method", choices=("mcd", "de"), required=False,
                   default=None)
    p.add_argument("--counts", nargs="+", required=False, default=None)
    p.add_argument("--plot", default=None, help="Optional output PNG path.")
    p.add_argument("--from-csv", default=None,
                   help="Plot an existing sweep CSV (column N + "
                        "Variance_<set> columns) instead of re-running "
                        "predictions; requires --plot.")

    p = add("figures", cmd_figures, "Thesis overview figure set.")
    p.add_argument("--registry", required=True)
    p.add_argument("--labels", nargs="+", required=True)
    p.add_argument("--out-dir", required=True)
    p.add_argument("--num-bins", type=int, default=10)

    p = add("cohort", cmd_cohort,
            "SHHS2 cohort demographics (and optional signal quality).")
    p.add_argument("--metadata-csv", required=True)
    p.add_argument("--signal-quality", action="store_true")

    # `telemetry` is a command group, not a stage: its subcommands read
    # run directories, take no --config, and never import jax in-process
    # (watch probes the backend in budgeted subprocesses).
    p = sub.add_parser("telemetry",
                       help="Read back, compare, and capture a run's "
                            "structured telemetry.")
    tsub = p.add_subparsers(dest="telemetry_command", required=True)
    ps = tsub.add_parser(
        "summarize",
        help="Render a run directory's events.jsonl as per-stage "
             "wall/device-time, throughput, recompile-count and "
             "HBM/headroom tables.")
    ps.add_argument("run_dir",
                    help="Run directory containing events.jsonl (what "
                         "--run-dir pointed at, or bench.py's "
                         "BENCH_RUN_DIR).")
    ps.add_argument("--json", action="store_true",
                    help="Emit the summary machine-readable (the same "
                         "fields as the rendered tables).")
    ps.add_argument("--all-runs", action="store_true",
                    help="Render every run of an appended multi-run log "
                         "(default: only the latest renders, which "
                         "hides replica restarts); with --json the "
                         "payload becomes {run_count, runs: [...]}.")
    ps.set_defaults(fn=cmd_telemetry_summarize)

    pf = tsub.add_parser(
        "fleet",
        help="Cross-replica SLO rollup: merge N serve run dirs' "
             "digest-carrying serve_slo events into fleet p50/p95/p99 "
             "+ throughput, flag the outlier replica, roll up "
             "serve_drift per tenant; exits 1 on an outlier or "
             "drifted tenant.")
    pf.add_argument("run_dirs", nargs="+", metavar="run_dir",
                    help="Serve replica run directories (each the "
                         "--run-dir of one `apnea-uq serve` process; "
                         "latest run of an appended log).")
    pf.add_argument("--spread-threshold", type=float, default=2.0,
                    help="Flag a replica as the fleet outlier when its "
                         "p99 is at least this many times the "
                         "replica-median p99 (default 2.0).")
    pf.add_argument("--out", default=None, metavar="DIR",
                    help="Persist the rollup into DIR as a fleet_rollup "
                         "event + registry artifact — a run-dir source "
                         "`telemetry compare` gates (fleet.p99_ms, "
                         "fleet.windows_per_s, fleet.imbalance_ratio) "
                         "and `telemetry trend` ingests.")
    from apnea_uq_tpu.lint.report import add_format_args as _fleet_fmt

    _fleet_fmt(pf)
    pf.set_defaults(fn=cmd_telemetry_fleet)

    px = tsub.add_parser(
        "trace",
        help="Cross-replica critical-path analyzer: merge N serve run "
             "dirs' serve_trace spans into per-request waterfalls, "
             "attribute latency (queue/service/pad) at p50/p95/p99 per "
             "bucket and replica, flag the tail-dominating replica, "
             "and audit exemplar coverage; exits 1 on a collision, "
             "missing exemplar, or dominated tail.")
    px.add_argument("run_dirs", nargs="+", metavar="run_dir",
                    help="Serve replica run directories (each the "
                         "--run-dir of one `apnea-uq serve` or replica "
                         "process; latest run of an appended log, torn "
                         "tails tolerated).")
    px.add_argument("--out", default=None, metavar="DIR",
                    help="Persist the report into DIR as a trace_report "
                         "event + registry artifact — a run-dir source "
                         "`telemetry compare` gates "
                         "(trace.queue_share_p99, "
                         "trace.service_share_p99, "
                         "trace.exemplar_coverage) and `telemetry "
                         "trend` ingests.")
    _fleet_fmt(px)
    px.set_defaults(fn=cmd_telemetry_trace)

    pc = tsub.add_parser(
        "compare",
        help="Regression gate: per-metric deltas between a baseline and "
             "a candidate (BENCH_r*.json files or run dirs); exits 1 on "
             "any regression past threshold.")
    pc.add_argument("baseline",
                    help="Baseline: a BENCH_r*.json capture or a "
                         "telemetry run directory.")
    pc.add_argument("candidate",
                    help="Candidate to gate, same formats.")
    pc.add_argument("--threshold-pct", type=float, default=5.0,
                    help="Allowed worsening per metric before it counts "
                         "as a regression (default 5%%).")
    pc.add_argument("--metric-threshold", action="append", default=[],
                    metavar="NAME=PCT",
                    help="Per-metric threshold override; repeatable.")
    pc.add_argument("--metric-direction", action="append", default=[],
                    metavar="NAME=higher|lower",
                    help="Per-metric better-direction override for "
                         "metrics whose unit the inference misreads "
                         "(unknown units default to higher-is-better); "
                         "repeatable.")
    pc.add_argument("--json", action="store_true",
                    help="Emit the comparison machine-readable.")
    pc.set_defaults(fn=cmd_telemetry_compare)

    pt = tsub.add_parser(
        "trend",
        help="Cross-run perf-trajectory ledger: per-metric "
             "best/latest/delta over every archived BENCH_r*.json round "
             "(error rounds shown as gaps) plus any extra sources.")
    pt.add_argument("sources", nargs="*", default=[],
                    help="Extra rounds appended after the archived ones: "
                         "bench capture JSON files or telemetry run "
                         "directories (e.g. a fresh BENCH_RUN_DIR).")
    pt.add_argument("--rounds-dir", default=None,
                    help="Where the archived BENCH_r*.json rounds live "
                         "(default: the repo checkout root).  Any "
                         "telemetry run dirs under <rounds-dir>/runs/ "
                         "(an artifact registry's layout) are swept in "
                         "too, so quality/eval series ride the ledger "
                         "without hand-listing run dirs.")
    pt.add_argument("--threshold-pct", type=float, default=5.0,
                    help="Worsening of latest-vs-best past this flags "
                         "the metric REGRESSED (default 5%%).")
    pt.add_argument("--json", action="store_true",
                    help="Emit the trajectory machine-readable.")
    pt.add_argument("--update-docs", action="store_true",
                    help="Regenerate docs/BENCH_TRAJECTORY.md from the "
                         "archived rounds only (byte-for-byte pinned by "
                         "the docs-consistency suite).")
    pt.add_argument("--docs", default=None,
                    help="With --update-docs: destination path (default "
                         "docs/BENCH_TRAJECTORY.md under the repo root).")
    pt.set_defaults(fn=cmd_telemetry_trend)

    pw = tsub.add_parser(
        "watch",
        help="Hardware-watch autopilot: probe the TPU backend with "
             "backoff; on the first green probe run the evidence ritual "
             "(bench + TPU-gated tests) into a fresh run dir.")
    pw.add_argument("--out", required=True,
                    help="Root directory for the watch run dir "
                         "(<out>/runs/watch-<stamp>-<pid>).")
    pw.add_argument("--budget-secs", type=float, default=86400.0,
                    help="Give up after this long without a green probe "
                         "(default 24h; exit code 2).")
    pw.add_argument("--probe-secs", type=float, default=120.0,
                    help="Per-probe subprocess budget (a hung "
                         "jax.devices() counts as red).")
    pw.add_argument("--skip-tests", action="store_true",
                    help="Run only the bench capture, not the TPU-gated "
                         "pytest step.")
    pw.set_defaults(fn=cmd_telemetry_watch)

    # `quality` is the model-quality twin of the telemetry group: its
    # subcommands read run directories, take no --config, and never
    # import jax (the write side — quality_metrics/drift_fingerprint
    # events — is emitted by the eval stages themselves).
    p = sub.add_parser("quality",
                       help="Gate a run's model-quality telemetry: "
                            "calibration regression and input drift.")
    qsub = p.add_subparsers(dest="quality_command", required=True)
    qc = qsub.add_parser(
        "check",
        help="Exit 1 when a run's drift_fingerprint scores exceed "
             "threshold, a serve run's serve_drift verdicts drifted, "
             "or (with --baseline) its calibration regressed vs a "
             "prior run; exit 2 when nothing is gateable.")
    qc.add_argument("run_dir",
                    help="Telemetry run directory of the eval to gate "
                         "(quality_metrics + drift_fingerprint events; "
                         "latest run of an appended log), or a serve/"
                         "score run directory whose --drift-check "
                         "emitted serve_drift verdicts.")
    qc.add_argument("--baseline", default=None,
                    help="Prior run directory to gate calibration "
                         "against: shared-label ECE/MCE/Brier worsening "
                         "past --threshold-pct is a regression "
                         "(lower-is-better, no direction flag needed).")
    qc.add_argument("--threshold-pct", type=float, default=5.0,
                    help="Allowed calibration worsening vs --baseline "
                         "before it counts as a regression (default "
                         "5%%).")
    qc.add_argument("--psi-threshold", type=float, default=0.2,
                    help="Max allowed per-set drift max_psi vs the "
                         "frozen quality_baseline (default 0.2, the "
                         "standard 'significant shift' PSI bar).")
    qc.add_argument("--ks-threshold", type=float, default=0.2,
                    help="Max allowed per-set drift max_ks (two-sample "
                         "KS statistic; default 0.2).")
    from apnea_uq_tpu.lint.report import add_format_args

    add_format_args(qc)
    qc.set_defaults(fn=cmd_quality_check)

    # `lint` is jax-free like the telemetry read side: a pure-AST scan
    # (apnea_uq_tpu/lint/) that takes no --config and must stay runnable
    # on machines where the backend (or jax itself) is unusable.
    from apnea_uq_tpu.lint import cli as lint_cli

    lint_cli.register(sub)

    # `flow` is the lint's pipeline-dataflow sibling (apnea_uq_tpu/flow/):
    # jax-free like lint, it extracts the registry producer->consumer
    # graph, verifies the artifact contract against the checked-in
    # flow/manifest.json, and enforces the tmp->fsync->os.replace
    # write discipline.
    from apnea_uq_tpu.flow import cli as flow_cli

    flow_cli.register(sub)

    # `audit` is the lint's IR-level sibling (apnea_uq_tpu/audit/):
    # lowers the compile-cache zoo on CPU — no dispatch, no registry —
    # and verifies dtypes/collectives/donation/constants against the
    # checked-in manifest.  Takes --config (the zoo is config-selected);
    # jax imports stay inside the handler.
    from apnea_uq_tpu.audit import cli as audit_cli

    audit_cli.register(sub, add_config_arg, load_config_fn)

    # `topo` is the fourth rule family (apnea_uq_tpu/topo/): multi-host
    # topology readiness — AST source rules plus the mesh program
    # families lowered under simulated topologies on CPU.  Takes
    # --config like audit; jax imports stay inside the handler (and are
    # skipped entirely when only source rules are selected).
    from apnea_uq_tpu.topo import cli as topo_cli

    topo_cli.register(sub, add_config_arg, load_config_fn)

    # `conc` is the fifth rule family (apnea_uq_tpu/conc/): the
    # concurrency & crash-consistency audit over the thread/process/
    # crash seams the serving tier grew.  Jax-free like lint/flow — no
    # --config, pure AST.
    from apnea_uq_tpu.conc import cli as conc_cli

    conc_cli.register(sub)

    # `check` runs all five static gates in one invocation with merged
    # output and a single exit code — the one-step CI recipe
    # (docs/LINT.md "CI recipe").
    p = sub.add_parser(
        "check",
        help="Run every static gate — lint + flow + audit + topo + "
             "conc — with merged output; exit 0 all clean, 1 on any "
             "finding, 2 on any usage error.")
    add_config_arg(p)
    p.add_argument("--format", choices=("text", "gha"), default="text",
                   help="Output format; `gha` concatenates the gates' "
                        "GitHub Actions annotation lines (empty on a "
                        "clean tree).")
    p.set_defaults(fn=lambda args: cmd_check(args, load_config_fn(args)))

    p = add("demo", cmd_demo,
            "Zero-data synthetic smoke demo of the UQ engine.")
    p.add_argument("--num-models", type=int, default=5)
    p.add_argument("--num-windows", type=int, default=1000)
    p.add_argument("--seed", type=int, default=2025)
    _add_plots_arg(p)
