"""Stage subcommand registry for the ``apnea-uq`` CLI.

Each pipeline stage contributes one subcommand; a stage registers here in
the same change that adds its runner.  Handlers import their heavy
dependencies (jax, pandas) lazily so ``--help`` stays instant.
"""

from __future__ import annotations


def register(sub, add_config_arg, load_config_fn) -> None:
    # Stage subcommands land together with their runner implementations.
    del sub, add_config_arg, load_config_fn
