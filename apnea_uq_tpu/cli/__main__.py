"""``python -m apnea_uq_tpu.cli`` — the same entry point as ``apnea-uq``."""

import sys

from apnea_uq_tpu.cli.main import main

if __name__ == "__main__":
    sys.exit(main())
