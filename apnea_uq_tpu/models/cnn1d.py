"""Alarcón et al. 1D-CNN apnea classifier, TPU-first in Flax.

Architecture parity target: reference ``al_1d_cnn_create_model``
(models/cnn_baseline_train.py:37-104 — duplicated at
models/train_deep_ensemble_cnns.py:25-77): six Conv1D(ReLU, same-pad) ->
BatchNorm -> Dropout blocks with (filters, kernel, rate) =
(128,7,.3)(192,5,.3)(224,3,.4)(96,7,.2)(256,9,.3)(96,9,.5), then global
average pooling over time and a Dense(1) sigmoid head; ~853K params.

TPU-first design choices (deliberate divergences from the Keras original):

- The head emits a **logit**; the sigmoid lives in the loss
  (``optax.sigmoid_binary_cross_entropy``) and in ``predict_proba``, which
  is numerically stabler and fuses better under XLA.
- Conv/dense math can run in **bfloat16** on the MXU (``compute_dtype``)
  with float32 parameters and float32 batch-norm statistics.
- **Inference-mode semantics are explicit.** Keras ``training=True``
  silently switches BatchNorm to batch statistics as well as enabling
  dropout — the cause of the reference's ~88% vs ~77% accuracy split
  (uq_techniques.py:22; SURVEY §6).  Here the four regimes are first-class
  modes (``MODES``): 'train', 'eval', 'mcd_clean' (dropout on, BN frozen —
  standard MC Dropout) and 'mcd_parity' (dropout on, BN in batch-stats
  mode, statistics updates discarded — the reference regime).
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from apnea_uq_tpu.config import ModelConfig

# mode -> (dropout_on, bn_use_running_average)
MODES: Mapping[str, Tuple[bool, bool]] = {
    "train": (True, False),
    "eval": (False, True),
    "mcd_clean": (True, True),
    "mcd_parity": (True, False),
}


class AlarconCNN1D(nn.Module):
    """1D CNN over (batch, time, channels) windows; returns (batch,) logits."""

    config: ModelConfig = ModelConfig()

    @nn.compact
    def __call__(self, x: jax.Array, *, mode: str = "eval") -> jax.Array:
        if mode not in MODES:
            raise ValueError(f"mode must be one of {sorted(MODES)}, got {mode!r}")
        dropout_on, bn_frozen = MODES[mode]
        cfg = self.config
        dtype = jnp.dtype(cfg.compute_dtype)

        x = x.astype(dtype)
        if not (len(cfg.features) == len(cfg.kernel_sizes) == len(cfg.dropout_rates)):
            raise ValueError(
                "features / kernel_sizes / dropout_rates must have equal length, got "
                f"{len(cfg.features)}/{len(cfg.kernel_sizes)}/{len(cfg.dropout_rates)}"
            )
        for i, (feat, ksize, rate) in enumerate(
            zip(cfg.features, cfg.kernel_sizes, cfg.dropout_rates)
        ):
            x = nn.Conv(
                features=feat,
                kernel_size=(ksize,),
                padding="SAME",
                dtype=dtype,
                param_dtype=jnp.float32,
                precision=cfg.matmul_precision,
                kernel_init=nn.initializers.glorot_uniform(),
                name=f"conv_{i}",
            )(x)
            x = nn.relu(x)
            x = nn.BatchNorm(
                use_running_average=bn_frozen,
                momentum=cfg.bn_momentum,
                epsilon=cfg.bn_epsilon,
                dtype=dtype,
                param_dtype=jnp.float32,
                name=f"bn_{i}",
            )(x)
            x = nn.Dropout(rate=rate, deterministic=not dropout_on, name=f"drop_{i}")(x)

        # Global average pooling over the time axis
        # (cnn_baseline_train.py:91), then the single-logit head (:94).
        # The 60-element mean accumulates in f32 even under
        # compute_dtype='bfloat16' — a bf16 accumulator loses ~3 bits
        # over the reduction tree, and the audit's program-dtype-drift
        # rule treats bf16-accumulated reduces as unblessed in every
        # tier (PARITY.md "Tolerance tiers").
        x = jnp.mean(x.astype(jnp.float32), axis=1).astype(dtype)
        x = nn.Dense(
            features=1,
            dtype=dtype,
            param_dtype=jnp.float32,
            precision=cfg.matmul_precision,
            kernel_init=nn.initializers.glorot_uniform(),
            name="head",
        )(x)
        return x[..., 0].astype(jnp.float32)


def init_variables(
    model: AlarconCNN1D, rng: jax.Array, batch_size: int = 2
) -> dict:
    """Initialize {'params', 'batch_stats'} for the model."""
    cfg = model.config
    dummy = jnp.zeros((batch_size, cfg.time_steps, cfg.num_channels), jnp.float32)
    return model.init({"params": rng}, dummy, mode="eval")


def apply_model(
    model: AlarconCNN1D,
    variables: dict,
    x: jax.Array,
    *,
    mode: str,
    dropout_rng: Optional[jax.Array] = None,
    update_batch_stats: bool = False,
) -> Tuple[jax.Array, dict]:
    """Apply the model in an explicit mode.

    Returns ``(logits, new_batch_stats)``.  ``new_batch_stats`` is the
    (possibly unchanged) batch_stats collection: it is updated only when
    ``mode='train'`` and ``update_batch_stats=True``.  In 'mcd_parity' mode
    batch statistics are *used* but updates are discarded, matching a Keras
    inference call with ``training=True`` (no optimizer step, so Keras'
    moving averages do update there — we deliberately do not persist them;
    persisting inference-time BN drift is a reference defect not worth
    keeping).
    """
    if mode not in MODES:
        raise ValueError(f"mode must be one of {sorted(MODES)}, got {mode!r}")
    dropout_on, bn_frozen = MODES[mode]
    if dropout_on and dropout_rng is None:
        raise ValueError(f"mode {mode!r} needs a dropout_rng")
    rngs = {"dropout": dropout_rng} if dropout_on else None
    if bn_frozen:
        logits = model.apply(variables, x, mode=mode, rngs=rngs)
        return logits, variables["batch_stats"]
    logits, mutated = model.apply(
        variables, x, mode=mode, rngs=rngs, mutable=["batch_stats"]
    )
    new_stats = mutated["batch_stats"] if update_batch_stats else variables["batch_stats"]
    return logits, new_stats


def predict_proba(logits: jax.Array) -> jax.Array:
    """Positive-class probability from logits."""
    return jax.nn.sigmoid(logits)


def param_count(variables: dict) -> int:
    return sum(int(p.size) for p in jax.tree.leaves(variables["params"]))
