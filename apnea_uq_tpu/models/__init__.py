from apnea_uq_tpu.models.cnn1d import (
    MODES,
    AlarconCNN1D,
    apply_model,
    init_variables,
    param_count,
    predict_proba,
)

__all__ = [
    "AlarconCNN1D",
    "MODES",
    "apply_model",
    "init_variables",
    "param_count",
    "predict_proba",
]
