"""Lower one named program and distill the facts the program rules need.

The capture re-uses the compile-cost subsystem's own re-expression
machinery (:mod:`apnea_uq_tpu.compilecache.store`): a program is the
jitted wrapper over its array leaves — exactly what the store would
compile, persist, and dispatch — traced and lowered **on CPU, with no
dispatch**.  From one acquisition three views are distilled into a
plain-data :class:`ProgramAudit`:

- the **jaxpr** (recursively, through scan/pjit/shard_map sub-jaxprs):
  explicit collective primitives with their mesh axis names, host
  callback primitives, and the closed-over constants (a weight pytree
  traced as a literal shows up here — HBM duplication plus a cache key
  per value);
- the **StableHLO text**: f64 tensor types anywhere, and bf16-
  accumulated reductions (the PARITY.md promise is f32 accumulation
  even under ``compute_dtype='bfloat16'``);
- the **compiled executable**: ``input_output_alias`` (did declared
  donation survive to aliasing? ``jax.export`` is known to drop it —
  PR 6), ``memory_analysis()`` and ``cost_analysis()`` (FLOPs, bytes
  accessed, arithmetic intensity — the ``program_audit`` telemetry
  payload).

Everything downstream (:mod:`apnea_uq_tpu.audit.rules`) consumes only
the dataclass, so the rules stay jax-free and tests can inject
violations by capturing deliberately-broken synthetic programs.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from apnea_uq_tpu.compilecache import store as store_mod

# jaxpr primitives that communicate across mesh axes.  A refactor that
# introduces one of these inside a shard_map body is exactly what the
# collective-budget rule exists to catch.  `pbroadcast` is deliberately
# absent: shard_map's replication-typing machinery inserts it freely and
# it lowers to identity — no wire traffic, not a budget item.
COLLECTIVE_PRIMS = frozenset({
    "psum", "psum2", "pmax", "pmin", "ppermute", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter", "collective_permute",
})

# shard_map's replication-rewrite renames psum to psum2 inside its
# bodies; budget keys use the canonical spelling so a manifest row
# survives jax refactors of that machinery.
_PRIM_CANONICAL = {"psum2": "psum"}

# jaxpr primitives that call back into the host mid-program: a
# guaranteed device->host sync inside what should be a pure device step.
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "host_callback",
    "outside_call",
})

# StableHLO collective ops, counted textually as a second, lowering-side
# view of the same budget (explicit collectives only; XLA's SPMD
# partitioner inserts resharding later, during backend compilation).
HLO_COLLECTIVE_OPS = (
    "stablehlo.all_reduce", "stablehlo.all_gather", "stablehlo.all_to_all",
    "stablehlo.collective_permute", "stablehlo.reduce_scatter",
    "stablehlo.collective_broadcast",
)

# Constant leaves smaller than this are recorded nowhere: eps scalars,
# iota index vectors and BN shape constants are normal.  The rule-level
# threshold (AuditContext.const_threshold) sits above this floor.
_CONST_RECORD_FLOOR_BYTES = 1024

# Any tensor whose element type is f64: `tensor<f64>`, `tensor<8xf64>`,
# `tensor<4xcomplex<f64>>`.  NOTE `\bf64\b` would miss the shaped forms
# ('x' and 'f' are both word characters, so there is no boundary in
# "8xf64") — the suffix match is the reliable spelling.
_F64_RE = re.compile(r"tensor<[^>]*f64>")
# `stablehlo.reduce(...) applies stablehlo.add ... tensor<...bf16>`:
# a sum whose accumulator carries bf16 — 8 mantissa bits — through the
# reduction tree.
_BF16_REDUCE_RE = re.compile(r"stablehlo\.reduce\b[^\n]*bf16")
# Any tensor whose element type is bf16 (`tensor<8x60xbf16>`): legal
# ONLY in programs whose label carries the `_bf16` tier suffix — the
# program-dtype-drift rule's blessed-low-precision check.  Same
# suffix-match reasoning as the f64 regex above.
_BF16_RE = re.compile(r"tensor<[^>]*bf16>")


@dataclasses.dataclass
class ProgramAudit:
    """Plain-data audit facts of one lowered program (jax-free to read)."""

    label: str
    group: str
    # "psum[data]" -> count: explicit collectives in the jaxpr, keyed by
    # primitive and sorted mesh axis names.
    collectives: Dict[str, int]
    # "stablehlo.all_reduce" -> count in the lowered module text.
    hlo_collectives: Dict[str, int]
    f64_ops: int
    bf16_accum_reduces: int
    # Closed-over constants >= the record floor: {shape, dtype, bytes}.
    consts: List[Dict[str, Any]]
    donated_args: int           # wrapper params declared donated
    aliased_outputs: int        # input-output aliases in the executable
    host_callbacks: List[str]
    flops: Optional[float]
    bytes_accessed: Optional[float]
    arithmetic_intensity: Optional[float]
    memory_fields: Optional[Dict[str, int]]
    platform: str
    num_devices: int
    # bf16 tensor types anywhere in the lowered module: legal only under
    # a `_bf16`-tier label (program-dtype-drift's blessed-low-precision
    # check).  Defaulted so synthetic-capture tests predating the field
    # keep constructing.
    bf16_ops: int = 0
    # collectives' keys -> summed operand bytes (per-shard avals): the
    # payload one participant contributes per collective, the topology
    # analysis's cross-host traffic input (apnea_uq_tpu/topo/).
    # Defaulted like bf16_ops for captures predating the field.
    collective_payloads: Dict[str, int] = dataclasses.field(
        default_factory=dict)

    @property
    def const_bytes(self) -> int:
        return sum(int(c["bytes"]) for c in self.consts)

    @property
    def tier(self) -> str:
        """The label-declared precision tier ('f32' | 'bf16') — the
        manifest's tier column derives from this, never from the IR."""
        return "bf16" if self.label.endswith("_bf16") else "f32"


def _iter_jaxprs(jaxpr) -> Any:
    """``jaxpr`` and every sub-jaxpr reachable through eqn params
    (scan/while bodies, pjit/closed_call/shard_map inner jaxprs, cond
    branches), depth-first."""
    stack = [jaxpr]
    seen = set()
    while stack:
        cur = stack.pop()
        if hasattr(cur, "jaxpr"):       # ClosedJaxpr -> Jaxpr
            cur = cur.jaxpr
        if not hasattr(cur, "eqns") or id(cur) in seen:
            continue
        seen.add(id(cur))
        yield cur
        for eqn in cur.eqns:
            for value in eqn.params.values():
                for item in (value if isinstance(value, (tuple, list))
                             else (value,)):
                    if hasattr(item, "eqns") or hasattr(item, "jaxpr"):
                        stack.append(item)


def _axis_names(params: Dict[str, Any]) -> Tuple[str, ...]:
    """The mesh axis names a collective eqn communicates over."""
    axes = params.get("axes", params.get("axis_name", ()))
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(sorted(str(a) for a in axes))


def _aval_bytes(var) -> int:
    """Best-effort byte size of one jaxpr atom's aval (0 when the aval
    carries no static shape/dtype — accounting stays best-effort)."""
    aval = getattr(var, "aval", None)
    try:
        size = int(np.prod(aval.shape)) if aval.shape else 1
        return size * int(np.dtype(aval.dtype).itemsize)
    except Exception:  # noqa: BLE001 - abstract/token avals
        return 0


def _scan_jaxpr(closed) -> Tuple[Dict[str, int], Dict[str, int], List[str]]:
    collectives: Dict[str, int] = {}
    payloads: Dict[str, int] = {}
    callbacks: List[str] = []
    for jaxpr in _iter_jaxprs(closed):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in COLLECTIVE_PRIMS:
                canonical = _PRIM_CANONICAL.get(name, name)
                key = f"{canonical}[{','.join(_axis_names(eqn.params))}]"
                collectives[key] = collectives.get(key, 0) + 1
                payloads[key] = payloads.get(key, 0) + sum(
                    _aval_bytes(v) for v in eqn.invars)
            elif name in CALLBACK_PRIMS or "callback" in name:
                callbacks.append(name)
    return (dict(sorted(collectives.items())),
            dict(sorted(payloads.items())), sorted(callbacks))


def _const_records(closed) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    for const in jax.tree_util.tree_leaves(getattr(closed, "consts", [])):
        nbytes = int(getattr(const, "nbytes", 0) or 0)
        if nbytes >= _CONST_RECORD_FLOOR_BYTES:
            out.append({
                "shape": list(getattr(const, "shape", ())),
                "dtype": str(getattr(const, "dtype", "?")),
                "bytes": nbytes,
            })
    out.sort(key=lambda c: (-c["bytes"], c["dtype"], c["shape"]))
    return out


def _cost_fields(compiled) -> Tuple[Optional[float], Optional[float]]:
    """(flops, bytes accessed) from ``cost_analysis()`` — which returns a
    dict on some jax versions and a one-per-device list on others."""
    try:
        cost = compiled.cost_analysis()
    except Exception:  # noqa: BLE001 - accounting is best-effort
        return None, None
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not isinstance(cost, dict):
        return None, None
    flops = cost.get("flops")
    nbytes = cost.get("bytes accessed")
    return (float(flops) if flops is not None else None,
            float(nbytes) if nbytes is not None else None)


def _alias_count(compiled) -> int:
    """Input-output aliases the backend actually honored, read from the
    compiled module header's ``input_output_alias={ {0}: (0, {},
    may-alias) ... }`` attribute — the ground truth ``donate_argnums``
    must survive to (CPU honors donation, so the audit sees it)."""
    try:
        text = compiled.as_text()
    except Exception:  # noqa: BLE001 - backend without as_text
        return 0
    return text.count("may-alias") + text.count("must-alias")


def capture_program(label: str, fn, args: tuple, kwargs: dict, *,
                    group: str = "", donate_args: Tuple[int, ...] = (),
                    ) -> ProgramAudit:
    """Trace + lower + compile ``fn(*args, **kwargs)`` exactly as the
    program store would (same wrapper, same leaf specs, same donation
    re-threading) and distill the audit facts.  Nothing dispatches."""
    flat, treedef, arr_idx, aux, key_impls = store_mod._split_leaves(
        args, kwargs)
    specs = store_mod._leaf_specs(flat, arr_idx, key_impls)
    wrapper = store_mod._make_wrapper(fn, treedef, len(flat), arr_idx, aux,
                                      key_impls)
    donate = store_mod._donated_leaf_positions(
        args, kwargs, tuple(donate_args), arr_idx)
    jitted = jax.jit(wrapper, donate_argnums=donate or ())
    traced = jitted.trace(*specs)
    closed = traced.jaxpr
    collectives, payloads, callbacks = _scan_jaxpr(closed)
    consts = _const_records(closed)
    lowered = traced.lower()
    hlo = lowered.as_text()
    hlo_collectives = {
        op: hlo.count(op) for op in HLO_COLLECTIVE_OPS if op in hlo
    }
    compiled = lowered.compile()
    flops, bytes_accessed = _cost_fields(compiled)
    intensity = (flops / bytes_accessed
                 if flops is not None and bytes_accessed else None)
    memory_fields = None
    try:
        stats = compiled.memory_analysis()
        if stats is not None:
            from apnea_uq_tpu.telemetry.memory import memory_analysis_fields

            memory_fields = memory_analysis_fields(stats)
    except Exception:  # noqa: BLE001 - accounting is best-effort
        pass
    try:
        # apnea-lint: disable=single-host-device-enumeration -- the audit is a single-process CPU lowering; the GLOBAL platform/device-count is the fact being recorded
        devices = jax.devices()
        platform, num_devices = devices[0].platform, len(devices)
    except Exception:  # noqa: BLE001 - no backend: facts still form
        platform, num_devices = "unknown", 0
    return ProgramAudit(
        label=label, group=group,
        collectives=collectives, collective_payloads=payloads,
        hlo_collectives=hlo_collectives,
        f64_ops=len(_F64_RE.findall(hlo)),
        bf16_accum_reduces=len(_BF16_REDUCE_RE.findall(hlo)),
        bf16_ops=len(_BF16_RE.findall(hlo)),
        consts=consts,
        donated_args=len(donate), aliased_outputs=_alias_count(compiled),
        host_callbacks=callbacks,
        flops=flops, bytes_accessed=bytes_accessed,
        arithmetic_intensity=intensity, memory_fields=memory_fields,
        platform=platform, num_devices=num_devices,
    )


class CaptureStore(store_mod.ProgramStore):
    """A program store whose acquisitions are audits, not executables.

    Activated around the zoo's no-dispatch entry points
    (``record_memory_only=True`` predictors, ``compile_only=True``
    trainers), every ``get_program`` call lands here: the program is
    captured (traced + lowered + compiled on CPU, nothing dispatched,
    nothing persisted) and ``None`` is returned so the caller's
    plain-jit fallback path stays untouched — which the no-dispatch
    modes never reach anyway."""

    def __init__(self):
        super().__init__(None)
        self.group = ""
        self.captures: Dict[str, ProgramAudit] = {}
        self.failures: Dict[str, str] = {}

    def get(self, label, fn, args, kwargs, *, exportable=True,
            donate_args=(), run_log=None):
        if label not in self.captures and label not in self.failures:
            try:
                self.captures[label] = capture_program(
                    label, fn, args, dict(kwargs), group=self.group,
                    donate_args=tuple(donate_args))
            except Exception as e:  # noqa: BLE001 - surfaced as exit 2
                self.failures[label] = f"{type(e).__name__}: {e}"
        return None
