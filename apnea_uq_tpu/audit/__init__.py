"""IR-level program audit (ISSUE 8 tentpole).

The AST lint (:mod:`apnea_uq_tpu.lint`) catches hazards visible in
Python source; the promises this codebase actually makes — f32
accumulation under bf16 compute (PARITY.md), zero cross-member
collectives in the shard_map ensemble paths, donation on the ensemble
epoch, weights passed as arguments rather than baked constants — live in
the *lowered* program.  This package lowers every compile-cache zoo
label on CPU (no dispatch) through the same no-dispatch entry points
``warm-cache`` uses, and runs a second rule family over the jaxpr, the
StableHLO text, and the compiled executable's memory/cost analysis:
``apnea-uq audit``.

Import discipline mirrors the lint package: :mod:`rules` and
:mod:`manifest` are jax-free (the rule logic and the manifest diff run
anywhere), only :mod:`capture` / :mod:`programs` import jax — and the
CLI imports those lazily, so ``apnea-uq --help`` stays instant.
"""

from apnea_uq_tpu.audit.manifest import (  # noqa: F401
    DEFAULT_MANIFEST_PATH,
    load_manifest,
    manifest_row,
    save_manifest,
    zoo_label_lines,
)
from apnea_uq_tpu.audit.rules import (  # noqa: F401
    PROGRAM_RULES,
    AuditContext,
    run_program_rules,
)
