"""The ``apnea-uq audit`` subcommand.

``apnea-uq audit [--programs GROUPS] [--json | --format gha]
[--update-manifest] [--rule NAME ...]`` — lower every compile-cache zoo
label on CPU through the same no-dispatch entry points ``warm-cache``
uses (nothing dispatches), run the program-rule family over the lowered
IR, and diff the structural facts against the checked-in manifest.
Exits 0 when clean, 1 on unsuppressed violations, 2 on usage errors —
the same contract as ``apnea-uq lint``, whose suppression mechanism
(``# apnea-lint: disable=<rule> -- <why>`` at the zoo-registration
site in ``compilecache/zoo.py``) findings here reuse.

With ``--run-dir`` the per-program FLOPs/bytes/arithmetic-intensity are
persisted as ``program_audit`` telemetry events, rendered by
``telemetry summarize`` and gateable by ``telemetry compare``
(``audit.<label>.flops`` / ``.bytes_accessed``, lower-is-better).
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Optional

from apnea_uq_tpu.telemetry import log


def audit_program_data(program) -> Dict[str, Any]:
    """The per-program payload of ``audit --json`` AND the
    ``program_audit`` telemetry event — one projection, so the two
    machine-readable views cannot drift."""
    memory = program.memory_fields or {}
    return {
        "label": program.label,
        "group": program.group,
        "flops": program.flops,
        "bytes_accessed": program.bytes_accessed,
        "arithmetic_intensity": program.arithmetic_intensity,
        "collectives": sum(program.collectives.values()),
        "donated_args": program.donated_args,
        "aliased_outputs": program.aliased_outputs,
        "const_bytes": program.const_bytes,
        "peak_bytes": memory.get("peak_bytes"),
    }


def _emit_events(run_log, captures) -> None:
    for label in sorted(captures):
        d = audit_program_data(captures[label])
        run_log.event(
            "program_audit",
            label=d["label"], group=d["group"], flops=d["flops"],
            bytes_accessed=d["bytes_accessed"],
            arithmetic_intensity=d["arithmetic_intensity"],
            collectives=d["collectives"],
            donated_args=d["donated_args"],
            aliased_outputs=d["aliased_outputs"],
            const_bytes=d["const_bytes"], peak_bytes=d["peak_bytes"],
        )


def cmd_audit(args, config) -> int:
    from apnea_uq_tpu.audit.manifest import (
        load_manifest, merge_rows, write_manifest, zoo_label_lines,
    )
    from apnea_uq_tpu.audit.rules import (
        PROGRAM_RULES, AuditContext, run_program_rules,
    )
    from apnea_uq_tpu.compilecache.zoo import WARM_GROUPS
    from apnea_uq_tpu.lint.engine import (
        LintResult, apply_suppressions, default_repo_root, load_files,
    )
    from apnea_uq_tpu.lint.report import emit_result, resolve_format
    from apnea_uq_tpu.telemetry.logging_shim import narration_to_stderr

    fmt = resolve_format(args)

    def narrate(message: str) -> None:
        # In --json mode stdout is a machine interface (one JSON
        # document); progress/skip/manifest lines go to stderr so
        # `audit --json | jq .` parses without stripping.
        if fmt == "json":
            with narration_to_stderr():
                log(message)
        else:
            log(message)

    groups = tuple(g.strip() for g in args.programs.split(",") if g.strip())
    bad = set(groups) - set(WARM_GROUPS)
    if bad or not groups:
        # Usage errors exit 2, like lint: CI gating on the exit code
        # must never mistake a typo for a clean or dirty zoo.
        log(f"audit: unknown --programs group(s) "
            f"{sorted(bad) or '(none given)'}; "
            f"valid: {','.join(WARM_GROUPS)}")
        raise SystemExit(2)

    # The audit is lowering-only: it never needs an accelerator, and a
    # manifest is only comparable when generated on the same platform
    # rules — so pin the canonical CPU rig before the first jax import
    # (an already-imported jax, e.g. under the test rig's virtual CPU
    # mesh, is left alone — the helper no-ops).  Same blessed seam as
    # topo and `check`, so standalone audit lowers under the exact
    # environment the meta-gate gives it.
    from apnea_uq_tpu.utils.env import pin_host_analysis_rig

    pin_host_analysis_rig()

    import contextlib

    run_log = None
    with contextlib.ExitStack() as stack:
        if getattr(args, "run_dir", None):
            from apnea_uq_tpu.telemetry import start_run

            run_log = stack.enter_context(
                start_run(args.run_dir, stage="audit", config=config,
                          argv=sys.argv[1:]))
            narrate(f"telemetry -> {args.run_dir}")

        from apnea_uq_tpu.audit.programs import capture_zoo

        captures, skipped, failures = capture_zoo(config, groups=groups)
        for label, reason in skipped:
            narrate(f"audit: {label} SKIPPED — {reason}")
        if failures:
            for label, error in sorted(failures.items()):
                log(f"audit: capturing {label} FAILED — {error}")
            raise SystemExit(2)

        manifest_path = args.manifest
        manifest = load_manifest(manifest_path)
        if args.update_manifest:
            # The merged rows drive the rules NOW; the file is written
            # only after the rules pass, so a failed update (e.g. an
            # unblessable cross-member collective) never mutates the
            # golden manifest.
            manifest = merge_rows(captures, prior=manifest)
        elif manifest is None:
            log(f"audit: no manifest at {manifest_path!r} — run "
                f"`apnea-uq audit --update-manifest` once to record the "
                f"golden per-label budgets")
            raise SystemExit(2)

        zoo_abs, label_lines = zoo_label_lines()
        repo_root = default_repo_root([zoo_abs])
        zoo_sf = load_files([zoo_abs], repo_root)[0]
        context = AuditContext(
            programs=captures, manifest=manifest, zoo_path=zoo_sf.path,
            label_lines=label_lines,
        )
        try:
            findings = run_program_rules(context, rules=args.rule or None)
        except ValueError as e:
            log(f"apnea-uq audit: {e}")
            raise SystemExit(2)
        findings = [apply_suppressions(f, zoo_sf) for f in findings]
        result = LintResult(
            findings=findings, files_scanned=len(captures),
            rules_run=tuple(dict.fromkeys(args.rule)
                            or sorted(PROGRAM_RULES)),
            scanned_paths=tuple(sorted(captures)),
        )
        if run_log is not None:
            _emit_events(run_log, captures)

        if args.update_manifest:
            if result.unsuppressed:
                narrate(f"audit: manifest NOT updated — unsuppressed "
                        f"finding(s) remain; fix (or suppress) them, "
                        f"then re-run --update-manifest")
            else:
                write_manifest(manifest_path, manifest)
                narrate(f"manifest -> {manifest_path} "
                        f"({len(captures)} row(s) updated)")

        emit_result(result, fmt, subject="program(s)", json_extra={
            "programs": {
                label: audit_program_data(captures[label])
                for label in sorted(captures)
            },
        })
        return 1 if result.unsuppressed else 0


def register(sub, add_config_arg, load_config_fn) -> None:
    """Attach the ``audit`` subcommand to the CLI's subparser registry
    (same lazy-config wiring as the pipeline stages)."""
    p = sub.add_parser(
        "audit",
        help="IR-level program audit: lower the compile-cache zoo on CPU "
             "(no dispatch) and statically verify dtypes, collectives, "
             "donation, constant capture, and host callbacks against the "
             "checked-in manifest.")
    from apnea_uq_tpu.compilecache.zoo import WARM_GROUPS  # jax-free

    add_config_arg(p)
    p.add_argument("--programs", default=",".join(WARM_GROUPS),
                   help=f"Comma-separated zoo groups to audit "
                        f"({','.join(WARM_GROUPS)}; default all).")
    p.add_argument("--json", action="store_true",
                   help="Emit findings + per-program cost facts "
                        "machine-readable (full audit trail).")
    p.add_argument("--format", choices=("text", "json", "gha"),
                   default="text",
                   help="Output format; `gha` emits GitHub Actions "
                        "::error/::warning annotation lines (shared "
                        "with `apnea-uq lint --format gha`).")
    p.add_argument("--rule", action="append", default=[], metavar="NAME",
                   help="Run only this program rule (repeatable); "
                        "default: all — see docs/LINT.md.")
    p.add_argument("--update-manifest", action="store_true",
                   help="Regenerate the audited labels' manifest rows "
                        "(rows of groups not audited are preserved). "
                        "Cross-member collectives still fail: no "
                        "manifest can bless them.")
    from apnea_uq_tpu.audit.manifest import DEFAULT_MANIFEST_PATH

    p.add_argument("--manifest", default=DEFAULT_MANIFEST_PATH,
                   help="Manifest path (default: the in-package golden "
                        "apnea_uq_tpu/audit/manifest.json).")
    p.add_argument("--run-dir", default=None,
                   help="Telemetry run directory: persists one "
                        "program_audit event per label "
                        "(FLOPs/bytes/arithmetic intensity), rendered "
                        "by `telemetry summarize` and gateable by "
                        "`telemetry compare`.")
    p.set_defaults(fn=lambda args: cmd_audit(args, load_config_fn(args)))
