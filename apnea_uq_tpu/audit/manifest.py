"""The golden per-label program manifest, checked into the repo.

One JSON row per zoo label records the structural facts of its lowered
program — the explicit collective budget and the donation declaration/
aliasing — so CI fails the moment a refactor introduces a stray
collective or drops donation, against a file a reviewer can read in the
diff.  The rows are *structural* (no FLOPs, no bytes — those are
shape-dependent and flow to telemetry instead), so the same manifest
holds across model sizes, topologies, and the canonical audit shapes.

``apnea-uq audit --update-manifest`` regenerates the rows for the
audited groups, merge-preserving rows of groups not audited in that
invocation.  This module is jax-free.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Any, Dict, Optional, Tuple

MANIFEST_VERSION = 1
DEFAULT_MANIFEST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "manifest.json")


def manifest_row(program) -> Dict[str, Any]:
    """The checked-in row for one captured program.  Structural facts
    only: donation is recorded as booleans, not leaf counts (a config
    with more layers donates more leaves without changing the contract),
    and FLOPs/bytes stay out entirely (shape-dependent — they flow to
    ``program_audit`` telemetry instead).  ``tier`` is the label-declared
    precision tier ('f32' | '_bf16'-suffixed labels -> 'bf16') the
    program-dtype-drift rule blesses bf16 tensor types under — in the
    diff, a reviewer reads which programs are allowed low precision."""
    return {
        "group": program.group,
        "tier": program.tier,
        "collectives": dict(sorted(program.collectives.items())),
        "donates": bool(program.donated_args),
        "aliased": bool(program.aliased_outputs),
    }


def load_manifest(path: str = DEFAULT_MANIFEST_PATH,
                  ) -> Optional[Dict[str, Dict[str, Any]]]:
    """label -> row, or None when no manifest exists yet."""
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "programs" not in doc:
        raise ValueError(
            f"{path!r} is not an audit manifest (no 'programs' key)")
    return dict(doc["programs"])


def merge_rows(programs: Dict[str, Any],
               prior: Optional[Dict[str, Dict[str, Any]]] = None,
               ) -> Dict[str, Dict[str, Any]]:
    """The would-be manifest after an update: rows for ``programs``,
    ``prior`` rows preserved for zoo labels not captured this run (a
    `--programs eval-mcd` update must not drop the trainer rows), and
    rows whose label left the zoo entirely PRUNED — `--update-manifest`
    is the documented remediation for the stale-row drift pin, so it
    must actually remove them.  Pure merge; :func:`write_manifest`
    persists (the CLI defers that until the rules pass, so a failed
    update never mutates the golden file)."""
    from apnea_uq_tpu.compilecache.zoo import GROUP_LABELS  # jax-free

    zoo_labels = {lb for labels in GROUP_LABELS.values() for lb in labels}
    rows: Dict[str, Dict[str, Any]] = {
        label: row for label, row in (prior or {}).items()
        if label in zoo_labels
    }
    for label, program in programs.items():
        rows[label] = manifest_row(program)
    return rows


def write_manifest(path: str, rows: Dict[str, Dict[str, Any]]) -> None:
    from apnea_uq_tpu.utils.io import atomic_write_json

    doc = {
        "version": MANIFEST_VERSION,
        "programs": {label: rows[label] for label in sorted(rows)},
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    # sort_keys=False keeps the version header first and the hand-read
    # row layout; the shared writer supplies the fsync the old local
    # tmp+rename skipped.
    atomic_write_json(path, doc, sort_keys=False, trailing_newline=True)


def save_manifest(path: str, programs: Dict[str, Any],
                  prior: Optional[Dict[str, Dict[str, Any]]] = None,
                  ) -> Dict[str, Dict[str, Any]]:
    """:func:`merge_rows` + :func:`write_manifest` in one step."""
    rows = merge_rows(programs, prior)
    write_manifest(path, rows)
    return rows


def zoo_label_lines() -> Tuple[str, Dict[str, int]]:
    """(absolute zoo.py path, label -> line of its string literal inside
    the ``GROUP_LABELS`` display) — the zoo-registration anchor every
    program finding points at, resolved by parsing the source (never by
    importing the jax-loaded zoo module)."""
    import apnea_uq_tpu

    zoo_path = os.path.join(
        os.path.dirname(os.path.abspath(apnea_uq_tpu.__file__)),
        "compilecache", "zoo.py")
    with open(zoo_path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=zoo_path)
    lines: Dict[str, int] = {}
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        if not any(isinstance(t, ast.Name) and t.id == "GROUP_LABELS"
                   for t in targets):
            continue
        value = node.value
        if not isinstance(value, ast.Dict):
            continue
        for group_value in value.values:
            for sub in ast.walk(group_value):
                if isinstance(sub, ast.Constant) and isinstance(
                        sub.value, str):
                    lines.setdefault(sub.value, sub.lineno)
    return zoo_path, lines
