"""Drive every compile-cache zoo label through its real entry point and
capture the lowered IR.

``warm-cache`` precompiles the variant a config will actually dispatch
(fused OR full, streamed OR in-HBM); the audit's job is the opposite —
statically verify **every** program named in
:data:`apnea_uq_tpu.compilecache.zoo.GROUP_LABELS`, because the variant
a production config skips today is the one a refactor breaks unnoticed.
So this module calls the same no-dispatch entry points warm-cache uses
(``record_memory_only=True`` predictors, ``compile_only=True``
trainers), but sweeps both stats modes and both streaming modes, against
small synthetic shapes — the audited invariants (collectives, donation,
dtypes, constants, callbacks) are structural, not shape-dependent, so
canonical tiny shapes keep a full-zoo audit a CPU-seconds affair.

The capture rides the active-program-store seam: a
:class:`~apnea_uq_tpu.audit.capture.CaptureStore` is pushed for the
duration, so every ``get_program`` acquisition in the drivers lands as a
:class:`~apnea_uq_tpu.audit.capture.ProgramAudit` and nothing compiles
twice, persists, or dispatches.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

from apnea_uq_tpu.compilecache.zoo import GROUP_LABELS, WARM_GROUPS

# Canonical audit shapes: small enough that the full zoo lowers in
# seconds on CPU, large enough that chunking/padding paths are real.
AUDIT_WINDOWS = 64
AUDIT_WINDOW_SHAPE = (60, 4)
AUDIT_BATCH = 32
AUDIT_PASSES = 4
AUDIT_MEMBERS = 4
AUDIT_TRAIN_BATCH = 16


def capture_zoo(config, *, groups: Tuple[str, ...] = WARM_GROUPS,
                ) -> Tuple[Dict[str, object], List[Tuple[str, str]],
                           Dict[str, str]]:
    """Lower every label of the selected ``groups`` on the current
    (CPU) backend.  Returns ``(captures, skipped, failures)``:
    label -> :class:`ProgramAudit`, ``(label, reason)`` for programs the
    config makes uncapturable (streaming trainers have no single epoch
    program — the same skip warm-cache logs), and label -> error for
    captures that failed outright."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from apnea_uq_tpu.audit.capture import CaptureStore
    from apnea_uq_tpu.compilecache.store import use_store
    from apnea_uq_tpu.models import AlarconCNN1D, init_variables
    from apnea_uq_tpu.parallel import fit_ensemble
    from apnea_uq_tpu.parallel.mesh import make_mesh, make_mesh_from_config
    from apnea_uq_tpu.training import create_train_state, fit
    from apnea_uq_tpu.training.trainer import predict_proba_batched
    from apnea_uq_tpu.uq.predict import (
        SERVE_BUCKET_SIZES,
        ensemble_predict,
        ensemble_predict_streaming,
        mc_dropout_predict,
        mc_dropout_predict_streaming,
        serve_bucket_predict,
        stack_member_variables,
    )
    from apnea_uq_tpu.utils import prng

    unknown = set(groups) - set(WARM_GROUPS)
    if unknown:
        raise ValueError(
            f"unknown audit group(s) {sorted(unknown)}; "
            f"valid: {list(WARM_GROUPS)}"
        )
    store = CaptureStore()
    skipped: List[Tuple[str, str]] = []

    model = AlarconCNN1D(config.model)
    variables = init_variables(model, jax.random.key(0))
    uq = config.uq
    stat_spec = ("nats", float(uq.entropy_eps))
    x_aval = jax.ShapeDtypeStruct((AUDIT_WINDOWS,) + AUDIT_WINDOW_SHAPE,
                                  jnp.float32)
    # The dtype sweep: every eval label exists in an f32 and a `_bf16`
    # tier (the variables are dtype-independent — params stay f32 under
    # either compute dtype, so one init serves both models).
    dtype_models = tuple(
        AlarconCNN1D(dataclasses.replace(config.model, compute_dtype=d))
        for d in ("float32", "bfloat16")
    )

    with use_store(store):
        if "eval-mcd" in groups:
            store.group = "eval-mcd"
            mesh = make_mesh_from_config(config.mesh,
                                         num_members=AUDIT_PASSES)
            key = prng.stochastic_key(config.train.seed)
            for dmodel in dtype_models:
                # Engine sweep: the `_pallas` labels lower their CPU
                # fallback body here (resolve_mcd_engine — the audit
                # runs off-TPU by design), which is exactly the program
                # a CPU process would dispatch under those labels.
                for engine in ("xla", "pallas"):
                    for stats in (None, stat_spec):  # full AND fused
                        common = dict(n_passes=AUDIT_PASSES,
                                      mode=uq.mcd_mode,
                                      batch_size=AUDIT_BATCH, key=key,
                                      mesh=mesh, record_memory_only=True,
                                      stats=stats, engine=engine)
                        mc_dropout_predict(dmodel, variables, x_aval,
                                           **common)
                        mc_dropout_predict_streaming(dmodel, variables,
                                                     x_aval, **common)
                predict_proba_batched(
                    dmodel, variables, x_aval, batch_size=AUDIT_BATCH,
                    mesh=mesh, record_memory_only=True,
                )

        if "eval-de" in groups:
            store.group = "eval-de"
            members = stack_member_variables([variables] * AUDIT_MEMBERS)
            mesh = make_mesh_from_config(config.mesh,
                                         num_members=AUDIT_MEMBERS)
            for dmodel in dtype_models:
                # Engine sweep mirrors eval-mcd: the DE `_pallas` labels
                # lower their CPU fallback body (resolve_de_engine — the
                # audit runs off-TPU by design).
                for engine in ("xla", "pallas"):
                    for stats in (None, stat_spec):
                        common = dict(batch_size=AUDIT_BATCH, mesh=mesh,
                                      record_memory_only=True, stats=stats,
                                      engine=engine)
                        ensemble_predict(dmodel, members, x_aval, **common)
                        ensemble_predict_streaming(dmodel, members, x_aval,
                                                   **common)

        if "serve" in groups:
            # The serving bucket ladder (uq/predict.py
            # SERVE_BUCKET_SIZES): fixed-shape programs, so the audit
            # lowers them at their REAL bucket sizes — the exact
            # programs `apnea-uq serve` dispatches — across both
            # methods and both dtype tiers.
            store.group = "serve"
            key = prng.stochastic_key(config.train.seed)
            serve_members = stack_member_variables(
                [variables] * AUDIT_MEMBERS)
            for dmodel in dtype_models:
                for bucket in SERVE_BUCKET_SIZES:
                    bucket_aval = jax.ShapeDtypeStruct(
                        (bucket,) + AUDIT_WINDOW_SHAPE, jnp.float32)
                    for engine in ("xla", "pallas"):
                        serve_bucket_predict(
                            dmodel, variables, bucket_aval, method="mcd",
                            bucket=bucket, n_passes=AUDIT_PASSES, key=key,
                            engine=engine, record_memory_only=True,
                        )
                        serve_bucket_predict(
                            dmodel, serve_members, bucket_aval, method="de",
                            bucket=bucket, engine=engine,
                            record_memory_only=True,
                        )

        need_train_data = bool({"train", "train-ensemble"} & set(groups))
        if need_train_data:
            rng = np.random.default_rng(0)
            x_train = rng.normal(
                size=(AUDIT_WINDOWS,) + AUDIT_WINDOW_SHAPE
            ).astype(np.float32)
            y_train = (np.arange(AUDIT_WINDOWS) % 2).astype(np.int8)

        if "train" in groups:
            store.group = "train"
            if config.train.streaming:
                skipped.extend(
                    (label, "TrainConfig.streaming dispatches per-step "
                            "programs with no single epoch program to "
                            "audit")
                    for label in GROUP_LABELS["train"]
                )
            else:
                tcfg = dataclasses.replace(config.train,
                                           batch_size=AUDIT_TRAIN_BATCH)
                state = create_train_state(
                    model, jax.random.key(tcfg.seed),
                    learning_rate=tcfg.learning_rate,
                )
                fit(model, state, x_train, y_train, tcfg,
                    mesh=make_mesh(num_members=1), compile_only=True)

        if "train-ensemble" in groups:
            store.group = "train-ensemble"
            if config.ensemble.streaming:
                skipped.extend(
                    (label, "EnsembleConfig.streaming dispatches per-step "
                            "programs with no single epoch program to "
                            "audit")
                    for label in GROUP_LABELS["train-ensemble"]
                )
            else:
                ecfg = dataclasses.replace(
                    config.ensemble, num_members=AUDIT_MEMBERS,
                    batch_size=AUDIT_TRAIN_BATCH,
                )
                fit_ensemble(
                    model, x_train, y_train, ecfg,
                    mesh=make_mesh_from_config(
                        config.mesh, num_members=AUDIT_MEMBERS),
                    compile_only=True,
                )

    # Any selected-group label that neither captured, skipped, nor failed
    # means an entry-point drift (a driver stopped acquiring through the
    # store) — surface it as a capture failure, not silence.
    expected = {
        label for g in groups for label in GROUP_LABELS[g]
    }
    accounted = (set(store.captures) | set(store.failures)
                 | {label for label, _ in skipped})
    for label in sorted(expected - accounted):
        store.failures[label] = (
            "entry point never acquired this label through the program "
            "store — zoo/driver drift"
        )
    return store.captures, skipped, store.failures
