"""The program-rule family: static verification of lowered programs.

Second rule family for the lint engine — same :class:`Finding` type,
same severities, same suppression mechanism — but the subject is a
lowered program (:class:`~apnea_uq_tpu.audit.capture.ProgramAudit`
facts), not an AST.  Findings anchor at the program's **zoo-registration
site** (the label string in ``compilecache/zoo.py``'s ``GROUP_LABELS``),
which gives every violation a pointable file:line and lets the existing
``# apnea-lint: disable=<rule> -- <why>`` comments suppress per label.

This module is deliberately jax-free, like the AST rules: it consumes
plain capture data, so the rule logic runs (and is tested) anywhere.

Rules:

- ``program-dtype-drift`` — any f64 tensor type in the lowered module
  (a silent x64 leak doubles bytes and halves MXU throughput), and, in
  the ``_fused`` statistics programs, any reduction that accumulates in
  bf16 (PARITY.md promises f32 accumulation even under
  ``compute_dtype='bfloat16'``).
- ``program-collective-budget`` — the program's explicit collectives
  (jaxpr primitives, keyed by mesh axes) must match the checked-in
  manifest row, and collectives over the ``ensemble`` axis are
  *unconditionally* violations: members are independent by design, so a
  cross-member collective is a correctness/perf bug no manifest update
  can bless.
- ``program-donation-effectiveness`` — declared ``donate_argnums`` must
  survive to input-output aliasing in the compiled executable
  (``jax.export`` round-trips drop donation — PR 6), and a label whose
  manifest row records donation must still declare it.
- ``program-constant-capture`` — closed-over constants above the size
  threshold: a weight pytree traced as a literal duplicates HBM per
  program and explodes the compile-cache key space per value.
- ``program-host-callback`` — host callback primitives inside a jitted
  hot-path program serialize the device stream mid-step.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from apnea_uq_tpu.lint.engine import SEVERITIES, Finding, Rule

# The mesh axis ensemble members shard over; collectives over it are
# cross-member by definition.  Mirrors parallel.mesh.AXIS_ENSEMBLE
# (pinned by a test) without importing the jax-loaded module here.
ENSEMBLE_AXIS = "ensemble"

# Constant leaves at or above this count as captured weights.  The
# largest legitimate closed-over constants (iota tables, BN shape
# vectors) stay well under it; even a tiny model's stacked kernels
# exceed it.
DEFAULT_CONST_THRESHOLD_BYTES = 64 * 1024

PROGRAM_RULES: Dict[str, Rule] = {}


def register_program_rule(name: str, severity: str, summary: str):
    """Decorator twin of :func:`apnea_uq_tpu.lint.engine.register_rule`
    for rules that check lowered programs instead of ASTs."""
    if severity not in SEVERITIES:
        raise ValueError(
            f"severity must be one of {SEVERITIES}, got {severity!r}")

    def wrap(fn: Callable[["AuditContext"], Iterable[Finding]]):
        PROGRAM_RULES[name] = Rule(name=name, severity=severity,
                                   summary=summary, check=fn)
        return fn

    return wrap


@dataclasses.dataclass
class AuditContext:
    """Everything a program rule sees: the captured programs, the golden
    manifest rows (None = no manifest yet), and the zoo-registration
    anchor (display path + label -> line) findings point at."""

    programs: Dict[str, Any]            # label -> ProgramAudit facts
    manifest: Optional[Dict[str, Dict[str, Any]]]
    zoo_path: str                       # repo-root-relative display path
    label_lines: Dict[str, int]
    const_threshold: int = DEFAULT_CONST_THRESHOLD_BYTES
    ensemble_axis: str = ENSEMBLE_AXIS

    def line_for(self, label: str) -> int:
        return self.label_lines.get(label, 1)

    def finding(self, rule: str, label: str, message: str) -> Finding:
        return Finding(
            rule=rule, severity=PROGRAM_RULES[rule].severity,
            path=self.zoo_path, line=self.line_for(label),
            message=f"{label}: {message}",
        )


def _collective_axes(key: str) -> Tuple[str, ...]:
    if "[" not in key:
        return ()
    inner = key[key.index("[") + 1:].rstrip("]")
    return tuple(a for a in inner.split(",") if a)


@register_program_rule(
    "program-dtype-drift", "error",
    "f64 ops anywhere in a lowered hot-path program; bf16 tensor types "
    "outside the blessed `_bf16` label tier; and bf16-accumulated "
    "reductions in ANY tier's _fused statistics programs (PARITY.md "
    "promises f32 accumulation even under compute_dtype='bfloat16')",
)
def check_dtype_drift(context: AuditContext) -> Iterable[Finding]:
    for label, p in sorted(context.programs.items()):
        if p.f64_ops:
            yield context.finding(
                "program-dtype-drift", label,
                f"lowered module contains {p.f64_ops} f64 tensor type(s) "
                f"— an x64 leak doubles memory traffic and falls off the "
                f"bf16/f32 matmul units",
            )
        # Blessed low-precision tier: a `_bf16` label MAY carry bf16
        # tensor types (that is what the tier declares — PARITY.md
        # "Tolerance tiers", <=2e-2 vs f32); any other label carrying
        # them is an unblessed precision leak.  Tier-blessed, never
        # suppressed: there is no inline-disable path for this.
        if p.tier != "bf16" and getattr(p, "bf16_ops", 0):
            yield context.finding(
                "program-dtype-drift", label,
                f"{p.bf16_ops} bf16 tensor type(s) in an f32-tier "
                f"program — low-precision compute must run under a "
                f"`_bf16`-suffixed label (the blessed tier; "
                f"ModelConfig.compute_dtype='bfloat16' labels programs "
                f"automatically) so the parity suite's 2e-2 tolerance "
                f"tier applies to it",
            )
        # The f32-accumulation promise holds in EVERY tier: `_fused`
        # appears mid-label in the suffix grammar
        # (mcd_predict_pallas_fused_bf16), so substring, not endswith.
        if "_fused" in label and p.bf16_accum_reduces:
            yield context.finding(
                "program-dtype-drift", label,
                f"{p.bf16_accum_reduces} reduction(s) accumulate in bf16 "
                f"— the fused sufficient-statistics reductions must "
                f"accumulate in f32 even in the _bf16 tier (PARITY.md; "
                f"pass dtype=jnp.float32 to the reducing op)",
            )


@register_program_rule(
    "program-collective-budget", "error",
    "explicit collectives in a lowered program must match the checked-in "
    "manifest row, and cross-member (ensemble-axis) collectives are "
    "unconditional violations — ensemble members are independent",
)
def check_collective_budget(context: AuditContext) -> Iterable[Finding]:
    for label, p in sorted(context.programs.items()):
        cross = {
            key: n for key, n in p.collectives.items()
            if context.ensemble_axis in _collective_axes(key)
        }
        if cross:
            yield context.finding(
                "program-collective-budget", label,
                f"cross-member collective(s) {cross} — members are "
                f"independent by design; communication over the "
                f"'{context.ensemble_axis}' axis serializes them "
                f"(no manifest update can bless this)",
            )
        if context.manifest is None:
            continue
        row = context.manifest.get(label)
        if row is None:
            yield context.finding(
                "program-collective-budget", label,
                "no manifest row for this zoo label — run "
                "`apnea-uq audit --update-manifest` to record its "
                "collective budget",
            )
        elif dict(row.get("collectives", {})) != dict(p.collectives):
            yield context.finding(
                "program-collective-budget", label,
                f"collective budget drift: program lowers with "
                f"{p.collectives or 'no collectives'} but the manifest "
                f"records {row.get('collectives') or 'none'} — an "
                f"intended change needs `--update-manifest`",
            )


@register_program_rule(
    "program-donation-effectiveness", "error",
    "declared donate_argnums must survive to input-output aliasing in "
    "the compiled executable (jax.export drops donation), and a label "
    "whose manifest row records donation must still declare it",
)
def check_donation(context: AuditContext) -> Iterable[Finding]:
    for label, p in sorted(context.programs.items()):
        if p.donated_args and not p.aliased_outputs:
            yield context.finding(
                "program-donation-effectiveness", label,
                f"{p.donated_args} argument(s) declared donated but the "
                f"compiled executable aliases no input to an output — "
                f"donation was dropped (a jax.export round-trip, or "
                f"shape/dtype-incompatible donated buffers), so the "
                f"program's HBM footprint silently doubles",
            )
        row = (context.manifest or {}).get(label)
        if row and row.get("donates") and not p.donated_args:
            yield context.finding(
                "program-donation-effectiveness", label,
                "manifest records this program as donating but it now "
                "declares no donated arguments — a refactor removed "
                "donate_argnums (an intended change needs "
                "`--update-manifest`)",
            )


@register_program_rule(
    "program-constant-capture", "error",
    "closed-over constants above the size threshold: weights traced as "
    "literals duplicate HBM per program and key the compile cache per "
    "value",
)
def check_constant_capture(context: AuditContext) -> Iterable[Finding]:
    for label, p in sorted(context.programs.items()):
        big = [c for c in p.consts
               if c["bytes"] >= context.const_threshold]
        if not big:
            continue
        total = sum(c["bytes"] for c in big)
        worst = ", ".join(
            f"{tuple(c['shape'])}:{c['dtype']}={c['bytes']}B"
            for c in big[:3]
        )
        yield context.finding(
            "program-constant-capture", label,
            f"{len(big)} constant(s) totalling {total} bytes baked into "
            f"the program ({worst}{', ...' if len(big) > 3 else ''}) — "
            f"pass arrays as arguments instead of closing over values, "
            f"or every new checkpoint recompiles and re-stores the "
            f"program",
        )


@register_program_rule(
    "program-host-callback", "error",
    "host callback primitives inside a jitted hot-path program serialize "
    "the device stream mid-step",
)
def check_host_callback(context: AuditContext) -> Iterable[Finding]:
    for label, p in sorted(context.programs.items()):
        if p.host_callbacks:
            yield context.finding(
                "program-host-callback", label,
                f"host callback(s) {sorted(set(p.host_callbacks))} in "
                f"the jaxpr — each one round-trips to Python mid-program "
                f"and stalls the device pipeline",
            )


def run_program_rules(
    context: AuditContext,
    *,
    rules: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Run the (selected) program rules over ``context``; findings come
    back sorted (path, line, rule, message) — suppressions are the
    caller's job (they need the zoo source file)."""
    if rules is None:
        selected = tuple(sorted(PROGRAM_RULES))
    else:
        selected = tuple(dict.fromkeys(rules))
    unknown = [r for r in selected if r not in PROGRAM_RULES]
    if unknown:
        raise ValueError(
            f"unknown program rule(s) {unknown}; "
            f"available: {sorted(PROGRAM_RULES)}"
        )
    findings: List[Finding] = []
    for name in selected:
        findings.extend(PROGRAM_RULES[name].check(context))
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings
