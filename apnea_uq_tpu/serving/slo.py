"""Serving SLO accounting: request latencies, queue waits, pad waste.

The read side of the serving telemetry triple: the serve loop records
one entry per dispatched batch and one per completed request, and this
tracker folds them into the ``serve_slo`` summary event — p50/p95/p99
request latency, achieved windows/sec, mean queue wait, and the padded
fraction of all dispatched bucket rows.  ``telemetry compare`` gates
the summary (``serve.p50_ms`` / ``serve.p99_ms`` / ``serve.windows_per_s``
/ ``serve.queue_wait_mean_s`` backend-bound, ``serve.pad_waste`` as a
backend-independent relative), and ``telemetry trend`` carries it as a
series.  Alongside the bounded raw history the tracker feeds a
mergeable log-spaced histogram digest (telemetry/digest.py) — overall
request latency plus per-bucket device time — serialized onto every
``serve_slo`` event, so ``telemetry fleet`` can reconstruct
cross-replica percentiles from event streams alone.  jax-free (NumPy
percentiles over host lists).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Deque, Dict, Optional

import numpy as np

from apnea_uq_tpu.telemetry.digest import LatencyDigest

# Per-sample history kept for the percentile/mean summaries: a
# long-lived serve process must stay O(1) in memory, so the counters
# (requests/windows/batches/pad accounting) are exact for the whole
# session while the latency percentiles and mean queue wait are
# computed over the most recent window of this many samples — far more
# than any SLO percentile needs to stabilize.
HISTORY_WINDOW = 65536

# Per-bucket service-time history: smaller than the global window (the
# ladder has at most a handful of buckets, and per-bucket percentiles
# stabilize long before this).
BUCKET_HISTORY_WINDOW = 8192


class SLOTracker:
    """Cumulative session accounting.  ``summary()`` is the
    whole-session-so-far view; periodic ``serve_slo`` events are
    cumulative snapshots and the ``final=True`` event is the one
    ``compare``/``trend`` read (the LAST ``serve_slo`` of a run).
    Counters are session-exact; the latency/queue-wait distributions
    are over the last :data:`HISTORY_WINDOW` samples (bounded memory
    for a long-lived process)."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self.t0 = clock()
        self.requests = 0
        self.windows = 0
        self.batches = 0
        self.bucket_rows = 0
        self.pad_rows = 0
        self.latencies_s: Deque[float] = collections.deque(
            maxlen=HISTORY_WINDOW)
        self.queue_waits_s: Deque[float] = collections.deque(
            maxlen=HISTORY_WINDOW)
        self.device_s = 0.0
        # The mergeable twin of the bounded history: exact-count
        # log-spaced digests (session-lifetime, O(bins) memory), the
        # only latency representation that survives cross-replica
        # aggregation.
        self.latency_digest = LatencyDigest(unit="s")
        # Per-bucket breakdown (ISSUE 17 satellite): exact counters plus
        # a bounded per-bucket device-service-time history, so a
        # saturated 256-bucket cannot hide behind a healthy global p95.
        self._buckets: Dict[int, Dict[str, Any]] = {}

    def record_batch(self, *, bucket: int, rows: int, pad_rows: int,
                     queue_wait_s: float, device_s: float) -> None:
        self.batches += 1
        self.windows += rows
        self.bucket_rows += bucket
        self.pad_rows += pad_rows
        self.queue_waits_s.append(float(queue_wait_s))
        self.device_s += float(device_s)
        per = self._buckets.get(int(bucket))
        if per is None:
            per = {"batches": 0, "windows": 0, "pad_rows": 0,
                   "device_ms": collections.deque(
                       maxlen=BUCKET_HISTORY_WINDOW),
                   "digest": LatencyDigest(unit="ms")}
            self._buckets[int(bucket)] = per
        per["batches"] += 1
        per["windows"] += rows
        per["pad_rows"] += pad_rows
        per["device_ms"].append(float(device_s) * 1e3)
        per["digest"].add(float(device_s) * 1e3)

    def record_request(self, *, latency_s: float) -> None:
        self.requests += 1
        self.latencies_s.append(float(latency_s))
        self.latency_digest.add(float(latency_s))

    def summary(self, now: Optional[float] = None) -> Dict[str, Any]:
        now = self._clock() if now is None else now
        interval = max(now - self.t0, 1e-9)
        lat = np.asarray(list(self.latencies_s), np.float64)
        if lat.size:
            p50, p95, p99 = (round(float(v) * 1e3, 3) for v in
                             np.percentile(lat, (50.0, 95.0, 99.0)))
        else:
            # No completed requests (e.g. the stream scorer, which has
            # windows but no request latencies): the percentiles are
            # UNDEFINED, not zero — a 0.0 here would land in `telemetry
            # compare` as a gateable latency every real serve run
            # "regresses" against.  None fields are skipped by the
            # metric extraction.
            p50 = p95 = p99 = None
        waits = np.asarray(list(self.queue_waits_s), np.float64)
        return {
            "requests": self.requests,
            "windows": self.windows,
            "batches": self.batches,
            "p50_ms": p50,
            "p95_ms": p95,
            "p99_ms": p99,
            "windows_per_s": round(self.windows / interval, 3),
            "queue_wait_mean_s": (round(float(waits.mean()), 6)
                                  if waits.size else 0.0),
            "pad_waste": (round(self.pad_rows / self.bucket_rows, 4)
                          if self.bucket_rows else 0.0),
            "device_s": round(self.device_s, 6),
            "interval_s": round(interval, 6),
            "digest": self.latency_digest.to_payload(),
            "buckets": self._bucket_summary(),
        }

    def _bucket_summary(self) -> Dict[str, Dict[str, Any]]:
        """Per-bucket-size breakdown: batch/window/pad counters plus
        p50/p95/p99 of the bucket's device service time (ms).  Keys are
        stringified bucket sizes (JSON object keys)."""
        out: Dict[str, Dict[str, Any]] = {}
        for bucket in sorted(self._buckets):
            per = self._buckets[bucket]
            times = np.asarray(list(per["device_ms"]), np.float64)
            if times.size:
                p50, p95, p99 = (round(float(v), 3) for v in
                                 np.percentile(times, (50.0, 95.0, 99.0)))
            else:
                p50 = p95 = p99 = None
            dispatched = per["batches"] * bucket
            out[str(bucket)] = {
                "batches": per["batches"],
                "windows": per["windows"],
                "pad_rows": per["pad_rows"],
                "pad_waste": (round(per["pad_rows"] / dispatched, 4)
                              if dispatched else 0.0),
                "p50_ms": p50,
                "p95_ms": p95,
                "p99_ms": p99,
                "digest": per["digest"].to_payload(),
            }
        return out

    def emit(self, run_log, *, final: bool = False,
             patients: Optional[int] = None,
             trace: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """Append one ``serve_slo`` event (cumulative snapshot; the
        final one is the session summary the gates read).  ``trace`` is
        the exemplar tracer's counter ledger
        (:meth:`~apnea_uq_tpu.telemetry.spans.ExemplarTracer.stats`):
        carried verbatim so every SLO line links to its exemplar span
        ids and the fleet assembler can audit coverage exactly."""
        from apnea_uq_tpu.telemetry.runlog import replica_id

        summary = self.summary()
        if trace is not None:
            summary["trace"] = dict(trace)
        if run_log is not None:
            fields = dict(summary)
            fields["final"] = bool(final)
            fields["replica_id"] = replica_id()
            if patients is not None:
                fields["patients"] = int(patients)
            run_log.event("serve_slo", **fields)
        return summary
