"""The serving engine: AOT-warm bucket dispatch + the serve loop.

``ServingEngine`` owns the model/weights and dispatches coalesced
batches through the fixed bucket ladder's fused-stats programs
(``uq/predict.py serve_bucket_predict``): each batch zero-pads to its
bucket, runs ONE already-compiled program, and ships a ``(4, bucket)``
sufficient-stats block device->host — the per-request payload the
ROADMAP's serving direction was designed around.  Pad rows are sliced
off on host; in the serving regimes (clean-mode MCD / eval-mode DE)
every window's compute is batch-neighbor-independent, so padded scores
are bit-identical (f32) to unpadded direct dispatch
(tests/test_serving.py pins it).

``serve_requests`` is the request-path loop `apnea-uq serve` (and the
bench's ``serve`` block) runs: enqueue -> coalesce -> dispatch ->
per-request completion, with the serving telemetry triple emitted as it
happens (``serve_batch`` per dispatch, ``serve_request`` per completed
request, periodic + final ``serve_slo`` summaries).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Iterable, Optional, Sequence

import numpy as np

from apnea_uq_tpu.serving.coalescer import (
    BatchPlan,
    BucketLadder,
    RequestCoalescer,
    ServeRequest,
)
from apnea_uq_tpu.serving.slo import SLOTracker
from apnea_uq_tpu.uq.metrics import (
    STAT_ALEATORIC,
    STAT_MEAN,
    STAT_TOTAL,
    STAT_VARIANCE,
)

# How often the serve loop checkpoints a cumulative serve_slo snapshot
# (every N completed requests); the final summary always emits.
DEFAULT_SLO_EVERY = 100


def decomposition_rows(stats: np.ndarray) -> Dict[str, np.ndarray]:
    """(4, n) sufficient statistics -> the per-window uncertainty
    decomposition vectors (host NumPy — n is request-sized here, and
    mutual information is the one derived row: max(total - aleatoric,
    0), uq/metrics.py's clamp)."""
    stats = np.asarray(stats, np.float32)
    return {
        "mean_prob": stats[STAT_MEAN],
        "variance": stats[STAT_VARIANCE],
        "total_entropy": stats[STAT_TOTAL],
        "aleatoric_entropy": stats[STAT_ALEATORIC],
        "mutual_info": np.maximum(
            stats[STAT_TOTAL] - stats[STAT_ALEATORIC], 0.0),
    }


class ServingEngine:
    """Long-lived scorer over one model + weight carrier.

    ``method='mcd'`` holds baseline variables and runs ``uq.mc_passes``
    clean-mode stochastic passes per window (a fresh ``fold_in`` of the
    root key per dispatched batch — no two batches share dropout
    noise); ``method='de'`` holds the stacked ensemble members and runs
    the deterministic member sweep.  ``warm()`` acquires every ladder
    bucket's program through the program store WITHOUT dispatching, so
    a warm-cached process front-loads its (zero-compile) acquisitions
    before the first request arrives.
    """

    def __init__(self, model, carrier, *, method: str = "mcd", uq,
                 buckets: Optional[Sequence[int]] = None, run_log=None,
                 seed: int = 0):
        from apnea_uq_tpu.uq.predict import as_stacked_members
        from apnea_uq_tpu.utils import prng

        if method not in ("mcd", "de"):
            raise ValueError(f"method must be 'mcd' or 'de', got {method!r}")
        if method == "mcd" and uq.mcd_mode != "clean":
            raise ValueError(
                "the serving tier requires UQConfig.mcd_mode='clean': "
                "parity-mode batch-statistics BN would let a bucket's "
                "zero-pad rows corrupt real windows"
            )
        self.model = model
        self.method = method
        self.uq = uq
        # The REQUESTED engine for every bucket program this process
        # acquires and dispatches: the method's UQConfig engine knob
        # (mcd_engine / de_engine), resolved per dispatch through the
        # shared fallback rules so off-TPU the `_pallas` labels run
        # their XLA fallback bodies under the same names.
        self.engine = uq.mcd_engine if method == "mcd" else uq.de_engine
        self.carrier = (as_stacked_members(carrier) if method == "de"
                        else carrier)
        # `buckets is not None` (not truthiness): an explicitly-empty
        # sequence must hit BucketLadder's cannot-be-empty error, not
        # silently fall back to the full ladder the caller tried to
        # restrict.
        self.ladder = (BucketLadder(buckets) if buckets is not None
                       else BucketLadder())
        self.run_log = run_log
        self._root_key = prng.stochastic_key(seed)
        self._dispatches = 0
        # Attribution of the most recent score_batch dispatch (label,
        # bucket, dispatch/device seconds) — the serve loop reads it to
        # fold per-batch timing into request trace spans without
        # re-measuring anything.
        self.last_batch: Optional[Dict[str, Any]] = None
        # Per-label acquisition memo (serve_bucket_predict `cache`): the
        # first touch of each bucket — warm(), normally — pays weight
        # placement + store acquisition + pricing; every request-path
        # dispatch after that reuses the program and the already-placed
        # carrier with zero per-batch acquisition overhead.
        self._program_cache: Dict[str, Any] = {}

    def _window_tail(self):
        return (self.model.config.time_steps, self.model.config.num_channels)

    def _predict(self, x, bucket: int, *, record_memory_only: bool = False):
        import jax

        from apnea_uq_tpu.uq.predict import serve_bucket_predict

        kwargs: Dict[str, Any] = dict(
            method=self.method, bucket=bucket, base="nats",
            eps=self.uq.entropy_eps, engine=self.engine,
            run_log=self.run_log,
            record_memory_only=record_memory_only,
            cache=self._program_cache,
        )
        if self.method == "mcd":
            kwargs["n_passes"] = self.uq.mc_passes
            # Fresh noise per dispatched batch: the per-batch fold_in is
            # the serving-tier spelling of the predictors' per-(pass,
            # chunk) key discipline.
            kwargs["key"] = jax.random.fold_in(self._root_key,
                                               self._dispatches)
        return serve_bucket_predict(self.model, self.carrier, x, **kwargs)

    def warm(self) -> None:
        """Acquire (and price) every ladder bucket's program with no
        dispatch — after `apnea-uq warm-cache`, every acquisition here
        is a ``source=store|cache`` hit and the request path never
        compiles (the warm-serve acceptance contract)."""
        tail = self._window_tail()
        for bucket in self.ladder.buckets:
            self._predict(np.empty((bucket,) + tail, np.float32), bucket,
                          record_memory_only=True)

    def score_batch(self, rows: np.ndarray, *, bucket: Optional[int] = None,
                    queue_wait_s: float = 0.0,
                    slo: Optional[SLOTracker] = None) -> np.ndarray:
        """Score ``(n, T, C)`` windows through the smallest fitting
        bucket: zero-pad to the bucket, dispatch, slice the pad columns
        off — returns the real rows' ``(4, n)`` sufficient statistics.
        Emits one ``serve_batch`` event (queue wait, pad waste,
        dispatch-vs-device time, windows/sec) when a run log is
        attached."""
        from apnea_uq_tpu.telemetry.steps import StepMetrics
        from apnea_uq_tpu.uq.predict import serve_program_label

        rows = np.asarray(rows, np.float32)
        n = int(rows.shape[0])
        bucket = self.ladder.bucket_for(n) if bucket is None else int(bucket)
        padded = rows
        if n < bucket:
            padded = np.zeros((bucket,) + rows.shape[1:], np.float32)
            padded[:n] = rows
        label = serve_program_label(self.model, method=self.method,
                                    bucket=bucket, engine=self.engine)
        metrics = StepMetrics(self.run_log)
        stats = metrics.measure(label, lambda: self._predict(padded, bucket),
                                n_items=n)
        self._dispatches += 1
        record = metrics.last
        out = np.asarray(stats)[:, :n]
        self.last_batch = {
            "label": label,
            "bucket": bucket,
            "rows": n,
            "pad_rows": bucket - n,
            "dispatch_s": record.dispatch_s,
            "device_s": record.device_s,
        }
        if self.run_log is not None:
            from apnea_uq_tpu.telemetry.runlog import replica_id

            self.run_log.event(
                "serve_batch",
                replica_id=replica_id(),
                label=label,
                bucket=bucket,
                rows=n,
                pad_rows=bucket - n,
                pad_waste=round((bucket - n) / bucket, 4),
                queue_wait_s=round(queue_wait_s, 6),
                dispatch_s=round(record.dispatch_s, 6),
                device_s=round(record.device_s, 6),
                windows_per_s=(round(record.items_per_s, 3)
                               if record.items_per_s is not None else None),
                retraces=record.retraces,
                backend_compiles=record.backend_compiles,
            )
        if slo is not None:
            slo.record_batch(bucket=bucket, rows=n, pad_rows=bucket - n,
                             queue_wait_s=queue_wait_s,
                             device_s=record.device_s)
        return out


def serve_requests(
    engine: ServingEngine,
    requests: Iterable[ServeRequest],
    *,
    max_wait_s: float = 0.005,
    slo_every: int = DEFAULT_SLO_EVERY,
    slo: Optional[SLOTracker] = None,
    coalescer: Optional[RequestCoalescer] = None,
    clock=time.perf_counter,
    on_result=None,
    trace_every: int = 0,
    trace_slow_ms: float = 0.0,
    drift=None,
) -> Dict[str, Any]:
    """The request-path loop: pull arrivals, coalesce into bucket
    batches, dispatch, complete requests.  ``on_result(request, stats,
    start_row)`` (stats = the ``(4, k)`` block for the request's rows
    ``start_row:start_row+k`` — a spilled request gets one call per
    batch its rows landed in) lets callers stream scores out; the
    returned dict is the final SLO summary, which is also emitted as
    the closing ``serve_slo`` event.

    ``trace_every=N`` (0 = off) samples one completed request in N and
    emits its ``serve_trace`` span waterfall: ``queue_s`` (enqueue ->
    coalesce -> first dispatch), ``service_s`` (first dispatch -> last
    batch scored, decomposed into summed host ``dispatch_s`` and
    ``device_s``/derived ``d2h_s`` attribution), ``respond_s`` (result
    fan-out after the last score).  ``queue_s + service_s`` equals the
    ``latency_s`` that ``serve_request``/``serve_slo`` report, exactly —
    the waterfall is a decomposition of the SLO number, not a parallel
    measurement.

    The sampling verdict lands AT COMPLETION through
    :class:`~apnea_uq_tpu.telemetry.spans.ExemplarTracer` (ISSUE 20):
    the first completed request always emits, ``trace_every`` keeps
    the 1-in-N baseline stream, and ``trace_slow_ms > 0`` arms tail
    mode — every request over the budget emits unconditionally (the
    exemplar-coverage contract) plus rolling per-bucket p99 outliers
    through a bounded reservoir with exact drop counters.  Each
    ``serve_slo`` snapshot then carries the tracer's counter ledger and
    recent exemplar span ids as its ``trace`` field.

    ``drift`` (a :class:`~apnea_uq_tpu.serving.drift.DriftMonitor`)
    folds every dispatched window into the per-tenant rolling
    fingerprint at dispatch time (tenant = the request's ``patient``,
    anonymous traffic pools under the default tenant) — host-side numpy
    on frozen edges, zero extra compiles on the request path.

    The request source is pumped on a daemon thread into a queue so the
    ``max_wait_s`` coalescing deadline holds even when the source
    BLOCKS (stdin, a sparse NDJSON tail): an idle poll re-checks the
    queue for overdue partial batches instead of sitting inside a
    blocking read — without it, one request on a quiet source would
    wait for the NEXT arrival, not the deadline.  Dispatch stays on the
    calling thread; only iteration of ``requests`` moves."""
    import queue as queue_mod
    import threading

    from apnea_uq_tpu.conc.perturb import perturb_point
    from apnea_uq_tpu.serving.drift import DEFAULT_TENANT
    from apnea_uq_tpu.telemetry.runlog import replica_id as _replica_id
    from apnea_uq_tpu.telemetry.spans import (
        ExemplarTracer,
        waterfall_children,
    )

    run_log = engine.run_log
    slo = slo or SLOTracker(clock)
    coalescer = coalescer or RequestCoalescer(engine.ladder)
    tracer = ExemplarTracer(trace_every=trace_every, slow_ms=trace_slow_ms)
    emitted_at = 0

    def dispatch(plan: BatchPlan) -> None:
        nonlocal emitted_at
        now = clock()
        for req, start, end in plan.slices:
            if req.first_dispatch_t is None:
                req.first_dispatch_t = now
            if drift is not None:
                # Timed: the fold is host numpy on the request path, so
                # the waterfall's drift_fold child shows its cost
                # instead of hiding it inside queue time.
                drift_t0 = clock()
                drift.observe(req.windows[start:end],
                              tenant=req.patient or DEFAULT_TENANT)
                req.trace_drift_s += clock() - drift_t0
        stats = engine.score_batch(
            plan.gather(), bucket=plan.bucket,
            queue_wait_s=plan.queue_wait_s(now), slo=slo,
        )
        done_t = clock()
        batch = engine.last_batch or {}
        offset = 0
        for req, start, end in plan.slices:
            take = end - start
            req.trace_dispatch_s += float(batch.get("dispatch_s", 0.0))
            req.trace_device_s += float(batch.get("device_s", 0.0))
            req.trace_pad_rows += plan.pad_rows
            req.trace_bucket = max(req.trace_bucket, plan.bucket)
            req.trace_label = str(batch.get("label", ""))
            if on_result is not None:
                on_result(req, stats[:, offset:offset + take], start)
            offset += take
            req.done += take
            if req.complete:
                latency = done_t - req.enqueue_t
                slo.record_request(latency_s=latency)
                if run_log is not None:
                    run_log.event(
                        "serve_request",
                        replica_id=_replica_id(),
                        request_id=req.request_id,
                        windows=req.rows,
                        batches=req.batches,
                        latency_s=round(latency, 6),
                    )
                reasons = tracer.decide(bucket=req.trace_bucket,
                                        latency_s=latency,
                                        span_id=req.span_id)
                if run_log is not None and reasons:
                    queue_s = req.first_dispatch_t - req.enqueue_t
                    service_s = done_t - req.first_dispatch_t
                    d2h_s = max(req.trace_device_s
                                - req.trace_dispatch_s, 0.0)
                    end_t = clock()
                    run_log.event(
                        "serve_trace",
                        replica_id=_replica_id(),
                        span_id=req.span_id,
                        trace_id=req.trace_id,
                        request_id=req.request_id,
                        windows=req.rows,
                        batches=req.batches,
                        bucket=req.trace_bucket,
                        pad_rows=req.trace_pad_rows,
                        label=req.trace_label,
                        queue_s=round(queue_s, 6),
                        service_s=round(service_s, 6),
                        dispatch_s=round(req.trace_dispatch_s, 6),
                        device_s=round(req.trace_device_s, 6),
                        d2h_s=round(d2h_s, 6),
                        respond_s=round(end_t - done_t, 6),
                        latency_s=round(latency, 6),
                        sampled_for=list(reasons),
                        exemplar=bool("slow" in reasons
                                      or "p99" in reasons),
                        children=waterfall_children(
                            enqueue_t=req.enqueue_t,
                            dequeue_t=req.dequeue_t,
                            first_dispatch_t=req.first_dispatch_t,
                            done_t=done_t,
                            end_t=end_t,
                            dispatch_s=req.trace_dispatch_s,
                            d2h_s=d2h_s,
                            drift_s=req.trace_drift_s,
                        ),
                    )
                if slo.requests - emitted_at >= max(1, int(slo_every)):
                    emitted_at = slo.requests
                    slo.emit(run_log, final=False,
                             trace=(tracer.stats() if tracer.enabled
                                    else None))

    # Bounded: a fast source (a big NDJSON file, loadgen at rate=0) must
    # not materialize every pending request's window arrays in memory —
    # under sustained overload the pump blocks on put() and the source
    # back-pressures, instead of the process growing without bound.  The
    # paced load generator keeps the queue far below the bound anyway,
    # so open-loop arrival measurements are unaffected.
    fifo: "queue_mod.Queue" = queue_mod.Queue(maxsize=1024)
    done = object()
    source_failure: list = []

    def pump() -> None:
        try:
            for request in requests:
                # Schedule-perturbation seam (conc/perturb.py): a no-op
                # unless a test/env arms a seed, then a deterministic
                # sub-ms sleep here forces producer/consumer
                # interleavings an idle box never explores.
                perturb_point("serve.pump.enqueue")
                fifo.put(request)
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            source_failure.append(e)
        finally:
            fifo.put(done)

    threading.Thread(target=pump, daemon=True,
                     name="serve-request-pump").start()
    # Idle poll bounded by the deadline itself: a partial batch is
    # dispatched at most ~max_wait_s late, never "when the next request
    # happens to arrive".
    poll_s = max(min(max_wait_s, 0.05), 0.001)
    while True:
        try:
            item = fifo.get(timeout=poll_s)
        except queue_mod.Empty:
            for plan in coalescer.drain(now=clock(), max_wait_s=max_wait_s):
                dispatch(plan)
            continue
        perturb_point("serve.pump.dequeue")
        if item is done:
            if source_failure:
                # The request source raised (e.g. a malformed NDJSON
                # request line): the contract is the caller's error,
                # not a silent drain — re-raise on the serving thread.
                raise source_failure[0]
            break
        # Pump-handoff clock: splits the waterfall's queue time into
        # its pump child (source -> serving thread) and coalesce child
        # (serving thread -> first dispatch).
        item.dequeue_t = clock()
        coalescer.enqueue(item)
        for plan in coalescer.drain(now=clock(), max_wait_s=max_wait_s):
            dispatch(plan)
    for plan in coalescer.drain(now=clock(), flush=True):
        dispatch(plan)
    if drift is not None:
        # The tail shorter than one re-score cadence still lands a
        # final verdict per tenant before the summary closes the run.
        drift.flush()
    return slo.emit(run_log, final=True,
                    trace=tracer.stats() if tracer.enabled else None)
