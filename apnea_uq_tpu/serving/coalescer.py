"""Request coalescer: dynamic arrivals -> fixed-shape bucket batches.

The serving tier's central trick: incoming 60-s/4-channel window
requests are batched into a small ladder of FIXED batch-size buckets
(default 16/64/256, :data:`SERVE_BUCKET_SIZES` below), each padded
up to its bucket — so every dispatch hits an already-compiled
fused-stats program and a warm process never traces or compiles on the
request path.  Rows (windows) are independent in the serving regimes
(clean-mode MCD / eval-mode DE), so requests pack FIFO into batches and
split freely at batch boundaries; a request larger than the biggest
bucket simply spills across several max-bucket batches.

jax-free by construction (pure host bookkeeping over NumPy arrays):
the engine owns dispatch, this module owns packing, padding accounting
and queue-wait bookkeeping.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
from typing import Deque, List, Optional, Sequence, Tuple

import numpy as np

from apnea_uq_tpu.telemetry.spans import mint_trace_id, span_id_for

# The serving tier's fixed batch-size ladder — the ONE canonical
# definition, living on the jax-free side so the CLI parser and this
# host-side coalescer read it without touching jax; uq/predict.py
# imports it and spells the per-bucket program-label grid
# (SERVE_PROGRAM_LABELS) from it.
SERVE_BUCKET_SIZES = (16, 64, 256)

_REQUEST_COUNTER = itertools.count()


@dataclasses.dataclass
class ServeRequest:
    """One scoring request: ``windows`` is a ``(k, T, C)`` float32 array
    (k >= 1); ``enqueue_t`` is the arrival clock reading latency is
    measured from.  ``dispatched``/``done`` track the overflow-spill
    bookkeeping: a request's rows may span several batches, and the
    request completes when its LAST row's batch returns.

    ``trace_id`` is minted at the request source (or carried inbound on
    the request line) and ``span_id`` is its globally-unique fleet
    spelling ``<replica_id>/<trace_id>`` (telemetry/spans.py) — NEVER a
    bare per-process counter, which collided across replicas; the
    ``trace_*`` fields are the per-request waterfall accumulators the
    serve loop folds batch attribution into (engine.py) and the sampled
    ``serve_trace`` event reports — host bookkeeping only, they never
    affect scoring."""

    windows: np.ndarray
    enqueue_t: float
    request_id: str = ""
    patient: Optional[str] = None
    dispatched: int = 0
    done: int = 0
    batches: int = 0
    trace_id: str = ""
    span_id: str = ""
    # Span-trace accumulators (ISSUE 17/20): pump-handoff and
    # first-dispatch clock readings, summed host-dispatch /
    # device(+D2H) / drift-fold attribution across the request's
    # batches, total pad rows it rode with, largest bucket touched, and
    # the last program label that scored it.
    dequeue_t: Optional[float] = None
    first_dispatch_t: Optional[float] = None
    trace_dispatch_s: float = 0.0
    trace_device_s: float = 0.0
    trace_drift_s: float = 0.0
    trace_pad_rows: int = 0
    trace_bucket: int = 0
    trace_label: str = ""

    def __post_init__(self):
        self.windows = np.asarray(self.windows, np.float32)
        if self.windows.ndim != 3 or self.windows.shape[0] < 1:
            raise ValueError(
                f"request windows must be (k>=1, T, C), got shape "
                f"{self.windows.shape}"
            )
        if not self.request_id:
            self.request_id = f"req-{next(_REQUEST_COUNTER)}"
        if not self.trace_id:
            self.trace_id = mint_trace_id()
        if not self.span_id:
            self.span_id = span_id_for(self.trace_id)

    @property
    def rows(self) -> int:
        return int(self.windows.shape[0])

    @property
    def complete(self) -> bool:
        return self.done >= self.rows


@dataclasses.dataclass
class BatchPlan:
    """One coalesced dispatch: FIFO row slices packed into ``bucket``.
    ``slices`` is ``[(request, start_row, end_row), ...]`` in request
    order; the engine gathers the rows, zero-pads ``pad_rows`` up to the
    bucket, dispatches, and hands each request its slice of the result."""

    bucket: int
    slices: List[Tuple[ServeRequest, int, int]]

    @property
    def rows(self) -> int:
        return sum(end - start for _r, start, end in self.slices)

    @property
    def pad_rows(self) -> int:
        return self.bucket - self.rows

    @property
    def pad_waste(self) -> float:
        """Padded fraction of the dispatched bucket — the coalescing
        efficiency number ``serve_batch``/``serve_slo`` report and
        `telemetry compare` gates lower-is-better."""
        return self.pad_rows / self.bucket

    @property
    def oldest_enqueue_t(self) -> float:
        return min(r.enqueue_t for r, _s, _e in self.slices)

    def queue_wait_s(self, now: float) -> float:
        """Age of the batch's OLDEST row at dispatch time."""
        return max(0.0, now - self.oldest_enqueue_t)

    def gather(self) -> np.ndarray:
        """The ``(rows, T, C)`` stack of the planned slices."""
        return np.concatenate(
            [r.windows[start:end] for r, start, end in self.slices], axis=0
        )


class BucketLadder:
    """The fixed batch-size ladder.  Buckets must come from
    ``SERVE_BUCKET_SIZES`` — each bucket is a registered program label
    (``{mcd|de}_serve_b<bucket>_fused[_bf16]``), and an unregistered
    bucket would dispatch a program warm-cache never saw."""

    def __init__(self, buckets: Sequence[int] = SERVE_BUCKET_SIZES):
        buckets = tuple(sorted({int(b) for b in buckets}))
        if not buckets:
            raise ValueError("the bucket ladder cannot be empty")
        bad = [b for b in buckets if b not in SERVE_BUCKET_SIZES]
        if bad:
            raise ValueError(
                f"bucket(s) {bad} are not registered serving buckets "
                f"{SERVE_BUCKET_SIZES} (serving/coalescer.py "
                f"SERVE_BUCKET_SIZES — the ladder is part of the "
                f"program-label grammar uq/predict.py builds on)"
            )
        self.buckets = buckets

    @property
    def max_bucket(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, rows: int) -> int:
        """Smallest ladder bucket holding ``rows`` (callers cap batches
        at ``max_bucket``, so a bucket always exists)."""
        if rows < 1:
            raise ValueError(f"a batch needs >= 1 row, got {rows}")
        for bucket in self.buckets:
            if rows <= bucket:
                return bucket
        raise ValueError(
            f"{rows} rows exceed the largest bucket "
            f"{self.max_bucket}; split the batch first"
        )


class RequestCoalescer:
    """FIFO request queue + batch planner.

    ``enqueue`` admits requests; ``drain`` emits :class:`BatchPlan`\\ s.
    A full ``max_bucket``'s worth of pending rows always drains; a
    partial tail drains when ``flush=True`` (input exhausted / shutdown)
    or when its oldest row has waited past ``max_wait_s`` — the
    latency/efficiency tradeoff knob (`apnea-uq serve --max-wait-ms`)."""

    def __init__(self, ladder: Optional[BucketLadder] = None):
        self.ladder = ladder or BucketLadder()
        self._pending: Deque[ServeRequest] = collections.deque()
        self.pending_rows = 0

    def enqueue(self, request: ServeRequest) -> None:
        # Fresh requests only: a spilled request's remainder stays at
        # the deque head inside _build_batch, it is never re-enqueued.
        self._pending.append(request)
        self.pending_rows += request.rows

    def _oldest_overdue(self, now: float, max_wait_s: float) -> bool:
        if not self._pending:
            return False
        return (now - self._pending[0].enqueue_t) >= max_wait_s

    def _build_batch(self) -> BatchPlan:
        """Pack up to ``max_bucket`` rows FIFO.  The boundary request
        splits (overflow spill): its remaining rows stay at the head of
        the queue for the next batch — rows are independent windows, so
        splitting never changes any score."""
        limit = self.ladder.max_bucket
        slices: List[Tuple[ServeRequest, int, int]] = []
        taken = 0
        while self._pending and taken < limit:
            req = self._pending[0]
            start = req.dispatched
            take = min(req.rows - start, limit - taken)
            end = start + take
            slices.append((req, start, end))
            req.dispatched = end
            req.batches += 1
            taken += take
            if req.dispatched >= req.rows:
                self._pending.popleft()
        self.pending_rows -= taken
        return BatchPlan(bucket=self.ladder.bucket_for(taken),
                         slices=slices)

    def drain(self, *, now: float, max_wait_s: float = 0.0,
              flush: bool = False) -> List[BatchPlan]:
        """Batch plans ready to dispatch at ``now``.  Without ``flush``,
        only full-ladder batches (>= ``max_bucket`` pending rows) or
        overdue tails (oldest wait >= ``max_wait_s``) drain — the rest
        keeps coalescing."""
        plans: List[BatchPlan] = []
        while self._pending:
            if (not flush
                    and self.pending_rows < self.ladder.max_bucket
                    and not self._oldest_overdue(now, max_wait_s)):
                break
            plans.append(self._build_batch())
        return plans
