"""One serve replica as a subprocess.

The worker the fleet-scale paths fork K times: bench.py's capacity
sweep and the fleet acceptance test launch
``python -m apnea_uq_tpu.serving.replica --run-dir <dir> ...`` per
replica, each building a ServingEngine over a fresh-initialized model
(weight values never matter to a perf harness), AOT-warming the bucket
ladder, and driving the seeded load generator.  Every replica's
telemetry lands in its own run dir; ``apnea-uq telemetry fleet`` merges
them afterwards.

Sharing the warm program store: the parent points every replica at ONE
store/cache pair via ``APNEA_UQ_PROGRAM_STORE_DIR`` /
``APNEA_UQ_XLA_CACHE_DIR`` (the compilecache env overrides), so after
the first replica (or a parent pre-warm) pays the compiles, the rest
acquire ``source=store`` hits and the fleet's request paths never
compile — the multi-replica spelling of the warm-serve contract.

``--slow-ms`` injects a fixed per-dispatch sleep in front of
``score_batch`` — the seeded way to manufacture one degraded replica so
the fleet rollup's imbalance/outlier gate has something real to catch
(acceptance-test harness, not a production knob).
"""

from __future__ import annotations

import argparse
import os
import time
from typing import Optional, Sequence


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m apnea_uq_tpu.serving.replica",
        description="One load-generated serve replica (fleet harness "
                    "worker).",
    )
    parser.add_argument("--run-dir", required=True,
                        help="Telemetry run directory this replica "
                             "writes (one per replica; merge with "
                             "`apnea-uq telemetry fleet`).")
    parser.add_argument("--requests", type=int, default=64,
                        help="Synthetic requests to serve.")
    parser.add_argument("--rate", type=float, default=0.0,
                        help="Per-replica offered arrival rate in "
                             "requests/sec (0 = as fast as possible).")
    parser.add_argument("--arrival", choices=("uniform", "poisson"),
                        default="poisson",
                        help="Arrival schedule (loadgen semantics; "
                             "capacity sweeps default to the bursty "
                             "poisson process).")
    parser.add_argument("--max-windows", type=int, default=4,
                        help="Max windows per synthetic request.")
    parser.add_argument("--seed", type=int, default=0,
                        help="Loadgen payload/arrival seed (give each "
                             "replica its own so the fleet's traffic "
                             "isn't K copies of one stream).")
    parser.add_argument("--passes", type=int, default=4,
                        help="MC-dropout passes per window.")
    parser.add_argument("--slo-every", type=int, default=0,
                        help="Emit a serve_slo snapshot every N "
                             "requests (0 = engine default).")
    parser.add_argument("--slow-ms", type=float, default=0.0,
                        help="Inject an N-ms sleep per dispatched "
                             "batch — the degraded-replica fixture for "
                             "outlier-detection tests.")
    parser.add_argument("--trace-every", type=int, default=0,
                        help="Emit a serve_trace waterfall for 1 in N "
                             "completed requests (0 = off).")
    parser.add_argument("--trace-slow-ms", type=float, default=0.0,
                        help="Tail-based exemplar capture: every "
                             "request over this latency budget emits "
                             "its waterfall, plus rolling per-bucket "
                             "p99 outliers (0 = off).")
    return parser


def run_replica(argv: Optional[Sequence[str]] = None) -> dict:
    """Serve the configured synthetic stream; returns the final SLO
    summary dict (also emitted as the closing ``serve_slo`` in the
    replica's run dir)."""
    args = build_parser().parse_args(argv)

    from apnea_uq_tpu import compilecache
    from apnea_uq_tpu.config import ModelConfig, UQConfig
    from apnea_uq_tpu.models import AlarconCNN1D, init_variables
    from apnea_uq_tpu.serving.engine import ServingEngine
    from apnea_uq_tpu.serving.loadgen import run_loadgen
    from apnea_uq_tpu.telemetry.runlog import start_run

    import jax

    cfg = ModelConfig()
    model = AlarconCNN1D(cfg)
    variables = init_variables(model, jax.random.key(0))
    with compilecache.activate(None), \
            start_run(args.run_dir, stage="serve-replica") as run_log:
        engine = ServingEngine(
            model, variables, method="mcd",
            uq=UQConfig(mc_passes=args.passes), run_log=run_log,
            seed=args.seed,
        )
        engine.warm()
        if args.slow_ms > 0:
            inner = engine.score_batch

            def slowed(rows, **kwargs):
                time.sleep(args.slow_ms / 1e3)
                return inner(rows, **kwargs)

            engine.score_batch = slowed
        summary = run_loadgen(
            engine, args.requests, max_windows=args.max_windows,
            seed=args.seed, rate=args.rate, arrival=args.arrival,
            slo_every=args.slo_every or None,
            trace_every=args.trace_every,
            trace_slow_ms=args.trace_slow_ms,
        )
    return summary


def main(argv: Optional[Sequence[str]] = None) -> int:
    summary = run_replica(argv)
    from apnea_uq_tpu.telemetry import log

    log(f"replica done: {summary.get('requests')} request(s), "
        f"p99 {summary.get('p99_ms')}ms, "
        f"{summary.get('windows_per_s')} windows/s "
        f"-> {os.environ.get('APNEA_UQ_REPLICA_ID', 'auto id')}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
