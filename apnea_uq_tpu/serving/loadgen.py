"""Load generator for the serving tier.

Synthesizes a deterministic (seeded) request stream at a configurable
arrival rate and drives the serve loop with it — the bench's ``serve``
block, the warm-serve acceptance test, and `apnea-uq serve --loadgen N`
all run this instead of waiting for real traffic.  ``rate`` paces
arrivals on the wall clock (requests/sec; 0 = as fast as possible), so
queue-wait and latency numbers under a paced run mean what they would
in production.  jax-free.
"""

from __future__ import annotations

import json
import time
from typing import Iterator, Optional

import numpy as np

from apnea_uq_tpu.serving.coalescer import ServeRequest


# The injected cohort shift of --drift-after traffic: a per-channel
# scale + offset big enough that a few hundred shifted windows push the
# rolling PSI far past the 0.2 drift threshold on the standardized
# baseline, yet tame enough that scoring stays numerically boring.
DRIFT_SCALE = 2.0
DRIFT_SHIFT = 1.5


ARRIVAL_MODES = ("uniform", "poisson")


def synthetic_requests(
    n_requests: int,
    *,
    max_windows: int = 4,
    time_steps: int = 60,
    channels: int = 4,
    seed: int = 0,
    rate: float = 0.0,
    arrival: str = "uniform",
    drift_after: Optional[int] = None,
    clock=time.perf_counter,
    sleep=time.sleep,
) -> Iterator[ServeRequest]:
    """Yield ``n_requests`` seeded synthetic requests of 1..max_windows
    standardized-shaped windows each.  With ``rate > 0``, request ``i``
    is released no earlier than its scheduled offset after the first —
    an open-loop arrival process, so a slow scorer accumulates queue
    wait instead of silently back-pressuring the generator.  ``arrival``
    picks the schedule: ``uniform`` (default) releases at the fixed
    cadence ``i / rate``; ``poisson`` draws seeded exponential
    inter-arrival gaps of mean ``1 / rate`` (a memoryless Poisson
    process — the burstiness a capacity sweep needs to find the real
    knee, since evenly-spaced arrivals flatter the coalescer).  The gap
    stream uses its own rng, so the window payloads are bit-identical
    across arrival modes for a given ``seed``.

    ``drift_after=N`` applies a per-channel mean/scale shift
    (``x * DRIFT_SCALE + DRIFT_SHIFT``) to every window from request N
    on — the seeded way to exercise the online-drift path: the first N
    requests score PSI ~ 0 against a standardized baseline, the shifted
    cohort flips the ``serve_drift`` verdict."""
    if n_requests < 1:
        raise ValueError(f"n_requests must be >= 1, got {n_requests}")
    if max_windows < 1:
        raise ValueError(f"max_windows must be >= 1, got {max_windows}")
    if drift_after is not None and drift_after < 0:
        raise ValueError(f"drift_after must be >= 0, got {drift_after}")
    if arrival not in ARRIVAL_MODES:
        raise ValueError(
            f"arrival must be one of {ARRIVAL_MODES}, got {arrival!r}")
    rng = np.random.default_rng(seed)
    # Arrival gaps come from a DISTINCT seeded stream: switching uniform
    # <-> poisson must never perturb the request payloads.
    gap_rng = np.random.default_rng((seed, 0xA221))
    offset = 0.0
    t0 = clock()
    for i in range(n_requests):
        if rate > 0:
            if arrival == "poisson":
                if i > 0:
                    offset += float(gap_rng.exponential(1.0 / rate))
            else:
                offset = i / rate
            target = t0 + offset
            delay = target - clock()
            if delay > 0:
                sleep(delay)
        k = int(rng.integers(1, max_windows + 1))
        windows = rng.normal(size=(k, time_steps, channels)).astype(
            np.float32)
        if drift_after is not None and i >= drift_after:
            windows = windows * DRIFT_SCALE + DRIFT_SHIFT
        yield ServeRequest(windows=windows, enqueue_t=clock(),
                           request_id=f"loadgen-{i}")


def ndjson_requests(path: str, *, time_steps: int = 60,
                    channels: int = 4,
                    clock=time.perf_counter) -> Iterator[ServeRequest]:
    """Real-traffic request source for `apnea-uq serve --input`: one
    ``{"id": ..., "windows": [[[c0..c3] x T] x k]}`` NDJSON object per
    line (``-`` = stdin); arrival time is the moment the line is read.
    An optional ``"trace_id"`` on the line is honored end-to-end — the
    caller's distributed-tracing context rides into the span id
    ``<replica_id>/<trace_id>`` instead of a locally-minted one.
    A malformed line raises — a request API, unlike the sample stream,
    has no partial-garbage regime worth limping through."""
    import sys

    def lines():
        if path == "-":
            yield from sys.stdin
            return
        with open(path, encoding="utf-8") as fh:
            yield from fh

    for i, line in enumerate(lines()):
        line = line.strip()
        if not line:
            continue
        doc = json.loads(line)
        windows = np.asarray(doc["windows"], np.float32)
        if windows.ndim != 3 or windows.shape[1:] != (time_steps, channels):
            raise ValueError(
                f"request line {i}: windows must be (k, {time_steps}, "
                f"{channels}), got {windows.shape}"
            )
        trace_id = doc.get("trace_id")
        yield ServeRequest(windows=windows, enqueue_t=clock(),
                           request_id=str(doc.get("id", f"req-{i}")),
                           patient=doc.get("patient"),
                           trace_id=str(trace_id) if trace_id else "")


def run_loadgen(
    engine,
    n_requests: int,
    *,
    max_windows: int = 4,
    seed: int = 0,
    rate: float = 0.0,
    arrival: str = "uniform",
    max_wait_s: float = 0.005,
    slo_every: Optional[int] = None,
    drift_after: Optional[int] = None,
    drift=None,
    trace_every: int = 0,
    trace_slow_ms: float = 0.0,
):
    """Drive ``engine`` with the synthetic stream; returns the final
    SLO summary dict (also emitted as the closing ``serve_slo``).
    ``drift_after``/``drift``/``trace_every`` thread the ISSUE 17
    observability knobs through: injected post-N cohort shift, the
    online drift monitor fed at dispatch, and 1-in-N span tracing;
    ``trace_slow_ms`` arms ISSUE 20's tail-based exemplar capture
    (every over-budget request emits its waterfall); ``arrival`` picks
    the pacing schedule (see synthetic_requests)."""
    from apnea_uq_tpu.serving.engine import DEFAULT_SLO_EVERY, serve_requests

    cfg = engine.model.config
    requests = synthetic_requests(
        n_requests, max_windows=max_windows, time_steps=cfg.time_steps,
        channels=cfg.num_channels, seed=seed, rate=rate, arrival=arrival,
        drift_after=drift_after,
    )
    return serve_requests(
        engine, requests, max_wait_s=max_wait_s,
        slo_every=slo_every or DEFAULT_SLO_EVERY,
        drift=drift, trace_every=trace_every,
        trace_slow_ms=trace_slow_ms,
    )
