"""Online UQ serving tier (ISSUE 15): the batch pipeline's request path.

Everything else in the repo is file-mediated batch; this package is the
long-lived scoring process behind ``apnea-uq serve`` and ``apnea-uq
score`` — request coalescing into the fixed bucket ladder's fused-stats
programs (coalescer.py), AOT-warm dispatch + per-batch device timing
(engine.py), sliding-window continuous scoring over a live PSG signal
stream with resumable per-patient ring state (stream.py), SLO telemetry
(slo.py: ``serve_request`` / ``serve_batch`` / ``serve_slo`` events),
online input-drift scoring against the frozen quality baseline
(drift.py: per-tenant rolling fingerprints, ``serve_drift`` events),
and a load generator (loadgen.py) that drives the loop for the bench's
``serve`` block and the warm-serve acceptance test.

Import discipline mirrors the telemetry layer: coalescer/slo/drift/
loadgen are jax-free (pure NumPy host logic); only engine.py (dispatch)
and stream.py (via the engine it is handed) touch jax.
"""

from apnea_uq_tpu.serving.coalescer import (  # noqa: F401
    BatchPlan,
    BucketLadder,
    RequestCoalescer,
    ServeRequest,
)
from apnea_uq_tpu.serving.drift import DriftMonitor  # noqa: F401
from apnea_uq_tpu.serving.slo import SLOTracker  # noqa: F401
