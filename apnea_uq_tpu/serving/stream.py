"""Sliding-window continuous scorer over a live PSG signal stream.

`apnea-uq score --stream` consumes per-sample NDJSON lines — one
``{"patient": ID, "t": seconds, "v": [<channels> floats]}`` object per
line, from a file (optionally tailed with ``--follow``) or stdin —
maintains a per-patient ring buffer of the last ``window`` samples,
re-windows with a configurable ``hop`` (window k starts at sample
``k * hop``), and scores each emitted window through the serving
engine's bucket programs.  Per-window uncertainty decompositions append
to an NDJSON results file, running per-patient rollups accumulate in
the state, and the serving telemetry triple lands in the run log.

Crash contract (the ingest-progress pattern, PR 8): the per-patient
ring state — buffer, sample counter, last-seen timestamp, rollups —
commits atomically (tmp -> fsync -> os.replace, utils/io.py) after
every scored batch, so a ``kill -9`` mid-stream leaves a resumable
snapshot.  On restart the scorer reloads the state and DEDUPES replayed
input per patient by timestamp (``t <= last_t`` is skipped), so feeding
the same stream from the beginning continues exactly where the last
commit left off.  Results are at-least-once: a kill in the gap between
the results append and the state commit re-scores that one batch —
windows are keyed by (patient, start_t), so consumers dedupe on the key
— and never leaves gaps.  MCD duplicates may differ in VALUE (the
rerun's engine draws fresh per-process dispatch keys), so a dedupe
keeps whichever row it picks consistently (first wins is fine); DE is
deterministic and its duplicates are identical.

Scaling note: the snapshot is ONE JSON document covering every patient
seen, rewritten per scored batch, and patients are never evicted — the
right shape for the per-process stream counts this tier serves today
(each commit is O(patients x window) floats).  A deployment fanning
thousands of concurrent patient streams through one scorer should shard
the state per patient (write only the patients a batch touched) before
anything else; the atomic-commit discipline carries over unchanged.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional, TextIO, Tuple

import numpy as np

from apnea_uq_tpu.serving.engine import ServingEngine, decomposition_rows
from apnea_uq_tpu.serving.slo import SLOTracker
from apnea_uq_tpu.telemetry import log

STATE_FILENAME = "stream_state.json"
STATE_VERSION = 1


class _PatientState:
    """Ring buffer + rollup for one patient (host-side, JSON-round-trippable)."""

    def __init__(self, window: int):
        self.window = window
        self.buffer: collections.deque = collections.deque(maxlen=window)
        self.times: collections.deque = collections.deque(maxlen=window)
        self.samples_seen = 0
        self.last_t = float("-inf")
        self.windows_scored = 0
        self.prob_sum = 0.0
        self.entropy_sum = 0.0

    def add(self, t: float, values: List[float],
            hop: int) -> Optional[Tuple[float, np.ndarray]]:
        """Admit one sample; returns ``(start_t, (window, C) array)``
        when a window boundary is crossed.  Replayed samples
        (``t <= last_t``) are ignored — the resume dedupe."""
        if t <= self.last_t:
            return None
        self.last_t = t
        self.buffer.append(values)
        self.times.append(t)
        self.samples_seen += 1
        if self.samples_seen < self.window:
            return None
        if (self.samples_seen - self.window) % hop != 0:
            return None
        return (float(self.times[0]),
                np.asarray(self.buffer, np.float32))

    def rollup(self) -> Dict[str, Any]:
        n = self.windows_scored
        return {
            "windows": n,
            "mean_prob": round(self.prob_sum / n, 6) if n else None,
            "mean_total_entropy": (round(self.entropy_sum / n, 6)
                                   if n else None),
        }

    def to_json(self) -> Dict[str, Any]:
        return {
            "buffer": [list(map(float, row)) for row in self.buffer],
            "times": [float(t) for t in self.times],
            "samples_seen": self.samples_seen,
            "last_t": self.last_t,
            "windows_scored": self.windows_scored,
            "prob_sum": self.prob_sum,
            "entropy_sum": self.entropy_sum,
        }

    @classmethod
    def from_json(cls, window: int, doc: Dict[str, Any]) -> "_PatientState":
        state = cls(window)
        for row in doc.get("buffer", []):
            state.buffer.append(list(row))
        for t in doc.get("times", []):
            state.times.append(float(t))
        state.samples_seen = int(doc.get("samples_seen", 0))
        state.last_t = float(doc.get("last_t", float("-inf")))
        state.windows_scored = int(doc.get("windows_scored", 0))
        state.prob_sum = float(doc.get("prob_sum", 0.0))
        state.entropy_sum = float(doc.get("entropy_sum", 0.0))
        return state


def read_sample_lines(path: str, *, follow: bool = False,
                      max_idle_s: float = 5.0,
                      poll_s: float = 0.2) -> Iterator[str]:
    """Lines from ``path`` (``-`` = stdin).  ``follow`` keeps tailing
    past EOF — new appended lines stream out as they land — until
    ``max_idle_s`` passes with no growth (the bounded-exit knob tests
    and operators both need; a production tail sets it large).  The
    idle timeout holds for stdin too: ``--follow`` on ``-`` polls with
    ``select`` instead of blocking forever on a quiet pipe.

    Every elapsed idle poll — stdin in either mode, and file tails
    under ``follow`` — additionally yields one empty-string HEARTBEAT
    line: the consumer's loop regains control on a quiet stream (the
    scorer's time-based pending flush hangs off it) while
    ``process_line`` treats the blank as a no-op.  Dense streams and
    non-follow FILE reads never emit one, so batch-exact tests over
    in-memory or file inputs stay deterministic."""
    import sys

    if path == "-":
        # select + raw-fd reads in BOTH stdin modes: selecting on the
        # buffered text stream would deadlock the classic way (readline
        # buffers several lines off the fd, select then reports the
        # drained fd idle while lines sit unread in the Python buffer),
        # and the idle heartbeats keep the consumer's time-based flush
        # honest on a live pipe that pauses without closing.  ``follow``
        # only controls whether prolonged silence EXITS; EOF (closed
        # pipe) always does, flushing a final unterminated line first.
        import select

        fd = sys.stdin.fileno()
        buf = b""
        idle_since = None
        while True:
            ready, _w, _x = select.select([fd], [], [], poll_s)
            if ready:
                chunk = os.read(fd, 65536)
                if not chunk:
                    if buf:  # final unterminated line
                        yield buf.decode("utf-8", "replace")
                    return  # closed pipe: nothing more can ever arrive
                idle_since = None
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    yield line.decode("utf-8", "replace") + "\n"
                continue
            if follow:
                now = time.monotonic()
                if idle_since is None:
                    idle_since = now
                elif now - idle_since >= max_idle_s:
                    if buf:  # quiet pipe's unterminated tail
                        yield buf.decode("utf-8", "replace")
                    return
            yield ""  # idle heartbeat: hand control back to the consumer
        return
    with open(path, encoding="utf-8") as fh:
        idle_since = None
        pending = ""
        while True:
            line = fh.readline()
            if line:
                idle_since = None
                # Hold back a partial line (the writer is mid-append and
                # the newline hasn't landed yet): yielding it now would
                # split one sample into two bogus lines, both of which
                # json-fail and silently drop the sample.
                pending += line
                if not pending.endswith("\n"):
                    continue
                yield pending
                pending = ""
                continue
            if not follow:
                if pending:
                    yield pending  # final unterminated line
                return
            now = time.monotonic()
            if idle_since is None:
                idle_since = now
            elif now - idle_since >= max_idle_s:
                if pending:
                    yield pending
                return
            time.sleep(poll_s)
            yield ""  # idle heartbeat: hand control back to the consumer


class StreamScorer:
    """The `score --stream` loop: samples in, scored windows out.

    Windows pending dispatch coalesce until a full max-ladder bucket is
    ready (or the input drains), then score through
    ``engine.score_batch`` — the same padded-bucket programs the serve
    path runs — and append one NDJSON result row per window to
    ``out_path``.
    """

    def __init__(self, engine: ServingEngine, *, state_dir: str,
                 out_path: str, window: Optional[int] = None,
                 hop: int = 60, run_log=None, drift=None,
                 trace_every: int = 0, trace_slow_ms: float = 0.0):
        from apnea_uq_tpu.telemetry.spans import ExemplarTracer

        self.engine = engine
        self.window = int(window or engine.model.config.time_steps)
        if self.window != engine.model.config.time_steps:
            raise ValueError(
                f"window must match the model's time_steps "
                f"({engine.model.config.time_steps}), got {self.window}"
            )
        if hop < 1:
            raise ValueError(f"hop must be >= 1 sample, got {hop}")
        self.hop = int(hop)
        self.state_dir = state_dir
        self.state_path = os.path.join(state_dir, STATE_FILENAME)
        self.out_path = out_path
        self.run_log = run_log
        self.slo = SLOTracker()
        # Optional online drift monitor (serving/drift.py): every scored
        # window folds into the patient's rolling fingerprint BEFORE the
        # state commit, and the monitor's state rides the same atomic
        # snapshot — ring state and drift window revert (or survive)
        # together, so replayed windows fold in exactly once.
        self.drift = drift
        # Flush-cycle span tracing (ISSUE 20): one serve_trace span per
        # flush cycle — the stream's unit of work — with flush/commit
        # child spans, through the same at-completion exemplar sampler
        # the serve loop runs (slow flush cycles always leave evidence).
        self.tracer = ExemplarTracer(trace_every=trace_every,
                                     slow_ms=trace_slow_ms)
        self._flushes = 0
        self.patients: Dict[str, _PatientState] = {}
        # (patient, start_t, window array, enqueue clock) awaiting dispatch.
        self._pending: List[Tuple[str, float, np.ndarray, float]] = []
        self._out_fh: Optional[TextIO] = None
        self._load_state()

    # -- state ------------------------------------------------------------

    def _load_state(self) -> None:
        from apnea_uq_tpu.utils.io import read_json_tolerant

        if not os.path.exists(self.state_path):
            return
        # Torn-tail-tolerant load (the conc gate's torn-read-protocol
        # rule): a half-written or corrupt snapshot degrades to a fresh
        # start instead of crash-looping the resume path.  The version/
        # geometry checks below still raise — those are VALID snapshots
        # this run must not silently reinterpret.
        doc = read_json_tolerant(self.state_path)
        if not isinstance(doc, dict):
            log(f"stream: state at {self.state_path} is torn or corrupt "
                f"— starting fresh")
            return
        if doc.get("version") != STATE_VERSION:
            raise ValueError(
                f"unsupported stream state version {doc.get('version')!r} "
                f"at {self.state_path}"
            )
        if doc.get("window") != self.window or doc.get("hop") != self.hop:
            raise ValueError(
                f"stream state at {self.state_path} was written with "
                f"window={doc.get('window')}/hop={doc.get('hop')}, "
                f"this run uses window={self.window}/hop={self.hop} — "
                f"resuming would mis-place every later window"
            )
        for pid, pdoc in doc.get("patients", {}).items():
            self.patients[pid] = _PatientState.from_json(self.window, pdoc)
        # Drift state is an OPTIONAL key (same STATE_VERSION): older
        # snapshots — and runs without --drift-check — simply lack it,
        # and a restored monitor keeps its rolling window instead of
        # resetting the verdict on every restart.
        if self.drift is not None and doc.get("drift"):
            self.drift.restore(doc["drift"])

    def _save_state(self) -> None:
        from apnea_uq_tpu.utils.io import atomic_write_json

        os.makedirs(self.state_dir, exist_ok=True)
        state = {
            "version": STATE_VERSION,
            "window": self.window,
            "hop": self.hop,
            "patients": {pid: p.to_json()
                         for pid, p in sorted(self.patients.items())},
        }
        if self.drift is not None:
            state["drift"] = self.drift.to_json()
        atomic_write_json(self.state_path, state)

    # -- scoring ----------------------------------------------------------

    def _out(self) -> TextIO:
        if self._out_fh is None:
            out_dir = os.path.dirname(os.path.abspath(self.out_path))
            os.makedirs(out_dir, exist_ok=True)
            self._out_fh = open(self.out_path, "a", encoding="utf-8")
        return self._out_fh

    def _flush_pending(self) -> None:
        """Score every pending window in max-bucket chunks, append the
        result rows, fold the rollups, THEN commit the ring state — the
        at-least-once ordering (see the module docstring).  Each flush
        cycle is one trace span candidate: ``latency_s`` runs from the
        oldest pending window's admission to the state commit, with
        flush (score + append) and commit child spans."""
        from apnea_uq_tpu.conc.perturb import perturb_point
        from apnea_uq_tpu.telemetry.runlog import replica_id
        from apnea_uq_tpu.telemetry.spans import mint_trace_id, span_id_for

        if not self._pending:
            self._save_state()
            return
        out = self._out()
        clock = time.perf_counter
        span_oldest = min(e for _p, _t, _w, e in self._pending)
        flush_start = clock()
        chunks = 0
        span_windows = 0
        span_pad_rows = 0
        span_bucket = 0
        span_label = ""
        span_dispatch_s = span_device_s = span_drift_s = 0.0
        while self._pending:
            # Schedule-perturbation seam (conc/perturb.py): a no-op
            # unless armed; armed, it stretches the observe->write->
            # commit gap so crash/replay tests can land inside it
            # deterministically.
            perturb_point("stream.flush.chunk")
            chunk = self._pending[:self.engine.ladder.max_bucket]
            del self._pending[:len(chunk)]
            rows = np.stack([w for _p, _t, w, _e in chunk])
            oldest = min(e for _p, _t, _w, e in chunk)
            if self.drift is not None:
                # Fold before the state commit below: the rolling
                # fingerprint and the ring state revert together on a
                # crash, so a replayed window is never double-counted.
                drift_t0 = clock()
                for pid, _t, w, _e in chunk:
                    self.drift.observe(w, tenant=pid)
                span_drift_s += clock() - drift_t0
            stats = self.engine.score_batch(
                rows, queue_wait_s=max(0.0, time.perf_counter() - oldest),
                slo=self.slo,
            )
            batch = self.engine.last_batch or {}
            span_dispatch_s += float(batch.get("dispatch_s", 0.0))
            span_device_s += float(batch.get("device_s", 0.0))
            span_pad_rows += int(batch.get("pad_rows", 0))
            span_bucket = max(span_bucket, int(batch.get("bucket", 0)))
            span_label = str(batch.get("label", ""))
            chunks += 1
            span_windows += len(chunk)
            decomp = decomposition_rows(stats)
            for i, (pid, start_t, _w, _e) in enumerate(chunk):
                record = {"patient": pid, "start_t": start_t}
                record.update(
                    {k: round(float(v[i]), 6) for k, v in decomp.items()}
                )
                out.write(json.dumps(record) + "\n")
                pstate = self.patients[pid]
                pstate.windows_scored += 1
                pstate.prob_sum += float(decomp["mean_prob"][i])
                pstate.entropy_sum += float(decomp["total_entropy"][i])
            out.flush()
        scored_t = clock()
        perturb_point("stream.flush.commit")
        self._save_state()
        committed_t = clock()
        flush_idx = self._flushes
        self._flushes += 1
        trace_id = mint_trace_id()
        span_id = span_id_for(trace_id)
        latency_s = committed_t - span_oldest
        reasons = self.tracer.decide(bucket=span_bucket,
                                     latency_s=latency_s,
                                     span_id=span_id)
        if self.run_log is not None and reasons:
            d2h_s = max(span_device_s - span_dispatch_s, 0.0)
            children = [
                {"phase": "flush",
                 "start_s": round(max(flush_start - span_oldest, 0.0), 6),
                 "dur_s": round(max(scored_t - flush_start, 0.0), 6)},
                {"phase": "commit",
                 "start_s": round(max(scored_t - span_oldest, 0.0), 6),
                 "dur_s": round(max(committed_t - scored_t, 0.0), 6)},
            ]
            if span_drift_s > 0.0:
                children.insert(1, {
                    "phase": "drift_fold",
                    "start_s": round(max(flush_start - span_oldest,
                                         0.0), 6),
                    "dur_s": round(span_drift_s, 6)})
            self.run_log.event(
                "serve_trace",
                replica_id=replica_id(),
                span_id=span_id,
                trace_id=trace_id,
                request_id=f"stream-flush-{flush_idx}",
                windows=span_windows,
                batches=chunks,
                bucket=span_bucket,
                pad_rows=span_pad_rows,
                label=span_label,
                queue_s=round(max(flush_start - span_oldest, 0.0), 6),
                service_s=round(max(committed_t - flush_start, 0.0), 6),
                dispatch_s=round(span_dispatch_s, 6),
                device_s=round(span_device_s, 6),
                d2h_s=round(d2h_s, 6),
                respond_s=round(max(committed_t - scored_t, 0.0), 6),
                latency_s=round(max(latency_s, 0.0), 6),
                sampled_for=list(reasons),
                exemplar=bool("slow" in reasons or "p99" in reasons),
                children=children,
            )

    def process_line(self, line: str) -> int:
        """Admit one NDJSON sample line; returns how many windows it
        completed (queued for the next flush).  Malformed lines are
        logged and skipped — one corrupt sample must not kill a
        long-lived scorer."""
        line = line.strip()
        if not line:
            return 0
        try:
            doc = json.loads(line)
            pid = str(doc["patient"])
            t = float(doc["t"])
            values = [float(v) for v in doc["v"]]
        except (ValueError, KeyError, TypeError) as e:
            log(f"stream: skipped malformed sample line "
                f"({type(e).__name__}: {e})")
            return 0
        if len(values) != self.engine.model.config.num_channels:
            log(f"stream: skipped sample for {pid}: {len(values)} "
                f"channel(s), model expects "
                f"{self.engine.model.config.num_channels}")
            return 0
        pstate = self.patients.get(pid)
        if pstate is None:
            pstate = self.patients[pid] = _PatientState(self.window)
        emitted = pstate.add(t, values, self.hop)
        if emitted is None:
            return 0
        start_t, window = emitted
        self._pending.append((pid, start_t, window, time.perf_counter()))
        return 1

    def run(self, lines: Iterator[str],
            max_pending_s: float = 1.0) -> Dict[str, Any]:
        """Consume the stream: score a batch whenever a full max bucket
        of windows is pending OR the oldest pending window has waited
        ``max_pending_s`` (the live-stream latency/crash-loss bound — a
        slow 1 Hz feed must not hold hours of admitted samples hostage
        to a 256-window batch; ``read_sample_lines`` follow mode emits
        idle heartbeats so the age check fires on quiet streams too),
        flush the tail at end of input, and close with the final
        ``serve_slo`` (carrying the patient count) plus per-patient
        rollup log lines.  Returns the SLO summary."""
        try:
            for line in lines:
                self.process_line(line)
                if len(self._pending) >= self.engine.ladder.max_bucket:
                    self._flush_pending()
                elif (self._pending
                      and time.perf_counter() - self._pending[0][3]
                      >= max_pending_s):
                    self._flush_pending()
            self._flush_pending()
        finally:
            if self._out_fh is not None:
                self._out_fh.close()
                self._out_fh = None
        if self.drift is not None:
            # Score the sub-cadence tail so every tenant closes with a
            # verdict, then persist the post-flush monitor state.
            if self.drift.flush():
                self._save_state()
        summary = self.slo.emit(
            self.run_log, final=True, patients=len(self.patients),
            trace=self.tracer.stats() if self.tracer.enabled else None)
        for pid, pstate in sorted(self.patients.items()):
            roll = pstate.rollup()
            log(f"stream rollup {pid}: {roll['windows']} window(s), "
                f"mean_prob {roll['mean_prob']}, "
                f"mean_total_entropy {roll['mean_total_entropy']}")
        return summary
