"""Online input-drift detection on the serving path (ISSUE 17).

The eval stages score drift once per batch eval (telemetry/quality.py,
vs the frozen ``quality_baseline``); this module moves the same PSI/KS
machinery onto the request path.  A :class:`DriftMonitor` keeps one
:class:`~apnea_uq_tpu.analysis.fingerprint.RollingFingerprint` per
stream/tenant, fed from every scored window, and re-scores it against
the frozen baseline every ``score_every`` windows — emitting a
``serve_drift`` telemetry event with an ok/warn/drift verdict, so a
cohort shift in live traffic becomes a gateable number minutes after it
starts instead of at the next batch eval.

All scoring is host-side NumPy on the baseline's frozen histogram
edges: the monitor adds **zero** request-path compiles (the warm-serve
acceptance pin in tests/test_serving.py keeps holding).  Jax-free like
coalescer/slo/loadgen — importable wherever the read side runs.

Thresholds follow the PSI rule of thumb (fingerprint.py): warn at
moderate shift, drift at significant shift; ``tenant_thresholds`` lets
one noisy tenant run looser (or a critical one tighter) without moving
the fleet-wide default.  The monitor's complete state round-trips
through :meth:`DriftMonitor.to_json` / :meth:`DriftMonitor.from_json`,
which is how it rides the stream scorer's atomic ``stream_state.json``
snapshot: ring state and drift state commit in the SAME snapshot, so a
kill -9 resume keeps the rolling window (no verdict reset) and replayed
windows fold in exactly once.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from apnea_uq_tpu.analysis.fingerprint import RollingFingerprint

DRIFT_STATE_VERSION = 1

#: Re-score cadence: windows folded into a tenant's rolling fingerprint
#: between ``serve_drift`` emissions.
DEFAULT_SCORE_EVERY = 256

#: Rolling-window recency: observation weight halves every this many
#: windows, so a resolved upstream incident ages out of the score.
DEFAULT_HALF_LIFE = 4096.0

# PSI/KS verdict thresholds (the fingerprint module's rule of thumb:
# < 0.1 stable, 0.1-0.2 moderate, > 0.2 significant drift).
DEFAULT_WARN_PSI = 0.1
DEFAULT_DRIFT_PSI = 0.2
DEFAULT_WARN_KS = 0.1
DEFAULT_DRIFT_KS = 0.2

_THRESHOLD_KEYS = ("warn_psi", "drift_psi", "warn_ks", "drift_ks")

#: The default tenant for traffic that carries no stream/patient
#: attribution (e.g. anonymous loadgen requests).
DEFAULT_TENANT = "default"


class DriftMonitor:
    """Per-tenant rolling drift scoring against a frozen baseline.

    ``baseline`` is one fingerprint document (a set entry of the
    registry's ``quality_baseline`` artifact — see
    :meth:`baseline_from_registry`).  Feed every scored window through
    :meth:`observe`; every ``score_every`` windows per tenant the
    monitor re-bins nothing (the rolling state already lives on the
    baseline's edges) and emits one ``serve_drift`` event through
    ``run_log`` with the verdict.
    """

    def __init__(self, baseline: Dict[str, Any], *,
                 score_every: int = DEFAULT_SCORE_EVERY,
                 half_life: Optional[float] = DEFAULT_HALF_LIFE,
                 warn_psi: float = DEFAULT_WARN_PSI,
                 drift_psi: float = DEFAULT_DRIFT_PSI,
                 warn_ks: float = DEFAULT_WARN_KS,
                 drift_ks: float = DEFAULT_DRIFT_KS,
                 tenant_thresholds: Optional[Dict[str, Dict[str, float]]]
                 = None,
                 run_log=None):
        if score_every < 1:
            raise ValueError(f"score_every must be >= 1, got {score_every}")
        self.baseline = baseline
        self.score_every = int(score_every)
        self.half_life = half_life
        self.thresholds = {"warn_psi": float(warn_psi),
                           "drift_psi": float(drift_psi),
                           "warn_ks": float(warn_ks),
                           "drift_ks": float(drift_ks)}
        self.tenant_thresholds = {
            str(tenant): {k: float(v) for k, v in (overrides or {}).items()
                          if k in _THRESHOLD_KEYS}
            for tenant, overrides in (tenant_thresholds or {}).items()
        }
        self.run_log = run_log
        # tenant -> {"rolling": RollingFingerprint, "since": int,
        #            "verdict": str|None}
        self._tenants: Dict[str, Dict[str, Any]] = {}

    @classmethod
    def baseline_from_registry(cls, registry) -> Dict[str, Any]:
        """The serving-side baseline fingerprint: the unbalanced
        test-set entry frozen into ``quality_baseline`` at prepare time
        (falling back to any frozen set when the cohort had no
        unbalanced split).  Imported lazily so the module stays
        importable with no registry on the path."""
        from apnea_uq_tpu.data import registry as reg

        doc = registry.load_json(reg.QUALITY_BASELINE)
        sets = doc.get("sets") or {}
        fingerprint = sets.get(reg.TEST_STD_UNBALANCED)
        if fingerprint is None and sets:
            fingerprint = sets[sorted(sets)[0]]
        if not fingerprint or not fingerprint.get("channels"):
            raise ValueError(
                "quality_baseline carries no usable fingerprint — "
                "re-run `apnea-uq prepare` to freeze one")
        return fingerprint

    def _thresholds_for(self, tenant: str) -> Dict[str, float]:
        merged = dict(self.thresholds)
        merged.update(self.tenant_thresholds.get(tenant, {}))
        return merged

    def _state_for(self, tenant: str) -> Dict[str, Any]:
        state = self._tenants.get(tenant)
        if state is None:
            state = {
                "rolling": RollingFingerprint(self.baseline,
                                              half_life=self.half_life),
                "since": 0,
                "verdict": None,
            }
            self._tenants[tenant] = state
        return state

    def observe(self, windows, *,
                tenant: str = DEFAULT_TENANT) -> Optional[Dict[str, Any]]:
        """Fold a window batch — (T, C) or (N, T, C) — into ``tenant``'s
        rolling fingerprint; returns the fresh verdict document when the
        fold crossed the re-score cadence, None otherwise."""
        state = self._state_for(str(tenant))
        rolling = state["rolling"]
        before = rolling.seen
        rolling.update(windows)
        state["since"] += rolling.seen - before
        if state["since"] >= self.score_every:
            return self.score_tenant(str(tenant))
        return None

    def score_tenant(self, tenant: str, *,
                     final: bool = False) -> Optional[Dict[str, Any]]:
        """Score one tenant's rolling fingerprint against the baseline
        now, emit the ``serve_drift`` event, and return the verdict
        document (None when the tenant has seen no windows)."""
        state = self._tenants.get(tenant)
        if state is None or state["rolling"].seen == 0:
            return None
        report = state["rolling"].score(self.baseline)
        limits = self._thresholds_for(tenant)
        if (report["max_psi"] >= limits["drift_psi"]
                or report["max_ks"] >= limits["drift_ks"]):
            verdict = "drift"
        elif (report["max_psi"] >= limits["warn_psi"]
                or report["max_ks"] >= limits["warn_ks"]):
            verdict = "warn"
        else:
            verdict = "ok"
        state["since"] = 0
        state["verdict"] = verdict
        doc = {
            "tenant": tenant,
            "verdict": verdict,
            "windows": int(state["rolling"].seen),
            "max_psi": report["max_psi"],
            "max_ks": report["max_ks"],
            "max_mean_shift": report["max_mean_shift"],
            "worst_channel": report["worst_channel"],
            "warn_psi": limits["warn_psi"],
            "drift_psi": limits["drift_psi"],
            "warn_ks": limits["warn_ks"],
            "drift_ks": limits["drift_ks"],
            "final": bool(final),
        }
        if self.run_log is not None:
            from apnea_uq_tpu.telemetry.runlog import replica_id

            self.run_log.event(
                "serve_drift",
                replica_id=replica_id(),
                tenant=doc["tenant"], verdict=doc["verdict"],
                windows=doc["windows"], max_psi=doc["max_psi"],
                max_ks=doc["max_ks"],
                max_mean_shift=doc["max_mean_shift"],
                worst_channel=doc["worst_channel"],
                warn_psi=doc["warn_psi"], drift_psi=doc["drift_psi"],
                warn_ks=doc["warn_ks"], drift_ks=doc["drift_ks"],
                final=doc["final"],
            )
        return doc

    def flush(self) -> Dict[str, Dict[str, Any]]:
        """Final scores for every tenant that accumulated windows since
        its last emission (shutdown path: the tail shorter than one
        cadence still lands a verdict).  Returns tenant -> verdict doc
        of the emitted scores."""
        out = {}
        for tenant in sorted(self._tenants):
            if self._tenants[tenant]["since"] > 0:
                doc = self.score_tenant(tenant, final=True)
                if doc is not None:
                    out[tenant] = doc
        return out

    def verdicts(self) -> Dict[str, Optional[str]]:
        """tenant -> latest verdict (None before the first score)."""
        return {tenant: state["verdict"]
                for tenant, state in sorted(self._tenants.items())}

    def windows_seen(self, tenant: str = DEFAULT_TENANT) -> int:
        state = self._tenants.get(tenant)
        return 0 if state is None else int(state["rolling"].seen)

    def to_json(self) -> Dict[str, Any]:
        """The monitor's complete per-tenant state as plain JSON — the
        payload that rides ``stream_state.json``'s atomic snapshot.  The
        baseline itself is NOT serialized (it is frozen in the registry;
        the restore path reloads it and hands it to
        :meth:`from_json`)."""
        return {
            "version": DRIFT_STATE_VERSION,
            "score_every": self.score_every,
            "half_life": self.half_life,
            "thresholds": dict(self.thresholds),
            "tenant_thresholds": {t: dict(v) for t, v in
                                  self.tenant_thresholds.items()},
            "tenants": {
                tenant: {
                    "rolling": state["rolling"].to_json(),
                    "since": int(state["since"]),
                    "verdict": state["verdict"],
                }
                for tenant, state in self._tenants.items()
            },
        }

    def restore(self, doc: Dict[str, Any]) -> None:
        """Adopt the per-tenant rolling state of a persisted snapshot
        while keeping THIS monitor's configuration (cadence, thresholds,
        baseline, run log) — the resume path: new flags win, the rolling
        windows survive."""
        restored = DriftMonitor.from_json(doc, baseline=self.baseline,
                                          run_log=self.run_log)
        self._tenants = restored._tenants

    @classmethod
    def from_json(cls, doc: Dict[str, Any], *, baseline: Dict[str, Any],
                  run_log=None) -> "DriftMonitor":
        version = doc.get("version")
        if version != DRIFT_STATE_VERSION:
            raise ValueError(f"drift state version {version!r} != "
                             f"{DRIFT_STATE_VERSION}")
        thresholds = doc.get("thresholds") or {}
        self = cls(
            baseline,
            score_every=int(doc.get("score_every", DEFAULT_SCORE_EVERY)),
            half_life=doc.get("half_life"),
            warn_psi=thresholds.get("warn_psi", DEFAULT_WARN_PSI),
            drift_psi=thresholds.get("drift_psi", DEFAULT_DRIFT_PSI),
            warn_ks=thresholds.get("warn_ks", DEFAULT_WARN_KS),
            drift_ks=thresholds.get("drift_ks", DEFAULT_DRIFT_KS),
            tenant_thresholds=doc.get("tenant_thresholds"),
            run_log=run_log,
        )
        for tenant, state in (doc.get("tenants") or {}).items():
            self._tenants[str(tenant)] = {
                "rolling": RollingFingerprint.from_json(state["rolling"]),
                "since": int(state.get("since", 0)),
                "verdict": state.get("verdict"),
            }
        return self
