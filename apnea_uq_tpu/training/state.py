"""Training state: {params, batch_stats, opt_state} as one pytree.

The TPU-native counterpart of a compiled Keras model + optimizer
(cnn_baseline_train.py:100-102): Adam(1e-3) via optax, explicit functional
state so the whole step jits, vmaps over an ensemble axis, and shards over
a device mesh.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

from apnea_uq_tpu.models.cnn1d import AlarconCNN1D, init_variables


class TrainState(flax.struct.PyTreeNode):
    params: Any
    batch_stats: Any
    opt_state: Any
    step: jax.Array

    def variables(self) -> dict:
        return {"params": self.params, "batch_stats": self.batch_stats}


@functools.lru_cache(maxsize=None)
def make_optimizer(learning_rate: float = 1e-3) -> optax.GradientTransformation:
    """Adam with Keras-default hyperparameters (cnn_baseline_train.py:100).

    Cached per learning rate: the returned transformation is a static jit
    argument of the epoch program, so handing out a fresh closure per call
    would force a full recompile on every ``fit`` invocation.
    """
    return optax.adam(learning_rate, b1=0.9, b2=0.999, eps=1e-7)


def create_train_state(
    model: AlarconCNN1D,
    rng: jax.Array,
    *,
    learning_rate: float = 1e-3,
    tx: Optional[optax.GradientTransformation] = None,
) -> TrainState:
    variables = init_variables(model, rng)
    tx = tx if tx is not None else make_optimizer(learning_rate)
    return TrainState(
        params=variables["params"],
        batch_stats=variables["batch_stats"],
        opt_state=tx.init(variables["params"]),
        step=jnp.zeros((), jnp.int32),
    )
