"""Single-model trainer: jitted scan epochs + Keras-parity early stopping.

Functional replacement for ``model.fit(batch_size=1024, epochs<=30,
validation_split=0.1, EarlyStopping(val_loss, patience=5,
restore_best_weights=True))`` (cnn_baseline_train.py:204-217):

- the train set lives in HBM once; each epoch is ONE jitted program — a
  ``lax.scan`` over permuted, padded, fixed-size batches (static shapes, no
  retrace), with the last partial batch masked out of the loss;
- validation is the trailing ``validation_split`` fraction of the provided
  data, evaluated in inference mode — both Keras semantics;
- early stopping is host logic between device epochs: track best val loss,
  keep the best parameters on device, restore them when patience runs out.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from apnea_uq_tpu.compilecache import store as program_store
from apnea_uq_tpu.config import TrainConfig
from apnea_uq_tpu.models.cnn1d import AlarconCNN1D, apply_model, predict_proba
from apnea_uq_tpu.ops import streaming_auc
from apnea_uq_tpu.ops.losses import masked_bce_with_logits
from apnea_uq_tpu.telemetry import memory as telemetry_memory
from apnea_uq_tpu.telemetry import trace as telemetry_trace
from apnea_uq_tpu.telemetry.steps import StepMetrics
from apnea_uq_tpu.training.state import TrainState, make_optimizer
from apnea_uq_tpu.utils import prng


@dataclasses.dataclass
class FitResult:
    state: TrainState
    history: Dict[str, List[float]]
    best_epoch: int
    stopped_early: bool


def make_train_step(model: AlarconCNN1D, tx: optax.GradientTransformation,
                    with_probs: bool = False):
    """One optimizer step on one masked batch. Pure; jit/vmap/shard-safe.

    ``with_probs=True`` additionally returns the training-mode
    probabilities of the batch (free — the loss already produced the
    logits), for streaming epoch metrics (ops/streaming_auc.py)."""

    def train_step(state: TrainState, xb, yb, mask, dropout_rng):
        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            logits, mutated = model.apply(
                variables, xb, mode="train",
                rngs={"dropout": dropout_rng}, mutable=["batch_stats"],
            )
            loss = masked_bce_with_logits(logits, yb, mask)
            return loss, (mutated["batch_stats"], logits)

        (loss, (new_stats, logits)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_state = TrainState(
            params=optax.apply_updates(state.params, updates),
            batch_stats=new_stats,
            opt_state=new_opt,
            step=state.step + 1,
        )
        if with_probs:
            return new_state, loss, predict_proba(logits)
        return new_state, loss

    return train_step


def _pad_perm(key, n: int, batch_size: int, shuffle: bool):
    """Permutation of [0,n) padded to a whole number of batches + mask.

    Padding wraps around the permutation (distinct real windows, not
    repeats of one sample) so the final batch's BatchNorm statistics stay
    representative; padded rows are still masked out of the loss.  (Keras
    instead runs a smaller final batch — impossible under static shapes.)
    """
    steps = -(-n // batch_size)
    total = steps * batch_size
    perm = jax.random.permutation(key, n) if shuffle else jnp.arange(n)
    perm = perm.astype(jnp.int32)
    idx = jnp.take(perm, jnp.arange(total) % n, axis=0).reshape(steps, batch_size)
    mask = (jnp.arange(total) < n).astype(jnp.float32).reshape(steps, batch_size)
    return idx, mask


@partial(
    jax.jit,
    static_argnames=(
        "model", "tx", "batch_size", "shuffle", "data_sharding",
        "track_metrics",
    ),
)
def _epoch_jit(model, tx, state, x, y, key, batch_size, shuffle,
               data_sharding=None, track_metrics=False):
    """One full training epoch as a scan over batches. Returns (state,
    mean_loss), plus (accuracy, auc) scalars when ``track_metrics``.

    ``data_sharding`` (a NamedSharding with spec P('data')) turns on data
    parallelism: each step's gathered batch is constrained to shard over
    the mesh's ``data`` axis, so every device computes the forward/backward
    pass on its batch slice only and XLA inserts the gradient all-reduce
    over ``data`` (params stay replicated on that axis).  The dataset
    itself stays replicated — the gather from a local replica needs no
    communication, and semantics are bit-identical to the single-device
    run (same global batches in the same order).

    ``track_metrics`` threads the fixed-size streaming-metric carry
    (ops/streaming_auc.py) through the scan — the TPU-native analogue of
    the reference's Keras compile metrics (cnn_baseline_train.py:100-102),
    computed on training-mode batch outputs like Keras, aggregated over
    the epoch instead of as a running mean.
    """
    train_step = make_train_step(model, tx, with_probs=track_metrics)
    n = x.shape[0]
    shuffle_key, dropout_key = jax.random.split(key)
    idx, mask = _pad_perm(shuffle_key, n, batch_size, shuffle)

    def body(carry, inputs):
        # named_scope labels the traced ops, so a profiler capture shows
        # "train_step/..." in the device timeline instead of fused soup.
        with jax.named_scope("train_step"):
            state, mstate = carry
            batch_idx, batch_mask, step_i = inputs
            xb = jnp.take(x, batch_idx, axis=0)
            yb = jnp.take(y, batch_idx, axis=0)
            if data_sharding is not None:
                xb = jax.lax.with_sharding_constraint(xb, data_sharding)
                yb = jax.lax.with_sharding_constraint(yb, data_sharding)
                batch_mask = jax.lax.with_sharding_constraint(batch_mask, data_sharding)
            step_rng = jax.random.fold_in(dropout_key, step_i)
            if track_metrics:
                state, loss, probs = train_step(state, xb, yb, batch_mask, step_rng)
                mstate = streaming_auc.metric_update(mstate, probs, yb, batch_mask)
            else:
                state, loss = train_step(state, xb, yb, batch_mask, step_rng)
            return (state, mstate), loss * jnp.sum(batch_mask)

    steps = idx.shape[0]
    # None (an empty pytree) when untracked: no dead carry in the scan.
    mstate0 = streaming_auc.empty_metric_state() if track_metrics else None
    (state, mstate), losses = jax.lax.scan(
        body, (state, mstate0), (idx, mask, jnp.arange(steps)),
    )
    mean_loss = jnp.sum(losses) / n
    if track_metrics:
        acc, auc = streaming_auc.metric_results(mstate)
        return state, mean_loss, acc, auc
    return state, mean_loss


@partial(jax.jit, static_argnames=("model", "batch_size", "data_sharding",
                                   "track_metrics"))
def _eval_loss_jit(model, variables, x, y, batch_size, data_sharding=None,
                   track_metrics=False):
    """Mean inference-mode BCE over a dataset (validation loss), plus
    (accuracy, auc) when ``track_metrics``."""
    n = x.shape[0]
    steps = -(-n // batch_size)
    total = steps * batch_size
    pad = total - n
    xp = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)]) if pad else x
    yp = jnp.concatenate([y, jnp.zeros((pad,), y.dtype)]) if pad else y
    mask = (jnp.arange(total) < n).astype(jnp.float32)

    def body(carry, inputs):
        with jax.named_scope("eval_loss_step"):
            total_loss, mstate = carry
            xb, yb, mb = inputs
            if data_sharding is not None:
                xb = jax.lax.with_sharding_constraint(xb, data_sharding)
                yb = jax.lax.with_sharding_constraint(yb, data_sharding)
                mb = jax.lax.with_sharding_constraint(mb, data_sharding)
            logits, _ = apply_model(model, variables, xb, mode="eval")
            loss = masked_bce_with_logits(logits, yb, mb)
            if track_metrics:
                mstate = streaming_auc.metric_update(
                    mstate, predict_proba(logits), yb, mb
                )
            return (total_loss + loss * jnp.sum(mb), mstate), None

    shape = lambda a: a.reshape((steps, batch_size) + a.shape[1:])
    mstate0 = streaming_auc.empty_metric_state() if track_metrics else None
    (total_loss, mstate), _ = jax.lax.scan(
        body, (jnp.zeros(()), mstate0),
        (shape(xp), shape(yp), shape(mask)),
    )
    if track_metrics:
        acc, auc = streaming_auc.metric_results(mstate)
        return total_loss / n, acc, auc
    return total_loss / n


@partial(jax.jit, static_argnames=("model", "batch_size", "data_sharding"))
def _predict_jit(model, variables, x, batch_size, data_sharding=None):
    n = x.shape[0]
    steps = -(-n // batch_size)
    pad = steps * batch_size - n
    xp = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)]) if pad else x

    def body(_, xb):
        with jax.named_scope("predict_eval"):
            if data_sharding is not None:
                xb = jax.lax.with_sharding_constraint(xb, data_sharding)
            logits, _ = apply_model(model, variables, xb, mode="eval")
            return None, predict_proba(logits)

    _, probs = jax.lax.scan(body, None, xp.reshape((steps, batch_size) + x.shape[1:]))
    return probs.reshape(-1)[:n]


def predict_proba_batched(model, variables, x, *, batch_size: int = 8192,
                          mesh=None, record_memory_only: bool = False):
    """Deterministic (eval-mode) probabilities, chunked over windows;
    with ``mesh``, each chunk shards over its ``data`` axis.  The program
    is acquired through the compile-cost subsystem (label
    ``predict_eval``, ``predict_eval_bf16`` under
    ``ModelConfig.compute_dtype='bfloat16'`` — the audit's blessed
    low-precision tier) when a store is active, so the eval drivers'
    sanity probe starts hot in a warmed process.
    ``record_memory_only=True`` (warm-cache) acquires/prices from an
    abstract window set and dispatches nothing."""
    label = ("predict_eval_bf16"
             if jnp.dtype(model.config.compute_dtype) == jnp.bfloat16
             else "predict_eval")
    data_sharding = None
    if mesh is not None:
        from apnea_uq_tpu.parallel import mesh as mesh_lib  # cycle-breaker
        data_sharding = mesh_lib.data_sharding(mesh)
        repl = mesh_lib.replicated(mesh)
        if record_memory_only:
            x = jax.ShapeDtypeStruct(tuple(np.shape(x)), jnp.float32,
                                     sharding=repl)
        else:
            x = jax.device_put(jnp.asarray(x, jnp.float32), repl)
        variables = jax.tree.map(lambda a: jax.device_put(a, repl), variables)
    elif record_memory_only:
        x = jax.ShapeDtypeStruct(tuple(np.shape(x)), jnp.float32)
    else:
        x = jnp.asarray(x, jnp.float32)
    args = (model, variables, x, batch_size, data_sharding)
    program = program_store.get_program(label, _predict_jit, *args)
    if record_memory_only:
        return None
    return program(*args) if program is not None else _predict_jit(*args)


@partial(jax.jit, static_argnames=("model", "tx", "data_sharding",
                                   "track_metrics"))
def _stream_step_jit(model, tx, state, xb, yb, mask, step_rng,
                     data_sharding=None, metric_state=None,
                     track_metrics=False):
    """One streamed optimizer step; returns (state, loss * batch weight) —
    the same per-step quantity the scan epoch accumulates — plus the
    updated metric carry when ``track_metrics``.  NOT donated:
    fit's early-stopping snapshot aliases the state buffers, and donation
    would invalidate the saved best weights on TPU (CPU ignores donation,
    so tests alone would not catch it)."""
    if data_sharding is not None:
        xb = jax.lax.with_sharding_constraint(xb, data_sharding)
        yb = jax.lax.with_sharding_constraint(yb, data_sharding)
        mask = jax.lax.with_sharding_constraint(mask, data_sharding)
    step = make_train_step(model, tx, with_probs=track_metrics)
    if track_metrics:
        state, loss, probs = step(state, xb, yb, mask, step_rng)
        metric_state = streaming_auc.metric_update(metric_state, probs, yb, mask)
        return state, loss * jnp.sum(mask), metric_state
    state, loss = step(state, xb, yb, mask, step_rng)
    return state, loss * jnp.sum(mask)


@partial(jax.jit, static_argnames=("model", "data_sharding", "track_metrics"))
def _stream_eval_batch_jit(model, variables, xb, yb, mask, data_sharding=None,
                           metric_state=None, track_metrics=False):
    if data_sharding is not None:
        xb = jax.lax.with_sharding_constraint(xb, data_sharding)
        yb = jax.lax.with_sharding_constraint(yb, data_sharding)
        mask = jax.lax.with_sharding_constraint(mask, data_sharding)
    logits, _ = apply_model(model, variables, xb, mode="eval")
    weighted = masked_bce_with_logits(logits, yb, mask) * jnp.sum(mask)
    if track_metrics:
        metric_state = streaming_auc.metric_update(
            metric_state, predict_proba(logits), yb, mask
        )
        return weighted, metric_state
    return weighted


def _stream_epoch(model, tx, state, x, y, key, batch_size, shuffle,
                  data_sharding, sharding, prefetch, track_metrics=False):
    """One training epoch fed batch-by-batch from HOST arrays through the
    double-buffered prefetch pipeline (data/feed.py) — the dataset never
    resides in HBM whole.  Identical math to _epoch_jit: same permutation
    (same shuffle key), same wrap-padded batches and masks, same per-step
    dropout streams, same sequential loss accumulation (and the same
    streaming-metric carry when ``track_metrics``)."""
    from apnea_uq_tpu.data.feed import prefetch_to_device

    n = x.shape[0]
    shuffle_key, dropout_key = jax.random.split(key)
    # apnea-lint: disable=host-sync-in-timed-region -- the permutation must land on host to slice the host-resident dataset; it runs once, before the first batch dispatches, so no in-flight device work is serialized
    idx, mask = (np.asarray(a) for a in _pad_perm(shuffle_key, n, batch_size, shuffle))

    def batches():
        for i in range(idx.shape[0]):
            rows = idx[i]
            yield x[rows], y[rows], mask[i]

    total = jnp.zeros(())
    mstate = streaming_auc.empty_metric_state() if track_metrics else None
    for i, (xb, yb, mb) in enumerate(prefetch_to_device(
        batches(), size=prefetch, sharding=sharding
    )):
        if track_metrics:
            state, weighted, mstate = _stream_step_jit(
                model, tx, state, xb, yb, mb,
                jax.random.fold_in(dropout_key, i), data_sharding,
                mstate, track_metrics=True,
            )
        else:
            state, weighted = _stream_step_jit(
                model, tx, state, xb, yb, mb,
                jax.random.fold_in(dropout_key, i), data_sharding,
            )
        total = total + weighted
    if track_metrics:
        acc, auc = streaming_auc.metric_results(mstate)
        return state, total / n, acc, auc
    return state, total / n


def _stream_eval_loss(model, variables, x, y, batch_size, data_sharding,
                      sharding, prefetch, track_metrics=False):
    """Streaming counterpart of _eval_loss_jit (same zero-pad + mask)."""
    from apnea_uq_tpu.data.feed import prefetch_to_device

    n = x.shape[0]
    steps = -(-n // batch_size)

    def batches():
        for i in range(steps):
            lo, hi = i * batch_size, min((i + 1) * batch_size, n)
            # Materializes ONE batch off a lazy store-backed slice (free
            # view for plain ndarrays) so device_put sees a concrete
            # array.
            # apnea-lint: disable=host-sync-in-timed-region -- x/y are HOST-resident (ndarray or memmap-backed store slice), not device arrays; this is the O(batch) gather that keeps the streamed path bounded, and it serializes nothing in flight
            xb, yb = np.asarray(x[lo:hi]), np.asarray(y[lo:hi])
            pad = batch_size - (hi - lo)
            if pad:
                xb = np.concatenate([xb, np.zeros((pad,) + xb.shape[1:], xb.dtype)])
                yb = np.concatenate([yb, np.zeros((pad,), yb.dtype)])
            mb = (np.arange(batch_size) < hi - lo).astype(np.float32)
            yield xb, yb, mb

    total = jnp.zeros(())
    mstate = streaming_auc.empty_metric_state() if track_metrics else None
    for xb, yb, mb in prefetch_to_device(batches(), size=prefetch,
                                         sharding=sharding):
        if track_metrics:
            weighted, mstate = _stream_eval_batch_jit(
                model, variables, xb, yb, mb, data_sharding,
                mstate, track_metrics=True,
            )
        else:
            weighted = _stream_eval_batch_jit(
                model, variables, xb, yb, mb, data_sharding
            )
        total = total + weighted
    if track_metrics:
        acc, auc = streaming_auc.metric_results(mstate)
        return total / n, acc, auc
    return total / n


def fit(
    model: AlarconCNN1D,
    state: TrainState,
    x_train,
    y_train,
    config: TrainConfig = TrainConfig(),
    *,
    tx: Optional[optax.GradientTransformation] = None,
    rng: Optional[jax.Array] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    streaming: Optional[bool] = None,
    prefetch: int = 2,
    log_fn: Optional[Callable[[str], None]] = None,
    run_log=None,
    profiler=None,
    compile_only: bool = False,
) -> FitResult:
    """Train with validation-split early stopping; returns best-weight state.

    ``compile_only=True`` (the ``apnea-uq warm-cache`` stage) runs the
    full setup and acquires/prices the epoch + validation programs via
    the compile-cost subsystem — exactly the programs a real fit at this
    config would dispatch, so the store/persistent-cache entries it
    leaves behind are guaranteed hits — then returns None without
    training an epoch.

    Pass ``mesh`` to data-parallelize the baseline trainer: every batch is
    sharded over the mesh's ``data_axis`` and XLA all-reduces the gradients
    over it (the reference's single-device ``model.fit``,
    cnn_baseline_train.py:210, has no equivalent).  Results are identical
    to the single-device run — same batches, same order, just computed in
    slices.

    ``run_log`` (a :class:`apnea_uq_tpu.telemetry.RunLog`) records one
    ``step`` event per dispatched epoch/validation program — dispatch vs
    ``block_until_ready``-bounded device time, windows/sec throughput,
    and XLA retrace/compile deltas — plus one structured ``epoch`` event
    per epoch with the loss trajectory.  With a run log on the in-HBM
    path, the epoch/validation programs' compiled memory analysis is also
    recorded once (``memory_profile`` events, telemetry/memory.py) — the
    HBM cost of the fit, attributed before the first step runs.

    ``profiler`` (a :class:`apnea_uq_tpu.telemetry.profiler.TraceSession`)
    is stepped once per epoch, bounding a ``--profile`` capture to the
    session's warmup/step budget.
    """
    tx = tx if tx is not None else make_optimizer(config.learning_rate)
    if rng is None:
        rng = prng.stream(prng.seed_key(config.seed), prng.STREAM_SHUFFLE)
    if streaming is None:
        streaming = config.streaming
    data_sharding = None
    if mesh is not None:
        # Import at call time: parallel.ensemble imports this module, so a
        # top-level import of the parallel package would be circular.
        from apnea_uq_tpu.parallel import mesh as mesh_lib
        data_sharding = mesh_lib.data_sharding(mesh)
        replicated = mesh_lib.replicated(mesh)
        state = jax.tree.map(lambda a: jax.device_put(a, replicated), state)

    if streaming:
        # The dataset stays in HOST memory; batches flow through the
        # double-buffered prefetch feed (data/feed.py).  Same math as the
        # in-HBM path — same permutation, batches, masks, RNG streams.
        # as_host_source passes a memmap-backed store array
        # (data/store.py ShardedArray / np.memmap) through WITHOUT
        # materializing it: each step then gathers only its batch rows,
        # so host RSS stays O(prefetch x batch) over an out-of-core set.
        from apnea_uq_tpu.data.store import as_host_source

        x = as_host_source(x_train)
        y = np.asarray(y_train, np.float32)
    else:
        x = jnp.asarray(x_train, jnp.float32)
        y = jnp.asarray(y_train, jnp.float32)
        if mesh is not None:
            # The dataset is replicated onto the mesh (it fits HBM at SHHS2
            # scale; streaming covers the case where it doesn't), so the
            # per-batch gather needs no communication.
            x, y = jax.device_put(x, replicated), jax.device_put(y, replicated)
    n = x.shape[0]
    # Keras split arithmetic: train gets int(n*(1-split)), val the remainder.
    n_val = n - int(n * (1.0 - config.validation_split))
    # Keras validation_split takes the TAIL of the data, pre-shuffle.
    if n_val > 0:
        x, x_val = x[: n - n_val], x[n - n_val :]
        y, y_val = y[: n - n_val], y[n - n_val :]
    else:
        x_val = y_val = None

    track = config.track_metrics
    history: Dict[str, List[float]] = {"loss": [], "val_loss": []}
    if track:
        history.update({"accuracy": [], "auc": [],
                        "val_accuracy": [], "val_auc": []})
    best_val = np.inf
    best_epoch = -1
    best_params = state.params
    best_stats = state.batch_stats
    patience_left = config.early_stopping_patience
    stopped_early = False

    batch_sharding = None
    if streaming and mesh is not None and config.batch_size % mesh.shape["data"] == 0:
        batch_sharding = data_sharding  # place streamed batches pre-sharded

    step_metrics = StepMetrics(run_log) if run_log is not None else None
    train_program = val_program = None

    for epoch in range(config.num_epochs):
        epoch_key = jax.random.fold_in(rng, epoch)

        if not streaming and epoch == 0:
            # Acquire the exact programs this fit dispatches through the
            # compile-cost subsystem (one lowering shared between the
            # HBM pricing below and every epoch's execution; None when
            # no store is active) and price them once per signature.
            train_args = (model, tx, state, x, y, epoch_key,
                          config.batch_size, config.shuffle, data_sharding,
                          track)
            # exportable=False: the epoch's output carries TrainState /
            # optax pytree nodes jax.export cannot serialize, so the
            # program is AOT-shared in-process (pricing + every epoch's
            # dispatch from ONE lowering) and its backend compile lands
            # in the persistent XLA cache for the next process — the
            # same treatment as the donating ensemble epoch.
            train_program = program_store.get_program(
                "train_epoch", _epoch_jit, *train_args,
                exportable=False, run_log=run_log)
            if run_log is not None:
                telemetry_memory.record_jit_memory(
                    run_log, "train_epoch", _epoch_jit, *train_args,
                    program=train_program,
                )
            if x_val is not None:
                val_args = (model, state.variables(), x_val, y_val,
                            config.batch_size, data_sharding, track)
                val_program = program_store.get_program(
                    "val_loss", _eval_loss_jit, *val_args, run_log=run_log)
                if run_log is not None:
                    telemetry_memory.record_jit_memory(
                        run_log, "val_loss", _eval_loss_jit, *val_args,
                        program=val_program,
                    )
        if compile_only:
            # warm-cache: the programs above are built, priced, and (for
            # the exportable ones) persisted; nothing dispatches.
            return None

        def run_epoch():
            if streaming:
                return _stream_epoch(
                    model, tx, state, x, y, epoch_key, config.batch_size,
                    config.shuffle, data_sharding, batch_sharding, prefetch,
                    track_metrics=track,
                )
            if train_program is not None:
                return train_program(
                    model, tx, state, x, y, epoch_key, config.batch_size,
                    config.shuffle, data_sharding, track,
                )
            return _epoch_jit(
                model, tx, state, x, y, epoch_key, config.batch_size,
                config.shuffle, data_sharding, track_metrics=track,
            )

        with telemetry_trace.annotate(f"fit/epoch{epoch + 1}"):
            if step_metrics is not None:
                out = step_metrics.measure(
                    "train_epoch", run_epoch, n_items=int(x.shape[0]),
                    extra={"epoch": epoch + 1},
                )
            else:
                out = run_epoch()
        epoch_record = step_metrics.last if step_metrics is not None else None
        if track:
            state, train_loss, train_acc, train_auc = out
            history["accuracy"].append(float(train_acc))
            history["auc"].append(float(train_auc))
        else:
            state, train_loss = out
        history["loss"].append(float(train_loss))

        def emit_epoch_event(val_loss=None):
            if run_log is None:
                return
            fields = {"epoch": epoch + 1, "loss": float(train_loss)}
            if val_loss is not None:
                fields["val_loss"] = float(val_loss)
            if track:
                fields["accuracy"] = history["accuracy"][-1]
                fields["auc"] = history["auc"][-1]
            if epoch_record is not None:
                fields["device_s"] = round(epoch_record.device_s, 6)
                fields["dispatch_s"] = round(epoch_record.dispatch_s, 6)
                if epoch_record.items_per_s is not None:
                    fields["windows_per_s"] = round(
                        epoch_record.items_per_s, 3
                    )
                fields["retraces"] = epoch_record.retraces
                fields["backend_compiles"] = epoch_record.backend_compiles
            run_log.event("epoch", **fields)

        metric_note = (
            f" acc={history['accuracy'][-1]:.4f} auc={history['auc'][-1]:.4f}"
            if track else ""
        )

        if x_val is not None:
            def run_val():
                if streaming:
                    return _stream_eval_loss(
                        model, state.variables(), x_val, y_val,
                        config.batch_size, data_sharding, batch_sharding,
                        prefetch, track_metrics=track,
                    )
                if val_program is not None:
                    return val_program(
                        model, state.variables(), x_val, y_val,
                        config.batch_size, data_sharding, track,
                    )
                return _eval_loss_jit(
                    model, state.variables(), x_val, y_val,
                    config.batch_size, data_sharding, track_metrics=track,
                )

            with telemetry_trace.annotate(f"fit/val{epoch + 1}"):
                if step_metrics is not None:
                    val_out = step_metrics.measure(
                        "val_loss", run_val, n_items=int(x_val.shape[0]),
                        extra={"epoch": epoch + 1},
                    )
                else:
                    val_out = run_val()
            if track:
                val_loss, val_acc, val_auc = val_out
                val_loss = float(val_loss)
                history["val_accuracy"].append(float(val_acc))
                history["val_auc"].append(float(val_auc))
                metric_note += (f" val_acc={float(val_acc):.4f} "
                                f"val_auc={float(val_auc):.4f}")
            else:
                val_loss = float(val_out)
            history["val_loss"].append(val_loss)
            emit_epoch_event(val_loss)
            if log_fn:
                log_fn(f"epoch {epoch + 1}/{config.num_epochs} "
                       f"loss={float(train_loss):.4f} val_loss={val_loss:.4f}"
                       f"{metric_note}")
            if val_loss < best_val:
                best_val = val_loss
                best_epoch = epoch
                best_params = state.params
                best_stats = state.batch_stats
                patience_left = config.early_stopping_patience
            else:
                patience_left -= 1
                if patience_left <= 0:
                    stopped_early = True
        else:
            emit_epoch_event()
            if log_fn:
                log_fn(f"epoch {epoch + 1}/{config.num_epochs} "
                       f"loss={float(train_loss):.4f}{metric_note}")
            best_epoch = epoch

        # Step the profiler BEFORE the early-stop break (fit_ensemble
        # does the same): the stopping epoch ran and was captured, so it
        # must count toward steps_profiled.
        if profiler is not None:
            profiler.step()
        if stopped_early:
            break

    if x_val is not None and config.restore_best_weights and best_epoch >= 0:
        state = state.replace(params=best_params, batch_stats=best_stats)

    return FitResult(
        state=state, history=history, best_epoch=best_epoch, stopped_early=stopped_early
    )
