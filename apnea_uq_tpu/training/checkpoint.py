"""Orbax checkpointing of training state, with per-member ensemble resume.

TPU-native replacement for the reference's whole-model Keras ``.keras``
save/load (cnn_baseline_train.py:230, train_deep_ensemble_cnns.py:170,
analyze_mcd_patient_level.py:199): here a checkpoint is the
``{params, batch_stats, opt_state, step}`` pytree written by orbax, so a
restore is bit-exact functional state — no architecture pickling, no
optimizer-state loss.

Ensemble layout mirrors the reference's resumability contract
(train_deep_ensemble_cnns.py:127,130-132): one checkpoint per member,
keyed by the member's seed, and ``member_exists`` gives the
skip-if-checkpoint-exists resume the reference implements by testing the
``.keras`` path.  Unlike the reference — whose *writers* name members
``seed{21+i}`` while its *readers* expect ``seed{i+5}`` or ``seed{i}``
(SURVEY §1 contract drift) — the naming here is a single function both
directions share.
"""

from __future__ import annotations

import os
from typing import List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from apnea_uq_tpu.training.state import TrainState

_MEMBER_PREFIX = "member_seed"


def _abspath(path: str) -> str:
    # orbax requires absolute paths.
    return os.path.abspath(os.path.expanduser(path))


def save_state(path: str, state: TrainState) -> str:
    """Write one TrainState checkpoint to ``path`` (a directory)."""
    path = _abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, state, force=True)
    return path


def restore_state(path: str, template: TrainState) -> TrainState:
    """Restore a TrainState saved by :func:`save_state`.

    ``template`` supplies the pytree structure and shapes/dtypes (build it
    with ``create_train_state`` for the same model/optimizer config); its
    array values are not read.
    """
    path = _abspath(path)
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, abstract)


class EnsembleCheckpointStore:
    """Directory of per-member checkpoints keyed by member seed.

    The seed key (``member_seed{s}``) rather than a positional index makes
    resume robust to changing ``num_members`` between runs: growing an
    ensemble N=5 -> N=10 re-trains only the five new seeds, exactly the
    property the reference's skip-if-exists loop has
    (train_deep_ensemble_cnns.py:125-132) but keyed consistently.
    """

    def __init__(self, root: str):
        self.root = _abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def member_path(self, seed: int) -> str:
        return os.path.join(self.root, f"{_MEMBER_PREFIX}{seed}")

    def member_exists(self, seed: int) -> bool:
        """True iff member ``seed`` has a complete (committed) checkpoint."""
        path = self.member_path(seed)
        # Orbax writes into a tmp dir and renames on commit, so a bare
        # directory test is already atomic; reject uncommitted leftovers.
        return os.path.isdir(path) and not ocp.utils.is_tmp_checkpoint(path)

    def existing_seeds(self) -> List[int]:
        seeds = []
        for name in os.listdir(self.root):
            if name.startswith(_MEMBER_PREFIX):
                try:
                    seed = int(name[len(_MEMBER_PREFIX):])
                except ValueError:
                    continue
                if self.member_exists(seed):
                    seeds.append(seed)
        return sorted(seeds)

    def save_member(self, seed: int, state: TrainState) -> str:
        return save_state(self.member_path(seed), state)

    def restore_member(self, seed: int, template: TrainState) -> TrainState:
        return restore_state(self.member_path(seed), template)

    def restore_members(
        self, seeds, template: TrainState
    ) -> List[TrainState]:
        return [self.restore_member(s, template) for s in seeds]


def member_state(stacked: TrainState, i: int) -> TrainState:
    """Member ``i`` of a member-stacked TrainState (see init_ensemble_state)."""
    return jax.tree.map(lambda a: a[i], stacked)


def save_ensemble(
    store: EnsembleCheckpointStore,
    stacked: TrainState,
    seeds,
    *,
    skip_existing: bool = False,
) -> List[str]:
    """Checkpoint each member of a stacked ensemble state under its seed."""
    paths = []
    for i, seed in enumerate(seeds):
        if skip_existing and store.member_exists(seed):
            paths.append(store.member_path(seed))
            continue
        paths.append(store.save_member(seed, member_state(stacked, i)))
    return paths


def result_member_seeds(result, seed_base: int) -> List[int]:
    """The checkpoint seeds of every member a ``fit_ensemble`` result
    returned: ``seed_base + global_member_index``, the same arithmetic the
    reference's seed-per-member scheme uses (train_deep_ensemble_cnns.py:
    126).  Derived from ``result.member_ids`` rather than a 0..N-1 range
    so promoted padded slots (``EnsembleConfig.keep_padded_members``) and
    resumed partial runs both land under the seed a fresh full run of
    that size would have used — growing N later re-trains nothing."""
    if result.member_ids is None:  # legacy result: positional members
        return [seed_base + i for i in range(result.num_members)]
    return [seed_base + int(g) for g in result.member_ids]


def save_ensemble_result(
    store: EnsembleCheckpointStore,
    result,
    *,
    seed_base: int,
    skip_existing: bool = False,
) -> List[str]:
    """Checkpoint every member of an :class:`EnsembleFitResult` — the
    requested members AND any promoted padded slots — under its
    global-index seed."""
    return save_ensemble(
        store, result.state, result_member_seeds(result, seed_base),
        skip_existing=skip_existing,
    )
