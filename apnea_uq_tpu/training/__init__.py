from apnea_uq_tpu.training.checkpoint import (
    EnsembleCheckpointStore,
    member_state,
    restore_state,
    result_member_seeds,
    save_ensemble,
    save_ensemble_result,
    save_state,
)
from apnea_uq_tpu.training.state import TrainState, create_train_state
from apnea_uq_tpu.training.trainer import FitResult, fit, predict_proba_batched

__all__ = [
    "TrainState",
    "create_train_state",
    "fit",
    "FitResult",
    "predict_proba_batched",
    "EnsembleCheckpointStore",
    "save_state",
    "restore_state",
    "member_state",
    "save_ensemble",
    "save_ensemble_result",
    "result_member_seeds",
]
