"""In-tree EDF (European Data Format) reader.

The reference reads SHHS2 EDF files through pyedflib, a C-extension
wrapper over EDFlib (preprocess_shhs_raw.py:3,128-155).  pyedflib is not
available in this environment, so the framework carries its own reader:
EDF is a simple fixed-layout binary format (256-byte global header,
256 bytes per signal of metadata, then interleaved int16 data records),
which decodes to float arrays with one vectorized NumPy pass per signal.
A native C++ fast path (apnea_uq_tpu.data._native) fuses record
de-interleaving and physical scaling for large files; the NumPy path is
the always-available fallback and the reference implementation for tests.

Only the features SHHS2 ingestion needs are implemented: signal labels,
per-signal sampling rates, and physically-scaled sample decode.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_GLOBAL_HEADER_BYTES = 256
_PER_SIGNAL_HEADER_BYTES = 256


@dataclass(frozen=True)
class EdfSignal:
    """One decoded EDF signal in physical units."""

    label: str
    sampling_rate: float
    samples: np.ndarray  # float32 (n,) physical values


@dataclass(frozen=True)
class _EdfLayout:
    """Parsed header fields needed to locate and scale the data records."""

    labels: List[str]
    n_records: int
    record_duration_s: float
    samples_per_record: np.ndarray  # int (n_signals,)
    physical_min: np.ndarray
    physical_max: np.ndarray
    digital_min: np.ndarray
    digital_max: np.ndarray
    header_bytes: int


def _ascii_field(raw: bytes) -> str:
    return raw.decode("ascii", errors="replace").strip()


def _parse_layout(f) -> _EdfLayout:
    head = f.read(_GLOBAL_HEADER_BYTES)
    if len(head) < _GLOBAL_HEADER_BYTES:
        raise ValueError("truncated EDF global header")
    header_bytes = int(_ascii_field(head[184:192]))
    n_records = int(_ascii_field(head[236:244]))
    record_duration_s = float(_ascii_field(head[244:252]))
    n_signals = int(_ascii_field(head[252:256]))
    if n_signals <= 0:
        raise ValueError(f"EDF header declares {n_signals} signals")

    sig_head = f.read(_PER_SIGNAL_HEADER_BYTES * n_signals)
    if len(sig_head) < _PER_SIGNAL_HEADER_BYTES * n_signals:
        raise ValueError("truncated EDF signal headers")

    def field(offset: int, width: int) -> List[str]:
        base = offset * n_signals
        return [
            _ascii_field(sig_head[base + i * width : base + (i + 1) * width])
            for i in range(n_signals)
        ]

    # Per-signal header layout: label(16) transducer(80) dimension(8)
    # physical min(8) physical max(8) digital min(8) digital max(8)
    # prefiltering(80) samples-per-record(8) reserved(32).
    labels = field(0, 16)
    physical_min = np.array([float(v) for v in field(104, 8)])
    physical_max = np.array([float(v) for v in field(112, 8)])
    digital_min = np.array([float(v) for v in field(120, 8)])
    digital_max = np.array([float(v) for v in field(128, 8)])
    samples_per_record = np.array([int(v) for v in field(216, 8)])
    return _EdfLayout(
        labels=labels,
        n_records=n_records,
        record_duration_s=record_duration_s,
        samples_per_record=samples_per_record,
        physical_min=physical_min,
        physical_max=physical_max,
        digital_min=digital_min,
        digital_max=digital_max,
        header_bytes=header_bytes,
    )


def _scale_params(layout: _EdfLayout, idx: int) -> Tuple[float, float]:
    """(gain, offset) mapping digital int16 to physical units."""
    dig_range = layout.digital_max[idx] - layout.digital_min[idx]
    if dig_range == 0:
        return 1.0, 0.0
    gain = (layout.physical_max[idx] - layout.physical_min[idx]) / dig_range
    offset = layout.physical_min[idx] - gain * layout.digital_min[idx]
    return float(gain), float(offset)


def read_edf_labels(path: str) -> List[str]:
    """Signal labels in file order, without decoding any data."""
    with open(path, "rb") as f:
        return _parse_layout(f).labels


def read_edf(
    path: str,
    channels: Optional[Sequence[str]] = None,
    *,
    use_native: bool = True,
) -> Dict[str, EdfSignal]:
    """Decode ``channels`` (default: all) from an EDF file.

    Returns ``{label: EdfSignal}`` with samples in physical units as
    float32 — the equivalent of pyedflib's ``readSignal`` +
    ``getSampleFrequency`` as used at preprocess_shhs_raw.py:129-137.
    Unknown requested channels are simply absent from the result (the
    ingestion layer handles alternative names and missing-channel
    policy).
    """
    with open(path, "rb") as f:
        layout = _parse_layout(f)
        record_words = int(layout.samples_per_record.sum())
        data = np.fromfile(f, dtype="<i2")

    n_records = layout.n_records
    if n_records < 0:  # -1 means "unknown"; infer from file size
        n_records = data.size // record_words if record_words else 0
    data = data[: n_records * record_words]
    if data.size < n_records * record_words:
        n_records = data.size // record_words
        data = data[: n_records * record_words]

    wanted = layout.labels if channels is None else list(channels)
    label_to_idx = {lbl: i for i, lbl in enumerate(layout.labels)}
    offsets = np.concatenate([[0], np.cumsum(layout.samples_per_record)])
    records = data.reshape(n_records, record_words) if record_words else data.reshape(0, 0)

    native = _native_decoder() if use_native else None
    out: Dict[str, EdfSignal] = {}
    for label in wanted:
        idx = label_to_idx.get(label)
        if idx is None:
            continue
        spr = int(layout.samples_per_record[idx])
        gain, offset = _scale_params(layout, idx)
        if native is not None:
            samples = native.decode_signal(
                data, n_records, record_words, int(offsets[idx]), spr, gain, offset
            )
        else:
            raw = records[:, offsets[idx] : offsets[idx] + spr]
            samples = (raw.astype(np.float32) * np.float32(gain)) + np.float32(offset)
            samples = samples.reshape(-1)
        rate = spr / layout.record_duration_s if layout.record_duration_s else float(spr)
        out[label] = EdfSignal(label=label, sampling_rate=rate, samples=samples)
    return out


def _native_decoder():
    """The C++ decode module, or None when the shared library is absent."""
    if os.environ.get("APNEA_UQ_NO_NATIVE"):
        return None
    try:
        from apnea_uq_tpu.data import _native
    except Exception:
        return None
    return _native if _native.available() else None


def write_edf(
    path: str,
    signals: Sequence[EdfSignal],
    *,
    record_duration_s: float = 1.0,
) -> None:
    """Write a minimal valid EDF file (test fixtures and round-trips).

    Samples are quantized to the int16 digital range with per-signal
    physical bounds taken from the data.
    """
    n_signals = len(signals)
    spr = []
    for s in signals:
        per_record = s.sampling_rate * record_duration_s
        if abs(per_record - round(per_record)) > 1e-9:
            raise ValueError(
                f"signal {s.label!r}: rate {s.sampling_rate} Hz does not give an "
                f"integer sample count per {record_duration_s}s record"
            )
        spr.append(int(round(per_record)))
    n_records_each = [
        len(s.samples) // n for s, n in zip(signals, spr)
    ]
    n_records = min(n_records_each) if signals else 0

    def num8(v: float) -> str:
        # Highest precision that fits the 8-char EDF numeric field.
        for p in range(8, 0, -1):
            s = f"{v:.{p}g}"
            if len(s) <= 8:
                return s
        raise ValueError(f"cannot format {v} into 8 ASCII chars")

    dig_min, dig_max = -32768, 32767
    phys_min, phys_max, quantized = [], [], []
    for s, n in zip(signals, spr):
        x = np.asarray(s.samples[: n_records * n], dtype=np.float64)
        lo = float(x.min()) if x.size else 0.0
        hi = float(x.max()) if x.size else 1.0
        if hi == lo:
            hi = lo + 1.0
        # Quantize against the header-rounded bounds so the read-back
        # scaling (which only sees the 8-char header fields) is exact.
        lo = float(num8(lo))
        hi = float(num8(hi))
        if hi <= lo:
            hi = lo + 1.0
        gain = (hi - lo) / (dig_max - dig_min)
        q = np.clip(np.round((x - lo) / gain + dig_min), dig_min, dig_max).astype("<i2")
        phys_min.append(lo)
        phys_max.append(hi)
        quantized.append(q.reshape(n_records, n))

    def pad(text: str, width: int) -> bytes:
        b = text.encode("ascii")
        if len(b) > width:
            raise ValueError(f"header field {text!r} exceeds {width} bytes")
        return b.ljust(width)

    header_bytes = _GLOBAL_HEADER_BYTES + _PER_SIGNAL_HEADER_BYTES * n_signals
    with open(path, "wb") as f:
        f.write(pad("0", 8))
        f.write(pad("X X X X", 80))
        f.write(pad("Startdate 01-JAN-2000 X X X", 80))
        f.write(pad("01.01.00", 8))
        f.write(pad("00.00.00", 8))
        f.write(pad(str(header_bytes), 8))
        f.write(pad("", 44))
        f.write(pad(str(n_records), 8))
        f.write(pad(f"{record_duration_s:g}", 8))
        f.write(pad(str(n_signals), 4))

        for s in signals:
            f.write(pad(s.label, 16))
        for _ in signals:
            f.write(pad("", 80))
        for _ in signals:
            f.write(pad("", 8))
        for v in phys_min:
            f.write(pad(num8(v), 8))
        for v in phys_max:
            f.write(pad(num8(v), 8))
        for _ in signals:
            f.write(pad(str(dig_min), 8))
        for _ in signals:
            f.write(pad(str(dig_max), 8))
        for _ in signals:
            f.write(pad("", 80))
        for n in spr:
            f.write(pad(str(n), 8))
        for _ in signals:
            f.write(pad("", 32))

        for r in range(n_records):
            for q in quantized:
                f.write(q[r].tobytes())
