"""Versioned on-disk artifact registry.

The reference pipeline hands artifacts between stages by ad-hoc file
names, and the names drifted apart between producers and consumers
(SURVEY §1 "contract drift": SHHS2_ID_all_60.csv vs SHHS2_ID_all.csv,
X_train_win_std_smote vs X_train_std_smote, seed{21+i} vs seed{i+5}, two
different default output dirs).  Here every artifact has one canonical
key, and a JSON manifest records shape, dtype, and the producing config
so a run is auditable and resumable.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional, Sequence

import numpy as np

from apnea_uq_tpu.config import _to_jsonable
from apnea_uq_tpu.data import store as store_mod

MANIFEST_NAME = "manifest.json"

# Canonical artifact keys (SURVEY §1 boundary table, without the drift).
WINDOWS = "windows"                            # L1 -> L2: ingested window set (.npz)
TRAIN_STD_SMOTE = "train_std_smote"            # L2 -> L3: balanced training set
TEST_STD_UNBALANCED = "test_std_unbalanced"    # L2 -> L3/L5: full test set
TEST_STD_RUS = "test_std_rus"                  # L2 -> L3/L5: RUS-balanced test set
RAW_PREDICTIONS = "raw_predictions"            # L5 side: (K, M) probability stack (full-probs evals)
UQ_STATS = "uq_stats"                          # L5 side: (4, M) sufficient statistics (fused evals)
DETAILED_WINDOWS = "detailed_windows"          # L5 -> L6: per-window CSV
METRICS = "metrics"                            # L5 side: aggregates/CIs/classification JSON
PATIENT_SUMMARY = "patient_summary"            # L6 -> L7: per-patient CSV
CHECKPOINT = "checkpoint"                      # L3 -> L5: model checkpoints (dir)
SWEEP = "sweep"                                # L7 side: T/N convergence table
QUALITY_BASELINE = "quality_baseline"          # L2 -> L5: frozen per-channel data fingerprint (drift scoring)
AUTOTUNE_CONFIG = "autotune_config"            # L5 side: measured kernel tile-geometry winners (ops/autotune.py)
FLEET_ROLLUP = "fleet_rollup"                  # serve side: cross-replica SLO rollup (telemetry/fleet.py)
TRACE_REPORT = "trace_report"                  # serve side: cross-replica trace/critical-path report (telemetry/spans.py)

#: Every canonical artifact key, in pipeline order.  The flow gate
#: (`apnea-uq flow`, apnea_uq_tpu/flow/) keys its producer->consumer
#: dataflow graph and the checked-in flow/manifest.json off this tuple,
#: so a key added above without a row here fails statically.
CANONICAL_KEYS = (
    WINDOWS, TRAIN_STD_SMOTE, TEST_STD_UNBALANCED, TEST_STD_RUS,
    QUALITY_BASELINE, RAW_PREDICTIONS, UQ_STATS, DETAILED_WINDOWS,
    METRICS, PATIENT_SUMMARY, CHECKPOINT, SWEEP, AUTOTUNE_CONFIG,
    FLEET_ROLLUP, TRACE_REPORT,
)


class ArtifactRegistry:
    """One root directory holding every pipeline artifact plus a manifest.

    Array artifacts are ``.npz`` bundles (arrays keyed by name); tabular
    artifacts are CSV.  Keys may carry a tag suffix for per-method
    variants, e.g. ``detailed_windows:CNN_MCD_Unbalanced``.
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    # -- manifest ---------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST_NAME)

    def manifest(self) -> Dict[str, Any]:
        path = self._manifest_path()
        if not os.path.exists(path):
            return {"version": 1, "artifacts": {}}
        with open(path) as f:
            return json.load(f)

    def _save_manifest(self, manifest: Dict[str, Any]) -> None:
        """The registry's commit point: every artifact becomes visible to
        readers only through this write, so it routes through the shared
        tmp -> fsync -> replace writer (utils/io.py) — the bare
        tmp+rename it used before PR 10 left a power-loss window where
        the rename could land before the data blocks."""
        store_mod.atomic_write_json(self._manifest_path(), manifest)

    def _record(self, key: str, entry: Dict[str, Any]) -> None:
        manifest = self.manifest()
        manifest["artifacts"][key] = entry
        self._save_manifest(manifest)

    def describe(self, key: str) -> Optional[Dict[str, Any]]:
        return self.manifest()["artifacts"].get(key)

    def exists(self, key: str) -> bool:
        entry = self.describe(key)
        return entry is not None and os.path.exists(
            os.path.join(self.root, entry["file"])
        )

    def available(self, prefix: str = "") -> list:
        """Keys starting with ``prefix`` whose artifact files exist on
        disk — the same on-disk requirement as :meth:`exists`, with ONE
        manifest read for the whole listing."""
        return sorted(
            key
            for key, entry in self.manifest()["artifacts"].items()
            if key.startswith(prefix)
            and os.path.exists(os.path.join(self.root, entry["file"]))
        )

    # -- arrays -----------------------------------------------------------

    def path_for(self, key: str, suffix: str) -> str:
        return os.path.join(self.root, key.replace(":", "__") + suffix)

    def save_arrays(
        self,
        key: str,
        arrays: Dict[str, np.ndarray],
        *,
        config: Any = None,
    ) -> str:
        path = self.path_for(key, ".npz")
        # Same-key re-saves reuse the path the manifest already points
        # at, so the .npz must commit atomically: a reader of the prior
        # entry must never map a half-written archive.
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._record(
            key,
            {
                "file": os.path.basename(path),
                "kind": "arrays",
                "arrays": {
                    name: {"shape": list(a.shape), "dtype": str(a.dtype)}
                    for name, a in arrays.items()
                },
                "config": _to_jsonable(config),
            },
        )
        return path

    def save_array_store(
        self,
        key: str,
        arrays: Dict[str, np.ndarray],
        *,
        rows_per_shard: int = store_mod.DEFAULT_ROWS_PER_SHARD,
        config: Any = None,
        meta: Optional[Dict[str, Any]] = None,
        patient_id_field: Optional[str] = None,
    ) -> str:
        """Persist arrays as a sharded memmap store (``array_store`` kind,
        data/store.py) instead of a monolithic ``.npz`` — the out-of-core
        artifact format: readers memory-map it instead of materializing,
        and writers stream into it shard by shard."""
        path = self.path_for(key, ".store")
        store_mod.write_store(
            path, arrays, rows_per_shard=rows_per_shard, meta=meta,
            patient_id_field=patient_id_field,
        )
        return self.adopt_array_store(key, config=config)

    def adopt_array_store(self, key: str, *, config: Any = None) -> str:
        """Record an already-written store directory at this key's
        canonical path (``<key>.store``) as an ``array_store`` artifact —
        the ingest path writes shards straight into the directory and
        adopts it once complete."""
        path = self.path_for(key, ".store")
        store = store_mod.ArrayStore.open(path)
        self._record(
            key,
            {
                "file": os.path.basename(path),
                "kind": "array_store",
                "arrays": {
                    **{
                        name: {
                            "shape": [store.rows] + list(spec["shape"]),
                            "dtype": spec["dtype"],
                        }
                        for name, spec in store.fields.items()
                    },
                    **{
                        name: {
                            "shape": list(np.shape(extra["values"])),
                            "dtype": extra["dtype"],
                        }
                        for name, extra in store.extra_arrays.items()
                    },
                },
                "rows": store.rows,
                "shards": store.num_shards,
                "config": _to_jsonable(config),
            },
        )
        return path

    def open_array_store(self, key: str) -> store_mod.ArrayStore:
        entry = self._entry(key)
        if entry.get("kind") != "array_store":
            raise ValueError(
                f"artifact {key!r} is kind {entry.get('kind')!r}, not "
                f"'array_store' (migrate it with "
                f"`apnea-uq migrate --key {key}`)"
            )
        return store_mod.ArrayStore.open(os.path.join(self.root, entry["file"]))

    def _entry(self, key: str) -> Dict[str, Any]:
        entry = self.describe(key)
        if entry is None:
            raise KeyError(
                f"artifact {key!r} not in registry at {self.root} "
                f"(have: {sorted(self.manifest()['artifacts'])})"
            )
        return entry

    def load_arrays(
        self,
        key: str,
        *,
        names: Optional[Sequence[str]] = None,
        mmap: bool = False,
    ) -> Dict[str, np.ndarray]:
        """Load an array artifact — either kind.

        ``names`` selects a subset so consumers stop decompressing keys
        they never read (each ``.npz`` member decompresses on access;
        store fields simply aren't mapped).  ``mmap=True`` returns
        memmap-backed lazy arrays for ``array_store`` artifacts (zero
        copy, zero load time) and is a no-op for ``.npz`` (the zip
        container cannot be mapped).  Emits one ``data_load`` telemetry
        event per call when a run log is active."""
        entry = self._entry(key)
        t0 = time.perf_counter()
        if entry.get("kind") == "array_store":
            store = store_mod.ArrayStore.open(
                os.path.join(self.root, entry["file"])
            )
            unknown = (set(names or ()) - set(store.fields)
                       - set(store.extra_arrays))
            if unknown:
                raise KeyError(
                    f"artifact {key!r} has no array(s) {sorted(unknown)} "
                    f"(have: {sorted(store.fields)})"
                )
            out = store.arrays(names, mmap=mmap)
        else:
            with np.load(os.path.join(self.root, entry["file"]),
                         allow_pickle=False) as z:
                unknown = set(names or ()) - set(z.files)
                if unknown:
                    raise KeyError(
                        f"artifact {key!r} has no array(s) "
                        f"{sorted(unknown)} (have: {sorted(z.files)})"
                    )
                out = {name: z[name]
                       for name in (names if names is not None else z.files)}
        self._record_data_load(key, entry, out, time.perf_counter() - t0,
                               mmap=mmap)
        return out

    def _record_data_load(self, key: str, entry: Dict[str, Any], arrays,
                          load_s: float, *, mmap: bool) -> None:
        """``data_load`` telemetry: how long a stage-start artifact load
        took, its logical volume, and the process's peak RSS — so the
        npz-vs-store cold-start cost is a gateable number, not prose."""
        from apnea_uq_tpu.telemetry.runlog import current_run

        run = current_run()
        if run is None:
            return
        rows = 0
        logical = 0
        for a in arrays.values():
            shape = np.shape(a)
            rows = max(rows, int(shape[0]) if shape else 0)
            logical += int(getattr(a, "nbytes", 0))
        run.event(
            "data_load", key=key, artifact_kind=entry.get("kind"),
            mmap=bool(mmap), rows=rows, bytes=logical,
            load_s=round(load_s, 6),
            rss_bytes=store_mod.peak_rss_bytes(),
        )

    # -- tables -----------------------------------------------------------

    def save_table(self, key: str, frame, *, config: Any = None) -> str:
        """Save a pandas DataFrame as CSV (atomic commit: a same-key
        re-save overwrites in place, so readers of the previous entry
        must never see a torn file)."""
        path = self.path_for(key, ".csv")
        tmp = path + ".tmp"
        # newline=""/utf-8 match what to_csv(path) would open itself with.
        with open(tmp, "w", newline="", encoding="utf-8") as f:
            frame.to_csv(f, index=False)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self._record(
            key,
            {
                "file": os.path.basename(path),
                "kind": "table",
                "rows": int(len(frame)),
                "columns": list(map(str, frame.columns)),
                "config": _to_jsonable(config),
            },
        )
        return path

    def load_table(self, key: str):
        import pandas as pd

        entry = self.describe(key)
        if entry is None:
            raise KeyError(f"artifact {key!r} not in registry at {self.root}")
        return pd.read_csv(os.path.join(self.root, entry["file"]))

    # -- json documents ---------------------------------------------------

    def save_json(self, key: str, document: Dict[str, Any], *, config: Any = None) -> str:
        """Save a JSON-able dict (numpy values are converted)."""
        path = self.path_for(key, ".json")
        store_mod.atomic_write_json(path, _to_jsonable(document))
        self._record(
            key,
            {
                "file": os.path.basename(path),
                "kind": "json",
                "keys": sorted(map(str, document)),
                "config": _to_jsonable(config),
            },
        )
        return path

    def load_json(self, key: str) -> Dict[str, Any]:
        entry = self.describe(key)
        if entry is None:
            raise KeyError(f"artifact {key!r} not in registry at {self.root}")
        with open(os.path.join(self.root, entry["file"])) as f:
            return json.load(f)

    # -- directories (checkpoints) ---------------------------------------

    def directory_for(self, key: str) -> str:
        """A managed subdirectory (created) for directory-shaped artifacts."""
        path = self.path_for(key, "")
        os.makedirs(path, exist_ok=True)
        self._record(
            key,
            {"file": os.path.basename(path), "kind": "directory"},
        )
        return path


def migrate_to_store(
    registry: ArtifactRegistry,
    key: str,
    *,
    rows_per_shard: int = store_mod.DEFAULT_ROWS_PER_SHARD,
    keep_npz: bool = True,
) -> str:
    """Convert an ``arrays`` (.npz) artifact to the ``array_store`` kind
    in place: same key, same array contents, sharded memmap layout.
    Old registries stay readable without migrating — this exists so a
    one-off command upgrades them to the zero-copy path.  The original
    ``.npz`` file is kept by default (the manifest no longer references
    it); ``keep_npz=False`` deletes it after a verified store write."""
    entry = registry._entry(key)
    if entry.get("kind") == "array_store":
        return os.path.join(registry.root, entry["file"])
    if entry.get("kind") != "arrays":
        raise ValueError(
            f"artifact {key!r} is kind {entry.get('kind')!r}; only "
            f"'arrays' (.npz) artifacts can migrate to a store"
        )
    arrays = registry.load_arrays(key)
    config = entry.get("config")
    path = registry.save_array_store(
        key, arrays, rows_per_shard=rows_per_shard, config=config,
        patient_id_field="patient_ids" if "patient_ids" in arrays else None,
    )
    store_mod.ArrayStore.open(path).verify()
    if not keep_npz:
        try:
            os.remove(os.path.join(registry.root, entry["file"]))
        except OSError:
            pass
    return path


