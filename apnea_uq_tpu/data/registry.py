"""Versioned on-disk artifact registry.

The reference pipeline hands artifacts between stages by ad-hoc file
names, and the names drifted apart between producers and consumers
(SURVEY §1 "contract drift": SHHS2_ID_all_60.csv vs SHHS2_ID_all.csv,
X_train_win_std_smote vs X_train_std_smote, seed{21+i} vs seed{i+5}, two
different default output dirs).  Here every artifact has one canonical
key, and a JSON manifest records shape, dtype, and the producing config
so a run is auditable and resumable.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import numpy as np

from apnea_uq_tpu.config import _to_jsonable

MANIFEST_NAME = "manifest.json"

# Canonical artifact keys (SURVEY §1 boundary table, without the drift).
WINDOWS = "windows"                            # L1 -> L2: ingested window set (.npz)
TRAIN_STD_SMOTE = "train_std_smote"            # L2 -> L3: balanced training set
TEST_STD_UNBALANCED = "test_std_unbalanced"    # L2 -> L3/L5: full test set
TEST_STD_RUS = "test_std_rus"                  # L2 -> L3/L5: RUS-balanced test set
RAW_PREDICTIONS = "raw_predictions"            # L5 side: (K, M) probability stack (full-probs evals)
UQ_STATS = "uq_stats"                          # L5 side: (4, M) sufficient statistics (fused evals)
DETAILED_WINDOWS = "detailed_windows"          # L5 -> L6: per-window CSV
METRICS = "metrics"                            # L5 side: aggregates/CIs/classification JSON
PATIENT_SUMMARY = "patient_summary"            # L6 -> L7: per-patient CSV
CHECKPOINT = "checkpoint"                      # L3 -> L5: model checkpoints (dir)


class ArtifactRegistry:
    """One root directory holding every pipeline artifact plus a manifest.

    Array artifacts are ``.npz`` bundles (arrays keyed by name); tabular
    artifacts are CSV.  Keys may carry a tag suffix for per-method
    variants, e.g. ``detailed_windows:CNN_MCD_Unbalanced``.
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    # -- manifest ---------------------------------------------------------

    def _manifest_path(self) -> str:
        return os.path.join(self.root, MANIFEST_NAME)

    def manifest(self) -> Dict[str, Any]:
        path = self._manifest_path()
        if not os.path.exists(path):
            return {"version": 1, "artifacts": {}}
        with open(path) as f:
            return json.load(f)

    def _record(self, key: str, entry: Dict[str, Any]) -> None:
        manifest = self.manifest()
        manifest["artifacts"][key] = entry
        tmp = self._manifest_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2, sort_keys=True)
        os.replace(tmp, self._manifest_path())

    def describe(self, key: str) -> Optional[Dict[str, Any]]:
        return self.manifest()["artifacts"].get(key)

    def exists(self, key: str) -> bool:
        entry = self.describe(key)
        return entry is not None and os.path.exists(
            os.path.join(self.root, entry["file"])
        )

    def available(self, prefix: str = "") -> list:
        """Keys starting with ``prefix`` whose artifact files exist on
        disk — the same on-disk requirement as :meth:`exists`, with ONE
        manifest read for the whole listing."""
        return sorted(
            key
            for key, entry in self.manifest()["artifacts"].items()
            if key.startswith(prefix)
            and os.path.exists(os.path.join(self.root, entry["file"]))
        )

    # -- arrays -----------------------------------------------------------

    def path_for(self, key: str, suffix: str) -> str:
        return os.path.join(self.root, key.replace(":", "__") + suffix)

    def save_arrays(
        self,
        key: str,
        arrays: Dict[str, np.ndarray],
        *,
        config: Any = None,
    ) -> str:
        path = self.path_for(key, ".npz")
        np.savez(path, **arrays)
        self._record(
            key,
            {
                "file": os.path.basename(path),
                "kind": "arrays",
                "arrays": {
                    name: {"shape": list(a.shape), "dtype": str(a.dtype)}
                    for name, a in arrays.items()
                },
                "config": _to_jsonable(config),
            },
        )
        return path

    def load_arrays(self, key: str) -> Dict[str, np.ndarray]:
        entry = self.describe(key)
        if entry is None:
            raise KeyError(
                f"artifact {key!r} not in registry at {self.root} "
                f"(have: {sorted(self.manifest()['artifacts'])})"
            )
        with np.load(os.path.join(self.root, entry["file"]), allow_pickle=False) as z:
            return {name: z[name] for name in z.files}

    # -- tables -----------------------------------------------------------

    def save_table(self, key: str, frame, *, config: Any = None) -> str:
        """Save a pandas DataFrame as CSV."""
        path = self.path_for(key, ".csv")
        frame.to_csv(path, index=False)
        self._record(
            key,
            {
                "file": os.path.basename(path),
                "kind": "table",
                "rows": int(len(frame)),
                "columns": list(map(str, frame.columns)),
                "config": _to_jsonable(config),
            },
        )
        return path

    def load_table(self, key: str):
        import pandas as pd

        entry = self.describe(key)
        if entry is None:
            raise KeyError(f"artifact {key!r} not in registry at {self.root}")
        return pd.read_csv(os.path.join(self.root, entry["file"]))

    # -- json documents ---------------------------------------------------

    def save_json(self, key: str, document: Dict[str, Any], *, config: Any = None) -> str:
        """Save a JSON-able dict (numpy values are converted)."""
        path = self.path_for(key, ".json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_to_jsonable(document), f, indent=2, sort_keys=True)
        os.replace(tmp, path)
        self._record(
            key,
            {
                "file": os.path.basename(path),
                "kind": "json",
                "keys": sorted(map(str, document)),
                "config": _to_jsonable(config),
            },
        )
        return path

    def load_json(self, key: str) -> Dict[str, Any]:
        entry = self.describe(key)
        if entry is None:
            raise KeyError(f"artifact {key!r} not in registry at {self.root}")
        with open(os.path.join(self.root, entry["file"])) as f:
            return json.load(f)

    # -- directories (checkpoints) ---------------------------------------

    def directory_for(self, key: str) -> str:
        """A managed subdirectory (created) for directory-shaped artifacts."""
        path = self.path_for(key, "")
        os.makedirs(path, exist_ok=True)
        self._record(
            key,
            {"file": os.path.basename(path), "kind": "directory"},
        )
        return path


