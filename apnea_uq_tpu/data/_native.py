"""ctypes loader for the native EDF decode library (native/edfio.cpp).

Loads ``_edfio.so`` from the package directory; if it is absent and a C++
compiler is on PATH, compiles it once from the in-tree source (build
artifacts are machine-local, never committed).  All entry points degrade
gracefully: ``available()`` is False whenever neither path works, and the
NumPy fallback in edf.py takes over.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_LIB_NAME = "_edfio.so"
_SOURCE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "edfio.cpp",
)
_ABI_VERSION = 1

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _lib_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), _LIB_NAME)


def _try_build() -> bool:
    if not os.path.exists(_SOURCE):
        return False
    try:
        subprocess.run(
            [
                os.environ.get("CXX", "g++"),
                "-O3",
                "-fPIC",
                "-shared",
                "-std=c++17",
                _SOURCE,
                "-o",
                _lib_path(),
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        path = _lib_path()
        if not os.path.exists(path) and not _try_build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(path)
            # AttributeError here means a stale or foreign .so without our
            # symbols — treat exactly like a failed load so the NumPy
            # fallback takes over instead of erroring on every read.
            if lib.edf_native_abi_version() != _ABI_VERSION:
                _load_failed = True
                return None
            lib.edf_decode_signal.argtypes = [
                ctypes.POINTER(ctypes.c_int16),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_float,
                ctypes.c_float,
                ctypes.POINTER(ctypes.c_float),
            ]
            lib.edf_decode_signal.restype = None
            _lib = lib
        except (OSError, AttributeError):
            _load_failed = True
    return _lib


def available() -> bool:
    return _load() is not None


def decode_signal(
    data: np.ndarray,
    n_records: int,
    record_words: int,
    signal_offset: int,
    spr: int,
    gain: float,
    offset: float,
) -> np.ndarray:
    """float32 (n_records * spr,) physical samples for one signal.

    ``data`` is the file's full int16 record block (C-contiguous,
    at least n_records * record_words elements).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native EDF library unavailable")
    data = np.ascontiguousarray(data, dtype=np.int16)
    if data.size < n_records * record_words:
        raise ValueError(
            f"record block has {data.size} samples, need "
            f"{n_records} records x {record_words} words"
        )
    out = np.empty(n_records * spr, dtype=np.float32)
    lib.edf_decode_signal(
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_int16)),
        n_records,
        record_words,
        signal_offset,
        spr,
        float(gain),
        float(offset),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
    )
    return out
