"""Raw SHHS2 ingestion: EDF + XML -> labeled 60 s windows (L1).

Capability parity with data_prepocessing/preprocess_shhs_raw.py:

- channel extraction with PR -> H.R. alternative-name fallback (:139-147),
- out-of-range interpolation for SaO2 (<80 or >100) and PR (<40 or >200)
  (:100-124),
- exclusion of recordings with >10% missing samples per channel (:53-72)
  or recording duration under 300 minutes (:75-96),
- FFT resampling of every channel to 1 Hz (:158-164),
- non-overlapping 60 s windows, labeled 1 iff they overlap an
  "Obstructive apnea|Obstructive Apnea" or "Hypopnea|Hypopnea" event for
  >= 10 s (:194-263),
- per-file error containment: a failing recording is reported and
  skipped, never aborts the run (:316-318).

Divergences (intentional, SURVEY §7 "hard parts"): window labeling is a
vectorized interval-overlap computation instead of a Python loop over
windows x events; a recording missing any required channel is excluded
with an explicit reason (the reference would emit a malformed frame);
windows are carried as (N, 60, 4) arrays in an .npz artifact, with the
reference's flattened-CSV schema available via
``windows_to_reference_csv`` / ``windows_from_reference_csv`` for interop.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from apnea_uq_tpu.config import IngestConfig
from apnea_uq_tpu.data.annotations import RespiratoryEvents, parse_xml_annotations
from apnea_uq_tpu.data.edf import read_edf

LABEL_COL = "Apnea/Hypopnea"
GROUP_COL = "Patient_ID"


@dataclass(frozen=True)
class WindowSet:
    """Labeled, windowed recordings — the L1 -> L2 artifact."""

    x: np.ndarray            # float32 (N, window, channels)
    y: np.ndarray            # int8 (N,)
    patient_ids: np.ndarray  # str (N,)
    start_time_s: np.ndarray # int32 (N,) window start within its recording
    channels: Tuple[str, ...]

    def __len__(self) -> int:
        return len(self.y)

    @classmethod
    def concat_all(cls, sets: Sequence["WindowSet"]) -> "WindowSet":
        """Single-pass concatenation of many WindowSets (one allocation
        per field, not O(K^2) pairwise copies)."""
        if not sets:
            raise ValueError("cannot concatenate zero WindowSets")
        channels = sets[0].channels
        for ws in sets[1:]:
            if ws.channels != channels:
                raise ValueError(f"channel mismatch: {channels} vs {ws.channels}")
        return cls(
            x=np.concatenate([ws.x for ws in sets]),
            y=np.concatenate([ws.y for ws in sets]),
            patient_ids=np.concatenate([ws.patient_ids for ws in sets]),
            start_time_s=np.concatenate([ws.start_time_s for ws in sets]),
            channels=channels,
        )

    def concat(self, other: "WindowSet") -> "WindowSet":
        return WindowSet.concat_all([self, other])

    def to_arrays(self) -> Dict[str, np.ndarray]:
        return {
            "x": self.x,
            "y": self.y,
            "patient_ids": self.patient_ids.astype(np.str_),
            "start_time_s": self.start_time_s,
            "channels": np.asarray(self.channels, dtype=np.str_),
        }

    @classmethod
    def from_arrays(cls, arrays: Dict[str, np.ndarray]) -> "WindowSet":
        return cls(
            x=arrays["x"],
            y=arrays["y"],
            patient_ids=arrays["patient_ids"].astype(str),
            start_time_s=arrays["start_time_s"],
            channels=tuple(arrays["channels"].astype(str)),
        )


@dataclass(frozen=True)
class IngestReport:
    """Outcome of one recording: included (n_windows) or excluded (reason)."""

    patient_id: str
    edf_path: str
    n_windows: int = 0
    excluded: Optional[str] = None
    error: Optional[str] = None


def interpolate_out_of_range(
    signal: np.ndarray, lo: float, hi: float
) -> np.ndarray:
    """Replace samples outside [lo, hi] (and NaNs) by linear interpolation.

    Mirrors remove_artifacts (preprocess_shhs_raw.py:100-124).  If no
    valid samples exist the signal is returned all-NaN, which the
    missing-value exclusion then catches (the reference instead raised
    from np.interp and the file was skipped by the outer try/except).
    """
    signal = np.asarray(signal, dtype=np.float32).copy()
    invalid = ~np.isfinite(signal) | (signal < lo) | (signal > hi)
    if not invalid.any():
        return signal
    valid_idx = np.flatnonzero(~invalid)
    if valid_idx.size == 0:
        signal[:] = np.nan
        return signal
    invalid_idx = np.flatnonzero(invalid)
    signal[invalid_idx] = np.interp(invalid_idx, valid_idx, signal[valid_idx])
    return signal


def missing_fraction_ok(
    signals: Dict[str, np.ndarray], max_nan_fraction: float
) -> bool:
    """True iff every channel has <= max_nan_fraction NaN samples
    (check_artifacts_and_missing_values, preprocess_shhs_raw.py:53-72)."""
    for sig in signals.values():
        if sig.size == 0:
            return False
        if np.isnan(sig).mean() > max_nan_fraction:
            return False
    return True


def fft_resample(signal: np.ndarray, target_length: int) -> np.ndarray:
    """FFT-domain resampling: the exact real-input semantics of
    scipy.signal.resample as used at preprocess_shhs_raw.py:163, in-tree
    (truncate/zero-pad the rfft spectrum, with the doubled/halved unpaired
    Nyquist bin when min(n, num) is even), verified against scipy to
    1e-12 in tests/test_data_ingest.py.  ``num == n`` returns a copy
    without the FFT round-trip (scipy's round-trip differs by ~1 ulp).

    The output dtype follows scipy: float32 in -> float32 out, float16
    promotes to float32, integer and other inputs promote to float64.
    The FFT itself runs in float64 regardless — numpy has no
    single-precision FFT — so a float32 input matches scipy's float32
    path to float32 roundoff (scipy computes the transform in single
    precision), while float64 matches to 1e-12."""
    signal = np.asarray(signal)
    out_dtype = (
        np.result_type(signal.dtype, np.float32)
        if np.issubdtype(signal.dtype, np.floating) else np.float64
    )
    signal = signal.astype(np.float64, copy=False)
    n = signal.shape[0]
    num = int(target_length)
    if num == n:
        return signal.astype(out_dtype, copy=True)
    if n == 0 or num <= 0:
        raise ValueError(f"cannot resample length {n} to {num}")
    spectrum = np.fft.rfft(signal)
    m = min(num, n)
    spectrum = spectrum[: m // 2 + 1]
    if m % 2 == 0:
        # The unpaired bin at m//2: its conjugate partner is folded in on
        # down-sampling (x2) or split back out on up-sampling (x0.5).
        spectrum[m // 2] *= 2.0 if num < n else 0.5
    return np.fft.irfft(spectrum * (num / n), n=num).astype(out_dtype, copy=False)


def label_windows(
    n_windows: int,
    window_size_s: float,
    events: RespiratoryEvents,
    *,
    concepts: Sequence[str],
    min_overlap_s: float,
    stride_s: Optional[float] = None,
) -> np.ndarray:
    """int8 (n_windows,) labels: 1 iff the window overlaps any selected
    event for >= min_overlap_s (preprocess_shhs_raw.py:206,236-249).

    Window w spans [w*stride, w*stride + window_size); stride defaults to
    window_size (the reference's non-overlapping case, overlap_size=0 at
    :194).  Vectorized: per event, the windows meeting the overlap
    threshold form a contiguous index interval, so labeling is two index
    bounds and a difference-array range update — O(E + W) instead of the
    reference's O(W*E) nested Python loop.
    """
    labels = np.zeros(n_windows, dtype=np.int8)
    if n_windows == 0 or len(events) == 0 or min_overlap_s > window_size_s:
        return labels
    sel = events.select_concepts(concepts)
    if len(sel) == 0:
        return labels
    start = sel.start_s
    end = sel.start_s + sel.duration_s
    ok = np.isfinite(start) & np.isfinite(end) & (end - start >= min_overlap_s)
    start, end = start[ok], end[ok]
    if start.size == 0:
        return labels

    # overlap(w) = min(end, w*stride + S) - max(start, w*stride) >= m
    # <=>  w >= (start + m - S)/stride  and  w <= (end - m)/stride
    # (given the filters end-start >= m and S >= m above).
    s = float(window_size_s)
    stride = s if stride_s is None else float(stride_s)
    if stride <= 0:
        raise ValueError(f"stride must be positive, got {stride}")
    w_lo = np.ceil((start - s + min_overlap_s) / stride).astype(np.int64)
    w_hi = np.floor((end - min_overlap_s) / stride).astype(np.int64)
    w_lo = np.clip(w_lo, 0, n_windows)
    w_hi = np.clip(w_hi, -1, n_windows - 1)
    keep = w_lo <= w_hi
    w_lo, w_hi = w_lo[keep], w_hi[keep]
    if w_lo.size == 0:
        return labels
    diff = np.zeros(n_windows + 1, dtype=np.int32)
    np.add.at(diff, w_lo, 1)
    np.add.at(diff, w_hi + 1, -1)
    labels[np.cumsum(diff[:-1]) > 0] = 1
    return labels


def ingest_recording(
    edf_path: str,
    xml_path: str,
    patient_id: str,
    config: IngestConfig = IngestConfig(),
) -> Tuple[Optional[WindowSet], IngestReport]:
    """One EDF + XML pair -> labeled windows, or an exclusion report
    (process_single_file, preprocess_shhs_raw.py:265-286)."""
    channels = tuple(config.channels)

    # Channel extraction with alternative-name fallback for PR (:139-147).
    want = set(channels) | set(config.pr_alt_names)
    decoded = read_edf(edf_path, sorted(want))
    signals: Dict[str, np.ndarray] = {}
    rates: Dict[str, float] = {}
    for ch in channels:
        source = ch
        if ch not in decoded and ch == "PR":
            source = next(
                (alt for alt in config.pr_alt_names if alt in decoded), ch
            )
        if source not in decoded:
            report = IngestReport(
                patient_id, edf_path, excluded=f"missing channel {ch!r}"
            )
            return None, report
        signals[ch] = decoded[source].samples
        rates[ch] = decoded[source].sampling_rate

    # Artifact interpolation for SaO2 and PR (:106-123).
    if "SaO2" in signals:
        signals["SaO2"] = interpolate_out_of_range(
            signals["SaO2"], *config.sao2_valid_range
        )
    if "PR" in signals:
        signals["PR"] = interpolate_out_of_range(
            signals["PR"], *config.pr_valid_range
        )

    if not missing_fraction_ok(signals, config.max_nan_fraction):
        return None, IngestReport(
            patient_id, edf_path, excluded="excessive missing values/artifacts"
        )

    events = parse_xml_annotations(
        xml_path, stop_at_first_stage_event=config.stop_at_first_stage_event
    )
    if events.recording_duration_s < config.min_sleep_time_s:
        return None, IngestReport(
            patient_id,
            edf_path,
            excluded=(
                f"recording duration {events.recording_duration_s:.0f}s "
                f"< {config.min_sleep_time_s:.0f}s"
            ),
        )

    # FFT resample every channel to the target rate (:158-164).
    resampled = {}
    for ch in channels:
        sig = signals[ch]
        target_len = int(len(sig) * (config.target_rate_hz / rates[ch]))
        resampled[ch] = fft_resample(sig, target_len)

    # Cut full windows at stride (window - overlap); trailing partial
    # window dropped (:208-220; overlap_size honored as at :194,211).
    samples_per_window = int(round(config.window_size_s * config.target_rate_hz))
    stride_s = config.window_size_s - config.overlap_s
    if stride_s <= 0:
        raise ValueError(
            f"overlap_s ({config.overlap_s}) must be smaller than "
            f"window_size_s ({config.window_size_s})"
        )
    stride_samples = int(round(stride_s * config.target_rate_hz))
    min_len = min(len(v) for v in resampled.values())
    n_windows = (
        (min_len - samples_per_window) // stride_samples + 1
        if min_len >= samples_per_window
        else 0
    )
    if n_windows == 0:
        return None, IngestReport(
            patient_id, edf_path, excluded="recording shorter than one window"
        )
    stacked = np.stack(
        [resampled[ch][:min_len] for ch in channels], axis=-1
    ).astype(np.float32)                              # (min_len, C)
    starts = np.arange(n_windows) * stride_samples
    idx = starts[:, None] + np.arange(samples_per_window)[None, :]
    x = stacked[idx]                                  # (n_windows, spw, C)

    labels = label_windows(
        n_windows,
        config.window_size_s,
        events,
        concepts=config.apnea_event_concepts,
        min_overlap_s=config.min_event_overlap_s,
        stride_s=stride_s,
    )

    window_set = WindowSet(
        x=x,
        y=labels,
        patient_ids=np.full(n_windows, str(patient_id)),
        start_time_s=(starts / config.target_rate_hz).astype(np.int32),
        channels=channels,
    )
    return window_set, IngestReport(patient_id, edf_path, n_windows=n_windows)


def _nsrr_pair(edf_file: str) -> Tuple[str, str]:
    """(patient_id, xml_name) from an shhs2-<id>.edf file name
    (preprocess_shhs_raw.py:302-303)."""
    nsrr_id = edf_file.split("-")[1].split(".")[0]
    return nsrr_id, f"shhs2-{nsrr_id}-nsrr.xml"


def ingest_directory(
    edf_folder: str,
    xml_folder: str,
    config: IngestConfig = IngestConfig(),
    *,
    num_files: Optional[int] = None,
    workers: int = 0,
) -> Tuple[Optional[WindowSet], List[IngestReport]]:
    """All EDF/XML pairs under two folders -> one combined WindowSet
    (process_all_files, preprocess_shhs_raw.py:290-326).

    ``num_files`` limits the number of processed recordings (the
    reference's --num_files dry-run flag, :19-26).  ``workers`` > 0
    decodes recordings in a thread pool (EDF decode and FFT resample are
    NumPy/SciPy calls that release the GIL); 0 keeps the reference's
    sequential order.
    """
    jobs = []
    for edf_file in sorted(os.listdir(edf_folder)):
        if num_files is not None and len(jobs) >= num_files:
            break
        if not edf_file.endswith(".edf"):
            continue
        try:
            patient_id, xml_name = _nsrr_pair(edf_file)
        except IndexError:
            continue
        xml_path = os.path.join(xml_folder, xml_name)
        if not os.path.exists(xml_path):
            continue
        jobs.append((os.path.join(edf_folder, edf_file), xml_path, patient_id))

    def run(job) -> Tuple[Optional[WindowSet], IngestReport]:
        edf_path, xml_path, patient_id = job
        try:
            return ingest_recording(edf_path, xml_path, patient_id, config)
        except Exception as e:  # per-file containment (:316-318)
            return None, IngestReport(patient_id, edf_path, error=str(e))

    if workers > 0:
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=workers) as pool:
            results = list(pool.map(run, jobs))
    else:
        results = [run(job) for job in jobs]

    reports = [r for _, r in results]
    sets = [ws for ws, _ in results if ws is not None]
    if not sets:
        return None, reports
    return WindowSet.concat_all(sets), reports


# -- reference CSV interop ------------------------------------------------

def _flat_columns(channels: Sequence[str], window: int) -> List[str]:
    # Time-major interleaved order, matching the reference's C-order
    # flatten of a (window, channels) frame (preprocess_shhs_raw.py:204,229).
    return [f"{ch}_t{t}" for t in range(window) for ch in channels]


def windows_to_reference_csv(
    windows: WindowSet, path: str, *, window_duration_s: Optional[float] = None
) -> None:
    """Emit the reference's flattened schema (SHHS2_ID_all_60.csv):
    {ch}_t{t} feature columns + Start_Time, End_Time, Apnea/Hypopnea,
    Patient_ID (preprocess_shhs_raw.py:204,253-256).

    ``window_duration_s`` defaults to the per-window sample count — exact
    at the standard 1 Hz target rate; pass it explicitly when ingesting
    at another rate so End_Time stays in seconds.
    """
    import pandas as pd

    n, window, c = windows.x.shape
    frame = pd.DataFrame(
        windows.x.reshape(n, window * c),
        columns=_flat_columns(windows.channels, window),
    )
    duration = window if window_duration_s is None else window_duration_s
    frame["Start_Time"] = windows.start_time_s
    frame["End_Time"] = windows.start_time_s + duration
    frame[LABEL_COL] = windows.y
    frame[GROUP_COL] = windows.patient_ids
    frame.to_csv(path, index=False)


def windows_from_reference_csv(
    path: str,
    channels: Sequence[str] = ("SaO2", "PR", "THOR RES", "ABDO RES"),
    window: int = 60,
) -> WindowSet:
    """Load a reference-format flattened CSV into a WindowSet
    (the prepare_numpy_datasets.py:114,134-136 consumer side)."""
    import pandas as pd

    frame = pd.read_csv(path)
    cols = _flat_columns(channels, window)
    missing = [c for c in cols + [LABEL_COL, GROUP_COL] if c not in frame.columns]
    if missing:
        raise ValueError(f"CSV {path} is missing columns, e.g. {missing[:4]}")
    x = frame[cols].to_numpy(dtype=np.float32).reshape(len(frame), window, len(channels))
    start = (
        frame["Start_Time"].to_numpy(dtype=np.int32)
        if "Start_Time" in frame.columns
        else np.zeros(len(frame), dtype=np.int32)
    )
    return WindowSet(
        x=x,
        y=frame[LABEL_COL].to_numpy(dtype=np.int8),
        patient_ids=frame[GROUP_COL].to_numpy(dtype=np.str_).astype(str),
        start_time_s=start,
        channels=tuple(channels),
    )
