"""Raw SHHS2 ingestion: EDF + XML -> labeled 60 s windows (L1).

Capability parity with data_prepocessing/preprocess_shhs_raw.py:

- channel extraction with PR -> H.R. alternative-name fallback (:139-147),
- out-of-range interpolation for SaO2 (<80 or >100) and PR (<40 or >200)
  (:100-124),
- exclusion of recordings with >10% missing samples per channel (:53-72)
  or recording duration under 300 minutes (:75-96),
- FFT resampling of every channel to 1 Hz (:158-164),
- non-overlapping 60 s windows, labeled 1 iff they overlap an
  "Obstructive apnea|Obstructive Apnea" or "Hypopnea|Hypopnea" event for
  >= 10 s (:194-263),
- per-file error containment: a failing recording is reported and
  skipped, never aborts the run (:316-318).

Divergences (intentional, SURVEY §7 "hard parts"): window labeling is a
vectorized interval-overlap computation instead of a Python loop over
windows x events; a recording missing any required channel is excluded
with an explicit reason (the reference would emit a malformed frame);
windows are carried as (N, 60, 4) arrays in an .npz artifact, with the
reference's flattened-CSV schema available via
``windows_to_reference_csv`` / ``windows_from_reference_csv`` for interop.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from apnea_uq_tpu.config import IngestConfig
from apnea_uq_tpu.data.annotations import RespiratoryEvents, parse_xml_annotations
from apnea_uq_tpu.data.edf import read_edf

LABEL_COL = "Apnea/Hypopnea"
GROUP_COL = "Patient_ID"


@dataclass(frozen=True)
class WindowSet:
    """Labeled, windowed recordings — the L1 -> L2 artifact."""

    x: np.ndarray            # float32 (N, window, channels)
    y: np.ndarray            # int8 (N,)
    patient_ids: np.ndarray  # str (N,)
    start_time_s: np.ndarray # int32 (N,) window start within its recording
    channels: Tuple[str, ...]

    def __len__(self) -> int:
        return len(self.y)

    @classmethod
    def concat_all(cls, sets: Sequence["WindowSet"]) -> "WindowSet":
        """Single-pass concatenation of many WindowSets (one allocation
        per field, not O(K^2) pairwise copies)."""
        if not sets:
            raise ValueError("cannot concatenate zero WindowSets")
        channels = sets[0].channels
        for ws in sets[1:]:
            if ws.channels != channels:
                raise ValueError(f"channel mismatch: {channels} vs {ws.channels}")
        return cls(
            x=np.concatenate([ws.x for ws in sets]),
            y=np.concatenate([ws.y for ws in sets]),
            patient_ids=np.concatenate([ws.patient_ids for ws in sets]),
            start_time_s=np.concatenate([ws.start_time_s for ws in sets]),
            channels=channels,
        )

    def concat(self, other: "WindowSet") -> "WindowSet":
        return WindowSet.concat_all([self, other])

    def to_arrays(self) -> Dict[str, np.ndarray]:
        return {
            "x": self.x,
            "y": self.y,
            "patient_ids": self.patient_ids.astype(np.str_),
            "start_time_s": self.start_time_s,
            "channels": np.asarray(self.channels, dtype=np.str_),
        }

    @classmethod
    def from_arrays(cls, arrays: Dict[str, np.ndarray]) -> "WindowSet":
        return cls(
            x=arrays["x"],
            y=arrays["y"],
            patient_ids=arrays["patient_ids"].astype(str),
            start_time_s=arrays["start_time_s"],
            channels=tuple(arrays["channels"].astype(str)),
        )


@dataclass(frozen=True)
class IngestReport:
    """Outcome of one recording: included (n_windows) or excluded (reason)."""

    patient_id: str
    edf_path: str
    n_windows: int = 0
    excluded: Optional[str] = None
    error: Optional[str] = None


def interpolate_out_of_range(
    signal: np.ndarray, lo: float, hi: float
) -> np.ndarray:
    """Replace samples outside [lo, hi] (and NaNs) by linear interpolation.

    Mirrors remove_artifacts (preprocess_shhs_raw.py:100-124).  If no
    valid samples exist the signal is returned all-NaN, which the
    missing-value exclusion then catches (the reference instead raised
    from np.interp and the file was skipped by the outer try/except).
    """
    signal = np.asarray(signal, dtype=np.float32).copy()
    invalid = ~np.isfinite(signal) | (signal < lo) | (signal > hi)
    if not invalid.any():
        return signal
    valid_idx = np.flatnonzero(~invalid)
    if valid_idx.size == 0:
        signal[:] = np.nan
        return signal
    invalid_idx = np.flatnonzero(invalid)
    signal[invalid_idx] = np.interp(invalid_idx, valid_idx, signal[valid_idx])
    return signal


def missing_fraction_ok(
    signals: Dict[str, np.ndarray], max_nan_fraction: float
) -> bool:
    """True iff every channel has <= max_nan_fraction NaN samples
    (check_artifacts_and_missing_values, preprocess_shhs_raw.py:53-72)."""
    for sig in signals.values():
        if sig.size == 0:
            return False
        if np.isnan(sig).mean() > max_nan_fraction:
            return False
    return True


def fft_resample(signal: np.ndarray, target_length: int) -> np.ndarray:
    """FFT-domain resampling: the exact real-input semantics of
    scipy.signal.resample as used at preprocess_shhs_raw.py:163, in-tree
    (truncate/zero-pad the rfft spectrum, with the doubled/halved unpaired
    Nyquist bin when min(n, num) is even), verified against scipy to
    1e-12 in tests/test_data_ingest.py.  ``num == n`` returns a copy
    without the FFT round-trip (scipy's round-trip differs by ~1 ulp).

    The output dtype follows scipy: float32 in -> float32 out, float16
    promotes to float32, integer and other inputs promote to float64.
    The FFT itself runs in float64 regardless — numpy has no
    single-precision FFT — so a float32 input matches scipy's float32
    path to float32 roundoff (scipy computes the transform in single
    precision), while float64 matches to 1e-12."""
    signal = np.asarray(signal)
    out_dtype = (
        np.result_type(signal.dtype, np.float32)
        if np.issubdtype(signal.dtype, np.floating) else np.float64
    )
    signal = signal.astype(np.float64, copy=False)
    n = signal.shape[0]
    num = int(target_length)
    if num == n:
        return signal.astype(out_dtype, copy=True)
    if n == 0 or num <= 0:
        raise ValueError(f"cannot resample length {n} to {num}")
    spectrum = np.fft.rfft(signal)
    m = min(num, n)
    spectrum = spectrum[: m // 2 + 1]
    if m % 2 == 0:
        # The unpaired bin at m//2: its conjugate partner is folded in on
        # down-sampling (x2) or split back out on up-sampling (x0.5).
        spectrum[m // 2] *= 2.0 if num < n else 0.5
    return np.fft.irfft(spectrum * (num / n), n=num).astype(out_dtype, copy=False)


def label_windows(
    n_windows: int,
    window_size_s: float,
    events: RespiratoryEvents,
    *,
    concepts: Sequence[str],
    min_overlap_s: float,
    stride_s: Optional[float] = None,
) -> np.ndarray:
    """int8 (n_windows,) labels: 1 iff the window overlaps any selected
    event for >= min_overlap_s (preprocess_shhs_raw.py:206,236-249).

    Window w spans [w*stride, w*stride + window_size); stride defaults to
    window_size (the reference's non-overlapping case, overlap_size=0 at
    :194).  Vectorized: per event, the windows meeting the overlap
    threshold form a contiguous index interval, so labeling is two index
    bounds and a difference-array range update — O(E + W) instead of the
    reference's O(W*E) nested Python loop.
    """
    labels = np.zeros(n_windows, dtype=np.int8)
    if n_windows == 0 or len(events) == 0 or min_overlap_s > window_size_s:
        return labels
    sel = events.select_concepts(concepts)
    if len(sel) == 0:
        return labels
    start = sel.start_s
    end = sel.start_s + sel.duration_s
    ok = np.isfinite(start) & np.isfinite(end) & (end - start >= min_overlap_s)
    start, end = start[ok], end[ok]
    if start.size == 0:
        return labels

    # overlap(w) = min(end, w*stride + S) - max(start, w*stride) >= m
    # <=>  w >= (start + m - S)/stride  and  w <= (end - m)/stride
    # (given the filters end-start >= m and S >= m above).
    s = float(window_size_s)
    stride = s if stride_s is None else float(stride_s)
    if stride <= 0:
        raise ValueError(f"stride must be positive, got {stride}")
    w_lo = np.ceil((start - s + min_overlap_s) / stride).astype(np.int64)
    w_hi = np.floor((end - min_overlap_s) / stride).astype(np.int64)
    w_lo = np.clip(w_lo, 0, n_windows)
    w_hi = np.clip(w_hi, -1, n_windows - 1)
    keep = w_lo <= w_hi
    w_lo, w_hi = w_lo[keep], w_hi[keep]
    if w_lo.size == 0:
        return labels
    diff = np.zeros(n_windows + 1, dtype=np.int32)
    np.add.at(diff, w_lo, 1)
    np.add.at(diff, w_hi + 1, -1)
    labels[np.cumsum(diff[:-1]) > 0] = 1
    return labels


def ingest_recording(
    edf_path: str,
    xml_path: str,
    patient_id: str,
    config: IngestConfig = IngestConfig(),
) -> Tuple[Optional[WindowSet], IngestReport]:
    """One EDF + XML pair -> labeled windows, or an exclusion report
    (process_single_file, preprocess_shhs_raw.py:265-286)."""
    channels = tuple(config.channels)

    # Channel extraction with alternative-name fallback for PR (:139-147).
    want = set(channels) | set(config.pr_alt_names)
    decoded = read_edf(edf_path, sorted(want))
    signals: Dict[str, np.ndarray] = {}
    rates: Dict[str, float] = {}
    for ch in channels:
        source = ch
        if ch not in decoded and ch == "PR":
            source = next(
                (alt for alt in config.pr_alt_names if alt in decoded), ch
            )
        if source not in decoded:
            report = IngestReport(
                patient_id, edf_path, excluded=f"missing channel {ch!r}"
            )
            return None, report
        signals[ch] = decoded[source].samples
        rates[ch] = decoded[source].sampling_rate

    # Artifact interpolation for SaO2 and PR (:106-123).
    if "SaO2" in signals:
        signals["SaO2"] = interpolate_out_of_range(
            signals["SaO2"], *config.sao2_valid_range
        )
    if "PR" in signals:
        signals["PR"] = interpolate_out_of_range(
            signals["PR"], *config.pr_valid_range
        )

    if not missing_fraction_ok(signals, config.max_nan_fraction):
        return None, IngestReport(
            patient_id, edf_path, excluded="excessive missing values/artifacts"
        )

    events = parse_xml_annotations(
        xml_path, stop_at_first_stage_event=config.stop_at_first_stage_event
    )
    if events.recording_duration_s < config.min_sleep_time_s:
        return None, IngestReport(
            patient_id,
            edf_path,
            excluded=(
                f"recording duration {events.recording_duration_s:.0f}s "
                f"< {config.min_sleep_time_s:.0f}s"
            ),
        )

    # FFT resample every channel to the target rate (:158-164).  The
    # result is pinned to float32 at the call site: the FFT itself runs
    # in double precision (numpy has no single-precision FFT) but only
    # as per-channel scratch — letting a float64 channel survive to the
    # stack below would double the per-recording window memory and leak
    # float64 into the L1 artifact (dtype hygiene pinned by
    # tests/test_data_ingest.py::TestIngestRecording::test_float32_end_to_end).
    resampled = {}
    for ch in channels:
        sig = signals[ch]
        target_len = int(len(sig) * (config.target_rate_hz / rates[ch]))
        resampled[ch] = fft_resample(sig, target_len).astype(
            np.float32, copy=False
        )

    # Cut full windows at stride (window - overlap); trailing partial
    # window dropped (:208-220; overlap_size honored as at :194,211).
    samples_per_window = int(round(config.window_size_s * config.target_rate_hz))
    stride_s = config.window_size_s - config.overlap_s
    if stride_s <= 0:
        raise ValueError(
            f"overlap_s ({config.overlap_s}) must be smaller than "
            f"window_size_s ({config.window_size_s})"
        )
    stride_samples = int(round(stride_s * config.target_rate_hz))
    min_len = min(len(v) for v in resampled.values())
    n_windows = (
        (min_len - samples_per_window) // stride_samples + 1
        if min_len >= samples_per_window
        else 0
    )
    if n_windows == 0:
        return None, IngestReport(
            patient_id, edf_path, excluded="recording shorter than one window"
        )
    stacked = np.stack(
        [resampled[ch][:min_len] for ch in channels], axis=-1
    ).astype(np.float32)                              # (min_len, C)
    starts = np.arange(n_windows) * stride_samples
    idx = starts[:, None] + np.arange(samples_per_window)[None, :]
    x = stacked[idx]                                  # (n_windows, spw, C)

    labels = label_windows(
        n_windows,
        config.window_size_s,
        events,
        concepts=config.apnea_event_concepts,
        min_overlap_s=config.min_event_overlap_s,
        stride_s=stride_s,
    )

    window_set = WindowSet(
        x=x,
        y=labels,
        patient_ids=np.full(n_windows, str(patient_id)),
        start_time_s=(starts / config.target_rate_hz).astype(np.int32),
        channels=channels,
    )
    return window_set, IngestReport(patient_id, edf_path, n_windows=n_windows)


def _nsrr_pair(edf_file: str) -> Tuple[str, str]:
    """(patient_id, xml_name) from an shhs2-<id>.edf file name
    (preprocess_shhs_raw.py:302-303)."""
    nsrr_id = edf_file.split("-")[1].split(".")[0]
    return nsrr_id, f"shhs2-{nsrr_id}-nsrr.xml"


def _error_detail(exc: Exception, tail_lines: int = 6) -> str:
    """``Type: message`` plus the traceback tail — a bare ``str(e)``
    (often just a filename, or empty) made three a.m. ingest triage
    impossible; the tail names the failing frame without shipping the
    whole stack into every report."""
    import traceback

    tail = traceback.format_exc().strip().splitlines()[-tail_lines:]
    return f"{type(exc).__name__}: {exc}\n" + "\n".join(tail)


def _run_ingest_job(
    job: Tuple[str, str, str], config: IngestConfig
) -> Tuple[Optional[WindowSet], IngestReport]:
    """One job with per-file containment (:316-318).  Module-level so the
    process-pool mode can pickle it."""
    edf_path, xml_path, patient_id = job
    try:
        return ingest_recording(edf_path, xml_path, patient_id, config)
    except Exception as e:
        return None, IngestReport(patient_id, edf_path,
                                  error=_error_detail(e))


def list_ingest_jobs(
    edf_folder: str,
    xml_folder: str,
    *,
    num_files: Optional[int] = None,
) -> List[Tuple[str, str, str]]:
    """Deterministic (edf_path, xml_path, patient_id) job list: sorted by
    EDF file name, capped at ``num_files`` — shared by the in-memory and
    store ingest paths so both process the same recordings in the same
    order."""
    jobs = []
    for edf_file in sorted(os.listdir(edf_folder)):
        if num_files is not None and len(jobs) >= num_files:
            break
        if not edf_file.endswith(".edf"):
            continue
        try:
            patient_id, xml_name = _nsrr_pair(edf_file)
        except IndexError:
            continue
        xml_path = os.path.join(xml_folder, xml_name)
        if not os.path.exists(xml_path):
            continue
        jobs.append((os.path.join(edf_folder, edf_file), xml_path, patient_id))
    return jobs


def _job_results(jobs, config: IngestConfig, workers: int, mode: str):
    """Iterate (window_set, report) per job, IN JOB ORDER regardless of
    worker scheduling, so every ingest mode produces identical report
    lists and shard sequences.

    ``mode='thread'`` suits the GIL-releasing NumPy decode path;
    ``mode='process'`` side-steps the GIL entirely for the CPU-bound
    EDF-decode + FFT-resample pipeline (jobs and the frozen config
    pickle).  Process workers use the ``spawn`` start method: this
    module transitively imports jax (a multithreaded runtime), and
    fork()ing a threaded parent can deadlock a worker on an inherited
    lock.  Submission is a bounded sliding window — ``Executor.map``
    would submit everything up front and buffer every completed result
    the consumer hasn't reached — so at most ``workers + 1`` decoded
    recordings exist ahead of the consumer and the store ingest's
    O(one recording) memory bound survives a slow shard writer."""
    if workers <= 0:
        for job in jobs:
            yield _run_ingest_job(job, config)
        return
    if mode == "thread":
        from concurrent.futures import ThreadPoolExecutor

        pool = ThreadPoolExecutor(max_workers=workers)
    elif mode == "process":
        import multiprocessing
        from concurrent.futures import ProcessPoolExecutor

        pool = ProcessPoolExecutor(
            max_workers=workers,
            mp_context=multiprocessing.get_context("spawn"),
        )
    else:
        raise ValueError(f"mode must be 'thread' or 'process', got {mode!r}")
    import collections

    with pool:
        it = iter(jobs)
        pending: collections.deque = collections.deque()

        def submit_next() -> None:
            job = next(it, None)
            if job is not None:
                pending.append(pool.submit(_run_ingest_job, job, config))

        for _ in range(workers + 1):
            submit_next()
        while pending:
            result = pending.popleft().result()
            submit_next()
            yield result


def ingest_directory(
    edf_folder: str,
    xml_folder: str,
    config: IngestConfig = IngestConfig(),
    *,
    num_files: Optional[int] = None,
    workers: int = 0,
    mode: str = "thread",
) -> Tuple[Optional[WindowSet], List[IngestReport]]:
    """All EDF/XML pairs under two folders -> one combined WindowSet
    (process_all_files, preprocess_shhs_raw.py:290-326).

    ``num_files`` limits the number of processed recordings (the
    reference's --num_files dry-run flag, :19-26).  ``workers`` > 0
    decodes recordings in a pool — ``mode='thread'`` (EDF decode and FFT
    resample are NumPy calls that release the GIL) or ``mode='process'``
    (fully GIL-free; CPU-bound decode parallelizes across cores); 0
    keeps the reference's sequential order.  Results are consumed in job
    order in every mode.

    This path materializes the combined set in host RAM — O(dataset).
    For SHHS2-scale ingests use :func:`ingest_directory_to_store`, which
    streams each recording straight into a sharded memmap store and
    keeps peak host memory at O(one recording).
    """
    jobs = list_ingest_jobs(edf_folder, xml_folder, num_files=num_files)
    results = list(_job_results(jobs, config, workers, mode))
    reports = [r for _, r in results]
    sets = [ws for ws, _ in results if ws is not None]
    if not sets:
        return None, reports
    return WindowSet.concat_all(sets), reports


def windows_from_store(store, *, mmap: bool = False) -> WindowSet:
    """A :class:`WindowSet` from a sharded windows store (either shape:
    the streaming ingest's layout with channels in manifest ``meta``, or
    a migrated ``.npz`` bundle carrying ``channels`` as an extra array).
    ``mmap=True`` keeps ``x`` lazy; labels/ids/starts materialize (they
    are O(rows) scalars the in-core consumers index freely)."""
    channels = store.extra_arrays.get("channels")
    if channels is not None:
        channels = tuple(np.asarray(channels["values"]).astype(str))
    else:
        channels = tuple(str(c) for c in store.meta.get("channels", ()))
    if not channels:
        raise ValueError(
            f"store at {store.directory} carries no channel names "
            f"(neither a 'channels' extra array nor manifest meta)"
        )
    n = store.rows
    start = (store.read("start_time_s", mmap=False)
             if "start_time_s" in store.fields
             else np.zeros(n, np.int32))
    return WindowSet(
        x=store.read("x", mmap=mmap),
        y=np.asarray(store.read("y", mmap=False)),
        patient_ids=np.asarray(
            store.read("patient_ids", mmap=False)).astype(str),
        start_time_s=np.asarray(start),
        channels=channels,
    )


# -- out-of-core ingest: recordings -> sharded memmap store ---------------

INGEST_PROGRESS_NAME = "ingest_progress.json"

# Fixed-width patient-id dtype so every shard shares one schema (per-
# recording ``np.full(n, str(id))`` infers a width from that id alone).
_PATIENT_ID_DTYPE = "U32"


def _progress_path(store_dir: str) -> str:
    return os.path.join(store_dir, INGEST_PROGRESS_NAME)


def read_ingest_progress(store_dir: str) -> Dict[str, Dict]:
    """{patient_id: completion record} of a (possibly interrupted) store
    ingest; tolerates a missing/torn/corrupt file (fresh start) via the
    shared tolerant reader the conc gate's torn-read rule enforces."""
    from apnea_uq_tpu.utils.io import read_json_tolerant

    doc = read_json_tolerant(_progress_path(store_dir), default={})
    if not isinstance(doc, dict):
        return {}
    completed = doc.get("completed", {})
    return completed if isinstance(completed, dict) else {}


def _write_ingest_progress(store_dir: str, completed: Dict[str, Dict]) -> None:
    from apnea_uq_tpu.data.store import atomic_write_json

    atomic_write_json(_progress_path(store_dir),
                       {"version": 1, "completed": completed})


def ingest_directory_to_store(
    edf_folder: str,
    xml_folder: str,
    store_dir: str,
    config: IngestConfig = IngestConfig(),
    *,
    num_files: Optional[int] = None,
    workers: int = 0,
    mode: str = "thread",
    resume: bool = True,
    run_log=None,
):
    """Stream every EDF/XML pair straight into a sharded memmap store
    (data/store.py): one shard per included recording, written and
    committed the moment the recording decodes, so peak host memory is
    O(one recording) — not O(dataset) like :func:`ingest_directory` —
    and CPU-bound decode+resample parallelizes across cores in
    ``mode='process'`` (at most ``workers`` recordings buffer ahead of
    the shard writer).

    Resumable by construction: a per-recording progress manifest
    (``ingest_progress.json``, atomic-replace) records each completed
    recording next to the store's own shard manifest.  A ``kill -9``
    mid-recording loses at most the shard in flight (the store writer
    deletes uncommitted files on reopen — no torn shard survives), and a
    rerun with ``resume=True`` (default) skips completed recordings and
    retries only errored ones.

    Returns ``(ArrayStore | None, reports)`` — the store holds fields
    ``x``/``y``/``patient_ids``/``start_time_s`` with the channel tuple
    in its manifest ``meta``; reports cover every job including resumed
    ones.  Progress is mirrored as ``ingest_progress`` telemetry events
    on ``run_log`` (default: the active run, if any).
    """
    import time

    from apnea_uq_tpu.data.store import ArrayStore, StoreWriter, peak_rss_bytes

    if run_log is None:
        from apnea_uq_tpu.telemetry.runlog import current_run

        run_log = current_run()

    jobs = list_ingest_jobs(edf_folder, xml_folder, num_files=num_files)
    if not resume:
        # Clear progress BEFORE resetting the store: a kill between the
        # two leaves empty progress + old shards, which the reconcile
        # below re-adopts from the store manifest — never the reverse
        # gap (reset store + stale progress), where a later resumed run
        # would skip recordings whose shards are gone.
        os.makedirs(store_dir, exist_ok=True)
        _write_ingest_progress(store_dir, {})
    writer = StoreWriter(
        store_dir, resume=resume,
        meta={"channels": list(config.channels),
              "window_size_s": config.window_size_s},
    )
    completed = read_ingest_progress(store_dir) if resume else {}
    # Reconcile progress against the store's own shard manifest, both
    # directions:
    # 1. Drop stale records whose shard no longer exists (or holds a
    #    different patient) — trusting them would silently skip a
    #    recording whose data is gone; the rerun re-ingests it instead.
    shard_patient = {
        i: rng[0] for i, rng in enumerate(writer.patient_ranges())
        if rng is not None
    }
    for pid, rec in list(completed.items()):
        si = rec.get("shard")
        if si is not None and shard_patient.get(si) != pid:
            del completed[pid]
    # 2. Adopt committed shards the progress file doesn't know about (a
    #    kill between a shard commit and its progress commit) — the
    #    shard IS the recording's data; re-ingesting would duplicate it.
    for i, pid in shard_patient.items():
        rec = completed.get(pid)
        if rec is None or rec.get("shard") is None:
            completed[pid] = {
                "n_windows": writer.shard_rows(i),
                "excluded": None, "error": None, "shard": i,
            }
    _write_ingest_progress(store_dir, completed)

    reports: List[IngestReport] = []
    pending = []
    skipped = 0
    for job in jobs:
        edf_path, _xml, patient_id = job
        prior = completed.get(patient_id)
        if prior is not None and prior.get("error") is None:
            # Included or excluded on a previous run: its shard (if any)
            # is already committed; reconstruct the report and move on.
            skipped += 1
            reports.append(IngestReport(
                patient_id, edf_path,
                n_windows=int(prior.get("n_windows", 0)),
                excluded=prior.get("excluded"),
            ))
        else:
            pending.append(job)

    t0 = time.perf_counter()
    rows_written = 0
    bytes_written = 0
    done = skipped
    total = len(jobs)
    for (edf_path, _xml, patient_id), (ws, report) in zip(
        pending, _job_results(pending, config, workers, mode)
    ):
        record: Dict[str, Optional[str]] = {
            "n_windows": report.n_windows,
            "excluded": report.excluded,
            "error": report.error,
        }
        if ws is not None:
            if tuple(ws.channels) != tuple(config.channels):
                raise ValueError(
                    f"recording {patient_id} decoded channels "
                    f"{ws.channels}, store expects {tuple(config.channels)}"
                )
            shard = {
                "x": ws.x.astype(np.float32, copy=False),
                "y": ws.y,
                "patient_ids": ws.patient_ids.astype(_PATIENT_ID_DTYPE),
                "start_time_s": ws.start_time_s,
            }
            record["shard"] = writer.append_shard(
                shard, patient_range=(patient_id, patient_id)
            )
            rows_written += len(ws)
            bytes_written += sum(np.asarray(a).nbytes for a in shard.values())
        completed[patient_id] = record
        # Progress commits AFTER the shard commit.  A kill in the gap
        # leaves one committed shard the progress file doesn't know
        # about; the rerun would append a duplicate — which the
        # per-patient shard check at finalize time detects loudly.
        _write_ingest_progress(store_dir, completed)
        reports.append(report)
        done += 1
        if run_log is not None:
            elapsed = max(time.perf_counter() - t0, 1e-9)
            run_log.event(
                "ingest_progress", done=done, total=total, skipped=skipped,
                rows=rows_written, rows_per_s=round(rows_written / elapsed, 3),
                bytes_written=bytes_written, rss_bytes=peak_rss_bytes(),
            )
    if run_log is not None and jobs and not pending:
        # A fully-resumed run processes nothing; still record the outcome
        # (every recording skipped) so the run's summary isn't silent.
        run_log.event(
            "ingest_progress", done=done, total=total, skipped=skipped,
            rows=0, rows_per_s=0.0, bytes_written=0,
            rss_bytes=peak_rss_bytes(),
        )

    if writer.num_shards == 0:
        return None, reports
    store = writer.finalize()
    _check_no_duplicate_shards(store)
    return store, reports


def _check_no_duplicate_shards(store) -> None:
    """Belt-and-braces invariant check at finalize: the reconcile loop
    above adopts any shard whose progress record was lost, so no rerun
    should ever append a second shard for a patient — if one exists
    anyway (hand-edited progress file, two concurrent ingests), fail
    loudly instead of silently double-counting a patient's windows."""
    seen = {}
    for i, rng in enumerate(store.patient_ranges()):
        if rng is None:
            continue
        pid = rng[0]
        if pid in seen:
            raise ValueError(
                f"store holds duplicate shards ({seen[pid]} and {i}) for "
                f"patient {pid} — concurrent or inconsistently-resumed "
                f"ingests; delete the store directory and re-run"
            )
        seen[pid] = i


# -- reference CSV interop ------------------------------------------------

def _flat_columns(channels: Sequence[str], window: int) -> List[str]:
    # Time-major interleaved order, matching the reference's C-order
    # flatten of a (window, channels) frame (preprocess_shhs_raw.py:204,229).
    return [f"{ch}_t{t}" for t in range(window) for ch in channels]


def windows_to_reference_csv(
    windows: WindowSet, path: str, *, window_duration_s: Optional[float] = None
) -> None:
    """Emit the reference's flattened schema (SHHS2_ID_all_60.csv):
    {ch}_t{t} feature columns + Start_Time, End_Time, Apnea/Hypopnea,
    Patient_ID (preprocess_shhs_raw.py:204,253-256).

    ``window_duration_s`` defaults to the per-window sample count — exact
    at the standard 1 Hz target rate; pass it explicitly when ingesting
    at another rate so End_Time stays in seconds.
    """
    import pandas as pd

    n, window, c = windows.x.shape
    frame = pd.DataFrame(
        windows.x.reshape(n, window * c),
        columns=_flat_columns(windows.channels, window),
    )
    duration = window if window_duration_s is None else window_duration_s
    frame["Start_Time"] = windows.start_time_s
    frame["End_Time"] = windows.start_time_s + duration
    frame[LABEL_COL] = windows.y
    frame[GROUP_COL] = windows.patient_ids
    frame.to_csv(path, index=False)


def windows_from_reference_csv(
    path: str,
    channels: Sequence[str] = ("SaO2", "PR", "THOR RES", "ABDO RES"),
    window: int = 60,
) -> WindowSet:
    """Load a reference-format flattened CSV into a WindowSet
    (the prepare_numpy_datasets.py:114,134-136 consumer side)."""
    import pandas as pd

    frame = pd.read_csv(path)
    cols = _flat_columns(channels, window)
    missing = [c for c in cols + [LABEL_COL, GROUP_COL] if c not in frame.columns]
    if missing:
        raise ValueError(f"CSV {path} is missing columns, e.g. {missing[:4]}")
    x = frame[cols].to_numpy(dtype=np.float32).reshape(len(frame), window, len(channels))
    start = (
        frame["Start_Time"].to_numpy(dtype=np.int32)
        if "Start_Time" in frame.columns
        else np.zeros(len(frame), dtype=np.int32)
    )
    return WindowSet(
        x=x,
        y=frame[LABEL_COL].to_numpy(dtype=np.int8),
        patient_ids=frame[GROUP_COL].to_numpy(dtype=np.str_).astype(str),
        start_time_s=start,
        channels=tuple(channels),
    )
