"""Patient-grouped splitting and class rebalancing (SMOTE / RUS).

The reference delegates these to scikit-learn / imbalanced-learn
(prepare_numpy_datasets.py:3-5,140,185,207).  All three are in-tree here
— the grouped split as a bit-identical GroupShuffleSplit replica, SMOTE
and random undersampling from the algorithm definitions — keeping
sklearn/imblearn out of the runtime dependency set.  SMOTE's O(n^2) minority k-NN search — the one
compute-heavy step — runs on device as chunked matmul distance blocks +
``lax.top_k`` (MXU-shaped), with the synthesis step staying in host
NumPy where the rest of the data pipeline lives.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import numpy as np


def grouped_train_test_split(
    groups: np.ndarray,
    *,
    test_size: float = 0.2,
    seed: int = 2025,
) -> Tuple[np.ndarray, np.ndarray]:
    """(train_idx, test_idx) with no group straddling the boundary.

    In-tree replica of sklearn's GroupShuffleSplit as used at
    prepare_numpy_datasets.py:140-142, bit-identical for any given seed
    (verified against sklearn in tests/test_data_sampling.py): test_size
    is a fraction of *unique groups* (ceil for test, floor for train),
    drawn by a ``RandomState(seed)`` permutation of the sorted unique
    groups — so a seed-2025 split here selects exactly the patients the
    reference's split did.
    """
    if not 0.0 < test_size < 1.0:
        raise ValueError(f"test_size must be in (0, 1), got {test_size}")
    classes, group_indices = np.unique(np.asarray(groups), return_inverse=True)
    n_groups = classes.shape[0]
    n_test = int(np.ceil(test_size * n_groups))
    # sklearn sizes train as the complement (not floor((1-t)*n), which can
    # land one short under float rounding and silently drop a group).
    n_train = n_groups - n_test
    if n_train <= 0:
        # sklearn raises here too; a silent empty train set would NaN the
        # downstream standardization instead of failing loudly.
        raise ValueError(
            f"test_size={test_size} leaves no training groups "
            f"({n_groups} unique groups, {n_test} assigned to test)"
        )
    permutation = np.random.RandomState(seed).permutation(n_groups)
    test_groups = permutation[:n_test]
    train_groups = permutation[n_test : n_test + n_train]
    train_idx = np.flatnonzero(np.isin(group_indices, train_groups))
    test_idx = np.flatnonzero(np.isin(group_indices, test_groups))
    return train_idx, test_idx


def verify_no_group_overlap(
    groups: np.ndarray, train_idx: np.ndarray, test_idx: np.ndarray
) -> None:
    """Raise if any group appears on both sides (the reference only
    printed a warning, prepare_numpy_datasets.py:156-160)."""
    overlap = np.intersect1d(
        np.unique(groups[train_idx]), np.unique(groups[test_idx])
    )
    if overlap.size:
        raise ValueError(
            f"{overlap.size} patient group(s) appear in both train and test, "
            f"e.g. {overlap[:5].tolist()}"
        )


def _minority_knn(
    x_min: np.ndarray, k: int, *, chunk: int = 2048
) -> np.ndarray:
    """int32 (n_min, k) indices of each minority sample's k nearest
    minority neighbors (self excluded), squared-L2 metric.

    Distance blocks are |a|^2 + |b|^2 - 2 a.b^T — one (chunk, n) matmul
    per block, computed under jit so XLA fuses the norm/addition epilogue.
    """
    import jax
    import jax.numpy as jnp

    n = x_min.shape[0]
    k = min(k, n - 1)
    if k <= 0:
        return np.zeros((n, 0), dtype=np.int32)

    x = jnp.asarray(x_min, jnp.float32)
    sq = jnp.sum(x * x, axis=1)

    @partial(jax.jit, static_argnames=("k",))
    def block_topk(rows, row_sq, row_ids, k):
        # Full-f32 matmul: the TPU MXU's default single-pass bf16 dot
        # perturbs distances by ~0.4% relative, enough to flip near-tie
        # neighbor rankings vs the reference's exact sklearn kNN.  SMOTE
        # runs once per prepare, so the multi-pass cost is irrelevant.
        prod = jnp.matmul(rows, x.T, precision=jax.lax.Precision.HIGHEST)
        d = row_sq[:, None] + sq[None, :] - 2.0 * prod
        d = d.at[jnp.arange(rows.shape[0]), row_ids].set(jnp.inf)  # mask self
        _, idx = jax.lax.top_k(-d, k)
        return idx

    out = np.empty((n, k), dtype=np.int32)
    for start in range(0, n, chunk):
        stop = min(start + chunk, n)
        ids = jnp.arange(start, stop)
        out[start:stop] = np.asarray(
            block_topk(x[start:stop], sq[start:stop], ids, k)
        )
    return out


def smote_oversample(
    x: np.ndarray,
    y: np.ndarray,
    *,
    k_neighbors: int = 5,
    seed: int = 2025,
    knn_chunk: int = 2048,
) -> Tuple[np.ndarray, np.ndarray]:
    """SMOTE oversampling of the minority class to parity with the
    majority (the imblearn.SMOTE call at prepare_numpy_datasets.py:185-187).

    x is 2-D (samples, features) — the reference flattens (N, 60, 4)
    windows to 240-dim vectors first (:183).  Synthetic samples are
    x_i + u * (x_nn - x_i) with u ~ U(0, 1) and x_nn one of x_i's
    k nearest minority neighbors, appended after the original rows in
    imblearn's order.  Returns float and label arrays of the input dtypes.
    """
    x = np.asarray(x)
    y = np.asarray(y)
    if x.ndim != 2:
        raise ValueError(f"SMOTE expects 2-D features, got shape {x.shape}")
    classes, counts = np.unique(y, return_counts=True)
    if classes.size < 2:
        raise ValueError("SMOTE needs at least two classes")
    if classes.size > 2:
        raise ValueError(f"binary SMOTE only, got classes {classes.tolist()}")
    minority = classes[np.argmin(counts)]
    n_needed = int(counts.max() - counts.min())
    if n_needed == 0:
        return x.copy(), y.copy()

    min_idx = np.flatnonzero(y == minority)
    synthetic = smote_synthesize(
        x[min_idx], n_needed, k_neighbors=k_neighbors, seed=seed,
        knn_chunk=knn_chunk,
    )

    x_out = np.concatenate([x, synthetic.astype(x.dtype, copy=False)])
    y_out = np.concatenate([y, np.full(n_needed, minority, dtype=y.dtype)])
    return x_out, y_out


def smote_synthesize(
    x_min: np.ndarray,
    n_needed: int,
    *,
    k_neighbors: int = 5,
    seed: int = 2025,
    knn_chunk: int = 2048,
) -> np.ndarray:
    """All ``n_needed`` SMOTE synthetic rows as one array — the in-core
    convenience over :func:`iter_smote_synthetic`."""
    blocks = list(iter_smote_synthetic(
        x_min, n_needed, k_neighbors=k_neighbors, seed=seed,
        knn_chunk=knn_chunk, block_rows=max(n_needed, 1),
    ))
    if not blocks:
        x_min = np.asarray(x_min)
        return np.empty((0, x_min.shape[1]), np.float32)
    return np.concatenate(blocks)


def iter_smote_synthetic(
    x_min: np.ndarray,
    n_needed: int,
    *,
    k_neighbors: int = 5,
    seed: int = 2025,
    knn_chunk: int = 2048,
    block_rows: int = 65536,
):
    """The SMOTE synthesis core, factored so the out-of-core prepare path
    shares it bit-for-bit with :func:`smote_oversample`: given the 2-D
    minority rows alone (O(minority) memory — the majority never needs to
    be resident), return an iterator of float32 synthetic blocks whose
    concatenation equals the in-core path exactly.

    Validation, the minority kNN, and ALL RNG draws (base rows, neighbor
    columns, gaps — O(n_needed) scalars, not rows) happen eagerly before
    this returns, so a caller can separate "can SMOTE run?" errors from
    the block iteration; only the O(block_rows) row synthesis is lazy,
    which is what keeps the streamed prepare's peak memory off the
    majority-class count."""
    x_min = np.asarray(x_min).astype(np.float32, copy=False)
    if len(x_min) <= 1:
        raise ValueError(
            f"minority class has {len(x_min)} sample(s); "
            "SMOTE needs at least 2"
        )
    nn = _minority_knn(x_min, k_neighbors, chunk=knn_chunk)

    rng = np.random.default_rng(seed)
    base = rng.integers(0, len(x_min), n_needed)
    neighbor_col = rng.integers(0, nn.shape[1], n_needed)
    gaps = rng.random((n_needed, 1), dtype=np.float32)

    def blocks():
        for lo in range(0, n_needed, block_rows):
            hi = min(lo + block_rows, n_needed)
            b = base[lo:hi]
            x_base = x_min[b]
            x_nn = x_min[nn[b, neighbor_col[lo:hi]]]
            yield x_base + gaps[lo:hi] * (x_nn - x_base)

    return blocks()


def random_undersample(
    x: np.ndarray,
    y: np.ndarray,
    *,
    seed: int = 2025,
    extras: Tuple[np.ndarray, ...] = (),
) -> Tuple[np.ndarray, np.ndarray, Tuple[np.ndarray, ...]]:
    """Balance classes by subsampling each to the minority count without
    replacement (the RandomUnderSampler call at
    prepare_numpy_datasets.py:207-211).

    ``extras`` are additional per-row arrays (e.g. patient IDs) gathered
    with the same kept indices.  Rows keep their original relative order.
    """
    y = np.asarray(y)
    keep_idx = undersample_indices(y, seed=seed)
    return (
        np.asarray(x)[keep_idx],
        y[keep_idx],
        tuple(np.asarray(e)[keep_idx] for e in extras),
    )


def undersample_indices(y: np.ndarray, *, seed: int = 2025) -> np.ndarray:
    """The kept-row indices of :func:`random_undersample`, factored so
    the out-of-core prepare path can select rows by INDEX and stream
    them into result shards — identical draw, identical order, without
    the feature matrix ever being resident."""
    y = np.asarray(y)
    classes, counts = np.unique(y, return_counts=True)
    if classes.size < 2:
        raise ValueError(
            "random undersampling needs at least two classes "
            f"(got {classes.tolist()})"
        )
    n_keep = int(counts.min())
    rng = np.random.default_rng(seed)
    kept = []
    for cls in classes:
        cls_idx = np.flatnonzero(y == cls)
        kept.append(rng.choice(cls_idx, size=n_keep, replace=False))
    return np.sort(np.concatenate(kept))
