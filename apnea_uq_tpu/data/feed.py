"""Host -> device feed: double-buffered prefetch of batch streams.

The reference loads entire datasets into host memory and hands them to
Keras whole (cnn_baseline_train.py:145-158), and runs UQ inference with
the full test set as one batch (uq_techniques.py:22).  On TPU the
pattern is a bounded pipeline: while the device computes on batch i,
batch i+1 is already being transferred, so HBM holds a constant number
of batches and the ICI/PCIe transfer overlaps compute.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, Optional

import jax


def prefetch_to_device(
    batches: Iterable,
    *,
    size: int = 2,
    device: Optional[jax.Device] = None,
    sharding: Optional[jax.sharding.Sharding] = None,
) -> Iterator:
    """Yield device-resident copies of ``batches``, staying ``size``
    transfers ahead of the consumer.

    Each batch is a pytree of host arrays; leaves are `device_put` as a
    whole so nested dict/tuple batches work.  Pass ``sharding`` to place
    batches onto a mesh (e.g. batch-sharded over the 'data' axis) instead
    of a single device — transfers then overlap the same way per shard.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    target = sharding if sharding is not None else device
    queue: collections.deque = collections.deque()
    it = iter(batches)

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                batch = next(it)
            except StopIteration:
                return
            if target is None:
                queue.append(jax.device_put(batch))
            else:
                queue.append(jax.device_put(batch, target))

    enqueue(size)
    while queue:
        yield queue.popleft()
        enqueue(1)


# (The host-side batch construction deliberately lives with each consumer
# — the trainers and predictors build their own index streams so that the
# streamed paths share exact permutations/masks/RNG with the in-HBM jitted
# programs.  A generic batch iterator here would duplicate that without
# being usable by them.)
