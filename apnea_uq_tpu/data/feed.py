"""Host -> device feed: double-buffered prefetch of batch streams.

The reference loads entire datasets into host memory and hands them to
Keras whole (cnn_baseline_train.py:145-158), and runs UQ inference with
the full test set as one batch (uq_techniques.py:22).  On TPU the
pattern is a bounded pipeline: while the device computes on batch i,
batch i+1 is already being transferred, so HBM holds a constant number
of batches and the ICI/PCIe transfer overlaps compute.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, Optional

import jax


def prefetch_to_device(
    batches: Iterable,
    *,
    size: int = 2,
    device: Optional[jax.Device] = None,
    sharding: Optional[jax.sharding.Sharding] = None,
) -> Iterator:
    """Yield device-resident copies of ``batches``, staying ``size``
    transfers ahead of the consumer.

    Each batch is a pytree of host arrays; leaves are `device_put` as a
    whole so nested dict/tuple batches work.  Pass ``sharding`` to place
    batches onto a mesh (e.g. batch-sharded over the 'data' axis) instead
    of a single device — transfers then overlap the same way per shard.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    target = sharding if sharding is not None else device
    queue: collections.deque = collections.deque()
    it = iter(batches)

    def enqueue(n: int) -> None:
        for _ in range(n):
            try:
                batch = next(it)
            except StopIteration:
                return
            if target is None:
                queue.append(jax.device_put(batch))
            else:
                queue.append(jax.device_put(batch, target))

    enqueue(size)
    while queue:
        yield queue.popleft()
        enqueue(1)


def batch_iterator(
    arrays,
    batch_size: int,
    *,
    shuffle: bool = False,
    seed: int = 0,
    drop_remainder: bool = False,
) -> Iterator:
    """Mini-batches over a pytree of equal-length host arrays.

    The host-side half of the feed: pair with `prefetch_to_device` for
    the full pipeline.  Shuffling permutes indices once per call
    (epoch-level reshuffle = one call per epoch with a folded seed).
    """
    import numpy as np

    leaves = jax.tree.leaves(arrays)
    if not leaves:
        return
    n = len(leaves[0])
    for leaf in leaves:
        if len(leaf) != n:
            raise ValueError("all arrays must share the leading dimension")
    order = np.arange(n)
    if shuffle:
        np.random.default_rng(seed).shuffle(order)
    stop = n - (n % batch_size) if drop_remainder else n
    for start in range(0, stop, batch_size):
        idx = order[start : start + batch_size]
        yield jax.tree.map(lambda a: a[idx], arrays)
