"""Sharded, memory-mapped array store — the out-of-core data plane (L1/L2).

The reference pipeline (and the first eight PRs here) hands datasets
between stages as monolithic in-memory arrays: ``ingest_directory``
concatenates every recording in host RAM and ``registry.save_arrays`` /
``load_arrays`` round-trips the whole set through one compressed ``.npz``
— full materialization on every stage start.  At SHHS2 scale host memory,
not the TPU, becomes the ceiling.  This module replaces that shape:

- **On disk**: a directory of per-shard raw ``.npy`` files written via
  ``np.lib.format.open_memmap`` plus one JSON manifest recording row
  counts, shapes/dtypes, per-shard patient-id ranges and content hashes.
  Raw ``.npy`` (not ``.npz``) because the numpy format maps directly —
  a reader faults in only the pages it touches.
- **Writes are atomic per shard**: each field lands under a temp name,
  is flushed, renamed, and only then recorded in the manifest (the
  commit point, itself an atomic replace).  A ``kill -9`` mid-shard
  leaves stray temp/unreferenced files that the next writer cleans up —
  never a torn shard a reader could see.
- **Reads are zero-copy**: :class:`ShardedArray` presents the shard
  sequence as one lazy array (``shape``/``dtype``/``__getitem__``) whose
  row gathers materialize only the requested rows; contiguous slices
  stay lazy views.  The streamed trainers/predictors slice batches
  straight off it, so steady-state host RSS is O(prefetch × batch)
  independent of dataset rows — and on a multi-process mesh each process
  faults in only the pages its data-axis shards actually read (memmap
  laziness makes per-process shard mapping automatic).

Deliberately jax-free: like the registry, this is pure host-side plumbing
that must import in telemetry/CLI contexts where no backend exists.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

STORE_MANIFEST_NAME = "store_manifest.json"
DEFAULT_ROWS_PER_SHARD = 65536
_TMP_PREFIX = ".tmp-"


def peak_rss_bytes() -> Optional[int]:
    """This process's peak resident set size in bytes (Linux/macOS), or
    None where the ``resource`` module is unavailable.  Telemetry-grade:
    best-effort, never raises."""
    try:
        import resource
        import sys

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KiB on Linux, bytes on macOS.
        return int(rss) if sys.platform == "darwin" else int(rss) * 1024
    except Exception:
        return None


# The commit protocol this module established now lives in the shared
# jax-free home (utils/io.py) so every artifact writer — registry
# manifest, run-dir JSON, program blobs — routes through ONE
# implementation; re-exported here because the store's callers (ingest,
# tests) import it from this module's namespace.
from apnea_uq_tpu.utils.io import atomic_write_json  # noqa: F401  (re-export)


def _content_hash(a: np.ndarray) -> str:
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(a).tobytes())
    return f"sha256:{h.hexdigest()[:16]}"


class ShardedArray:
    """Read-only lazy concatenation of per-shard ``.npy`` memmaps.

    Presents ``shape``/``dtype``/``ndim``/``len`` like an ndarray.
    Integer-array indexing (``a[rows]``, any index shape) gathers and
    materializes ONLY the requested rows; a unit-step slice returns
    another lazy view sharing the open memmaps; ``np.asarray(a)``
    materializes the whole selection (the in-HBM consumers' path).
    Shard files open lazily and stay memory-mapped, so a view over a
    multi-GB store costs pages actually touched, not bytes on disk.
    """

    def __init__(self, paths: Sequence[str], counts: Sequence[int],
                 shape_tail: Tuple[int, ...], dtype,
                 start: int = 0, stop: Optional[int] = None,
                 _maps: Optional[list] = None):
        self._paths = list(paths)
        self._offsets = np.concatenate(
            [[0], np.cumsum(np.asarray(counts, np.int64))]
        )
        total = int(self._offsets[-1])
        if not 0 <= start <= total:
            raise ValueError(f"start {start} out of range [0, {total}]")
        self._start = int(start)
        self._stop = total if stop is None else int(stop)
        if not self._start <= self._stop <= total:
            raise ValueError(f"stop {stop} out of range [{start}, {total}]")
        self._tail = tuple(int(d) for d in shape_tail)
        self._dtype = np.dtype(dtype)
        # Views share the open-memmap cache with their parent, so slicing
        # never re-opens files.
        self._maps = [None] * len(self._paths) if _maps is None else _maps

    # -- array-likeness ---------------------------------------------------

    @property
    def shape(self) -> Tuple[int, ...]:
        return (self._stop - self._start,) + self._tail

    @property
    def dtype(self) -> np.dtype:
        return self._dtype

    @property
    def ndim(self) -> int:
        return 1 + len(self._tail)

    @property
    def nbytes(self) -> int:
        return int(np.prod(self.shape, dtype=np.int64)) * self._dtype.itemsize

    def __len__(self) -> int:
        return self._stop - self._start

    def __repr__(self) -> str:
        return (f"ShardedArray(shape={self.shape}, dtype={self._dtype}, "
                f"shards={len(self._paths)})")

    def _shard(self, i: int) -> np.ndarray:
        if self._maps[i] is None:
            self._maps[i] = np.load(self._paths[i], mmap_mode="r")
        return self._maps[i]

    def _gather(self, rows: np.ndarray) -> np.ndarray:
        """Materialize the requested rows (any integer-index shape)."""
        rows = np.asarray(rows)
        flat = rows.reshape(-1).astype(np.int64, copy=True)
        n = len(self)
        if flat.size:
            if flat.min() < -n or flat.max() >= n:
                raise IndexError(
                    f"row index out of range for length-{n} ShardedArray"
                )
            flat[flat < 0] += n
        flat += self._start
        out = np.empty((flat.size,) + self._tail, self._dtype)
        shard_idx = np.searchsorted(self._offsets, flat, side="right") - 1
        for si in np.unique(shard_idx):
            m = shard_idx == si
            out[m] = self._shard(int(si))[flat[m] - self._offsets[si]]
        return out.reshape(rows.shape + self._tail)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            lo, hi, step = idx.indices(len(self))
            if step == 1:
                return ShardedArray(
                    self._paths, np.diff(self._offsets),
                    self._tail, self._dtype,
                    start=self._start + lo, stop=self._start + max(hi, lo),
                    _maps=self._maps,
                )
            return self._gather(np.arange(lo, hi, step))
        if isinstance(idx, (int, np.integer)):
            return self._gather(np.asarray([idx]))[0]
        idx = np.asarray(idx)
        if idx.dtype == bool:
            idx = np.flatnonzero(idx)
        return self._gather(idx)

    def __array__(self, dtype=None, copy=None):
        out = self._gather(np.arange(len(self)))
        if dtype is not None and np.dtype(dtype) != self._dtype:
            out = out.astype(dtype)
        return out

    def __iter__(self) -> Iterator[np.ndarray]:
        for i in range(len(self)):
            yield self[i]

    def iter_blocks(self, block_rows: int) -> Iterator[Tuple[int, np.ndarray]]:
        """Yield ``(start_row, materialized block)`` over the whole view —
        the out-of-core scan primitive (each block is O(block_rows))."""
        if block_rows < 1:
            raise ValueError(f"block_rows must be >= 1, got {block_rows}")
        for lo in range(0, len(self), block_rows):
            hi = min(lo + block_rows, len(self))
            yield lo, self._gather(np.arange(lo, hi))


def as_host_source(x, dtype=np.float32):
    """Normalize a host-side batch source WITHOUT materializing it.

    A :class:`ShardedArray` (or an ndarray/memmap already at ``dtype``)
    passes through untouched — the streamed feeds then gather only the
    rows of each batch, which is what keeps host RSS O(batch) over a
    memmap-backed dataset.  Anything else falls back to
    ``np.asarray(x, dtype)`` (a view when dtypes already match, the
    historical copy otherwise)."""
    dtype = np.dtype(dtype)
    if isinstance(x, ShardedArray):
        if x.dtype == dtype:
            return x
        # Wrong-dtype lazy source: materialize (correctness over economy;
        # the pipeline writes float32 stores, so this is the escape hatch,
        # not the path).
        return np.asarray(x, dtype)
    return np.asarray(x, dtype)


class StoreWriter:
    """Appends shards to (or resumes) an on-disk sharded array store.

    The first ``append_shard`` fixes the field schema (names, trailing
    shapes, dtypes); every later shard must match.  Each shard's files
    are written whole, flushed, renamed into place, and only then
    recorded in the manifest — the atomic commit point.  Opening a
    writer over an interrupted store keeps every committed shard and
    deletes stray uncommitted files, so ``kill -9`` mid-shard costs at
    most the shard in flight.
    """

    def __init__(self, directory: str, *, resume: bool = True,
                 meta: Optional[Dict[str, Any]] = None):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._manifest_path = os.path.join(directory, STORE_MANIFEST_NAME)
        if resume and os.path.exists(self._manifest_path):
            with open(self._manifest_path) as f:
                self._manifest = json.load(f)
            if meta:
                self._manifest.setdefault("meta", {}).update(meta)
        else:
            self._manifest = {
                "version": 1, "complete": False,
                "fields": {}, "meta": dict(meta or {}), "shards": [],
            }
            self._commit()
        self._clean_uncommitted()

    # -- internals --------------------------------------------------------

    def _commit(self) -> None:
        atomic_write_json(self._manifest_path, self._manifest)

    def _committed_files(self) -> set:
        return {
            fname
            for shard in self._manifest["shards"]
            for fname in shard["files"].values()
        }

    def _clean_uncommitted(self) -> None:
        """Delete shard files a dead writer left behind uncommitted — a
        torn shard must never survive into a reader's view."""
        keep = self._committed_files() | {STORE_MANIFEST_NAME}
        for name in os.listdir(self.directory):
            if name in keep or not name.endswith(".npy"):
                continue
            if name.startswith(_TMP_PREFIX) or name.startswith("shard-"):
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass

    # -- API --------------------------------------------------------------

    @property
    def num_shards(self) -> int:
        return len(self._manifest["shards"])

    @property
    def rows(self) -> int:
        return sum(s["rows"] for s in self._manifest["shards"])

    def shard_rows(self, i: int) -> int:
        return int(self._manifest["shards"][i]["rows"])

    def patient_ranges(self) -> List[Optional[Tuple[str, str]]]:
        return [
            tuple(s["patient_range"]) if s.get("patient_range") else None
            for s in self._manifest["shards"]
        ]

    def append_shard(self, arrays: Dict[str, np.ndarray], *,
                     patient_range: Optional[Tuple[str, str]] = None) -> int:
        """Write one shard (a dict of equal-leading-dim arrays) and commit
        it to the manifest.  Returns the shard index."""
        if not arrays:
            raise ValueError("cannot append an empty shard")
        rows = {name: int(np.shape(a)[0]) for name, a in arrays.items()}
        if len(set(rows.values())) != 1:
            raise ValueError(f"shard arrays disagree on row count: {rows}")
        n_rows = next(iter(rows.values()))
        if n_rows == 0:
            raise ValueError("cannot append a zero-row shard")

        fields = self._manifest["fields"]
        if fields:
            if set(arrays) != set(fields):
                raise ValueError(
                    f"shard fields {sorted(arrays)} != store schema "
                    f"{sorted(fields)}"
                )
        for name, a in arrays.items():
            a = np.asarray(a)
            tail = list(a.shape[1:])
            dtype = str(a.dtype)
            spec = fields.get(name)
            if spec is None:
                fields[name] = {"shape": tail, "dtype": dtype}
            elif spec["shape"] != tail or spec["dtype"] != dtype:
                raise ValueError(
                    f"shard field {name!r} is {tail}/{dtype}, store schema "
                    f"says {spec['shape']}/{spec['dtype']}"
                )

        idx = self.num_shards
        files: Dict[str, str] = {}
        hashes: Dict[str, str] = {}
        for name, a in arrays.items():
            a = np.ascontiguousarray(a)
            safe = name.replace(os.sep, "_")
            final = f"shard-{idx:05d}.{safe}.npy"
            tmp = os.path.join(self.directory, _TMP_PREFIX + final)
            mm = np.lib.format.open_memmap(
                tmp, mode="w+", dtype=a.dtype, shape=a.shape
            )
            mm[:] = a
            mm.flush()
            del mm
            os.replace(tmp, os.path.join(self.directory, final))
            files[name] = final
            hashes[name] = _content_hash(a)
        entry: Dict[str, Any] = {
            "rows": n_rows, "files": files, "hashes": hashes,
        }
        if patient_range is not None:
            entry["patient_range"] = [str(patient_range[0]),
                                      str(patient_range[1])]
        self._manifest["shards"].append(entry)
        self._commit()  # the commit point: shard is now visible
        return idx

    def finalize(self, *, meta: Optional[Dict[str, Any]] = None) -> "ArrayStore":
        if meta:
            self._manifest.setdefault("meta", {}).update(meta)
        self._manifest["complete"] = True
        self._commit()
        return ArrayStore.open(self.directory)


class ArrayStore:
    """Read side of a sharded store directory (see module docstring)."""

    def __init__(self, directory: str, manifest: Dict[str, Any]):
        self.directory = directory
        self.manifest = manifest

    @classmethod
    def open(cls, directory: str) -> "ArrayStore":
        path = os.path.join(directory, STORE_MANIFEST_NAME)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"no {STORE_MANIFEST_NAME} under {directory!r} — not a "
                f"sharded array store"
            )
        with open(path) as f:
            return cls(directory, json.load(f))

    @property
    def fields(self) -> Dict[str, Dict[str, Any]]:
        return self.manifest["fields"]

    @property
    def meta(self) -> Dict[str, Any]:
        return self.manifest.get("meta", {})

    @property
    def extra_arrays(self) -> Dict[str, Dict[str, Any]]:
        """Small non-row-aligned arrays carried whole in the manifest
        (e.g. the windows bundle's ``channels`` — (n_channels,) next to
        (N, ...) fields), so a migrated ``.npz`` artifact loses nothing:
        ``{name: {"values": [...], "dtype": str}}``."""
        return self.meta.get("extra_arrays", {})

    @property
    def rows(self) -> int:
        return sum(s["rows"] for s in self.manifest["shards"])

    @property
    def num_shards(self) -> int:
        return len(self.manifest["shards"])

    @property
    def nbytes(self) -> int:
        total = 0
        for name, spec in self.fields.items():
            per_row = int(np.prod(spec["shape"], dtype=np.int64)
                          if spec["shape"] else 1)
            total += self.rows * per_row * np.dtype(spec["dtype"]).itemsize
        return total

    def patient_ranges(self) -> List[Optional[Tuple[str, str]]]:
        return [
            tuple(s["patient_range"]) if s.get("patient_range") else None
            for s in self.manifest["shards"]
        ]

    def read(self, name: str, *, mmap: bool = True):
        """One field across every shard: a lazy :class:`ShardedArray`
        (memmap-backed; ``mmap=True``) or the materialized ndarray.
        Manifest-carried extra arrays come back as plain ndarrays."""
        spec = self.fields.get(name)
        if spec is None:
            extra = self.extra_arrays.get(name)
            if extra is not None:
                return np.asarray(extra["values"],
                                  dtype=np.dtype(extra["dtype"]))
            raise KeyError(
                f"field {name!r} not in store at {self.directory} "
                f"(have: {sorted(self.fields) + sorted(self.extra_arrays)})"
            )
        shards = self.manifest["shards"]
        paths = [os.path.join(self.directory, s["files"][name])
                 for s in shards]
        counts = [s["rows"] for s in shards]
        if not shards:
            return np.empty((0,) + tuple(spec["shape"]),
                            np.dtype(spec["dtype"]))
        arr = ShardedArray(paths, counts, tuple(spec["shape"]),
                           spec["dtype"])
        return arr if mmap else np.asarray(arr)

    def arrays(self, names: Optional[Sequence[str]] = None, *,
               mmap: bool = True) -> Dict[str, Any]:
        if names is None:
            names = list(self.fields) + list(self.extra_arrays)
        return {name: self.read(name, mmap=mmap) for name in list(names)}

    def verify(self) -> None:
        """Recompute every shard file's content hash against the manifest;
        raises ValueError naming the first mismatch (bit rot, torn write
        that somehow got committed, manual tampering)."""
        for i, shard in enumerate(self.manifest["shards"]):
            for name, fname in shard["files"].items():
                a = np.load(os.path.join(self.directory, fname),
                            mmap_mode="r")
                got = _content_hash(np.asarray(a))
                want = shard["hashes"][name]
                if got != want:
                    raise ValueError(
                        f"content hash mismatch for shard {i} field "
                        f"{name!r} ({fname}): manifest {want}, disk {got}"
                    )


def write_store(
    directory: str,
    arrays: Dict[str, np.ndarray],
    *,
    rows_per_shard: int = DEFAULT_ROWS_PER_SHARD,
    meta: Optional[Dict[str, Any]] = None,
    patient_id_field: Optional[str] = None,
) -> ArrayStore:
    """Write in-memory arrays as a fresh sharded store (the migrate /
    save path).  ``patient_id_field`` names the per-row id array used to
    stamp each shard's patient range.

    Arrays whose leading dimension disagrees with the dominant (largest)
    array's row count are not row-aligned data (e.g. the windows
    bundle's per-channel name list) — they ride whole in the manifest as
    ``extra_arrays`` and read back via :meth:`ArrayStore.read` like any
    field, so migrating a mixed ``.npz`` bundle is lossless."""
    if rows_per_shard < 1:
        raise ValueError(f"rows_per_shard must be >= 1, got {rows_per_shard}")
    arrays = {name: np.asarray(a) for name, a in arrays.items()}
    n = 0
    if arrays:
        anchor = max(arrays.values(), key=lambda a: a.nbytes)
        n = int(anchor.shape[0]) if anchor.ndim else 0
    extras = {
        name: {"values": a.tolist(), "dtype": str(a.dtype)}
        for name, a in arrays.items()
        if a.ndim == 0 or int(a.shape[0]) != n
    }
    if extras:
        meta = dict(meta or {})
        meta.setdefault("extra_arrays", {}).update(extras)
        arrays = {name: a for name, a in arrays.items()
                  if name not in extras}
    # A fresh write replaces any previous store at this path wholesale.
    if os.path.exists(os.path.join(directory, STORE_MANIFEST_NAME)):
        import shutil

        shutil.rmtree(directory)
    writer = StoreWriter(directory, resume=False, meta=meta)
    for lo in range(0, n, rows_per_shard):
        hi = min(lo + rows_per_shard, n)
        block = {name: np.asarray(a[lo:hi]) for name, a in arrays.items()}
        prange = None
        if patient_id_field is not None and patient_id_field in block:
            # np.min/max lack a ufunc loop for unicode dtypes; go through
            # Python strings (shard-sized lists, negligible).
            ids = sorted(block[patient_id_field].astype(str).tolist())
            prange = (ids[0], ids[-1])
        writer.append_shard(block, patient_range=prange)
    return writer.finalize()
