"""Host-side data layer: ingestion, finalization, artifacts, device feed.

Replaces the reference's two preprocessing scripts
(data_prepocessing/preprocess_shhs_raw.py, prepare_numpy_datasets.py) and
their file-name drift (SURVEY §1) with one versioned artifact registry and
library-grade stages.

Lazy exports: the artifact registry is imported by jax-free contexts —
the ``telemetry fleet``/``telemetry trace`` report writers, the
lint/flow gates — so importing this package must not drag in the
jax-loaded ``feed`` module (device prefetch) as a side effect.
Submodule imports (``from apnea_uq_tpu.data import registry``) stay
jax-free too; only touching ``prefetch_to_device`` (or importing
``data.feed`` directly) pays the jax import.
"""

__all__ = [
    "ArtifactRegistry",
    "EdfSignal",
    "PreparedDatasets",
    "RespiratoryEvents",
    "WindowSet",
    "grouped_train_test_split",
    "ingest_directory",
    "ingest_recording",
    "parse_xml_annotations",
    "prefetch_to_device",
    "prepare_datasets",
    "random_undersample",
    "read_edf",
    "smote_oversample",
    "windows_from_reference_csv",
    "windows_to_reference_csv",
]

_EXPORTS = {
    "RespiratoryEvents": "annotations",
    "parse_xml_annotations": "annotations",
    "EdfSignal": "edf",
    "read_edf": "edf",
    "prefetch_to_device": "feed",
    "WindowSet": "ingest",
    "ingest_directory": "ingest",
    "ingest_recording": "ingest",
    "windows_from_reference_csv": "ingest",
    "windows_to_reference_csv": "ingest",
    "PreparedDatasets": "prepare",
    "prepare_datasets": "prepare",
    "ArtifactRegistry": "registry",
    "grouped_train_test_split": "sampling",
    "random_undersample": "sampling",
    "smote_oversample": "sampling",
}


def __getattr__(name):
    try:
        module = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib

    return getattr(
        importlib.import_module(f"apnea_uq_tpu.data.{module}"), name)
