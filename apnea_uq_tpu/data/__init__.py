"""Host-side data layer: ingestion, finalization, artifacts, device feed.

Replaces the reference's two preprocessing scripts
(data_prepocessing/preprocess_shhs_raw.py, prepare_numpy_datasets.py) and
their file-name drift (SURVEY §1) with one versioned artifact registry and
library-grade stages.
"""

from apnea_uq_tpu.data.annotations import (
    RespiratoryEvents,
    parse_xml_annotations,
)
from apnea_uq_tpu.data.edf import EdfSignal, read_edf
from apnea_uq_tpu.data.feed import prefetch_to_device
from apnea_uq_tpu.data.ingest import (
    WindowSet,
    ingest_directory,
    ingest_recording,
    windows_from_reference_csv,
    windows_to_reference_csv,
)
from apnea_uq_tpu.data.prepare import PreparedDatasets, prepare_datasets
from apnea_uq_tpu.data.registry import ArtifactRegistry
from apnea_uq_tpu.data.sampling import (
    grouped_train_test_split,
    random_undersample,
    smote_oversample,
)

__all__ = [
    "ArtifactRegistry",
    "EdfSignal",
    "PreparedDatasets",
    "RespiratoryEvents",
    "WindowSet",
    "grouped_train_test_split",
    "ingest_directory",
    "ingest_recording",
    "parse_xml_annotations",
    "prefetch_to_device",
    "prepare_datasets",
    "random_undersample",
    "read_edf",
    "smote_oversample",
    "windows_from_reference_csv",
    "windows_to_reference_csv",
]
