"""Dataset finalization: windows -> model-ready array artifacts (L2).

Capability parity with data_prepocessing/prepare_numpy_datasets.py:

- NaN imputation by column means (:126-128) — but computed from the
  *training* split by default, fixing the reference's global-mean
  train->test leak; ``nan_fill='global'`` reproduces the reference
  behavior for parity experiments (PrepareConfig).
- patient-independent 80/20 split, seed 2025 (:140-152), with the
  overlap check hardened from a warning to an error (:156-160),
- per-window standardization over the time axis, eps 1e-8 (:83-95),
- SMOTE on flattened standardized training windows (:180-196) with
  fallback to the unbalanced set on failure,
- RUS-balanced copy of the test set (:202-219), skipped on failure,
- artifacts under canonical registry keys instead of the drifted file
  names (SURVEY §1).

Arrays are float32 (the TPU compute dtype) rather than the reference's
float64 — training casts to bf16/f32 on device either way.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from typing import Optional

import numpy as np

from apnea_uq_tpu.config import PrepareConfig
from apnea_uq_tpu.data import registry as reg
from apnea_uq_tpu.data import store as store_mod
from apnea_uq_tpu.data.ingest import WindowSet
from apnea_uq_tpu.data.registry import ArtifactRegistry
from apnea_uq_tpu.data.sampling import (
    grouped_train_test_split,
    random_undersample,
    smote_oversample,
    iter_smote_synthetic,
    undersample_indices,
    verify_no_group_overlap,
)


@dataclass(frozen=True)
class PreparedDatasets:
    """The L2 -> L3/L5 artifact bundle.

    ``x_train``/``y_train`` are None when loaded with
    ``load_prepared(..., include_train=False)`` (inference-only stages skip
    reading the largest artifact in the registry).
    """

    x_train: Optional[np.ndarray]  # (N, 60, 4) standardized (+SMOTE) float32
    y_train: Optional[np.ndarray]  # (N,)
    x_test: np.ndarray           # (M, 60, 4) standardized, unbalanced
    y_test: np.ndarray           # (M,)
    patient_ids_test: np.ndarray # (M,) str
    x_test_rus: Optional[np.ndarray]  # RUS-balanced copy, None if skipped
    y_test_rus: Optional[np.ndarray]


def standardize_per_window(x: np.ndarray, eps: float = 1e-8) -> np.ndarray:
    """Standardize each window independently over its time axis
    (prepare_numpy_datasets.py:83-95): (x - mean) / (std + eps), with
    mean/std over axis 1 per (window, channel)."""
    x = np.asarray(x, dtype=np.float32)
    mean = x.mean(axis=1, keepdims=True)
    std = x.std(axis=1, keepdims=True)
    return (x - mean) / (std + np.float32(eps))


def nan_column_means(x: np.ndarray) -> np.ndarray:
    """Per-(time, channel) NaN-ignoring means; all-NaN columns map to 0."""
    with warnings.catch_warnings():
        # All-NaN columns are expected and handled below; silence the
        # "Mean of empty slice" RuntimeWarning they trigger.
        warnings.simplefilter("ignore", RuntimeWarning)
        means = np.nanmean(np.asarray(x, dtype=np.float32), axis=0)
    return np.where(np.isfinite(means), means, 0.0)


def fill_nan_with_column_means(
    x: np.ndarray,
    fit_on: Optional[np.ndarray] = None,
    *,
    means: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Impute NaNs with per-(time, channel) means computed on ``fit_on``
    (default: x itself), or with precomputed ``means`` — pass the latter
    when filling several arrays from one source to avoid recomputing the
    reduction.  The reference computes means over the full dataset before
    splitting (prepare_numpy_datasets.py:126-128); fitting on the
    training slice gives the leak-free variant."""
    x = np.asarray(x, dtype=np.float32)
    if not np.isnan(x).any():
        return x
    if means is None:
        means = nan_column_means(x if fit_on is None else fit_on)
    out = x.copy()
    nan_mask = np.isnan(out)
    out[nan_mask] = np.broadcast_to(means, out.shape)[nan_mask]
    return out


def prepare_datasets(
    windows: WindowSet,
    config: PrepareConfig = PrepareConfig(),
    *,
    registry: Optional[ArtifactRegistry] = None,
) -> PreparedDatasets:
    """Split, standardize, and balance a WindowSet; optionally persist
    every artifact into ``registry`` (prepare_final_datasets,
    prepare_numpy_datasets.py:99-249)."""
    x_all = np.asarray(windows.x, dtype=np.float32)
    y_all = np.asarray(windows.y)
    groups = np.asarray(windows.patient_ids)

    train_idx, test_idx = grouped_train_test_split(
        groups, test_size=config.test_size, seed=config.seed
    )
    verify_no_group_overlap(groups, train_idx, test_idx)

    x_train, x_test = x_all[train_idx], x_all[test_idx]
    y_train, y_test = y_all[train_idx], y_all[test_idx]
    ids_test = groups[test_idx]

    # NaN imputation (leak-free by default; 'global' = reference parity).
    if config.nan_fill == "train":
        fit = x_train
    elif config.nan_fill == "global":
        fit = x_all
    else:
        raise ValueError(f"nan_fill must be 'train' or 'global', got {config.nan_fill!r}")
    if np.isnan(x_train).any() or np.isnan(x_test).any():
        means = nan_column_means(fit)
        x_train = fill_nan_with_column_means(x_train, means=means)
        x_test = fill_nan_with_column_means(x_test, means=means)

    x_train = standardize_per_window(x_train, config.standardize_eps)
    x_test = standardize_per_window(x_test, config.standardize_eps)

    n_train, steps, feats = x_train.shape
    if config.smote:
        try:
            flat, y_train = smote_oversample(
                x_train.reshape(n_train, steps * feats),
                y_train,
                k_neighbors=config.smote_k_neighbors,
                seed=config.seed,
            )
            x_train = flat.reshape(-1, steps, feats)
        except ValueError:
            # Reference falls back to the unbalanced training set when
            # SMOTE cannot run (prepare_numpy_datasets.py:194-197).
            pass

    x_test_rus = y_test_rus = None
    if config.rus:
        try:
            flat_rus, y_test_rus, _ = random_undersample(
                x_test.reshape(len(x_test), steps * feats), y_test, seed=config.seed
            )
            x_test_rus = flat_rus.reshape(-1, steps, feats)
        except ValueError:
            # Reference skips the balanced test set when RUS fails (:218-220).
            x_test_rus = y_test_rus = None

    prepared = PreparedDatasets(
        x_train=x_train,
        y_train=y_train,
        x_test=x_test,
        y_test=y_test,
        patient_ids_test=ids_test,
        x_test_rus=x_test_rus,
        y_test_rus=y_test_rus,
    )

    if registry is not None:
        save_prepared(prepared, registry, config)
    return prepared


def save_prepared(
    prepared: PreparedDatasets,
    registry: ArtifactRegistry,
    config: Optional[PrepareConfig] = None,
    *,
    store: bool = False,
    rows_per_shard: int = store_mod.DEFAULT_ROWS_PER_SHARD,
) -> None:
    """Persist the bundle under canonical keys (the save block at
    prepare_numpy_datasets.py:223-245, minus the name drift).

    ``store=True`` writes sharded memmap stores (``array_store`` kind,
    data/store.py) instead of monolithic ``.npz`` bundles, so later
    stages memory-map instead of materializing; contents are identical.
    """
    save = (
        (lambda key, arrays, **kw: registry.save_array_store(
            key, arrays, rows_per_shard=rows_per_shard, **kw))
        if store else registry.save_arrays
    )
    save(
        reg.TRAIN_STD_SMOTE,
        {"x": prepared.x_train, "y": prepared.y_train},
        config=config,
    )
    save(
        reg.TEST_STD_UNBALANCED,
        {
            "x": prepared.x_test,
            "y": prepared.y_test,
            "patient_ids": prepared.patient_ids_test.astype(np.str_),
        },
        config=config,
    )
    if prepared.x_test_rus is not None:
        save(
            reg.TEST_STD_RUS,
            {"x": prepared.x_test_rus, "y": prepared.y_test_rus},
            config=config,
        )
    _freeze_quality_baseline(
        registry,
        {reg.TEST_STD_UNBALANCED: prepared.x_test,
         reg.TEST_STD_RUS: prepared.x_test_rus},
        config,
    )


def _freeze_quality_baseline(registry: ArtifactRegistry, test_sets,
                             config) -> None:
    """Freeze the per-channel statistical fingerprint of EACH prepared
    test set (keyed by its registry artifact key; None entries — a
    skipped RUS set — are dropped) as the registry's
    ``quality_baseline`` artifact (JSON, atomic commit like every
    registry write): the eval stages re-score their live windows
    against the matching set's fingerprint into ``drift_fingerprint``
    telemetry, so a drifted cohort is a gateable number instead of a
    silent miscalibration.  Per-set baselines matter: the RUS set is a
    *deliberate* class re-balance of the unbalanced cohort, so scoring
    it against the unbalanced fingerprint would read the designed
    resampling as drift.  Streaming — values may be memmap-backed
    :class:`~apnea_uq_tpu.data.store.ShardedArray` sources.

    Re-running prepare RE-FREEZES the baseline (the artifact describes
    "the cohort this registry was prepared on"), which would otherwise
    silently absorb a drifted cohort — so when a prior baseline exists,
    each new set is first scored against it and the drift is logged
    (fail-soft), leaving an on-record number for the overwrite."""
    from apnea_uq_tpu.analysis import fingerprint as fp_mod
    from apnea_uq_tpu.telemetry import log

    fingerprints = {
        key: fp_mod.compute_fingerprint(x)
        for key, x in test_sets.items()
        if x is not None
    }
    if registry.exists(reg.QUALITY_BASELINE):
        try:
            prior = (registry.load_json(reg.QUALITY_BASELINE)
                     .get("sets") or {})
        except Exception:  # noqa: BLE001 - telemetry never breaks prepare
            prior = {}
        for key, fingerprint in fingerprints.items():
            old = prior.get(key)
            if old is None:
                continue
            try:
                report = fp_mod.drift_report(old, fp_mod.compute_fingerprint(
                    test_sets[key], edges=fp_mod.baseline_edges(old)))
            except Exception as e:  # noqa: BLE001 - incomparable prior
                log(f"quality_baseline re-freeze for {key}: prior "
                    f"baseline not comparable ({type(e).__name__}: {e})")
                continue
            log(f"quality_baseline re-freeze for {key}: drift vs prior "
                f"baseline max_psi={report['max_psi']:g} "
                f"max_ks={report['max_ks']:g} "
                f"(worst channel {report['worst_channel']})")
    registry.save_json(
        reg.QUALITY_BASELINE,
        {"version": 1, "sets": fingerprints},
        config=config,
    )


def load_prepared(
    registry: ArtifactRegistry, *, include_train: bool = True,
    mmap: bool = False,
) -> PreparedDatasets:
    """Load the bundle saved by :func:`save_prepared`.

    ``include_train=False`` skips the SMOTE-balanced training arrays —
    the registry's largest artifact — for stages that only evaluate.
    Each artifact is loaded by the exact key subset a consumer reads
    (``names=``), so nothing is decompressed and then dropped.

    ``mmap=True`` returns memmap-backed window arrays for ``array_store``
    artifacts (data/store.py): zero copy, zero load time — the streamed
    trainers/predictors then slice batches straight off the mapping and
    steady-state host RSS stays O(prefetch × batch) regardless of
    dataset rows.  Labels and patient ids (O(rows) scalars/strings) are
    always materialized; ``.npz`` artifacts are unaffected.
    """
    train = (registry.load_arrays(reg.TRAIN_STD_SMOTE, names=("x", "y"),
                                  mmap=mmap)
             if include_train else None)
    test = registry.load_arrays(
        reg.TEST_STD_UNBALANCED, names=("x", "y", "patient_ids"), mmap=mmap
    )
    if registry.exists(reg.TEST_STD_RUS):
        rus = registry.load_arrays(reg.TEST_STD_RUS, names=("x", "y"),
                                   mmap=mmap)
        x_rus, y_rus = rus["x"], np.asarray(rus["y"])
    else:
        x_rus = y_rus = None
    return PreparedDatasets(
        x_train=train["x"] if train is not None else None,
        y_train=np.asarray(train["y"]) if train is not None else None,
        x_test=test["x"],
        y_test=np.asarray(test["y"]),
        patient_ids_test=np.asarray(test["patient_ids"]).astype(str),
        x_test_rus=x_rus,
        y_test_rus=y_rus,
    )


# -- out-of-core prepare: sharded store in, sharded stores out -------------

def streaming_nan_stats(x, fit_mask: np.ndarray, *, block_rows: int):
    """(has_nan anywhere, per-(time, channel) NaN-ignoring means over the
    ``fit_mask`` rows) in one streaming pass of O(block_rows) memory.

    Accumulates in float64 (a blockwise float32 sum would drift with the
    block size); the in-core :func:`nan_column_means` reduces in float32
    pairwise order instead, so the two agree to float32 roundoff — exact
    whenever the data has no NaNs at all, because then the means are
    never applied."""
    x = store_mod.as_host_source(x)
    fit_mask = np.asarray(fit_mask, bool)
    tail = tuple(np.shape(x))[1:]
    total = np.zeros(tail, np.float64)
    count = np.zeros(tail, np.int64)
    has_nan = False
    blocks = (x.iter_blocks(block_rows)
              if isinstance(x, store_mod.ShardedArray)
              else ((lo, np.asarray(x[lo:lo + block_rows]))
                    for lo in range(0, len(x), block_rows)))
    for lo, block in blocks:
        nan = np.isnan(block)
        has_nan = has_nan or bool(nan.any())
        fit = fit_mask[lo:lo + len(block)]
        if fit.any():
            sub = block[fit]
            sub_nan = nan[fit]
            total += np.where(sub_nan, 0.0, sub).sum(axis=0, dtype=np.float64)
            count += (~sub_nan).sum(axis=0)
    with np.errstate(invalid="ignore"):
        means = np.where(count > 0, total / np.maximum(count, 1), 0.0)
    return has_nan, means.astype(np.float32)


def _stream_standardized(x, rows: np.ndarray, *, means, eps: float,
                         block_rows: int):
    """Yield imputed + per-window-standardized float32 blocks of the
    selected rows — the row-local math of the in-core path, applied
    O(block_rows) at a time."""
    rows = np.asarray(rows)
    for lo in range(0, len(rows), block_rows):
        block = np.asarray(x[rows[lo:lo + block_rows]], dtype=np.float32)
        if means is not None and np.isnan(block).any():
            block = fill_nan_with_column_means(block, means=means)
        yield standardize_per_window(block, eps)


def prepare_from_store(
    store: store_mod.ArrayStore,
    registry: ArtifactRegistry,
    config: PrepareConfig = PrepareConfig(),
    *,
    block_rows: int = 16384,
    rows_per_shard: int = store_mod.DEFAULT_ROWS_PER_SHARD,
) -> None:
    """Out-of-core :func:`prepare_datasets`: windows stream from a
    sharded memmap store and the three prepared artifacts stream into
    sharded stores, so peak host memory is O(block) + O(labels) instead
    of the in-core path's 4-5 whole-set copies.

    Where the math allows it the pipeline is block-local and matches the
    in-core path exactly: per-window standardization and NaN imputation
    are row-local, the grouped split / SMOTE / RUS all operate on INDEX
    arrays (sampling.py's factored helpers draw the identical RNG
    streams), and SMOTE's synthesis needs only the standardized minority
    rows resident (gathered back off the just-written train store's
    mmap).  The one permitted divergence: streaming NaN means accumulate
    in float64 (see :func:`streaming_nan_stats`), so imputed values can
    differ from in-core by float32 roundoff — bit-identical whenever the
    windows carry no NaNs.
    """
    y_all = np.asarray(store.read("y", mmap=False))
    groups = np.asarray(store.read("patient_ids", mmap=False)).astype(str)
    x_all = store.read("x")  # lazy

    train_idx, test_idx = grouped_train_test_split(
        groups, test_size=config.test_size, seed=config.seed
    )
    verify_no_group_overlap(groups, train_idx, test_idx)
    y_train = y_all[train_idx]
    y_test = y_all[test_idx]
    ids_test = groups[test_idx]

    # Streaming pass for NaN presence + imputation means over the fit set.
    if config.nan_fill == "train":
        fit_mask = np.zeros(len(y_all), bool)
        fit_mask[train_idx] = True
    elif config.nan_fill == "global":
        fit_mask = np.ones(len(y_all), bool)
    else:
        raise ValueError(
            f"nan_fill must be 'train' or 'global', got {config.nan_fill!r}"
        )
    has_nan, means = streaming_nan_stats(x_all, fit_mask,
                                         block_rows=block_rows)
    if not has_nan:
        means = None

    steps, feats = tuple(np.shape(x_all))[1:]

    # -- train: standardized originals, then SMOTE synthetic shards ------
    train_path = registry.path_for(reg.TRAIN_STD_SMOTE, ".store")
    writer = store_mod.StoreWriter(train_path, resume=False)
    for lo, block in zip(
        range(0, len(train_idx), block_rows),
        _stream_standardized(x_all, train_idx, means=means,
                             eps=config.standardize_eps,
                             block_rows=block_rows),
    ):
        writer.append_shard({
            "x": block, "y": y_train[lo:lo + len(block)],
        })
    if config.smote:
        # The try covers ONLY "can SMOTE run?" (class structure, minority
        # size — what the in-core path's fallback catches); the shard
        # writes below run outside it, so a store error mid-append fails
        # loudly instead of silently adopting a half-oversampled train
        # set.  iter_smote_synthetic validates and draws eagerly, then
        # yields O(block) synthetic rows at a time — peak memory tracks
        # the minority rows + one block, never the majority count.
        smote_plan = None
        try:
            classes, counts = np.unique(y_train, return_counts=True)
            if classes.size != 2:
                raise ValueError(
                    f"binary SMOTE only, got classes {classes.tolist()}")
            minority = classes[np.argmin(counts)]
            n_needed = int(counts.max() - counts.min())
            if n_needed:
                # Gather ONLY the standardized minority rows back off the
                # just-written store — O(minority), not O(train).
                train_x = store_mod.ArrayStore.open(train_path).read("x")
                min_rows = np.flatnonzero(y_train == minority)
                x_min = train_x[min_rows].reshape(len(min_rows),
                                                  steps * feats)
                smote_plan = (minority, iter_smote_synthetic(
                    x_min, n_needed, k_neighbors=config.smote_k_neighbors,
                    seed=config.seed, block_rows=rows_per_shard,
                ))
        except ValueError:
            # Reference fallback: unbalanced training set when SMOTE
            # cannot run (prepare_numpy_datasets.py:194-197).
            smote_plan = None
        if smote_plan is not None:
            minority, blocks = smote_plan
            for block in blocks:
                writer.append_shard({
                    "x": block.reshape(-1, steps, feats),
                    "y": np.full(len(block), minority, dtype=y_train.dtype),
                })
    writer.finalize()
    registry.adopt_array_store(reg.TRAIN_STD_SMOTE, config=config)

    # -- test: standardized, unbalanced ----------------------------------
    test_path = registry.path_for(reg.TEST_STD_UNBALANCED, ".store")
    writer = store_mod.StoreWriter(test_path, resume=False)
    for lo, block in zip(
        range(0, len(test_idx), block_rows),
        _stream_standardized(x_all, test_idx, means=means,
                             eps=config.standardize_eps,
                             block_rows=block_rows),
    ):
        hi = lo + len(block)
        ids_block = ids_test[lo:hi].astype(np.str_)
        id_sorted = sorted(ids_block.tolist())
        writer.append_shard(
            {"x": block, "y": y_test[lo:hi], "patient_ids": ids_block},
            patient_range=(id_sorted[0], id_sorted[-1]),
        )
    writer.finalize()
    registry.adopt_array_store(reg.TEST_STD_UNBALANCED, config=config)

    # -- RUS-balanced test copy: index selection, streamed gather --------
    rus_path = None
    if config.rus:
        try:
            keep_idx = undersample_indices(y_test, seed=config.seed)
        except ValueError:
            keep_idx = None  # reference skips the balanced set (:218-220)
        if keep_idx is not None:
            test_x = store_mod.ArrayStore.open(test_path).read("x")
            rus_path = registry.path_for(reg.TEST_STD_RUS, ".store")
            writer = store_mod.StoreWriter(rus_path, resume=False)
            for lo in range(0, len(keep_idx), block_rows):
                rows = keep_idx[lo:lo + block_rows]
                writer.append_shard({
                    "x": test_x[rows], "y": y_test[rows],
                })
            writer.finalize()
            registry.adopt_array_store(reg.TEST_STD_RUS, config=config)

    # Freeze the per-set drift baselines off the just-written stores'
    # mmaps — O(block) like everything else in this path.
    _freeze_quality_baseline(
        registry,
        {
            reg.TEST_STD_UNBALANCED:
                store_mod.ArrayStore.open(test_path).read("x"),
            reg.TEST_STD_RUS: (
                store_mod.ArrayStore.open(rus_path).read("x")
                if rus_path is not None else None),
        },
        config,
    )
