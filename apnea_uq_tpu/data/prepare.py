"""Dataset finalization: windows -> model-ready array artifacts (L2).

Capability parity with data_prepocessing/prepare_numpy_datasets.py:

- NaN imputation by column means (:126-128) — but computed from the
  *training* split by default, fixing the reference's global-mean
  train->test leak; ``nan_fill='global'`` reproduces the reference
  behavior for parity experiments (PrepareConfig).
- patient-independent 80/20 split, seed 2025 (:140-152), with the
  overlap check hardened from a warning to an error (:156-160),
- per-window standardization over the time axis, eps 1e-8 (:83-95),
- SMOTE on flattened standardized training windows (:180-196) with
  fallback to the unbalanced set on failure,
- RUS-balanced copy of the test set (:202-219), skipped on failure,
- artifacts under canonical registry keys instead of the drifted file
  names (SURVEY §1).

Arrays are float32 (the TPU compute dtype) rather than the reference's
float64 — training casts to bf16/f32 on device either way.
"""

from __future__ import annotations

import warnings
from dataclasses import dataclass
from typing import Optional

import numpy as np

from apnea_uq_tpu.config import PrepareConfig
from apnea_uq_tpu.data import registry as reg
from apnea_uq_tpu.data.ingest import WindowSet
from apnea_uq_tpu.data.registry import ArtifactRegistry
from apnea_uq_tpu.data.sampling import (
    grouped_train_test_split,
    random_undersample,
    smote_oversample,
    verify_no_group_overlap,
)


@dataclass(frozen=True)
class PreparedDatasets:
    """The L2 -> L3/L5 artifact bundle.

    ``x_train``/``y_train`` are None when loaded with
    ``load_prepared(..., include_train=False)`` (inference-only stages skip
    reading the largest artifact in the registry).
    """

    x_train: Optional[np.ndarray]  # (N, 60, 4) standardized (+SMOTE) float32
    y_train: Optional[np.ndarray]  # (N,)
    x_test: np.ndarray           # (M, 60, 4) standardized, unbalanced
    y_test: np.ndarray           # (M,)
    patient_ids_test: np.ndarray # (M,) str
    x_test_rus: Optional[np.ndarray]  # RUS-balanced copy, None if skipped
    y_test_rus: Optional[np.ndarray]


def standardize_per_window(x: np.ndarray, eps: float = 1e-8) -> np.ndarray:
    """Standardize each window independently over its time axis
    (prepare_numpy_datasets.py:83-95): (x - mean) / (std + eps), with
    mean/std over axis 1 per (window, channel)."""
    x = np.asarray(x, dtype=np.float32)
    mean = x.mean(axis=1, keepdims=True)
    std = x.std(axis=1, keepdims=True)
    return (x - mean) / (std + np.float32(eps))


def nan_column_means(x: np.ndarray) -> np.ndarray:
    """Per-(time, channel) NaN-ignoring means; all-NaN columns map to 0."""
    with warnings.catch_warnings():
        # All-NaN columns are expected and handled below; silence the
        # "Mean of empty slice" RuntimeWarning they trigger.
        warnings.simplefilter("ignore", RuntimeWarning)
        means = np.nanmean(np.asarray(x, dtype=np.float32), axis=0)
    return np.where(np.isfinite(means), means, 0.0)


def fill_nan_with_column_means(
    x: np.ndarray,
    fit_on: Optional[np.ndarray] = None,
    *,
    means: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Impute NaNs with per-(time, channel) means computed on ``fit_on``
    (default: x itself), or with precomputed ``means`` — pass the latter
    when filling several arrays from one source to avoid recomputing the
    reduction.  The reference computes means over the full dataset before
    splitting (prepare_numpy_datasets.py:126-128); fitting on the
    training slice gives the leak-free variant."""
    x = np.asarray(x, dtype=np.float32)
    if not np.isnan(x).any():
        return x
    if means is None:
        means = nan_column_means(x if fit_on is None else fit_on)
    out = x.copy()
    nan_mask = np.isnan(out)
    out[nan_mask] = np.broadcast_to(means, out.shape)[nan_mask]
    return out


def prepare_datasets(
    windows: WindowSet,
    config: PrepareConfig = PrepareConfig(),
    *,
    registry: Optional[ArtifactRegistry] = None,
) -> PreparedDatasets:
    """Split, standardize, and balance a WindowSet; optionally persist
    every artifact into ``registry`` (prepare_final_datasets,
    prepare_numpy_datasets.py:99-249)."""
    x_all = np.asarray(windows.x, dtype=np.float32)
    y_all = np.asarray(windows.y)
    groups = np.asarray(windows.patient_ids)

    train_idx, test_idx = grouped_train_test_split(
        groups, test_size=config.test_size, seed=config.seed
    )
    verify_no_group_overlap(groups, train_idx, test_idx)

    x_train, x_test = x_all[train_idx], x_all[test_idx]
    y_train, y_test = y_all[train_idx], y_all[test_idx]
    ids_test = groups[test_idx]

    # NaN imputation (leak-free by default; 'global' = reference parity).
    if config.nan_fill == "train":
        fit = x_train
    elif config.nan_fill == "global":
        fit = x_all
    else:
        raise ValueError(f"nan_fill must be 'train' or 'global', got {config.nan_fill!r}")
    if np.isnan(x_train).any() or np.isnan(x_test).any():
        means = nan_column_means(fit)
        x_train = fill_nan_with_column_means(x_train, means=means)
        x_test = fill_nan_with_column_means(x_test, means=means)

    x_train = standardize_per_window(x_train, config.standardize_eps)
    x_test = standardize_per_window(x_test, config.standardize_eps)

    n_train, steps, feats = x_train.shape
    if config.smote:
        try:
            flat, y_train = smote_oversample(
                x_train.reshape(n_train, steps * feats),
                y_train,
                k_neighbors=config.smote_k_neighbors,
                seed=config.seed,
            )
            x_train = flat.reshape(-1, steps, feats)
        except ValueError:
            # Reference falls back to the unbalanced training set when
            # SMOTE cannot run (prepare_numpy_datasets.py:194-197).
            pass

    x_test_rus = y_test_rus = None
    if config.rus:
        try:
            flat_rus, y_test_rus, _ = random_undersample(
                x_test.reshape(len(x_test), steps * feats), y_test, seed=config.seed
            )
            x_test_rus = flat_rus.reshape(-1, steps, feats)
        except ValueError:
            # Reference skips the balanced test set when RUS fails (:218-220).
            x_test_rus = y_test_rus = None

    prepared = PreparedDatasets(
        x_train=x_train,
        y_train=y_train,
        x_test=x_test,
        y_test=y_test,
        patient_ids_test=ids_test,
        x_test_rus=x_test_rus,
        y_test_rus=y_test_rus,
    )

    if registry is not None:
        save_prepared(prepared, registry, config)
    return prepared


def save_prepared(
    prepared: PreparedDatasets,
    registry: ArtifactRegistry,
    config: Optional[PrepareConfig] = None,
) -> None:
    """Persist the bundle under canonical keys (the save block at
    prepare_numpy_datasets.py:223-245, minus the name drift)."""
    registry.save_arrays(
        reg.TRAIN_STD_SMOTE,
        {"x": prepared.x_train, "y": prepared.y_train},
        config=config,
    )
    registry.save_arrays(
        reg.TEST_STD_UNBALANCED,
        {
            "x": prepared.x_test,
            "y": prepared.y_test,
            "patient_ids": prepared.patient_ids_test.astype(np.str_),
        },
        config=config,
    )
    if prepared.x_test_rus is not None:
        registry.save_arrays(
            reg.TEST_STD_RUS,
            {"x": prepared.x_test_rus, "y": prepared.y_test_rus},
            config=config,
        )


def load_prepared(
    registry: ArtifactRegistry, *, include_train: bool = True
) -> PreparedDatasets:
    """Load the bundle saved by :func:`save_prepared`.

    ``include_train=False`` skips the SMOTE-balanced training arrays —
    the registry's largest artifact — for stages that only evaluate.
    """
    train = registry.load_arrays(reg.TRAIN_STD_SMOTE) if include_train else None
    test = registry.load_arrays(reg.TEST_STD_UNBALANCED)
    if registry.exists(reg.TEST_STD_RUS):
        rus = registry.load_arrays(reg.TEST_STD_RUS)
        x_rus, y_rus = rus["x"], rus["y"]
    else:
        x_rus = y_rus = None
    return PreparedDatasets(
        x_train=train["x"] if train is not None else None,
        y_train=train["y"] if train is not None else None,
        x_test=test["x"],
        y_test=test["y"],
        patient_ids_test=test["patient_ids"].astype(str),
        x_test_rus=x_rus,
        y_test_rus=y_rus,
    )
