"""NSRR profusion XML annotation parsing for SHHS2 recordings.

Equivalent of preprocess_shhs_raw.py:169-190 (`parse_xml_annotations`) and
:75-96 (`calculate_sleep_time`): scored respiratory events are read from
``ScoredEvents/ScoredEvent`` elements, and the recording duration is the
``Duration`` of the ``Recording Start Time`` event.

Events are returned as structure-of-arrays (NumPy), not a list of dicts:
downstream window labeling is a vectorized interval-overlap computation
(ingest.py) instead of the reference's O(windows x events) Python loop
(preprocess_shhs_raw.py:236-249).
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from dataclasses import dataclass
from typing import Optional

import numpy as np

RECORDING_START_CONCEPT = "Recording Start Time"
STAGE_EVENT_TYPE = "Stages|Stages"


@dataclass(frozen=True)
class RespiratoryEvents:
    """Scored events of one recording, structure-of-arrays."""

    event_type: np.ndarray     # object (E,)
    event_concept: np.ndarray  # object (E,)
    start_s: np.ndarray        # float64 (E,)
    duration_s: np.ndarray     # float64 (E,)
    recording_duration_s: float

    def __len__(self) -> int:
        return len(self.start_s)

    def select_concepts(self, concepts) -> "RespiratoryEvents":
        """Events whose concept is in ``concepts`` (order preserved)."""
        mask = np.isin(self.event_concept, list(concepts))
        return RespiratoryEvents(
            event_type=self.event_type[mask],
            event_concept=self.event_concept[mask],
            start_s=self.start_s[mask],
            duration_s=self.duration_s[mask],
            recording_duration_s=self.recording_duration_s,
        )


def parse_xml_annotations(
    xml_path: str,
    *,
    stop_at_first_stage_event: bool = True,
) -> RespiratoryEvents:
    """Parse a profusion XML annotation file.

    ``stop_at_first_stage_event=True`` reproduces the reference's loop
    ``break`` on the first ``Stages|Stages`` event
    (preprocess_shhs_raw.py:176-177) — NSRR files list all scored events
    before the sleep-stage block, so this skips the (large) stage tail.
    Set it False to scan every event regardless of ordering.

    The recording duration is taken from the ``Recording Start Time``
    event wherever it appears among the collected events, 0.0 when absent
    (preprocess_shhs_raw.py:86-91).
    """
    root = ET.parse(xml_path).getroot()
    types, concepts, starts, durations = [], [], [], []
    recording_duration = 0.0
    seen_recording_start = False

    for scored in root.iterfind("ScoredEvents/ScoredEvent"):
        etype = _text(scored, "EventType")
        if stop_at_first_stage_event and etype == STAGE_EVENT_TYPE:
            break
        concept = _text(scored, "EventConcept")
        start = _float(scored, "Start")
        duration = _float(scored, "Duration")
        if concept == RECORDING_START_CONCEPT and not seen_recording_start:
            recording_duration = 0.0 if duration is None else duration
            seen_recording_start = True
        types.append(etype)
        concepts.append(concept)
        starts.append(np.nan if start is None else start)
        durations.append(np.nan if duration is None else duration)

    return RespiratoryEvents(
        event_type=np.asarray(types, dtype=object),
        event_concept=np.asarray(concepts, dtype=object),
        start_s=np.asarray(starts, dtype=np.float64),
        duration_s=np.asarray(durations, dtype=np.float64),
        recording_duration_s=recording_duration,
    )


def _text(element: ET.Element, tag: str) -> Optional[str]:
    child = element.find(tag)
    return None if child is None else child.text


def _float(element: ET.Element, tag: str) -> Optional[float]:
    text = _text(element, tag)
    return None if text is None else float(text)
