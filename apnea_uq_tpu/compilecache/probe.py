"""Cold-vs-warm start probe: ``python -m apnea_uq_tpu.compilecache.probe``.

One subprocess = one process start.  The probe wires the persistent XLA
cache and the program store at the given directories, acquires and runs
the fused MCD predict program once at the given shapes, and prints ONE
JSON line with the in-process timings::

    {"acquire_s": ..., "predict_s": ..., "total_s": ...,
     "source": "jit" | "store", "backend_compiles": N,
     "persistent_cache_misses": N}

bench.py's ``compile`` context block runs it twice against the same
fresh directories — the first run is the true cold start (trace + lower
+ XLA compile), the second the warmed start (store hit + cache hit) —
and reports both sides plus the process wall clock, so the cold-start
cost the subsystem removes is a measured number, not prose.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m apnea_uq_tpu.compilecache.probe")
    parser.add_argument("--cache-dir", required=True)
    parser.add_argument("--store-dir", required=True)
    parser.add_argument("--windows", type=int, default=2048)
    parser.add_argument("--passes", type=int, default=50)
    parser.add_argument("--chunk", type=int, default=512)
    parser.add_argument("--platform", default=None,
                        help="Retarget the backend (the BENCH_PLATFORM "
                             "dance: a config update, because "
                             "sitecustomize pins JAX_PLATFORMS at boot).")
    parser.add_argument("--dtype", default="bfloat16")
    args = parser.parse_args(argv)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    import numpy as np

    from apnea_uq_tpu.compilecache.store import (
        ProgramStore, enable_persistent_cache, use_store,
    )
    from apnea_uq_tpu.config import ModelConfig
    from apnea_uq_tpu.models import AlarconCNN1D, init_variables
    from apnea_uq_tpu.telemetry.steps import compile_counts
    from apnea_uq_tpu.uq.predict import mc_dropout_predict
    from apnea_uq_tpu.utils import prng

    # Explicit dirs always win (force=True): the probe measures THESE
    # caches, whatever the ambient environment configured.
    enable_persistent_cache(args.cache_dir, force=True)
    store = ProgramStore(args.store_dir)
    model = AlarconCNN1D(ModelConfig(compute_dtype=args.dtype))
    variables = init_variables(model, jax.random.key(0))
    x = np.zeros((args.windows, 60, 4), np.float32)
    key = prng.stochastic_key(1)

    before = compile_counts()
    t0 = time.perf_counter()
    with use_store(store):
        stats = mc_dropout_predict(
            model, variables, x, n_passes=args.passes, mode="clean",
            batch_size=args.chunk, key=key, stats=("nats", 1e-10),
        )
    acquired = time.perf_counter()
    np.asarray(stats)  # force execution + D2H
    done = time.perf_counter()
    after = compile_counts()
    acquisition = store.history[0] if store.history else {}
    # The one result line is the machine interface; the module prints
    # nothing else to stdout.
    # apnea-lint: disable=bare-print -- the probe's stdout IS the machine interface bench.py parses (one JSON line)
    print(json.dumps({
        "acquire_s": round(acquired - t0, 3),
        "predict_s": round(done - acquired, 3),
        "total_s": round(done - t0, 3),
        "source": acquisition.get("source"),
        "backend_compiles": (after["backend_compiles"]
                             - before["backend_compiles"]),
        "persistent_cache_misses": (after["persistent_cache_misses"]
                                    - before["persistent_cache_misses"]),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
