"""Compile-cost subsystem: persistent XLA cache + AOT program store.

Pay for XLA compilation once per (program, shapes, topology, code
version), not once per process (ISSUE 7):

- :mod:`~apnea_uq_tpu.compilecache.store` — the core:
  :func:`enable_persistent_cache` (JAX's on-disk compilation cache under
  ``<registry>/xla-cache``), :class:`ProgramStore` (``jax.export``-
  serialized named hot-path programs with compile-on-miss fallback),
  :func:`get_program` (one lowering shared between HBM pricing and
  execution), and :func:`activate` (the per-stage context the CLI uses);
- :mod:`~apnea_uq_tpu.compilecache.zoo` — the named program zoo behind
  ``apnea-uq warm-cache``: precompile every hot-path program a config
  will run, so production eval/train starts hot;
- :mod:`~apnea_uq_tpu.compilecache.probe` — the cold-vs-warm start probe
  bench.py's ``compile`` context block runs in subprocesses.

Everything resolves lazily (PEP 562): importing this package costs no
jax import, and the AST linter scans it without executing anything.
"""

from __future__ import annotations

_LAZY = {
    "ProgramStore": "store",
    "Program": "store",
    "get_program": "store",
    "active_store": "store",
    "use_store": "store",
    "activate": "store",
    "enable_persistent_cache": "store",
    "program_signature": "store",
    "store_key": "store",
    "backend_fingerprint": "store",
    "warm_cache": "zoo",
    "GROUP_LABELS": "zoo",
    "WARM_GROUPS": "zoo",
}

__all__ = sorted(_LAZY)

_SUBMODULES = frozenset({"store", "zoo", "probe"})


def __getattr__(name: str):
    import importlib

    module = _LAZY.get(name)
    if module is not None:
        return getattr(
            importlib.import_module(f"apnea_uq_tpu.compilecache.{module}"),
            name,
        )
    if name in _SUBMODULES:
        return importlib.import_module(f"apnea_uq_tpu.compilecache.{name}")
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
