"""Persistent compile cache + AOT program store (ISSUE 7 tentpole).

Every process start used to recompile the full program zoo — the four
predict families, ``train_epoch``/``val_loss``, the lockstep
``ensemble_epoch`` — from scratch, and the HBM accounting paid a *second*
AOT ``lower().compile()`` on top because it could not share the jit call
cache.  This module makes recompilation a one-time cost per (program,
shapes, topology, code version), in three layers:

1. **Persistent XLA cache** (:func:`enable_persistent_cache`): JAX's
   ``jax_compilation_cache_dir`` pointed at ``<registry>/xla-cache``
   (env-overridable) with the min-entry-size / min-compile-time knobs
   from :class:`~apnea_uq_tpu.config.CompileCacheConfig`, so identical
   backend compiles are disk hits across processes.
2. **:class:`ProgramStore`** — an explicit AOT store for the *named*
   hot-path programs: each is re-expressed as a jitted wrapper over its
   array leaves (static/aux leaves closed over; typed PRNG keys travel
   as their ``uint32`` key data, because ``jax.export`` cannot serialize
   extended key dtypes), exported via ``jax.export``, serialized to
   ``<store>/<key>.jaxprog``, and keyed by (label, abstract argument
   signature incl. shardings, jax/jaxlib version, backend+topology
   fingerprint, package source hash).  A warmed second process
   deserializes the StableHLO — no trace/lower — and its backend compile
   of the identical module is a persistent-cache disk hit, so the hot
   path runs with **zero fresh XLA compiles**.  Both processes execute
   through ``jax.jit(exported.call)`` compiled from the *deserialized*
   bytes, which is what makes the two modules byte-identical.
3. **One lowering, shared** (:func:`get_program`): the returned
   :class:`Program` carries the compiled executable *and* its
   ``memory_analysis()`` fields, persisted alongside the serialized
   program — ``record_jit_memory`` consumes them instead of paying its
   own AOT compile, and the execution path dispatches the same
   executable.  Compile-on-miss is always the fallback; every failure
   mode (unexportable program, missing store, version skew) degrades to
   the plain jit path.

Every acquisition is recorded as a ``compile_event`` telemetry event
(label, ``source=jit|store|cache``, hit/miss, lower/compile seconds,
compile-counter deltas) so ``telemetry summarize`` can render the hit
ratio and ``telemetry compare`` can gate cold-start regressions.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
import hashlib
import json
import os
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from apnea_uq_tpu.telemetry import log
from apnea_uq_tpu.telemetry.memory import memory_analysis_fields
from apnea_uq_tpu.telemetry.runlog import current_run
from apnea_uq_tpu.telemetry.steps import compile_counts

STORE_SUFFIX = ".jaxprog"
META_SUFFIX = ".json"

# Innermost-last stack of active stores; get_program is a no-op (None)
# outside any activation so library callers see byte-identical behavior
# unless a CLI stage / warm-cache / test opted in.
_ACTIVE: List["ProgramStore"] = []


def active_store() -> Optional["ProgramStore"]:
    """The innermost active program store, or None outside any."""
    return _ACTIVE[-1] if _ACTIVE else None


@contextlib.contextmanager
def use_store(store: "ProgramStore"):
    """Make ``store`` the active store for the block."""
    _ACTIVE.append(store)
    try:
        yield store
    finally:
        while store in _ACTIVE:
            _ACTIVE.remove(store)


def _cache_disabled() -> bool:
    return os.environ.get("APNEA_UQ_COMPILE_CACHE", "1").lower() in (
        "0", "false", "off")


def enable_persistent_cache(
    cache_dir: str,
    *,
    min_entry_size_bytes: int = 0,
    min_compile_time_secs: float = 0.0,
    force: bool = False,
) -> Dict[str, Any]:
    """Point JAX's persistent compilation cache at ``cache_dir`` with the
    given thresholds.  When a cache dir is already configured (the
    ``JAX_COMPILATION_CACHE_DIR`` env var, a test rig, a notebook) that
    choice — thresholds included — wins unless ``force``.  Returns the
    previous values of every config entry changed, for restoration."""
    prev: Dict[str, Any] = {}
    if jax.config.jax_compilation_cache_dir and not force:
        return prev
    for name, value in (
        ("jax_compilation_cache_dir", cache_dir),
        ("jax_persistent_cache_min_entry_size_bytes",
         int(min_entry_size_bytes)),
        ("jax_persistent_cache_min_compile_time_secs",
         float(min_compile_time_secs)),
    ):
        prev[name] = getattr(jax.config, name)
        jax.config.update(name, value)
    return prev


@contextlib.contextmanager
def activate(cc_config=None, registry_root: Optional[str] = None):
    """Activate the whole compile-cost subsystem for a stage: wire the
    persistent XLA cache (default ``<registry>/xla-cache``, env override
    ``APNEA_UQ_XLA_CACHE_DIR``) and push a :class:`ProgramStore`
    (default ``<registry>/program-store``, env override
    ``APNEA_UQ_PROGRAM_STORE_DIR``).  Yields the store, or None when the
    subsystem is disabled (``CompileCacheConfig.enabled`` false or
    ``APNEA_UQ_COMPILE_CACHE=0``).  Restores any jax config entries it
    changed on exit."""
    if _cache_disabled() or (cc_config is not None
                             and not cc_config.enabled):
        yield None
        return
    cache_dir = (
        (cc_config.cache_dir if cc_config is not None else "")
        or os.environ.get("APNEA_UQ_XLA_CACHE_DIR", "")
        or (os.path.join(registry_root, "xla-cache") if registry_root
            else "")
    )
    prev: Dict[str, Any] = {}
    if cache_dir:
        prev = enable_persistent_cache(
            cache_dir,
            min_entry_size_bytes=(cc_config.min_entry_size_bytes
                                  if cc_config is not None else 0),
            min_compile_time_secs=(cc_config.min_compile_time_secs
                                   if cc_config is not None else 0.0),
            # An explicit config/env dir is a deliberate operator choice;
            # only the registry-derived default defers to a pre-set cache.
            force=bool((cc_config is not None and cc_config.cache_dir)
                       or os.environ.get("APNEA_UQ_XLA_CACHE_DIR")),
        )
    store_dir = None
    if cc_config is None or cc_config.program_store:
        store_dir = (
            (cc_config.store_dir if cc_config is not None else "")
            or os.environ.get("APNEA_UQ_PROGRAM_STORE_DIR", "")
            or (os.path.join(registry_root, "program-store")
                if registry_root else "")
        ) or None
    store = ProgramStore(store_dir)
    try:
        with use_store(store):
            yield store
    finally:
        for name, value in prev.items():
            jax.config.update(name, value)


# ------------------------------------------------------------- keying ----

def _source_version() -> str:
    """Code-version component of the store key: hash of every ``.py``
    source in the package (a code change must invalidate stored
    programs — the serialized StableHLO was traced from the old code).
    ``APNEA_UQ_SOURCE_VERSION`` overrides (tests pin staleness with it)."""
    override = os.environ.get("APNEA_UQ_SOURCE_VERSION")
    if override:
        return override
    return _hashed_package_source()


@functools.lru_cache(maxsize=1)
def _hashed_package_source() -> str:
    import apnea_uq_tpu

    root = os.path.dirname(os.path.abspath(apnea_uq_tpu.__file__))
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            h.update(os.path.relpath(path, root).encode())
            with open(path, "rb") as f:
                h.update(hashlib.sha256(f.read()).digest())
    return h.hexdigest()


def backend_fingerprint() -> str:
    """Backend + topology component of the store key: a program compiled
    for one platform/device-kind/device-count must never be offered to
    another."""
    try:
        # apnea-lint: disable=single-host-device-enumeration -- the store key fingerprints the GLOBAL topology on purpose: a program compiled for one device/process count must never be offered to another
        devices = jax.devices()
        return (f"{devices[0].platform}/{devices[0].device_kind}"
                f"/d{len(devices)}/p{jax.process_count()}")
    except Exception:  # noqa: BLE001 - no backend: key still forms
        return "nobackend"


def _is_array_leaf(leaf: Any) -> bool:
    return hasattr(leaf, "shape") and hasattr(leaf, "dtype")


def _is_typed_key(leaf: Any) -> bool:
    try:
        return _is_array_leaf(leaf) and jnp.issubdtype(
            leaf.dtype, jax.dtypes.prng_key)
    except Exception:  # noqa: BLE001 - exotic dtype objects
        return False


def _sharding_desc(leaf: Any) -> str:
    """The sharding component of a leaf's signature: the sharding when it
    is pinned (a committed array, or an aval carrying one), else "" —
    so the record_memory_only pre-pass (avals with explicit shardings)
    and the real call (committed arrays) key identically, while programs
    lowered at different placements never collide."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is None:
        return ""
    if isinstance(leaf, jax.ShapeDtypeStruct) or getattr(
            leaf, "_committed", False):
        return str(sharding)
    return ""


def program_signature(args: tuple, kwargs: dict) -> str:
    """Process-stable abstract signature of a call: array leaves become
    (shape, dtype, pinned sharding), everything else its repr — the same
    distinctions the jit cache key makes, plus placement."""
    flat, treedef = jax.tree.flatten((args, dict(kwargs)))
    parts = []
    for leaf in flat:
        if _is_array_leaf(leaf):
            parts.append(
                f"arr{tuple(leaf.shape)}:{leaf.dtype}:{_sharding_desc(leaf)}"
            )
        elif callable(leaf) and not isinstance(leaf, type):
            # Function leaves (optax transforms are namedtuples of
            # closures): repr embeds the process-local address, which
            # would make the key differ on every process/activation —
            # the qualname is the stable identity (the code-version hash
            # already covers behavioral drift).
            parts.append(
                f"fn:{getattr(leaf, '__module__', '?')}."
                f"{getattr(leaf, '__qualname__', repr(leaf))}"
            )
        else:
            parts.append(repr(leaf))
    return f"{treedef}|{';'.join(parts)}"


def store_key(label: str, signature: str) -> str:
    """sha256 over every invalidation axis of one stored program."""
    import jaxlib

    # Lazy: ops/autotune.py imports this module at module level for the
    # fingerprint helpers.  The active tuned-geometry digest is a keying
    # axis because geometry is a STATIC argument of the kernel program
    # families — a program stored under one tile geometry must never be
    # offered to a process that activated another.
    from apnea_uq_tpu.ops import autotune as autotune_mod

    material = json.dumps({
        "label": label,
        "signature": signature,
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": backend_fingerprint(),
        "source": _source_version(),
        "autotune": autotune_mod.active_digest(),
    }, sort_keys=True)
    return hashlib.sha256(material.encode()).hexdigest()


# ------------------------------------------------------- program build ----

@dataclasses.dataclass
class Program:
    """One acquired hot-path program: a callable executable plus the
    memory-analysis fields priced when it was first compiled.

    ``source`` is how THIS acquisition resolved: ``"jit"`` = fresh
    trace+lower+compile (miss), ``"store"`` = deserialized from the
    on-disk program store (no trace/lower; backend compile via the
    persistent cache), ``"cache"`` = the in-process memo.  Call it with
    the exact (positionally-bound) argument structure it was built from;
    static/aux leaves are baked and only the array leaves are consumed.
    """

    label: str
    source: str
    key: str
    signature: str
    memory_fields: Optional[Dict[str, int]]
    lower_s: float
    compile_s: float
    executable: Any
    _treedef: Any
    _arr_idx: Tuple[int, ...]
    _key_impls: Dict[int, str]

    def __call__(self, *args, **kwargs):
        flat, treedef = jax.tree.flatten((args, dict(kwargs)))
        if treedef != self._treedef:
            raise ValueError(
                f"program {self.label!r} called with argument structure "
                f"{treedef}, but it was built for {self._treedef}"
            )
        arrs = [
            jax.random.key_data(flat[i]) if i in self._key_impls
            else flat[i]
            for i in self._arr_idx
        ]
        return self.executable(*arrs)


def _split_leaves(args: tuple, kwargs: dict):
    """(flat leaves, treedef, array positions, aux leaves, key impls)."""
    flat, treedef = jax.tree.flatten((args, dict(kwargs)))
    arr_idx: List[int] = []
    aux: Dict[int, Any] = {}
    key_impls: Dict[int, str] = {}
    for i, leaf in enumerate(flat):
        if _is_array_leaf(leaf):
            arr_idx.append(i)
            if _is_typed_key(leaf):
                key_impls[i] = str(jax.random.key_impl(leaf))
        else:
            aux[i] = leaf
    return flat, treedef, tuple(arr_idx), aux, key_impls


def _leaf_specs(flat, arr_idx, key_impls):
    """ShapeDtypeStructs for the wrapper's array arguments.  Uncommitted
    leaves in a program that has any mesh-sharded (NamedSharding) leaf
    are exported replicated over that mesh — ``jax.export`` gives every
    arg a placement, and a bare single-device default would conflict
    with the multi-device assignment at lowering time."""
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = None
    for i in arr_idx:
        sharding = getattr(flat[i], "sharding", None)
        if isinstance(sharding, NamedSharding) and _sharding_desc(flat[i]):
            mesh = sharding.mesh
            break
    replicated = (NamedSharding(mesh, PartitionSpec()) if mesh is not None
                  else None)
    specs = []
    for i in arr_idx:
        leaf = flat[i]
        if i in key_impls:
            leaf = jax.random.key_data(leaf)
        sharding = (getattr(leaf, "sharding", None)
                    if _sharding_desc(leaf) else None) or replicated
        specs.append(jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype,
                                          sharding=sharding))
    return specs


def _make_wrapper(fn: Callable, treedef, n_leaves: int, arr_idx, aux,
                  key_impls) -> Callable:
    """The exportable twin of ``fn(*args, **kwargs)``: a function of the
    array leaves only.  Static/aux leaves are closed over, typed PRNG
    keys arrive as uint32 key data and are re-wrapped — the numerics are
    the original program's, inlined under one jit."""

    def wrapper(*arrs):
        leaves: List[Any] = [None] * n_leaves
        for i, value in aux.items():
            leaves[i] = value
        for pos, arr in zip(arr_idx, arrs):
            leaves[pos] = (
                jax.random.wrap_key_data(arr, impl=key_impls[pos])
                if pos in key_impls else arr
            )
        args, kwargs = jax.tree.unflatten(treedef, leaves)
        return fn(*args, **kwargs)

    return wrapper


def _donated_leaf_positions(args: tuple, kwargs: dict, donate_args,
                            arr_idx) -> Tuple[int, ...]:
    """Wrapper-parameter indices of the leaves under the donated
    positional args — donation must survive the re-expression, or the
    stored twin of a donating program (the lockstep ensemble epoch)
    would double its HBM footprint."""
    if not donate_args:
        return ()
    donated_flat: set = set()
    offset = 0
    for pos, arg in enumerate(args):
        n = len(jax.tree.flatten(arg)[0])
        if pos in donate_args:
            donated_flat.update(range(offset, offset + n))
        offset += n
    # kwargs flatten after args in the ((args, kwargs)) tree; donation is
    # positional-only here, so kwargs leaves are never donated.
    return tuple(
        wrapper_pos for wrapper_pos, flat_pos in enumerate(arr_idx)
        if flat_pos in donated_flat
    )


class ProgramStore:
    """On-disk + in-memory store of AOT-compiled named programs.

    ``root=None`` keeps the store purely in-process (the one-lowering
    sharing still works; nothing persists).  All failures degrade to
    returning ``None`` from :meth:`get`, never raising into a run."""

    def __init__(self, root: Optional[str] = None):
        self.root = root
        self._programs: Dict[str, Program] = {}
        self._failed: set = set()
        # Chronological compile_event field dicts (run-log-independent
        # mirror, so warm-cache and the bench probe can report sources
        # without re-reading events.jsonl).
        self.history: List[Dict[str, Any]] = []

    # -- paths ------------------------------------------------------------

    def _blob_path(self, key: str) -> str:
        return os.path.join(self.root, key + STORE_SUFFIX)

    def _meta_path(self, key: str) -> str:
        return os.path.join(self.root, key + META_SUFFIX)

    def _persist(self, key: str, blob: bytes, meta: Dict[str, Any]) -> None:
        if self.root is None:
            return
        try:
            if jax.process_index() != 0:
                return  # one writer on multi-process topologies
        except Exception:  # noqa: BLE001 - no backend: single process
            pass
        from apnea_uq_tpu.utils.io import atomic_write_bytes

        os.makedirs(self.root, exist_ok=True)
        for path, data in ((self._blob_path(key), blob),
                           (self._meta_path(key),
                            json.dumps(meta, indent=2).encode())):
            # tmp -> fsync -> replace (pid-suffixed tmp: multi-process
            # meshes can race on a shared store root).
            atomic_write_bytes(path, data)

    def _load_serialized(self, key: str):
        """(blob, meta) when both files exist and parse, else None."""
        if self.root is None:
            return None
        blob_path, meta_path = self._blob_path(key), self._meta_path(key)
        if not (os.path.exists(blob_path) and os.path.exists(meta_path)):
            return None
        try:
            with open(blob_path, "rb") as f:
                blob = f.read()
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return None
        if meta.get("key") != key:
            return None
        return blob, meta

    # -- acquisition ------------------------------------------------------

    def get(self, label: str, fn: Callable, args: tuple, kwargs: dict,
            *, exportable: bool = True, donate_args: Tuple[int, ...] = (),
            run_log=None) -> Optional[Program]:
        """Acquire the compiled program for ``fn(*args, **kwargs)``:
        in-process memo, then the on-disk store (``exportable`` programs
        only), then compile-on-miss (exporting + persisting when
        possible).  Returns None when acquisition failed — callers fall
        back to the plain jit path.  Emits one ``compile_event`` per
        acquisition."""
        try:
            signature = program_signature(args, kwargs)
            key = store_key(label, signature)
        except Exception:  # noqa: BLE001 - unkeyable args: jit fallback
            return None
        if key in self._failed:
            return None
        cached = self._programs.get(key)
        if cached is not None:
            program = dataclasses.replace(cached, source="cache")
            self._event(program, run_log, lower_s=0.0, compile_s=0.0,
                        deltas={})
            return program
        try:
            program = self._acquire(label, fn, args, kwargs, signature,
                                    key, exportable, donate_args, run_log)
        except Exception as e:  # noqa: BLE001 - never break a run
            # One log line, one failed attempt: the program is unexportable
            # or otherwise unbuildable in this environment, so stop paying
            # the attempt (the plain jit path serves every later call).
            self._failed.add(key)
            log(f"program store: building {label!r} failed "
                f"({type(e).__name__}: {e}); falling back to plain jit")
            return None
        self._programs[key] = program
        return program

    def _acquire(self, label, fn, args, kwargs, signature, key,
                 exportable, donate_args, run_log) -> Program:
        from jax import export as jax_export

        flat, treedef, arr_idx, aux, key_impls = _split_leaves(args, kwargs)
        specs = _leaf_specs(flat, arr_idx, key_impls)
        common = dict(label=label, key=key, signature=signature,
                      _treedef=treedef, _arr_idx=arr_idx,
                      _key_impls=key_impls)

        loaded = self._load_serialized(key) if exportable else None
        before = compile_counts()
        if loaded is not None:
            blob, meta = loaded
            t0 = time.perf_counter()
            exported = jax_export.deserialize(blob)
            lower_s = time.perf_counter() - t0
            t0 = time.perf_counter()
            executable = jax.jit(exported.call).lower(*specs).compile()
            compile_s = time.perf_counter() - t0
            program = Program(
                source="store", memory_fields=meta.get("memory_fields"),
                lower_s=round(lower_s, 6), compile_s=round(compile_s, 6),
                executable=executable, **common)
            self._event(program, run_log, lower_s=lower_s,
                        compile_s=compile_s,
                        deltas=_count_deltas(before, compile_counts()))
            return program

        wrapper = _make_wrapper(fn, treedef, len(flat), arr_idx, aux,
                                key_impls)
        donate = _donated_leaf_positions(args, kwargs, tuple(donate_args),
                                         arr_idx)
        wrapped = jax.jit(wrapper, donate_argnums=donate or ())
        t0 = time.perf_counter()
        blob = None
        if exportable and not donate:
            try:
                # Round-trip through serialize/deserialize BEFORE
                # compiling, so this process and every later store-hit
                # process compile the byte-identical module — that
                # identity is what turns the warm process's backend
                # compile into a guaranteed persistent-cache hit.
                blob = jax_export.export(wrapped)(*specs).serialize()
                to_compile = jax.jit(jax_export.deserialize(blob).call)
            except Exception:  # noqa: BLE001 - unexportable: AOT-share only
                blob = None
                to_compile = wrapped
        else:
            # Donating programs are AOT-shared in-process (and their
            # backend compile still lands in the persistent XLA cache)
            # but not serialized: jax.export drops donation, and a
            # store-loaded twin would silently double the program's HBM
            # footprint.
            to_compile = wrapped
        lowered = to_compile.lower(*specs)
        lower_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        executable = lowered.compile()
        compile_s = time.perf_counter() - t0
        memory_fields = None
        try:
            stats = executable.memory_analysis()
            if stats is not None:
                memory_fields = memory_analysis_fields(stats)
        except Exception:  # noqa: BLE001 - accounting is best-effort
            pass
        program = Program(
            source="jit", memory_fields=memory_fields,
            lower_s=round(lower_s, 6), compile_s=round(compile_s, 6),
            executable=executable, **common)
        if blob is not None:
            self._persist(key, blob, {
                "label": label, "key": key, "signature": signature,
                "jax": jax.__version__,
                "backend": backend_fingerprint(),
                "source_version": _source_version(),
                "memory_fields": memory_fields,
                "lower_s": program.lower_s,
                "compile_s": program.compile_s,
                "created_ts": round(time.time(), 3),
            })
        self._event(program, run_log, lower_s=lower_s, compile_s=compile_s,
                    deltas=_count_deltas(before, compile_counts()))
        return program

    def _event(self, program: Program, run_log, *, lower_s: float,
               compile_s: float, deltas: Dict[str, int]) -> None:
        fields = {
            "label": program.label,
            "source": program.source,
            "hit": program.source != "jit",
            "lower_s": round(lower_s, 6),
            "compile_s": round(compile_s, 6),
            "backend_compiles": deltas.get("backend_compiles", 0),
            "persistent_cache_hits": deltas.get("persistent_cache_hits", 0),
            "persistent_cache_misses": deltas.get(
                "persistent_cache_misses", 0),
            "key": program.key[:16],
        }
        self.history.append(dict(fields))
        if run_log is None:
            run_log = current_run()
        if run_log is not None and not getattr(run_log, "disabled", False):
            try:
                run_log.event("compile_event", **fields)
            except Exception:  # noqa: BLE001 - telemetry must never break
                pass


def _count_deltas(before: Dict[str, int], after: Dict[str, int]):
    return {k: after.get(k, 0) - before.get(k, 0) for k in after}


def get_program(label: str, fn: Callable, *args,
                exportable: bool = True,
                donate_args: Tuple[int, ...] = (),
                run_log=None, **kwargs) -> Optional[Program]:
    """Acquire ``label``'s compiled program from the active store, or
    None when no store is active (callers then dispatch the plain jitted
    ``fn`` — the pre-subsystem behavior, byte for byte)."""
    store = active_store()
    if store is None:
        return None
    return store.get(label, fn, tuple(args), dict(kwargs),
                     exportable=exportable, donate_args=donate_args,
                     run_log=run_log)
