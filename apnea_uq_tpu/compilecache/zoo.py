"""The named hot-path program zoo behind ``apnea-uq warm-cache``.

``warm_cache`` precompiles, prices, and (where exportable) persists every
program a given config will dispatch — the four predict families, the
deterministic sanity/eval predictor, ``train_epoch``/``val_loss``, and
the lockstep ``ensemble_epoch`` — so a later production eval/train
process starts hot: program-store hits skip trace+lower, and every
backend compile is a persistent-XLA-cache disk hit.

Nothing here re-derives argument shapes by hand: the warm paths are the
*real* entry points in their no-dispatch modes (``record_memory_only=True``
on the predictors, ``compile_only=True`` on the trainers), so the warmed
program signatures are the executed ones by construction — the property
the zoo-coverage test (tests/test_compilecache.py) pins from the other
side by asserting every memory-priced label in the drivers has a zoo
entry.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

# One entry per warmable stage group; the label sets double as the
# store-vs-pricing-table drift pin: every ``*_fused``/memory-priced
# label the drivers use MUST appear here (enforced by
# tests/test_compilecache.py against the driver sources).
#
# GROUP_LABELS is ALSO the IR audit's registration site (ISSUE 8,
# apnea_uq_tpu/audit/): `apnea-uq audit` lowers every label below on CPU
# and anchors its findings at the label's line here, every label must
# have a row in audit/manifest.json (same drift pin enforces it), and a
# per-label exemption is an inline `# apnea-lint: disable=<program-rule>
# -- <why>` comment next to the label string.
WARM_GROUPS: Tuple[str, ...] = (
    "eval-mcd", "eval-de", "train", "train-ensemble", "serve",
)

# Label grammar (uq/predict.py mcd_program_label / de_program_label):
# base + optional suffixes in fixed order — `_pallas` (the fused
# ops/pallas_mcd.py MCD engine was requested; off-TPU the label runs the
# XLA fallback body), `_fused` (on-device sufficient-statistics
# reduction), `_bf16` (ModelConfig.compute_dtype='bfloat16', the
# audit's blessed low-precision tier — audit/rules.py
# program-dtype-drift; manifest rows carry the tier column).
GROUP_LABELS: Dict[str, Tuple[str, ...]] = {
    "eval-mcd": ("mcd_predict", "mcd_predict_bf16",
                 "mcd_predict_fused", "mcd_predict_fused_bf16",
                 "mcd_predict_pallas", "mcd_predict_pallas_bf16",
                 "mcd_predict_pallas_fused",
                 "mcd_predict_pallas_fused_bf16",
                 "mcd_chunk_predict", "mcd_chunk_predict_bf16",
                 "mcd_chunk_predict_fused", "mcd_chunk_predict_fused_bf16",
                 "mcd_chunk_predict_pallas", "mcd_chunk_predict_pallas_bf16",
                 "mcd_chunk_predict_pallas_fused",
                 "mcd_chunk_predict_pallas_fused_bf16",
                 "predict_eval", "predict_eval_bf16"),
    "eval-de": ("de_predict", "de_predict_bf16",
                "de_predict_fused", "de_predict_fused_bf16",
                "de_predict_pallas", "de_predict_pallas_bf16",
                "de_predict_pallas_fused", "de_predict_pallas_fused_bf16",
                "de_chunk_predict", "de_chunk_predict_bf16",
                "de_chunk_predict_fused", "de_chunk_predict_fused_bf16",
                "de_chunk_predict_pallas", "de_chunk_predict_pallas_bf16",
                "de_chunk_predict_pallas_fused",
                "de_chunk_predict_pallas_fused_bf16"),
    "train": ("train_epoch", "val_loss"),
    "train-ensemble": ("ensemble_epoch",),
    # The online serving tier's bucket ladder (uq/predict.py
    # SERVE_BUCKET_SIZES; `apnea-uq serve`): one fused-stats program per
    # (method, bucket, dtype) cell, grammar
    # `{mcd|de}_serve_b<bucket>_fused[_bf16]`.  Warmed here so a warm
    # serve process does ZERO fresh XLA compiles on the request path —
    # the PR-6 contract extended to serving, pinned by the warm-serve
    # subprocess acceptance test (tests/test_serving.py).
    "serve": ("mcd_serve_b16_fused", "mcd_serve_b16_fused_bf16",
              "mcd_serve_b64_fused", "mcd_serve_b64_fused_bf16",
              "mcd_serve_b256_fused", "mcd_serve_b256_fused_bf16",
              "mcd_serve_b16_pallas_fused", "mcd_serve_b16_pallas_fused_bf16",
              "mcd_serve_b64_pallas_fused", "mcd_serve_b64_pallas_fused_bf16",
              "mcd_serve_b256_pallas_fused",
              "mcd_serve_b256_pallas_fused_bf16",
              "de_serve_b16_fused", "de_serve_b16_fused_bf16",
              "de_serve_b64_fused", "de_serve_b64_fused_bf16",
              "de_serve_b256_fused", "de_serve_b256_fused_bf16",
              "de_serve_b16_pallas_fused", "de_serve_b16_pallas_fused_bf16",
              "de_serve_b64_pallas_fused", "de_serve_b64_pallas_fused_bf16",
              "de_serve_b256_pallas_fused",
              "de_serve_b256_pallas_fused_bf16"),
}


def _test_set_shapes(prepared) -> List[Tuple[int, ...]]:
    shapes = [tuple(prepared.x_test.shape)]
    if prepared.x_test_rus is not None:
        shapes.append(tuple(prepared.x_test_rus.shape))
    return shapes


def resolve_de_members(num_members: int, config,
                       ckpt_root: Optional[str]) -> int:
    """The member count a later ``eval-de`` will actually run: an
    explicit ``num_members`` wins; otherwise the checkpointed member
    count when an ensemble store exists (eval-de's own ``--num-members
    0`` resolution — a store grown by promoted padded slots, or by a
    config edited after training, would otherwise make every warmed de_*
    signature miss), else the configured ensemble size."""
    if num_members > 0:
        return num_members
    if ckpt_root:
        try:
            from apnea_uq_tpu.training import EnsembleCheckpointStore

            seeds = EnsembleCheckpointStore(
                os.path.join(ckpt_root, "ensemble")).existing_seeds()
            if seeds:
                return len(seeds)
        except Exception:  # noqa: BLE001 - no/unreadable store: config wins
            pass
    return config.ensemble.num_members


def warm_cache(
    registry,
    config,
    *,
    num_members: int = 0,
    groups: Tuple[str, ...] = WARM_GROUPS,
    ckpt_root: Optional[str] = None,
    run_log=None,
) -> List[Dict[str, Any]]:
    """Precompile the program zoo ``config`` selects, against the
    registry's prepared data shapes.  ``num_members`` (<=0 → every
    checkpointed member under ``ckpt_root`` when one exists, else the
    configured ensemble size; see :func:`resolve_de_members`) must match
    the ``--num-members`` a later ``eval-de`` will run with, or that
    eval's member axis — and thus its program signature — will differ.
    Returns the compile_event field dicts of every acquisition performed
    (source ``jit`` = compiled and banked, ``store``/``cache`` = already
    warm).  Streaming trainer configs have no single epoch program to
    warm (their per-step programs are not memory-priced); those groups
    log an explicit skip instead of silently warming nothing."""
    import jax
    import jax.numpy as jnp

    from apnea_uq_tpu.compilecache.store import active_store
    from apnea_uq_tpu.data.prepare import load_prepared
    from apnea_uq_tpu.telemetry import log
    from apnea_uq_tpu.models import AlarconCNN1D, init_variables
    from apnea_uq_tpu.parallel import fit_ensemble
    from apnea_uq_tpu.parallel.mesh import make_mesh, make_mesh_from_config
    from apnea_uq_tpu.training import create_train_state, fit
    from apnea_uq_tpu.training.trainer import predict_proba_batched
    from apnea_uq_tpu.uq.predict import (
        SERVE_BUCKET_SIZES,
        ensemble_predict,
        ensemble_predict_streaming,
        mc_dropout_predict,
        mc_dropout_predict_streaming,
        serve_bucket_predict,
        stack_member_variables,
    )
    from apnea_uq_tpu.utils import prng

    unknown = set(groups) - set(WARM_GROUPS)
    if unknown:
        raise ValueError(
            f"unknown warm-cache group(s) {sorted(unknown)}; "
            f"valid: {list(WARM_GROUPS)}"
        )
    store = active_store()
    history_base = len(store.history) if store is not None else 0

    need_train = bool({"train", "train-ensemble"} & set(groups))
    # Serving bucket programs have FIXED shapes from the model config
    # (bucket x time_steps x channels) — a serve-only warm needs no
    # prepared window sets, so a serving registry can be warmed before
    # any data pipeline has run.
    need_prepared = bool(set(groups) - {"serve"})
    prepared = (load_prepared(registry, include_train=need_train)
                if need_prepared else None)
    model = AlarconCNN1D(config.model)
    # Fresh-initialized variables are aval-identical to any checkpoint of
    # this model config — values never matter to compilation.
    variables = init_variables(model, jax.random.key(0))
    uq = config.uq
    stat_spec = ("nats", uq.entropy_eps) if uq.fused_reduction else None
    test_shapes = _test_set_shapes(prepared) if prepared is not None else []

    if "eval-mcd" in groups:
        mesh = make_mesh_from_config(config.mesh, num_members=uq.mc_passes)
        predict = (mc_dropout_predict_streaming if uq.mcd_streaming
                   else mc_dropout_predict)
        key = prng.stochastic_key(config.train.seed)
        for i, shape in enumerate(test_shapes):
            x_aval = jax.ShapeDtypeStruct(shape, jnp.float32)
            predict(
                model, variables, x_aval,
                n_passes=uq.mc_passes, mode=uq.mcd_mode,
                batch_size=uq.mcd_batch_size, key=key, mesh=mesh,
                run_log=run_log, record_memory_only=True, stats=stat_spec,
                engine=uq.mcd_engine,
            )
            if i == 0:
                # The drivers' deterministic sanity probe runs on the
                # first test set only (run_mcd_analysis sanity_check).
                predict_proba_batched(
                    model, variables, x_aval,
                    batch_size=uq.inference_batch_size, mesh=mesh,
                    record_memory_only=True,
                )

    if "eval-de" in groups:
        n_members = resolve_de_members(num_members, config, ckpt_root)
        members = stack_member_variables([variables] * n_members)
        mesh = make_mesh_from_config(config.mesh, num_members=n_members)
        predict = (ensemble_predict_streaming if uq.de_streaming
                   else ensemble_predict)
        for shape in test_shapes:
            x_aval = jax.ShapeDtypeStruct(shape, jnp.float32)
            predict(
                model, members, x_aval,
                batch_size=uq.inference_batch_size, mesh=mesh,
                run_log=run_log, record_memory_only=True, stats=stat_spec,
                engine=uq.de_engine,
            )

    if "train" in groups:
        if config.train.streaming:
            log("warm-cache: train group SKIPPED — TrainConfig.streaming "
                "dispatches per-step programs with no single epoch "
                "program to warm")
        else:
            state = create_train_state(
                model, jax.random.key(config.train.seed),
                learning_rate=config.train.learning_rate,
            )
            fit(
                model, state, prepared.x_train, prepared.y_train,
                config.train, mesh=make_mesh(num_members=1),
                run_log=run_log, compile_only=True,
            )

    if "serve" in groups:
        # The config-selected serving bucket programs: every ladder
        # bucket x both methods, under the dtype the config runs.  The
        # DE member count must match the later `apnea-uq serve
        # --num-members` exactly as warm-cache's eval-de contract does
        # (resolve_de_members).  Dispatch discipline matches the serve
        # process by construction — serve_bucket_predict is the one
        # entry point both sides call.
        key = prng.stochastic_key(config.train.seed)
        n_members = resolve_de_members(num_members, config, ckpt_root)
        members = stack_member_variables([variables] * n_members)
        tail = (config.model.time_steps, config.model.num_channels)
        for bucket in SERVE_BUCKET_SIZES:
            x_aval = jax.ShapeDtypeStruct((bucket,) + tail, jnp.float32)
            serve_bucket_predict(
                model, variables, x_aval, method="mcd", bucket=bucket,
                n_passes=uq.mc_passes, key=key, base="nats",
                eps=uq.entropy_eps, engine=uq.mcd_engine, run_log=run_log,
                record_memory_only=True,
            )
            serve_bucket_predict(
                model, members, x_aval, method="de", bucket=bucket,
                base="nats", eps=uq.entropy_eps, engine=uq.de_engine,
                run_log=run_log, record_memory_only=True,
            )

    if "train-ensemble" in groups:
        if config.ensemble.streaming:
            log("warm-cache: train-ensemble group SKIPPED — "
                "EnsembleConfig.streaming dispatches per-step programs "
                "with no single epoch program to warm")
        else:
            fit_ensemble(
                model, prepared.x_train, prepared.y_train, config.ensemble,
                mesh=make_mesh_from_config(
                    config.mesh, num_members=config.ensemble.num_members),
                run_log=run_log, compile_only=True,
            )

    return (list(store.history[history_base:]) if store is not None
            else [])
