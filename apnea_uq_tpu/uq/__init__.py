from apnea_uq_tpu.uq.bootstrap import (
    bootstrap_aggregates,
    bootstrap_metrics,
    compute_confidence_intervals,
)
from apnea_uq_tpu.uq.drivers import (
    UQEvaluation,
    UQRunResult,
    detailed_frame,
    detailed_frame_from_stats,
    evaluate_uq,
    evaluate_uq_from_stats,
    run_de_analysis,
    run_mcd_analysis,
    run_metrics_document,
    run_synthetic_demo,
    save_run,
    save_run_plots,
)
from apnea_uq_tpu.uq.metrics import (
    decompose_from_stats,
    sufficient_stats,
    uq_evaluation_dist,
)
from apnea_uq_tpu.uq.predict import (
    ensemble_predict,
    ensemble_predict_streaming,
    mc_dropout_predict,
    mc_dropout_predict_streaming,
)

__all__ = [
    "uq_evaluation_dist",
    "sufficient_stats",
    "decompose_from_stats",
    "evaluate_uq_from_stats",
    "detailed_frame_from_stats",
    "bootstrap_aggregates",
    "bootstrap_metrics",
    "compute_confidence_intervals",
    "mc_dropout_predict",
    "mc_dropout_predict_streaming",
    "ensemble_predict",
    "ensemble_predict_streaming",
    "evaluate_uq",
    "detailed_frame",
    "run_mcd_analysis",
    "run_de_analysis",
    "run_metrics_document",
    "run_synthetic_demo",
    "save_run",
    "save_run_plots",
    "UQEvaluation",
    "UQRunResult",
]
